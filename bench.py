"""Benchmark driver — prints ONE JSON line, no matter what.

Headline metric (BASELINE.md north star #2): solver TFLOPS/chip of the
block-least-squares inner loop — per-chip MXU gemms (residual update, gram,
gradient) + psum over ICI + replicated Cholesky, the lowering of the
reference's BlockCoordinateDescent/treeAggregate stack (SURVEY.md §3.2).

vs_baseline compares against a nominal 0.3 TFLOPS/node — the dgemm-class
throughput of one of the reference's EC2 r3.4xlarge CPU nodes (16 vcpus;
BASELINE.md has no published per-node figure, so this is a documented
engineering estimate for a sustained f64→f32-class BLAS3 workload).

Robustness contract (the round-1 gate failure was rc=1 with no output):
the orchestrator probes TPU liveness in a short-timeout subprocess first,
runs the measurement itself in a subprocess with a hard timeout, falls back
to a scaled-down CPU-mesh measurement when the TPU is dead/hung, and — if
even that fails — emits a parseable JSON error line. Timing through the
TPU relay has lied before (impossible TFLOPS readings), so the timed loop
forces a device-to-host fetch each rep and the result carries a residual
check; `suspect_timing` flags a value above the chip's plausible peak.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

REPO_DIR = os.path.dirname(os.path.abspath(__file__))

BASELINE_NODE_TFLOPS = 0.3
# v5e peak: ~197 bf16 / ~99 f32 TFLOPS per chip. Anything measured above
# this is a transport lie, not a fast program. "f32h" = f32 storage with
# HIGH (3-pass bf16) matmul precision: every canonical gemm FLOP costs 3
# MXU passes, so the canonical-FLOPs ceiling is bf16_peak/3 — NOT the
# midpoint of the f32-emulation and bf16 peaks the old 140 guessed at (a
# transport-inflated reading between ~70 and 140 sailed through that
# guard; advisor r5). The declared bound carries the same ~1% measurement
# headroom f32 does (100 declared over ~99 raw).
_BF16_PEAK = 200.0
_F32_RAW_PEAK = 99.0
_F32_BOUND = 100.0
PLAUSIBLE_PEAK_TFLOPS = {
    "bf16": _BF16_PEAK,
    "f32": _F32_BOUND,
    "f32h": round(_BF16_PEAK / 3.0 * (_F32_BOUND / _F32_RAW_PEAK), 1),
}

# Solver-code revision marker, stamped into every bench line. A checkpointed
# silicon row from an older solver (e.g. the pre-fused dispatch-per-block
# loop) describes code this round no longer ships: the checkride re-measures
# instead of skipping, and the round bench never serves it as current.
# r5: factor-phase rework, AOT-verified at the bench shapes — (a) identity
# RHS of the inverse's trsm is column-chunked (the unchunked program
# exceeded v5e HBM at the ImageNet shape); (b) one trsm + an MXU gemm
# (A⁻¹ = L⁻ᵀL⁻¹) replaces the chained pair, halving the sequential tail.
SOLVER_REV = "r5-trsm-gemm-inv"

# (n, d, k, block, iters) per backend class — CPU emulation gets a smaller
# problem so the gate finishes; the FLOP formula keeps the metric honest.
# "quick" exists for the checkride's CPU dry-run (harness validation only;
# its TFLOPS are not a perf claim).
SCALE = {
    "tpu": dict(n=32768, d=8192, k=16, block=4096, iters=2),
    # Reference-scale dimensionality (TIMIT 528k / CIFAR 256k features,
    # SURVEY.md §6): d >= 262144 exercises the many-block regime.
    # f32 residency: A (n·d·4B = 2 GiB) + the solver's a_blocks partition
    # copy (another 2 GiB — see bcd.py's slice-once note) + cached ridge
    # inverses (d·block·4B = 2 GiB) ≈ 6 GiB of v5e's 16 GiB, leaving
    # gram/Cholesky/inverse workspace headroom.
    "tpu-xl": dict(n=2048, d=262144, k=16, block=2048, iters=2),
    # The ImageNet headline shape (SURVEY.md §2.11 ImageNetSiftLcsFV:
    # 64k-dim FV features, k=1000 classes, 3 epochs): per-epoch gemms are
    # (n×b)·(b×1000) — real MXU work, unlike the k=16 rows whose skinny
    # epochs under-represent the shape the north star extrapolates to.
    # f32 residency: A 2 GiB + stacked-blocks copy 2 GiB + 8 cached ridge
    # inverses 2 GiB + W/R ≈ 0.3 GiB ≈ 6.3 GiB.
    "tpu-imagenet": dict(n=8192, d=65536, k=1000, block=8192, iters=3),
    "cpu": dict(n=8192, d=2048, k=16, block=512, iters=2),
    "quick": dict(n=1024, d=512, k=8, block=128, iters=2),
}


def bcd_flops(n: int, d: int, k: int, block: int, iters: int) -> float:
    """CANONICAL FLOPs of block_coordinate_descent's device work with gram
    caching: gram + Cholesky + explicit ridge inverse once per block, then
    per-epoch residual/rhs gemms and one inverse-multiply gemm (no
    triangular solves in the epoch loop).

    This is a FIXED accounting, not a per-revision raw-arithmetic count —
    TFLOPS stay comparable across solver revisions (r3/r4 rows, BASELINE
    ratios) as canonical-work/time. The formula charges the inverse at
    2·b³ (the two-trsm formation); the r5 implementation actually spends
    ~3·b³ there (one trsm + a full YᵀY gemm that ignores Y's
    triangularity), so reported TFLOPS slightly UNDERSTATE raw device
    throughput — the conservative direction."""
    nb = d // block
    # gram + Cholesky + canonical inverse formation (charged at 2·b³)
    once = 2.0 * n * block * block + block**3 / 3.0 + 2.0 * block**3
    per_epoch = (
        2.0 * n * block * k  # residual restore  A_b @ W_b
        + 2.0 * n * block * k  # rhs  A_bᵀR
        + 2.0 * block * block * k  # inverse-multiply solve gemm
        + 2.0 * n * block * k  # residual update
    )
    return nb * (once + per_epoch * iters)


def make_problem(rng, n: int, d: int, k: int, sparse_threshold: int = 1 << 25):
    """(A, B) with B exactly in A's column span.

    Huge-d·k scales (the ImageNet-shaped bench): a dense (d, k) W_true
    would cost ~n·d·k host FLOPs just to fabricate B. A W_true supported
    on 256 columns of every 8192-wide stripe (spread so no single feature
    block trivializes the solve) keeps B in-span at ~3% of the cost;
    solver FLOPs are value-independent, so the measurement is unchanged."""
    A = rng.normal(size=(n, d)).astype(np.float32)
    if d * k > sparse_threshold:
        stripe, per = 8192, 256
        support = np.concatenate(
            [np.arange(s, s + min(per, d - s)) for s in range(0, d, stripe)]
        )
        W_small = rng.normal(size=(support.size, k)).astype(np.float32)
        B = (A[:, support] @ W_small).astype(np.float32)
    else:
        W_true = rng.normal(size=(d, k)).astype(np.float32)
        B = (A @ W_true).astype(np.float32)
    return A, B


def worker(scale_key: str, dtype: str) -> None:
    """Runs one measurement on this process's default backend and prints the
    JSON line. Platform selection already happened (env / config)."""
    from keystone_tpu.utils.platform import env_forces_cpu, force_cpu

    if env_forces_cpu():
        force_cpu()
    import jax

    from keystone_tpu.config import config
    from keystone_tpu.linalg import RowMatrix, block_coordinate_descent

    # The flag decides the measured mode outright — an ambient
    # KEYSTONE_SOLVER_DTYPE must never mislabel an f32 measurement.
    config.solver_storage_dtype = "bfloat16" if dtype == "bf16" else None
    # "f32h": f32 storage, HIGH (3-pass) matmul precision — the candidate
    # default the sweep measures against "highest" on silicon.
    config.solver_precision = "high" if dtype == "f32h" else "highest"

    p = SCALE[scale_key]
    n, d, k, block, iters = p["n"], p["d"], p["k"], p["block"], p["iters"]
    # Block-size override for the MFU sweep (tools/bench_mfu.py); clamped
    # to a divisor of d so the FLOP formula stays exact.
    env_block = os.environ.get("KEYSTONE_BENCH_BLOCK")
    if env_block:
        block = max(1, min(int(env_block), d))
        while d % block:
            block -= 1
    A, B = make_problem(np.random.default_rng(0), n, d, k)

    from keystone_tpu.linalg.row_matrix import storage_dtype

    Ma = RowMatrix.from_array(A, dtype=storage_dtype())
    Mb = RowMatrix.from_array(B)

    def run():
        # cache_grams pinned True so the timed path always matches bcd_flops.
        W, _blocks = block_coordinate_descent(
            Ma, Mb, block_size=block, num_iters=iters, lam=1e-3,
            cache_grams=True,
        )
        for w in W:
            w.block_until_ready()
        # Force a real device→host round trip: block_until_ready through a
        # flaky transport has returned early before; a fetch cannot.
        np.asarray(W[-1][-1, -1])
        return W

    W = run()  # warmup + compile
    # Validity check: a wrong or unconverged solve makes TFLOPS meaningless.
    West = np.concatenate([np.asarray(w) for w in W], axis=0)
    resid = float(np.linalg.norm(A @ West - B) / np.linalg.norm(B))
    # Two epochs cut the residual ~92% on this problem; anything worse means
    # the solve (or the transport) is lying and the timing is meaningless.
    assert resid < 0.2, f"BCD did not make progress (resid={resid})"

    # Time enough repetitions to amortize dispatch noise (>= 2s or 5 runs).
    # KEYSTONE_PROFILE_DIR additionally captures an XLA trace of the loop.
    from keystone_tpu.utils.metrics import maybe_trace

    reps, total = 0, 0.0
    with maybe_trace(f"bcd_{scale_key}_{dtype}"):
        while total < 2.0 and reps < 5:
            t0 = time.perf_counter()
            run()
            total += time.perf_counter() - t0
            reps += 1
    dt = total / reps

    n_dev = len(jax.devices())
    backend = jax.default_backend()
    # HBM high-water (TPU runtimes report it; CPU returns None) — the
    # donation/aliasing evidence channel (SURVEY.md §5 sanitizer row).
    from keystone_tpu.utils.metrics import environment_fingerprint, peak_hbm_bytes
    tflops_per_chip = bcd_flops(n, d, k, block, iters) / dt / 1e12 / n_dev
    peak = PLAUSIBLE_PEAK_TFLOPS[dtype]
    line = {
        "metric": "bcd_solver_tflops_per_chip",
        "value": round(tflops_per_chip, 3),
        "unit": "TFLOPS/chip",
        "vs_baseline": round(tflops_per_chip / BASELINE_NODE_TFLOPS, 2),
        "backend": backend,
        "env": environment_fingerprint(),
        "detail": {
            "n": n,
            "d": d,
            "k": k,
            "block": block,
            "epochs": iters,
            "dtype": dtype,
            "solver_rev": SOLVER_REV,
            "seconds_per_solve": round(dt, 4),
            "relative_residual": round(resid, 6),
            "devices": n_dev,
            "peak_hbm_bytes": peak_hbm_bytes(),
        },
    }
    if backend != "cpu" and tflops_per_chip > peak:
        line["suspect_timing"] = True
    print(json.dumps(line), flush=True)


def _run_worker(env: dict, scale_key: str, dtype: str, timeout: float):
    """Run the worker in a subprocess; return its parsed JSON line or None.
    Failures tail the worker's stderr to our stderr so the gate log is
    diagnosable (the round-1 failure mode was rc=1 with no diagnostics)."""
    cmd = [
        sys.executable, os.path.abspath(__file__),
        "--worker", "--scale", scale_key, "--dtype", dtype,
    ]
    try:
        proc = subprocess.run(
            cmd, env=env, capture_output=True, text=True, timeout=timeout
        )
    except subprocess.TimeoutExpired as e:
        tail = (e.stderr or b"")
        if isinstance(tail, bytes):
            tail = tail.decode(errors="replace")
        print(f"bench worker timed out; stderr tail:\n{tail[-2000:]}", file=sys.stderr)
        return None
    except OSError as e:
        print(f"bench worker failed to launch: {e}", file=sys.stderr)
        return None
    from keystone_tpu.utils.platform import parse_json_line

    parsed = parse_json_line(proc.stdout)
    if parsed is not None and "metric" in parsed:
        return parsed
    print(
        f"bench worker rc={proc.returncode}, no JSON line; stderr tail:\n"
        f"{(proc.stderr or '')[-2000:]}",
        file=sys.stderr,
    )
    return None


def _checkride_checkpoint(scale_key: str, dtype: str):
    """Checkpointed live-chip bench line for this scale+dtype, if the
    resumable checkride (tools/checkride.py) captured one earlier.

    The relay dies for whole sessions: when the driver's end-of-round bench
    lands on a dead chip, the round's REAL silicon measurement may already
    sit in .checkride/. Serving it — provenance-tagged, config-matched, and
    only after the live attempt failed — beats reporting a CPU number for a
    round that did produce TPU evidence."""
    step = {"tpu-xl": "bench_xl", "tpu-imagenet": "bench_imagenet"}.get(
        scale_key, {"f32": "bench_f32", "bf16": "bench_bf16"}.get(dtype)
    )
    if step is None:
        return None
    path = os.path.join(REPO_DIR, ".checkride", f"step_{step}.json")
    try:
        with open(path) as f:
            rec = json.load(f)
        # In-record wall-clock stamp only: the state dir is committed, so
        # file mtime is checkout time on a fresh clone — trusting it would
        # re-date a previous round's silicon. No stamp = no serve.
        mtime = float(rec["saved_at"])
        age_h = (time.time() - mtime) / 3600.0
        # A checkpoint can outlive its round (the state dir is committed
        # for resume): past this age it is some PREVIOUS round's silicon,
        # not a substitute for this one's.
        if age_h > 36.0:
            return None
        line = rec.get("bench_line")
        if not (
            rec.get("backend") == "tpu"
            and rec.get("ok")
            and not rec.get("quick_scale")
            and isinstance(line, dict)
            # A checkpoint carrying suspect_timing measured above plausible
            # peak — a transport lie must not be replayed as the round's
            # silicon number just because the live attempt failed.
            and not line.get("suspect_timing")
        ):
            return None
        det = line.get("detail") or {}
        cfg = SCALE[scale_key]
        # The checkpoint must describe the CURRENT benchmark config — a
        # stale file from an older scale definition is not this config's
        # number (epochs shift the once-vs-per-epoch FLOP split) — and the
        # CURRENT solver code (a pre-fused row mislabels this round's
        # speed).
        if det.get("dtype") != dtype or any(
            det.get(key) != cfg[key] for key in ("n", "d", "k", "block")
        ) or det.get("epochs") != cfg["iters"] or det.get("solver_rev") != SOLVER_REV:
            return None
        line = dict(line)
    except (OSError, ValueError, AttributeError, TypeError, KeyError):
        # Malformed/legacy state must degrade to the CPU fallback, never
        # break the one-JSON-line contract.
        return None
    line["source"] = "checkride_checkpoint"
    line["measured_at"] = time.strftime(
        "%Y-%m-%dT%H:%M:%S", time.localtime(mtime)
    )
    line["age_hours"] = round(age_h, 1)
    return line


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", action="store_true")
    # --scale default None = pick by backend (tpu scale on a live chip,
    # cpu scale on the fallback); an explicit value wins everywhere.
    ap.add_argument("--scale", choices=list(SCALE), default=None)
    # bf16 = store A in bfloat16, accumulate f32 (config.solver_storage_dtype).
    ap.add_argument("--dtype", choices=["f32", "bf16", "f32h"], default="f32")
    # Generous: first TPU contact through a cold relay can take ~a minute
    # (backend init + tiny-op compile); a dead backend just costs the wait.
    ap.add_argument("--probe-timeout", type=float, default=120.0)
    ap.add_argument("--run-timeout", type=float, default=900.0)
    args = ap.parse_args()

    if args.worker:
        worker(args.scale or "tpu", args.dtype)
        return

    from keystone_tpu.utils.platform import (
        cpu_mesh_env,
        env_forces_cpu,
        probe_backend,
    )

    error = None
    if not env_forces_cpu():
        # An explicit CPU request skips the probe — no point waking the TPU
        # only to force the worker onto CPU anyway.
        info = probe_backend(timeout=args.probe_timeout)
        if info is not None and info.get("platform") != "cpu":
            result = _run_worker(
                dict(os.environ), args.scale or "tpu", args.dtype, args.run_timeout
            )
            if result is not None:
                print(json.dumps(result))
                return
            error = "tpu_run_failed_or_hung"
        elif info is None:
            error = "backend_init_dead_or_hung"
        else:
            # Probe came back alive but CPU-only: in this environment that
            # means the TPU plugin degraded, not that no TPU exists.
            error = "backend_reports_cpu_only"
        if error is not None:
            # Dead/hung chip, but the checkride may have measured this very
            # config on silicon earlier in the round.
            ckpt = _checkride_checkpoint(args.scale or "tpu", args.dtype)
            if ckpt is not None:
                ckpt["backend_error"] = error
                print(json.dumps(ckpt))
                return

    # CPU-mesh fallback: a real measurement, honestly labelled. TPU-sized
    # scales degrade to the cpu scale — a d=262144 solve on the emulated
    # mesh would only hit the run-timeout, not produce a number.
    env = cpu_mesh_env(8)
    fb_scale = "cpu" if (args.scale or "").startswith("tpu") else (args.scale or "cpu")
    result = _run_worker(env, fb_scale, args.dtype, args.run_timeout)
    if result is not None:
        if error:
            result["backend_error"] = error
        print(json.dumps(result))
        return

    print(
        json.dumps(
            {
                "metric": "bcd_solver_tflops_per_chip",
                "value": None,
                "unit": "TFLOPS/chip",
                "vs_baseline": None,
                "error": error or "cpu_fallback_failed",
            }
        )
    )


if __name__ == "__main__":
    main()
