"""Benchmark driver — prints ONE JSON line.

Headline metric (BASELINE.md north star #2): solver TFLOPS/chip of the
block-least-squares inner loop — per-chip MXU gemms (residual update, gram,
gradient) + psum over ICI + replicated Cholesky, the lowering of the
reference's BlockCoordinateDescent/treeAggregate stack (SURVEY.md §3.2).

vs_baseline compares against a nominal 0.3 TFLOPS/node — the dgemm-class
throughput of one of the reference's EC2 r3.4xlarge CPU nodes (16 vcpus;
BASELINE.md has no published per-node figure, so this is a documented
engineering estimate for a sustained f64→f32-class BLAS3 workload).
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_NODE_TFLOPS = 0.3


def bcd_flops(n: int, d: int, k: int, block: int, iters: int) -> float:
    """FLOPs of block_coordinate_descent's device work with gram caching
    (the default for multi-epoch solves): grams + Cholesky once per block,
    then per-epoch residual/rhs gemms and triangular solves."""
    nb = d // block
    once = 2.0 * n * block * block + block**3 / 3.0  # gram + Cholesky
    per_epoch = (
        2.0 * n * block * k  # residual restore  A_b @ W_b
        + 2.0 * n * block * k  # rhs  A_bᵀR
        + 2.0 * block * block * k  # triangular solves
        + 2.0 * n * block * k  # residual update
    )
    return nb * (once + per_epoch * iters)


def main():
    import jax

    from keystone_tpu.linalg import RowMatrix, block_coordinate_descent

    n, d, k, block, iters = 32768, 8192, 16, 2048, 2
    rng = np.random.default_rng(0)
    A = rng.normal(size=(n, d)).astype(np.float32)
    W_true = rng.normal(size=(d, k)).astype(np.float32)
    B = (A @ W_true).astype(np.float32)

    Ma = RowMatrix.from_array(A)
    Mb = RowMatrix.from_array(B)

    def run():
        # cache_grams pinned True so the timed path always matches bcd_flops.
        W, _blocks = block_coordinate_descent(
            Ma, Mb, block_size=block, num_iters=iters, lam=1e-3,
            cache_grams=True,
        )
        for w in W:
            w.block_until_ready()
        return W

    W = run()  # warmup + compile
    # Validity check: timing through flaky transports can lie; a wrong or
    # unconverged solve would make the TFLOPS number meaningless.
    West = np.concatenate([np.asarray(w) for w in W], axis=0)
    resid = float(np.linalg.norm(A @ West - B) / np.linalg.norm(B))
    # Two epochs cut the residual ~92% on this problem; anything worse means
    # the solve (or the transport) is lying and the timing is meaningless.
    assert resid < 0.2, f"BCD did not make progress (resid={resid})"

    # Time enough repetitions to amortize dispatch noise (>= 2s or 5 runs).
    reps, total = 0, 0.0
    while total < 2.0 and reps < 5:
        t0 = time.perf_counter()
        run()
        total += time.perf_counter() - t0
        reps += 1
    dt = total / reps

    n_dev = len(jax.devices())
    tflops_per_chip = bcd_flops(n, d, k, block, iters) / dt / 1e12 / n_dev
    print(
        json.dumps(
            {
                "metric": "bcd_solver_tflops_per_chip",
                "value": round(tflops_per_chip, 3),
                "unit": "TFLOPS/chip",
                "vs_baseline": round(tflops_per_chip / BASELINE_NODE_TFLOPS, 2),
                "detail": {
                    "n": n,
                    "d": d,
                    "k": k,
                    "block": block,
                    "epochs": iters,
                    "seconds_per_solve": round(dt, 4),
                    "relative_residual": round(resid, 6),
                    "devices": n_dev,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
