"""Reference-scale dimensionality stress: d >= 262,144 (VERDICT r2 #3).

The reference's headline problems run at 256k (CIFAR) to 528k (TIMIT)
feature dims (SURVEY.md §6); until this test the streamed solver was only
exercised to 65,536. This stresses the many-block regime end-to-end on the
CPU mesh — memory accounting, per-epoch checkpointing, fingerprint-matched
resume — and records the evidence the round notes cite (peak host RSS,
per-epoch wall, checkpoint bytes) to stdout under `-s`.

Sized for the 1-core CI host: n=512, block=1024 keeps the first-epoch
gram+inverse work ~1 TFLOP and factor residency (d·b·4B = 1 GiB, replicated
8x on the virtual mesh) well inside host RAM.
"""

import os
import resource
import time

import numpy as np
import pytest

from keystone_tpu.linalg import RowMatrix, block_coordinate_descent_streamed

D = 262_144
N = 512
K = 2
BLOCK = 1024


def _peak_rss_bytes() -> int:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024


def _checkpoint_bytes(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            total += os.path.getsize(os.path.join(root, f))
    return total


@pytest.mark.slow
def test_streamed_bcd_at_reference_scale(tmp_path):
    rng = np.random.default_rng(0)
    # Low-rank + noise keeps the synthetic problem solvable at n << d
    # without materializing a (d, k) dense truth on every check.
    A = rng.normal(size=(N, D)).astype(np.float32)
    w_true = rng.normal(size=(D, K)).astype(np.float32) / np.sqrt(D)
    B = (A @ w_true + 0.01 * rng.normal(size=(N, K))).astype(np.float32)
    Mb = RowMatrix.from_array(B)
    ck = str(tmp_path / "ck_256k")

    # Heavy ridge ON PURPOSE: with n << d an unregularized solve
    # interpolates to ~0 residual in one epoch, leaving no margin for the
    # resume-improves assertion; lam ~ 0.1 n keeps epoch-over-epoch
    # progress measurable.
    lam = 50.0
    rss0 = _peak_rss_bytes()
    t0 = time.perf_counter()
    W1, blocks = block_coordinate_descent_streamed(
        A, Mb, block_size=BLOCK, num_iters=1, lam=lam, checkpoint_dir=ck
    )
    t_first = time.perf_counter() - t0
    assert len(blocks) == D // BLOCK == 256
    ck_bytes = _checkpoint_bytes(ck)
    assert ck_bytes > 0  # epoch 1 checkpoint landed

    # Fingerprint-matched resume: epoch 2 continues from the checkpoint
    # (the solve must IMPROVE, proving state actually carried over).
    t0 = time.perf_counter()
    W2, _ = block_coordinate_descent_streamed(
        A, Mb, block_size=BLOCK, num_iters=2, lam=lam, checkpoint_dir=ck
    )
    t_resumed_epoch = time.perf_counter() - t0

    West1 = np.concatenate([np.asarray(w) for w in W1], axis=0)
    West2 = np.concatenate([np.asarray(w) for w in W2], axis=0)
    r1 = float(np.linalg.norm(A @ West1 - B) / np.linalg.norm(B))
    r2 = float(np.linalg.norm(A @ West2 - B) / np.linalg.norm(B))
    assert np.isfinite(r1) and np.isfinite(r2)
    assert r2 < r1  # second epoch from resumed state made progress

    # A different lam must NOT resume this checkpoint (fingerprint guard),
    # even against the SAME dir: a wrong resume with num_iters=1 would
    # return the stored epoch-2 state immediately (W3 == W2); a correct
    # fresh start computes a different (2-lam) solution.
    W3, _ = block_coordinate_descent_streamed(
        A, Mb, block_size=BLOCK, num_iters=1, lam=2 * lam,
        checkpoint_dir=ck,
    )
    West3 = np.concatenate([np.asarray(w) for w in W3], axis=0)
    assert np.isfinite(West3).all()
    assert not np.allclose(West3, West2)  # did not serve foreign state

    peak_rss = _peak_rss_bytes()
    print(
        f"\n[reference-scale d={D}] peak_rss={peak_rss / 1e9:.2f} GB "
        f"(start {rss0 / 1e9:.2f}) first_epoch={t_first:.1f}s "
        f"resumed_epoch={t_resumed_epoch:.1f}s "
        f"checkpoint={ck_bytes / 1e6:.1f} MB residuals r1={r1:.3e} r2={r2:.3e}"
    )
    # Memory sanity: streaming must not materialize another full-size A.
    # Budget: A (0.5 GB) + 8x-replicated factor cache (8 GB) + JAX/XLA
    # overheads; 3x A on top of that would signal an accidental dense copy.
    assert peak_rss < 20e9, f"peak RSS {peak_rss / 1e9:.1f} GB"
