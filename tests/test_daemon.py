"""Networked serving daemon: admission control, SLA tiers, hot-swap.

Acceptance pins (ISSUE 11):

- **Swap-under-load**: sustained concurrent traffic while hot-swapping
  the artifact twice — zero dropped/unresolved requests, every response
  attributable to exactly one generation (bit-identical to that
  generation's model), and a mid-swap ``swap_abort`` fault leaves the
  old generation serving (rollback, not outage) with a forensic dump
  naming the generation and in-flight ids.
- **Admission gate**: at 2x the admitted concurrency, over-quota /
  over-budget tenants fast-fail with 429 BEFORE any device work while
  gold-tier traffic keeps being served within its deadline; the
  flight-recorder journeys cover the network leg end to end
  (accepted → parsed → admitted → submitted → resolved; the HTTP path
  pre-admits on the header key before the body read, so there admitted
  precedes parsed).

Clients here retry on dropped connections: under ``make chaos``
(``conn_drop:0.05``) ~5% of data-plane responses are deliberately lost
after serving, and re-sending a pure serve is exactly what a real
client does — the tests must pass identically.
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from keystone_tpu.config import config
from keystone_tpu.utils import reliability
from keystone_tpu.utils.metrics import reliability_counters
from keystone_tpu.utils.reliability import (
    AuthError,
    QueueFullError,
    QuotaExceeded,
    ServiceClosed,
    SwapAborted,
    active_plan,
)
from keystone_tpu.workflow.daemon import (
    BE_BUDGET_FRAC,
    AdmissionController,
    ServingDaemon,
    Tenant,
    TokenBucket,
    derive_health,
    parse_tenants,
)
from keystone_tpu.workflow.serialization import save_artifact

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")

D = 6


@pytest.fixture
def faults():
    """Arm a fault plan for the test; restores the prior plan after
    (the test_reliability fixture pattern)."""
    prior = (config.faults, config.faults_seed)

    def arm(spec: str, seed: int = 0):
        config.faults, config.faults_seed = spec, seed
        reliability.reset_fault_plan()

    yield arm
    config.faults, config.faults_seed = prior
    reliability.reset_fault_plan()


def _serve_daemon_mod():
    sys.path.insert(0, TOOLS)
    try:
        import serve_daemon
    finally:
        sys.path.pop(0)
    return serve_daemon


def _socket_client():
    return _serve_daemon_mod().SocketClient


def _build_pipeline(seed=0):
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.nodes.stats.random_features import CosineRandomFeatures

    return (
        CosineRandomFeatures.create(D, 12, seed=seed)
        .and_then(L2Normalizer())
        .fit()
    )


def _save(tmp_path, seed, tag):
    pipe = _build_pipeline(seed)
    path = str(tmp_path / f"model_{tag}.kart")
    save_artifact(pipe, path, feature_shape=(D,), dtype="float32")
    return pipe, path


def _post(port, path, body, headers=None, timeout=60, retries=8):
    """The SHIPPED retrying client (tools/serve_daemon.http_post), with
    test-friendly defaults: an injected conn_drop loses only the
    response of an already-served pure request; re-sending is the real
    client behavior."""
    return _serve_daemon_mod().http_post(
        port, path, body, headers, timeout=timeout, retries=retries
    )


def _get(port, path, timeout=30):
    status, body = _serve_daemon_mod().http_get(port, path, timeout=timeout)
    return status, json.loads(body)


def _settle(daemon, timeout=10.0):
    """Wait for server-side bookkeeping to settle: finish_request runs
    AFTER the response write, so a client can observe its answer a beat
    before the journey closes. Returns the settled snapshot."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        snap = daemon._flight.snapshot()
        if daemon.stats()["active_requests"] == 0 and all(
            r["outcome"] is not None for r in snap["records"]
        ):
            return snap
        time.sleep(0.01)
    return daemon._flight.snapshot()


def _socket_request(SocketClient, port, doc, retries=8):
    last = None
    for _ in range(retries):
        sc = SocketClient(port)
        try:
            return sc.request(doc)
        except (ConnectionError, OSError) as e:
            last = e
        finally:
            sc.close()
    raise last


# ---------------------------------------------------------------------------
# Admission units (no daemon)
# ---------------------------------------------------------------------------


def test_parse_tenants_and_errors():
    tenants = parse_tenants(
        "acme:sk-1:100:gold,free:sk-2:5,bulk:sk-3:2.5:best_effort:9"
    )
    assert set(tenants) == {"sk-1", "sk-2", "sk-3"}
    assert tenants["sk-1"].tier == "gold" and tenants["sk-1"].qps == 100
    assert tenants["sk-2"].tier == "best_effort"
    assert tenants["sk-3"].burst == 9
    assert parse_tenants("") == {}
    with pytest.raises(ValueError, match="expected"):
        parse_tenants("nokey")
    with pytest.raises(ValueError, match="duplicate"):
        parse_tenants("a:k:1,b:k:2")
    with pytest.raises(ValueError, match="tier"):
        Tenant("x", "k", tier="platinum")


def test_token_bucket_rate_and_refill():
    tb = TokenBucket(rate=50.0, burst=2.0)
    assert tb.try_acquire() and tb.try_acquire()
    assert not tb.try_acquire()  # burst exhausted
    time.sleep(0.06)  # 50/s refills ~3 tokens (capped at burst 2)
    assert tb.try_acquire()
    assert TokenBucket(rate=0.0, burst=1.0).try_acquire()  # unlimited


def test_admission_quota_budget_and_gold_headroom():
    tenants = {
        "g": Tenant("gold", "g", qps=0, tier="gold"),
        "b": Tenant("be", "b", qps=0, tier="best_effort"),
        "q": Tenant("capped", "q", qps=1, burst=1, tier="best_effort"),
    }
    adm = AdmissionController(tenants, pending_budget=10)
    with pytest.raises(AuthError):
        adm.admit("unknown-key")
    with pytest.raises(AuthError):
        adm.admit(None)
    # Quota: burst 1 -> the second immediate request is over quota.
    adm.admit("q")
    with pytest.raises(QuotaExceeded):
        adm.admit("q")
    # Budget priority: best-effort refused at BE_BUDGET_FRAC of the
    # budget, gold admitted up to the full budget.
    be_limit = int(10 * BE_BUDGET_FRAC)
    while adm.inflight() < be_limit:
        adm.admit("b")
    with pytest.raises(QueueFullError):
        adm.admit("b")
    while adm.inflight() < 10:
        adm.admit("g")  # gold rides the reserved headroom
    with pytest.raises(QueueFullError):
        adm.admit("g")
    # Releases reopen the gate.
    adm.release()
    assert adm.admit("g").tier == "gold"
    stats = adm.stats()
    assert stats["rejected_auth"] == 2
    assert stats["rejected_quota"] == 1
    assert stats["rejected_budget"] == 2


def test_derive_health_draining_and_identity():
    healthy, doc = derive_health({
        "worker_alive": True, "closed": False, "draining": False,
        "generation": 3, "artifact_fingerprint": "abc",
    })
    assert healthy and doc["generation"] == 3
    assert doc["artifact_fingerprint"] == "abc"
    healthy, doc = derive_health({
        "worker_alive": True, "closed": False, "draining": True,
        "generation": 3, "artifact_fingerprint": "abc",
    })
    assert not healthy and doc["draining"] is True


def test_daemon_threads_registered_in_keystone_lint():
    sys.path.insert(0, TOOLS)
    try:
        import keystone_lint
    finally:
        sys.path.pop(0)
    assert {"_accept_loop", "_serve_conn", "_swap_loop"} <= (
        keystone_lint.KNOWN_THREAD_TARGETS
    )


# ---------------------------------------------------------------------------
# Live daemon: both wires, admission, healthz
# ---------------------------------------------------------------------------


def test_daemon_http_socket_and_network_leg_journeys(tmp_path):
    pipe, art_path = _save(tmp_path, 0, "v1")
    SocketClient = _socket_client()
    tenants = {"sk-g": Tenant("acme", "sk-g", qps=0, tier="gold")}
    rng = np.random.default_rng(0)
    X = rng.normal(size=(3, D)).astype(np.float32)
    ref = np.asarray(pipe.apply(X).get())
    with ServingDaemon(
        artifact=art_path, tenants=tenants, devices=1, buckets=(4,),
        max_delay_ms=1.0, name="t-basic", gold_deadline_ms=60000,
        flight_dir=str(tmp_path),
    ) as daemon:
        st, doc = _post(daemon.http_port, "/predict", {"x": X.tolist()},
                        {"X-API-Key": "sk-g"})
        assert st == 200
        assert doc["generation"] == 0 and doc["tier"] == "gold"
        np.testing.assert_array_equal(
            np.asarray(doc["y"], np.float32), ref
        )
        # Single-datum request: feature-shaped in, feature-shaped out.
        st, doc1 = _post(daemon.http_port, "/predict",
                         {"x": X[0].tolist()}, {"X-API-Key": "sk-g"})
        assert st == 200
        np.testing.assert_array_equal(
            np.asarray(doc1["y"], np.float32), ref[0]
        )
        # The framed socket wire answers bit-identically.
        resp = _socket_request(
            SocketClient, daemon.socket_port,
            {"x": X.tolist(), "key": "sk-g"},
        )
        assert resp["status"] == 200 and resp["generation"] == 0
        np.testing.assert_array_equal(
            np.asarray(resp["y"], np.float32), ref
        )
        # Malformed payloads are 400s, not crashes.
        assert _post(daemon.http_port, "/predict", {"nope": 1},
                     {"X-API-Key": "sk-g"})[0] == 400
        assert _post(daemon.http_port, "/predict",
                     {"x": [[1.0] * (D + 1)]},
                     {"X-API-Key": "sk-g"})[0] == 400
        # Garbage deadline_ms: 400 BEFORE admission — a malformed field
        # must never leak an admission slot (review-found DoS).
        assert _post(daemon.http_port, "/predict",
                     {"x": X.tolist(), "deadline_ms": "fast"},
                     {"X-API-Key": "sk-g"})[0] == 400
        # ...and the header spelling of the same mistake: explicit but
        # unreadable overrides 400 too (no silent tier-default fallback).
        assert _post(daemon.http_port, "/predict", {"x": X.tolist()},
                     {"X-API-Key": "sk-g", "X-Deadline-Ms": "soon"},
                     )[0] == 400
        _settle(daemon)  # slot release runs just after the 400 write
        assert daemon.stats()["admission"]["inflight"] == 0
        # /healthz carries the generation identity.
        st, health = _get(daemon.http_port, "/healthz")
        assert st == 200 and health["healthy"] is True
        assert health["generation"] == 0
        assert health["artifact_fingerprint"] == daemon.artifact_fingerprint
        assert health["draining"] is False
        # The network leg is journaled end to end for every ok request.
        snap = _settle(daemon)
        ok = [r for r in snap["records"] if r["outcome"] == "ok"]
        assert ok, "expected at least one ok journey"
        for r in ok:
            phases = [p["phase"] for p in r["phases"]]
            assert phases[0] == "accepted"
            for needed in ("parsed", "admitted", "submitted", "resolved"):
                assert needed in phases
            stamps = [p["t_ns"] for p in r["phases"]]
            assert stamps == sorted(stamps), "journey stamps not monotone"
            assert r["meta"]["tenant"] == "acme"
            assert r["meta"]["generation"] == 0
            assert r["meta"]["status"] == 200
        assert daemon.stats()["active_requests"] == 0


def test_daemon_auth_and_quota_fast_fail_before_device_work(tmp_path):
    _, art_path = _save(tmp_path, 0, "v1")
    tenants = {
        "sk-g": Tenant("acme", "sk-g", qps=0, tier="gold"),
        "sk-q": Tenant("capped", "sk-q", qps=1, burst=2,
                       tier="best_effort"),
    }
    x = [[0.5] * D]
    with ServingDaemon(
        artifact=art_path, tenants=tenants, devices=1, buckets=(4,),
        name="t-adm", gold_deadline_ms=60000, flight_dir=str(tmp_path),
    ) as daemon:
        assert _post(daemon.http_port, "/predict", x and {"x": x})[0] == 403
        assert _post(daemon.http_port, "/predict", {"x": x},
                     {"X-API-Key": "wrong"})[0] == 403
        before = daemon.stats()
        codes = [
            _post(daemon.http_port, "/predict", {"x": x},
                  {"X-API-Key": "sk-q"})[0]
            for _ in range(8)
        ]
        assert codes.count(429) >= 4, codes
        _settle(daemon)
        after = daemon.stats()
        # 429s never reached the device service: it saw exactly the
        # ADMITTED requests, no more. (Client-visible 200 counts can
        # run below the admitted delta under `make chaos` — a dropped
        # response is retried, and the retry is a fresh admission.)
        assert (
            after["service"]["requests"] - before["service"]["requests"]
            == after["admission"]["admitted"]
            - before["admission"]["admitted"]
        )
        assert codes.count(200) <= (
            after["admission"]["admitted"] - before["admission"]["admitted"]
        )
        adm = daemon.stats()["admission"]
        assert adm["rejected_quota"] >= 4
        assert adm["rejected_auth"] >= 2
        # Rejected journeys carry the network leg too.
        snap = _settle(daemon)
        rejected = [r for r in snap["records"] if r["outcome"] == "rejected"]
        assert rejected
        assert all(
            [p["phase"] for p in r["phases"]][0] == "accepted"
            for r in rejected
        )


def test_daemon_admission_gate_under_2x_concurrency(tmp_path):
    """Acceptance pin: flood at 2x the admitted best-effort concurrency
    through the real socket — the excess fast-fails 429 at admission
    (zero device work) while concurrent gold traffic is served in full
    within its deadline."""
    _, art_path = _save(tmp_path, 0, "v1")
    SocketClient = _socket_client()
    tenants = {
        "sk-g": Tenant("acme", "sk-g", qps=0, tier="gold"),
        "sk-b": Tenant("flood", "sk-b", qps=0, tier="best_effort"),
    }
    budget = 4
    be_limit = max(1, int(budget * BE_BUDGET_FRAC))  # = 3
    clients = 2 * be_limit
    gold_deadline_ms = 30000.0
    with ServingDaemon(
        artifact=art_path, tenants=tenants, devices=1, buckets=(4,),
        max_rows=4, max_delay_ms=0.5, pending_budget=budget,
        gold_deadline_ms=gold_deadline_ms, name="t-gate",
        flight_dir=str(tmp_path),
    ) as daemon:
        lock = threading.Lock()
        flood_codes: list = []
        gold_results: list = []
        stop = threading.Event()

        def flood():
            end = time.perf_counter() + 1.5
            while time.perf_counter() < end:
                try:
                    resp = _socket_request(
                        SocketClient, daemon.socket_port,
                        {"x": [[0.25] * D], "key": "sk-b"}, retries=2,
                    )
                    with lock:
                        flood_codes.append(resp["status"])
                except (ConnectionError, OSError):
                    continue  # injected drop after serving; just go on

        def gold():
            while not stop.is_set():
                t0 = time.perf_counter()
                st, _doc = _post(daemon.http_port, "/predict",
                                 {"x": [[0.1] * D]}, {"X-API-Key": "sk-g"})
                with lock:
                    gold_results.append((st, time.perf_counter() - t0))
                time.sleep(0.01)

        threads = [threading.Thread(target=flood) for _ in range(clients)]
        gold_t = threading.Thread(target=gold)
        for t in threads:
            t.start()
        gold_t.start()
        for t in threads:
            t.join()
        stop.set()
        gold_t.join(timeout=30)

        assert flood_codes.count(429) > 0, "backpressure never engaged"
        assert all(c in (200, 429, 504) for c in flood_codes)
        # Gold rode its reserved headroom: served in full, within SLA.
        assert gold_results
        gold_codes = [c for c, _ in gold_results]
        assert all(c == 200 for c in gold_codes), gold_codes
        gold_lat_ms = sorted(t * 1e3 for _, t in gold_results)
        p99 = gold_lat_ms[min(len(gold_lat_ms) - 1,
                              int(0.99 * len(gold_lat_ms)))]
        assert p99 <= gold_deadline_ms
        # Fast-fail happened at admission, not after device work: the
        # service only ever saw admitted requests.
        _settle(daemon)
        stats = daemon.stats()
        assert stats["admission"]["rejected_budget"] > 0
        assert stats["service"]["requests"] == stats["admission"]["admitted"]
        assert stats["active_requests"] == 0


# ---------------------------------------------------------------------------
# Hot swap
# ---------------------------------------------------------------------------


def test_daemon_swap_under_load_two_swaps(tmp_path):
    """Acceptance pin: sustained concurrent traffic across TWO hot
    swaps — zero dropped/unresolved, every response attributable to
    exactly one generation and bit-identical to that generation's
    model."""
    p1, a1 = _save(tmp_path, 0, "v1")
    p2, a2 = _save(tmp_path, 1, "v2")
    SocketClient = _socket_client()
    rng = np.random.default_rng(1)
    X = rng.normal(size=(2, D)).astype(np.float32)
    refs = {
        0: np.asarray(p1.apply(X).get()),
        1: np.asarray(p2.apply(X).get()),
        2: np.asarray(p1.apply(X).get()),  # swap back to v1
    }
    with ServingDaemon(
        artifact=a1, devices=1, buckets=(4,), max_delay_ms=0.5,
        name="t-swap", be_deadline_ms=0, flight_dir=str(tmp_path),
    ) as daemon:
        stop = threading.Event()
        lock = threading.Lock()
        responses: list = []
        failures: list = []

        def http_traffic():
            while not stop.is_set():
                st, doc = _post(daemon.http_port, "/predict",
                                {"x": X.tolist()},
                                {"X-Trace-Id": "swap-trace-http"})
                with lock:
                    if st == 200:
                        if doc.get("trace_id") != "swap-trace-http":
                            failures.append(("trace", doc.get("trace_id")))
                        responses.append(
                            (doc["generation"],
                             np.asarray(doc["y"], np.float32))
                        )
                    else:
                        failures.append((st, doc.get("error")))

        def socket_traffic():
            while not stop.is_set():
                try:
                    resp = _socket_request(
                        SocketClient, daemon.socket_port,
                        {"x": X.tolist(),
                         "trace_id": "swap-trace-sock"},
                    )
                except (ConnectionError, OSError):
                    continue
                with lock:
                    if resp["status"] == 200:
                        if resp.get("trace_id") != "swap-trace-sock":
                            failures.append(
                                ("trace", resp.get("trace_id"))
                            )
                        responses.append(
                            (resp["generation"],
                             np.asarray(resp["y"], np.float32))
                        )
                    else:
                        failures.append(
                            (resp["status"], resp.get("error"))
                        )

        threads = [
            threading.Thread(target=http_traffic),
            threading.Thread(target=http_traffic),
            threading.Thread(target=socket_traffic),
        ]
        for t in threads:
            t.start()
        try:
            time.sleep(0.2)
            assert daemon.request_swap(a2, timeout_s=120) == 1
            time.sleep(0.2)
            assert daemon.request_swap(a1, timeout_s=120) == 2
            time.sleep(0.2)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=60)
        assert not failures, failures
        assert len(responses) > 10
        gens = sorted({g for g, _ in responses})
        assert set(gens) <= {0, 1, 2} and 0 in gens and 2 in gens
        # Exactly-one-generation attribution, verified by VALUE: each
        # response matches precisely its tagged generation's model.
        for gen, y in responses:
            np.testing.assert_array_equal(y, refs[gen])
        assert daemon.generation == 2
        st, health = _get(daemon.http_port, "/healthz")
        assert st == 200 and health["generation"] == 2
        snap = _settle(daemon)
        # Trace-id continuity across the swap: journeys tagged with the
        # client's id span more than one generation (the echo itself is
        # asserted per-response in the traffic loops).
        gens_by_trace = {
            r["meta"].get("generation")
            for r in snap["records"]
            if (r.get("meta") or {}).get("trace_id") == "swap-trace-http"
            and r["outcome"] == "ok"
        }
        assert len(gens_by_trace) >= 2
        stats = daemon.stats()
        assert stats["swaps"] == 2 and stats["swap_failures"] == 0
        assert stats["active_requests"] == 0
        assert stats["service"]["pending"] == 0


def test_daemon_replica_by_replica_handoff(tmp_path):
    """devices=2: the successor warms replica-by-replica while the old
    generation drains one replica at a time (never the last), and
    /healthz reports 503 draining:true mid-swap."""
    _, a1 = _save(tmp_path, 0, "v1")
    _, a2 = _save(tmp_path, 1, "v2")
    seen = {}

    def hook(daemon):
        # Between the successor's warmup and the flip: the OLD service
        # must still be answering, one replica retired, one kept live.
        old_stats = daemon._gen.service.stats()
        seen["retired"] = old_stats["replicas"]["retired"]
        seen["worker_alive"] = old_stats["worker_alive"]
        healthy, doc = derive_health(daemon.health_stats())
        seen["healthy_mid_swap"] = healthy
        seen["draining_mid_swap"] = doc["draining"]
        st, body = _get(daemon.http_port, "/healthz")
        seen["healthz_status_mid_swap"] = st
        # Traffic STILL lands on the old generation mid-drain.
        st, resp = _post(daemon.http_port, "/predict",
                         {"x": [[0.5] * D]})
        seen["mid_swap_predict"] = (st, resp.get("generation"))

    with ServingDaemon(
        artifact=a1, devices=2, buckets=(4,), max_delay_ms=0.5,
        name="t-handoff", swap_hook=hook, flight_dir=str(tmp_path),
    ) as daemon:
        assert daemon.request_swap(a2, timeout_s=180) == 1
        assert seen["retired"] == [True, False]
        assert seen["worker_alive"] is True
        assert seen["healthy_mid_swap"] is False
        assert seen["draining_mid_swap"] is True
        assert seen["healthz_status_mid_swap"] == 503
        assert seen["mid_swap_predict"] == (200, 0)
        # Post-flip: healthy again on the new generation.
        st, health = _get(daemon.http_port, "/healthz")
        assert st == 200 and health["generation"] == 1
        assert health["draining"] is False
        st, doc = _post(daemon.http_port, "/predict", {"x": [[0.5] * D]})
        assert st == 200 and doc["generation"] == 1


def test_daemon_swap_abort_rolls_back(tmp_path, faults):
    """Acceptance pin: a mid-swap swap_abort fault leaves the old
    generation serving — rollback, not outage — and force-dumps
    forensics naming the generation and the in-flight ids."""
    _, a1 = _save(tmp_path, 0, "v1")
    _, a2 = _save(tmp_path, 1, "v2")
    faults("swap_abort:1")
    flight_dir = str(tmp_path / "flight")
    os.makedirs(flight_dir, exist_ok=True)
    with ServingDaemon(
        artifact=a1, devices=1, buckets=(4,), name="t-abort",
        flight_dir=flight_dir,
    ) as daemon:
        st, doc = _post(daemon.http_port, "/predict", {"x": [[1.0] * D]})
        assert st == 200 and doc["generation"] == 0
        with pytest.raises(SwapAborted):
            daemon.request_swap(a2, timeout_s=120)
        # Rollback: generation unchanged, old model still answering.
        assert daemon.generation == 0
        st, doc = _post(daemon.http_port, "/predict", {"x": [[1.0] * D]})
        assert st == 200 and doc["generation"] == 0
        stats = daemon.stats()
        assert stats["swap_failures"] == 1 and stats["swaps"] == 0
        # Forensic dump: names the reason, the surviving generation, and
        # the in-flight ids at abort time.
        dumps = [f for f in os.listdir(flight_dir) if "swap_abort" in f]
        assert dumps, os.listdir(flight_dir)
        with open(os.path.join(flight_dir, dumps[0])) as f:
            dump = json.load(f)
        assert dump["reason"] == "swap_abort"
        abort_events = [
            e for e in dump["errors"] if e["kind"] == "swap_abort"
        ]
        assert abort_events
        assert "generation 0 keeps serving" in abort_events[0]["message"]
        assert "in-flight request ids" in abort_events[0]["message"]
        assert dump["stats"]["generation"] == 0
        # The fault is consumed: the NEXT swap succeeds (the abort left
        # nothing wedged).
        assert daemon.request_swap(a2, timeout_s=120) == 1
        st, health = _get(daemon.http_port, "/healthz")
        assert st == 200 and health["generation"] == 1


def test_daemon_swap_rejects_bad_artifact(tmp_path):
    _, a1 = _save(tmp_path, 0, "v1")
    bad = str(tmp_path / "bad.kart")
    with open(bad, "wb") as f:
        f.write(b"not an artifact")
    with ServingDaemon(
        artifact=a1, devices=1, buckets=(4,), name="t-badswap",
        flight_dir=str(tmp_path),
    ) as daemon:
        st, doc = _post(daemon.http_port, "/swap", {"artifact": bad},
                        timeout=120)
        assert st == 409
        assert doc["error"] == "ArtifactVersionError"
        assert daemon.generation == 0
        # Wrong fingerprint pin refused the same way.
        st, doc = _post(
            daemon.http_port, "/swap",
            {"artifact": a1, "expect_fingerprint": "feedface"}, timeout=120,
        )
        assert st == 409 and daemon.generation == 0


# ---------------------------------------------------------------------------
# Wire-propagated trace context + SLO surfaces
# ---------------------------------------------------------------------------


def test_daemon_trace_id_adopt_mint_and_error_echo(tmp_path):
    """Propagation contract: a well-formed inbound X-Trace-Id is
    adopted verbatim and echoed (header AND body) on every response —
    200s and errors alike; a malformed one is replaced by a minted id,
    never a rejection."""
    import urllib.request

    from keystone_tpu.utils.telemetry import TRACE_ID_RE

    _, a1 = _save(tmp_path, 0, "v1")
    tenants = {"sk-g": Tenant("acme", "sk-g", qps=0, tier="gold")}
    x = [[1.0] * D]
    with ServingDaemon(
        artifact=a1, tenants=tenants, devices=1, buckets=(4,),
        name="t-trace", gold_deadline_ms=60000, flight_dir=str(tmp_path),
    ) as daemon:
        req = urllib.request.Request(
            f"http://127.0.0.1:{daemon.http_port}/predict",
            data=json.dumps({"x": x}).encode(),
            headers={"X-API-Key": "sk-g", "X-Trace-Id": "client.trace:1",
                     "Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            doc = json.loads(resp.read())
            assert resp.headers["X-Trace-Id"] == "client.trace:1"
        assert doc["trace_id"] == "client.trace:1"
        # ...and the journey carries the same id.
        snap = _settle(daemon)
        assert any(
            (r.get("meta") or {}).get("trace_id") == "client.trace:1"
            for r in snap["records"]
        )
        # Malformed (whitespace) -> minted; the request is still served.
        st, doc = _post(daemon.http_port, "/predict", {"x": x},
                        {"X-API-Key": "sk-g", "X-Trace-Id": "bad id!"})
        assert st == 200
        assert doc["trace_id"] != "bad id!"
        assert TRACE_ID_RE.match(doc["trace_id"])
        # Errors echo too: 400 (bad shape) keeps the client's id...
        st, doc = _post(daemon.http_port, "/predict",
                        {"x": [[1.0] * (D + 1)]},
                        {"X-API-Key": "sk-g", "X-Trace-Id": "err-trace"})
        assert st == 400 and doc["trace_id"] == "err-trace"
        # ...and so does a 403 (unknown key, pre-admitted on headers).
        st, doc = _post(daemon.http_port, "/predict", {"x": x},
                        {"X-API-Key": "sk-nope",
                         "X-Trace-Id": "auth-trace"}, retries=1)
        assert st == 403 and doc["trace_id"] == "auth-trace"


def test_daemon_socket_trace_roundtrip_and_mint(tmp_path):
    """The framed wire's spelling of the same contract: ``trace_id`` in
    the request frame comes back on the response frame — adopted when
    well-formed, minted otherwise, present even with none sent."""
    from keystone_tpu.utils.telemetry import TRACE_ID_RE

    _, a1 = _save(tmp_path, 0, "v1")
    SocketClient = _socket_client()
    x = [[1.0] * D]
    with ServingDaemon(
        artifact=a1, devices=1, buckets=(4,), name="t-socktrace",
        flight_dir=str(tmp_path),
    ) as daemon:
        resp = _socket_request(
            SocketClient, daemon.socket_port,
            {"x": x, "trace_id": "sock.trace-9"},
        )
        assert resp["status"] == 200
        assert resp["trace_id"] == "sock.trace-9"
        resp = _socket_request(
            SocketClient, daemon.socket_port,
            {"x": x, "trace_id": "no spaces allowed"},
        )
        assert resp["status"] == 200
        assert resp["trace_id"] != "no spaces allowed"
        assert TRACE_ID_RE.match(resp["trace_id"])
        resp = _socket_request(SocketClient, daemon.socket_port, {"x": x})
        assert resp["status"] == 200
        assert TRACE_ID_RE.match(resp["trace_id"])
        # Rejections echo too: a 400 (wrong feature shape) answers with
        # the id the frame carried; an unparseable frame (adoption never
        # ran) still answers with the minted placeholder.
        resp = _socket_request(
            SocketClient, daemon.socket_port,
            {"x": [[1.0] * (D + 1)], "trace_id": "bad-shape-trace"},
        )
        assert resp["status"] == 400
        assert resp["trace_id"] == "bad-shape-trace"
        resp = _socket_request(
            SocketClient, daemon.socket_port, {"nope": 1}
        )
        assert resp["status"] == 400
        assert TRACE_ID_RE.match(resp["trace_id"])


def test_daemon_stats_slo_latency_and_metrics_gauges(tmp_path, monkeypatch):
    """/stats carries the SLO block (tenant names redacted for
    anonymous callers), per-tier latency percentiles, and telemetry
    accounting; /metrics exports per-tier SLO gauges plus the
    tracer/telemetry loss counters — with tenant names NEVER on the
    open scrape surface."""
    from keystone_tpu.utils.metrics import telemetry_counters
    from keystone_tpu.utils.telemetry import reset_telemetry

    _, a1 = _save(tmp_path, 0, "v1")
    tenants = {"sk-g": Tenant("acme-corp", "sk-g", qps=0, tier="gold")}
    x = [[1.0] * D]
    # Telemetry ON (so the accounting counters move) and a 2-slot
    # journey ring (so evictions — the flight-recorder loss counter —
    # actually fire under 4 requests).
    monkeypatch.setenv("KEYSTONE_TELEMETRY_DIR", str(tmp_path / "tel"))
    monkeypatch.setattr(config, "flight_records", 2)
    reset_telemetry()
    telemetry_counters.reset()
    try:
        with ServingDaemon(
            artifact=a1, tenants=tenants, devices=1, buckets=(4,),
            name="t-slo", gold_deadline_ms=60000,
            flight_dir=str(tmp_path), swap_token="s3cret",
        ) as daemon:
            for _ in range(3):
                assert _post(daemon.http_port, "/predict", {"x": x},
                             {"X-API-Key": "sk-g"})[0] == 200
            # A client-caused 400 must NOT enter the SLO denominator.
            assert _post(daemon.http_port, "/predict",
                         {"x": [[1.0] * (D + 1)]},
                         {"X-API-Key": "sk-g"})[0] == 400
            _settle(daemon)
            st, stats = _get(daemon.http_port, "/stats")
            assert st == 200
            slo = stats["slo"]
            # Anonymous caller: tenant keys collapsed to "*".
            assert "acme-corp" not in json.dumps(slo)
            entry = slo["tenants"]["*"]["gold"]
            plan = active_plan()
            if plan is not None and "conn_drop" in plan.sites:
                # Chaos runs: an injected conn_drop loses only the
                # response; the shipped retrying client re-sends, so
                # each drop adds one more (good) serve to the
                # denominator. The exact-count pin holds clean runs.
                assert entry["total"] >= 3
                assert entry["good"] == entry["total"]
            else:
                assert entry["total"] == 3 and entry["good"] == 3
            assert entry["hit_rate"] == 1.0 and entry["burn"] == 0.0
            # Per-tier latency percentiles ride /stats next to the SLO.
            lat = stats["latency"]["gold"]
            assert lat["count"] >= 3 and lat["p99_ms"] > 0
            # Telemetry accounting rides /stats too.
            assert stats["telemetry"]["enqueued"] >= 3
            # The operator (swap-token holder) sees the breakdown.
            import urllib.request

            req = urllib.request.Request(
                f"http://127.0.0.1:{daemon.http_port}/stats",
                headers={"X-Swap-Token": "s3cret"},
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                full = json.loads(resp.read())
            assert "acme-corp" in full["slo"]["tenants"]
            # The 2-slot ring evicted resolved journeys, counted.
            assert full["flight"]["records_evicted"] >= 1
            # /metrics: per-tier SLO gauges, tracer + telemetry loss
            # accounting, journey-ring evictions — no tenant names.
            st, body = _serve_daemon_mod().http_get(
                daemon.http_port, "/metrics"
            )
            assert st == 200
            body = body.decode() if isinstance(body, bytes) else body
            assert "keystone_daemon_slo_gold" in body
            assert "hit_rate" in body and "burn" in body
            assert "keystone_tracer_" in body
            assert "keystone_telemetry_total" in body
            assert "records_enqueued" in body
            assert "journeys_evicted" in body
            assert "acme-corp" not in body
    finally:
        reset_telemetry()


# ---------------------------------------------------------------------------
# conn_drop semantics
# ---------------------------------------------------------------------------


def test_daemon_conn_drop_journey_and_no_stranded_future(tmp_path, faults):
    """A dropped client connection loses the RESPONSE, never the work:
    the journey shows outcome conn_drop, the admission slot frees, and
    a retried request succeeds."""
    _, a1 = _save(tmp_path, 0, "v1")
    faults("conn_drop:1")
    reliability_counters.reset()
    with ServingDaemon(
        artifact=a1, devices=1, buckets=(4,), name="t-drop",
        flight_dir=str(tmp_path),
    ) as daemon:
        x = [[1.0] * D]
        # First data-plane response is dropped mid-write; the retry
        # (a fresh request) is served. Both carry the client's trace id.
        st, doc = _post(daemon.http_port, "/predict", {"x": x},
                        {"X-Trace-Id": "drop-trace-1"})
        assert st == 200
        assert doc["trace_id"] == "drop-trace-1"
        snap = _settle(daemon)
        outcomes = [r["outcome"] for r in snap["records"]]
        assert "conn_drop" in outcomes
        assert "ok" in outcomes
        dropped = [r for r in snap["records"]
                   if r["outcome"] == "conn_drop"]
        # The dropped request WAS served end to end: its journey has the
        # full network leg (through submitted) before the drop.
        phases = [p["phase"] for p in dropped[0]["phases"]]
        assert "submitted" in phases and phases[0] == "accepted"
        # Trace-id continuity under failure: the client vanished, but
        # the conn_drop journey is still findable by the id it sent.
        assert dropped[0]["meta"]["trace_id"] == "drop-trace-1"
        assert daemon._outcomes.snapshot().get("conn_drop", 0) >= 1
        assert reliability_counters.get("faults_injected_conn_drop") >= 1
        # Zero unresolved: no admission slot or active record leaked.
        stats = daemon.stats()
        assert stats["active_requests"] == 0
        assert stats["admission"]["inflight"] == 0
        assert stats["service"]["pending"] == 0


def test_daemon_socket_conn_drop(tmp_path, faults):
    _, a1 = _save(tmp_path, 0, "v1")
    SocketClient = _socket_client()
    faults("conn_drop:1")
    with ServingDaemon(
        artifact=a1, devices=1, buckets=(4,), name="t-sockdrop",
        flight_dir=str(tmp_path),
    ) as daemon:
        resp = _socket_request(
            SocketClient, daemon.socket_port,
            {"x": [[1.0] * D], "trace_id": "sock-drop-trace"},
        )
        assert resp["status"] == 200  # the retry after the dropped conn
        assert resp["trace_id"] == "sock-drop-trace"
        snap = _settle(daemon)
        dropped = [r for r in snap["records"]
                   if r["outcome"] == "conn_drop"]
        assert dropped
        assert dropped[0]["meta"]["trace_id"] == "sock-drop-trace"
        assert daemon.stats()["active_requests"] == 0


# ---------------------------------------------------------------------------
# Integration: metrics server reuse + the make serve-daemon smoke
# ---------------------------------------------------------------------------


def test_metrics_server_healthz_carries_generation(tmp_path):
    sys.path.insert(0, TOOLS)
    try:
        from metrics_server import MetricsServer, _fetch
    finally:
        sys.path.pop(0)
    _, a1 = _save(tmp_path, 0, "v1")
    with ServingDaemon(
        artifact=a1, devices=1, buckets=(4,), name="t-ms",
        flight_dir=str(tmp_path),
    ) as daemon:
        with MetricsServer(port=0,
                           health_source=daemon.health_stats) as server:
            st, body = _fetch(server.url("/healthz"))
            doc = json.loads(body)
            assert st == 200
            assert doc["generation"] == 0
            assert doc["artifact_fingerprint"] == daemon.artifact_fingerprint
            assert doc["draining"] is False

    # A draining health source flips to 503 with draining:true — the
    # early load-balancer signal — without any daemon in the loop.
    def draining_source():
        return {"worker_alive": True, "closed": False, "draining": True,
                "generation": 7, "artifact_fingerprint": "ff00"}

    with MetricsServer(port=0, health_source=draining_source) as server:
        st, body = _fetch(server.url("/healthz"))
        doc = json.loads(body)
        assert st == 503
        assert doc["draining"] is True and doc["generation"] == 7


def test_serve_daemon_smoke_in_process(tmp_path):
    """`make serve-daemon`, in-process (the obs-serve idiom): the gate
    can never silently rot."""
    sys.path.insert(0, TOOLS)
    try:
        import serve_daemon
    finally:
        sys.path.pop(0)
    result = serve_daemon.run_smoke(out_dir=str(tmp_path))
    assert result["ok"], result["pass"]


# ---------------------------------------------------------------------------
# Review-round pins: construction failure, close deadline, key redaction
# ---------------------------------------------------------------------------


def test_daemon_ingress_bind_failure_leaks_nothing():
    """An occupied socket port fails __init__ AFTER the generation-0
    service/swap worker are running — the failure must tear all of it
    down (a retrying operator process would otherwise accumulate thread
    pools and keep the ephemeral HTTP port wedged)."""
    import socket as socket_mod

    blocker = socket_mod.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    taken = blocker.getsockname()[1]
    try:
        before = {
            t.name for t in threading.enumerate()
            if t.name.startswith("keystone-serve")
        }
        with pytest.raises(OSError):
            ServingDaemon(
                pipeline=_build_pipeline(),
                http_port=0,
                socket_port=taken,
                feature_shape=(D,),
                name="bindfail",
            )
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            leaked = [
                t.name for t in threading.enumerate()
                if "bindfail" in t.name
                or (t.name.startswith("keystone-serve")
                    and t.name not in before)
            ]
            if not leaked:
                break
            time.sleep(0.05)
        assert leaked == []
    finally:
        blocker.close()


def test_service_close_join_s_is_a_total_deadline():
    """close(join_s=) bounds the TOTAL drain wait, not per-thread-join:
    the documented KEYSTONE_SWAP_DRAIN_MS contract for the hot-swap
    flip. Pinned with never-exiting stand-in completer threads — the
    per-join behavior would wait join_s for EACH of them."""
    from keystone_tpu.workflow.serving import CompiledPipeline, PipelineService

    cp = CompiledPipeline(_build_pipeline(), max_batch=8).warmup((D,))
    svc = PipelineService(cp)
    try:
        svc.submit(np.ones((1, D), dtype=np.float32)).result(timeout=30)
        park = threading.Event()
        stuck = [
            threading.Thread(target=park.wait, daemon=True)
            for _ in range(4)
        ]
        for t in stuck:
            t.start()
        svc._completers = svc._completers + stuck
        t0 = time.monotonic()
        svc.close(join_s=0.5)
        elapsed = time.monotonic() - t0
        # Per-join semantics would block >= 4 * 0.5s on the parked
        # threads alone; the shared deadline hands back in ~join_s.
        assert elapsed < 1.5, elapsed
        park.set()
    finally:
        svc.close()


def test_environment_fingerprint_redacts_tenant_keys(monkeypatch):
    """KEYSTONE_TENANTS carries API keys and environment_fingerprint()
    lands in committed bench JSON: the key field must be masked while
    name/qps/tier provenance survives."""
    from keystone_tpu.utils.metrics import environment_fingerprint

    monkeypatch.setenv(
        "KEYSTONE_TENANTS", "acme:sk-live-secret:100:gold,beta:k2beta:5"
    )
    monkeypatch.setenv("KEYSTONE_SWAP_TOKEN", "prod-swap-secret")
    fp = environment_fingerprint(devices=False)
    dumped = json.dumps(fp)
    assert "sk-live-secret" not in dumped and "k2beta" not in dumped
    assert "prod-swap-secret" not in dumped  # control-plane credential
    assert fp["keystone_env"]["KEYSTONE_SWAP_TOKEN"] == "****"
    assert (
        fp["keystone_env"]["KEYSTONE_TENANTS"]
        == "acme:****:100:gold,beta:****:5"
    )


def test_daemon_close_outliving_slow_swap_does_not_park_swap_worker(tmp_path):
    """close() racing a long in-progress swap consumes the shutdown
    sentinel in its queue drain — it must re-seed it, or the swap
    worker parks forever on an empty queue (one leaked thread per such
    close, pinning both generations in memory)."""
    _, a1 = _save(tmp_path, 0, "v1")
    _, a2 = _save(tmp_path, 1, "v2")
    hold = threading.Event()
    entered = threading.Event()

    def hook(_d):
        entered.set()
        hold.wait(timeout=30)

    d = ServingDaemon(
        artifact=a1, devices=1, buckets=(4,), name="t-slowswap",
        flight_dir=str(tmp_path), swap_hook=hook,
    )
    d.CLOSE_JOIN_S = 0.3  # instance override: don't wait 10s in a test
    fut = d.request_swap(a2, wait=False)
    assert entered.wait(timeout=30)
    d.close()  # join times out while the hook holds the swap mid-flight
    hold.set()
    with pytest.raises(ServiceClosed):
        fut.result(timeout=30)
    d._swap_thread.join(timeout=10)
    assert not d._swap_thread.is_alive()


def test_daemon_trickled_body_cannot_pin_admission_slot(monkeypatch, tmp_path):
    """The HTTP path pre-admits on the header key BEFORE the body read:
    a client trickling its body must be cut off by ONE total deadline
    (not per-recv timeouts it can individually beat), releasing the
    admission slot — pinned slots would starve every tenant."""
    import socket as socket_mod

    from keystone_tpu.workflow import daemon as daemon_mod

    monkeypatch.setattr(daemon_mod, "CONN_TIMEOUT_S", 1.0)
    _, a1 = _save(tmp_path, 0, "v1")
    with ServingDaemon(
        artifact=a1, devices=1, buckets=(4,), name="t-trickle",
        flight_dir=str(tmp_path),
    ) as daemon:
        conn = socket_mod.create_connection(("127.0.0.1", daemon.http_port))
        try:
            conn.sendall(
                b"POST /predict HTTP/1.1\r\nHost: x\r\n"
                b"Content-Length: 1000000\r\n\r\n"
            )
            conn.sendall(b"{")  # trickle one byte, then stall
            # First observe the slot actually HELD (pre-admission ran),
            # then released — polling straight for 0 would pass before
            # the handler even reached admit.
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                if daemon._admission.stats()["inflight"] >= 1:
                    break
                time.sleep(0.01)
            assert daemon._admission.stats()["inflight"] == 1
            while time.monotonic() < deadline:
                if daemon._admission.stats()["inflight"] == 0 and \
                        daemon.stats()["active_requests"] == 0:
                    break
                time.sleep(0.05)
            assert daemon._admission.stats()["inflight"] == 0
            assert daemon.stats()["active_requests"] == 0
        finally:
            conn.close()
        # The daemon still serves normally after shedding the trickler.
        st, doc = _post(daemon.http_port, "/predict", {"x": [[1.0] * D]})
        assert st == 200, doc


def test_flight_record_meta_is_copy_on_write():
    """note() must swap the meta dict atomically, not mutate in place: a
    concurrent snapshot()/dump() copies it, and a key insert during that
    iteration would raise RuntimeError mid-dump (dump never raises)."""
    from keystone_tpu.utils.flight_recorder import FlightRecord

    rec = FlightRecord(1, 4, first_phase="accepted")
    rec.note(tenant="acme")
    before = rec.meta
    rec.note(status=200)  # new key: must land in a NEW dict
    assert rec.meta is not before
    assert before == {"tenant": "acme"}
    assert rec.as_dict()["meta"] == {"tenant": "acme", "status": 200}


def test_control_plane_locked_when_tenants_configured(tmp_path):
    """POST /swap is operator privilege, not data-plane privilege: with
    tenants configured and no swap token set, the control plane is
    LOCKED (403 even with a valid tenant key); with a token set, only
    the exact X-Swap-Token opens it. /stats redacts the tenant table
    (names/quotas/tiers) from anonymous callers either way."""
    _, a1 = _save(tmp_path, 0, "v1")
    _, a2 = _save(tmp_path, 1, "v2")
    tenants = {"sk-g": Tenant("acme-corp", "sk-g", qps=0, tier="gold")}

    # No token configured: locked, data-plane key does NOT help.
    with ServingDaemon(
        artifact=a1, tenants=tenants, devices=1, buckets=(4,),
        name="t-ctl-locked", flight_dir=str(tmp_path), swap_token="",
    ) as daemon:
        st, doc = _post(daemon.http_port, "/swap", {"artifact": a2},
                        {"X-API-Key": "sk-g"}, retries=1)
        assert st == 403 and daemon.generation == 0
        st, stats = _get(daemon.http_port, "/stats")
        assert st == 200 and stats["admission"]["tenants"] == 1  # count only
        assert "acme-corp" not in json.dumps(stats)

    # Token configured: wrong token 403, exact token swaps; /stats is
    # full for the token holder.
    with ServingDaemon(
        artifact=a1, tenants=tenants, devices=1, buckets=(4,),
        name="t-ctl-token", flight_dir=str(tmp_path), swap_token="s3cret",
    ) as daemon:
        st, _doc = _post(daemon.http_port, "/swap", {"artifact": a2},
                         {"X-Swap-Token": "wrong"}, retries=1)
        assert st == 403 and daemon.generation == 0
        st, doc = _post(daemon.http_port, "/swap", {"artifact": a2},
                        {"X-Swap-Token": "s3cret"}, timeout=120, retries=1)
        assert st == 200 and doc["generation"] == 1
        serve_daemon = _serve_daemon_mod()
        st, body = serve_daemon.http_get(
            daemon.http_port, "/stats", timeout=30
        )
        anon = json.loads(body)
        assert anon["admission"]["tenants"] == 1
        import urllib.request
        req = urllib.request.Request(
            f"http://127.0.0.1:{daemon.http_port}/stats",
            headers={"X-Swap-Token": "s3cret"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            full = json.loads(r.read())
        assert full["admission"]["tenants"][0]["name"] == "acme-corp"

    # Open dev mode (no tenants, no token): /swap stays open — the
    # existing open-mode tests and demos rely on it.
    with ServingDaemon(
        artifact=a1, devices=1, buckets=(4,), name="t-ctl-open",
        flight_dir=str(tmp_path), swap_token="",
    ) as daemon:
        st, doc = _post(daemon.http_port, "/swap", {"artifact": a2},
                        timeout=120, retries=1)
        assert st == 200 and doc["generation"] == 1
