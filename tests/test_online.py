"""Online learning subsystem (workflow/online.py, ISSUE-15).

The equivalence contracts, pinned:

- **Grouping invariance**: ``partial_fit`` over K batches is
  BIT-identical to one ``partial_fit`` over their concatenation — the
  buffered fixed-phase chunk fold makes the batching of the stream
  unobservable — and folds of sharded device batches are bit-identical
  to folds of the same bytes on the host (the RowMatrix re-shard
  placement-invariance rule).
- **Batch agreement**: the online re-solve (uncentered sums + exact
  rank-one centering correction) matches the classic centered batch
  ``fit`` numerically (not bitwise — documented).
- **Decay / window math** pinned against NumPy float64 oracles
  (exponentially-weighted resp. last-k-batches ridge, intercepts
  included), plus subtract-on-evict consistency and the
  ``windows_evicted`` counter.
- **Typed refusals**: width/label-tail/mesh-manifest mismatches raise
  ``OnlineStateError``; a checkpoint resumed under a different mesh
  width raises the shared ``MeshMismatchError``.
- **Continuous refresh**: the OnlineTrainer folds, re-solves, publishes
  versioned artifacts, and hot-swaps a live daemon; a refresh killed at
  the ``refresh_abort``/``swap_abort`` fault sites leaves the old
  generation serving and the retained state (and its checkpoint)
  resuming bit-identically. A/B-serving answers two generations from
  one replica pool by per-tenant routing.

These tests must pass identically under ``make chaos``
(io:0.05,oom:1,conn_drop:0.05): daemon clients retry dropped
connections, and the fold/checkpoint paths carry no chaos fault sites.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

from keystone_tpu.config import config
from keystone_tpu.nodes.learning.block_least_squares import (
    BlockLeastSquaresEstimator,
    BlockWeightedLeastSquaresEstimator,
)
from keystone_tpu.nodes.learning.least_squares import LeastSquaresEstimator
from keystone_tpu.nodes.learning.linear_mapper import (
    LinearMapEstimator,
    LinearMapper,
)
from keystone_tpu.nodes.stats.normalizer import L2Normalizer
from keystone_tpu.nodes.stats.random_features import CosineRandomFeatures
from keystone_tpu.utils import reliability
from keystone_tpu.utils.metrics import metrics_registry, online_counters
from keystone_tpu.utils.mesh import MeshMismatchError, default_mesh
from keystone_tpu.utils.reliability import RefreshAborted
from keystone_tpu.workflow import LabelEstimator
from keystone_tpu.workflow.online import (
    OnlineState,
    OnlineStateError,
    OnlineTrainer,
    supports_partial_fit,
)
from keystone_tpu.workflow.serialization import save_artifact

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")

D_IN, K = 10, 3


@pytest.fixture
def faults():
    """Arm a fault plan for the test; restores the prior plan after
    (the test_daemon fixture pattern)."""
    prior = (config.faults, config.faults_seed)

    def arm(spec: str, seed: int = 0):
        config.faults, config.faults_seed = spec, seed
        reliability.reset_fault_plan()

    yield arm
    config.faults, config.faults_seed = prior
    reliability.reset_fault_plan()


def _data(n=300, d=D_IN, k=K, seed=0, noise=0.01):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    Wt = rng.normal(size=(d, k)).astype(np.float32)
    Y = (X @ Wt + noise * rng.normal(size=(n, k))).astype(np.float32)
    return X, Y


def _split(X, Y, cuts):
    edges = [0] + list(cuts) + [len(X)]
    return [(X[a:b], Y[a:b]) for a, b in zip(edges[:-1], edges[1:])]


# ---------------------------------------------------------------------------
# The fold contracts
# ---------------------------------------------------------------------------


def test_partial_fit_k_batches_bit_identical_to_concat():
    """The tentpole contract: the batching of the stream must be
    unobservable in the bits — awkward batch sizes straddle the
    canonical chunk boundary on purpose."""
    X, Y = _data()
    est = LinearMapEstimator(lam=1e-3)
    st_k = None
    for bx, by in _split(X, Y, [37, 110, 111, 230]):
        st_k = est.partial_fit(bx, by, state=st_k)
    st_1 = est.partial_fit(X, Y)
    m_k, m_1 = est.solve_online(st_k), est.solve_online(st_1)
    assert np.array_equal(np.asarray(m_k.W), np.asarray(m_1.W))
    assert np.array_equal(np.asarray(m_k.b), np.asarray(m_1.b))
    # ... and a THIRD grouping agrees too.
    st_3 = None
    for bx, by in _split(X, Y, [1, 2, 299]):
        st_3 = est.partial_fit(bx, by, state=st_3)
    m_3 = est.solve_online(st_3)
    assert np.array_equal(np.asarray(m_3.W), np.asarray(m_1.W))


def test_partial_fit_sharded_fold_bit_identical():
    """Sharded arrival placement must be unobservable: every fold
    re-shards through RowMatrix onto the one mesh."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    X, Y = _data(n=296)  # divisible by the 8-device mesh
    est = LinearMapEstimator(lam=1e-3)
    mesh = default_mesh()
    Xs = jax.device_put(X, NamedSharding(mesh, P(config.data_axis)))
    Ys = jax.device_put(Y, NamedSharding(mesh, P(config.data_axis)))
    m_sharded = est.solve_online(est.partial_fit(Xs, Ys))
    m_host = est.solve_online(est.partial_fit(X, Y))
    assert np.array_equal(np.asarray(m_sharded.W), np.asarray(m_host.W))
    assert np.array_equal(np.asarray(m_sharded.b), np.asarray(m_host.b))


def test_online_solve_matches_batch_fit_numerically():
    """The online solve is the SAME math as the centered batch fit at a
    different (exact-correction) flop grouping: predictions agree to
    f32 working precision, intercept included."""
    X, Y = _data()
    est = LinearMapEstimator(lam=1e-3)
    online = est.solve_online(est.partial_fit(X, Y))
    batch = est.fit(X, Y)
    po = np.asarray(online.apply_batch(X[:64]))
    pb = np.asarray(batch.apply_batch(X[:64]))
    scale = max(np.abs(pb).max(), 1.0)
    assert np.allclose(po, pb, atol=1e-4 * scale)


def test_intercept_means_ride_the_fold():
    """The retained column sums ARE the intercept means: exact to f64
    addition over the canonical chunks."""
    X, Y = _data()
    st = LinearMapEstimator().partial_fit(X, Y)
    g, ab, xs, ys, n = st._totals_with_pending()
    assert n == len(X)
    assert np.allclose(xs / n, X.astype(np.float64).mean(axis=0),
                       atol=1e-6)
    assert np.allclose(ys / n, Y.astype(np.float64).mean(axis=0),
                       atol=1e-6)


def test_decay_matches_numpy_oracle():
    """γ-decay per fold = exponentially-weighted ridge: pinned against a
    float64 weighted-normal-equations oracle, intercept included."""
    X, Y = _data()
    est = LinearMapEstimator(lam=1e-3)
    gamma = 0.5
    st = None
    batches = _split(X, Y, [100, 200])
    for bx, by in batches:
        st = est.partial_fit(bx, by, state=st, decay=gamma)
    m = est.solve_online(st)
    w = np.concatenate([
        np.full(len(b[0]), gamma ** (len(batches) - 1 - i))
        for i, b in enumerate(batches)
    ])
    Xd, Yd = X.astype(np.float64), Y.astype(np.float64)
    ne = w.sum()
    xm, ym = (w @ Xd) / ne, (w @ Yd) / ne
    Xc, Yc = Xd - xm, Yd - ym
    G = (Xc * w[:, None]).T @ Xc + 1e-3 * np.eye(D_IN)
    Wo = np.linalg.solve(G, (Xc * w[:, None]).T @ Yc)
    assert np.allclose(np.asarray(m.W), Wo, atol=2e-3)
    assert np.allclose(np.asarray(m.b), ym - xm @ Wo, atol=2e-3)


def test_window_matches_oracle_and_counts_evictions():
    """window=k keeps exactly the last k calls: the running totals match
    a fresh fold of the live windows (subtract-on-evict is benign in
    f64) and the solve matches the last-k NumPy oracle."""
    X, Y = _data()
    est = LinearMapEstimator(lam=1e-3)
    before = online_counters.get("windows_evicted")
    st = None
    batches = _split(X, Y, [100, 200])
    for bx, by in batches:
        st = est.partial_fit(bx, by, state=st, window=2)
    assert online_counters.get("windows_evicted") == before + 1
    # Totals == a fresh state folded with only the live windows.
    fresh = None
    for bx, by in batches[1:]:
        fresh = est.partial_fit(bx, by, state=fresh, window=2)
    for a, b in zip(st._totals_with_pending(),
                    fresh._totals_with_pending()):
        assert np.allclose(a, b, rtol=1e-9, atol=1e-9)
    # ... and the solve matches the last-200-rows oracle.
    m = est.solve_online(st)
    Xd = X[100:].astype(np.float64)
    Yd = Y[100:].astype(np.float64)
    xm, ym = Xd.mean(axis=0), Yd.mean(axis=0)
    Xc, Yc = Xd - xm, Yd - ym
    Wo = np.linalg.solve(Xc.T @ Xc + 1e-3 * np.eye(D_IN), Xc.T @ Yc)
    assert np.allclose(np.asarray(m.W), Wo, atol=2e-3)


def test_fold_copies_caller_buffers():
    """A streaming reader reusing ONE preallocated batch buffer must not
    corrupt pending rows: the fold copies what it buffers."""
    X, Y = _data(n=120)
    est = LinearMapEstimator(lam=1e-3)
    buf_x = np.empty((40, D_IN), np.float32)
    buf_y = np.empty((40, K), np.float32)
    st = None
    for a in (0, 40, 80):
        buf_x[:] = X[a:a + 40]
        buf_y[:] = Y[a:a + 40]
        st = est.partial_fit(buf_x, buf_y, state=st)
        buf_x[:] = np.nan  # the reader clobbers its buffer
        buf_y[:] = np.nan
    m = est.solve_online(st)
    m_ref = est.solve_online(est.partial_fit(X, Y))
    assert np.array_equal(np.asarray(m.W), np.asarray(m_ref.W))


def test_typed_refusals():
    X, Y = _data(n=64)
    est = LinearMapEstimator()
    st = est.partial_fit(X, Y)
    with pytest.raises(OnlineStateError, match="width"):
        st.fold(np.zeros((4, D_IN + 1), np.float32), Y[:4])
    with pytest.raises(OnlineStateError, match="label tail"):
        st.fold(X[:4], np.zeros((4, K + 2), np.float32))
    with pytest.raises(OnlineStateError, match="row mismatch"):
        st.fold(X[:4], Y[:5])
    with pytest.raises(OnlineStateError, match="empty"):
        st.fold(X[:0], Y[:0])
    with pytest.raises(OnlineStateError, match="exclusive"):
        st.decay(0.5) if st.window else OnlineState(
            D_IN, (K,), window=2
        ).decay(0.5)
    with pytest.raises(OnlineStateError, match="empty online state"):
        OnlineState(D_IN, (K,)).solve()
    with pytest.raises(OnlineStateError, match="label tail"):
        # ndim>=2 tails would break the rank-one intercept centering in
        # solve(): refused at creation, not a crash later.
        OnlineState.for_batch(X, np.zeros((64, K, 2), np.float32))
    with pytest.raises(OnlineStateError, match="chunk_rows"):
        # Fold granularity is fingerprint identity: a conflicting
        # chunk_rows on a later call refuses like a conflicting window.
        est.partial_fit(X[:4], Y[:4], state=est.partial_fit(X, Y),
                        chunk_rows=64)
    with pytest.raises(OnlineStateError, match="mesh"):
        st.device_count = 99
        st.fold(X[:4], Y[:4])


def test_mesh_manifest_refusal_on_resume(tmp_path, monkeypatch):
    """With elastic migration pinned off (KEYSTONE_ELASTIC_MESH=0), a
    snapshot recorded under one mesh width refuses to resume under
    another — the shared MeshMismatchError, never a wrong-answer
    resume; a different-problem snapshot refuses typed too. The
    default-on migration path is pinned in test_elastic_mesh.py."""
    from keystone_tpu.config import config

    monkeypatch.setattr(config, "elastic_mesh", False)
    X, Y = _data(n=64)
    st = LinearMapEstimator().partial_fit(X, Y)
    st.save(str(tmp_path))
    # Doctor the saved manifest: folded on a 2-device mesh.
    from keystone_tpu.workflow.disk_cache import DiskCache

    store = DiskCache(str(tmp_path), suffix=".online.pkl")
    snap = store.get("online_state")
    snap["fingerprint"]["device_count"] = 2
    store.put("online_state", snap, overwrite=True)
    with pytest.raises(MeshMismatchError, match="mesh"):
        OnlineState.load(str(tmp_path))
    # A different dtype REGIME (same mesh) is an OnlineStateError, not a
    # mesh one — the accumulators carry a dtype identity.
    snap["fingerprint"]["device_count"] = st.device_count
    snap["fingerprint"]["default_dtype"] = "float64"
    store.put("online_state", snap, overwrite=True)
    with pytest.raises(OnlineStateError, match="different problem"):
        OnlineState.load(str(tmp_path))


def test_checkpoint_resume_bit_identical(tmp_path):
    """Kill-and-resume mid-stream: the reloaded state (accumulators AND
    the pending partial-chunk buffer) continues to the same bits as the
    uninterrupted fold."""
    X, Y = _data()
    est = LinearMapEstimator(lam=1e-3)
    batches = _split(X, Y, [70, 140, 210])
    st = None
    for bx, by in batches[:2]:
        st = est.partial_fit(bx, by, state=st)
    st.save(str(tmp_path))
    resumed = OnlineState.load(str(tmp_path))  # "new process"
    assert resumed is not None and resumed.folds == 2
    for bx, by in batches[2:]:
        resumed = est.partial_fit(bx, by, state=resumed)
    uninterrupted = est.partial_fit(X, Y)
    m_r = est.solve_online(resumed)
    m_u = est.solve_online(uninterrupted)
    assert np.array_equal(np.asarray(m_r.W), np.asarray(m_u.W))
    assert np.array_equal(np.asarray(m_r.b), np.asarray(m_u.b))


# ---------------------------------------------------------------------------
# The estimator family
# ---------------------------------------------------------------------------


def test_block_least_squares_partial_fit():
    X, Y = _data()
    est = BlockLeastSquaresEstimator(lam=1e-3)
    m = est.solve_online(est.partial_fit(X, Y))
    # Same exact solve as the LinearMap head, in BlockLinearMapper garb.
    ref = LinearMapEstimator(lam=1e-3)
    m_ref = ref.solve_online(ref.partial_fit(X, Y))
    assert np.array_equal(np.asarray(m.W), np.asarray(m_ref.W))
    assert np.array_equal(np.asarray(m.b), np.asarray(m_ref.b))
    assert m.blocks == [(0, D_IN)]
    # fit_intercept=False drops the correction AND the bias.
    est0 = BlockLeastSquaresEstimator(lam=1e-3, fit_intercept=False)
    m0 = est0.solve_online(est0.partial_fit(X, Y))
    assert m0.b is None
    Xd, Yd = X.astype(np.float64), Y.astype(np.float64)
    Wo = np.linalg.solve(Xd.T @ Xd + 1e-3 * np.eye(D_IN), Xd.T @ Yd)
    assert np.allclose(np.asarray(m0.W), Wo, atol=2e-3)


def test_least_squares_estimator_partial_fit_and_support_map():
    X, Y = _data(n=128)
    est = LeastSquaresEstimator(lam=1e-3)
    m = est.solve_online(est.partial_fit(X, Y))
    assert isinstance(m, LinearMapper)
    assert est.last_choice is not None and est.last_choice.name == "normal"
    assert supports_partial_fit(LinearMapEstimator())
    assert supports_partial_fit(BlockLeastSquaresEstimator())
    assert supports_partial_fit(LeastSquaresEstimator())
    # Class-rebalanced weights need full class counts: contract nulled.
    assert not supports_partial_fit(BlockWeightedLeastSquaresEstimator())


def test_online_counters_visible_on_registry():
    before = online_counters.get("batches_folded")
    est = LinearMapEstimator()
    X, Y = _data(n=32)
    est.solve_online(est.partial_fit(X, Y))
    snap = metrics_registry.snapshot()["online"]
    assert snap["batches_folded"] >= before + 1
    assert snap["resolves"] >= 1
    assert "keystone_online" in metrics_registry.prometheus()


def test_one_d_labels_fold_and_solve():
    """The CSV label_col shape: 1-D labels ride the same fold (AᵀB is
    (d,), the intercept a scalar)."""
    rng = np.random.default_rng(3)
    X = rng.normal(size=(100, 6)).astype(np.float32)
    y = (X @ rng.normal(size=6).astype(np.float32) + 0.5).astype(np.float32)
    est = LinearMapEstimator(lam=1e-3)
    st = None
    for a, b in ((0, 33), (33, 100)):
        st = est.partial_fit(X[a:b], y[a:b], state=st)
    m = est.solve_online(st)
    m1 = est.solve_online(est.partial_fit(X, y))
    assert np.array_equal(np.asarray(m.W), np.asarray(m1.W))
    pred = np.asarray(m.apply_batch(X))
    assert np.allclose(pred, y, atol=0.05)


# ---------------------------------------------------------------------------
# Pipeline.refit_stream
# ---------------------------------------------------------------------------


def _drift_pipeline(X, Y, lam=1e-3, head=None):
    feat = CosineRandomFeatures.create(D_IN, 16, gamma=0.3, seed=1)
    return feat.and_then(L2Normalizer()).and_then(
        head or LinearMapEstimator(lam=lam), X, Y
    )


def test_refit_stream_freezes_prefix_and_matches_manual_fold():
    X, Y = _data()
    pipe = _drift_pipeline(X[:100], Y[:100])
    batches = _split(X[100:], Y[100:], [64, 128])
    outs = list(pipe.refit_stream(batches, every=2))
    assert len(outs) == 2  # 2 full ticks + the tail tick of batch 3
    # Frozen featurize: the SAME fitted (fused) prefix object rides
    # every yield; only the head is re-solved per tick.
    t0, t1 = outs[0].transformers(), outs[1].transformers()
    assert t0[0] is t1[0]
    assert t0[-1] is not t1[-1]
    # The head equals a manual fold of the initial problem (the default
    # seed) plus the SAME featurized batches.
    fitted = pipe.fit()
    from keystone_tpu.workflow.online import split_fitted_head

    prefix, _head = split_fitted_head(fitted)
    est = LinearMapEstimator(lam=1e-3)
    st = est.partial_fit(
        np.asarray(prefix.apply(X[:100]).get()), Y[:100]
    )
    for bx, by in batches:
        st = est.partial_fit(np.asarray(prefix.apply(bx).get()), by,
                             state=st)
    manual = est.solve_online(st)
    yielded = outs[-1].transformers()[-1]
    assert np.array_equal(np.asarray(yielded.W), np.asarray(manual.W))
    assert np.array_equal(np.asarray(yielded.b), np.asarray(manual.b))


def test_refit_stream_full_refit_fallback_counted():
    class BatchOnlyHead(LabelEstimator):
        def __init__(self):
            self.fits = 0
            self.fit_rows = []

        def fit(self, X, y):
            self.fits += 1
            X = np.asarray(X, np.float64)
            y = np.asarray(y, np.float64)
            self.fit_rows.append(X.shape[0])
            W = np.linalg.lstsq(X, y, rcond=None)[0]
            return LinearMapper(W.astype(np.float32))

    X, Y = _data()
    head = BatchOnlyHead()
    pipe = _drift_pipeline(X[:100], Y[:100], head=head)
    before = online_counters.get("full_refits")
    before_buf = online_counters.get("batches_buffered")
    before_folded = online_counters.get("batches_folded")
    outs = list(pipe.refit_stream(
        _split(X[100:], Y[100:], [164]), every=1
    ))
    assert len(outs) == 2
    # Initial fit + one FULL refit per tick — the KG105 cost, counted.
    assert head.fits == 3
    assert online_counters.get("full_refits") == before + 2
    # Buffered, not folded: nothing reached retained accumulators.
    assert online_counters.get("batches_buffered") == before_buf + 2
    assert online_counters.get("batches_folded") == before_folded
    # The fallback honors the seed too: each full refit runs over
    # initial ∪ streamed-so-far (100 + 164, then 100 + 200).
    assert head.fit_rows[1:] == [264, 300]
    assert np.asarray(outs[-1].apply(X[:8]).get()).shape == (8, K)


def test_refit_stream_fallback_refuses_forgetting_args():
    """decay/window on a partial_fit-less head must refuse, never
    silently full-refit with every batch weighted equally."""

    class BatchOnlyHead(LabelEstimator):
        def fit(self, X, y):
            return LinearMapper(np.zeros((16, K), np.float32))

    X, Y = _data(n=64)
    pipe = _drift_pipeline(X, Y, head=BatchOnlyHead())
    # EAGER refusal: the call itself refuses (no next() needed) — a
    # never-consumed generator must not swallow the misconfiguration.
    with pytest.raises(ValueError, match="partial_fit head"):
        pipe.refit_stream([(X[:8], Y[:8])], decay=0.5)
    # A caller-supplied state refuses the same way: the fallback would
    # never fold its retained history.
    st = LinearMapEstimator().partial_fit(
        np.zeros((4, 16), np.float32), Y[:4]
    )
    with pytest.raises(ValueError, match="OnlineState"):
        pipe.refit_stream([(X[:8], Y[:8])], state=st)


def test_refit_stream_refuses_non_estimator_sink():
    fitted = CosineRandomFeatures.create(D_IN, 8, seed=0).to_pipeline()
    with pytest.raises(ValueError, match="estimator head"):
        fitted.refit_stream([(np.zeros((2, D_IN)), None)])


# ---------------------------------------------------------------------------
# OnlineTrainer + daemon refresh (the serving half)
# ---------------------------------------------------------------------------


def _serve_daemon_mod():
    sys.path.insert(0, TOOLS)
    try:
        import serve_daemon
    finally:
        sys.path.pop(0)
    return serve_daemon


def _post(port, path, body, headers=None, retries=8):
    return _serve_daemon_mod().http_post(port, path, body, headers,
                                         timeout=60, retries=retries)


def _settle(daemon, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        snap = daemon._flight.snapshot()
        if daemon.stats()["active_requests"] == 0 and all(
            r["outcome"] is not None for r in snap["records"]
        ):
            return snap
        time.sleep(0.01)
    return daemon._flight.snapshot()


def _trainer_rig(tmp_path, decay=0.5):
    """A live daemon on generation 0 + a trainer wired to hot-swap it."""
    from keystone_tpu.workflow.daemon import ServingDaemon

    X, Y = _data(n=256, seed=5)
    pipe = _drift_pipeline(X, Y)
    art0 = str(tmp_path / "model-g0000.kart")
    save_artifact(pipe.fit(), art0, feature_shape=(D_IN,), dtype="float32")
    daemon = ServingDaemon(artifact=art0, http_port=0, enable_socket=False,
                           buckets=(32,), max_batch=32)
    trainer = OnlineTrainer(
        pipe, daemon=daemon, artifact_dir=str(tmp_path), decay=decay,
        refresh_ms=0, start=False, feature_shape=(D_IN,), name="t",
    )
    return daemon, trainer, (X, Y)


def test_trainer_refresh_hot_swaps_live_daemon(tmp_path):
    daemon, trainer, (X, Y) = _trainer_rig(tmp_path)
    try:
        probe = X[:32]
        st, doc = _post(daemon.http_port, "/predict",
                        {"x": probe.tolist()})
        assert st == 200 and doc["generation"] == 0
        Xs, Ys = _data(n=96, seed=9)
        for a, b in ((0, 48), (48, 96)):
            trainer.submit(Xs[a:b], Ys[a:b])
        before = online_counters.get("refreshes_pushed")
        refreshed = trainer.refresh()
        assert online_counters.get("refreshes_pushed") == before + 1
        assert daemon.generation == 1
        assert trainer.last_artifact and os.path.exists(
            trainer.last_artifact)
        # The wire answers with the refreshed model's bits.
        st, doc = _post(daemon.http_port, "/predict",
                        {"x": probe.tolist()})
        assert st == 200 and doc["generation"] == 1
        want = np.asarray(refreshed.apply(probe).get())
        assert np.array_equal(
            np.asarray(doc["y"], dtype=np.float32), want
        )
        snap = _settle(daemon)
        assert all(r["outcome"] is not None for r in snap["records"])
    finally:
        trainer.close()
        daemon.close()


def test_trainer_refresh_emits_traced_telemetry(tmp_path, monkeypatch):
    """The cadence refresh mints ONE trace id and threads it through
    the re-solve -> artifact -> swap chain: with durable export on,
    both lifecycle records (refresh + swap) land on disk carrying that
    id — "why did the model change?" resolves to one grep."""
    import json as _json

    from keystone_tpu.utils.telemetry import (
        TRACE_ID_RE,
        active_telemetry,
        reset_telemetry,
    )

    tel_dir = str(tmp_path / "telemetry")
    monkeypatch.setenv("KEYSTONE_TELEMETRY_DIR", tel_dir)
    reset_telemetry()
    try:
        daemon, trainer, (X, Y) = _trainer_rig(tmp_path)
        try:
            Xs, Ys = _data(n=96, seed=9)
            trainer.submit(Xs[:48], Ys[:48])
            trainer.refresh()
            assert daemon.generation == 1
            tel = active_telemetry()
            assert tel is not None and tel.drain(timeout=20.0)
        finally:
            trainer.close()
            daemon.close()
        records = []
        for name in sorted(os.listdir(tel_dir)):
            with open(os.path.join(tel_dir, name)) as fh:
                records.extend(_json.loads(line) for line in fh)
        refreshes = [r for r in records if r.get("kind") == "refresh"]
        swaps = [r for r in records if r.get("kind") == "swap"]
        assert refreshes and swaps
        tid = refreshes[0]["trace_id"]
        assert TRACE_ID_RE.match(tid)
        assert swaps[0]["trace_id"] == tid
        assert refreshes[0]["folds_applied"] >= 1
        assert swaps[0]["from_generation"] == 0
        assert swaps[0]["generation"] == 1
    finally:
        reset_telemetry()


def test_trainer_refresh_abort_keeps_serving_and_retries(tmp_path, faults):
    """The chaos gate: a refresh killed at the refresh_abort site leaves
    generation 0 answering and the accumulators untouched; the retry
    (the next cadence tick) succeeds from identical state."""
    daemon, trainer, (X, Y) = _trainer_rig(tmp_path)
    try:
        Xs, Ys = _data(n=64, seed=9)
        trainer.submit(Xs, Ys)
        faults("refresh_abort:1")
        # Re-arm the trainer's resolved-once plan (the test flipped the
        # knobs after construction).
        trainer._plan = reliability.active_plan()
        before = online_counters.get("refreshes_failed")
        with pytest.raises(RefreshAborted):
            trainer.refresh()
        assert online_counters.get("refreshes_failed") == before + 1
        # stats() reports COMPLETED publishes, not attempts: a trainer
        # failing every tick must not read as "refreshing".
        assert trainer.stats()["refreshes"] == 0
        assert daemon.generation == 0
        st, doc = _post(daemon.http_port, "/predict",
                        {"x": X[:32].tolist()})
        assert st == 200 and doc["generation"] == 0
        # The retry refreshes from the SAME retained state.
        trainer.refresh()
        assert daemon.generation == 1
    finally:
        trainer.close()
        daemon.close()


def test_trainer_swap_abort_rolls_back_then_recovers(tmp_path, faults):
    """A refresh whose SWAP dies mid-handoff is a rollback, not an
    outage: generation 0 keeps serving, the failure is counted, and the
    next refresh lands."""
    # Armed BEFORE the rig: the daemon resolves its fault plan once at
    # construction (the active_plan discipline); the swap_abort site
    # only fires inside _do_swap, so generation 0 still stands up.
    faults("swap_abort:1")
    daemon, trainer, (X, Y) = _trainer_rig(tmp_path)
    try:
        Xs, Ys = _data(n=64, seed=9)
        trainer.submit(Xs, Ys)
        before = online_counters.get("refreshes_failed")
        with pytest.raises(Exception):
            trainer.refresh()
        assert online_counters.get("refreshes_failed") == before + 1
        assert daemon.generation == 0 and daemon.swap_failures == 1
        # The fold debt survives the failed PUBLISH: the cadence loop
        # still sees work and retries next tick (the counter clears
        # only on a successful publish).
        assert trainer.stats()["folds_since_refresh"] > 0
        st, doc = _post(daemon.http_port, "/predict",
                        {"x": X[:32].tolist()})
        assert st == 200 and doc["generation"] == 0
        trainer.refresh()
        assert daemon.generation == 1
        _settle(daemon)
    finally:
        trainer.close()
        daemon.close()


def test_trainer_checkpoint_resume_bit_identical(tmp_path):
    """A killed trainer process (simulated: a second trainer over the
    same checkpoint_dir) resumes the accumulator checkpoint and
    refreshes to the same bits as an uninterrupted one."""
    X, Y = _data(n=128, seed=5)
    pipe = _drift_pipeline(X, Y)
    Xs, Ys = _data(n=120, seed=9)
    ck_a = str(tmp_path / "ck_a")
    t_a = OnlineTrainer(pipe, refresh_ms=0, start=False,
                        checkpoint_dir=ck_a, name="a")
    t_a.submit(Xs[:40], Ys[:40])
    t_a.submit(Xs[40:70], Ys[40:70])
    t_a.close()  # "killed" — the checkpoint is the survivor
    t_b = OnlineTrainer(pipe, refresh_ms=0, start=False,
                        checkpoint_dir=ck_a, name="b")
    t_b.submit(Xs[70:], Ys[70:])
    resumed = t_b.resolve()
    t_b.close()
    t_c = OnlineTrainer(pipe, refresh_ms=0, start=False, name="c")
    for a, b in ((0, 40), (40, 70), (70, 120)):
        t_c.submit(Xs[a:b], Ys[a:b])
    uninterrupted = t_c.resolve()
    t_c.close()
    W_r = np.asarray(resumed.transformers()[-1].W)
    W_u = np.asarray(uninterrupted.transformers()[-1].W)
    assert np.array_equal(W_r, W_u)


def test_trainer_seeds_initial_problem_and_prunes_artifacts(tmp_path):
    """The first refresh re-solves initial ∪ streamed (never the first
    small batch alone), and artifact retention keeps only the newest
    keep_artifacts files."""
    X, Y = _data(n=128, seed=5)
    pipe = _drift_pipeline(X, Y)
    tr = OnlineTrainer(pipe, artifact_dir=str(tmp_path), refresh_ms=0,
                       start=False, feature_shape=(D_IN,), name="s",
                       keep_artifacts=2)
    try:
        Xs, Ys = _data(n=16, seed=9)
        tr.submit(Xs, Ys)
        got = tr.resolve()
        # Manual: seed with the featurized INITIAL problem, then the
        # streamed batch — bit-identical.
        fitted = pipe.fit()
        from keystone_tpu.workflow.online import split_fitted_head

        prefix, _ = split_fitted_head(fitted)
        est = LinearMapEstimator(lam=1e-3)
        st = est.partial_fit(np.asarray(prefix.apply(X).get()), Y)
        st = est.partial_fit(np.asarray(prefix.apply(Xs).get()), Ys,
                             state=st)
        manual = est.solve_online(st)
        assert np.array_equal(
            np.asarray(got.transformers()[-1].W), np.asarray(manual.W)
        )
        # Retention: 3 refreshes at keep_artifacts=2 leave the newest 2.
        for i in range(3):
            tr.submit(Xs, Ys)
            tr.refresh()
        kept = sorted(p for p in os.listdir(str(tmp_path))
                      if p.startswith("s-g"))
        assert kept == ["s-g0002.kart", "s-g0003.kart"]
        assert tr.stats()["refreshes"] == 3
    finally:
        tr.close()
    # A restarted trainer over the same artifact_dir CONTINUES the
    # sequence past the published files — never a fresh g0001 sorting
    # under a stale g0003.
    tr2 = OnlineTrainer(pipe, artifact_dir=str(tmp_path), refresh_ms=0,
                        start=False, feature_shape=(D_IN,), name="s")
    try:
        Xs, Ys = _data(n=16, seed=9)
        tr2.submit(Xs, Ys)
        tr2.refresh()
        assert os.path.basename(tr2.last_artifact) == "s-g0004.kart"
    finally:
        tr2.close()


def test_trainer_resolve_races_submit_without_deadlock():
    """The off-lock re-solve must never launch mesh collectives
    concurrently with a submit fold (interleaved participant arrivals
    deadlock the XLA rendezvous): the snapshot flushes its pending tail
    UNDER the trainer lock, leaving the off-lock solve collective-free.
    Subprocess-isolated so a regression FAILS (timeout) instead of
    wedging the shared mesh for the rest of the suite."""
    import subprocess

    code = r"""
import os, threading
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from keystone_tpu.workflow.online import OnlineTrainer
from keystone_tpu.nodes.learning.linear_mapper import LinearMapEstimator
from keystone_tpu.nodes.stats.random_features import CosineRandomFeatures

rng = np.random.default_rng(0)
X = rng.normal(size=(200, 10)).astype(np.float32)
Y = rng.normal(size=(200, 3)).astype(np.float32)
feat = CosineRandomFeatures.create(10, 16, gamma=0.3, seed=1)
pipe = feat.and_then(LinearMapEstimator(lam=1e-3), X, Y)
tr = OnlineTrainer(pipe, refresh_ms=0, start=False, name="race")
stop = threading.Event()

def feeder():
    while not stop.is_set():
        tr.submit(X[:24], Y[:24])  # sub-chunk: pending tail always live

t = threading.Thread(target=feeder, daemon=True)
t.start()
for _ in range(6):
    out = tr.resolve()
    assert np.isfinite(np.asarray(out.transformers()[-1].W)).all()
stop.set()
t.join(10)
tr.close()
print("RACE_OK")
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0 and "RACE_OK" in proc.stdout, (
        proc.stdout[-2000:], proc.stderr[-2000:],
    )


def test_trainer_resume_mode_conflict_refuses_at_construction(tmp_path):
    """Restarting a trainer with a different forgetting mode (or fold
    granularity) over an existing checkpoint refuses AT CONSTRUCTION —
    not on every submit while the cadence loop silently serves the
    pre-kill model forever."""
    X, Y = _data(n=96, seed=5)
    pipe = _drift_pipeline(X, Y)
    ck = str(tmp_path / "ck")
    t = OnlineTrainer(pipe, refresh_ms=0, start=False,
                      checkpoint_dir=ck, name="m")
    t.submit(X[:32], Y[:32])
    t.close()
    with pytest.raises(OnlineStateError, match="window"):
        OnlineTrainer(pipe, refresh_ms=0, start=False,
                      checkpoint_dir=ck, window=2, name="m")
    with pytest.raises(OnlineStateError, match="chunk_rows"):
        OnlineTrainer(pipe, refresh_ms=0, start=False,
                      checkpoint_dir=ck, chunk_rows=64, name="m")
    # Same mode resumes fine.
    t2 = OnlineTrainer(pipe, refresh_ms=0, start=False,
                       checkpoint_dir=ck, name="m")
    t2.close()
    # γ-weighted history must not continue unweighted: a decayed
    # checkpoint refuses a decay-less restart (a different γ is legal).
    ck2 = str(tmp_path / "ck2")
    td = OnlineTrainer(pipe, refresh_ms=0, start=False,
                       checkpoint_dir=ck2, decay=0.5, name="d")
    td.submit(X[:16], Y[:16])
    td.submit(X[16:32], Y[16:32])  # decay actually applied
    td.close()
    with pytest.raises(OnlineStateError, match="decay"):
        OnlineTrainer(pipe, refresh_ms=0, start=False,
                      checkpoint_dir=ck2, name="d")
    OnlineTrainer(pipe, refresh_ms=0, start=False, checkpoint_dir=ck2,
                  decay=0.7, name="d").close()


def test_trainer_refreshes_serialize(tmp_path):
    """A manual refresh racing the cadence tick must publish in
    snapshot order: whole refreshes hold one mutex end-to-end."""
    X, Y = _data(n=96, seed=5)
    tr = OnlineTrainer(_drift_pipeline(X, Y), artifact_dir=str(tmp_path),
                       refresh_ms=0, start=False, feature_shape=(D_IN,),
                       name="ser")
    try:
        tr.submit(X[:32], Y[:32])
        import threading

        done = threading.Event()
        tr._refresh_lock.acquire()  # stand in for an in-flight refresh
        t = threading.Thread(
            target=lambda: (tr.refresh(), done.set()), daemon=True
        )
        t.start()
        assert not done.wait(0.3)  # blocked behind the held refresh
        tr._refresh_lock.release()
        assert done.wait(30)
        t.join(10)
        assert tr.stats()["refreshes"] == 1
    finally:
        tr.close()


def test_trainer_cadence_loop_refreshes(tmp_path):
    """The background _refresh_loop actually drives a swap (short
    cadence), and close() stops it."""
    daemon, trainer, (X, Y) = _trainer_rig(tmp_path)
    trainer.close()
    trainer2 = OnlineTrainer(
        _drift_pipeline(X, Y), daemon=daemon,
        artifact_dir=str(tmp_path), decay=0.5, refresh_ms=50,
        feature_shape=(D_IN,), name="loop",
    )
    try:
        Xs, Ys = _data(n=64, seed=9)
        trainer2.submit(Xs, Ys)
        deadline = time.monotonic() + 20
        while daemon.generation < 1 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert daemon.generation >= 1
        assert trainer2.stats()["refreshes"] >= 1
    finally:
        trainer2.close()
        daemon.close()


def test_trainer_refuses_batch_only_head():
    class BatchOnlyHead(LabelEstimator):
        def fit(self, X, y):
            return LinearMapper(np.zeros((16, K), np.float32))

    X, Y = _data(n=64)
    pipe = _drift_pipeline(X, Y, head=BatchOnlyHead())
    with pytest.raises(OnlineStateError, match="partial_fit"):
        OnlineTrainer(pipe, refresh_ms=0, start=False)


# ---------------------------------------------------------------------------
# A/B serving: two generations, one replica pool (per-tenant routing)
# ---------------------------------------------------------------------------


def test_ab_serving_two_generations_per_tenant(tmp_path):
    from keystone_tpu.workflow.daemon import ServingDaemon, parse_tenants

    X, Y = _data(n=128, seed=5)
    pipe_a = _drift_pipeline(X, Y, lam=1e-3)
    pipe_b = _drift_pipeline(X, Y, lam=1e-1)  # visibly different weights
    a0 = str(tmp_path / "a.kart")
    a1 = str(tmp_path / "b.kart")
    fitted_a, fitted_b = pipe_a.fit(), pipe_b.fit()
    save_artifact(fitted_a, a0, feature_shape=(D_IN,), dtype="float32")
    save_artifact(fitted_b, a1, feature_shape=(D_IN,), dtype="float32")
    tenants = parse_tenants("alpha:ka:0:gold,beta:kb:0:gold")
    daemon = ServingDaemon(artifact=a0, tenants=tenants, http_port=0,
                           enable_socket=False, buckets=(16,), max_batch=16)
    try:
        probe = X[:16]
        want_a = np.asarray(fitted_a.apply(probe).get())
        want_b = np.asarray(fitted_b.apply(probe).get())

        def ask(key):
            st, doc = _post(daemon.http_port, "/predict",
                            {"x": probe.tolist()},
                            headers={"X-Api-Key": key})
            assert st == 200
            return doc["generation"], np.asarray(doc["y"],
                                                 dtype=np.float32)

        # A typo'd tenant name refuses up front — never an experiment
        # that silently serves the candidate zero traffic.
        with pytest.raises(ValueError, match="betta"):
            daemon.ab_swap(a1, tenants=["betta"])
        # Tenant OBJECTS are accepted too (not reduced to their repr).
        cand = daemon.ab_swap(a1, tenants=[tenants["kb"]])
        assert cand == 1
        gen_a, y_a = ask("ka")
        gen_b, y_b = ask("kb")
        assert (gen_a, gen_b) == (0, 1)
        assert np.array_equal(y_a, want_a)
        assert np.array_equal(y_b, want_b)
        stats = daemon.stats()
        assert stats["ab"]["tenants"] == ["beta"]
        # Anonymous /stats redacts the enrolled-tenant names to a count.
        assert daemon.stats(redact_tenants=True)["ab"]["tenants"] == 1
        # A full swap mid-experiment is refused, typed.
        with pytest.raises(RuntimeError, match="A/B"):
            daemon.request_swap(a1)
        # Promote: everyone on the candidate, zero dropped requests.
        assert daemon.promote_ab() == 1
        gen_a, y_a = ask("ka")
        assert gen_a == 1 and np.array_equal(y_a, want_b)
        # A second experiment aborts cleanly back to the live gen.
        daemon.ab_swap(a0, tenants=["alpha"])
        gen_a, y_a = ask("ka")
        assert gen_a == 2 and np.array_equal(y_a, want_a)
        daemon.abort_ab()
        gen_a, y_a = ask("ka")
        assert gen_a == 1 and np.array_equal(y_a, want_b)
        # The aborted candidate's number is BURNED (it served tagged
        # responses): the next experiment never reuses 2.
        assert daemon.ab_swap(a0, tenants=["alpha"]) == 3
        daemon.abort_ab()
        snap = _settle(daemon)
        assert all(r["outcome"] is not None for r in snap["records"])
    finally:
        daemon.close()


# ---------------------------------------------------------------------------
# The bench harness, in-process (make bench-online)
# ---------------------------------------------------------------------------


def test_bench_online_harness_inprocess(tmp_path):
    sys.path.insert(0, TOOLS)
    try:
        import bench_online
    finally:
        sys.path.pop(0)
    rc = bench_online.main(["--quick"])
    assert rc == 0


def test_bench_online_row_shape():
    """The committed fit_online row carries the gate evidence the watch
    family judges (the directions test lives in test_bench_watch)."""
    rows = [json.loads(line)
            for line in open(os.path.join(REPO, "BENCH_fit.json"))]
    online = [r for r in rows if r.get("metric") == "fit_online"]
    assert online, "make bench-online must append its row"
    row = online[-1]
    d = row["detail"]
    assert row["ok"] is True
    assert d["swap_gate"] and d["recovery_gate"] and d["drift_observed"]
    assert d["dropped_requests"] == 0 and d["unresolved"] == 0
    assert d["post_refresh_accuracy"] >= d["full_refit_accuracy"] - 0.05
    assert 1 in d["generations_served"] or d["final_generation"] >= 1
