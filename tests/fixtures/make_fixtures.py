"""Deterministic real-format loader fixtures (run once; outputs committed).

The reference keeps small real-format files under src/test/resources/ and
tests loaders against them (SURVEY.md §4 fixtures row [unverified]); these
are the rebuild's equivalent. Every file is generated from fixed seeds and
closed-form byte patterns so loader tests can assert labels, ordering, and
channel layout byte-exactly — no synthetic() fallback anywhere.

Regenerate with:  python tests/fixtures/make_fixtures.py
(The JPEG bytes are committed, so tests never depend on the local PIL
encoder; only the *decoder* runs at test time, checked tolerantly.)
"""

from __future__ import annotations

import json
import os
import struct
import tarfile

import numpy as np

ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")

# ---------------------------------------------------------------------------
# Closed-form byte patterns shared with the tests (import both sides).
# ---------------------------------------------------------------------------

CIFAR_LABELS = [3, 8, 0, 6, 1, 9]
MNIST_LABELS = [7, 2, 1, 0, 4]
IMAGENET_SYNSETS = {  # synset -> (label, [solid RGB colors per image])
    "n01440764": (0, [(220, 30, 30), (30, 220, 30)]),
    "n02102040": (1, [(30, 30, 220), (200, 200, 40)]),
}
VOC_FIXTURES = {  # name -> (classes present, solid RGB color)
    "000012": (["car"], (200, 40, 40)),
    "000017": (["person", "horse"], (40, 200, 40)),
    "000023": (["bicycle", "person", "person"], (40, 40, 200)),
}
NEWS_DOCS = {  # group -> {doc name -> exact text}
    "rec.sport.hockey": {
        "10001": "The goalie made a glove save in overtime.\n",
        "10002": "Playoff season starts next week.\n",
    },
    "sci.space": {
        "20001": "The rocket reached orbit after launch.\n",
        "20002": "A satellite photographed the moon.\n",
    },
}
AMAZON_ROWS = [  # (text, stars) -> expected label = stars > 3.5
    ("Great product, works perfectly.", 5.0),
    ("Terrible, broke after a day.", 1.0),
    ("It is okay, nothing special.", 3.0),
    ("Love it, best purchase this year.", 4.5),
]
TIMIT_N, TIMIT_D = 12, 40


def cifar_pixel_bytes(rec: int) -> np.ndarray:
    """Record `rec`'s 3072 channel-major pixel bytes: plane fill values
    chosen per (record, channel) so the NHWC transpose is checkable."""
    planes = [np.full(32 * 32, (rec * 40 + 17 * ch) % 256, np.uint8) for ch in range(3)]
    return np.concatenate(planes)


def mnist_image_bytes(idx: int) -> np.ndarray:
    """28x28 uint8 where pixel (r, c) = (idx*13 + r*28 + c) % 256."""
    base = np.arange(28 * 28, dtype=np.int64).reshape(28, 28)
    return ((idx * 13 + base) % 256).astype(np.uint8)


def _solid_jpeg(color, size=48) -> bytes:
    from PIL import Image
    import io

    im = Image.new("RGB", (size, size), color)
    buf = io.BytesIO()
    im.save(buf, format="JPEG", quality=95)
    return buf.getvalue()


def main() -> None:
    os.makedirs(ROOT, exist_ok=True)

    # CIFAR-10 binary: 1 label byte + 3072 channel-major pixel bytes/record.
    cdir = os.path.join(ROOT, "cifar")
    os.makedirs(cdir, exist_ok=True)
    with open(os.path.join(cdir, "data_batch.bin"), "wb") as f:
        for i, label in enumerate(CIFAR_LABELS):
            f.write(bytes([label]))
            f.write(cifar_pixel_bytes(i).tobytes())

    # MNIST IDX pair (big-endian magic + dims headers).
    mdir = os.path.join(ROOT, "mnist")
    os.makedirs(mdir, exist_ok=True)
    n = len(MNIST_LABELS)
    with open(os.path.join(mdir, "t10k-images-idx3-ubyte"), "wb") as f:
        f.write(struct.pack(">HBB", 0, 0x08, 3))
        f.write(struct.pack(">3I", n, 28, 28))
        for i in range(n):
            f.write(mnist_image_bytes(i).tobytes())
    with open(os.path.join(mdir, "t10k-labels-idx1-ubyte"), "wb") as f:
        f.write(struct.pack(">HBB", 0, 0x08, 1))
        f.write(struct.pack(">I", n))
        f.write(bytes(MNIST_LABELS))

    # ImageNet: per-synset tar of JPEGs + one dir-layout synset + label map.
    idir = os.path.join(ROOT, "imagenet", "train")
    os.makedirs(idir, exist_ok=True)
    with open(os.path.join(ROOT, "imagenet", "labels.txt"), "w") as f:
        for synset, (label, _colors) in sorted(IMAGENET_SYNSETS.items()):
            f.write(f"{synset} {label}\n")
    for si, (synset, (_label, colors)) in enumerate(sorted(IMAGENET_SYNSETS.items())):
        if si == 0:  # first synset as a tar archive
            with tarfile.open(os.path.join(idir, synset + ".tar"), "w") as tf:
                for j, color in enumerate(colors):
                    data = _solid_jpeg(color)
                    info = tarfile.TarInfo(f"{synset}_{j}.JPEG")
                    info.size = len(data)
                    import io

                    tf.addfile(info, io.BytesIO(data))
        else:  # second synset as a directory of JPEGs
            sdir = os.path.join(idir, synset)
            os.makedirs(sdir, exist_ok=True)
            for j, color in enumerate(colors):
                with open(os.path.join(sdir, f"{synset}_{j}.JPEG"), "wb") as f:
                    f.write(_solid_jpeg(color))

    # VOC: Annotations/<name>.xml + JPEGImages/<name>.jpg.
    vdir = os.path.join(ROOT, "voc")
    os.makedirs(os.path.join(vdir, "Annotations"), exist_ok=True)
    os.makedirs(os.path.join(vdir, "JPEGImages"), exist_ok=True)
    for name, (classes, color) in VOC_FIXTURES.items():
        objs = "".join(
            f"  <object><name>{c}</name><difficult>0</difficult></object>\n"
            for c in classes
        )
        xml = (
            f"<annotation>\n  <filename>{name}.jpg</filename>\n"
            f"  <size><width>48</width><height>48</height><depth>3</depth></size>\n"
            f"{objs}</annotation>\n"
        )
        with open(os.path.join(vdir, "Annotations", name + ".xml"), "w") as f:
            f.write(xml)
        with open(os.path.join(vdir, "JPEGImages", name + ".jpg"), "wb") as f:
            f.write(_solid_jpeg(color))

    # 20 Newsgroups: directory-per-class of plain-text docs.
    ndir = os.path.join(ROOT, "newsgroups", "train")
    for group, docs in NEWS_DOCS.items():
        gdir = os.path.join(ndir, group)
        os.makedirs(gdir, exist_ok=True)
        for doc, text in docs.items():
            with open(os.path.join(gdir, doc), "w") as f:
                f.write(text)

    # Amazon reviews: JSON-lines with reviewText/overall.
    adir = os.path.join(ROOT, "amazon")
    os.makedirs(adir, exist_ok=True)
    with open(os.path.join(adir, "reviews.jsonl"), "w") as f:
        for text, stars in AMAZON_ROWS:
            f.write(json.dumps({"reviewText": text, "overall": stars}) + "\n")

    # TIMIT: npz of frame features + labels (deterministic integers).
    tdir = os.path.join(ROOT, "timit")
    os.makedirs(tdir, exist_ok=True)
    feats = (
        np.arange(TIMIT_N * TIMIT_D, dtype=np.float64).reshape(TIMIT_N, TIMIT_D)
        / 100.0
    )
    labels = (np.arange(TIMIT_N) * 7 % 24).astype(np.int64)
    np.savez(os.path.join(tdir, "frames.npz"), features=feats, labels=labels)

    print(f"fixtures written under {ROOT}")


if __name__ == "__main__":
    main()
