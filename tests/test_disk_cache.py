"""Cross-process fitted-prefix reuse: content-stable signatures, structural
digests, the on-disk fit store, and NodeOptimizationRule memoization.

Ref: the reference's prefix-state reuse across fits (SURVEY.md §2.1
auto-caching row, §5 checkpoint/resume row) [unverified].
"""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from keystone_tpu.nodes.learning import LeastSquaresEstimator
from keystone_tpu.nodes.stats import StandardScaler
from keystone_tpu.workflow import LabelEstimator, PipelineEnv, Transformer
from keystone_tpu.workflow.fingerprint import (
    UNSTABLE,
    digest_tree,
    is_stable,
    stable_value,
)
from keystone_tpu.workflow.graph import structural_digest


def _data(seed=0, n=256, d=16, k=3):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    Y = rng.normal(size=(n, k)).astype(np.float32)
    return X, Y


class _MatTransformer(Transformer):
    jittable = False

    def __init__(self, W):
        self.W = W

    def apply_batch(self, B):
        return np.asarray(B) @ self.W


class CountingEstimator(LabelEstimator):
    """Closed-form ridge whose fits are counted — the cache-hit oracle."""

    fits = 0

    def __init__(self, lam: float = 1e-3):
        self.lam = lam

    def fit(self, data, labels):
        type(self).fits += 1
        X = np.asarray(data)
        Y = np.asarray(labels)
        W = np.linalg.solve(
            X.T @ X + self.lam * np.eye(X.shape[1]), X.T @ Y
        ).astype(np.float32)
        return _MatTransformer(W)


class TestStableSignatures:
    def test_identical_estimators_share_signature(self):
        a = LeastSquaresEstimator(lam=0.5, block_size=128)
        b = LeastSquaresEstimator(lam=0.5, block_size=128)
        assert a.signature() == b.signature()
        assert is_stable(stable_value(a.signature()))

    def test_hyperparams_distinguish(self):
        a = LeastSquaresEstimator(lam=0.5)
        b = LeastSquaresEstimator(lam=0.25)
        assert a.signature() != b.signature()

    def test_fit_time_diagnostics_do_not_change_signature(self):
        est = LeastSquaresEstimator(lam=0.5)
        before = est.signature()
        X, Y = _data()
        est.fit(X, Y)
        assert est.last_choice is not None  # the excluded mutable field moved
        assert est.signature() == before

    def test_unknown_objects_poison_but_stay_unique(self):
        o1, o2 = object(), object()  # keep both alive: distinct ids
        tree_a = stable_value({"fn": o1})
        tree_b = stable_value({"fn": o2})
        assert not is_stable(tree_a)
        assert tree_a != tree_b  # id keeps in-process uniqueness
        assert digest_tree(tree_a) is None

    def test_array_content_addresses(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        y = x.copy()
        assert stable_value(x) == stable_value(y)
        z = y.copy()
        z[0, 0] += 1
        assert stable_value(x) != stable_value(z)

    def test_sampled_fingerprint_bounded_and_probing(self, monkeypatch):
        """Over-limit arrays with FEW, HUGE rows (n0 < 64) used to degrade to
        a full-buffer hash; now the per-chunk cap bounds pass 1 and the
        prime-strided element probe still sees changes past the cap."""
        from keystone_tpu.config import config
        from keystone_tpu.workflow.fingerprint import array_fingerprint

        monkeypatch.setattr(config, "fingerprint_max_bytes", 1 << 20)
        # 4 rows x 2 MiB: rows_per=1, so pass 1 hashes only the first 1 MiB
        # of each row. A change in the second MiB must still flip the digest
        # via the whole-array probe lattice (~32-element step here).
        a = np.zeros((4, 512 * 1024), dtype=np.float32)
        tag, shape, dt, dig = array_fingerprint(a)
        assert tag == "ndarray-sampled"
        b = a.copy()
        b[0, 300 * 1024 : 300 * 1024 + 512] = 1.0  # byte offset ~1.2 MiB
        assert array_fingerprint(b)[3] != dig
        assert array_fingerprint(a.copy())[3] == dig  # deterministic

    def test_sampled_fingerprint_layout_independent(self, monkeypatch):
        """The same logical matrix, C- vs F-contiguous, must digest equal —
        the cross-process cache key can't depend on who materialized it."""
        from keystone_tpu.config import config
        from keystone_tpu.workflow.fingerprint import array_fingerprint

        monkeypatch.setattr(config, "fingerprint_max_bytes", 1 << 16)
        rng = np.random.default_rng(3)
        c = np.ascontiguousarray(rng.normal(size=(64, 2048)).astype(np.float32))
        f = np.asfortranarray(c)
        assert not f.flags.c_contiguous and f.flags.f_contiguous
        assert array_fingerprint(c) == array_fingerprint(f)

    def test_sampled_fingerprint_noncontiguous_probed(self, monkeypatch):
        """Non-contiguous over-limit views get the element probe too: a
        change past pass 1's per-chunk cap still flips the digest."""
        from keystone_tpu.config import config
        from keystone_tpu.workflow.fingerprint import array_fingerprint

        monkeypatch.setattr(config, "fingerprint_max_bytes", 1 << 20)
        base = np.zeros((4, 1024 * 1024), dtype=np.float32)
        a = base[:, ::2]  # non-contiguous, 4 rows x 2 MiB
        dig = array_fingerprint(a)[3]
        base2 = base.copy()
        base2[0, 600 * 1024 : 600 * 1024 + 1024] = 1.0  # past the 1 MiB cap
        assert array_fingerprint(base2[:, ::2])[3] != dig


class TestStructuralDigest:
    def test_digest_stable_across_rebuilds(self):
        X, Y = _data()

        def build():
            p = StandardScaler().with_data(X.copy()).and_then(
                LeastSquaresEstimator(lam=1e-3), X.copy(), Y.copy()
            )
            return p

        from keystone_tpu.workflow.operators import EstimatorOperator

        digests = []
        for _ in range(2):
            p = build()
            g = p.graph
            for nid in g.reachable([p.sink]):
                if isinstance(g.operators[nid], EstimatorOperator):
                    digests.append(structural_digest(g, nid))
        assert digests and all(d is not None for d in digests)
        # Both estimator nodes (scaler + solver) match across rebuilds.
        assert digests[: len(digests) // 2] == digests[len(digests) // 2 :]

    def test_non_array_data_disables_digest(self):
        est = CountingEstimator()
        _, Y = _data(n=3)
        p = est.with_data([b"not", b"an", b"array"], Y)
        from keystone_tpu.workflow.operators import EstimatorOperator

        g = p.graph
        (enid,) = [
            nid
            for nid in g.reachable([p.sink])
            if isinstance(g.operators[nid], EstimatorOperator)
        ]
        assert structural_digest(g, enid) is None


class TestSessionCacheCrossInstance:
    def test_identical_pipelines_fit_once(self):
        X, Y = _data()
        CountingEstimator.fits = 0
        p1 = CountingEstimator(lam=1e-3).with_data(X.copy(), Y.copy()).fit()
        p2 = CountingEstimator(lam=1e-3).with_data(X.copy(), Y.copy()).fit()
        assert CountingEstimator.fits == 1
        out1 = np.asarray(p1.apply(X).get())
        out2 = np.asarray(p2.apply(X).get())
        np.testing.assert_allclose(out1, out2)

    def test_different_data_refits(self):
        X, Y = _data(seed=0)
        X2, Y2 = _data(seed=1)
        CountingEstimator.fits = 0
        CountingEstimator(lam=1e-3).with_data(X, Y).fit()
        CountingEstimator(lam=1e-3).with_data(X2, Y2).fit()
        assert CountingEstimator.fits == 2


class TestDiskCache:
    def test_second_session_hits_disk(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KEYSTONE_CACHE_DIR", str(tmp_path))
        X, Y = _data()
        CountingEstimator.fits = 0

        PipelineEnv.reset()
        p = CountingEstimator(lam=1e-3).with_data(X.copy(), Y.copy()).fit()
        ref = np.asarray(p.apply(X).get())
        assert CountingEstimator.fits == 1
        assert any(f.endswith(".fit.pkl") for f in os.listdir(tmp_path))

        PipelineEnv.reset()  # a "new process" as far as session state goes
        p2 = CountingEstimator(lam=1e-3).with_data(X.copy(), Y.copy()).fit()
        assert CountingEstimator.fits == 1  # served from disk
        np.testing.assert_allclose(np.asarray(p2.apply(X).get()), ref)

    def test_corrupt_entry_degrades_to_miss(self, tmp_path, monkeypatch):
        monkeypatch.setenv("KEYSTONE_CACHE_DIR", str(tmp_path))
        X, Y = _data()
        CountingEstimator.fits = 0
        PipelineEnv.reset()
        CountingEstimator(lam=1e-3).with_data(X.copy(), Y.copy()).fit()
        (entry,) = [f for f in os.listdir(tmp_path) if f.endswith(".fit.pkl")]
        (tmp_path / entry).write_bytes(b"corrupt")
        PipelineEnv.reset()
        CountingEstimator(lam=1e-3).with_data(X.copy(), Y.copy()).fit()
        assert CountingEstimator.fits == 2  # refit, no crash

    def test_malicious_entry_rejected(self, tmp_path):
        """A planted pickle whose payload resolves a non-allowlisted callable
        (the classic ``os.system`` reduce) must degrade to a miss, not run."""
        import pickle

        from keystone_tpu.workflow.disk_cache import DiskFitCache

        class Evil:
            def __reduce__(self):
                return (os.system, ("echo pwned > /dev/null",))

        cache = DiskFitCache(str(tmp_path / "store"))
        path = cache._path("deadbeef")
        with open(path, "wb") as f:
            pickle.dump(Evil(), f)
        assert cache.get("deadbeef") is None  # rejected and dropped
        assert not os.path.exists(path)

    def test_unimported_module_never_imported_by_cache_read(self, tmp_path):
        """find_class must refuse to IMPORT unknown modules — even resolving
        one runs its top-level code, so rejection has to come first."""
        import pickle
        import pickletools  # stdlib, importable, NOT in sys.modules' deps

        from keystone_tpu.workflow.disk_cache import DiskFitCache

        # Hand-craft a pickle whose GLOBAL names a module that is importable
        # but not yet imported; loading must miss without importing it.
        victim = "antigravity"  # stdlib easter egg; never imported by us
        payload = (
            b"\x80\x04" + b"c" + victim.encode() + b"\nfly\n" + b"."
        )  # proto4, GLOBAL antigravity.fly, STOP
        cache = DiskFitCache(str(tmp_path / "store"))
        with open(cache._path("k"), "wb") as f:
            f.write(payload)
        assert cache.get("k") is None
        assert victim not in sys.modules

    def test_gadget_chain_callables_rejected(self, tmp_path):
        """Allowlisted-module FUNCTIONS (numpy.load, functools.partial) are
        denied — only enumerated reconstructors and classes resolve."""
        import pickle

        from keystone_tpu.workflow.disk_cache import DiskFitCache

        class NumpyLoadGadget:
            def __reduce__(self):
                import numpy

                return (numpy.load, ("/nonexistent.npy",))

        class PartialGadget:
            def __reduce__(self):
                import functools

                return (functools.partial, (print,))

        class MemmapGadget:
            def __reduce__(self):
                import numpy

                target = str(tmp_path / "victim.bin")
                return (numpy.memmap, (target, "uint8", "w+", 0, (1,)))

        cache = DiskFitCache(str(tmp_path / "store"))
        gadgets = (NumpyLoadGadget(), PartialGadget(), MemmapGadget())
        for i, evil in enumerate(gadgets):
            with open(cache._path(f"g{i}"), "wb") as f:
                pickle.dump(evil, f)
            assert cache.get(f"g{i}") is None, type(evil).__name__
        # The memmap constructor must never have run (no file created).
        assert not (tmp_path / "victim.bin").exists()

    def test_restricted_unpickler_roundtrips_real_transformers(self, tmp_path):
        """The allowlist must not break the normal path: a fitted keystone
        transformer holding jax/numpy state loads back through it."""
        from keystone_tpu.nodes.stats import StandardScaler
        from keystone_tpu.workflow.disk_cache import DiskFitCache

        X = np.random.default_rng(0).normal(size=(32, 4)).astype(np.float32)
        fitted = StandardScaler().fit(X)
        cache = DiskFitCache(str(tmp_path / "store"))
        cache.put("k", fitted)
        loaded = cache.get("k")
        assert loaded is not None
        np.testing.assert_allclose(
            np.asarray(loaded.apply_batch(X)), np.asarray(fitted.apply_batch(X))
        )

    def test_cache_dir_created_private(self, tmp_path):
        from keystone_tpu.workflow.disk_cache import DiskFitCache

        root = tmp_path / "fresh"
        DiskFitCache(str(root))
        assert (root.stat().st_mode & 0o777) == 0o700

    @pytest.mark.slow
    def test_cross_process_reuse(self, tmp_path):
        """The VERDICT regression: a second *process* skips every refit."""
        script = textwrap.dedent(
            """
            import logging, sys
            import numpy as np
            import jax
            jax.config.update("jax_platforms", "cpu")
            logging.basicConfig(level=logging.INFO)
            from keystone_tpu.nodes.learning import LeastSquaresEstimator
            from keystone_tpu.nodes.stats import StandardScaler

            rng = np.random.default_rng(0)
            X = rng.normal(size=(512, 32)).astype(np.float32)
            Y = rng.normal(size=(512, 4)).astype(np.float32)
            p = StandardScaler().with_data(X).and_then(
                LeastSquaresEstimator(lam=1e-3), X, Y
            ).fit()
            out = np.asarray(p.apply(X).get())
            np.save(sys.argv[1], out)
            """
        )
        from keystone_tpu.utils.platform import cpu_mesh_env

        env = cpu_mesh_env(8)
        env["KEYSTONE_CACHE_DIR"] = str(tmp_path)
        outs, hits = [], []
        for i in range(2):
            out_npy = str(tmp_path / f"out{i}.npy")
            proc = subprocess.run(
                [sys.executable, "-c", script, out_npy],
                env=env,
                capture_output=True,
                text=True,
                timeout=300,
                cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            )
            assert proc.returncode == 0, proc.stderr[-2000:]
            outs.append(np.load(out_npy))
            hits.append(proc.stderr.count("disk fit cache: hit"))
        np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5, atol=1e-5)
        assert hits[0] == 0
        # Both estimators (scaler + solver) served from the store, no refits.
        assert hits[1] == 2


class TestDiskCacheTrim:
    def test_evicts_least_recently_used(self, tmp_path):
        import time

        from keystone_tpu.workflow.disk_cache import DiskFitCache

        X, _ = _data()
        one = len(__import__("pickle").dumps(_MatTransformer(X)))
        # Budget fits exactly one entry, so the eviction ORDER is pinned:
        # the stale entry goes, the freshly-used one survives.
        cache = DiskFitCache(str(tmp_path), max_bytes=int(one * 1.5))
        cache.put("aaa", _MatTransformer(X))
        time.sleep(0.05)
        assert cache.get("aaa") is not None  # refreshes recency
        time.sleep(0.05)
        cache.put("bbb", _MatTransformer(X))
        time.sleep(0.05)
        assert cache.get("bbb") is not None
        cache.put("ccc", _MatTransformer(X))  # trims: aaa is now the LRU
        remaining = {
            f for f in os.listdir(tmp_path) if f.endswith(".fit.pkl")
        }
        assert "ccc.fit.pkl" in remaining
        assert "aaa.fit.pkl" not in remaining

    def test_no_trim_under_budget(self, tmp_path):
        from keystone_tpu.workflow.disk_cache import DiskFitCache

        cache = DiskFitCache(str(tmp_path), max_bytes=1 << 30)
        X, _ = _data()
        cache.put("aaa", _MatTransformer(X))
        cache.put("bbb", _MatTransformer(X))
        assert cache.get("aaa") is not None and cache.get("bbb") is not None


class TestCrashSafety:
    """The checkpoint/resume substrate (ISSUE 3 satellite): a process
    killed mid-write must never leave a truncated entry a later get()
    trips over."""

    def test_put_is_atomic_no_partial_entry_visible(self, tmp_path):
        """Simulate a kill mid-write: a pickler that dies halfway through
        dump leaves ONLY a temp file — the addressed entry never exists in
        a partial state."""
        import pickle
        from unittest import mock

        from keystone_tpu.workflow.disk_cache import DiskCache

        cache = DiskCache(str(tmp_path / "store"))
        payload = {"W": np.zeros((64, 64), dtype=np.float32)}

        class Killed(BaseException):
            pass

        def dying_dump(obj, f):
            f.write(pickle.dumps(obj)[:100])  # partial bytes on disk...
            raise Killed()  # ...then the "kill"

        with mock.patch.object(pickle, "dump", dying_dump):
            with pytest.raises(Killed):
                cache.put("ck", payload)
        assert cache.get("ck") is None  # entry never became addressable
        assert not os.path.exists(cache._path("ck"))

    def test_overwrite_is_atomic_old_entry_survives_killed_rewrite(
        self, tmp_path
    ):
        import pickle
        from unittest import mock

        from keystone_tpu.workflow.disk_cache import DiskCache

        cache = DiskCache(str(tmp_path / "store"))
        cache.put("ck", {"chunks_done": 4}, overwrite=True)

        class Killed(BaseException):
            pass

        def dying_dump(obj, f):
            raise Killed()

        with mock.patch.object(pickle, "dump", dying_dump):
            with pytest.raises(Killed):
                cache.put("ck", {"chunks_done": 6}, overwrite=True)
        # The PREVIOUS complete checkpoint is still there, readable.
        assert cache.get("ck") == {"chunks_done": 4}

    def test_overwrite_replaces_and_default_put_dedups(self, tmp_path):
        from keystone_tpu.workflow.disk_cache import DiskCache

        cache = DiskCache(str(tmp_path / "store"))
        cache.put("k", 1)
        cache.put("k", 2)  # content-addressed default: first write wins
        assert cache.get("k") == 1
        cache.put("k", 3, overwrite=True)
        assert cache.get("k") == 3

    def test_stale_tmps_swept_fresh_ones_kept(self, tmp_path):
        import time

        from keystone_tpu.workflow.disk_cache import DiskCache

        root = tmp_path / "store"
        DiskCache(str(root))  # create
        stale = root / "deadbeef.pkl.tmp"
        fresh = root / "inflight.pkl.tmp"
        other = root / "cafe.fit.pkl.tmp"  # a CO-RESIDENT store's orphan
        for f in (stale, fresh, other):
            f.write_bytes(b"partial")
        old = time.time() - 2 * DiskCache._TMP_MAX_AGE_S
        os.utime(stale, (old, old))
        os.utime(other, (old, old))
        DiskCache(str(root))  # a new store sweeps its root
        assert not stale.exists()  # own orphan gone
        assert fresh.exists()  # live concurrent writer's temp untouched
        assert other.exists()  # suffix-scoped: another store's, not ours

    def test_suffixes_namespace_coresident_stores(self, tmp_path):
        from keystone_tpu.workflow.disk_cache import DiskCache, DiskFitCache

        root = str(tmp_path / "store")
        ckpt = DiskCache(root, suffix=".ckpt.pkl")
        fits = DiskFitCache(root)
        ckpt.put("same-key", {"kind": "checkpoint"})
        fits.put("same-key", {"kind": "fit"})
        assert ckpt.get("same-key") == {"kind": "checkpoint"}
        assert fits.get("same-key") == {"kind": "fit"}


class TestConcurrentWriters:
    @pytest.mark.slow
    def test_parallel_processes_share_one_store(self, tmp_path):
        """Four processes share one cache dir, two per problem — the pairs
        race the SAME content key's tmp+rename commit while the pairs
        differ. Every process's second session must log a real store hit
        (not just reproduce values by refitting), entries must end corrupt-
        free, and a distinct-key pair must coexist with the racing pair."""
        script = textwrap.dedent(
            """
            import logging, os, sys
            import numpy as np
            import jax
            jax.config.update("jax_platforms", "cpu")
            logging.basicConfig(level=logging.INFO)
            from keystone_tpu.nodes.learning import LeastSquaresEstimator
            from keystone_tpu.workflow import PipelineEnv

            seed = int(sys.argv[1])
            rng = np.random.default_rng(seed)
            X = rng.normal(size=(128, 16)).astype(np.float32)
            W = rng.normal(size=(16, 2)).astype(np.float32)
            Y = X @ W
            p = LeastSquaresEstimator(lam=1e-4).with_data(X, Y).fit()
            out1 = np.asarray(p.apply(X).get())
            PipelineEnv.reset()  # second "session": must hit the store
            p2 = LeastSquaresEstimator(lam=1e-4).with_data(X.copy(), Y.copy()).fit()
            out2 = np.asarray(p2.apply(X).get())
            np.testing.assert_allclose(out2, out1, rtol=1e-6)
            resid = np.linalg.norm(out1 - Y) / np.linalg.norm(Y)
            assert resid < 1e-3, resid
            print("WRITER_OK", seed)
            """
        )
        from keystone_tpu.utils.platform import cpu_mesh_env

        env = cpu_mesh_env(2)
        env["KEYSTONE_CACHE_DIR"] = str(tmp_path)
        procs = []
        try:
            procs = [
                subprocess.Popen(
                    [sys.executable, "-c", script, str(seed)],
                    env=env,
                    cwd=os.path.dirname(
                        os.path.dirname(os.path.abspath(__file__))
                    ),
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    text=True,
                )
                for seed in (0, 0, 1, 1)  # pairs race the same key
            ]
            for p in procs:
                out, err = p.communicate(timeout=300)
                assert p.returncode == 0, err[-2000:]
                assert "WRITER_OK" in out
                # The read path must actually serve the entry — a refit
                # would reproduce the values and hide a dead get().
                assert "disk fit cache: hit" in err
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.communicate()
        entries = [f for f in os.listdir(tmp_path) if f.endswith(".fit.pkl")]
        assert len(entries) == 2  # one entry per distinct problem
        assert not [f for f in os.listdir(tmp_path) if f.endswith(".tmp")]


class TestNodeOptimizationMemo:
    def test_concrete_estimator_stable_across_passes(self):
        from keystone_tpu.workflow.operators import EstimatorOperator
        from keystone_tpu.workflow.rules import NodeOptimizationRule

        X, Y = _data(n=256, d=16)
        p = LeastSquaresEstimator(lam=1e-3).with_data(X, Y)
        rule = NodeOptimizationRule()
        g1 = rule.apply(p.graph, [p.sink])
        g2 = rule.apply(p.graph, [p.sink])
        c1 = [
            op.estimator
            for op in g1.operators.values()
            if isinstance(op, EstimatorOperator)
            and not isinstance(op.estimator, LeastSquaresEstimator)
        ]
        c2 = [
            op.estimator
            for op in g2.operators.values()
            if isinstance(op, EstimatorOperator)
            and not isinstance(op.estimator, LeastSquaresEstimator)
        ]
        assert c1 and c2 and c1[0] is c2[0]


def test_trust_all_knob_fails_closed_on_falsy_spellings(tmp_path, monkeypatch):
    """KEYSTONE_CACHE_TRUST_ALL is a security knob: only the strict "1"
    disables the restricted unpickler; "off"/"disabled"/"0" keep it."""
    import glob
    import pickle

    import numpy as np

    from keystone_tpu.workflow.disk_cache import DiskFitCache

    cache = DiskFitCache(str(tmp_path))
    key = "deadbeef" * 8
    cache.put(key, np.arange(4.0))
    entry = glob.glob(str(tmp_path / "**" / "*.pkl"), recursive=True)[0]

    class Evil:
        def __reduce__(self):
            return (eval, ("['pwned']",))

    for spelling, expect_blocked in [
        ("off", True),
        ("disabled", True),
        ("0", True),
        ("1", False),
    ]:
        with open(entry, "wb") as f:
            pickle.dump(Evil(), f)
        monkeypatch.setenv("KEYSTONE_CACHE_TRUST_ALL", spelling)
        got = cache.get(key)  # rejected entries -> dropped, miss (None)
        assert (got is None) == expect_blocked, (spelling, got)
