"""Mesh-native data-parallel fit (the ISSUE-13 tentpole).

Covers the four contracts of the spec-threaded path:

1. **Spec threading** — fused chains lower ONCE with the ``SpecLayout``
   convention's explicit ``in_shardings``/``out_shardings`` instead of
   inheriting input placement, and ``fitted_forward(layout=...)`` does
   the same for the functional replay.
2. **No silent cliff** — non-divisible batches mask-pad onto the mesh
   and trim (row counts downstream unchanged); the single-device
   fallback survives only below ``shard_min_rows`` and every decision
   is registry-counted.
3. **Bit-identity** — sharded vs unsharded fit/apply is byte-equal on
   the canonical pipeline shapes (MNIST FFT, the two-branch
   featurize→solve shape, newsgroups text), including under the
   standard chaos plan.
4. **Sharding-safe state** — checkpoints carry the mesh manifest and a
   mesh-width change is REFUSED with the typed ``MeshMismatchError``
   (both solvers, pinned both ways); profile-store entries from a
   different device_count are refused at load; profile rows carry the
   shard count; the resource planner prices chunks per shard.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.config import config
from keystone_tpu.utils import mesh as mesh_util
from keystone_tpu.utils.mesh import (
    MeshMismatchError,
    SpecLayout,
    batch_layout,
    layout_of_array,
    num_data_shards,
    reset_default_mesh,
    set_default_mesh,
    value_data_shards,
)
from keystone_tpu.utils.metrics import sharding_counters
from keystone_tpu.workflow.executor import PipelineEnv
from keystone_tpu.workflow.operators import DatasetOperator
from keystone_tpu.workflow.pipeline import Pipeline, Transformer


@pytest.fixture(autouse=True)
def _fresh_sharding_state():
    """Counters and the shard toggle restored around every test."""
    prior = config.shard_data_batches
    sharding_counters.reset()
    yield
    config.shard_data_batches = prior
    sharding_counters.reset()


class MatmulChain(Transformer):
    """A deterministic jittable featurize chain (matmul + elementwise)."""

    def __init__(self, seed: int, d_in: int = 32, d_out: int = 48):
        self.seed, self.d_in, self.d_out = int(seed), int(d_in), int(d_out)
        rng = np.random.default_rng(self.seed)
        self._W = jnp.asarray(
            rng.normal(size=(d_in, d_out)).astype(np.float32)
        )

    def signature(self):
        return self.stable_signature(self.seed, self.d_in, self.d_out)

    def apply_batch(self, X):
        Y = jnp.tanh(X @ self._W)
        return Y / (1.0 + jnp.abs(Y))


def _two_branch_pipeline(X, y):
    """The two-branch ImageNet-featurizer shape at test scale: two
    jittable branches gathered into one block least squares."""
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator

    feat = Pipeline.gather(
        [MatmulChain(1).to_pipeline(), MatmulChain(2).to_pipeline()]
    )
    return feat.and_then(
        BlockLeastSquaresEstimator(block_size=96, num_iters=1, lam=1e-3),
        X, y,
    )


def _fit_apply(build, X_test, shard: bool) -> np.ndarray:
    PipelineEnv.reset()
    prior = config.shard_data_batches
    config.shard_data_batches = shard
    try:
        fitted = build().fit()
        return np.asarray(fitted.apply(X_test).get())
    finally:
        config.shard_data_batches = prior
        PipelineEnv.reset()


# ---------------------------------------------------------------------------
# Mesh helpers: reset + SpecLayout
# ---------------------------------------------------------------------------


def test_reset_default_mesh_drops_memoized_narrow_mesh():
    set_default_mesh(
        mesh_util.default_mesh(devices=jax.devices()[:1])
    )
    assert num_data_shards() == 1
    reset_default_mesh()
    assert num_data_shards() == len(jax.devices()) == 8


def test_spec_layout_convention_and_pad_put():
    layout = SpecLayout.for_mesh()
    assert layout.num_shards == 8
    assert layout.data().spec == jax.sharding.PartitionSpec(
        config.data_axis
    )
    assert layout.replicated().spec == jax.sharding.PartitionSpec()
    x = np.arange(70 * 4, dtype=np.float32).reshape(70, 4)
    padded, n = layout.pad_put(x)
    assert n == 70 and padded.shape == (72, 4)
    assert layout_of_array(padded) == layout
    assert value_data_shards(padded) == 8
    # The explicit lowering is bit-identical to the plain jit.
    chain = lambda a: jnp.tanh(a) * 2.0  # noqa: E731
    got = np.asarray(layout.jit(chain)(padded))[:70]
    np.testing.assert_array_equal(got, np.asarray(jax.jit(chain)(x)))


def test_batch_layout_decisions():
    layout = SpecLayout.for_mesh()
    big_div = np.zeros((128, 4), dtype=np.float32)
    big_odd = np.zeros((130, 4), dtype=np.float32)
    small = np.zeros((8, 4), dtype=np.float32)
    text = np.array(["a", "b"], dtype=object)
    # Divisible host batches stage (and donate) through the chain call
    # when they arrive host-side (e.g. from a host stage mid-chain).
    assert batch_layout(big_div) == layout
    # Non-divisible >= min rows: the mask-pad path.
    assert batch_layout(big_odd) == layout
    assert batch_layout(small) is None
    assert batch_layout(text) is None
    # An already-sharded array re-lowers with its own layout.
    assert batch_layout(layout.put(big_div)) == layout


# ---------------------------------------------------------------------------
# No silent cliff: DatasetOperator + fused-chain pad path
# ---------------------------------------------------------------------------


def test_dataset_operator_places_and_counts():
    div = DatasetOperator(np.zeros((128, 4), dtype=np.float32)).execute([])
    assert isinstance(div, jax.Array)
    assert value_data_shards(div) == 8
    odd = DatasetOperator(np.zeros((130, 4), dtype=np.float32)).execute([])
    assert isinstance(odd, np.ndarray)  # deferred to the chain's pad path
    small = DatasetOperator(np.zeros((16, 4), dtype=np.float32)).execute([])
    assert isinstance(small, np.ndarray)
    snap = sharding_counters.snapshot()
    assert snap.get("batches_sharded") == 1
    assert snap.get("batches_deferred_pad") == 1
    assert snap.get("fallback_small_batch") == 1


def test_fused_chain_pads_trims_and_counts():
    """A non-divisible batch through a jittable chain: output rows are
    unchanged, values are bit-identical to the unsharded walk, and the
    pad traffic is registry-counted — zero silent fallbacks."""
    t = MatmulChain(3)
    X = np.random.default_rng(0).normal(size=(70, 32)).astype(np.float32)
    config.shard_data_batches = False
    ref = np.asarray(t.batch_call(X))
    config.shard_data_batches = True
    sharding_counters.reset()
    out = t.batch_call(X)
    assert out.shape[0] == 70
    np.testing.assert_array_equal(np.asarray(out), ref)
    snap = sharding_counters.snapshot()
    assert snap.get("batches_padded") == 1
    assert snap.get("pad_rows_added") == 2
    assert snap.get("sharded_chain_calls") == 1
    assert "fallback_small_batch" not in snap


def test_row_coupled_chain_refuses_padding():
    class RowCoupled(MatmulChain):
        row_independent = False

    t = RowCoupled(4)
    X = np.random.default_rng(0).normal(size=(70, 32)).astype(np.float32)
    config.shard_data_batches = False
    ref = np.asarray(t.batch_call(X))
    config.shard_data_batches = True
    sharding_counters.reset()
    out = np.asarray(t.batch_call(X))
    np.testing.assert_array_equal(out, ref)
    snap = sharding_counters.snapshot()
    assert snap.get("fallback_row_coupled") == 1
    assert "batches_padded" not in snap


def test_sharded_input_uses_explicit_specs():
    """An already-sharded batch re-lowers with the explicit SpecLayout
    shardings (counted), and the output keeps the row-sharded layout."""
    t = MatmulChain(5)
    layout = SpecLayout.for_mesh()
    X = np.random.default_rng(0).normal(size=(64, 32)).astype(np.float32)
    config.shard_data_batches = True
    sharding_counters.reset()
    out = t.batch_call(layout.put(X))
    assert sharding_counters.get("sharded_chain_calls") == 1
    assert layout_of_array(out) == layout
    config.shard_data_batches = False
    np.testing.assert_array_equal(np.asarray(out), np.asarray(t.batch_call(X)))


# ---------------------------------------------------------------------------
# Bit-identity on the canonical pipeline shapes
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("rows", [512, 518])
def test_two_branch_fit_apply_bit_identical(rows):
    """The two-branch featurize→solve shape, divisible and mask-padded:
    the sharded walk's held-out predictions equal the single-device
    walk's byte for byte."""
    rng = np.random.default_rng(0)
    X = rng.normal(size=(rows, 32)).astype(np.float32)
    y = rng.normal(size=(rows, 4)).astype(np.float32)
    X_test = rng.normal(size=(70, 32)).astype(np.float32)
    build = lambda: _two_branch_pipeline(X, y)  # noqa: E731
    ref = _fit_apply(build, X_test, shard=False)
    sharding_counters.reset()
    got = _fit_apply(build, X_test, shard=True)
    np.testing.assert_array_equal(ref, got)
    snap = sharding_counters.snapshot()
    assert snap.get("sharded_chain_calls", 0) > 0
    assert "fallback_small_batch" not in snap


def test_mnist_fft_fit_apply_bit_identical():
    """The canonical MNIST random-FFT pipeline (gathered FFT branches →
    LinearMapEstimator → MaxClassifier), sharded vs unsharded."""
    from keystone_tpu.loaders import MnistLoader
    from keystone_tpu.pipelines.images.mnist_random_fft import (
        MnistRandomFFTConfig,
        build_pipeline,
    )

    conf = MnistRandomFFTConfig(num_ffts=2, synthetic_n=512, seed=0)
    train, test = MnistLoader.synthetic(n=conf.synthetic_n, seed=conf.seed)
    build = lambda: build_pipeline(  # noqa: E731
        conf, train.data, train.labels
    )
    ref = _fit_apply(build, test.data, shard=False)
    got = _fit_apply(build, test.data, shard=True)
    np.testing.assert_array_equal(ref, got)


def test_newsgroups_fit_apply_bit_identical():
    """The canonical newsgroups text shape (host tokenize → n-grams →
    term frequency → sparse features → naive bayes): the sharded walk
    must leave the host/text path byte-identical."""
    from keystone_tpu.loaders.newsgroups import NewsgroupsDataLoader
    from keystone_tpu.nodes.learning import NaiveBayesEstimator
    from keystone_tpu.nodes.nlp import (
        CommonSparseFeatures,
        LowerCase,
        NGramsFeaturizer,
        TermFrequency,
        Tokenizer,
        Trim,
    )
    from keystone_tpu.nodes.util import MaxClassifier

    train, test, classes = NewsgroupsDataLoader.synthetic(
        n=240, num_classes=3
    )

    def build():
        featurizer = (
            Trim()
            .and_then(LowerCase())
            .and_then(Tokenizer())
            .and_then(NGramsFeaturizer(1, 2))
            .and_then(TermFrequency("log"))
            .and_then(CommonSparseFeatures(512), train.data)
        )
        return featurizer.and_then(
            NaiveBayesEstimator(len(classes)), train.data, train.labels
        ).and_then(MaxClassifier())

    ref = _fit_apply(build, test.data, shard=False)
    got = _fit_apply(build, test.data, shard=True)
    np.testing.assert_array_equal(ref, got)


def test_chaos_parity_sharded_fit():
    """The standard chaos plan (io:0.05,oom:1) injected under the SHARDED
    walk: every fault recovers invisibly and the fit/apply stays
    bit-identical to the fault-free sharded run."""
    rng = np.random.default_rng(1)
    X = rng.normal(size=(518, 32)).astype(np.float32)
    y = rng.normal(size=(518, 4)).astype(np.float32)
    X_test = rng.normal(size=(70, 32)).astype(np.float32)
    build = lambda: _two_branch_pipeline(X, y)  # noqa: E731
    baseline = _fit_apply(build, X_test, shard=True)
    prior = (config.faults, config.faults_seed)
    try:
        config.faults, config.faults_seed = "io:0.05,oom:1", 0
        chaos = _fit_apply(build, X_test, shard=True)
    finally:
        config.faults, config.faults_seed = prior
    np.testing.assert_array_equal(baseline, chaos)


def test_fitted_forward_with_layout():
    """The functional replay lowered once with explicit shardings is
    bit-identical to the un-jitted replay and row-sharded on output."""
    from keystone_tpu.workflow.functional import fitted_forward

    rng = np.random.default_rng(0)
    X = rng.normal(size=(512, 32)).astype(np.float32)
    y = rng.normal(size=(512, 4)).astype(np.float32)
    fitted = _two_branch_pipeline(X, y).fit()
    layout = SpecLayout.for_mesh()
    fn_plain = fitted_forward(fitted, X[:8])
    fn_sharded = fitted_forward(fitted, X[:8], layout=layout)
    Xb = rng.normal(size=(64, 32)).astype(np.float32)
    ref = np.asarray(jax.jit(fn_plain)(Xb))
    out = fn_sharded(layout.put(Xb))
    assert layout_of_array(out) == layout
    np.testing.assert_array_equal(np.asarray(out), ref)


# ---------------------------------------------------------------------------
# Sharding-safe state: checkpoints, profile store, planner
# ---------------------------------------------------------------------------


def test_stream_checkpoint_mesh_width_refusal_both_ways(tmp_path, monkeypatch):
    # The refuse-only contract under KEYSTONE_ELASTIC_MESH=0 — the
    # default-on elastic migration path is pinned in test_elastic_mesh.py.
    monkeypatch.setattr(config, "elastic_mesh", False)
    from keystone_tpu.linalg.normal_equations import (
        _STREAM_CKPT_KEY,
        _StreamCheckpointer,
        _stream_fingerprint,
    )

    rng = np.random.default_rng(0)
    chunk = (
        rng.normal(size=(64, 8)).astype(np.float32),
        rng.normal(size=(64, 2)).astype(np.float32),
    )
    fp = _stream_fingerprint(chunk)
    assert fp["device_count"] == 8
    assert fp["data_axis"] == config.data_axis

    # Same problem recorded under a DIFFERENT mesh width: typed refusal.
    ck = _StreamCheckpointer(str(tmp_path), checkpoint_every=1)
    narrow = dict(fp, device_count=1)
    ck.store.put(
        _STREAM_CKPT_KEY,
        {"fingerprint": narrow, "chunks_done": 2,
         "gram": np.eye(8), "atb": np.zeros((8, 2))},
        overwrite=True,
    )
    with pytest.raises(MeshMismatchError):
        ck.resume(chunk)

    # Same mesh width: resumes (the refusal never blocks a legal resume).
    ck2 = _StreamCheckpointer(str(tmp_path), checkpoint_every=1)
    ck2.store.put(
        _STREAM_CKPT_KEY,
        {"fingerprint": dict(fp), "chunks_done": 2,
         "gram": np.eye(8), "atb": np.zeros((8, 2))},
        overwrite=True,
    )
    ck2.resume(chunk)
    assert ck2.skip == 2

    # A genuinely different PROBLEM on a different width stays on the
    # warn-and-start-fresh path (no typed refusal).
    ck3 = _StreamCheckpointer(str(tmp_path), checkpoint_every=1)
    other = dict(fp, device_count=1, d=99)
    ck3.store.put(
        _STREAM_CKPT_KEY,
        {"fingerprint": other, "chunks_done": 2,
         "gram": np.eye(8), "atb": np.zeros((8, 2))},
        overwrite=True,
    )
    ck3.resume(chunk)
    assert ck3.skip == 0  # fresh start

    # A PRE-MANIFEST snapshot (no mesh keys) of the same problem still
    # RESUMES after the manifest upgrade: absent keys are wildcards, so
    # the upgrade never silently throws away accumulated progress.
    ck4 = _StreamCheckpointer(str(tmp_path), checkpoint_every=1)
    legacy = {k: v for k, v in fp.items()
              if k not in ("device_count", "data_axis")}
    ck4.store.put(
        _STREAM_CKPT_KEY,
        {"fingerprint": legacy, "chunks_done": 3,
         "gram": np.eye(8), "atb": np.zeros((8, 2))},
        overwrite=True,
    )
    ck4.resume(chunk)
    assert ck4.skip == 3  # legacy resume preserved


def test_bcd_checkpoint_mesh_width_refusal_both_ways(monkeypatch):
    monkeypatch.setattr(config, "elastic_mesh", False)
    from keystone_tpu.linalg.bcd import _refuse_bcd_mesh_mismatch

    fp = {
        "rows": 520, "n": 518, "d": 64, "k": 4, "block_size": 64,
        "lam": 0.001, "weighted": False, "a_dtype": "float32",
        "a_probe": 1.5, "b_probe": 2.5,
        "device_count": 8, "data_axis": "data",
    }
    narrow = dict(fp, device_count=1, rows=518)
    with pytest.raises(MeshMismatchError):
        _refuse_bcd_mesh_mismatch(narrow, fp, "/tmp/ck")
    # Same width: no refusal. Different problem: no refusal (fresh path).
    _refuse_bcd_mesh_mismatch(dict(fp), fp, "/tmp/ck")
    _refuse_bcd_mesh_mismatch(
        dict(narrow, d=128), fp, "/tmp/ck"
    )
    # Pre-manifest snapshots (no mesh claim) never refuse.
    legacy = {k: v for k, v in narrow.items()
              if k not in ("device_count", "data_axis")}
    _refuse_bcd_mesh_mismatch(legacy, fp, "/tmp/ck")


def test_bcd_legacy_fingerprint_still_matches():
    """A pre-manifest BCD fingerprint of the same problem (no mesh keys)
    must still MATCH after the upgrade — mesh_fp_compat backfills the
    absent keys as wildcards, so an epoch checkpoint recorded by the
    previous release resumes instead of silently restarting."""
    from keystone_tpu.linalg.bcd import _fingerprint_matches
    from keystone_tpu.utils.mesh import mesh_fp_compat

    fp = {
        "rows": 520, "n": 518, "d": 64, "k": 4, "block_size": 64,
        "lam": 0.001, "weighted": False, "a_dtype": "float32",
        "a_probe": 1.5, "b_probe": 2.5,
        "device_count": 8, "data_axis": "data",
    }
    legacy = {k: v for k, v in fp.items()
              if k not in ("device_count", "data_axis")}
    assert not _fingerprint_matches(legacy, fp)  # raw: key-set mismatch
    assert _fingerprint_matches(mesh_fp_compat(legacy, fp), fp)
    # Present keys keep their saved values: a REAL width mismatch stays
    # a mismatch after compat.
    narrow = dict(fp, device_count=1)
    assert not _fingerprint_matches(mesh_fp_compat(narrow, fp), fp)


def test_profile_store_device_count_refused_both_ways(tmp_path, monkeypatch):
    monkeypatch.setattr(config, "elastic_mesh", False)
    from keystone_tpu.workflow.profile_store import (
        ProfileFingerprintError,
        load_profile,
        save_profile,
    )

    digest = "d" * 40
    digests = {"abc": {"label": "X", "calls": 1, "wall_ns": 10,
                       "out_bytes": 4, "out_rows": 1,
                       "queue_wait_ns": 0, "out_shape": [1, 1],
                       "data_shards": 1}}
    save_profile(
        digest, digests, [], store_dir=str(tmp_path),
        fingerprint={"backend": "cpu", "device_kind": "cpu",
                     "device_count": 1},
    )
    # A 1-device profile must never size an 8-device plan: refused.
    with pytest.raises(ProfileFingerprintError):
        load_profile(
            digest, store_dir=str(tmp_path),
            fingerprint={"backend": "cpu", "device_kind": "cpu",
                         "device_count": 8},
        )
    # The matching width loads (and carries the shard count per row).
    entry = load_profile(
        digest, store_dir=str(tmp_path),
        fingerprint={"backend": "cpu", "device_kind": "cpu",
                     "device_count": 1},
    )
    assert entry is not None
    assert entry.node("abc")["data_shards"] == 1


def test_profile_rows_carry_data_shards():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(512, 32)).astype(np.float32)
    y = rng.normal(size=(512, 4)).astype(np.float32)
    config.shard_data_batches = True
    PipelineEnv.reset()
    fitted = _two_branch_pipeline(X, y).fit(profile=True)
    rows = fitted.fit_profile.rows
    sharded = [r for r in rows if r.get("data_shards") == 8]
    assert sharded, f"no 8-shard rows in {[r['node'] for r in rows]}"


def test_plan_chunk_rows_prices_per_shard():
    """The planner sizes solver chunks against per-device HBM ÷ shard
    count: on the 8-shard mesh the planned rows are 8x the 1-shard
    sizing for the same measured bytes/row."""
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
    from keystone_tpu.utils.metrics import device_hbm_bytes
    from keystone_tpu.workflow.rules import PlanResourcesRule

    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, 8)).astype(np.float32)
    y = rng.normal(size=(64, 2)).astype(np.float32)
    pipe = BlockLeastSquaresEstimator(block_size=8).with_data(X, y)
    graph, sink = pipe.graph, pipe.sink

    budget = device_hbm_bytes() // PlanResourcesRule.CHUNK_BUDGET_FRAC
    bytes_per_row = float(budget)  # 1 row/shard-free budget: forces a plan
    measured_rows = 10**9

    class FakeMeasured:
        def node(self, digest):
            return {"out_rows": measured_rows,
                    "out_bytes": int(bytes_per_row * measured_rows)}

    plan: dict = {}
    PlanResourcesRule()._plan_chunk_rows(
        graph, [sink], FakeMeasured(), plan
    )
    shards = num_data_shards()
    assert shards == 8
    expected = int(budget // max(1.0, bytes_per_row / shards))
    assert plan["solve_chunk_rows"] == expected
    assert expected == shards  # budget == bytes_per_row → shards rows


# ---------------------------------------------------------------------------
# KG103: the silent-cliff class at lint time
# ---------------------------------------------------------------------------


def test_kg103_flags_never_divisible_batch():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(130, 32)).astype(np.float32)
    y = rng.normal(size=(130, 4)).astype(np.float32)
    pipe = _two_branch_pipeline(X, y)
    report = pipe.lint()
    hits = report.by_rule("KG103")
    assert hits and all(d.severity == "warning" for d in hits)
    assert "130 rows" in hits[0].message


def test_kg103_ignores_estimator_only_datasets():
    """Labels/side inputs consumed solely by estimators never go through
    the fused-chain pad path (RowMatrix re-pads them once regardless), so
    KG103 must not fire on them — only the feature batch warns."""
    rng = np.random.default_rng(0)
    X = rng.normal(size=(128, 32)).astype(np.float32)  # divisible
    y = rng.normal(size=(130, 4)).astype(np.float32)   # never divides
    hits = _two_branch_pipeline(X, y[:128]).lint().by_rule("KG103")
    assert not hits  # divisible X, aligned labels: clean
    X_odd = rng.normal(size=(130, 32)).astype(np.float32)
    hits = _two_branch_pipeline(X_odd, y).lint().by_rule("KG103")
    # Only the FEATURE dataset (feeding the jittable branches) fires;
    # the labels dataset (estimator-only consumer) stays silent.
    assert len(hits) == 1


def test_kg103_sees_through_host_stages():
    """A non-divisible batch whose jittable chain sits BEHIND a
    row-preserving host stage still pays the pad on every chain call —
    the traversal must reach through the host node and flag it."""

    class HostPass(Transformer):
        jittable = False

        def signature(self):
            return self.stable_signature()

        def apply_batch(self, X):
            return np.asarray(X) * 1.0

    rng = np.random.default_rng(0)
    X = rng.normal(size=(130, 32)).astype(np.float32)
    y = rng.normal(size=(130, 4)).astype(np.float32)
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator

    pipe = HostPass().and_then(MatmulChain(9)).and_then(
        BlockLeastSquaresEstimator(block_size=48, num_iters=1, lam=1e-3),
        X, y,
    )
    assert pipe.lint().by_rule("KG103")


def test_kg103_silent_on_divisible_and_small_batches():
    rng = np.random.default_rng(0)
    for rows in (128, 16):  # divisible; below shard_min_rows
        X = rng.normal(size=(rows, 32)).astype(np.float32)
        y = rng.normal(size=(rows, 4)).astype(np.float32)
        assert not _two_branch_pipeline(X, y).lint().by_rule("KG103")


def test_kg103_in_catalog():
    from keystone_tpu.workflow.analysis import GRAPH_RULES

    assert "KG103" in GRAPH_RULES


# ---------------------------------------------------------------------------
# bench_watch: the fit_multichip family
# ---------------------------------------------------------------------------


def _multichip_row(value, bit_identical=True, rows_per_s=4000.0):
    return {
        "metric": "fit_multichip",
        "value": value,
        "unit": "x rows_per_s scaling (8-device sharded fit / "
                "1-device sharded fit)",
        "backend": "cpu",
        "host_cores": 1,
        "n_devices": 8,
        "detail": {
            "rows_per_s_ndev": rows_per_s,
            "bit_identical": bit_identical,
            "shard_fallbacks": 0,
        },
        "ok": True,
    }


def _bench_watch_run(tmp_path, rows):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_watch_under_test",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "bench_watch.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    with open(tmp_path / "BENCH_fit.json", "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    return mod.run(str(tmp_path))


def test_bench_watch_judges_fit_multichip(tmp_path):
    # Healthy history then a collapse: scaling (value) and rows/s down,
    # bit_identical flipped — all three must be flagged.
    rows = [
        _multichip_row(4.0), _multichip_row(4.2), _multichip_row(3.9),
        _multichip_row(1.0, bit_identical=False, rows_per_s=900.0),
    ]
    result = _bench_watch_run(tmp_path, rows)
    bad = {v["series"] for v in result["regressions"]}
    assert "fit:fit_multichip:value" in bad
    assert "fit:fit_multichip:detail.rows_per_s_ndev" in bad
    assert "fit:fit_multichip:detail.bit_identical" in bad
    assert not result["ok"]


def test_bench_watch_passes_healthy_fit_multichip(tmp_path):
    rows = [_multichip_row(4.0), _multichip_row(4.2), _multichip_row(4.1)]
    result = _bench_watch_run(tmp_path, rows)
    assert result["ok"], result["regressions"]


@pytest.mark.slow
def test_bench_multichip_quick_green():
    """The bench harness end-to-end (two subprocesses, quick scale)."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "bench_multichip.py"),
         "--quick"],
        cwd=repo, capture_output=True, text=True, timeout=560,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    assert row["ok"] and row["detail"]["bit_identical"]
    assert row["detail"]["shard_fallbacks"] == 0
    assert row["detail"]["batches_padded"] > 0  # the pad path exercised
