"""The tpu-checkride harness must stay runnable while the chip is dead:
every step executes on the CPU fallback, results persist per step, and a
re-run resumes instead of repeating work (VERDICT r2 next-round #1)."""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CKR = os.path.join(REPO, "tools", "checkride.py")


def _run(tmp_path, steps, timeout=420):
    return subprocess.run(
        [
            sys.executable,
            CKR,
            "--quick",
            "--state-dir",
            str(tmp_path / "state"),
            "--report",
            str(tmp_path / "report.json"),
            "--probe-timeout",
            "3",  # the orchestrator itself must not wait on a dead chip
            "--steps",
            *steps,
        ],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
    )


@pytest.mark.slow
def test_checkride_cpu_dryrun_and_resume(tmp_path):
    steps = ["streamed_overlap", "memory_stats", "featurize",
             "factor_primitives", "ring_vs_dp", "acceptance_synthetic"]
    proc = _run(tmp_path, steps)
    assert proc.returncode == 0, proc.stderr[-2000:]
    report = json.loads((tmp_path / "report.json").read_text())
    for s in steps:
        assert report["steps"][s]["ok"], report["steps"][s]
        assert report["steps"][s]["backend"] == "cpu"
    assert report["complete_on_tpu"] is False  # honesty: CPU is not evidence
    # Per-step state persisted the moment each step finished.
    for s in steps:
        assert (tmp_path / "state" / f"step_{s}.json").exists()

    # Resume: every step skips (stderr says so, and it's fast because no
    # subprocess backend init happens for skipped steps).
    proc2 = _run(tmp_path, steps, timeout=120)
    assert proc2.returncode == 0, proc2.stderr[-2000:]
    assert proc2.stderr.count("skip") == len(steps)

    # Deleting one step's state re-runs exactly that step.
    (tmp_path / "state" / "step_memory_stats.json").unlink()
    proc3 = _run(tmp_path, steps)
    assert proc3.returncode == 0, proc3.stderr[-2000:]
    assert "skip streamed_overlap" in proc3.stderr
    assert "run memory_stats" in proc3.stderr


@pytest.mark.slow
def test_checkride_step_failure_is_recorded_not_fatal(tmp_path):
    """A failing step writes an ok=false record, the ride continues to the
    next step, and the exit code reports the failure."""
    env = dict(os.environ)
    env["KEYSTONE_CHECKRIDE_FAIL_STEP"] = "streamed_overlap"
    proc = subprocess.run(
        [
            sys.executable,
            CKR,
            "--quick",
            "--state-dir",
            str(tmp_path / "fstate"),
            "--report",
            str(tmp_path / "freport.json"),
            "--probe-timeout",
            "3",
            "--steps",
            "streamed_overlap",
            "memory_stats",
        ],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 1  # failure surfaced
    report = json.loads((tmp_path / "freport.json").read_text())
    assert report["steps"]["streamed_overlap"]["ok"] is False
    assert report["steps"]["memory_stats"]["ok"] is True  # ride continued


@pytest.mark.slow
def test_checkride_keeps_tpu_ok_priors(tmp_path):
    """A tpu-ok prior is never downgraded by a CPU re-run."""
    state = tmp_path / "state"
    state.mkdir(parents=True)
    # Pre-plant a bogus prior for one step with backend "tpu": the target
    # here is cpu, so a tpu-ok prior must be KEPT (never downgraded).
    (state / "step_streamed_overlap.json").write_text(
        json.dumps({"ok": True, "backend": "tpu", "step": "streamed_overlap"})
    )
    proc = _run(tmp_path, ["streamed_overlap"])
    assert proc.returncode == 0
    assert "skip streamed_overlap (done on tpu)" in proc.stderr
    report = json.loads((tmp_path / "report.json").read_text())
    assert report["steps"]["streamed_overlap"]["backend"] == "tpu"
    assert report["tpu_evidence_steps"] == ["streamed_overlap"]


@pytest.mark.slow
def test_quick_scale_prior_satisfies_quick_but_not_full_evidence(tmp_path):
    """A --quick TPU result must satisfy a quick re-run, never count as
    full-scale TPU evidence in the report, and never block a full ride."""
    checkride = _sweep_module()
    state = tmp_path / "state"
    state.mkdir()
    (state / "step_streamed_overlap.json").write_text(
        json.dumps({"ok": True, "backend": "tpu", "quick_scale": True,
                    "step": "streamed_overlap"})
    )
    report_path = tmp_path / "report.json"
    checkride._write_report(str(state), str(report_path), {})
    report = json.loads(report_path.read_text())
    assert report["tpu_evidence_steps"] == []  # toy scale is not evidence
    assert report["complete_on_tpu"] is False

    proc = _run(tmp_path, ["streamed_overlap"])  # --quick run: skip is fine
    assert proc.returncode == 0
    assert "skip streamed_overlap (done on tpu)" in proc.stderr

    # The central claim: a FULL (non --quick) ride must NOT be blocked by
    # the toy-scale prior — it re-runs the step at full scale.
    proc_full = subprocess.run(
        [
            sys.executable, CKR,
            "--state-dir", str(state),
            "--report", str(report_path),
            "--probe-timeout", "3",
            "--steps", "streamed_overlap",
        ],
        capture_output=True, text=True, timeout=420, cwd=REPO,
    )
    assert proc_full.returncode == 0, proc_full.stderr[-2000:]
    assert "run streamed_overlap" in proc_full.stderr
    saved = json.loads((state / "step_streamed_overlap.json").read_text())
    assert not saved.get("quick_scale")


def _sweep_module():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import importlib

    import checkride

    return importlib.reload(checkride)


def test_bench_serves_checkride_checkpoint_only_when_config_matches(
    tmp_path, monkeypatch
):
    """bench.py's dead-chip fallback may serve a checkpointed live-chip
    line ONLY for the current config: stale scales, quick-scale toys, and
    CPU-tagged records must all be rejected (they would fake a round
    number)."""
    sys.path.insert(0, REPO)
    import importlib

    import bench

    bench = importlib.reload(bench)
    monkeypatch.setattr(bench, "REPO_DIR", str(tmp_path))
    state = tmp_path / ".checkride"
    state.mkdir()
    cfg = bench.SCALE["tpu"]
    good_line = {
        "metric": "bcd_solver_tflops_per_chip",
        "value": 7.0,
        "detail": {"n": cfg["n"], "d": cfg["d"], "k": cfg["k"],
                   "block": cfg["block"], "epochs": cfg["iters"],
                   "dtype": "f32", "solver_rev": bench.SOLVER_REV},
    }
    rec = {"ok": True, "backend": "tpu", "bench_line": good_line,
           "saved_at": bench.time.time()}
    p = state / "step_bench_f32.json"

    p.write_text(json.dumps(rec))
    out = bench._checkride_checkpoint("tpu", "f32")
    assert out is not None and out["source"] == "checkride_checkpoint"
    assert out["value"] == 7.0 and "measured_at" in out

    # Wrong dtype request → no serve.
    assert bench._checkride_checkpoint("tpu", "bf16") is None
    # Stale config (block from an older scale definition) → no serve.
    stale = json.loads(json.dumps(rec))
    stale["bench_line"]["detail"]["block"] = cfg["block"] // 2
    p.write_text(json.dumps(stale))
    assert bench._checkride_checkpoint("tpu", "f32") is None
    # Toy-scale (--quick) record → no serve.
    quick = json.loads(json.dumps(rec))
    quick["quick_scale"] = True
    p.write_text(json.dumps(quick))
    assert bench._checkride_checkpoint("tpu", "f32") is None
    # CPU-tagged record → no serve.
    cpu = json.loads(json.dumps(rec))
    cpu["backend"] = "cpu"
    p.write_text(json.dumps(cpu))
    assert bench._checkride_checkpoint("tpu", "f32") is None
    # Retired solver revision (measured code this round no longer ships)
    # → no serve.
    oldrev = json.loads(json.dumps(rec))
    oldrev["bench_line"]["detail"]["solver_rev"] = "r0-retired"
    p.write_text(json.dumps(oldrev))
    assert bench._checkride_checkpoint("tpu", "f32") is None
    p.write_text(json.dumps(rec))
    # Different epoch count (FLOP split changes) → no serve.
    ep = json.loads(json.dumps(rec))
    ep["bench_line"]["detail"]["epochs"] = cfg["iters"] + 1
    p.write_text(json.dumps(ep))
    assert bench._checkride_checkpoint("tpu", "f32") is None
    # Previous-round checkpoint (too old) → no serve. The stamp lives IN
    # the record: mtime is checkout time on a fresh clone and is ignored.
    aged = json.loads(json.dumps(rec))
    aged["saved_at"] = bench.time.time() - 48 * 3600
    p.write_text(json.dumps(aged))
    assert bench._checkride_checkpoint("tpu", "f32") is None
    # Unstamped legacy record (mtime would look fresh) → no serve.
    unstamped = json.loads(json.dumps(rec))
    del unstamped["saved_at"]
    p.write_text(json.dumps(unstamped))
    assert bench._checkride_checkpoint("tpu", "f32") is None
    # Malformed state (JSON array) degrades silently, never raises.
    p.write_text("[1, 2, 3]")
    assert bench._checkride_checkpoint("tpu", "f32") is None
    p.write_text(json.dumps({"ok": True, "backend": "tpu",
                             "bench_line": {"detail": None}}))
    assert bench._checkride_checkpoint("tpu", "f32") is None
    # Transport lie (suspect_timing on the stored line) → no serve, even
    # under a legacy ok=True record saved before checkride rejected them.
    suspect = json.loads(json.dumps(rec))
    suspect["bench_line"]["suspect_timing"] = True
    p.write_text(json.dumps(suspect))
    assert bench._checkride_checkpoint("tpu", "f32") is None


def test_suspect_timing_rejected_at_capture(monkeypatch):
    """checkride must refuse to record a worker line measured above
    plausible peak: run_bench_step marks the step failed (so resume
    re-measures and the report excludes it) and run_mfu_sweep records an
    error row instead of letting the lie win the 'best' pick."""
    checkride = _sweep_module()
    import bench

    lie = {
        "metric": "bcd_solver_tflops_per_chip",
        "value": 400.0,
        "backend": "tpu",
        "suspect_timing": True,
        "detail": {"block": 4096, "seconds_per_solve": 0.01},
    }
    monkeypatch.setattr(
        bench, "_run_worker", lambda env, scale, dtype, timeout: dict(lie)
    )
    rec = checkride.run_bench_step("bench_f32", "tpu", False, 10.0)
    assert rec["ok"] is False and "suspect_timing" in rec["error"]


def test_suspect_timing_sweep_rows_become_error_rows(tmp_path, monkeypatch):
    checkride = _sweep_module()
    import bench

    lie = {
        "value": 400.0,
        "backend": "tpu",
        "suspect_timing": True,
        "detail": {"block": 4096, "seconds_per_solve": 0.01},
    }
    monkeypatch.setattr(
        bench, "_run_worker", lambda env, scale, dtype, timeout: dict(lie)
    )
    state = tmp_path / "state"
    state.mkdir()
    rec = checkride.run_mfu_sweep("mfu_sweep", "tpu", False, 10.0, str(state))
    assert rec["ok"] is False  # no clean rows survived
    assert all(r.get("error") == "suspect_timing" for r in rec["rows"])
    assert rec["best"] is None  # the lie never wins the best pick


def test_mid_sweep_tpu_death_sets_degrade_flag(tmp_path, monkeypatch):
    """A chip death mid-sweep with completed rows returns ok=True (the rows
    are evidence) but must carry tpu_dead so the orchestrator degrades the
    remaining ride instead of burning a full timeout per step."""
    checkride = _sweep_module()
    import bench

    monkeypatch.setattr(checkride, "_probe", lambda t: {"live": False})
    monkeypatch.setattr(bench, "_run_worker", lambda env, scale, dtype, timeout: None)

    r = checkride.run_mfu_sweep("mfu_sweep", "tpu", True, 5.0, str(tmp_path))
    assert r.get("tpu_dead") is True
    assert r["ok"] is False  # no completed rows

    rows = [
        {
            "block": 64,
            "dtype": "f32",
            "tflops_per_chip": 7.5,
            "mfu_vs_plausible_peak": 0.4,
            "seconds_per_solve": 0.01,
        }
    ]
    seeded = tmp_path / "seeded"
    seeded.mkdir()
    (seeded / "step_mfu_sweep.json").write_text(
        json.dumps(
            {
                "ok": True,
                "backend": "tpu",
                "scale": "quick",
                "solver_rev": bench.SOLVER_REV,
                "rows": rows,
                "partial": True,
                "step": "mfu_sweep",
            }
        )
    )
    r2 = checkride.run_mfu_sweep("mfu_sweep", "tpu", True, 5.0, str(seeded))
    assert r2.get("tpu_dead") is True
    assert r2["ok"] is True  # the checkpointed row survives as evidence
    assert [row for row in r2["rows"] if "error" not in row] == rows
    # The orchestrator's degrade condition must fire in BOTH cases.
    assert not r["ok"] or r.get("tpu_dead")
    assert not r2["ok"] or r2.get("tpu_dead")


def test_precision_recommendation_from_tpu_sweep(tmp_path):
    """The report self-interprets f32h-vs-f32 sweep evidence: recommend
    'high' only on ≥1.3× speedup at ≤2× residual, at the largest shared
    block, and only from TPU rows."""
    checkride = _sweep_module()
    rows = [
        {"block": 8192, "dtype": "f32", "tflops_per_chip": 10.0,
         "relative_residual": 0.07},
        {"block": 8192, "dtype": "f32h", "tflops_per_chip": 19.0,
         "relative_residual": 0.09},
        {"block": 4096, "dtype": "f32h", "tflops_per_chip": 12.0,
         "relative_residual": 0.09},
        {"block": 2048, "dtype": "f32", "error": "failed"},
    ]
    import bench

    rp = str(tmp_path / "r.json")

    def seed(**over):
        state = {"ok": True, "backend": "tpu",
                 "solver_rev": bench.SOLVER_REV, "rows": rows}
        state.update(over)
        checkride._save_state(str(tmp_path), "mfu_sweep", state)
        checkride._write_report(str(tmp_path), rp, {})
        return json.loads(open(rp).read())

    rec = seed()["precision_recommendation"]
    assert rec["recommend"] == "high" and rec["block"] == 8192
    assert rec["speedup"] == 1.9
    # Residual blowup flips the call back to highest.
    rows[1]["relative_residual"] = 0.5
    assert seed()["precision_recommendation"]["recommend"] == "highest"
    # Missing residual = no accuracy evidence: never flip blind.
    rows[1]["relative_residual"] = None
    rec = seed()["precision_recommendation"]
    assert rec["recommend"] == "highest" and "missing" in rec["reason"]
    rows[1]["relative_residual"] = 0.09
    # Provenance gates: CPU, retired-rev, quick, and partial sweeps carry
    # no recommendation (same rules as tpu_evidence_steps).
    assert "precision_recommendation" not in seed(backend="cpu")
    assert "precision_recommendation" not in seed(solver_rev="r0-retired")
    assert "precision_recommendation" not in seed(quick_scale=True)
    assert "precision_recommendation" not in seed(partial=True)


def test_cpu_rerun_preserves_partial_tpu_sweep_rows(tmp_path):
    """A partial TPU sweep checkpoint must never be overwritten by a
    CPU-degraded re-run — partial live-chip evidence is the harness's
    whole purpose."""
    checkride = _sweep_module()
    import bench

    rows = [
        {
            "block": 64,
            "dtype": "f32",
            "tflops_per_chip": 7.5,
            "mfu_vs_plausible_peak": 0.4,
            "seconds_per_solve": 0.01,
        }
    ]
    (tmp_path / "step_mfu_sweep.json").write_text(
        json.dumps(
            {
                "ok": True,
                "backend": "tpu",
                "scale": "quick",
                "solver_rev": bench.SOLVER_REV,
                "rows": rows,
                "partial": True,
                "step": "mfu_sweep",
            }
        )
    )
    r = checkride.run_mfu_sweep("mfu_sweep", "cpu", True, 5.0, str(tmp_path))
    assert r.get("preserved_tpu_rows") is True
    assert r["backend"] == "tpu" and r["rows"] == rows
    # State on disk untouched (still the TPU rows).
    saved = json.loads((tmp_path / "step_mfu_sweep.json").read_text())
    assert saved["backend"] == "tpu" and saved["rows"] == rows
