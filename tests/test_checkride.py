"""The tpu-checkride harness must stay runnable while the chip is dead:
every step executes on the CPU fallback, results persist per step, and a
re-run resumes instead of repeating work (VERDICT r2 next-round #1)."""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CKR = os.path.join(REPO, "tools", "checkride.py")


def _run(tmp_path, steps, timeout=420):
    return subprocess.run(
        [
            sys.executable,
            CKR,
            "--quick",
            "--state-dir",
            str(tmp_path / "state"),
            "--report",
            str(tmp_path / "report.json"),
            "--probe-timeout",
            "3",  # the orchestrator itself must not wait on a dead chip
            "--steps",
            *steps,
        ],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
    )


@pytest.mark.slow
def test_checkride_cpu_dryrun_and_resume(tmp_path):
    steps = ["streamed_overlap", "memory_stats"]
    proc = _run(tmp_path, steps)
    assert proc.returncode == 0, proc.stderr[-2000:]
    report = json.loads((tmp_path / "report.json").read_text())
    for s in steps:
        assert report["steps"][s]["ok"], report["steps"][s]
        assert report["steps"][s]["backend"] == "cpu"
    assert report["complete_on_tpu"] is False  # honesty: CPU is not evidence
    # Per-step state persisted the moment each step finished.
    for s in steps:
        assert (tmp_path / "state" / f"step_{s}.json").exists()

    # Resume: both steps skip (stderr says so, and it's fast because no
    # subprocess backend init happens for skipped steps).
    proc2 = _run(tmp_path, steps, timeout=120)
    assert proc2.returncode == 0, proc2.stderr[-2000:]
    assert proc2.stderr.count("skip") == len(steps)

    # Deleting one step's state re-runs exactly that step.
    (tmp_path / "state" / "step_memory_stats.json").unlink()
    proc3 = _run(tmp_path, steps)
    assert proc3.returncode == 0, proc3.stderr[-2000:]
    assert "skip streamed_overlap" in proc3.stderr
    assert "run memory_stats" in proc3.stderr


@pytest.mark.slow
def test_checkride_step_failure_is_recorded_not_fatal(tmp_path):
    """A failing step writes an ok=false record, the ride continues to the
    next step, and the exit code reports the failure."""
    env = dict(os.environ)
    env["KEYSTONE_CHECKRIDE_FAIL_STEP"] = "streamed_overlap"
    proc = subprocess.run(
        [
            sys.executable,
            CKR,
            "--quick",
            "--state-dir",
            str(tmp_path / "fstate"),
            "--report",
            str(tmp_path / "freport.json"),
            "--probe-timeout",
            "3",
            "--steps",
            "streamed_overlap",
            "memory_stats",
        ],
        capture_output=True,
        text=True,
        timeout=420,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 1  # failure surfaced
    report = json.loads((tmp_path / "freport.json").read_text())
    assert report["steps"]["streamed_overlap"]["ok"] is False
    assert report["steps"]["memory_stats"]["ok"] is True  # ride continued


@pytest.mark.slow
def test_checkride_keeps_tpu_ok_priors(tmp_path):
    """A tpu-ok prior is never downgraded by a CPU re-run."""
    state = tmp_path / "state"
    state.mkdir(parents=True)
    # Pre-plant a bogus prior for one step with backend "tpu": the target
    # here is cpu, so a tpu-ok prior must be KEPT (never downgraded).
    (state / "step_streamed_overlap.json").write_text(
        json.dumps({"ok": True, "backend": "tpu", "step": "streamed_overlap"})
    )
    proc = _run(tmp_path, ["streamed_overlap"])
    assert proc.returncode == 0
    assert "skip streamed_overlap (done on tpu)" in proc.stderr
    report = json.loads((tmp_path / "report.json").read_text())
    assert report["steps"]["streamed_overlap"]["backend"] == "tpu"
    assert report["tpu_evidence_steps"] == ["streamed_overlap"]
