"""CsvDataLoader + LabeledData — the generic CSV ingestion path
(Ref: loaders/CsvDataLoader.scala, loaders/LabeledData.scala [unverified])."""

import numpy as np

from keystone_tpu.loaders import CsvDataLoader, LabeledData


def test_load_plain_matrix(tmp_path):
    p = tmp_path / "m.csv"
    p.write_text("1.0,2.0,3.5\n4.0,5.0,6.5\n")
    out = CsvDataLoader.load(str(p))
    assert out.dtype == np.float32
    np.testing.assert_allclose(out, [[1.0, 2.0, 3.5], [4.0, 5.0, 6.5]])


def test_load_labeled_first_column(tmp_path):
    p = tmp_path / "l.csv"
    p.write_text("3,0.5,0.25\n7,1.5,2.5\n")
    got = CsvDataLoader.load_labeled(str(p))
    np.testing.assert_array_equal(got.labels, [3, 7])
    assert got.labels.dtype == np.int32
    np.testing.assert_allclose(got.data, [[0.5, 0.25], [1.5, 2.5]])


def test_load_labeled_other_column(tmp_path):
    p = tmp_path / "l.csv"
    p.write_text("0.5,9,0.25\n1.5,2,2.5\n")
    got = CsvDataLoader.load_labeled(str(p), label_col=1)
    np.testing.assert_array_equal(got.labels, [9, 2])
    np.testing.assert_allclose(got.data, [[0.5, 0.25], [1.5, 2.5]])


def test_labeled_data_unpacks():
    X, y = LabeledData(np.zeros((3, 2)), np.ones(3))
    assert X.shape == (3, 2) and y.shape == (3,)
