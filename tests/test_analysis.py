"""Graph-linter tests (workflow/analysis.py, Layer 1 of keystone-lint).

Every shipped KG rule is pinned both ways: one fixture that must flag it
and one that must stay clean. The canonical fused serving chains (the
test_serving.py head) must lint clean; a RandomPatcher chain must flag
serveability; and the KEYSTONE_LINT gate must refuse at compiled() in
error mode, log-only in warn mode, and stay silent when off.
"""

import logging

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.config import config
from keystone_tpu.nodes.images.patches import RandomPatcher
from keystone_tpu.nodes.learning.linear_mapper import LinearMapper
from keystone_tpu.nodes.stats.hellinger import SignedHellingerMapper
from keystone_tpu.nodes.stats.normalizer import L2Normalizer
from keystone_tpu.nodes.stats.random_features import CosineRandomFeatures
from keystone_tpu.nodes.stats.scalers import StandardScalerModel
from keystone_tpu.workflow import LintError, Pipeline, Transformer
from keystone_tpu.workflow.analysis import GRAPH_RULES, lint_graph
from keystone_tpu.workflow.graph import Graph, fresh_source_id
from keystone_tpu.workflow.operators import GatherOperator, TransformerOperator


@pytest.fixture(autouse=True)
def lint_off():
    """Isolate the process-wide lint/serve knobs per test."""
    prior = (config.lint, config.serve_buckets)
    config.lint = "off"
    yield
    config.lint, config.serve_buckets = prior


def _fused_head(d=8, D=16, k=3, seed=0):
    """The canonical fused serving head from tests/test_serving.py, built
    as a pipeline — the chain the serving engine actually compiles."""
    rng = np.random.default_rng(seed)
    return (
        StandardScalerModel(
            rng.normal(size=d).astype(np.float32),
            (1.0 + rng.uniform(size=d)).astype(np.float32),
        ).to_pipeline()
        .and_then(CosineRandomFeatures.create(d, D, seed=seed))
        .and_then(SignedHellingerMapper())
        .and_then(L2Normalizer())
        .and_then(LinearMapper(rng.normal(size=(D, k)).astype(np.float32)))
    )


class Identity(Transformer):
    def apply_batch(self, X):
        return X


class CastF32(Transformer):
    def apply_batch(self, X):
        return X.astype(jnp.float32)


class HostOnly(Transformer):
    jittable = False

    def apply_batch(self, X):
        return X


# ---------------------------------------------------------------------------
# Serveability rules: KG001 / KG002 / KG003
# ---------------------------------------------------------------------------


def test_canonical_fused_serving_chain_lints_clean():
    report = _fused_head().lint(example=(8,), serve=True, have_ladder=True)
    assert not report.errors()
    for rule in ("KG001", "KG002", "KG003"):
        assert not report.by_rule(rule), report.render()


def test_random_patcher_chain_flags_serveability_as_errors():
    bad = RandomPatcher(4, 3).and_then(L2Normalizer())
    report = bad.lint(serve=True, have_ladder=True)
    rules = {d.rule for d in report.errors()}
    assert "KG001" in rules  # not jittable
    assert "KG002" in rules  # row-coupled
    # every serveability diagnostic names the offending node
    assert all("RandomPatcher" in d.node for d in report.errors())


def test_serveability_is_warning_without_serve_intent():
    bad = RandomPatcher(4, 3).and_then(L2Normalizer())
    report = bad.lint(serve=False, have_ladder=True)
    assert not report.errors()
    assert {d.rule for d in report.warnings()} >= {"KG001", "KG002"}


def test_host_transformer_flags_kg001_only():
    report = HostOnly().and_then(L2Normalizer()).lint(
        serve=True, have_ladder=True
    )
    rules = {d.rule for d in report.errors()}
    assert rules == {"KG001"}


def test_gather_flags_kg003_linear_chain_clean():
    gathered = Pipeline.gather([L2Normalizer(), Identity()])
    report = gathered.lint(serve=True, have_ladder=True)
    assert {d.rule for d in report.errors()} == {"KG003"}
    linear = L2Normalizer().and_then(Identity())
    clean = linear.lint(serve=True, have_ladder=True)
    assert not clean.by_rule("KG003")


# ---------------------------------------------------------------------------
# KG101 recompile hazard
# ---------------------------------------------------------------------------


def test_kg101_polymorphic_without_ladder_flags():
    p = L2Normalizer().and_then(Identity())
    report = p.lint()  # no example: polymorphic traffic, no ladder
    assert report.by_rule("KG101")
    assert report.by_rule("KG101")[0].severity == "warning"


def test_kg101_suppressed_by_ladder_or_concrete_batch():
    p = L2Normalizer().and_then(Identity())
    assert not p.lint(have_ladder=True).by_rule("KG101")
    # a concrete sample batch is not polymorphic traffic
    assert not p.lint(
        example=np.zeros((4, 8), np.float32)
    ).by_rule("KG101")
    # config.serve_buckets counts as a ladder
    config.serve_buckets = (8, 64)
    assert not p.lint().by_rule("KG101")


# ---------------------------------------------------------------------------
# KG102 dtype seams (abstract shape/dtype propagation)
# ---------------------------------------------------------------------------


def test_kg102_silent_upcast_flagged_with_node_and_dtypes():
    p = CastF32().and_then(L2Normalizer())
    report = p.lint(example=np.zeros((4, 8), np.float16), have_ladder=True)
    seams = report.by_rule("KG102")
    assert len(seams) == 1
    assert "float16" in seams[0].message and "float32" in seams[0].message
    assert "CastF32" in seams[0].node


def test_kg102_clean_on_dtype_preserving_chain():
    report = _fused_head().lint(
        example=np.zeros((4, 8), np.float32), have_ladder=True
    )
    assert not report.by_rule("KG102"), report.render()


def test_kg102_mixed_dtype_gather():
    gathered = Pipeline.gather([Identity(), CastF32()])
    report = gathered.lint(example=np.zeros((4, 8), np.float16))
    seams = report.by_rule("KG102")
    # the branch upcast itself is one seam; the mixed-dtype join another
    assert any("gather" in d.message.lower() for d in seams), report.render()


# ---------------------------------------------------------------------------
# KG201 dead nodes / KG202 cache advice
# ---------------------------------------------------------------------------


def _shared_prefix_graph(cache_after_prefix=False):
    src = fresh_source_id()
    g, prefix = Graph().add(TransformerOperator(L2Normalizer()), [src])
    tail_src = prefix
    if cache_after_prefix:
        from keystone_tpu.workflow.cache import CacheOperator

        g, tail_src = g.add(CacheOperator(), [prefix])
    g, b1 = g.add(TransformerOperator(SignedHellingerMapper()), [tail_src])
    g, b2 = g.add(TransformerOperator(Identity()), [tail_src])
    g, out = g.add(GatherOperator(), [b1, b2])
    return Pipeline(g, src, out)


def test_kg201_dead_node_flagged_and_pruned_graph_clean():
    src = fresh_source_id()
    g, live = Graph().add(TransformerOperator(L2Normalizer()), [src])
    g, _orphan = g.add(TransformerOperator(Identity()), [src])
    report = Pipeline(g, src, live).lint(example=(8,), have_ladder=True)
    assert report.by_rule("KG201")
    pruned = Pipeline(g.pruned([live]), src, live)
    assert not pruned.lint(example=(8,), have_ladder=True).by_rule("KG201")


def test_kg202_shared_subchain_advice_and_cache_satisfies_it():
    report = _shared_prefix_graph().lint(example=(8,), have_ladder=True)
    advice = report.by_rule("KG202")
    assert advice and advice[0].severity == "info"
    assert "L2Normalizer" in advice[0].node
    cached = _shared_prefix_graph(cache_after_prefix=True)
    assert not cached.lint(
        example=(8,), have_ladder=True
    ).by_rule("KG202")


# ---------------------------------------------------------------------------
# API robustness + catalog
# ---------------------------------------------------------------------------


def test_lint_never_executes_and_survives_unfitted_estimators():
    from keystone_tpu.workflow import LabelEstimator

    class Boom(LabelEstimator):
        def fit(self, X, y):  # would explode if lint executed the graph
            raise AssertionError("lint must not fit")

    X = np.zeros((4, 8), np.float32)
    y = np.zeros((4, 3), np.float32)
    p = L2Normalizer().and_then(Boom(), X, y)
    report = p.lint(example=(8,), have_ladder=True)
    assert isinstance(report.render(), str)  # completed without executing


def test_rule_catalog_covers_every_emitted_rule():
    fixtures = [
        RandomPatcher(4, 3).and_then(L2Normalizer()).lint(serve=True),
        Pipeline.gather([Identity(), CastF32()]).lint(
            example=np.zeros((4, 8), np.float16)
        ),
        L2Normalizer().and_then(Identity()).lint(),
        _shared_prefix_graph().lint(example=(8,), have_ladder=True),
    ]
    emitted = {d.rule for rep in fixtures for d in rep}
    assert emitted <= set(GRAPH_RULES)
    assert {"KG001", "KG002", "KG003", "KG101", "KG102", "KG202"} <= emitted


def test_lint_graph_matches_pipeline_lint():
    p = _fused_head()
    direct = lint_graph(p.graph, p.source, p.sink, example=(8,),
                        serve=True, have_ladder=True)
    assert direct.as_dicts() == p.lint(
        example=(8,), serve=True, have_ladder=True
    ).as_dicts()


# ---------------------------------------------------------------------------
# The KEYSTONE_LINT gate
# ---------------------------------------------------------------------------


def test_gate_error_mode_refuses_unserveable_compiled():
    config.lint = "error"
    bad = RandomPatcher(4, 3).and_then(L2Normalizer())
    with pytest.raises(LintError, match="KG00"):
        bad.compiled()


def test_gate_error_mode_passes_clean_chain():
    config.lint = "error"
    cp = _fused_head().compiled(buckets=(4, 8), devices=1)
    assert cp.ladder == (4, 8)


def test_gate_warn_mode_logs_but_never_blocks(caplog):
    config.lint = "warn"
    bad = RandomPatcher(4, 3).and_then(L2Normalizer())
    with caplog.at_level(logging.ERROR, logger="keystone_tpu"):
        with pytest.raises(Exception) as ei:
            bad.compiled()  # the RUNTIME refusal still fires downstream
    assert not isinstance(ei.value, LintError)
    assert any("KG00" in r.message for r in caplog.records)


def test_gate_off_is_silent(caplog):
    config.lint = "off"
    with caplog.at_level(logging.INFO, logger="keystone_tpu"):
        _fused_head().fit()
    assert not any("lint[" in r.message for r in caplog.records)


def test_gate_fit_runs_lint_in_warn_mode(caplog):
    config.lint = "warn"
    with caplog.at_level(logging.WARNING, logger="keystone_tpu"):
        # polymorphic + no ladder: the fit gate logs KG101 as a warning
        L2Normalizer().and_then(Identity()).fit()
    assert any("KG101" in r.message for r in caplog.records)


# ---------------------------------------------------------------------------
# The in-process demo (the `make lint` graph half, like make trace-demo)
# ---------------------------------------------------------------------------


def test_lint_report_demo_in_process():
    import importlib
    import os
    import sys

    tools = os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")
    sys.path.insert(0, tools)
    try:
        lint_report = importlib.import_module("lint_report")
        verdict = lint_report.run_graph_demo()
    finally:
        sys.path.remove(tools)
    assert verdict["canonical_clean"], verdict
    assert verdict["control_refused"], verdict
    assert "KG002" in verdict["control_rules"]


# ---------------------------------------------------------------------------
# KG104: pinned memory plan priced beyond the HBM budget (shape-only)
# ---------------------------------------------------------------------------


def test_kg104_flags_over_budget_pinned_ladder(monkeypatch):
    """A pinned serve ladder whose priced residency (ladder x replicas x
    dtype) exceeds the ladder budget share is flagged statically — no
    execution, no compile, no device work (the Boom-estimator test above
    already pins that lint never executes)."""
    config.serve_buckets = (1024,)
    monkeypatch.setattr(config, "hbm_budget_bytes", 50_000)
    hits = _fused_head().lint(example=(8,), have_ladder=True).by_rule(
        "KG104"
    )
    assert hits and hits[0].severity == "warning"
    assert "serve ladder" in hits[0].message
    assert "1024" in hits[0].message
    assert "KEYSTONE_SERVE_BUCKETS" in hits[0].hint


def test_kg104_silent_on_in_budget_plans():
    """The other way: an in-budget pinned ladder — and the unpinned
    default (no ladder configured at all) — stay silent."""
    p = _fused_head()
    assert not p.lint(example=(8,), have_ladder=True).by_rule("KG104")
    config.serve_buckets = (8, 64)  # tiny ladder, default 12 GiB budget
    assert not p.lint(example=(8,), have_ladder=True).by_rule("KG104")


def test_kg104_flags_over_budget_pinned_solve_chunk(monkeypatch):
    from keystone_tpu.nodes.learning import LinearMapEstimator

    X = np.zeros((4, 8), np.float32)
    y = np.zeros((4, 3), np.float32)
    p = L2Normalizer().and_then(LinearMapEstimator(lam=1e-3), X, y)
    monkeypatch.setattr(config, "solve_chunk_rows", 1 << 22)
    monkeypatch.setattr(config, "hbm_budget_bytes", 1 << 20)
    hits = p.lint(example=(8,), have_ladder=True).by_rule("KG104")
    assert hits and hits[0].severity == "warning"
    assert "solve chunk" in hits[0].message
    assert "OOM-halving" in hits[0].message
    # Unpinned chunk (the planner's to size): silent under any budget.
    monkeypatch.setattr(config, "solve_chunk_rows", 0)
    assert not p.lint(example=(8,), have_ladder=True).by_rule("KG104")


def test_kg104_env_pin_reads_live(monkeypatch):
    """The env-pins live-read convention: an exported
    KEYSTONE_SOLVE_CHUNK_ROWS=0 retires a programmatic pin, and an
    exported value prices instead of the config snapshot."""
    from keystone_tpu.nodes.learning import LinearMapEstimator

    X = np.zeros((4, 8), np.float32)
    y = np.zeros((4, 3), np.float32)
    p = L2Normalizer().and_then(LinearMapEstimator(lam=1e-3), X, y)
    monkeypatch.setattr(config, "solve_chunk_rows", 1 << 22)
    monkeypatch.setattr(config, "hbm_budget_bytes", 1 << 20)
    monkeypatch.setenv("KEYSTONE_SOLVE_CHUNK_ROWS", "0")
    assert not p.lint(example=(8,), have_ladder=True).by_rule("KG104")
    monkeypatch.setenv("KEYSTONE_SOLVE_CHUNK_ROWS", str(1 << 22))
    monkeypatch.setattr(config, "solve_chunk_rows", 0)
    assert p.lint(example=(8,), have_ladder=True).by_rule("KG104")


# ---------------------------------------------------------------------------
# KG105 — refit_stream head without partial_fit (ISSUE-15)
# ---------------------------------------------------------------------------


def _refit_pipeline(head):
    X = np.zeros((8, 8), np.float32)
    y = np.zeros((8, 3), np.float32)
    return L2Normalizer().and_then(head, X, y)


def test_kg105_flags_batch_only_head_under_refit():
    from keystone_tpu.workflow import LabelEstimator

    class BatchOnlyHead(LabelEstimator):
        def fit(self, X, y):
            return LinearMapper(np.zeros((8, 3), np.float32))

    hits = _refit_pipeline(BatchOnlyHead()).lint(
        example=(8,), have_ladder=True, refit=True
    ).by_rule("KG105")
    assert hits and hits[0].severity == "warning"
    assert "partial_fit" in hits[0].message
    assert "FULL head refit" in hits[0].message
    assert "BatchOnlyHead" in hits[0].node


def test_kg105_silent_on_online_head_and_without_refit():
    from keystone_tpu.nodes.learning import LinearMapEstimator
    from keystone_tpu.workflow import LabelEstimator

    # The whole normal-equation family implements the contract.
    p = _refit_pipeline(LinearMapEstimator(lam=1e-3))
    assert not p.lint(example=(8,), have_ladder=True,
                      refit=True).by_rule("KG105")

    class BatchOnlyHead(LabelEstimator):
        def fit(self, X, y):
            return LinearMapper(np.zeros((8, 3), np.float32))

    # A batch-only head is a fine BATCH pipeline: silent unless the
    # refit contract is requested.
    assert not _refit_pipeline(BatchOnlyHead()).lint(
        example=(8,), have_ladder=True
    ).by_rule("KG105")


def test_kg105_weighted_block_head_flags():
    """BlockWeighted nulls the online contract (per-batch folds cannot
    know the full class counts) — the lint must see that, not just a
    missing attribute."""
    from keystone_tpu.nodes.learning.block_least_squares import (
        BlockWeightedLeastSquaresEstimator,
    )

    hits = _refit_pipeline(
        BlockWeightedLeastSquaresEstimator(lam=1e-3)
    ).lint(example=(8,), have_ladder=True, refit=True).by_rule("KG105")
    assert hits and "BlockWeightedLeastSquaresEstimator" in hits[0].node
