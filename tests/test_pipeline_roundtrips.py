"""Every canonical pipeline family fits, saves, loads, and reproduces its
predictions bit-for-bit — the model-export contract (SURVEY.md §5
checkpoint/resume row [unverified]). This net catches any node that sneaks
unpicklable state (lambdas, closures, file handles) into a fitted graph,
the class of bug that broke text-pipeline export until round 2.
"""

import numpy as np
import pytest

from keystone_tpu.workflow.serialization import load_pipeline, save_pipeline


def _roundtrip(pipe, sample, tmp_path, tag):
    ref = np.asarray(pipe.apply(sample).get())
    path = str(tmp_path / f"{tag}.pkl")
    save_pipeline(pipe, path)
    got = np.asarray(load_pipeline(path).apply(sample).get())
    np.testing.assert_array_equal(got, ref)


def test_mnist_fft_roundtrip(tmp_path):
    from keystone_tpu.loaders import MnistLoader
    from keystone_tpu.pipelines.images.mnist_random_fft import (
        MnistRandomFFTConfig,
        build_pipeline,
    )

    train, _ = MnistLoader.synthetic(n=256, seed=0)
    conf = MnistRandomFFTConfig(num_ffts=2, synthetic_n=256)
    pipe = build_pipeline(conf, train.data, train.labels).fit()
    _roundtrip(pipe, train.data[:16], tmp_path, "mnist")


def test_cifar_conv_roundtrip(tmp_path):
    from keystone_tpu.loaders.cifar import CifarLoader
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
    from keystone_tpu.nodes.util import ClassLabelIndicators, MaxClassifier
    from keystone_tpu.pipelines.images.random_patch_cifar import (
        RandomPatchCifarConfig,
        build_featurizer,
    )

    train, _ = CifarLoader.synthetic(n=192)
    conf = RandomPatchCifarConfig(
        num_filters=16, patch_sample=256, synthetic_n=192, num_iters=1
    )
    feat = build_featurizer(conf, train.data)
    targets = ClassLabelIndicators(10)(train.labels)
    pipe = (
        feat.and_then(
            BlockLeastSquaresEstimator(num_iters=1, lam=1.0),
            train.data,
            targets,
        )
        .and_then(MaxClassifier())
        .fit()
    )
    _roundtrip(pipe, train.data[:8], tmp_path, "cifar")


def test_timit_features_roundtrip(tmp_path):
    from keystone_tpu.loaders.timit import TimitFeaturesDataLoader
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
    from keystone_tpu.nodes.stats import CosineRandomFeatures
    from keystone_tpu.nodes.util import ClassLabelIndicators, MaxClassifier

    train, _ = TimitFeaturesDataLoader.synthetic(n=256)
    targets = ClassLabelIndicators(int(train.labels.max()) + 1)(train.labels)
    pipe = (
        CosineRandomFeatures.create(
            train.data.shape[1], 512, gamma=0.05, seed=0
        )
        .and_then(
            BlockLeastSquaresEstimator(num_iters=1, lam=1e-2),
            train.data,
            targets,
        )
        .and_then(MaxClassifier())
        .fit()
    )
    _roundtrip(pipe, train.data[:16], tmp_path, "timit")


def test_newsgroups_nb_roundtrip(tmp_path):
    from keystone_tpu.loaders.newsgroups import NewsgroupsDataLoader
    from keystone_tpu.nodes.learning import NaiveBayesEstimator
    from keystone_tpu.nodes.nlp import (
        CommonSparseFeatures,
        LowerCase,
        NGramsFeaturizer,
        TermFrequency,
        Tokenizer,
        Trim,
    )
    from keystone_tpu.nodes.util import MaxClassifier

    train, _test, classes = NewsgroupsDataLoader.synthetic(n=200)
    pipe = (
        Trim()
        .and_then(LowerCase())
        .and_then(Tokenizer())
        .and_then(NGramsFeaturizer(1, 2))
        .and_then(TermFrequency("log"))
        .and_then(CommonSparseFeatures(2000), train.data)
        .and_then(NaiveBayesEstimator(len(classes)), train.data, train.labels)
        .and_then(MaxClassifier())
        .fit()
    )
    _roundtrip(pipe, train.data[:16], tmp_path, "newsgroups")


def test_sparse_csr_text_roundtrip(tmp_path):
    """The explicit-CSR text path (sparse=True vectorizer + NB)."""
    from keystone_tpu.nodes.learning import NaiveBayesEstimator
    from keystone_tpu.nodes.nlp import (
        CommonSparseFeatures,
        TermFrequency,
        Tokenizer,
    )

    rng = np.random.default_rng(0)
    texts, labels = [], []
    for _ in range(120):
        c = int(rng.integers(0, 3))
        texts.append(
            " ".join(f"s{c}x{int(rng.integers(0, 20))}" for _ in range(10))
        )
        labels.append(c)
    labels = np.asarray(labels, dtype=np.int32)
    pipe = (
        Tokenizer()
        .and_then(TermFrequency("log"))
        .and_then(CommonSparseFeatures(1000, sparse=True), texts)
        .and_then(NaiveBayesEstimator(3), texts, labels)
        .fit()
    )
    _roundtrip(pipe, texts[:16], tmp_path, "sparse_csr")


def test_kernel_pcg_model_roundtrip(tmp_path):
    from keystone_tpu.nodes.learning import KernelRidgeRegression

    rng = np.random.default_rng(0)
    X = rng.normal(size=(128, 8)).astype(np.float32)
    Y = rng.normal(size=(128, 2)).astype(np.float32)
    pipe = (
        KernelRidgeRegression(
            gamma=0.2, lam=1e-2, max_iters=100, precond_landmarks=32
        )
        .with_data(X, Y)
        .fit()
    )
    _roundtrip(pipe, X[:16], tmp_path, "krr_pcg")


def test_text_estimator_prefix_is_persistable():
    """The whole canonical text prefix — corpus fingerprint + stable nlp
    node signatures — must produce a non-None structural digest, or the
    cross-process fit cache can never serve text pipelines."""
    from keystone_tpu.nodes.learning import NaiveBayesEstimator
    from keystone_tpu.nodes.nlp import (
        CommonSparseFeatures,
        LowerCase,
        NGramsFeaturizer,
        TermFrequency,
        Tokenizer,
        Trim,
    )
    from keystone_tpu.workflow.graph import structural_digest
    from keystone_tpu.workflow.operators import EstimatorOperator

    texts = [f"doc number {i} words" for i in range(50)]
    labels = np.arange(50, dtype=np.int32) % 3
    p = (
        Trim()
        .and_then(LowerCase())
        .and_then(Tokenizer())
        .and_then(NGramsFeaturizer(1, 2))
        .and_then(TermFrequency("log"))
        .and_then(CommonSparseFeatures(500), texts)
        .and_then(NaiveBayesEstimator(3), texts, labels)
    )
    g = p.graph
    est_nodes = [
        nid
        for nid in g.reachable([p.sink])
        if isinstance(g.operators[nid], EstimatorOperator)
    ]
    assert est_nodes
    for nid in est_nodes:
        assert structural_digest(g, nid) is not None


def test_logistic_roundtrip(tmp_path):
    from keystone_tpu.nodes.learning import LogisticRegressionEstimator
    from keystone_tpu.nodes.stats import StandardScaler

    rng = np.random.default_rng(0)
    X = rng.normal(size=(128, 16)).astype(np.float32)
    y = rng.integers(0, 3, size=128)
    pipe = (
        StandardScaler()
        .with_data(X)
        .and_then(LogisticRegressionEstimator(3, max_iters=20), X, y)
        .fit()
    )
    _roundtrip(pipe, X[:16], tmp_path, "logistic")
