"""Elastic mesh (ISSUE-18): durable solver state reshards across mesh
width changes instead of refusing.

The contracts, pinned:

- **Bit-identity**: a fit checkpointed at one width and resumed at
  another (both shrink and grow, in-process via a narrowed default mesh)
  produces bit-identical final weights to an uninterrupted fit at the
  TARGET width — chunked solve, BCD epoch checkpoints, and OnlineState
  (plain, decay, and window modes). The accumulators are placement-free
  f64/psum'd sums (the PR-14 grouping-invariance rule), so migration is
  a manifest rewrite, never a recompute.
- **Never silent**: every migration lands in the "elastic" counter
  family (``states_migrated`` + per-family keys) and rides ``/metrics``;
  torn/partial payloads refuse with the typed ``MeshMismatchError``
  (``migrations_refused`` counted).
- **Escape hatch**: ``KEYSTONE_ELASTIC_MESH=0`` pins the pre-elastic
  refuse-only contract (pinned in test_mesh_fit/test_online; the
  default-on path here).
- **One triage**: the three legacy-wildcard ``mesh_fp_compat`` call
  sites (stream solve, BCD, OnlineState) ride one helper
  (``mesh_resume_decision``) — pre-manifest checkpoints resume across
  all three families, parametrized.
- **KG107**: a checkpointed estimator whose directory's mesh manifest
  was recorded under a different width is flagged at lint time from the
  JSON sidecar (static dict read, no execution).
- **bench_watch**: the ``fit_elastic`` family (migration speedup,
  HIGHER_BETTER) regresses on speedup collapse and bit_identical flips,
  passes healthy reruns.

The 8→16 grow direction needs more devices than the in-process fake-8
mesh; tools/chaos_elastic.py covers it in subprocesses (the `make
chaos-elastic` leg, slow-marked here).
"""

import json
import os

import jax
import numpy as np
import pytest

from keystone_tpu.config import config
from keystone_tpu.utils import mesh as mesh_util
from keystone_tpu.utils.mesh import (
    MeshMismatchError,
    SpecLayout,
    layout_of_array,
    mesh_resume_decision,
    num_data_shards,
    read_mesh_manifest,
    reshard_state,
    set_default_mesh,
    value_data_shards,
    write_mesh_manifest,
)
from keystone_tpu.utils.metrics import (
    elastic_counters,
    metrics_registry,
    reliability_counters,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

D, K = 12, 3


@pytest.fixture(autouse=True)
def _fresh_elastic_counters():
    elastic_counters.reset()
    reliability_counters.reset()
    yield
    elastic_counters.reset()
    reliability_counters.reset()


def _narrow_mesh(width: int) -> None:
    """Shrink the default mesh to the first ``width`` fake devices (the
    test-suite analog of losing hosts mid-run)."""
    set_default_mesh(mesh_util.default_mesh(devices=jax.devices()[:width]))


def _stream(n=72, chunks=6):
    """Six 12-row chunks: 12 % 8 != 0 (mask-pad at width 8) while
    12 % 4 == 0 (direct shard at width 4) — both placement classes in
    one stream."""
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, D)).astype(np.float32)
    Y = rng.normal(size=(n, K)).astype(np.float32)
    rows = n // chunks

    def it():
        for i in range(chunks):
            yield X[i * rows:(i + 1) * rows], Y[i * rows:(i + 1) * rows]

    return X, Y, it


class Kill(Exception):
    pass


def _killed(it, at):
    def gen():
        for i, batch in enumerate(it()):
            if i == at:
                raise Kill()
            yield batch

    return gen


# ---------------------------------------------------------------------------
# Tentpole: bit-identical migrated resume, per family
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("source_w,target_w", [(8, 4), (4, 8)])
def test_stream_solve_migrates_bit_identical(tmp_path, source_w, target_w):
    """Chunked solve killed at one width resumes at another through the
    elastic migration, matching the uninterrupted fit at the target
    width bit-for-bit — and the migration is counted, never silent."""
    from keystone_tpu.linalg import solve_least_squares_chunked

    _, _, it = _stream()
    ckpt = str(tmp_path / "ckpt")
    if source_w != 8:
        _narrow_mesh(source_w)
    with pytest.raises(Kill):
        solve_least_squares_chunked(
            _killed(it, 4)(), lam=0.1,
            checkpoint_dir=ckpt, checkpoint_every=2,
        )
    # "Pod resize": the surviving run continues on the target width.
    if target_w == 8:
        mesh_util.reset_default_mesh()
    else:
        _narrow_mesh(target_w)
    assert num_data_shards() == target_w
    ref = np.asarray(solve_least_squares_chunked(it(), lam=0.1))
    out = np.asarray(
        solve_least_squares_chunked(
            it(), lam=0.1, checkpoint_dir=ckpt, checkpoint_every=2
        )
    )
    np.testing.assert_array_equal(ref, out)
    assert elastic_counters.get("states_migrated") == 1
    assert elastic_counters.get("stream_solve_migrated") == 1
    assert reliability_counters.get("checkpoints_resumed") == 1
    assert reliability_counters.get("chunks_skipped_on_resume") == 4


@pytest.mark.parametrize("source_w,target_w", [(8, 4), (4, 8)])
def test_bcd_epoch_checkpoint_migrates_bit_identical(
    tmp_path, monkeypatch, source_w, target_w
):
    """BCD epoch checkpoints re-pad the residual onto the new shard
    multiple (68 rows: padded 72 at width 8, unpadded at width 4) and
    resume to the same bits as the uninterrupted target-width solve."""
    from keystone_tpu.linalg.bcd import (
        assemble_blocks,
        block_coordinate_descent,
    )
    from keystone_tpu.linalg.row_matrix import RowMatrix

    rng = np.random.default_rng(1)
    Xh = rng.normal(size=(68, 16)).astype(np.float32)
    Yh = rng.normal(size=(68, K)).astype(np.float32)
    ckpt = str(tmp_path / "bcd_ckpt")
    if source_w != 8:
        _narrow_mesh(source_w)
    # Epoch 1 of 2 completes and checkpoints, then the "pod" dies
    # mid-epoch-2. Interrupting a real num_iters=2 run (rather than
    # seeding with num_iters=1) keeps every auto solver policy —
    # cache_grams in particular — identical across seed, resume, and
    # the fresh reference, so the bit gate tests resharding alone.
    import keystone_tpu.linalg.bcd as bcd_mod

    real_save = bcd_mod._save_epoch

    def killing_save(*a, **k):
        real_save(*a, **k)
        raise Kill()

    monkeypatch.setattr(bcd_mod, "_save_epoch", killing_save)
    with pytest.raises(Kill):
        block_coordinate_descent(
            RowMatrix.from_array(Xh), RowMatrix.from_array(Yh),
            block_size=8, num_iters=2, lam=1e-3, checkpoint_dir=ckpt,
        )
    monkeypatch.setattr(bcd_mod, "_save_epoch", real_save)
    bcd_mod.wait_for_checkpoints(ckpt)
    if target_w == 8:
        mesh_util.reset_default_mesh()
    else:
        _narrow_mesh(target_w)
    assert num_data_shards() == target_w
    A, B = RowMatrix.from_array(Xh), RowMatrix.from_array(Yh)
    Wr, _ = block_coordinate_descent(
        A, B, block_size=8, num_iters=2, lam=1e-3, checkpoint_dir=ckpt,
    )
    Wf, _ = block_coordinate_descent(
        RowMatrix.from_array(Xh), RowMatrix.from_array(Yh),
        block_size=8, num_iters=2, lam=1e-3,
    )
    np.testing.assert_array_equal(
        np.asarray(assemble_blocks(Wr)), np.asarray(assemble_blocks(Wf))
    )
    assert elastic_counters.get("bcd_epoch_migrated") == 1
    assert elastic_counters.get("states_migrated") == 1


@pytest.mark.parametrize("mode", ["plain", "decay", "window"])
@pytest.mark.parametrize("source_w,target_w", [(8, 4), (4, 8)])
def test_online_state_migrates_bit_identical(
    tmp_path, mode, source_w, target_w
):
    """An OnlineState snapshot folded at one width loads at another
    (migrated, counted) and the continued stream solves to the same bits
    as a fresh fold of the whole stream at the target width — in every
    forgetting mode."""
    from keystone_tpu.nodes.learning.linear_mapper import LinearMapEstimator
    from keystone_tpu.workflow.online import OnlineState

    kw = {}
    if mode == "decay":
        kw["decay"] = 0.5
    if mode == "window":
        kw["window"] = 2
    rng = np.random.default_rng(2)
    X = rng.normal(size=(64, D)).astype(np.float32)
    Y = rng.normal(size=(64, K)).astype(np.float32)
    splits = [(X[s:e], Y[s:e]) for s, e in
              [(0, 20), (20, 36), (36, 52), (52, 64)]]
    est = LinearMapEstimator(lam=1e-3)
    if source_w != 8:
        _narrow_mesh(source_w)
    st = None
    for bx, by in splits[:2]:
        st = est.partial_fit(bx, by, state=st, **kw)
    st.save(str(tmp_path))
    if target_w == 8:
        mesh_util.reset_default_mesh()
    else:
        _narrow_mesh(target_w)
    assert num_data_shards() == target_w
    resumed = OnlineState.load(str(tmp_path))
    assert resumed is not None
    assert elastic_counters.get("online_state_migrated") == 1
    assert resumed.device_count == target_w
    for bx, by in splits[2:]:
        resumed = est.partial_fit(bx, by, state=resumed, **kw)
    fresh = None
    for bx, by in splits:
        fresh = est.partial_fit(bx, by, state=fresh, **kw)
    m_r, m_f = est.solve_online(resumed), est.solve_online(fresh)
    np.testing.assert_array_equal(np.asarray(m_r.W), np.asarray(m_f.W))
    np.testing.assert_array_equal(np.asarray(m_r.b), np.asarray(m_f.b))


@pytest.mark.parametrize("source_w,target_w", [(8, 4), (4, 8)])
def test_profile_entry_migrates_onto_live_width(
    tmp_path, source_w, target_w
):
    """A profile-store entry recorded at another width re-scales its
    per-shard plan rows onto the live mesh (persisted back, counted)
    instead of refusing — but only when the lookup IS the live runtime."""
    from keystone_tpu.workflow.profile_store import (
        load_profile,
        save_profile,
    )

    digest = "e" * 40
    digests = {"abc": {"label": "X", "calls": 1, "wall_ns": 10,
                       "out_bytes": 4, "out_rows": 1,
                       "queue_wait_ns": 0, "out_shape": [1, 1],
                       "data_shards": source_w}}
    rows = [{"node": "X", "data_shards": source_w}]
    save_profile(
        digest, digests, rows, store_dir=str(tmp_path),
        fingerprint={"backend": "cpu", "device_kind": "cpu",
                     "device_count": source_w},
    )
    if target_w != 8:
        _narrow_mesh(target_w)
    entry = load_profile(
        digest, store_dir=str(tmp_path),
        fingerprint={"backend": "cpu", "device_kind": "cpu",
                     "device_count": target_w},
    )
    assert entry is not None
    assert entry.fingerprint["device_count"] == target_w
    assert entry.node("abc")["data_shards"] == target_w
    assert entry.rows[0]["data_shards"] == target_w
    assert elastic_counters.get("profile_migrated") == 1
    # Migration persisted: the next load at the new width is a clean hit
    # (no second migration).
    again = load_profile(
        digest, store_dir=str(tmp_path),
        fingerprint={"backend": "cpu", "device_kind": "cpu",
                     "device_count": target_w},
    )
    assert again is not None
    assert elastic_counters.get("profile_migrated") == 1


def test_profile_migration_requires_live_width(tmp_path):
    """A lookup fingerprint naming NEITHER the recorded nor the live
    width is a question about another machine: still refused typed."""
    from keystone_tpu.workflow.profile_store import (
        ProfileFingerprintError,
        load_profile,
        save_profile,
    )

    digest = "f" * 40
    save_profile(
        digest, {"abc": {"label": "X", "data_shards": 2}}, [],
        store_dir=str(tmp_path),
        fingerprint={"backend": "cpu", "device_kind": "cpu",
                     "device_count": 2},
    )
    with pytest.raises(ProfileFingerprintError):
        load_profile(
            digest, store_dir=str(tmp_path),
            fingerprint={"backend": "cpu", "device_kind": "cpu",
                         "device_count": 4},  # live mesh is 8
        )
    assert elastic_counters.get("profile_migrated") == 0
    # A backend mismatch is never elastically recoverable, even at the
    # live width: a CPU profile must not size a TPU plan.
    with pytest.raises(ProfileFingerprintError):
        load_profile(
            digest, store_dir=str(tmp_path),
            fingerprint={"backend": "tpu", "device_kind": "tpu",
                         "device_count": 8},
        )
    assert elastic_counters.get("profile_migrated") == 0


# ---------------------------------------------------------------------------
# Non-migratable state still refuses, typed and counted
# ---------------------------------------------------------------------------


def test_torn_stream_snapshot_refuses_typed():
    state = {
        "fingerprint": {"d": 8, "device_count": 4, "data_axis": "data"},
        "chunks_done": 2,
        "gram": np.eye(5),  # contradicts d=8: a torn payload
        "atb": np.zeros((8, 2)),
    }
    with pytest.raises(MeshMismatchError, match="torn|refuses"):
        reshard_state(state, family="stream_solve")
    assert elastic_counters.get("migrations_refused") == 1
    assert elastic_counters.get("states_migrated") == 0


def test_torn_bcd_residual_refuses_typed():
    """Nonzero rows in the residual's pad region can only mean a partial
    per-shard write — the mid-chunk-partial-shard case the issue names
    as truly non-migratable."""
    fp = {"rows": 72, "n": 68, "d": 16, "k": K, "block_size": 8,
          "lam": 1e-3, "weighted": False, "a_dtype": "float32",
          "a_probe": 1.0, "b_probe": 2.0,
          "device_count": 8, "data_axis": "data"}
    R = np.zeros((72, K), dtype=np.float32)
    R[70] = 7.0  # torn: pad rows must be zero by construction
    state = {"fingerprint": fp, "epoch": 1,
             "W": [np.zeros((8, K), np.float32)], "R": R}
    with pytest.raises(MeshMismatchError, match="pad region"):
        reshard_state(state, family="bcd_epoch")
    assert elastic_counters.get("migrations_refused") == 1

    # The clean counterpart migrates (sanity: the refusal above is about
    # the torn bytes, not the shape change).
    state["R"] = np.zeros((72, K), dtype=np.float32)
    migrated = reshard_state(
        state, new_layout=SpecLayout.for_mesh(
            mesh_util.default_mesh(devices=jax.devices()[:4])
        ),
        family="bcd_epoch",
    )
    assert migrated["fingerprint"]["device_count"] == 4
    assert migrated["fingerprint"]["rows"] == 80
    assert migrated["R"].shape == (80, K)


def test_unknown_family_refuses_typed():
    with pytest.raises(MeshMismatchError, match="no migration adapter"):
        reshard_state({"mystery": 1})
    assert elastic_counters.get("migrations_refused") == 1


# ---------------------------------------------------------------------------
# Satellite: one resume triage, legacy pre-manifest resumes everywhere
# ---------------------------------------------------------------------------


def _bcd_matcher():
    from keystone_tpu.linalg.bcd import _fingerprint_matches

    return _fingerprint_matches


_STREAM_FP = {"d": D, "b_tail": (K,), "accum_dtype": "float32",
              "storage_dtype": "float32", "chunk_rows": 12,
              "x0_probe": 1.25, "device_count": 8, "data_axis": "data"}
_BCD_FP = {"rows": 72, "n": 68, "d": 16, "k": K, "block_size": 8,
           "lam": 1e-3, "weighted": False, "a_dtype": "float32",
           "a_probe": 1.0, "b_probe": 2.0,
           "device_count": 8, "data_axis": "data"}
_ONLINE_FP = {"d": D, "b_tail": (K,), "chunk_rows": 512, "window": None,
              "default_dtype": "float32", "accum_dtype": "float32",
              "device_count": 8, "data_axis": "data"}


@pytest.mark.parametrize("expected,extra,matcher", [
    (_STREAM_FP, (), None),
    (_BCD_FP, ("rows",), _bcd_matcher),
    (_ONLINE_FP, (), None),
], ids=["stream_solve", "bcd", "online_state"])
def test_legacy_premanifest_checkpoints_resume(expected, extra, matcher):
    """The consolidated triage backfills absent mesh keys as wildcards
    for every family: a pre-manifest checkpoint of the same problem
    RESUMES (never silently restarts), a width conflict migrates, a
    different problem goes fresh — one rule, three families."""
    matches = matcher() if matcher else None
    legacy = {k: v for k, v in expected.items()
              if k not in ("device_count", "data_axis")}
    decision, backfilled = mesh_resume_decision(
        legacy, expected, "test", extra_mesh_keys=extra,
        same_problem=matches,
    )
    assert decision == "resume"
    assert backfilled["device_count"] == expected["device_count"]
    # Same problem, explicit other width: migrate (elastic default-on).
    other = dict(expected, device_count=2)
    if "rows" in other:
        other["rows"] = other["n"]  # padded rows follow the mesh
    decision, _ = mesh_resume_decision(
        other, expected, "test", extra_mesh_keys=extra,
        same_problem=matches,
    )
    assert decision == "migrate"
    # Different problem: fresh, never a typed mesh refusal.
    decision, _ = mesh_resume_decision(
        dict(other, d=999), expected, "test", extra_mesh_keys=extra,
        same_problem=matches,
    )
    assert decision == "fresh"


# ---------------------------------------------------------------------------
# Satellite: post-reshard arrays report the NEW width
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("source_w,target_w", [(8, 4), (4, 8)])
def test_post_reshard_arrays_report_new_width(source_w, target_w):
    """value_data_shards / layout_of_array on arrays re-placed after a
    width change name the NEW width — what profile rows and /metrics
    report for migrated state, both directions."""
    x = np.random.default_rng(3).normal(size=(64, D)).astype(np.float32)
    if source_w != 8:
        _narrow_mesh(source_w)
    layout = SpecLayout.for_mesh()
    placed = layout.put(x)
    assert value_data_shards(placed) == source_w
    assert layout_of_array(placed).num_shards == source_w
    if target_w == 8:
        mesh_util.reset_default_mesh()
    else:
        _narrow_mesh(target_w)
    relayout = SpecLayout.for_mesh()
    replaced = relayout.put(x)
    assert value_data_shards(replaced) == target_w
    assert layout_of_array(replaced) == relayout
    assert layout_of_array(replaced).num_shards == target_w


def test_elastic_counters_ride_metrics():
    """Migrations are observable where every other counter lives: the
    registry snapshot and the Prometheus exposition."""
    elastic_counters.bump("states_migrated")
    elastic_counters.bump("stream_solve_migrated")
    snap = metrics_registry.snapshot()
    assert snap["elastic"]["states_migrated"] == 1
    prom = metrics_registry.prometheus()
    assert "keystone_elastic" in prom
    assert "states_migrated" in prom


# ---------------------------------------------------------------------------
# Satellite: KG107 — checkpoint mesh drift at lint time
# ---------------------------------------------------------------------------


def _ckpt_pipeline(ckpt_dir):
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
    from keystone_tpu.workflow import Transformer

    class Ident(Transformer):
        def apply_batch(self, X):
            return X * 1.0

    rng = np.random.default_rng(4)
    X = rng.normal(size=(64, D)).astype(np.float32)
    y = rng.normal(size=(64, K)).astype(np.float32)
    return Ident().to_pipeline().and_then(
        BlockLeastSquaresEstimator(
            block_size=8, num_iters=1, lam=1e-3,
            checkpoint_dir=str(ckpt_dir),
        ),
        X, y,
    )


def test_kg107_flags_checkpoint_width_drift(tmp_path):
    write_mesh_manifest(str(tmp_path), {"device_count": 2,
                                        "data_axis": "data"})
    assert read_mesh_manifest(str(tmp_path))["device_count"] == 2
    hits = _ckpt_pipeline(tmp_path).lint().by_rule("KG107")
    assert hits, "width drift in checkpoint_dir must be flagged"
    assert hits[0].severity == "warning"
    assert "2-shard" in hits[0].message
    assert "reshard_state" in hits[0].hint


def test_kg107_silent_on_matching_or_absent_manifest(tmp_path):
    # No sidecar at all (no checkpoint yet): silent.
    assert not _ckpt_pipeline(tmp_path / "empty").lint().by_rule("KG107")
    # Manifest recorded on THIS mesh: silent.
    write_mesh_manifest(str(tmp_path), {"device_count": 8,
                                        "data_axis": "data"})
    assert not _ckpt_pipeline(tmp_path).lint().by_rule("KG107")


def test_kg107_in_catalog():
    from keystone_tpu.workflow.analysis import GRAPH_RULES

    assert "KG107" in GRAPH_RULES


def test_checkpoint_writers_drop_mesh_sidecars(tmp_path):
    """All three checkpoint families leave the JSON sidecar KG107 reads
    — the static-lint window is populated by normal operation."""
    from keystone_tpu.linalg import solve_least_squares_chunked
    from keystone_tpu.nodes.learning.linear_mapper import LinearMapEstimator

    _, _, it = _stream()
    sdir = tmp_path / "stream"
    solve_least_squares_chunked(
        it(), lam=0.1, checkpoint_dir=str(sdir), checkpoint_every=2
    )
    manifest = read_mesh_manifest(str(sdir))
    assert manifest is not None and manifest["device_count"] == 8

    odir = tmp_path / "online"
    est = LinearMapEstimator(lam=1e-3)
    rng = np.random.default_rng(5)
    st = est.partial_fit(rng.normal(size=(32, D)).astype(np.float32),
                         rng.normal(size=(32, K)).astype(np.float32))
    st.save(str(odir))
    manifest = read_mesh_manifest(str(odir))
    assert manifest is not None and manifest["device_count"] == 8


# ---------------------------------------------------------------------------
# Satellite: bench_watch learns the fit_elastic family
# ---------------------------------------------------------------------------


def _elastic_row(value, bit_identical=True, resume_wall=0.5):
    return {
        "metric": "fit_elastic",
        "value": value,
        "unit": "x migration speedup (thrown-away-work restart wall / "
                "elastic resume wall)",
        "backend": "cpu",
        "host_cores": 1,
        "n_devices": 8,
        "detail": {
            "bit_identical": bit_identical,
            "migrations": 2,
            "resume_wall_s": resume_wall,
            "restart_wall_s": 2.0,
        },
        "ok": True,
    }


def _bench_watch_run(tmp_path, rows):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_watch_under_elastic_test",
        os.path.join(REPO, "tools", "bench_watch.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    with open(tmp_path / "BENCH_fit.json", "w") as f:
        for row in rows:
            f.write(json.dumps(row) + "\n")
    return mod.run(str(tmp_path))


def test_bench_watch_judges_fit_elastic(tmp_path):
    # Healthy history, then the migration stops paying for itself AND
    # stops being exact: speedup (value) collapses, resume wall blows
    # up, bit_identical flips — all flagged.
    rows = [
        _elastic_row(4.0), _elastic_row(4.2), _elastic_row(3.9),
        _elastic_row(0.6, bit_identical=False, resume_wall=3.5),
    ]
    result = _bench_watch_run(tmp_path, rows)
    bad = {v["series"] for v in result["regressions"]}
    assert "fit:fit_elastic:value" in bad
    assert "fit:fit_elastic:detail.resume_wall_s" in bad
    assert "fit:fit_elastic:detail.bit_identical" in bad
    assert not result["ok"]


def test_bench_watch_passes_healthy_fit_elastic(tmp_path):
    rows = [_elastic_row(4.0), _elastic_row(4.2), _elastic_row(4.1)]
    result = _bench_watch_run(tmp_path, rows)
    assert result["ok"], result["regressions"]


# ---------------------------------------------------------------------------
# The chaos leg end-to-end (subprocesses at widths 8 → 4 and 8 → 16)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_chaos_elastic_quick_green():
    """tools/chaos_elastic.py under the chaos fault plan: a width-8 fit
    and a width-8 online stream killed mid-solve resume at widths 4 AND
    16 to the uninterrupted target-width bits, migrations counted, and
    the fit_elastic bench row emitted."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "chaos_elastic.py"),
         "--quick"],
        cwd=REPO, capture_output=True, text=True, timeout=560,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "KEYSTONE_FAULTS": "io:0.05,oom:1",
             "KEYSTONE_FAULTS_SEED": "0"},
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    assert row["metric"] == "fit_elastic" and row["ok"]
    detail = row["detail"]
    assert detail["bit_identical_shrink"] is True
    assert detail["bit_identical_grow"] is True
    assert detail["migrations"] >= 2
    assert detail["fresh_migrations"] == 0  # zero silent migrations
