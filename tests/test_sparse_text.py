"""Sparse text features: CSR SparseBatch through CommonSparseFeatures into
the classifiers and solvers, densified only per column block.

Ref: the reference's Spark SparseVector text path (SURVEY.md §2.7/§2.8)
[unverified]; VERDICT round-2 item 9 — vocab ≫ 10k must never materialize
an (n, vocab) dense array.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.config import config
from keystone_tpu.nodes.learning import (
    BlockLeastSquaresEstimator,
    NaiveBayesEstimator,
)
from keystone_tpu.nodes.nlp import CommonSparseFeatures, WordFrequencyEncoder
from keystone_tpu.utils.sparse import SparseBatch


def _random_sparse(rng, n=64, d=512, nnz_per_row=8, centered=False):
    X = np.zeros((n, d), dtype=np.float32)
    for i in range(n):
        cols = rng.choice(d, size=nnz_per_row, replace=False)
        if centered:
            # Zero-mean values keep the intercept column near-orthogonal to
            # the features, so coordinate descent converges fast — the
            # parity tests compare SOLUTIONS, not convergence rates.
            X[i, cols] = rng.normal(size=nnz_per_row)
        else:
            X[i, cols] = rng.uniform(0.5, 2.0, size=nnz_per_row)
    return X


class TestSparseBatch:
    def test_densify_matches_dense(self, rng):
        X = _random_sparse(rng)
        sb = SparseBatch.from_dense(X)
        np.testing.assert_allclose(sb.toarray(), X)
        np.testing.assert_allclose(sb.densify(100, 300), X[:, 100:300])

    def test_matmul_blocks(self, rng):
        X = _random_sparse(rng)
        M = rng.normal(size=(512, 7)).astype(np.float32)
        np.testing.assert_allclose(
            SparseBatch.from_dense(X).matmul(M, block=100),
            X @ M,
            rtol=1e-4,
            atol=1e-5,
        )

    def test_reductions(self, rng):
        X = _random_sparse(rng)
        sb = SparseBatch.from_dense(X)
        np.testing.assert_allclose(sb.column_sums(), X.sum(0), rtol=1e-5)
        y = rng.integers(0, 3, size=len(X))
        grouped = sb.grouped_column_sums(y, 3)
        for c in range(3):
            np.testing.assert_allclose(
                grouped[c], X[y == c].sum(0), rtol=1e-5
            )
        assert sb.row_sum(0) == pytest.approx(float(X[0].sum()), rel=1e-5)

    def test_append_ones(self, rng):
        X = _random_sparse(rng, n=16, d=32)
        aug = SparseBatch.from_dense(X).append_ones()
        dense = aug.toarray()
        np.testing.assert_allclose(dense[:, :32], X)
        np.testing.assert_allclose(dense[:, 32], np.ones(16))


class TestVectorizers:
    def test_sparse_output_parity(self):
        docs = [{"a": 1.0, "b": 2.0}, {"b": 3.0, "c": 4.0}, {"a": 5.0}]
        dense_fit = CommonSparseFeatures(3, sparse=False).fit(docs)
        sparse_fit = CommonSparseFeatures(3, sparse=True).fit(docs)
        sb = sparse_fit.apply_batch(docs)
        assert isinstance(sb, SparseBatch)
        np.testing.assert_allclose(sb.toarray(), dense_fit.apply_batch(docs))

    def test_count_vectorizer_parity(self):
        docs = [["a", "b", "a"], ["c"], ["b", "b", "b"]]
        dense = WordFrequencyEncoder(3, sparse=False).fit(docs).apply_batch(docs)
        sb = WordFrequencyEncoder(3, sparse=True).fit(docs).apply_batch(docs)
        np.testing.assert_allclose(sb.toarray(), np.asarray(dense))

    def test_auto_switches_on_threshold(self, monkeypatch):
        docs = [{"a": 1.0}, {"b": 2.0}]
        monkeypatch.setattr(config, "text_sparse_threshold", 2)
        assert isinstance(
            CommonSparseFeatures(2).fit(docs).apply_batch(docs), SparseBatch
        )
        monkeypatch.setattr(config, "text_sparse_threshold", 100)
        assert isinstance(
            CommonSparseFeatures(2).fit(docs).apply_batch(docs), np.ndarray
        )


class TestSparseClassifiers:
    def test_naive_bayes_sparse_matches_dense(self, rng):
        X = _random_sparse(rng, n=128, d=256)
        y = rng.integers(0, 4, size=128)
        dense_model = NaiveBayesEstimator(4).fit(X, y)
        sparse_model = NaiveBayesEstimator(4).fit(SparseBatch.from_dense(X), y)
        np.testing.assert_allclose(
            np.asarray(sparse_model.apply_batch(SparseBatch.from_dense(X))),
            np.asarray(dense_model.apply_batch(jnp.asarray(X))),
            rtol=1e-4,
            atol=1e-4,
        )

    def test_block_ls_sparse_matches_dense(self, rng):
        X = _random_sparse(rng, n=256, d=96, centered=True)
        W = rng.normal(size=(96, 3)).astype(np.float32)
        Y = X @ W + 0.5
        est = dict(block_size=32, num_iters=6, lam=0.0)
        dense_pred = np.asarray(
            BlockLeastSquaresEstimator(**est).fit(X, Y).apply_batch(X)
        )
        sb = SparseBatch.from_dense(X)
        sparse_pred = np.asarray(
            BlockLeastSquaresEstimator(**est).fit(sb, Y).apply_batch(sb)
        )
        # Same model class solved two ways (centering vs ones-column);
        # at lam=0 both converge to the same least-squares predictions.
        np.testing.assert_allclose(sparse_pred, dense_pred, rtol=2e-2, atol=2e-2)

    def test_logistic_sparse_roundtrip(self, rng):
        """Sparse input: fit runs device-sparse (BCOO inside the LBFGS
        loop), inference stays CSR — and both match the dense fit."""
        from keystone_tpu.nodes.learning import LogisticRegressionEstimator

        X = _random_sparse(rng, n=96, d=64, centered=True)
        y = rng.integers(0, 3, size=96)
        dense_model = LogisticRegressionEstimator(3, max_iters=30).fit(X, y)
        model = LogisticRegressionEstimator(3, max_iters=30).fit(
            SparseBatch.from_dense(X), y
        )
        dense_scores = np.asarray(model.apply_batch(jnp.asarray(X)))
        sparse_scores = np.asarray(
            model.apply_batch(SparseBatch.from_dense(X))
        )
        np.testing.assert_allclose(
            sparse_scores, dense_scores, rtol=1e-4, atol=1e-4
        )
        # Same loss, same optimizer: the two fits make the same predictions
        # (weight-level comparison would be brittle across matmul
        # summation orders after 30 iterated steps).
        ref_scores = np.asarray(dense_model.apply_batch(jnp.asarray(X)))
        assert (
            sparse_scores.argmax(axis=1) == ref_scores.argmax(axis=1)
        ).mean() > 0.97

    def test_block_ls_sparse_no_intercept_exact(self, rng):
        X = _random_sparse(rng, n=256, d=64)
        W = rng.normal(size=(64, 3)).astype(np.float32)
        Y = X @ W
        kw = dict(block_size=64, num_iters=3, lam=1e-6, fit_intercept=False)
        dense_pred = np.asarray(
            BlockLeastSquaresEstimator(**kw).fit(X, Y).apply_batch(X)
        )
        sb = SparseBatch.from_dense(X)
        sparse_pred = np.asarray(
            BlockLeastSquaresEstimator(**kw).fit(sb, Y).apply_batch(sb)
        )
        np.testing.assert_allclose(sparse_pred, dense_pred, rtol=1e-3, atol=1e-3)


def _wide_corpus(n=500, tail_vocab=120_000, num_classes=4, seed=0):
    """Synthetic text whose vocabulary genuinely exceeds the sparse
    threshold: per-class signal tokens plus a long tail of rare words (the
    newsgroups loader's built-in topics only span a few hundred terms)."""
    r = np.random.default_rng(seed)
    texts, labels = [], []
    for _ in range(n):
        c = int(r.integers(0, num_classes))
        sig = [f"sig{c}x{int(r.integers(0, 50))}" for _ in range(15)]
        tail = [f"w{int(r.integers(0, tail_vocab))}" for _ in range(60)]
        words = sig + tail
        r.shuffle(words)
        texts.append(" ".join(words))
        labels.append(c)
    return texts, np.asarray(labels, dtype=np.int32)


class TestNewsgroupsLargeVocab:
    @pytest.mark.slow
    def test_pipeline_at_100k_feature_budget(self):
        """The VERDICT regression: the canonical text stages with a 100k
        feature budget stay CSR end-to-end — an (n, vocab) dense array is
        never built — and the classifier still works."""
        from keystone_tpu.evaluation import MulticlassClassifierEvaluator
        from keystone_tpu.nodes.nlp import (
            LowerCase,
            TermFrequency,
            Tokenizer,
            Trim,
        )
        from keystone_tpu.nodes.util import MaxClassifier

        texts, labels = _wide_corpus()
        featurizer = (
            Trim()
            .and_then(LowerCase())
            .and_then(Tokenizer())
            .and_then(TermFrequency("log"))
            .and_then(CommonSparseFeatures(100_000), texts)
        )
        feats = featurizer(texts).get()
        assert isinstance(feats, SparseBatch)  # over the sparse threshold
        assert feats.dim > config.text_sparse_threshold
        pipeline = featurizer.and_then(
            NaiveBayesEstimator(4), texts, labels
        ).and_then(MaxClassifier())
        preds = pipeline(texts).get()
        metrics = MulticlassClassifierEvaluator(4).evaluate(preds, labels)
        assert metrics.total_accuracy > 0.9
