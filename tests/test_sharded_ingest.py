"""Per-host shard ingest (SURVEY.md §7 hard part 4; VERDICT r2 #7).

Host h of H decodes only slice h of the sorted synset list. Validated
in-process (disjointness/union/labels) and across two REAL processes —
the 2-host ingest pattern as code, against the committed real-format
ImageNet fixture (one .tar synset + one directory synset).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from keystone_tpu.loaders.imagenet import ImageNetLoader, _pool_workers

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DATA = os.path.join(os.path.dirname(__file__), "fixtures", "data", "imagenet")


def test_pool_workers_capped_at_core_count():
    cores = os.cpu_count() or 1
    assert _pool_workers(None) == min(16, cores)
    assert _pool_workers(64) == min(64, cores)
    assert _pool_workers(1) == 1


def test_shards_are_disjoint_and_cover():
    label_map = ImageNetLoader.load_label_map(os.path.join(DATA, "labels.txt"))
    root = os.path.join(DATA, "train")
    full = [
        (len(buf), label)
        for buf, label in ImageNetLoader.iter_jobs(root, label_map)
    ]
    for num_hosts in (2, 3):
        parts = [
            [
                (len(buf), label)
                for buf, label in ImageNetLoader.iter_jobs(
                    root, label_map, shard=(h, num_hosts)
                )
            ]
            for h in range(num_hosts)
        ]
        union = [job for part in parts for job in part]
        assert sorted(union) == sorted(full)  # cover, no duplicates
    with pytest.raises(ValueError, match="shard index"):
        list(ImageNetLoader.iter_jobs(root, label_map, shard=(2, 2)))


_WORKER = r"""
import json, os, sys
sys.path.insert(0, {repo!r})
from keystone_tpu.loaders.imagenet import ImageNetLoader

h, H = int(sys.argv[1]), int(sys.argv[2])
label_map = ImageNetLoader.load_label_map(os.path.join({data!r}, "labels.txt"))
batches = list(ImageNetLoader.stream_batches(
    os.path.join({data!r}, "train"), label_map,
    batch_size=2, size=16, workers=1, shard=(h, H),
))
out = {{
    "host": h,
    "labels": [int(l) for _X, y in batches for l in y],
    "pixels": [round(float(X.mean()), 4) for X, _y in batches],
}}
print(json.dumps(out))
"""


@pytest.mark.slow
def test_two_process_sharded_ingest():
    """Two real processes each stream their shard; together they cover the
    dataset exactly once — the multi-host ingest seam as running code."""
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER.format(repo=REPO, data=DATA), str(h), "2"],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        for h in range(2)
    ]
    outs = []
    for p in procs:
        stdout, stderr = p.communicate(timeout=120)
        assert p.returncode == 0, stderr[-2000:]
        outs.append(json.loads(stdout.strip().splitlines()[-1]))

    label_map = ImageNetLoader.load_label_map(os.path.join(DATA, "labels.txt"))
    full_labels = sorted(
        label
        for _buf, label in ImageNetLoader.iter_jobs(
            os.path.join(DATA, "train"), label_map
        )
    )
    got = sorted(l for o in outs for l in o["labels"])
    assert got == full_labels  # disjoint cover across the two processes
    # Each host actually decoded pixels (not just listed files).
    assert all(len(o["pixels"]) >= 1 for o in outs)
