"""Native C++ library tests (skip when the toolchain can't build it —
mirroring the reference's native-lib-gated suites, SURVEY.md §4)."""

import numpy as np
import pytest

from keystone_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason=f"native lib unavailable: {native.build_error()}"
)


def test_sift_shapes_and_normalization(rng):
    imgs = rng.uniform(size=(3, 40, 48)).astype(np.float32)
    d = native.dense_sift(imgs, step=8, bin_size=4)
    nkp = native.sift_num_keypoints(40, 48, 8, 4)
    assert d.shape == (3, nkp, 128)
    norms = np.linalg.norm(d, axis=2)
    # descriptors are L2-normalized (or zero for flat patches)
    assert np.all((np.abs(norms - 1.0) < 1e-3) | (norms < 1e-6))
    assert d.min() >= 0.0  # gradient-magnitude histograms are nonnegative


def test_sift_deterministic_and_translation_sensitive(rng):
    img = rng.uniform(size=(1, 32, 32)).astype(np.float32)
    a = native.dense_sift(img, step=4, bin_size=4)
    b = native.dense_sift(img, step=4, bin_size=4)
    np.testing.assert_array_equal(a, b)
    # A constant image has zero gradients -> zero descriptors.
    flat = np.full((1, 32, 32), 0.5, dtype=np.float32)
    z = native.dense_sift(flat, step=4, bin_size=4)
    np.testing.assert_allclose(z, 0.0)


def test_sift_oriented_edges_hit_expected_bins():
    # Vertical edge gradient (pointing +x) should concentrate energy in the
    # orientation bin around theta = 0.
    img = np.tile(
        (np.arange(32, dtype=np.float32) / 31.0)[None, :], (32, 1)
    )[None]
    d = native.dense_sift(img, step=4, bin_size=4)
    desc = d[0, 0].reshape(16, 8)
    assert desc.sum() > 0
    assert np.argmax(desc.sum(axis=0)) == 0  # bin 0 = theta ~ 0 (+x)


def test_gmm_fit_recovers_mixture(rng):
    X = np.concatenate(
        [
            rng.normal(-3, 0.5, (500, 4)),
            rng.normal(3, 1.0, (1500, 4)),
        ]
    ).astype(np.float32)
    w, mu, var = native.gmm_fit(X, k=2, iters=40, seed=1)
    order = np.argsort(mu[:, 0])
    np.testing.assert_allclose(w[order], [0.25, 0.75], atol=0.03)
    np.testing.assert_allclose(mu[order][:, 0], [-3, 3], atol=0.2)
    np.testing.assert_allclose(
        var[order][:, 0], [0.25, 1.0], atol=0.15
    )


def test_native_gmm_matches_jnp_gmm(rng):
    """Native EM and the TPU (jnp) EM should land on the same mixture."""
    from keystone_tpu.nodes.learning import GaussianMixtureModelEstimator

    X = np.concatenate(
        [rng.normal(-2, 0.6, (400, 3)), rng.normal(2, 0.9, (600, 3))]
    ).astype(np.float32)
    w_n, mu_n, _ = native.gmm_fit(X, k=2, iters=50, seed=0)
    jgmm = GaussianMixtureModelEstimator(k=2, max_iters=50, seed=0).fit(X)
    order_n = np.argsort(mu_n[:, 0])
    order_j = np.argsort(np.asarray(jgmm.means)[:, 0])
    np.testing.assert_allclose(
        mu_n[order_n], np.asarray(jgmm.means)[order_j], atol=0.1
    )
    np.testing.assert_allclose(
        w_n[order_n], np.asarray(jgmm.weights)[order_j], atol=0.03
    )


def test_fisher_vector_native_matches_tpu(rng):
    """The two FV backends implement the same math."""
    from keystone_tpu.nodes.images.external import FisherVector

    X = rng.normal(size=(2, 50, 6)).astype(np.float32)
    w, mu, var = native.gmm_fit(
        rng.normal(size=(500, 6)).astype(np.float32), k=3, iters=10, seed=0
    )
    fv_native = FisherVector(w, mu, var, backend="native")(X)
    fv_tpu = np.asarray(FisherVector(w, mu, var, backend="tpu")(X))
    assert fv_native.shape == fv_tpu.shape == (2, 2 * 3 * 6)
    np.testing.assert_allclose(fv_native, fv_tpu, rtol=1e-3, atol=1e-4)


def test_fisher_vector_oracle(rng):
    """FV against a direct NumPy implementation of the formulas."""
    n, d, k = 30, 4, 2
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = np.array([0.4, 0.6], dtype=np.float32)
    mu = rng.normal(size=(k, d)).astype(np.float32)
    var = rng.uniform(0.5, 1.5, size=(k, d)).astype(np.float32)
    fv = native.fisher_vector(X, w, mu, var)
    # NumPy oracle
    log_r = np.zeros((n, k))
    for j in range(k):
        log_r[:, j] = (
            np.log(w[j])
            - 0.5 * (d * np.log(2 * np.pi) + np.sum(np.log(var[j])))
            - 0.5 * np.sum((X - mu[j]) ** 2 / var[j], axis=1)
        )
    r = np.exp(log_r - log_r.max(axis=1, keepdims=True))
    r /= r.sum(axis=1, keepdims=True)
    gmu = np.zeros((k, d))
    gvar = np.zeros((k, d))
    for j in range(k):
        u = (X - mu[j]) / np.sqrt(var[j])
        gmu[j] = (r[:, j : j + 1] * u).sum(0) / (n * np.sqrt(w[j]))
        gvar[j] = (r[:, j : j + 1] * (u**2 - 1)).sum(0) / (
            n * np.sqrt(2 * w[j])
        )
    oracle = np.concatenate([gmu.ravel(), gvar.ravel()])
    np.testing.assert_allclose(fv, oracle, rtol=1e-3, atol=1e-5)
