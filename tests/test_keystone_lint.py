"""AST invariant-checker tests (tools/keystone_lint.py, Layer 2).

Every shipped KL rule is pinned by one synthetic violating snippet and
one clean one, the PR-5 lost-wakeup serving bug is reproduced as a
regression fixture (and its per-waiter-condition FIX must lint clean),
the live workflow/serving.py must carry zero concurrency findings, and
the repo-wide gate (`make lint`'s AST half) runs in-process against the
checked-in baseline so it can never silently rot.
"""

import importlib
import os
import sys
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO_ROOT, "tools")
sys.path.insert(0, TOOLS)

keystone_lint = importlib.import_module("keystone_lint")


def lint_snippet(tmp_path, source, name="snippet.py"):
    """Scan one synthetic module; returns the findings."""
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    findings, _keys = keystone_lint.scan([str(p)], root=str(tmp_path))
    return findings


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# KL001 lock discipline
# ---------------------------------------------------------------------------

SERVICE_SHAPE = """
    import threading

    class PipelineService:
        def __init__(self):
            self._lock = threading.Lock()
            self._cv = threading.Condition(self._lock)
            self._pending = []
            self.batches_run = 0
            self._worker = threading.Thread(target=self._loop, daemon=True)

        def submit(self, x):
            with self._cv:
                self._pending.append(x)
                self.batches_run += 1
                self._cv.notify_all()

        def _loop(self):
            while True:
                with self._cv:
                    x = self._pending.pop()
                {loop_tail}

        def close(self):
            with self._cv:
                self._pending.clear()
"""


def test_kl001_catches_service_shared_attr_mutated_outside_lock(tmp_path):
    # The acceptance fixture: a PipelineService-shaped class whose worker
    # bumps a shared counter OUTSIDE self._lock while submit bumps it
    # under the lock.
    bad = SERVICE_SHAPE.format(loop_tail="self.batches_run += 1")
    findings = [f for f in lint_snippet(tmp_path, bad) if f.rule == "KL001"]
    assert len(findings) == 1
    f = findings[0]
    assert "batches_run" in f.message and "_loop" in f.message
    assert f.severity == "error"


def test_kl001_clean_when_every_write_is_locked(tmp_path):
    good = SERVICE_SHAPE.format(
        loop_tail="with self._lock:\n                    self.batches_run += 1"
    )
    assert "KL001" not in rules_of(lint_snippet(tmp_path, good))


def test_kl001_locked_suffix_convention_and_single_owner_attrs(tmp_path):
    src = """
    import threading

    class Engine:
        def __init__(self):
            self._lock = threading.Lock()
            self.compiles = 0
            self.private = 0

        def warmup(self):
            with self._lock:
                self._compile_locked()

        def serve(self):
            with self._lock:
                self._compile_locked()

        def _compile_locked(self):
            self.compiles += 1  # caller holds the lock: the convention

        def stats_only(self):
            self.private += 1  # single entry point: not shared state
    """
    assert "KL001" not in rules_of(lint_snippet(tmp_path, src))


def test_kl001_mutator_calls_count_as_writes(tmp_path):
    bad = SERVICE_SHAPE.format(loop_tail="self._pending.append(x)")
    findings = [f for f in lint_snippet(tmp_path, bad) if f.rule == "KL001"]
    assert findings and "mutates self._pending" in findings[0].message


def test_kl001_suppression_tag(tmp_path):
    bad = SERVICE_SHAPE.format(
        loop_tail="self.batches_run += 1  # lint: ok(KL001) benign stats race"
    )
    assert "KL001" not in rules_of(lint_snippet(tmp_path, bad))


# ---------------------------------------------------------------------------
# KL002 lock ordering
# ---------------------------------------------------------------------------

TWO_LOCKS = """
    import threading

    class Two:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def m1(self):
            with self._a:
                with self._b:
                    pass

        def m2(self):
            with {second}:
                with {inner}:
                    pass
"""


def test_kl002_opposite_order_cycle_flagged(tmp_path):
    bad = TWO_LOCKS.format(second="self._b", inner="self._a")
    findings = [f for f in lint_snippet(tmp_path, bad) if f.rule == "KL002"]
    assert findings and "cycle" in findings[0].message


def test_kl002_consistent_order_clean(tmp_path):
    good = TWO_LOCKS.format(second="self._a", inner="self._b")
    assert "KL002" not in rules_of(lint_snippet(tmp_path, good))


def test_kl002_nested_nonreentrant_lock_flagged(tmp_path):
    src = """
    import threading

    class SelfDeadlock:
        def __init__(self):
            self._lock = threading.Lock()

        def m(self):
            with self._lock:
                with self._lock:
                    pass
    """
    findings = [f for f in lint_snippet(tmp_path, src) if f.rule == "KL002"]
    assert findings and "non-reentrant" in findings[0].message


def test_kl002_condition_aliases_to_its_shared_lock(tmp_path):
    # with self._cv: with self._lock: -- same underlying lock.
    src = """
    import threading

    class Aliased:
        def __init__(self):
            self._lock = threading.Lock()
            self._cv = threading.Condition(self._lock)

        def m(self):
            with self._cv:
                with self._lock:
                    pass
    """
    findings = [f for f in lint_snippet(tmp_path, src) if f.rule == "KL002"]
    assert findings and "non-reentrant" in findings[0].message


# ---------------------------------------------------------------------------
# KL008 lost wakeup (the PR-5 serving bug, pinned)
# ---------------------------------------------------------------------------

PR5_SHAPE = """
    import threading

    class Service:
        def __init__(self):
            self._lock = threading.Lock()
            self._cv = threading.Condition(self._lock)
            {extra_init}
            self._pending = []
            self._worker = threading.Thread(target=self._loop)
            self._completer = threading.Thread(target=self._complete_loop)

        def submit(self, x):
            with self._cv:
                self._pending.append(x)
                self._cv.notify()

        def _loop(self):
            with self._cv:
                while not self._pending:
                    self._cv.wait()

        def _complete_loop(self):
            with {completer_cv}:
                {completer_cv}.wait()
    """


def test_kl008_pr5_lost_wakeup_shape_is_flagged(tmp_path):
    # Pre-fix PR-5: dispatcher AND completer wait on ONE condition; a
    # submit notify() meant for the dispatcher can wake the completer
    # instead -> stranded request.
    bad = PR5_SHAPE.format(extra_init="pass", completer_cv="self._cv")
    findings = [f for f in lint_snippet(tmp_path, bad) if f.rule == "KL008"]
    assert findings
    assert "lost wakeup" in findings[0].message
    assert "_complete_loop" in findings[0].message
    assert "_loop" in findings[0].message


def test_kl008_per_waiter_conditions_fix_is_clean(tmp_path):
    # The PR-5 FIX: each waiter class gets its own Condition over the
    # shared lock. Distinct wait-sets -> notify() is safe again.
    good = PR5_SHAPE.format(
        extra_init="self._ccv = threading.Condition(self._lock)",
        completer_cv="self._ccv",
    )
    assert "KL008" not in rules_of(lint_snippet(tmp_path, good))


def test_kl008_notify_all_is_clean(tmp_path):
    bad = PR5_SHAPE.format(extra_init="pass", completer_cv="self._cv")
    good = bad.replace("self._cv.notify()", "self._cv.notify_all()")
    assert "KL008" not in rules_of(lint_snippet(tmp_path, good))


# ---------------------------------------------------------------------------
# KL003 env reads / KL004 resolve-once / KL005 wall-clock
# ---------------------------------------------------------------------------


def test_kl003_env_read_flagged_and_tag_suppresses(tmp_path):
    bad = """
    import os

    MODE = os.environ.get("SOME_KNOB", "x")
    OTHER = os.getenv("OTHER_KNOB")
    """
    assert rules_of(lint_snippet(tmp_path, bad)) == ["KL003"]
    tagged = bad.replace(
        'MODE = os.environ.get("SOME_KNOB", "x")',
        'MODE = os.environ.get("SOME_KNOB", "x")  # lint: ok(KL003) why',
    ).replace(
        'OTHER = os.getenv("OTHER_KNOB")',
        'OTHER = os.getenv("OTHER_KNOB")  # lint: ok(KL003) why',
    )
    assert "KL003" not in rules_of(lint_snippet(tmp_path, tagged))


def test_kl003_config_py_is_exempt():
    findings, _ = keystone_lint.scan(
        ["keystone_tpu/config.py"], root=REPO_ROOT
    )
    assert "KL003" not in rules_of(findings)


def test_kl004_resolve_in_loop_flagged_hoisted_clean(tmp_path):
    bad = """
    from keystone_tpu.utils.reliability import active_plan

    def stream(records):
        for r in records:
            plan = active_plan()
    """
    findings = [f for f in lint_snippet(tmp_path, bad) if f.rule == "KL004"]
    assert findings and "active_plan" in findings[0].message
    good = """
    from keystone_tpu.utils.metrics import active_tracer

    def stream(records):
        tracer = active_tracer()
        for r in records:
            pass
    """
    assert "KL004" not in rules_of(lint_snippet(tmp_path, good))


def test_kl004_nested_function_resets_loop_context(tmp_path):
    src = """
    from keystone_tpu.utils.reliability import active_plan

    def outer(items):
        for i in items:
            pass

        def helper():
            return active_plan()  # not in a loop at runtime
        return helper
    """
    # the def sits lexically after a loop but not inside one
    assert "KL004" not in rules_of(lint_snippet(tmp_path, src))


def test_kl005_time_time_flagged_perf_counter_clean(tmp_path):
    bad = """
    import time

    def timed():
        t0 = time.time()
        return time.time() - t0
    """
    assert len(
        [f for f in lint_snippet(tmp_path, bad) if f.rule == "KL005"]
    ) == 2
    good = bad.replace("time.time()", "time.perf_counter()")
    assert "KL005" not in rules_of(lint_snippet(tmp_path, good))


# ---------------------------------------------------------------------------
# KL006 broad except
# ---------------------------------------------------------------------------


def test_kl006_bare_broad_handler_flagged(tmp_path):
    bad = """
    def f():
        try:
            work()
        except Exception:
            return None
    """
    findings = [f for f in lint_snippet(tmp_path, bad) if f.rule == "KL006"]
    assert findings


@pytest.mark.parametrize(
    "body",
    [
        "raise",                                   # re-raise
        "raise RuntimeError('translated') from e", # translate + raise
        "if is_oom(e):\n                return None\n            raise",
        "return is_transient(e)",                  # reliability routing
    ],
)
def test_kl006_reraise_or_classification_passes(tmp_path, body):
    src = f"""
    from keystone_tpu.utils.reliability import is_oom, is_transient

    def f():
        try:
            work()
        except Exception as e:
            {body}
    """
    assert "KL006" not in rules_of(lint_snippet(tmp_path, src))


def test_kl006_broad_ok_tag_passes_and_base_exception_covered(tmp_path):
    src = """
    def f():
        try:
            work()
        except BaseException:  # lint: broad-ok surfaced on the consumer side
            return None
    """
    assert "KL006" not in rules_of(lint_snippet(tmp_path, src))
    untagged = src.replace("  # lint: broad-ok surfaced on the consumer side", "")
    assert "KL006" in rules_of(lint_snippet(tmp_path, untagged))


# ---------------------------------------------------------------------------
# KL007 dispatch-path host syncs
# ---------------------------------------------------------------------------


def test_kl007_host_sync_in_dispatch_flagged_completion_side_clean(tmp_path):
    bad = """
    import numpy as np

    class Service:
        def _dispatch(self, group):
            out = self.handle.block_until_ready()
            return np.asarray(out)

        def _complete_chunk(self, lc):
            return np.asarray(lc.out)  # completion side: allowed
    """
    findings = [f for f in lint_snippet(tmp_path, bad) if f.rule == "KL007"]
    assert len(findings) == 2  # both syncs in _dispatch, none in completion
    assert all("_dispatch" in f.message for f in findings)


# ---------------------------------------------------------------------------
# The live serving module + the repo gate
# ---------------------------------------------------------------------------


def test_live_serving_module_has_zero_concurrency_findings():
    """workflow/serving.py is the module these rules were written FOR:
    after the PR's fixes it must carry no lock-discipline, lock-order,
    lost-wakeup, or dispatch-sync findings at all."""
    findings, _ = keystone_lint.scan(
        ["keystone_tpu/workflow/serving.py"], root=REPO_ROOT
    )
    concurrency = [
        f for f in findings if f.rule in ("KL001", "KL002", "KL007", "KL008")
    ]
    assert not concurrency, [(f.rule, f.line, f.message) for f in concurrency]


def test_known_thread_targets_are_kl001_roots_without_visible_spawn(
    tmp_path,
):
    """The ISSUE-8 satellite: watchdog/flight-recorder thread targets are
    registered KL001 entry roots BY NAME — a `_watchdog_loop` that
    mutates shared state outside the lock is a finding even when no
    `Thread(target=...)` spawn is statically visible in the class
    (spawned via a helper or registry)."""
    assert "_watchdog_loop" in keystone_lint.KNOWN_THREAD_TARGETS
    bad = """
    import threading

    class Watched:
        def __init__(self):
            self._lock = threading.Lock()
            self.stalls = 0

        def submit(self, x):
            with self._lock:
                self.stalls = 0

        def _watchdog_loop(self):
            while True:
                self.stalls += 1
    """
    findings = [f for f in lint_snippet(tmp_path, bad) if f.rule == "KL001"]
    assert findings, "registered thread target not treated as a root"
    assert any(
        "_watchdog_loop" in f.message and "stalls" in f.message
        for f in findings
    )
    good = bad.replace(
        "self.stalls += 1",
        "with self._lock:\n                    self.stalls += 1",
    )
    assert "KL001" not in rules_of(lint_snippet(tmp_path, good))


def test_parallel_walk_worker_is_a_registered_kl001_root(tmp_path):
    """ISSUE-10 satellite: the executor's pool-worker entry point
    (`_run_node_worker`) is a KNOWN_THREAD_TARGETS root, so an unlocked
    write to scheduler-shared state (the values/pend/inflight dicts both
    the caller and the workers touch) is a KL001 finding — even though
    the spawn is a ``ThreadPoolExecutor.submit`` no ``Thread(target=)``
    makes statically visible."""
    assert "_run_node_worker" in keystone_lint.KNOWN_THREAD_TARGETS
    bad = """
    import threading

    class Walk:
        def __init__(self):
            self._lock = threading.Lock()
            self.values = {}
            self.inflight = 0

        def run(self, sources):
            with self._lock:
                for s in sources:
                    self.values[s] = s
                    self.inflight += 1

        def _run_node_worker(self, nid):
            out = nid * 2
            self.values[nid] = out
            self.inflight -= 1
    """
    findings = [f for f in lint_snippet(tmp_path, bad) if f.rule == "KL001"]
    assert findings, "_run_node_worker not treated as a KL001 root"
    assert any(
        "_run_node_worker" in f.message and "values" in f.message
        for f in findings
    )
    assert any("inflight" in f.message for f in findings)
    # The fix shape the live scheduler uses: the worker publishes through
    # a *_locked helper (caller-holds-the-lock convention) — clean.
    good = """
    import threading

    class Walk:
        def __init__(self):
            self._lock = threading.Lock()
            self.values = {}
            self.inflight = 0

        def run(self, sources):
            with self._lock:
                for s in sources:
                    self.values[s] = s
                    self.inflight += 1

        def _run_node_worker(self, nid):
            out = nid * 2
            with self._lock:
                self._publish_locked(nid, out)

        def _publish_locked(self, nid, out):
            self.values[nid] = out
            self.inflight -= 1
    """
    assert "KL001" not in rules_of(lint_snippet(tmp_path, good))


def test_live_executor_module_has_zero_concurrency_findings():
    """workflow/executor.py now hosts the parallel walk: it must carry
    no lock-discipline, lock-order, or lost-wakeup findings, and the
    worker method the lint registry names must actually exist on
    _ParallelWalk (a rename that silently unregisters the root is a
    failure here, not a blind spot)."""
    findings, _ = keystone_lint.scan(
        ["keystone_tpu/workflow/executor.py"], root=REPO_ROOT
    )
    concurrency = [
        f for f in findings if f.rule in ("KL001", "KL002", "KL007", "KL008")
    ]
    assert not concurrency, [(f.rule, f.line, f.message) for f in concurrency]
    import ast

    src_path = os.path.join(
        REPO_ROOT, "keystone_tpu", "workflow", "executor.py"
    )
    with open(src_path) as f:
        tree = ast.parse(f.read())
    walk = next(
        n for n in ast.walk(tree)
        if isinstance(n, ast.ClassDef) and n.name == "_ParallelWalk"
    )
    methods = {m.name for m in walk.body if isinstance(m, ast.FunctionDef)}
    assert "_run_node_worker" in methods
    assert "_run_node_worker" in keystone_lint.KNOWN_THREAD_TARGETS & methods


def test_watchdog_and_flight_recorder_lint_clean_live():
    """The new observability modules lint clean from day one: zero
    findings in utils/flight_recorder.py, zero NEW findings in the
    watchdog-bearing serving.py (the repo gate pins the baseline side;
    this pins the modules directly)."""
    findings, _ = keystone_lint.scan(
        ["keystone_tpu/utils/flight_recorder.py"], root=REPO_ROOT
    )
    assert not findings, [(f.rule, f.line, f.message) for f in findings]
    # And the live PipelineService really does register _watchdog_loop as
    # a root (via the visible spawn AND the name registry).
    import ast

    src_path = os.path.join(
        REPO_ROOT, "keystone_tpu", "workflow", "serving.py"
    )
    with open(src_path) as f:
        tree = ast.parse(f.read())
    svc = next(
        n for n in ast.walk(tree)
        if isinstance(n, ast.ClassDef) and n.name == "PipelineService"
    )
    methods = {m.name for m in svc.body if isinstance(m, ast.FunctionDef)}
    assert "_watchdog_loop" in methods
    assert "_watchdog_loop" in keystone_lint.KNOWN_THREAD_TARGETS & methods


def test_repo_gate_is_green_against_checked_in_baseline(capsys):
    """`make lint`'s AST half, in-process (the trace-demo idiom): the
    shipped tree + shipped baseline must produce zero NEW findings."""
    rc = keystone_lint.main([])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "0 new" in out


def test_baseline_entries_all_carry_a_reason():
    import json

    with open(os.path.join(TOOLS, "lint_baseline.json")) as f:
        doc = json.load(f)
    assert doc["entries"], "baseline exists to demonstrate the workflow"
    for e in doc["entries"]:
        assert e.get("why") and "TODO" not in e["why"], e


def test_new_violation_fails_the_gate(tmp_path):
    """Zero tolerance on NEW findings: a fresh violation in a scanned file
    is not absorbed by the baseline."""
    pkg = tmp_path / "keystone_tpu"
    pkg.mkdir()
    (pkg / "fresh.py").write_text(
        "import os\nKNOB = os.environ.get('NEW_KNOB')\n"
    )
    baseline_path = os.path.join(REPO_ROOT, "tools", "lint_baseline.json")
    rc = keystone_lint.main(
        ["keystone_tpu", "--root", str(tmp_path),
         "--baseline", baseline_path]
    )
    assert rc == 1


def test_baseline_matching_is_count_aware(tmp_path):
    (tmp_path / "m.py").write_text(
        "import os\nA = os.environ.get('K')\nA = os.environ.get('K')\n"
    )
    findings, keys = keystone_lint.scan([str(tmp_path / "m.py")],
                                        root=str(tmp_path))
    assert len(findings) == 2 and keys[0] == keys[1]
    one = {"entries": [{"key": keys[0], "why": "x"}]}
    fresh = keystone_lint.new_findings(findings, keys, one)
    assert len(fresh) == 1  # one budgeted, one new
    two = {"entries": [{"key": keys[0], "why": "x"}] * 2}
    assert not keystone_lint.new_findings(findings, keys, two)


def test_ast_rule_catalog_ids_match_severities():
    assert set(keystone_lint.AST_RULES) == set(keystone_lint.SEVERITY)
    assert len(keystone_lint.AST_RULES) >= 5  # the acceptance floor


def test_syntax_error_is_a_finding_not_a_crash(tmp_path):
    (tmp_path / "broken.py").write_text("def f(:\n")
    findings, keys = keystone_lint.scan([str(tmp_path / "broken.py")],
                                        root=str(tmp_path))
    assert rules_of(findings) == ["KL000"]
    assert findings[0].severity == "error"  # must not KeyError
    assert findings[0].as_dict()["severity"] == "error"


def test_nonexistent_scan_path_fails_loudly(tmp_path, capsys):
    """A misspelled path must not make the zero-tolerance gate pass
    vacuously: scan() raises, the CLI exits 2."""
    with pytest.raises(FileNotFoundError):
        keystone_lint.scan(["no_such_dir"], root=str(tmp_path))
    rc = keystone_lint.main(["no_such_dir", "--root", str(tmp_path)])
    assert rc == 2
    assert "does not exist" in capsys.readouterr().err
