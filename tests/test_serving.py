"""Shape-stable serving tests: bucket ladder math, padded-vs-unpadded
bit-equivalence, zero post-warmup compiles over a mixed-size trace,
row-dependence refusal, batch_call wiring, and the micro-batcher.

The compile-count assertions are backed two ways: the serving layer's own
counters AND a jax.monitoring listener on XLA compile-cache requests (one
event per backend compile), so a silent recompile on the hot path cannot
hide.
"""

import threading

import jax
import numpy as np
import pytest

from keystone_tpu.config import config, pow2_ladder
from keystone_tpu.nodes.learning.linear_mapper import LinearMapper
from keystone_tpu.nodes.stats.hellinger import SignedHellingerMapper
from keystone_tpu.nodes.stats.normalizer import L2Normalizer
from keystone_tpu.nodes.stats.random_features import CosineRandomFeatures
from keystone_tpu.nodes.stats.scalers import StandardScaler, StandardScalerModel
from keystone_tpu.utils.metrics import CompileEventCounter, serving_counters
from keystone_tpu.workflow import (
    CompiledPipeline,
    PipelineService,
    RowDependenceError,
    Transformer,
)
from keystone_tpu.workflow.pipeline import FusedTransformer
from keystone_tpu.workflow.serving import (
    bucket_for,
    bucketed_call,
    resolve_ladder,
)


@pytest.fixture(autouse=True)
def serve_config():
    """Isolate the process-wide serving knobs and counters per test."""
    prior = (config.serve_buckets, config.serve_max_batch)
    serving_counters.reset()
    yield
    config.serve_buckets, config.serve_max_batch = prior
    serving_counters.reset()


# The compile oracle shared with tools/bench_serve.py — one listener per
# process (registration is global and permanent).
_compile_events = CompileEventCounter()


def _head(d=8, D=16, k=3, seed=0):
    """A canonical fused serving head (the TIMIT/CIFAR-style apply tail)."""
    rng = np.random.default_rng(seed)
    return FusedTransformer(
        [
            StandardScalerModel(
                rng.normal(size=d).astype(np.float32),
                (1.0 + rng.uniform(size=d)).astype(np.float32),
            ),
            CosineRandomFeatures.create(d, D, seed=seed),
            SignedHellingerMapper(),
            L2Normalizer(),
            LinearMapper(rng.normal(size=(D, k)).astype(np.float32)),
        ]
    )


class RowMean(Transformer):
    """Batch output depends on other rows: padding must be refused."""

    row_independent = False

    def apply_batch(self, X):
        return X - X.mean(axis=0)


# ---------------------------------------------------------------------------
# Ladder math
# ---------------------------------------------------------------------------


def test_pow2_ladder():
    assert pow2_ladder(8) == (1, 2, 4, 8)
    assert pow2_ladder(1) == (1,)
    # Non-pow2 top: the max batch itself always serves as the top bucket.
    assert pow2_ladder(100) == (1, 2, 4, 8, 16, 32, 64, 100)
    with pytest.raises(ValueError):
        pow2_ladder(0)


def test_bucket_for_boundaries():
    ladder = (1, 2, 4, 8)
    assert bucket_for(1, ladder) == 1
    assert bucket_for(3, ladder) == 4
    assert bucket_for(8, ladder) == 8
    assert bucket_for(9, ladder) is None  # oversize: caller chunks


def test_resolve_ladder_precedence():
    config.serve_buckets = (4, 16)
    assert resolve_ladder() == (4, 16)
    assert resolve_ladder(buckets=(2, 8)) == (2, 8)
    config.serve_buckets = ()
    config.serve_max_batch = 8
    assert resolve_ladder() == (1, 2, 4, 8)
    # An explicit max extends/clips the explicit ladder.
    assert resolve_ladder(buckets=(2, 64), max_batch=8) == (2, 8)


# ---------------------------------------------------------------------------
# CompiledPipeline: equivalence + compile discipline
# ---------------------------------------------------------------------------


def test_padded_bit_equivalence_canonical_chains(rng):
    """Mask-safety, at the bit level: at a FIXED bucket shape, the pad
    rows must be provably inert — real rows come out bit-identical no
    matter what the padding contains (last-row replication, zeros, or
    garbage). This is the property that makes bucket-padding sound; it
    holds exactly, unlike cross-batch-size comparisons where CPU gemm
    vectorization can differ in the last ulp."""
    d = 8
    chains = [
        _head(d=d),
        FusedTransformer([SignedHellingerMapper(), L2Normalizer()]),
        FusedTransformer(
            [
                CosineRandomFeatures.create(d, 12, seed=3),
                LinearMapper(rng.normal(size=(12, 2)).astype(np.float32)),
            ]
        ),
    ]
    for chain in chains:
        cp = CompiledPipeline(chain, max_batch=32).warmup((d,))
        jitted = jax.jit(chain.apply_batch)
        for n in (1, 3, 5, 9, 17, 31):
            X = rng.normal(size=(n, d)).astype(np.float32)
            b = bucket_for(n, cp.ladder)
            pads = [
                np.broadcast_to(X[-1:], (b - n, d)),
                np.zeros((b - n, d), np.float32),
                rng.normal(size=(b - n, d)).astype(np.float32) * 100,
            ]
            outs = [
                np.asarray(jitted(np.concatenate([X, p])))[:n] for p in pads
            ]
            assert np.array_equal(outs[0], outs[1])
            assert np.array_equal(outs[0], outs[2])
            # The serving engine returns exactly the fixed-shape program's
            # real rows...
            assert np.array_equal(cp(X), outs[0])
            # ...and matches the per-shape jit at the exact size to float
            # tolerance (bit-equal is not guaranteed across gemm shapes).
            np.testing.assert_allclose(
                cp(X), np.asarray(jitted(X)), rtol=2e-6, atol=2e-6
            )


def test_zero_compiles_after_warmup_on_mixed_trace(rng):
    """A warmed CompiledPipeline performs ZERO new XLA compiles over a
    50-request mixed-size trace (the acceptance gate), measured at the
    monitoring layer, the serving counters, and the engine's own count."""
    d = 8
    cp = CompiledPipeline(_head(d=d), max_batch=32).warmup((d,))
    warm_compiles = cp.compile_count
    # One ladder per replica: warmup covers the whole pool.
    assert warm_compiles == len(cp.ladder) * len(cp.replicas)
    ev0 = _compile_events.count
    c0 = serving_counters.snapshot()["compiles"]
    sizes = rng.integers(1, 33, size=50)
    for n in sizes:
        out = cp(rng.normal(size=(int(n), d)).astype(np.float32))
        assert out.shape == (int(n), 3)
    assert cp.compile_count == warm_compiles
    assert serving_counters.snapshot()["compiles"] == c0
    assert _compile_events.count == ev0
    hits = serving_counters.snapshot()["bucket_hits"]
    assert sum(hits.values()) == 50
    assert set(hits) <= set(cp.ladder)


def test_warmup_idempotent_and_cold_bucket_counted(rng):
    d = 4
    cp = CompiledPipeline(_head(d=d), max_batch=8)
    cp.warmup((d,))
    n = cp.compile_count
    cp.warmup((d,))  # no-op: every bucket already compiled
    assert cp.compile_count == n

    # A never-warmed engine warms the whole ladder (on every replica) off
    # the first request's signature (correct, but first-traffic latency
    # pays the ladder).
    cold = CompiledPipeline(_head(d=d, seed=1), max_batch=8)
    cold(rng.normal(size=(3, d)).astype(np.float32))
    assert cold.compile_count == len(cold.ladder) * len(cold.replicas)

    # Re-warming a shape-polymorphic chain for a NEW traffic signature
    # drops the stale executables and recompiles the ladder.
    poly = CompiledPipeline(
        FusedTransformer([SignedHellingerMapper(), L2Normalizer()]),
        max_batch=8,
    ).warmup((d,))
    n_poly = poly.compile_count
    poly.warmup((d + 2,))
    assert poly.compile_count == 2 * n_poly
    out = poly(np.ones((3, d + 2), np.float32))
    assert out.shape == (3, d + 2)


def test_oversize_batch_chunks_through_top_bucket(rng):
    d = 4
    cp = CompiledPipeline(_head(d=d), max_batch=8).warmup((d,))
    X = rng.normal(size=(21, d)).astype(np.float32)
    out = cp(X)
    assert out.shape == (21, 3)
    oracle = jax.jit(cp.transformer.apply_batch)
    np.testing.assert_allclose(
        out, np.asarray(oracle(X)), rtol=1e-6, atol=1e-6
    )


def test_feature_shape_mismatch_and_empty_batch(rng):
    d = 4
    cp = CompiledPipeline(_head(d=d), max_batch=8).warmup((d,))
    with pytest.raises(ValueError, match="feature shape"):
        cp(rng.normal(size=(3, d + 1)).astype(np.float32))
    with pytest.raises(ValueError, match="empty"):
        cp(np.zeros((0, d), np.float32))


def test_compiled_pipeline_from_fitted_estimator_pipeline(rng):
    """Pipeline.compiled() fits estimators, fuses the chain, and serves
    numerically-identical results to graph execution."""
    d = 6
    Xtrain = rng.normal(size=(32, d)).astype(np.float32)
    pipe = StandardScaler().with_data(Xtrain).and_then(L2Normalizer())
    cp = pipe.compiled(max_batch=16).warmup((d,))
    X = rng.normal(size=(5, d)).astype(np.float32)
    np.testing.assert_allclose(
        cp(X), np.asarray(pipe(X).get()), rtol=1e-6, atol=1e-6
    )


def test_serving_refuses_nonlinear_and_host_chains(rng):
    from keystone_tpu.workflow import Pipeline

    class HostOp(Transformer):
        jittable = False

        def apply_batch(self, X):
            return X

    with pytest.raises(TypeError, match="jittable"):
        CompiledPipeline(HostOp())
    gathered = Pipeline.gather([L2Normalizer(), SignedHellingerMapper()])
    with pytest.raises(TypeError, match="linear"):
        gathered.compiled()


# ---------------------------------------------------------------------------
# Row dependence
# ---------------------------------------------------------------------------


def test_row_dependent_refused_on_compiled_path():
    with pytest.raises(RowDependenceError, match="RowMean"):
        CompiledPipeline(RowMean())
    with pytest.raises(RowDependenceError, match="RowMean"):
        CompiledPipeline(FusedTransformer([L2Normalizer(), RowMean()]))


def test_row_dependent_falls_back_on_bucketed_batch_call(rng, caplog):
    """The process-wide knob must never crash a working pipeline: a
    row-coupled transformer is served per-shape (padding refused) with a
    one-time warning instead."""
    import logging

    from keystone_tpu.workflow import serving

    serving._fallback_warned.clear()
    config.serve_buckets = (4, 8)
    t = RowMean()
    X = rng.normal(size=(3, 4)).astype(np.float32)
    with caplog.at_level(logging.WARNING, logger="keystone_tpu"):
        got = np.asarray(t.batch_call(X))
    np.testing.assert_allclose(got, X - X.mean(axis=0), rtol=1e-6, atol=1e-6)
    assert any("RowMean" in r.message for r in caplog.records)
    # No padded/bucketed call was recorded for it.
    assert serving_counters.snapshot()["calls"] == 0


def test_row_dependence_flags_on_patch_nodes():
    from keystone_tpu.nodes.images.patches import (
        CenterCornerPatcher,
        RandomPatcher,
        Windower,
    )

    assert not Windower(1, 2).row_independent
    assert not CenterCornerPatcher(2).row_independent
    assert not RandomPatcher(4, 2).row_independent
    assert L2Normalizer().row_independent
    fused = FusedTransformer([L2Normalizer(), Windower(1, 2)])
    assert not fused.row_independent


# ---------------------------------------------------------------------------
# batch_call wiring (config.serve_buckets)
# ---------------------------------------------------------------------------


def test_batch_call_bucketing_matches_pershape_jit(rng):
    d = 8
    chain = _head(d=d)
    oracle = jax.jit(_head(d=d).apply_batch)  # fresh twin, per-shape jit
    config.serve_buckets = (4, 8, 16)
    for n in (1, 3, 6, 13, 16):
        X = rng.normal(size=(n, d)).astype(np.float32)
        got = np.asarray(chain.batch_call(X))
        np.testing.assert_allclose(
            got, np.asarray(oracle(X)), rtol=2e-6, atol=2e-6
        )
    # The jit cache is bounded by the ladder, not the request mix.
    from keystone_tpu.workflow.serving import _jit_cache_size

    assert _jit_cache_size(chain._jitted()) <= 3


def test_batch_call_bucketing_oversize_chunks(rng):
    d = 4
    chain = FusedTransformer([SignedHellingerMapper(), L2Normalizer()])
    config.serve_buckets = (4,)
    X = rng.normal(size=(11, d)).astype(np.float32)
    got = np.asarray(chain.batch_call(X))
    ref = np.asarray(jax.jit(chain.apply_batch)(X))
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)
    assert got.shape == ref.shape


def test_batch_call_disabled_ladder_is_pershape(rng):
    config.serve_buckets = ()
    chain = FusedTransformer([SignedHellingerMapper(), L2Normalizer()])
    for n in (3, 5):
        chain.batch_call(rng.normal(size=(n, 4)).astype(np.float32))
    assert serving_counters.snapshot()["calls"] == 0  # bucketing untouched


# ---------------------------------------------------------------------------
# PipelineService micro-batcher
# ---------------------------------------------------------------------------


def test_service_coalesces_and_matches_direct(rng):
    d = 8
    cp = CompiledPipeline(_head(d=d), max_batch=32).warmup((d,))
    rows = [rng.normal(size=(d,)).astype(np.float32) for _ in range(12)]
    batch = rng.normal(size=(5, d)).astype(np.float32)
    with PipelineService(cp, max_delay_ms=20.0) as svc:
        futs = [svc.submit(r) for r in rows]
        bfut = svc.submit(batch)
        outs = [f.result(timeout=30) for f in futs]
        bout = bfut.result(timeout=30)
    for r, o in zip(rows, outs):
        assert o.shape == (3,)
        # Coalescing serves the row inside a larger bucket: identical to a
        # solo call up to gemm-shape vectorization (last-ulp) differences.
        np.testing.assert_allclose(o, cp(r[None])[0], rtol=2e-6, atol=2e-6)
    np.testing.assert_allclose(bout, cp(batch), rtol=2e-6, atol=2e-6)
    stats = svc.stats()
    assert stats["requests"] == 13
    assert stats["rows_served"] == 17
    assert 1 <= stats["batches_run"] <= 13


def test_service_concurrent_clients(rng):
    d = 4
    cp = CompiledPipeline(_head(d=d), max_batch=16).warmup((d,))
    results, lock = {}, threading.Lock()

    def client(cid):
        crng = np.random.default_rng(cid)
        x = crng.normal(size=(d,)).astype(np.float32)
        out = svc.submit(x).result(timeout=30)
        with lock:
            results[cid] = (x, out)

    with PipelineService(cp, max_delay_ms=5.0) as svc:
        threads = [
            threading.Thread(target=client, args=(c,)) for c in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert len(results) == 8
    for x, out in results.values():
        np.testing.assert_allclose(out, cp(x[None])[0], rtol=2e-6, atol=2e-6)


def test_service_requires_warmup_and_rejects_after_close(rng):
    d = 4
    cold = CompiledPipeline(_head(d=d), max_batch=8)
    with pytest.raises(RuntimeError, match="warm"):
        PipelineService(cold)
    svc = PipelineService(cold.warmup((d,)))
    svc.close()
    with pytest.raises(RuntimeError, match="closed"):
        svc.submit(np.zeros(d, np.float32))


def test_service_shape_mismatch_raises_at_submit(rng):
    d = 4
    cp = CompiledPipeline(_head(d=d), max_batch=8).warmup((d,))
    with PipelineService(cp) as svc:
        with pytest.raises(ValueError, match="shape"):
            svc.submit(np.zeros((2, d + 1), np.float32))


# ---------------------------------------------------------------------------
# Satellites: metrics + cache memo
# ---------------------------------------------------------------------------


def test_achieved_tflops_compiles_once():
    from keystone_tpu.utils.metrics import achieved_tflops

    W = np.ones((8, 8), np.float32)
    ev0 = _compile_events.count
    out = achieved_tflops(lambda x: x @ W, np.ones((4, 8), np.float32))
    assert _compile_events.count - ev0 == 1  # one lowered/compiled object
    assert out["flops"] > 0
    assert out["seconds"] > 0


def test_flops_ratio_memo_fifo_bounded():
    from keystone_tpu.workflow import cache as wcache

    wcache._flops_ratio_memo.clear()
    for i in range(wcache._FLOPS_MEMO_CAP):
        wcache._flops_ratio_memo[("sentinel", i)] = 1.0
    t = L2Normalizer()
    ratio = wcache.Profiler._flops_ratio(
        t, np.ones((4, 4), np.float32), 8.0
    )
    assert ratio is not None
    assert len(wcache._flops_ratio_memo) <= wcache._FLOPS_MEMO_CAP
    # FIFO: the oldest sentinel went first, the fresh key is present.
    assert ("sentinel", 0) not in wcache._flops_ratio_memo
    assert ("sentinel", 1) in wcache._flops_ratio_memo
