"""Test fixture: a virtual 8-device CPU mesh.

The reference tests all "distributed" logic on a local[n] SparkContext
(Ref: src/test/scala shared LocalSparkContext trait [unverified]); our analog
is XLA's forced host-platform device count — the same collective code paths
run on 8 fake CPU devices as on a TPU pod slice.
"""

import os

# XLA_FLAGS must be in the env before the CPU backend initializes.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# The axon sitecustomize force-registers the TPU platform ignoring
# JAX_PLATFORMS; overriding the config after import is the reliable switch.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

assert len(jax.devices()) == 8, f"expected 8 CPU devices, got {jax.devices()}"


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running test")


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(autouse=True)
def fresh_env():
    """Fresh PipelineEnv per test — the analog of a fresh SparkContext.
    The default-mesh memo resets too, so a test that installed a narrow
    mesh via ``set_default_mesh`` (fake device counts) can never leak a
    memoized 1-device mesh into a later 8-device test."""
    from keystone_tpu.utils.mesh import reset_default_mesh
    from keystone_tpu.workflow.executor import PipelineEnv

    PipelineEnv.reset()
    reset_default_mesh()
    yield
    PipelineEnv.reset()
    reset_default_mesh()
