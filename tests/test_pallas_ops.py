"""Pallas kernel tests (interpreter mode on the CPU mesh; same code lowers
through Mosaic on TPU)."""

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.nodes.images.external.fisher_vector import FisherVector, _fv_tpu
from keystone_tpu.ops import fisher_vectors_pallas


@pytest.fixture
def gmm(rng):
    k, d = 4, 8
    w = rng.uniform(0.1, 1.0, size=k).astype(np.float32)
    w /= w.sum()
    mu = rng.normal(size=(k, d)).astype(np.float32)
    var = rng.uniform(0.5, 1.5, size=(k, d)).astype(np.float32)
    return w, mu, var


def test_pallas_fv_matches_xla(rng, gmm):
    w, mu, var = gmm
    X = rng.normal(size=(3, 100, 8)).astype(np.float32)
    out_p = np.asarray(fisher_vectors_pallas(X, w, mu, var, tile_m=32))
    out_x = np.asarray(
        _fv_tpu(jnp.asarray(X), jnp.asarray(w), jnp.asarray(mu), jnp.asarray(var))
    )
    # 100 % 32 != 0: the padded-tile mask path is exercised.
    np.testing.assert_allclose(out_p, out_x, rtol=1e-4, atol=1e-5)


def test_pallas_fv_tile_size_invariance(rng, gmm):
    w, mu, var = gmm
    X = rng.normal(size=(2, 64, 8)).astype(np.float32)
    a = np.asarray(fisher_vectors_pallas(X, w, mu, var, tile_m=16))
    b = np.asarray(fisher_vectors_pallas(X, w, mu, var, tile_m=64))
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_fisher_vector_node_pallas_backend(rng, gmm):
    w, mu, var = gmm
    X = rng.normal(size=(2, 50, 8)).astype(np.float32)
    node_p = FisherVector(w, mu, var, backend="pallas")
    node_t = FisherVector(w, mu, var, backend="tpu")
    np.testing.assert_allclose(
        np.asarray(node_p(X)), np.asarray(node_t(X)), rtol=1e-4, atol=1e-5
    )
    assert node_p.jittable


def test_pallas_fv_zero_weight_component(rng):
    # A starved component must produce a zero block, not NaNs (same clamp
    # as the other backends).
    k, d = 3, 4
    w = np.array([0.5, 0.5, 0.0], dtype=np.float32)
    mu = rng.normal(size=(k, d)).astype(np.float32)
    var = np.ones((k, d), dtype=np.float32)
    X = rng.normal(size=(1, 40, d)).astype(np.float32)
    out = np.asarray(fisher_vectors_pallas(X, w, mu, var))
    assert np.isfinite(out).all()
