"""Image node tests vs direct NumPy loops (the reference's ConvolverSuite
strategy: compare against naive convolution; SURVEY.md §4)."""

import numpy as np

from keystone_tpu.nodes.images import (
    CenterCornerPatcher,
    Convolver,
    GrayScaler,
    ImageVectorizer,
    PixelScaler,
    Pooler,
    RandomPatcher,
    SymmetricRectifier,
    Windower,
)
from keystone_tpu.nodes.learning import ZCAWhitenerEstimator
from keystone_tpu.utils.image import grayscale, metadata_of


def _naive_conv(X, F):
    n, h, w, c = X.shape
    nf, fh, fw, _ = F.shape
    oh, ow = h - fh + 1, w - fw + 1
    out = np.zeros((n, oh, ow, nf), dtype=np.float64)
    for i in range(oh):
        for j in range(ow):
            patch = X[:, i : i + fh, j : j + fw, :].reshape(n, -1)
            out[:, i, j, :] = patch @ F.reshape(nf, -1).T
    return out


def test_convolver_matches_naive(rng):
    X = rng.normal(size=(2, 8, 8, 3)).astype(np.float32)
    F = rng.normal(size=(4, 3, 3, 3)).astype(np.float32)
    out = np.asarray(Convolver(F)(X))
    np.testing.assert_allclose(out, _naive_conv(X, F), rtol=1e-4, atol=1e-4)


def test_convolver_with_whitener_matches_explicit(rng):
    X = rng.normal(size=(2, 8, 8, 3)).astype(np.float32)
    patches = rng.normal(size=(500, 27)).astype(np.float32)
    whitener = ZCAWhitenerEstimator(eps=0.1).fit(patches)
    F = rng.normal(size=(4, 3, 3, 3)).astype(np.float32)
    out = np.asarray(Convolver(F, whitener=whitener)(X))
    # Explicit: whiten each patch, then dot with raw filters.
    M = np.asarray(whitener.whitener)
    mu = np.asarray(whitener.mean)
    n, h, w, c = X.shape
    flat_f = F.reshape(4, -1)
    expected = np.zeros((n, 6, 6, 4))
    for i in range(6):
        for j in range(6):
            patch = X[:, i : i + 3, j : j + 3, :].reshape(n, -1)
            expected[:, i, j, :] = ((patch - mu) @ M) @ flat_f.T
    np.testing.assert_allclose(out, expected, rtol=1e-3, atol=1e-3)


def test_symmetric_rectifier():
    X = np.array([[[[1.0, -2.0]]]], dtype=np.float32)
    out = np.asarray(SymmetricRectifier(alpha=0.5)(X))
    np.testing.assert_allclose(out[0, 0, 0], [0.5, 0.0, 0.0, 1.5])


def test_pooler_modes(rng):
    X = rng.normal(size=(1, 4, 4, 2)).astype(np.float32)
    s = np.asarray(Pooler(2, 2, "sum")(X))
    m = np.asarray(Pooler(2, 2, "mean")(X))
    mx = np.asarray(Pooler(2, 2, "max")(X))
    block = X[0, :2, :2, 0]
    np.testing.assert_allclose(s[0, 0, 0, 0], block.sum(), rtol=1e-5)
    np.testing.assert_allclose(m[0, 0, 0, 0], block.mean(), rtol=1e-5)
    np.testing.assert_allclose(mx[0, 0, 0, 0], block.max(), rtol=1e-5)
    assert s.shape == (1, 2, 2, 2)


def test_random_patcher_shapes_and_determinism(rng):
    X = rng.normal(size=(4, 16, 16, 3)).astype(np.float32)
    a = np.asarray(RandomPatcher(32, 5, seed=7)(X))
    b = np.asarray(RandomPatcher(32, 5, seed=7)(X))
    assert a.shape == (32, 5, 5, 3)
    np.testing.assert_array_equal(a, b)


def test_windower_matches_direct(rng):
    X = rng.normal(size=(2, 6, 6, 1)).astype(np.float32)
    wins = np.asarray(Windower(2, 3)(X))
    assert wins.shape == (2 * 2 * 2, 3, 3, 1)
    np.testing.assert_allclose(wins[0], X[0, :3, :3, :], atol=1e-6)
    # second window of first image: rows 0-2, cols 2-4
    np.testing.assert_allclose(wins[1], X[0, :3, 2:5, :], atol=1e-6)


def test_center_corner_patcher(rng):
    X = rng.normal(size=(2, 8, 8, 3)).astype(np.float32)
    node = CenterCornerPatcher(crop_size=4, with_flips=True)
    out = np.asarray(node(X))
    assert out.shape == (2 * 10, 4, 4, 3)
    np.testing.assert_allclose(out[0], X[0, :4, :4, :], atol=1e-6)
    # flipped top-left crop of image 0 is view index 5 (width axis reversed)
    np.testing.assert_allclose(out[5], X[0, :4, :4, :][:, ::-1, :], atol=1e-6)


def test_pixel_nodes(rng):
    X = (rng.uniform(0, 255, size=(2, 4, 4, 3))).astype(np.float32)
    scaled = np.asarray(PixelScaler()(X))
    assert scaled.max() <= 1.0
    g = np.asarray(GrayScaler()(X))
    assert g.shape == (2, 4, 4, 1)
    v = np.asarray(ImageVectorizer()(X))
    assert v.shape == (2, 48)
    assert metadata_of(X).num_pixels == 48
