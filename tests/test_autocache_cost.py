"""XLA cost-model integration in the auto-cache rule (SURVEY.md §7 hard
part 5: the profiler's linear row extrapolation mis-costs non-linear
stages; compiled FLOP counts fix the ranking)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.workflow import Transformer
from keystone_tpu.workflow.cache import CacheOperator, NodeProfile, Profiler
from keystone_tpu.workflow.graph import Graph
from keystone_tpu.workflow.operators import DatasetOperator, TransformerOperator
from keystone_tpu.workflow.rules import AutoCacheRule


class Linear(Transformer):
    """O(n) in rows — linear extrapolation is exact for this."""

    def apply_batch(self, X):
        return X * 2.0 + 1.0


class Quadratic(Transformer):
    """O(n²) in rows (gram against the whole batch): the stage class the
    sample profiler under-costs by the row ratio."""

    def apply_batch(self, X):
        return (X @ X.T) @ X


def _graph(n=1024, d=16):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, d)).astype(np.float32)
    g = Graph()
    g, data = g.add(DatasetOperator(X), [])
    g, q = g.add(TransformerOperator(Quadratic()), [data])
    g, l = g.add(TransformerOperator(Linear()), [q])
    g, sink = g.add(TransformerOperator(Linear()), [l])
    return g, data, q, l, sink


def test_flops_ratio_counts_quadratic_stages():
    g, data, q, l, sink = _graph(n=1024)
    profiles = Profiler(sample_rows=64).profile(g, [sink])
    scale = profiles[q].scale
    assert scale == pytest.approx(16.0)
    # Quadratic stage: FLOPs grow ~scale², so the XLA-counted ratio must be
    # far above the row ratio; the linear stage must sit at ~scale.
    assert profiles[q].flops_ratio == pytest.approx(scale**2, rel=0.1)
    assert profiles[l].flops_ratio == pytest.approx(scale, rel=0.1)
    assert profiles[q].time_scale > 10 * profiles[l].time_scale


def test_compiled_flops_flip_the_caching_decision(monkeypatch):
    """The VERDICT regression: a budget that fits ONE cached value, a
    quadratic node whose sampled seconds look cheaper than a linear node's.
    Linear extrapolation picks the wrong node; the FLOPs ratio corrects it."""
    g, data, q, l, sink = _graph()
    nbytes = 1000

    def fake_profile(self, graph, targets):
        return {
            # Quadratic node: fast on the sample (0.5ms) but ratio 256.
            q: NodeProfile(seconds=5e-4, bytes=nbytes, scale=16.0,
                           flops_ratio=256.0),
            # Linear node: slower on the sample (2ms), honest ratio 16.
            l: NodeProfile(seconds=2e-3, bytes=nbytes, scale=16.0,
                           flops_ratio=16.0),
        }

    def cached_nodes(graph):
        out = set()
        for nid, op in graph.operators.items():
            if isinstance(op, CacheOperator):
                out.add(graph.dependencies[nid][0])
        return out

    monkeypatch.setattr(Profiler, "profile", fake_profile)
    # Budget fits exactly one full-size value (est_bytes = bytes * scale).
    rule = AutoCacheRule(budget_bytes=nbytes * 16, min_consumers=1)
    got = cached_nodes(rule.apply(g, [sink]))
    # Full-size truth: q costs 0.5ms*256 = 128ms, l costs 2ms*16 = 32ms.
    assert got == {q}

    # Strip the FLOPs info: linear extrapolation ranks l first (2ms*16=32ms
    # vs q's 0.5ms*16=8ms) — the wrong call the cost model exists to fix.
    def fake_profile_linear(self, graph, targets):
        return {
            q: NodeProfile(seconds=5e-4, bytes=nbytes, scale=16.0),
            l: NodeProfile(seconds=2e-3, bytes=nbytes, scale=16.0),
        }

    monkeypatch.setattr(Profiler, "profile", fake_profile_linear)
    got = cached_nodes(rule.apply(g, [sink]))
    assert got == {l}


def test_device_hbm_budget_reports_positive():
    from keystone_tpu.utils.metrics import device_hbm_bytes

    assert device_hbm_bytes() > 0


def test_device_hbm_budget_default_on_unreportable(monkeypatch):
    def boom():
        raise RuntimeError("no backend")

    monkeypatch.setattr(jax, "local_devices", boom)
    from keystone_tpu.utils.metrics import device_hbm_bytes

    assert device_hbm_bytes(default=123) == 123


def test_auto_cache_flag_works_after_env_creation(monkeypatch):
    """Flipping config.auto_cache mid-session must take effect — the rule
    is installed unconditionally and gated per apply."""
    from keystone_tpu.config import config
    from keystone_tpu.workflow import PipelineEnv

    # (conftest's autouse fresh_env fixture resets PipelineEnv around every
    # test, so no explicit cleanup is needed even on assertion failure.)
    g, data, q, l, sink = _graph(n=256)
    env = PipelineEnv.get()  # constructed while auto_cache is False
    out_off = env.optimizer.execute(g, [sink])
    assert not any(
        isinstance(op, CacheOperator) for op in out_off.operators.values()
    )
    monkeypatch.setattr(config, "auto_cache", True)
    out_on = env.optimizer.execute(g, [sink])  # same env, flag now on
    assert any(
        isinstance(op, CacheOperator) for op in out_on.operators.values()
    )


def test_zero_budget_caches_nothing(monkeypatch):
    g, data, q, l, sink = _graph()

    def fake_profile(self, graph, targets):
        return {q: NodeProfile(seconds=1e-3, bytes=100, scale=16.0)}

    monkeypatch.setattr(Profiler, "profile", fake_profile)
    got = AutoCacheRule(budget_bytes=0).apply(g, [sink])
    assert not any(
        isinstance(op, CacheOperator) for op in got.operators.values()
    )
