"""Learned serving-capacity model (ISSUE 20): prediction oracle,
predicted-deadline admission on both wires, deadline-aware cross-tenant
micro-batching, traffic-aware autoscaling, and the cold/persistence
contracts.

Acceptance pins:

- **Prediction oracle**: ``predict_completion_ms`` against hand-computed
  values — effective flush bucket (``eff = min(max_rows, rows + depth)``,
  ``b = max(bucket, eff)``), nearest-rung row-ratio scaling, the
  observed rows-per-flush drain rate, the ``ADMIT_Q`` quantile, and the
  signed prediction-bias EWMA.
- **Refusal wire contract**: a warm model that predicts past the
  caller's deadline answers a counted 429 ``predicted_infeasible``
  BEFORE any device work, on the framed socket AND the HTTP wire, with
  the caller's trace id echoed; the refused admission slot is released.
- **Strict-accuracy guard**: refusals re-validate once the evidence
  doubles (the ``check_at`` watermark), at the recorded effective bucket
  and the refusal-time bias — a refusal the matured model calls feasible
  is a counted violation; a consistent one is not.
- **Cold = bit-identical no-op**: below ``min_samples`` every consumer
  no-ops (counted); ``KEYSTONE_CAPACITY_MODEL=0`` builds no model at all
  and /stats reports ``{"enabled": False}``.
- **Micro-batching**: riders fill a gold group's padding slack only when
  tier, slack, and both deadlines allow; skipped requests keep FIFO
  order; everything is counted and journey-attributed.
- **Autoscale re-plan**: a mix shift past the threshold executes and
  decision-logs a re-plan; a second shift inside the no-flap window is
  refused and counted.
- **Persistence**: snapshot/restore round-trips (fill and bias
  included); corrupt snapshots are refused untouched; the telemetry-dir
  loader prefers the newest snapshot and falls back to journey replay.
"""

import json
import math
import os
import sys
import time

import numpy as np
import pytest

from keystone_tpu.config import config, resolved_capacity_model
from keystone_tpu.utils.metrics import capacity_counters
from keystone_tpu.workflow.capacity import (
    ADMIT_Q,
    CapacityModel,
    load_capacity_model,
)
from keystone_tpu.workflow.daemon import ServingDaemon, Tenant
from keystone_tpu.workflow.serialization import save_artifact

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")

D = 6


@pytest.fixture(autouse=True)
def capacity_env(monkeypatch):
    """Isolate the capacity knobs and counters per test: model off by
    default (tests opt in), fast warmup, counters reset both sides."""
    monkeypatch.delenv("KEYSTONE_CAPACITY_MODEL", raising=False)
    monkeypatch.delenv("KEYSTONE_TELEMETRY_DIR", raising=False)
    prior = (config.capacity_min_samples, config.capacity_replan_s,
             config.telemetry_dir)
    config.telemetry_dir = None
    capacity_counters.reset()
    yield
    (config.capacity_min_samples, config.capacity_replan_s,
     config.telemetry_dir) = prior
    capacity_counters.reset()


def _serve_daemon_mod():
    sys.path.insert(0, TOOLS)
    try:
        import serve_daemon
    finally:
        sys.path.pop(0)
    return serve_daemon


def _build_pipeline(seed=0):
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.nodes.stats.random_features import CosineRandomFeatures

    return (
        CosineRandomFeatures.create(D, 12, seed=seed)
        .and_then(L2Normalizer())
        .fit()
    )


def _save(tmp_path, tag="v1"):
    pipe = _build_pipeline()
    path = str(tmp_path / f"model_{tag}.kart")
    save_artifact(pipe, path, feature_shape=(D,), dtype="float32")
    return path


def _warm(model, n=None, tier="best_effort", bucket=4, service_ms=5.0):
    """Observe ``n`` (default min_samples) journeys so the model turns
    ready."""
    n = model.min_samples if n is None else n
    for _ in range(n):
        model.observe_journey(tier, "t", 1, bucket, service_ms)


# ---------------------------------------------------------------------------
# Prediction oracle (unit, no daemon)
# ---------------------------------------------------------------------------


def test_cold_model_predicts_none_and_not_ready():
    m = CapacityModel("t", min_samples=4)
    assert not m.ready()
    assert m.predict_completion_ms("gold", 1, 0, 4) is None
    assert m.predict_batch_ms(4) is None
    _warm(m, 4)
    assert m.ready()


def test_prediction_oracle_effective_bucket_and_quantile():
    m = CapacityModel("t", min_samples=4)
    _warm(m, 4)
    for v in (10.0, 20.0, 30.0, 40.0):
        m.observe_batch(4, 4, v)
    # Nearest-rank ADMIT_Q over [10, 20, 30, 40]: index
    # ceil(0.75 * 4) - 1 = 2 -> 30.0. Full flushes: fill = max_rows.
    q = math.ceil(ADMIT_Q * 4) - 1
    assert q == 2
    pred = m.predict_completion_ms("best_effort", 4, 0, 4, bucket=4)
    assert pred["batch_ms"] == pytest.approx(30.0)
    assert pred["batches_ahead"] == 1
    assert pred["bias_ms"] == 0.0
    assert pred["predicted_ms"] == pytest.approx(30.0)

    # Effective flush bucket: a 1-row request at queue depth 6 flushes
    # as part of a FULL bucket (eff = min(4, 1 + 6) = 4), never at the
    # solo rung — and two more flushes drain ahead of it.
    pred = m.predict_completion_ms("best_effort", 1, 6, 4, bucket=1)
    assert pred["bucket"] == 4
    assert pred["batch_ms"] == pytest.approx(30.0)
    assert pred["batches_ahead"] == 1 + 6 // 4
    assert pred["predicted_ms"] == pytest.approx(2 * 30.0)

    # Unobserved rung: nearest observed rung scaled by the row ratio
    # (row-linear pricing) — bucket 2 from the bucket-4 ring.
    pred = m.predict_completion_ms("best_effort", 2, 0, 4, bucket=2)
    assert pred["bucket"] == 2
    assert pred["batch_ms"] == pytest.approx(30.0 * 2 / 4)


def test_prediction_uses_observed_fill_as_drain_rate():
    m = CapacityModel("t", min_samples=4)
    _warm(m, 4)
    for _ in range(8):
        m.observe_batch(4, 4, 10.0)
    full = m.predict_completion_ms("best_effort", 1, 8, 4, bucket=1)
    assert full["batches_ahead"] == 1 + 8 // 4  # fill == max_rows
    # Partial flushes observed: the queue drains SLOWER than perfect
    # packing, so the same depth now prices more batches ahead.
    for _ in range(40):
        m.observe_batch(4, 1, 10.0)
    fill = m.stats()["fill_rows"]
    assert 1.0 <= fill < 2.0
    part = m.predict_completion_ms("best_effort", 1, 8, 4, bucket=1)
    assert part["batches_ahead"] == 1 + int(8 / fill)
    assert part["batches_ahead"] > full["batches_ahead"]
    assert part["predicted_ms"] > full["predicted_ms"]


def test_prediction_bias_feedback_corrects_underestimates():
    m = CapacityModel("t", min_samples=4)
    _warm(m, 4)
    for _ in range(8):
        m.observe_batch(4, 4, 10.0)
    base = m.predict_completion_ms("best_effort", 4, 0, 4, bucket=4)
    assert base["bias_ms"] == 0.0
    # Realized journeys keep coming in 6ms past their prediction: the
    # bias EWMA feeds the systematic error straight back.
    for _ in range(64):
        m.observe_journey("best_effort", "t", 4, 4, 16.0, predicted_ms=10.0)
    stats = m.stats()
    assert stats["bias_ms"] == pytest.approx(6.0, abs=0.5)
    pred = m.predict_completion_ms("best_effort", 4, 0, 4, bucket=4)
    assert pred["bias_ms"] == pytest.approx(stats["bias_ms"])
    assert pred["predicted_ms"] == pytest.approx(
        base["predicted_ms"] + pred["bias_ms"]
    )


def test_mix_shift_is_total_variation_distance():
    a = {1: 0.5, 4: 0.5}
    assert CapacityModel.mix_shift(a, a) == pytest.approx(0.0)
    assert CapacityModel.mix_shift(
        {1: 1.0}, {4: 1.0}
    ) == pytest.approx(1.0)
    assert CapacityModel.mix_shift(
        {1: 0.5, 4: 0.5}, {1: 1.0}
    ) == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# Strict-accuracy guard
# ---------------------------------------------------------------------------


def test_guard_watermark_validates_once_evidence_doubles():
    m = CapacityModel("t", min_samples=4)
    _warm(m, 4)  # samples = 4
    for _ in range(8):
        m.observe_batch(4, 4, 1.0)  # cheap batches: 1ms at every rung
    # A refusal the model should NEVER have made (predicted 200ms
    # against a 100ms deadline while batches cost 1ms): check_at =
    # max(4 + 4, 4 * 2) = 8 observations.
    m.note_refusal("best_effort", 1, 0, 4, 100.0, 200.0,
                   trace_id="g1", bucket=4)
    stats = m.stats()
    assert stats["refusals"] == 1 and stats["guard_checked"] == 0
    _warm(m, 3)  # samples = 7: still below the watermark
    assert m.stats()["guard_checked"] == 0
    _warm(m, 1)  # samples = 8: validation fires
    stats = m.stats()
    assert stats["guard_checked"] == 1
    assert stats["guard_violations"] == 1


def test_guard_accepts_consistent_refusal_and_frozen_bias():
    m = CapacityModel("t", min_samples=4)
    _warm(m, 4)
    for _ in range(8):
        m.observe_batch(4, 4, 50.0)
    # Consistent refusal: 50ms batch against a 10ms deadline stays
    # infeasible under the matured model — checked, no violation.
    m.note_refusal("best_effort", 1, 0, 4, 10.0, 50.0, bucket=4)
    _warm(m, 4)
    stats = m.stats()
    assert stats["guard_checked"] == 1 and stats["guard_violations"] == 0

    # Refusal-time bias is FROZEN in the record: drive the live bias up,
    # refuse at a deadline only the biased estimate breaches, then let
    # the live bias decay to zero before validation. Re-validating with
    # the live bias would flag it; the frozen bias must not.
    for _ in range(64):
        m.observe_journey("best_effort", "t", 4, 4, 80.0, predicted_ms=50.0)
    biased = m.stats()["bias_ms"]
    assert biased == pytest.approx(30.0, abs=2.0)
    samples = m.stats()["samples"]
    m.note_refusal("best_effort", 1, 0, 4, 60.0, 50.0 + biased, bucket=4)
    for _ in range(samples + 8):  # decay bias, cross the watermark
        m.observe_journey("best_effort", "t", 4, 4, 50.0, predicted_ms=50.0)
    stats = m.stats()
    assert abs(stats["bias_ms"]) < 1.0  # live bias decayed
    assert stats["guard_checked"] == 2
    assert stats["guard_violations"] == 0  # frozen 30ms bias held


# ---------------------------------------------------------------------------
# Persistence
# ---------------------------------------------------------------------------


def test_snapshot_restore_roundtrip_carries_fill_and_bias():
    m = CapacityModel("t", min_samples=4)
    _warm(m, 6)
    for v in (10.0, 20.0, 30.0, 40.0):
        m.observe_batch(4, 3, v)
    for _ in range(8):
        m.observe_journey("gold", "t", 4, 4, 20.0, predicted_ms=15.0)
    snap = m.snapshot()
    m2 = CapacityModel("t2", min_samples=4)
    assert m2.restore(snap)
    s1, s2 = m.stats(), m2.stats()
    assert s2["samples"] == s1["samples"]
    assert s2["fill_rows"] == pytest.approx(s1["fill_rows"])
    assert s2["bias_ms"] == pytest.approx(s1["bias_ms"])
    assert s2["batch_ms"] == s1["batch_ms"]
    p1 = m.predict_completion_ms("gold", 1, 5, 4, bucket=1)
    p2 = m2.predict_completion_ms("gold", 1, 5, 4, bucket=1)
    assert p2 == p1
    # Corrupt snapshots are refused with state untouched.
    m3 = CapacityModel("t3", min_samples=4)
    assert not m3.restore({"schema": 999})
    assert not m3.restore({"schema": snap["schema"], "samples": "nope"})
    assert m3.samples() == 0


def test_load_capacity_model_snapshot_wins_then_journey_replay(tmp_path):
    m = CapacityModel("alpha", min_samples=4)
    _warm(m, 10)
    m.observe_batch(4, 4, 25.0)
    seg = tmp_path / "keystone_telemetry_0001.jsonl"
    journey = {
        "id": 1, "rows": 2, "bucket": 2, "replicas": 1,
        "phases": [{"phase": "submitted", "t_ns": 0},
                   {"phase": "resolved", "t_ns": int(7e6)}],
        "outcome": "ok", "meta": {"tier": "gold", "tenant": "g"},
    }
    with open(seg, "w") as f:
        f.write("this line is torn\n")
        f.write(json.dumps({"kind": "journey", "service": "daemon-alpha",
                            "journey": journey}) + "\n")
        f.write(json.dumps({"kind": "capacity", "service": "daemon-alpha",
                            "pid": 1, "model": m.snapshot()}) + "\n")
        f.write(json.dumps({"kind": "capacity", "service": "daemon-other",
                            "pid": 1, "model": {"schema": -5}}) + "\n")
    # Snapshot wins over replay; other services' records are ignored.
    loaded = load_capacity_model(str(tmp_path), "alpha", min_samples=4)
    assert loaded.samples() == m.samples()
    assert loaded.stats()["batch_ms"] == m.stats()["batch_ms"]
    # No snapshot for this daemon: journeys replay instead.
    with open(seg, "a") as f:
        f.write(json.dumps({"kind": "journey", "service": "daemon-beta",
                            "journey": journey}) + "\n")
    replayed = load_capacity_model(str(tmp_path), "beta", min_samples=1)
    assert replayed.samples() == 1
    per = replayed.stats()["per_bucket"]
    assert per["gold:2"]["observed_p50_ms"] == pytest.approx(7.0)
    # Missing/empty directory: a cold model, not an error.
    assert load_capacity_model(None, "x").samples() == 0
    assert load_capacity_model(str(tmp_path / "nope"), "x").samples() == 0


# ---------------------------------------------------------------------------
# Config resolution
# ---------------------------------------------------------------------------


def test_resolved_capacity_model_resolution_order(monkeypatch, tmp_path):
    # Unset env + no telemetry dir: off.
    assert resolved_capacity_model() is False
    # Telemetry dir configured: defaults ON (the model persists through
    # those segments; without them it would relearn every restart).
    config.telemetry_dir = str(tmp_path)
    assert resolved_capacity_model() is True
    # An exported env wins outright, both directions.
    monkeypatch.setenv("KEYSTONE_CAPACITY_MODEL", "0")
    assert resolved_capacity_model() is False
    monkeypatch.setenv("KEYSTONE_CAPACITY_MODEL", "1")
    config.telemetry_dir = None
    assert resolved_capacity_model() is True


# ---------------------------------------------------------------------------
# Predicted-deadline admission on both wires (live daemon)
# ---------------------------------------------------------------------------


def _capacity_daemon(tmp_path, monkeypatch, min_samples=8, **kw):
    monkeypatch.setenv("KEYSTONE_CAPACITY_MODEL", "1")
    config.capacity_min_samples = min_samples
    art = _save(tmp_path)
    kw.setdefault("tenants", {
        "k-gold": Tenant("gold", "k-gold", qps=0, tier="gold"),
        "k-be": Tenant("be", "k-be", qps=0, tier="best_effort"),
    })
    return ServingDaemon(
        artifact=art, devices=1, buckets=(4,), name="t-capacity",
        flight_dir=str(tmp_path), **kw,
    )


def _force_infeasible(daemon, batch_ms=60000.0):
    """Warm the daemon's model with absurdly slow observed batches so
    ANY finite deadline is predicted infeasible."""
    model = daemon._capacity
    assert model is not None
    _warm(model)
    for _ in range(8):
        model.observe_batch(4, 4, batch_ms)


def test_refusal_counted_and_trace_echoed_on_both_wires(
        tmp_path, monkeypatch):
    sd = _serve_daemon_mod()
    x = [[1.0] * D]
    with _capacity_daemon(tmp_path, monkeypatch) as daemon:
        _force_infeasible(daemon)
        before = capacity_counters.snapshot().get("predicted_refusals", 0)

        # Framed socket: explicit deadline, caller trace adopted.
        sc = sd.SocketClient(daemon.socket_port)
        try:
            resp = sc.request({"x": x, "key": "k-be", "deadline_ms": 50.0,
                               "trace_id": "cap.sock-1"})
        finally:
            sc.close()
        assert resp["status"] == 429
        assert resp["error"] == "predicted_infeasible"
        assert resp["trace_id"] == "cap.sock-1"

        # HTTP wire: same contract — trace via header, key + deadline in
        # the body (the body-key path: a header key pre-admits before
        # the body — and thus the deadline — is even read).
        status, doc = sd.http_post(
            daemon.http_port, "/predict",
            {"x": x, "key": "k-be", "deadline_ms": 50.0},
            {"X-Trace-Id": "cap.http-1"},
        )
        assert status == 429
        assert doc["error"] == "predicted_infeasible"
        assert doc["trace_id"] == "cap.http-1"

        after = capacity_counters.snapshot()["predicted_refusals"]
        assert after - before == 2
        assert daemon._capacity.stats()["refusals"] == 2

        # The refused slot was released: an undeadlined request on the
        # same tenant still serves (prediction never breaches "none").
        sc = sd.SocketClient(daemon.socket_port)
        try:
            ok = sc.request({"x": x, "key": "k-be"})
        finally:
            sc.close()
        assert ok["status"] == 200

        # finish_request runs AFTER the response write: settle before
        # reading the journeys (the test_daemon _settle contract).
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            snap = daemon._flight.snapshot()
            if daemon.stats()["active_requests"] == 0 and all(
                r["outcome"] is not None for r in snap["records"]
            ):
                break
            time.sleep(0.01)
        assert daemon.stats()["active_requests"] == 0

        # The refused journeys closed as predicted_infeasible — never
        # admitted, never submitted (no device work).
        refused = [r for r in snap["records"]
                   if r["outcome"] == "predicted_infeasible"]
        assert len(refused) == 2
        for r in refused:
            phases = {p["phase"] for p in r["phases"]}
            assert "admitted" not in phases
            assert "submitted" not in phases


def test_cold_model_is_counted_noop_and_off_builds_none(
        tmp_path, monkeypatch):
    sd = _serve_daemon_mod()
    x = [[1.0] * D]
    # Cold (enabled but below min_samples): tight deadlines still serve,
    # the skip is counted, nothing is refused.
    with _capacity_daemon(tmp_path, monkeypatch, min_samples=10_000) as d:
        before = capacity_counters.snapshot().get("model_cold_skips", 0)
        sc = sd.SocketClient(d.socket_port)
        try:
            resp = sc.request({"x": x, "key": "k-be", "deadline_ms": 30000.0})
        finally:
            sc.close()
        assert resp["status"] == 200
        assert capacity_counters.snapshot()["model_cold_skips"] > before
        assert d.stats()["capacity"]["enabled"] is True
        assert d.stats()["capacity"]["ready"] is False
        assert d.stats()["capacity"]["refusals"] == 0
    # KEYSTONE_CAPACITY_MODEL=0: no model object at all — the PR-19
    # daemon, bit-identically — and /stats says so.
    monkeypatch.setenv("KEYSTONE_CAPACITY_MODEL", "0")
    art = _save(tmp_path, "off")
    with ServingDaemon(artifact=art, devices=1, buckets=(4,),
                      name="t-cap-off", flight_dir=str(tmp_path)) as d:
        assert d._capacity is None
        assert d.stats()["capacity"] == {"enabled": False}
        sc = sd.SocketClient(d.socket_port)
        try:
            resp = sc.request({"x": x, "deadline_ms": 30000.0})
        finally:
            sc.close()
        assert resp["status"] == 200


# ---------------------------------------------------------------------------
# Traffic-aware autoscaling
# ---------------------------------------------------------------------------


def test_autoscale_replan_executes_then_no_flap_suppresses(
        tmp_path, monkeypatch):
    config.capacity_replan_s = 30.0  # no-flap window: 60s — never expires
    with _capacity_daemon(tmp_path, monkeypatch, min_samples=8) as daemon:
        model = daemon._capacity
        _warm(model, 20, bucket=1, service_ms=5.0)
        for _ in range(8):
            model.observe_batch(1, 1, 2.0)
            model.observe_batch(4, 4, 5.0)
        model.observe_arrival("be", now=0.0)
        model.observe_arrival("be", now=0.01)

        daemon._maybe_replan()  # first warm tick: baselines the mix
        assert capacity_counters.snapshot().get("replans", 0) == 0
        assert daemon.stats()["capacity"]["last_replan"] is None

        _warm(model, 200, bucket=4, service_ms=5.0)  # the shift
        daemon._maybe_replan()
        snap = capacity_counters.snapshot()
        assert snap["replans"] == 1
        last = daemon.stats()["capacity"]["last_replan"]
        assert last is not None
        assert last["mix_shift"] >= 0.25
        assert "replicas=" in last["action"]

        _warm(model, 200, bucket=1, service_ms=5.0)  # shift straight back
        daemon._maybe_replan()
        snap = capacity_counters.snapshot()
        assert snap["replans"] == 1  # not executed again...
        assert snap["replans_suppressed"] == 1  # ...refused and counted
        # Decision-logged, both ways.
        from keystone_tpu.workflow.rules import optimizer_decisions

        acts = [d for d in optimizer_decisions()
                if d.rule == "CapacityReplan"]
        assert {a.action for a in acts} >= {last["action"], "suppress"}


def test_replan_noops_cold_and_small_shift(tmp_path, monkeypatch):
    config.capacity_replan_s = 30.0
    with _capacity_daemon(tmp_path, monkeypatch, min_samples=50) as daemon:
        model = daemon._capacity
        _warm(model, 10)  # still cold
        before = capacity_counters.snapshot().get("model_cold_skips", 0)
        daemon._maybe_replan()
        assert capacity_counters.snapshot()["model_cold_skips"] == before + 1
        _warm(model, 40)  # warm now; baseline then barely-shifted mix
        daemon._maybe_replan()
        _warm(model, 2, bucket=1)
        daemon._maybe_replan()
        snap = capacity_counters.snapshot()
        assert snap.get("replans", 0) == 0
        assert snap.get("replans_suppressed", 0) == 0


# ---------------------------------------------------------------------------
# Deadline-aware cross-tenant micro-batching
# ---------------------------------------------------------------------------


class _Rec:
    """Duck-typed journey record for white-box micro-batch tests (note
    for attribution, finish for the service's close() sweep)."""

    def __init__(self):
        self.meta = {}

    def note(self, **kw):
        self.meta.update(kw)

    def finish(self, *a, **kw):
        pass

    def stamp(self, *a, **kw):
        pass


def _mk_req(rows, tier, deadline_s=None):
    from concurrent.futures import Future

    from keystone_tpu.workflow.serving import _Request

    return _Request(
        x=np.zeros((rows, D), np.float32), datum=False, fut=Future(),
        deadline=(time.monotonic() + deadline_s
                  if deadline_s is not None else None),
        t_sub=time.perf_counter_ns(), rid=0, rec=_Rec(), tier=tier,
    )


@pytest.fixture
def svc(tmp_path):
    """A real PipelineService (loop parked: we drive the fill helper
    directly under its own lock discipline) with no capacity model."""
    from keystone_tpu.workflow import CompiledPipeline
    from keystone_tpu.workflow.serving import PipelineService

    cp = CompiledPipeline(_build_pipeline(), max_batch=4).warmup((D,))
    s = PipelineService(cp, max_rows=4, name="t-microbatch")
    yield s
    s.close(drain=False)


def test_microbatch_fills_gold_slack_deadline_aware(svc):
    model = CapacityModel("t", min_samples=4)
    _warm(model, 4)
    for _ in range(8):
        model.observe_batch(4, 4, 2.0)  # rung p99: 2ms
    svc._capacity = model
    gold = _mk_req(3, "gold", deadline_s=10.0)
    tight = _mk_req(1, "best_effort", deadline_s=0.0001)  # can't survive
    big = _mk_req(2, "best_effort", deadline_s=10.0)      # over slack
    untiered = _mk_req(1, None, deadline_s=10.0)
    rider = _mk_req(1, "best_effort", deadline_s=10.0)
    group = [gold]
    svc._pending.extend([tight, big, untiered, rider])
    rows = svc._microbatch_fill_locked(group, 3)
    # Only the eligible best-effort rider rode the 1-row padding slack.
    assert rows == 4
    assert group == [gold, rider]
    assert rider.rec.meta["microbatched"] is True
    assert rider.rec.meta["microbatch_bucket"] == 4
    # Skipped requests kept their FIFO order.
    assert list(svc._pending) == [tight, big, untiered]
    snap = capacity_counters.snapshot()
    assert snap["microbatches_formed"] == 1
    assert snap["microbatch_rows_filled"] == 1


def test_microbatch_noops_without_anchor_slack_or_warm_model(svc):
    rider = _mk_req(1, "best_effort", deadline_s=10.0)
    # No capacity model: the _loop gate never calls the fill helper —
    # the PR-19 path. The helper itself is also anchor-gated:
    svc._capacity = CapacityModel("t", min_samples=4)  # cold
    group_be = [_mk_req(3, "best_effort", deadline_s=10.0)]
    svc._pending.append(rider)
    assert svc._microbatch_fill_locked(group_be, 3) == 3  # no gold anchor
    assert list(svc._pending) == [rider]

    gold_group = [_mk_req(3, "gold", deadline_s=10.0)]
    before = capacity_counters.snapshot().get("model_cold_skips", 0)
    assert svc._microbatch_fill_locked(gold_group, 3) == 3  # cold model
    assert capacity_counters.snapshot()["model_cold_skips"] == before + 1
    assert len(gold_group) == 1 and list(svc._pending) == [rider]

    # Exact-fit group: no padding slack to fill.
    model = CapacityModel("t", min_samples=4)
    _warm(model, 4)
    for _ in range(4):
        model.observe_batch(4, 4, 2.0)
    svc._capacity = model
    full_group = [_mk_req(4, "gold", deadline_s=10.0)]
    assert svc._microbatch_fill_locked(full_group, 4) == 4
    assert list(svc._pending) == [rider]


def test_microbatch_protects_gold_anchor_deadline(svc):
    model = CapacityModel("t", min_samples=4)
    _warm(model, 4)
    for _ in range(8):
        model.observe_batch(4, 4, 50.0)  # rung p99: 50ms
    svc._capacity = model
    # The anchor's own deadline is inside the modeled batch tail: adding
    # riders is forbidden outright.
    gold = _mk_req(3, "gold", deadline_s=0.005)
    rider = _mk_req(1, "best_effort", deadline_s=10.0)
    svc._pending.append(rider)
    assert svc._microbatch_fill_locked([gold], 3) == 3
    assert list(svc._pending) == [rider]


# ---------------------------------------------------------------------------
# Lint registration
# ---------------------------------------------------------------------------


def test_replan_thread_registered_in_keystone_lint():
    sys.path.insert(0, TOOLS)
    try:
        import keystone_lint
    finally:
        sys.path.pop(0)
    assert "_replan_loop" in keystone_lint.KNOWN_THREAD_TARGETS


def test_kg108_flags_pinned_resources_under_enabled_model(monkeypatch):
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer

    p = _build_pipeline()
    prior = (config.serve_buckets, config.serve_devices)
    try:
        monkeypatch.setenv("KEYSTONE_CAPACITY_MODEL", "1")
        config.serve_buckets = (4, 8)
        report = p.lint()
        hits = report.by_rule("KG108")
        assert hits and hits[0].severity == "warning"
        assert "hand-pinned" in hits[0].message
        # Un-pinned defaults are the healthy configuration, not a finding.
        config.serve_buckets = ()
        config.serve_devices = 0
        assert not p.lint().by_rule("KG108")
        # Model off: pins are fine (nothing is being defeated).
        monkeypatch.setenv("KEYSTONE_CAPACITY_MODEL", "0")
        config.serve_buckets = (4, 8)
        assert not p.lint().by_rule("KG108")
    finally:
        config.serve_buckets, config.serve_devices = prior
