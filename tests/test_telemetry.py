"""Durable telemetry pipeline: trace ids, the JSONL export log, SLO
accounting, and the offline trace_report reconstruction.

Acceptance pins (ISSUE 19):

- **Trace-id contract**: a well-formed inbound id is adopted verbatim,
  anything else is minted — a bad optional header can never reject a
  request or propagate garbage into logs/response headers.
- **Drops-counted-never-blocks**: ``TelemetryLog.emit`` never blocks
  and never raises — a full queue / closed log / write error drops the
  record AND counts it. Rotation + bounded retention keep the volume
  finite under a steady flood.
- **Offline reconstruction**: ``tools/trace_report.py --telemetry``
  rebuilds the cross-process timeline (and the per-tenant SLO report)
  from the on-disk segments ALONE — after the daemon has exited, with
  the live rings gone.
"""

import importlib
import json
import os
import sys
import time

import pytest

from keystone_tpu.config import config
from keystone_tpu.utils.telemetry import (
    SLO_BAD_STATUSES,
    SLO_EXCLUDED_STATUSES,
    TRACE_ID_RE,
    SloAccounting,
    TelemetryLog,
    accept_trace_id,
    active_telemetry,
    mint_trace_id,
    reset_telemetry,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")

D = 6


def _trace_report_mod():
    sys.path.insert(0, TOOLS)
    try:
        return importlib.import_module("trace_report")
    finally:
        sys.path.pop(0)


def _read_segments(directory):
    records = []
    for name in sorted(os.listdir(directory)):
        if not name.startswith("keystone_telemetry_"):
            continue
        with open(os.path.join(directory, name)) as fh:
            for line in fh:
                records.append(json.loads(line))
    return records


# ---------------------------------------------------------------------------
# Trace-id contract
# ---------------------------------------------------------------------------


def test_trace_id_accept_and_mint():
    # Well-formed ids are adopted VERBATIM.
    for good in ("abc", "a" * 64, "A-Z.0:9_x", "req:1234.span-7"):
        assert accept_trace_id(good) == good
    # Absent/empty/malformed ids are replaced with a minted one.
    for bad in (None, "", "a" * 65, "has space", "new\nline", "ütf8",
                "semi;colon", "q?x", "a/b"):
        minted = accept_trace_id(bad)
        assert minted != bad
        assert TRACE_ID_RE.match(minted)
    # Minted ids are well-formed and unique.
    ids = {mint_trace_id() for _ in range(64)}
    assert len(ids) == 64
    assert all(TRACE_ID_RE.match(i) for i in ids)


def test_writer_loop_is_a_registered_thread_root():
    sys.path.insert(0, TOOLS)
    try:
        import keystone_lint
    finally:
        sys.path.pop(0)
    assert "_writer_loop" in keystone_lint.KNOWN_THREAD_TARGETS


# ---------------------------------------------------------------------------
# TelemetryLog: durability, rotation, retention, never-blocks
# ---------------------------------------------------------------------------


class _FakeJourney:
    def __init__(self, trace_id="t-1", outcome="ok"):
        self._doc = {
            "id": 1, "rows": 2, "outcome": outcome,
            "phases": [{"phase": "accepted", "t_ns": 1000},
                       {"phase": "resolved", "t_ns": 2000}],
            "meta": {"trace_id": trace_id, "status": 200,
                     "tenant": "acme", "tier": "gold", "generation": 0},
        }

    def as_dict(self):
        return dict(self._doc)


def test_telemetry_log_meta_anchor_and_journey_roundtrip(tmp_path):
    log = TelemetryLog(str(tmp_path), name="unit", queue_cap=64)
    try:
        assert log.journey("svc-a", _FakeJourney("trace-xyz"))
        assert log.drain(timeout=10.0)
    finally:
        log.close()
    records = _read_segments(str(tmp_path))
    # Segment opens with the meta record: schema + the wall/perf anchor
    # pair that makes offline cross-process merging possible.
    assert records[0]["kind"] == "meta"
    assert records[0]["schema"] == TelemetryLog.SCHEMA
    anchor = records[0]["anchor"]
    assert anchor["unix_time"] > 0 and anchor["perf_ns"] > 0
    journeys = [r for r in records if r["kind"] == "journey"]
    assert len(journeys) == 1
    assert journeys[0]["trace_id"] == "trace-xyz"
    assert journeys[0]["service"] == "svc-a"
    assert journeys[0]["journey"]["meta"]["tenant"] == "acme"
    stats = log.stats()
    assert stats["enqueued"] == stats["written"] == 1
    assert stats["dropped"] == 0


def test_telemetry_rotation_and_bounded_retention(tmp_path):
    # ~1.6KB records against a 0.004MB (4KB) rotation threshold: many
    # rotations; retention keeps only the newest 2 segments.
    log = TelemetryLog(str(tmp_path), name="rot", rotate_mb=0.004,
                       keep=2, queue_cap=512)
    try:
        for i in range(40):
            assert log.emit({"kind": "journey", "i": i, "pad": "x" * 1500})
        assert log.drain(timeout=10.0)
    finally:
        log.close()
    segs = [n for n in os.listdir(str(tmp_path))
            if n.startswith("keystone_telemetry_rot_")]
    assert len(segs) <= 2, segs
    assert log.rotations >= 3
    # Every surviving line is complete JSON; newest records survive.
    records = _read_segments(str(tmp_path))
    kept = [r["i"] for r in records if r.get("kind") == "journey"]
    assert kept and max(kept) == 39
    assert log.stats()["written"] == 40


def test_telemetry_emit_never_blocks_and_counts_drops(tmp_path):
    log = TelemetryLog(str(tmp_path), name="drops", queue_cap=4)
    try:
        # Jam the queue from the producer side faster than the writer
        # can drain: emit must return (True or False) immediately and
        # count every False as a drop — by construction it cannot block
        # (put_nowait) or raise.
        results = [log.emit({"kind": "journey", "i": i, "pad": "y" * 200})
                   for i in range(5000)]
        assert log.drain(timeout=20.0)
        accepted = sum(results)
        stats = log.stats()
        assert stats["enqueued"] == accepted
        assert stats["written"] == accepted
        assert stats["dropped"] == len(results) - accepted
        # The accounting invariant the bench gates on: everything is
        # either durably written or counted dropped.
        assert stats["enqueued"] + stats["dropped"] == len(results)
    finally:
        log.close()
    # Emit AFTER close: dropped and counted, never raised.
    before = log.stats()["dropped"]
    assert log.emit({"kind": "journey"}) is False
    assert log.stats()["dropped"] == before + 1


def test_active_telemetry_singleton_follows_the_knob(tmp_path, monkeypatch):
    reset_telemetry()
    try:
        monkeypatch.delenv("KEYSTONE_TELEMETRY_DIR", raising=False)
        monkeypatch.setattr(config, "telemetry_dir", "")
        assert active_telemetry() is None
        d1 = str(tmp_path / "a")
        monkeypatch.setenv("KEYSTONE_TELEMETRY_DIR", d1)
        t1 = active_telemetry()
        assert t1 is not None and t1.directory == d1
        assert active_telemetry() is t1  # cached, resolved once
        # Flipping the knob rebuilds (tests flip without a reload).
        d2 = str(tmp_path / "b")
        monkeypatch.setenv("KEYSTONE_TELEMETRY_DIR", d2)
        t2 = active_telemetry()
        assert t2 is not t1 and t2.directory == d2
        # Env-presence-over-truthiness: exported empty = explicit off.
        monkeypatch.setenv("KEYSTONE_TELEMETRY_DIR", "")
        assert active_telemetry() is None
    finally:
        reset_telemetry()


def test_torn_tail_line_recovers_everything_before_it(tmp_path):
    log = TelemetryLog(str(tmp_path), name="torn", queue_cap=16)
    try:
        for i in range(3):
            log.emit({"kind": "journey", "i": i, "trace_id": f"t{i}",
                      "pid": os.getpid()})
        assert log.drain(timeout=10.0)
        path = log.stats()["segment"]
    finally:
        log.close()
    # Simulate a process killed mid-write: append half a record.
    with open(path, "a") as fh:
        fh.write('{"kind": "journey", "i": 99, "tr')
    report = _trace_report_mod()
    records, paths = report.load_telemetry(str(tmp_path))
    assert paths == [path]
    idx = [r.get("i") for r in records if r.get("kind") == "journey"]
    assert idx == [0, 1, 2]  # the torn line is skipped, not fatal


# ---------------------------------------------------------------------------
# SLO accounting
# ---------------------------------------------------------------------------


def test_slo_status_semantics_and_burn_math():
    slo = SloAccounting(window_s=300.0, target=0.9)
    # 8 good, 2 server-side bad, plus excluded client-caused statuses
    # that must not enter the denominator.
    for _ in range(8):
        slo.observe("acme", "gold", 200)
    slo.observe("acme", "gold", 500)
    slo.observe("acme", "gold", 504)
    for status in sorted(SLO_EXCLUDED_STATUSES):
        slo.observe("acme", "gold", status)
    assert SLO_BAD_STATUSES.isdisjoint(SLO_EXCLUDED_STATUSES)
    entry = slo.snapshot()["tenants"]["acme"]["gold"]
    assert entry["total"] == 10 and entry["good"] == 8
    assert entry["hit_rate"] == 0.8
    # burn = miss_rate / (1 - target) = 0.2 / 0.1
    assert entry["burn"] == 2.0


def test_slo_redaction_and_tier_rates():
    slo = SloAccounting(window_s=300.0, target=0.99)
    slo.observe("acme", "gold", 200)
    slo.observe("tenant-b", "gold", 503)
    slo.observe("tenant-c", "best_effort", 200)
    full = slo.snapshot()
    assert set(full["tenants"]) == {"acme", "tenant-b", "tenant-c"}
    red = slo.snapshot(redact_tenants=True)
    # Tenant names collapse to "*"; per-tier aggregates survive.
    assert set(red["tenants"]) == {"*"}
    assert red["tenants"]["*"]["gold"]["total"] == 2
    assert red["tenants"]["*"]["gold"]["good"] == 1
    rates = slo.tier_rates()
    assert rates["gold"]["hit_rate"] == 0.5
    assert rates["best_effort"]["hit_rate"] == 1.0
    assert "acme" not in json.dumps(rates)


def test_slo_window_expires_old_events(monkeypatch):
    slo = SloAccounting(window_s=10.0, target=0.99)
    now = [1000.0]
    monkeypatch.setattr(time, "monotonic", lambda: now[0])
    slo.observe("acme", "gold", 500)
    now[0] += 5.0
    slo.observe("acme", "gold", 200)
    entry = slo.snapshot()["tenants"]["acme"]["gold"]
    assert entry["total"] == 2 and entry["good"] == 1
    # The failure ages out of the window; the hit rate recovers.
    now[0] += 7.0
    entry = slo.snapshot()["tenants"]["acme"]["gold"]
    assert entry["total"] == 1 and entry["hit_rate"] == 1.0


# ---------------------------------------------------------------------------
# Offline reconstruction (trace_report --telemetry / --slo)
# ---------------------------------------------------------------------------


def _write_segment(directory, name, pid, anchor_unix, records):
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(
        directory, f"keystone_telemetry_{name}_{pid}_000001.jsonl"
    )
    meta = {"kind": "meta", "schema": 1, "service": name, "pid": pid,
            "anchor": {"unix_time": anchor_unix, "perf_ns": 1_000_000},
            "segment": 1}
    with open(path, "w") as fh:
        for rec in [meta] + records:
            fh.write(json.dumps(rec) + "\n")
    return path


def test_merge_telemetry_joins_processes_on_wall_clock(tmp_path):
    """Two processes with DIFFERENT perf epochs but overlapping wall
    time merge onto one timeline, joined by trace id — the router →
    daemon cross-process stitch, reconstructed offline."""
    report = _trace_report_mod()
    directory = str(tmp_path)
    journey = {
        "kind": "journey", "service": "daemon-a", "pid": 11,
        "trace_id": "cross-1",
        "journey": {
            "id": 7, "rows": 1, "outcome": "ok",
            "phases": [{"phase": "accepted", "t_ns": 2_000_000},
                       {"phase": "resolved", "t_ns": 4_000_000}],
            "meta": {"trace_id": "cross-1", "status": 200,
                     "tenant": "acme", "tier": "gold", "generation": 0},
        },
    }
    spans = {
        "kind": "spans", "pid": 22,
        "events": [{"name": "serve.request", "cat": "serve",
                    "start_ns": 3_000_000, "dur_ns": 500_000, "tid": 1,
                    "thread": "w0", "args": {"trace_id": "cross-1"}}],
    }
    _write_segment(directory, "procA", 11, 100.0, [journey])
    _write_segment(directory, "procB", 22, 100.0, [spans])
    records, paths = report.load_telemetry(directory)
    assert len(paths) == 2
    doc = report.merge_telemetry(records)
    from keystone_tpu.utils.metrics import validate_chrome_trace

    assert validate_chrome_trace(doc) == []
    idx = report.trace_index(doc)
    entry = idx["cross-1"]
    # One trace id crossed both processes.
    assert set(entry["pids"]) == {11, 22}
    assert "daemon-a" in entry["services"]
    assert "ok" in entry["outcomes"]
    # Wall-clock math: journey accepted at anchor 100s + (2ms - 1ms
    # anchor perf) = 100.001s -> µs; the two processes share the axis.
    ts = [ev["ts"] for ev in doc["traceEvents"]
          if (ev.get("args") or {}).get("trace_id") == "cross-1"
          and ev["ph"] == "X"]
    assert min(ts) == pytest.approx(100.001e6, rel=1e-6)


def test_slo_report_from_journeys_alone(tmp_path):
    report = _trace_report_mod()
    directory = str(tmp_path)

    def j(trace, status, tenant="acme", tier="gold", t_ns=2_000_000):
        return {
            "kind": "journey", "service": "d", "pid": 5, "trace_id": trace,
            "journey": {
                "id": 1, "rows": 1,
                "outcome": "ok" if status == 200 else "error",
                "phases": [{"phase": "accepted", "t_ns": t_ns},
                           {"phase": "resolved", "t_ns": t_ns + 1000}],
                "meta": {"trace_id": trace, "status": status,
                         "tenant": tenant, "tier": tier, "generation": 0},
            },
        }

    _write_segment(directory, "d", 5, 50.0, [
        j("t1", 200), j("t2", 200), j("t3", 504),
        j("t4", 429),  # excluded: admission refusal, not a failure
        j("t5", 200, tenant="other", tier="best_effort"),
    ])
    records, _ = report.load_telemetry(directory)
    out = report.slo_report(records, window_s=300.0, target=0.9)
    gold = out["tenants"]["acme"]["gold"]
    assert gold["total"] == 3 and gold["good"] == 2  # 429 excluded
    assert gold["burn"] == pytest.approx((1 / 3) / 0.1, rel=1e-3)
    be = out["tenants"]["other"]["best_effort"]
    assert be["hit_rate"] == 1.0


def test_trace_report_telemetry_cli_empty_dir_fails(tmp_path):
    report = _trace_report_mod()
    rc = report.main(["--telemetry", str(tmp_path)])
    assert rc == 1  # a dead pipeline must not produce a green report


# ---------------------------------------------------------------------------
# End to end: daemon -> disk -> offline reconstruction (the
# `make trace-report` smoke, in-process so the gate can't rot)
# ---------------------------------------------------------------------------


def test_daemon_journeys_reconstruct_offline_after_exit(
        tmp_path, monkeypatch):
    import numpy as np

    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.nodes.stats.random_features import CosineRandomFeatures
    from keystone_tpu.workflow.daemon import ServingDaemon, Tenant
    from keystone_tpu.workflow.serialization import save_artifact

    tel_dir = str(tmp_path / "telemetry")
    monkeypatch.setenv("KEYSTONE_TELEMETRY_DIR", tel_dir)
    reset_telemetry()
    pipe = (CosineRandomFeatures.create(D, 12, seed=0)
            .and_then(L2Normalizer()).fit())
    art = str(tmp_path / "model.kart")
    save_artifact(pipe, art, feature_shape=(D,), dtype="float32")
    sys.path.insert(0, TOOLS)
    try:
        import serve_daemon as sd
    finally:
        sys.path.pop(0)
    pipe2 = (CosineRandomFeatures.create(D, 12, seed=1)
             .and_then(L2Normalizer()).fit())
    art2 = str(tmp_path / "model2.kart")
    save_artifact(pipe2, art2, feature_shape=(D,), dtype="float32")
    x = [[1.0] * D]
    tenants = {"sk-g": Tenant("acme", "sk-g", qps=0, tier="gold")}
    try:
        with ServingDaemon(
            artifact=art, tenants=tenants, devices=1, buckets=(4,),
            name="t-offline", gold_deadline_ms=60000,
            flight_dir=str(tmp_path),
        ) as daemon:
            st, doc = sd.http_post(
                daemon.http_port, "/predict", {"x": x},
                {"X-API-Key": "sk-g", "X-Trace-Id": "offline-trace-1"},
            )
            assert st == 200 and doc["trace_id"] == "offline-trace-1"
            # A hot swap carries its requester's trace id into the
            # durable lifecycle record.
            assert daemon.request_swap(
                art2, timeout_s=120, trace_id="swap-trace-7"
            ) == 1
        # Daemon exited; drop the live singleton too — reconstruction
        # must need NOTHING but the directory.
        reset_telemetry()
        report = _trace_report_mod()
        records, paths = report.load_telemetry(tel_dir)
        assert paths, "no segments written"
        merged = report.merge_telemetry(records)
        from keystone_tpu.utils.metrics import validate_chrome_trace

        assert validate_chrome_trace(merged) == []
        idx = report.trace_index(merged)
        entry = idx["offline-trace-1"]
        assert "daemon-t-offline" in entry["services"]
        assert "ok" in entry["outcomes"]
        # The swap's lifecycle record reconstructs under ITS trace id,
        # naming both generations.
        swaps = [r for r in records if r.get("kind") == "swap"]
        assert swaps and swaps[0]["trace_id"] == "swap-trace-7"
        assert swaps[0]["from_generation"] == 0
        assert swaps[0]["generation"] == 1
        assert "swap-trace-7" in idx
        slo = report.slo_report(records, window_s=300.0, target=0.99)
        assert slo["tenants"]["acme"]["gold"]["total"] >= 1
        assert slo["tenants"]["acme"]["gold"]["hit_rate"] == 1.0
    finally:
        reset_telemetry()
