"""Learning-node tests: solvers vs oracles, statistical models vs
recoverable synthetic structure (SURVEY.md §4 test strategy)."""

import numpy as np
import pytest

from keystone_tpu.nodes.learning import (
    BlockLeastSquaresEstimator,
    BlockWeightedLeastSquaresEstimator,
    DistributedPCAEstimator,
    GaussianKernelGenerator,
    GaussianMixtureModelEstimator,
    KernelRidgeRegression,
    KMeansPlusPlusEstimator,
    LeastSquaresEstimator,
    LinearDiscriminantAnalysis,
    LinearMapEstimator,
    LogisticRegressionEstimator,
    NaiveBayesEstimator,
    PCAEstimator,
    ZCAWhitenerEstimator,
    choose_solver,
)


# ---------------------------------------------------------------------- block LS


def _ridge_with_intercept_oracle(X, Y, lam):
    Xc = X - X.mean(axis=0)
    Yc = Y - Y.mean(axis=0)
    d = X.shape[1]
    W = np.linalg.solve(Xc.T @ Xc + lam * np.eye(d), Xc.T @ Yc)
    b = Y.mean(axis=0) - X.mean(axis=0) @ W
    return W, b


def test_block_least_squares_converges(rng):
    X = rng.normal(size=(300, 24)).astype(np.float32)
    W_true = rng.normal(size=(24, 4)).astype(np.float32)
    Y = X @ W_true + 0.5
    model = BlockLeastSquaresEstimator(block_size=8, num_iters=25, lam=0.05).fit(
        X, Y
    )
    W, b = _ridge_with_intercept_oracle(
        X.astype(np.float64), Y.astype(np.float64), 0.05
    )
    np.testing.assert_allclose(np.asarray(model.W), W, rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(model.b), b, rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(model(X), X @ W + b, rtol=2e-2, atol=5e-2)


def test_block_weighted_upweights_rare_class(rng):
    # Highly imbalanced two-class problem: balanced weighting must improve
    # the rare class's margin vs the unweighted solve.
    n_major, n_minor = 500, 25
    X = np.concatenate(
        [
            rng.normal(loc=0.0, size=(n_major, 8)),
            rng.normal(loc=1.2, size=(n_minor, 8)),
        ]
    ).astype(np.float32)
    y = np.concatenate([np.zeros(n_major), np.ones(n_minor)]).astype(int)
    Y = (2 * np.eye(2)[y] - 1).astype(np.float32)
    unweighted = BlockLeastSquaresEstimator(block_size=8, num_iters=5, lam=0.1).fit(X, Y)
    weighted = BlockWeightedLeastSquaresEstimator(
        block_size=8, num_iters=5, lam=0.1, mixture_weight=1.0
    ).fit(X, Y)
    minor_scores_u = np.asarray(unweighted(X[n_major:]))[:, 1]
    minor_scores_w = np.asarray(weighted(X[n_major:]))[:, 1]
    assert minor_scores_w.mean() > minor_scores_u.mean()


def test_choose_solver_cost_model():
    assert choose_solver(100, 10, 3).name == "local"
    assert choose_solver(100_000, 4096, 10).name == "normal"
    assert choose_solver(1_000_000, 262_144, 1000).name == "block"


def test_least_squares_estimator_dispatches(rng):
    X = rng.normal(size=(50, 6)).astype(np.float32)
    Y = rng.normal(size=(50, 2)).astype(np.float32)
    est = LeastSquaresEstimator(lam=0.1)
    model = est.fit(X, Y)
    assert est.last_choice.name == "local"
    direct = LinearMapEstimator(lam=0.1).fit(X, Y)
    np.testing.assert_allclose(model.W, direct.W, rtol=1e-3, atol=1e-3)


# ---------------------------------------------------------------------- PCA/ZCA


def test_pca_matches_numpy_svd(rng):
    X = rng.normal(size=(100, 12)).astype(np.float32)
    pca = PCAEstimator(dims=4).fit(X)
    out = np.asarray(pca(X))
    Xc = X - X.mean(axis=0)
    _, _, vt = np.linalg.svd(Xc, full_matrices=False)
    oracle = Xc @ vt[:4].T
    # Components are sign-ambiguous; compare per-column up to sign.
    for j in range(4):
        diff = min(
            np.abs(out[:, j] - oracle[:, j]).max(),
            np.abs(out[:, j] + oracle[:, j]).max(),
        )
        assert diff < 1e-3


def test_distributed_pca_matches_local(rng):
    X = rng.normal(size=(160, 10)).astype(np.float32)
    p_local = PCAEstimator(dims=3).fit(X)
    p_dist = DistributedPCAEstimator(dims=3).fit(X)
    out_l = np.asarray(p_local(X))
    out_d = np.asarray(p_dist(X))
    for j in range(3):
        diff = min(
            np.abs(out_l[:, j] - out_d[:, j]).max(),
            np.abs(out_l[:, j] + out_d[:, j]).max(),
        )
        assert diff < 1e-3


def test_zca_whitens_covariance(rng):
    A = rng.normal(size=(6, 6))
    X = (rng.normal(size=(2000, 6)) @ A).astype(np.float32)
    zca = ZCAWhitenerEstimator(eps=1e-5).fit(X)
    out = np.asarray(zca(X))
    cov = out.T @ out / out.shape[0]
    np.testing.assert_allclose(cov, np.eye(6), atol=0.05)


# ---------------------------------------------------------------------- clustering


def test_kmeans_recovers_separated_clusters(rng):
    centers_true = np.array([[0, 0], [10, 0], [0, 10]], dtype=np.float32)
    X = np.concatenate(
        [c + rng.normal(scale=0.5, size=(100, 2)) for c in centers_true]
    ).astype(np.float32)
    model = KMeansPlusPlusEstimator(k=3, max_iters=20, seed=1).fit(X)
    found = np.asarray(model.centers)
    # Each true center has a found center within 0.5.
    for c in centers_true:
        assert np.min(np.linalg.norm(found - c, axis=1)) < 0.5
    onehot = np.asarray(model(X[:5]))
    assert onehot.shape == (5, 3)
    np.testing.assert_allclose(onehot.sum(axis=1), 1.0)


def test_gmm_recovers_mixture(rng):
    means_true = np.array([[-4.0, 0.0], [4.0, 2.0]])
    X = np.concatenate(
        [
            means_true[0] + rng.normal(scale=0.7, size=(300, 2)),
            means_true[1] + rng.normal(scale=1.2, size=(700, 2)),
        ]
    ).astype(np.float32)
    gmm = GaussianMixtureModelEstimator(k=2, max_iters=60, seed=0).fit(X)
    means = np.asarray(gmm.means)
    order = np.argsort(means[:, 0])
    np.testing.assert_allclose(means[order], means_true, atol=0.3)
    w = np.asarray(gmm.weights)[order]
    np.testing.assert_allclose(w, [0.3, 0.7], atol=0.05)
    resp = np.asarray(gmm(X[:4]))
    np.testing.assert_allclose(resp.sum(axis=1), 1.0, atol=1e-5)


# ---------------------------------------------------------------------- classifiers


def test_naive_bayes_hand_computation():
    X = np.array([[2, 0], [1, 1], [0, 3]], dtype=np.float32)
    y = np.array([0, 0, 1])
    model = NaiveBayesEstimator(num_classes=2, smoothing=1.0).fit(X, y)
    # priors: [2/3, 1/3]
    np.testing.assert_allclose(
        np.exp(np.asarray(model.log_prior)), [2 / 3, 1 / 3], atol=1e-6
    )
    # class 0 counts: [3, 1] + 1 → [4, 2]/6
    np.testing.assert_allclose(
        np.exp(np.asarray(model.log_likelihood))[0], [4 / 6, 2 / 6], atol=1e-6
    )
    scores = np.asarray(model(X))
    assert scores.shape == (3, 2)
    assert scores[0, 0] > scores[0, 1] and scores[2, 1] > scores[2, 0]


def test_logistic_regression_separable(rng):
    X = np.concatenate(
        [
            rng.normal(loc=-2.0, size=(200, 4)),
            rng.normal(loc=2.0, size=(200, 4)),
        ]
    ).astype(np.float32)
    y = np.concatenate([np.zeros(200), np.ones(200)]).astype(int)
    model = LogisticRegressionEstimator(num_classes=2, max_iters=50).fit(X, y)
    pred = np.argmax(np.asarray(model(X)), axis=1)
    assert (pred == y).mean() > 0.99


def test_lda_projects_classes_apart(rng):
    X = np.concatenate(
        [
            rng.normal(loc=[0, 0, 0, 0], size=(150, 4)),
            rng.normal(loc=[3, 0, 0, 0], size=(150, 4)),
        ]
    ).astype(np.float32)
    y = np.concatenate([np.zeros(150), np.ones(150)]).astype(int)
    proj = LinearDiscriminantAnalysis(dims=1).fit(X, y)
    z = np.asarray(proj(X)).ravel()
    gap = abs(z[:150].mean() - z[150:].mean())
    spread = 0.5 * (z[:150].std() + z[150:].std())
    # Two unit-variance clusters 3σ apart project to gap/spread ≈ 3.
    assert gap > 2.5 * spread


# ---------------------------------------------------------------------- kernel ridge


def test_kernel_ridge_matches_direct_solve(rng):
    n, d, k = 150, 5, 2
    X = rng.normal(size=(n, d)).astype(np.float32)
    Y = rng.normal(size=(n, k)).astype(np.float32)
    gamma, lam = 0.3, 0.1
    est = KernelRidgeRegression(gamma=gamma, lam=lam, max_iters=400, tol=1e-7)
    model = est.fit(X, Y)
    # Direct dense oracle.
    sq = ((X[:, None, :] - X[None, :, :]) ** 2).sum(-1)
    K = np.exp(-gamma * sq)
    alpha = np.linalg.solve(K + lam * np.eye(n), Y.astype(np.float64))
    np.testing.assert_allclose(np.asarray(model.alpha), alpha, atol=1e-2)
    np.testing.assert_allclose(np.asarray(model(X)), K @ alpha, atol=1e-2)
    assert est.last_cg_iters is not None and est.last_cg_iters < 400


def test_kernel_ridge_interpolates_nonlinear_function(rng):
    X = np.linspace(-3, 3, 200).reshape(-1, 1).astype(np.float32)
    Y = np.sin(2 * X)
    model = KernelRidgeRegression(gamma=2.0, lam=1e-4, max_iters=500).fit(X, Y)
    pred = np.asarray(model(X))
    assert np.abs(pred - Y).max() < 0.05


def test_kernel_ridge_dense_fallback_linear_kernel(rng):
    from keystone_tpu.nodes.learning import LinearKernelGenerator

    X = rng.normal(size=(60, 4)).astype(np.float32)
    Y = rng.normal(size=(60, 2)).astype(np.float32)
    model = KernelRidgeRegression(kernel=LinearKernelGenerator(), lam=0.5).fit(X, Y)
    K = X @ X.T
    alpha = np.linalg.solve(K + 0.5 * np.eye(60), Y.astype(np.float64))
    np.testing.assert_allclose(np.asarray(model.alpha), alpha, atol=1e-2)


def test_kernel_ridge_rejects_kernel_plus_gamma():
    with pytest.raises(ValueError, match="not both"):
        KernelRidgeRegression(kernel=GaussianKernelGenerator(1.0), gamma=2.0)


def test_block_ls_model_parallel_matches_data_parallel(rng):
    """parallelism='model' (d-sharded ring) reaches the same solution as
    the default data-parallel solve."""
    n, d, k = 256, 64, 3
    X = rng.normal(size=(n, d)).astype(np.float32)
    W_true = rng.normal(size=(d, k)).astype(np.float32)
    Y = X @ W_true + 0.25  # consistent system + intercept: oracle = Y
    kw = dict(block_size=16, num_iters=12, lam=1e-4)
    # Different sweep schedules converge at different rates, so compare
    # each to the exact answer rather than to each other mid-trajectory.
    for est in (
        BlockLeastSquaresEstimator(**kw),
        BlockLeastSquaresEstimator(**kw, parallelism="model"),
    ):
        pred = np.asarray(est.fit(X, Y).apply_batch(X))
        resid = np.linalg.norm(pred - Y) / np.linalg.norm(Y)
        assert resid < 5e-3, (est.parallelism, resid)


def test_block_ls_model_parallel_accepts_device_arrays(rng):
    """Regression: np.asarray over a jax.Array is a read-only zero-copy
    view, and the ring path's in-place intercept centering crashed on it
    (the executor device_puts every pipeline input, so this is the normal
    case, not the exotic one)."""
    import jax.numpy as jnp

    n, d, k = 128, 32, 2
    X = rng.normal(size=(n, d)).astype(np.float32)
    Y = (X @ rng.normal(size=(d, k)).astype(np.float32) + 0.5).astype(np.float32)
    est = BlockLeastSquaresEstimator(
        block_size=16, num_iters=8, lam=1e-4, parallelism="model"
    )
    pred = np.asarray(est.fit(jnp.asarray(X), jnp.asarray(Y)).apply_batch(X))
    resid = np.linalg.norm(pred - Y) / np.linalg.norm(Y)
    assert resid < 5e-3, resid


def test_block_ls_model_parallel_rejects_weights(rng):
    from keystone_tpu.nodes.learning import BlockWeightedLeastSquaresEstimator

    X = rng.normal(size=(64, 16)).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, size=64)]
    with pytest.raises(ValueError, match="weight"):
        BlockWeightedLeastSquaresEstimator(
            num_iters=2, lam=1e-3, parallelism="model"
        ).fit(X, Y)


def test_kernel_ridge_nystrom_preconditioner(rng):
    """PCG must (a) agree with the plain CG solution and (b) converge in
    strictly fewer iterations on an ill-conditioned RBF system (wide
    kernel, small lam) — the regime the preconditioner exists for."""
    n, d, k = 600, 12, 2
    X = rng.normal(size=(n, d)).astype(np.float32)
    Y = rng.normal(size=(n, k)).astype(np.float32)
    gamma, lam = 0.05, 1e-3
    plain = KernelRidgeRegression(gamma=gamma, lam=lam, max_iters=500, tol=1e-4)
    m_plain = plain.fit(X, Y)
    pre = KernelRidgeRegression(
        gamma=gamma, lam=lam, max_iters=500, tol=1e-4, precond_landmarks=200
    )
    m_pre = pre.fit(X, Y)
    # Same stopping rule, same operator: both land on the same system
    # solution within the residual tolerance.
    sq = ((X[:, None, :] - X[None, :, :]) ** 2).sum(-1)
    K = np.exp(-gamma * sq)
    for m in (m_plain, m_pre):
        resid = np.linalg.norm(
            (K + lam * np.eye(n)) @ np.asarray(m.alpha) - Y
        ) / np.linalg.norm(Y)
        assert resid < 1e-3
    assert pre.last_cg_iters < plain.last_cg_iters / 2


def test_kernel_ridge_preconditioned_padded_rows(rng):
    """n not divisible by the mesh: padded rows must stay inert under the
    preconditioner exactly as under plain CG."""
    n, d = 150, 5  # 150 % 8 != 0
    X = rng.normal(size=(n, d)).astype(np.float32)
    Y = rng.normal(size=(n, 2)).astype(np.float32)
    gamma, lam = 0.3, 0.1
    est = KernelRidgeRegression(
        gamma=gamma, lam=lam, max_iters=400, tol=1e-7, precond_landmarks=64
    )
    model = est.fit(X, Y)
    sq = ((X[:, None, :] - X[None, :, :]) ** 2).sum(-1)
    K = np.exp(-gamma * sq)
    alpha = np.linalg.solve(K + lam * np.eye(n), Y.astype(np.float64))
    np.testing.assert_allclose(np.asarray(model.alpha), alpha, atol=1e-2)


def test_block_weighted_matches_weighted_ridge_oracle(rng):
    # Full check incl. intercept: weighted centering must reproduce the
    # exact weighted-ridge-with-intercept optimum in the single-block case.
    X = rng.normal(size=(200, 10)).astype(np.float32) + 1.5
    y = (rng.uniform(size=200) < 0.2).astype(int)
    Y = (2 * np.eye(2)[y] - 1).astype(np.float32)
    est = BlockWeightedLeastSquaresEstimator(
        block_size=10, num_iters=1, lam=0.3, mixture_weight=1.0
    )
    model = est.fit(X, Y)
    w = np.asarray(est._weights(Y)).astype(np.float64)
    Xd, Yd = X.astype(np.float64), Y.astype(np.float64)
    xm = (w[:, None] * Xd).sum(0) / w.sum()
    ym = (w[:, None] * Yd).sum(0) / w.sum()
    Xc, Yc = Xd - xm, Yd - ym
    W = np.linalg.solve(
        (Xc * w[:, None]).T @ Xc + 0.3 * np.eye(10), (Xc * w[:, None]).T @ Yc
    )
    b = ym - xm @ W
    np.testing.assert_allclose(np.asarray(model.W), W, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(model.b), b, rtol=1e-3, atol=1e-3)


def test_block_ls_streaming_matches_device_path(rng):
    X = rng.normal(size=(300, 24)).astype(np.float32) + 0.5
    Y = rng.normal(size=(300, 4)).astype(np.float32)
    dev = BlockLeastSquaresEstimator(block_size=8, num_iters=3, lam=0.1, stream=False).fit(X, Y)
    str_ = BlockLeastSquaresEstimator(block_size=8, num_iters=3, lam=0.1, stream=True).fit(X, Y)
    np.testing.assert_allclose(np.asarray(str_.W), np.asarray(dev.W), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(str_.b), np.asarray(dev.b), rtol=1e-4, atol=1e-4)


def test_block_weighted_streaming_matches_device_path(rng):
    X = rng.normal(size=(240, 16)).astype(np.float32)
    y = (rng.uniform(size=240) < 0.2).astype(int)
    Y = (2 * np.eye(2)[y] - 1).astype(np.float32)
    kw = dict(block_size=8, num_iters=2, lam=0.2, mixture_weight=1.0)
    dev = BlockWeightedLeastSquaresEstimator(stream=False, **kw).fit(X, Y)
    str_ = BlockWeightedLeastSquaresEstimator(stream=True, **kw).fit(X, Y)
    np.testing.assert_allclose(np.asarray(str_.W), np.asarray(dev.W), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(str_.b), np.asarray(dev.b), rtol=1e-4, atol=1e-4)
