"""Profile-guided optimization tests (ISSUE-12): the measured-profile
store (workflow/profile_store.py) and the rules that consume it
(workflow/rules.py), end to end.

The contract pinned here:

- ``Pipeline.fit(profile=True)`` attaches a ``FitProfile`` handle to the
  fitted pipeline and auto-persists the measured per-node rows to the
  store (``KEYSTONE_PROFILE_STORE`` / ``config.profile_store``), keyed
  by the pipeline's content-stable digest + runtime fingerprint.
- On a store hit, ``AutoCacheRule`` / ``NodeOptimizationRule`` /
  ``PlanResourcesRule`` consume MEASURED costs with ZERO sample-run
  executions (the acceptance pin: the ``Profiler`` entry points are
  replaced with ``raise`` and optimization still completes), and the
  resulting plan is bit-stable across export -> reload.
- A fingerprint-incompatible entry is refused with the typed
  ``ProfileFingerprintError``; corrupt / tampered / unknown-version
  entries are SKIPPED with a warning and the optimizer degrades to the
  sampled path instead of crashing.
- KG202 cache advice goes quiet once the optimizer acts on it; KG203
  reports a stored profile that model-only optimization would ignore.
- ``PlanResourcesRule`` turns measured bytes-per-row into a planned
  solver chunk size (``planned_chunk_rows``) and the graph's branch
  width into an executor worker plan — explicit knobs always win.
"""

import glob
import json
import logging
import os

import numpy as np
import pytest

from keystone_tpu.config import config
from keystone_tpu.nodes.learning.least_squares import (
    LeastSquaresEstimator,
    SolverChoice,
)
from keystone_tpu.nodes.learning.linear_mapper import LinearMapEstimator
from keystone_tpu.utils.metrics import profile_scope, resource_profile
from keystone_tpu.workflow import Pipeline, Transformer
from keystone_tpu.workflow import profile_store as ps
from keystone_tpu.workflow import rules
from keystone_tpu.workflow.cache import CacheOperator, Profiler
from keystone_tpu.workflow.executor import PipelineEnv
from keystone_tpu.workflow.graph import Graph, fresh_source_id
from keystone_tpu.workflow.operators import (
    DatasetOperator,
    GatherOperator,
    TransformerOperator,
)


class HostWork(Transformer):
    """Deterministic host-bound featurizer: heavy enough (~ms per call)
    to clear the auto-cache wall floor, with a FIXED iteration count so
    every output (and the bit-identity assertions) is exact."""

    jittable = False

    def __init__(self, seed: int, iters: int = 16):
        self.seed, self.iters = int(seed), int(iters)

    def signature(self):
        return self.stable_signature(self.seed, self.iters)

    def apply_batch(self, X):
        Y = np.asarray(X, dtype=np.float32)
        rng = np.random.default_rng(self.seed)
        filt = (1.0 + rng.uniform(size=Y.shape[1] // 2 + 1)).astype(
            np.complex64
        )
        for _ in range(self.iters):
            spec = np.fft.rfft(Y, axis=1) * filt
            Y = np.tanh(Y + np.fft.irfft(
                spec, n=Y.shape[1], axis=1
            ).astype(np.float32))
        return Y


class ScaleBy(Transformer):
    jittable = True

    def __init__(self, c: float):
        self.c = float(c)

    def signature(self):
        return self.stable_signature(self.c)

    def apply_batch(self, X):
        return X * self.c


N, D, K = 256, 64, 4


def _data(n=N, d=D, k=K):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, d)).astype(np.float32)
    Y = (X @ rng.normal(size=(d, k)).astype(np.float32)).astype(np.float32)
    return X, Y


def build_reused_subchain(X, Y, estimator=None):
    """The canonical re-used-subchain pipeline: one heavy prefix fanned
    out to two consumers, gathered into a solve."""
    prefix = HostWork(seed=1).to_pipeline()
    b1 = prefix.and_then(ScaleBy(2.0))
    b2 = prefix.and_then(ScaleBy(0.5))
    return Pipeline.gather([b1, b2]).and_then(
        estimator or LinearMapEstimator(lam=1e-3), X, Y
    )


def dataset_rooted_reused_graph(X):
    """The fit-side shape alone: Dataset -> heavy prefix -> two consumers
    -> gather, no source-fed serve template (whose re-used prefix the
    optimizer legitimately cannot cache — it depends on runtime input)."""
    src = fresh_source_id()
    g, data = Graph().add(DatasetOperator(X), [])
    g, prefix = g.add(TransformerOperator(HostWork(seed=1)), [data])
    g, b1 = g.add(TransformerOperator(ScaleBy(2.0)), [prefix])
    g, b2 = g.add(TransformerOperator(ScaleBy(0.5)), [prefix])
    g, out = g.add(GatherOperator(), [b1, b2])
    return g, src, out


@pytest.fixture
def store(tmp_path, monkeypatch):
    """A private profile store + full isolation of the knobs and
    process-wide state the loop touches. The store is pinned via the
    ENV var (which wins over config.profile_store), so a developer
    machine exporting KEYSTONE_PROFILE_STORE cannot leak in."""
    prior = (config.auto_cache, config.plan_resources,
             config.solve_chunk_rows, config.exec_workers)
    path = str(tmp_path / "profiles")
    monkeypatch.setenv("KEYSTONE_PROFILE_STORE", path)
    PipelineEnv.reset()
    resource_profile.reset()
    rules.clear_decisions()
    ps._load_memo.clear()
    yield path
    (config.auto_cache, config.plan_resources,
     config.solve_chunk_rows, config.exec_workers) = prior
    PipelineEnv.reset()
    resource_profile.reset()
    rules.clear_decisions()
    ps._load_memo.clear()


def _entry_paths(store_dir):
    return sorted(glob.glob(os.path.join(store_dir, "*.json")))


def _profiled_fit(pipe):
    PipelineEnv.reset()
    fitted = pipe.fit(profile=True)
    return fitted


def _boom(self, *a, **k):
    raise AssertionError("sample run executed on the measured path")


# ---------------------------------------------------------------------------
# FitProfile handle + store artifact
# ---------------------------------------------------------------------------


def test_fit_profile_handle_attached_and_autosaved(store):
    X, Y = _data()
    fitted = _profiled_fit(build_reused_subchain(X, Y))
    fp = fitted.fit_profile
    assert isinstance(fp, ps.FitProfile)
    assert fp.pipeline_digest and fp.rows and fp.digests
    assert fp.saved_to and os.path.exists(fp.saved_to)
    # Handle rows are THIS fit's delta, renderable without the registry.
    assert "wall_ms" in fp.table() or "wall" in fp.table()
    doc = json.load(open(fp.saved_to))
    assert doc["version"] == ps.STORE_VERSION
    assert doc["pipeline_digest"] == fp.pipeline_digest
    assert set(doc["fingerprint"]) == {"backend", "device_kind",
                                       "device_count"}
    assert doc["payload_digest"] == ps._payload_digest(
        doc["digests"], doc["rows"]
    )
    # The measured aggregates carry what the rules price with.
    entry = next(iter(doc["digests"].values()))
    assert {"label", "calls", "wall_ns", "out_bytes", "out_rows"} <= set(
        entry
    )


def test_fit_profile_without_store_attached_not_saved(store, monkeypatch):
    # An exported EMPTY env var explicitly disables the store.
    monkeypatch.setenv("KEYSTONE_PROFILE_STORE", "")
    X, Y = _data()
    fitted = _profiled_fit(build_reused_subchain(X, Y))
    fp = fitted.fit_profile
    assert fp is not None and fp.saved_to is None
    with pytest.raises(ps.ProfileStoreError):
        fp.save()  # still no store configured
    fp.save(store_dir=store)  # explicit destination works
    assert fp.saved_to and os.path.exists(fp.saved_to)


def test_warm_session_refit_keeps_stored_measurements(store):
    """A second fit(profile=True) in the same session serves every node
    from the fit cache — its EMPTY delta must keep the cold fit's store
    entry, not clobber it with zero rows (which would silently turn
    every later measured optimization into a no-op)."""
    X, Y = _data()
    p = build_reused_subchain(X, Y)
    cold = _profiled_fit(p)
    n_rows = len(json.load(open(cold.fit_profile.saved_to))["digests"])
    assert n_rows > 0
    warm = p.fit(profile=True)  # same session: full fit-cache hit
    assert len(json.load(
        open(cold.fit_profile.saved_to)
    )["digests"]) == n_rows
    # The warm handle knows it has nothing to store.
    if not warm.fit_profile.digests:
        with pytest.raises(ps.ProfileStoreError, match="no executions"):
            warm.fit_profile.save()
    # And an artificially emptied entry never shadows the sampled path.
    ps.save_profile(cold.fit_profile.pipeline_digest, {}, [])
    ps._load_memo.clear()
    assert ps.lookup_measured(cold.fit_profile.pipeline_digest) is None


def test_nested_optimization_restores_outer_plan(store):
    """An interleaved/nested optimize-and-execute (sub-pipeline fit,
    concurrent apply) must not retire the plan an enclosing solve is
    still reading: orchestration points restore the outer plan on
    exit."""
    X, _ = _data(n=64, d=16)
    g, _src, out = dataset_rooted_reused_graph(X)
    env = PipelineEnv.get()
    env.resource_plan["solve_chunk_rows"] = 77  # the outer pass's plan
    env.optimize_and_execute(g, out)  # nested pass, no profile of its own
    assert env.resource_plan.get("solve_chunk_rows") == 77


def test_plain_fit_attaches_no_profile(store):
    X, Y = _data()
    PipelineEnv.reset()
    fitted = build_reused_subchain(X, Y).fit()
    assert getattr(fitted, "fit_profile", None) is None
    assert not _entry_paths(store)


def test_forced_profile_apply_saves_store_entry(store):
    """A profiled EXECUTION (not just fit) persists its measured walk
    too: the dataset-rooted graph run under profile_scope() lands in the
    store keyed by its own digest — profile-once covers apply graphs."""
    X, _ = _data()
    g, _src, out = dataset_rooted_reused_graph(X)
    PipelineEnv.reset()
    with profile_scope():
        PipelineEnv.get().optimize_and_execute(g, out)
    assert len(_entry_paths(store)) == 1
    digest = ps.pipeline_profile_digest(g, out)
    assert ps.has_profile(digest)
    loaded = ps.load_profile(digest)
    assert loaded is not None and loaded.digests


# ---------------------------------------------------------------------------
# Zero sample runs + bit-stable plans on a store hit
# ---------------------------------------------------------------------------


def test_zero_sample_runs_end_to_end(store, monkeypatch):
    """THE acceptance pin: with a stored measured profile, auto-cache +
    node-level solver dispatch both run from measurements — zero
    sample-run executions (any ``Profiler`` entry raises) — and
    predictions stay bit-identical to the un-optimized arm."""
    X, Y = _data(n=512, d=128)

    def build():
        return build_reused_subchain(X, Y, LeastSquaresEstimator(lam=1e-3))

    # Off-arm reference + profile phase, with sampling available.
    PipelineEnv.reset()
    ref = np.asarray(build().fit().apply(X).get())
    _profiled_fit(build())
    assert len(_entry_paths(store)) >= 1

    # On-arm: store hit, sampling FORBIDDEN, optimizer fully on.
    monkeypatch.setattr(Profiler, "profile", _boom)
    monkeypatch.setattr(Profiler, "sample_values", _boom)
    PipelineEnv.reset()
    rules.clear_decisions()
    config.auto_cache = True
    try:
        fitted = build().fit()
    finally:
        config.auto_cache = False
    out = np.asarray(fitted.apply(X).get())

    decisions = rules.optimizer_decisions()
    assert any(d.action == "cache-insert" and d.provenance == "measured"
               for d in decisions)
    assert all(d.provenance == "measured" for d in decisions
               if d.rule == "AutoCacheRule")
    # The deep-graph estimator's solver dispatch resolved its (n, d)
    # from MEASURED output shapes, not a sampled prefix run.
    solver = [d for d in decisions if d.rule == "NodeOptimizationRule"]
    assert solver and solver[0].provenance == "measured"
    assert solver[0].action.startswith("solver=")
    assert out.shape == ref.shape and np.array_equal(out, ref)


def test_export_reload_identical_decisions_bit_stable_plan(store):
    X, Y = _data()

    def build():
        return build_reused_subchain(X, Y)

    _profiled_fit(build())

    def optimize():
        PipelineEnv.reset()
        rules.clear_decisions()
        config.auto_cache = True
        try:
            p = build()
            g = PipelineEnv.get().optimizer.execute(p.graph, [p.sink])
        finally:
            config.auto_cache = False
        caches = sorted(
            g.operators[g.dependencies[nid][0]].label()
            for nid, op in g.operators.items()
            if isinstance(op, CacheOperator)
        )
        return caches, [d.as_dict() for d in rules.optimizer_decisions()]

    caches_a, decisions_a = optimize()
    assert caches_a  # the heavy prefix earned its cache
    ps._load_memo.clear()  # force a true reload from disk
    caches_b, decisions_b = optimize()
    assert caches_a == caches_b
    assert decisions_a == decisions_b


# ---------------------------------------------------------------------------
# Store refusal semantics
# ---------------------------------------------------------------------------


def _tamper(path, mutate):
    doc = json.load(open(path))
    mutate(doc)
    with open(path, "w") as f:
        json.dump(doc, f)


def test_fingerprint_mismatch_refused_with_typed_error(store, caplog):
    X, Y = _data()
    fitted = _profiled_fit(build_reused_subchain(X, Y))
    fp = fitted.fit_profile
    _tamper(fp.saved_to, lambda doc: doc.__setitem__(
        "fingerprint",
        {"backend": "tpu", "device_kind": "TPU v4", "device_count": 8},
    ))
    ps._load_memo.clear()
    with pytest.raises(ps.ProfileFingerprintError) as ei:
        ps.load_profile(fp.pipeline_digest)
    assert "re-profile" in str(ei.value)
    # The rules' entry point degrades to no-profile, loudly.
    with caplog.at_level(logging.WARNING, logger="keystone_tpu"):
        assert ps.lookup_measured(fp.pipeline_digest) is None
    assert any("incompatible" in r.message for r in caplog.records)


def test_corrupt_entry_skipped_with_warning_not_crash(store, caplog):
    X, Y = _data()
    fitted = _profiled_fit(build_reused_subchain(X, Y))
    path = fitted.fit_profile.saved_to
    with open(path, "w") as f:
        f.write("{definitely not json")
    ps._load_memo.clear()
    with caplog.at_level(logging.WARNING, logger="keystone_tpu"):
        assert ps.load_profile(fitted.fit_profile.pipeline_digest) is None
    assert any("skipping" in r.message for r in caplog.records)
    # The optimizer pass survives: it falls back to the SAMPLED path.
    PipelineEnv.reset()
    rules.clear_decisions()
    config.auto_cache = True
    try:
        build_reused_subchain(X, Y).fit()
    finally:
        config.auto_cache = False
    cache_decisions = [d for d in rules.optimizer_decisions()
                       if d.rule == "AutoCacheRule"]
    assert cache_decisions
    assert all(d.provenance == "sampled" for d in cache_decisions)


def test_tampered_payload_skipped(store, caplog):
    X, Y = _data()
    fitted = _profiled_fit(build_reused_subchain(X, Y))
    fp = fitted.fit_profile

    def flip_wall(doc):
        entry = next(iter(doc["digests"].values()))
        entry["wall_ns"] = int(entry["wall_ns"]) * 1000  # lie bigger

    _tamper(fp.saved_to, flip_wall)
    ps._load_memo.clear()
    with caplog.at_level(logging.WARNING, logger="keystone_tpu"):
        assert ps.load_profile(fp.pipeline_digest) is None
    assert any("payload digest mismatch" in r.message
               for r in caplog.records)


def test_unknown_schema_version_skipped(store, caplog):
    X, Y = _data()
    fitted = _profiled_fit(build_reused_subchain(X, Y))
    fp = fitted.fit_profile
    _tamper(fp.saved_to, lambda doc: doc.__setitem__("version", 99))
    ps._load_memo.clear()
    with caplog.at_level(logging.WARNING, logger="keystone_tpu"):
        assert ps.load_profile(fp.pipeline_digest) is None
    assert any("schema version" in r.message for r in caplog.records)


# ---------------------------------------------------------------------------
# Lint integration: KG202 goes quiet, KG203 says "you have measurements"
# ---------------------------------------------------------------------------


def test_kg202_quiet_after_optimizer_inserts_cache(store):
    """The advice loop closes: the canonical re-used subchain lints
    KG202 un-optimized, and lints CLEAN after the profile-guided
    optimizer inserts the cache node it was asking for."""
    from keystone_tpu.workflow.analysis import lint_graph

    X, _ = _data()
    g, src, out = dataset_rooted_reused_graph(X)
    assert lint_graph(g, src, out, example=(D,),
                      have_ladder=True).by_rule("KG202")

    # Profile the walk, then let the optimizer consume the measurements.
    PipelineEnv.reset()
    with profile_scope():
        PipelineEnv.get().optimize_and_execute(g, out)
    PipelineEnv.reset()
    config.auto_cache = True
    try:
        g_on = PipelineEnv.get().optimizer.execute(g, [out])
    finally:
        config.auto_cache = False
    assert any(isinstance(op, CacheOperator)
               for op in g_on.operators.values())
    assert not lint_graph(g_on, src, out, example=(D,),
                          have_ladder=True).by_rule("KG202")


def test_kg203_reports_unconsumed_profile(store):
    X, Y = _data()
    p = build_reused_subchain(X, Y)
    # No store entry yet: silent.
    assert not p.lint(example=(D,), have_ladder=True).by_rule("KG203")
    _profiled_fit(p)
    # Entry exists, optimization is model-only: say so.
    found = p.lint(example=(D,), have_ladder=True).by_rule("KG203")
    assert found and found[0].severity == "info"
    assert "auto_cache" in found[0].message
    # Optimizer on: the profile WILL be consumed — silent again.
    config.auto_cache = True
    try:
        assert not p.lint(example=(D,),
                          have_ladder=True).by_rule("KG203")
    finally:
        config.auto_cache = False
    # Store disabled (exported empty): silent, and no digest walk at all.
    import os as os_mod

    os_mod.environ["KEYSTONE_PROFILE_STORE"] = ""
    try:
        assert not p.lint(example=(D,),
                          have_ladder=True).by_rule("KG203")
    finally:
        os_mod.environ["KEYSTONE_PROFILE_STORE"] = store


# ---------------------------------------------------------------------------
# PlanResourcesRule: workers + solver chunk rows
# ---------------------------------------------------------------------------


def test_plan_workers_from_branch_width(store, monkeypatch):
    import os as os_mod

    X, Y = _data()
    _profiled_fit(build_reused_subchain(X, Y))
    monkeypatch.setattr(os_mod, "cpu_count", lambda: 4)
    PipelineEnv.reset()
    rules.clear_decisions()
    p = build_reused_subchain(X, Y)
    PipelineEnv.get().optimizer.execute(p.graph, [p.sink])
    plan = PipelineEnv.get().resource_plan
    # Two independent branches on a "4-core" host: plan 2 workers.
    assert plan.get("exec_workers") == 2
    planned = [d for d in rules.optimizer_decisions()
               if d.rule == "PlanResourcesRule"
               and d.action.startswith("exec_workers=")]
    assert planned and planned[0].provenance == "measured"
    assert planned[0].cost["branch_width"] == 2


def test_plan_workers_serial_on_one_core(store, monkeypatch):
    import os as os_mod

    X, Y = _data()
    _profiled_fit(build_reused_subchain(X, Y))
    monkeypatch.setattr(os_mod, "cpu_count", lambda: 1)
    PipelineEnv.reset()
    rules.clear_decisions()
    p = build_reused_subchain(X, Y)
    PipelineEnv.get().optimizer.execute(p.graph, [p.sink])
    assert "exec_workers" not in PipelineEnv.get().resource_plan
    kept = [d for d in rules.optimizer_decisions()
            if d.action == "exec_workers=0"]
    assert kept and "serial walk kept" in kept[0].reason


def test_plan_chunk_rows_from_measured_bytes_per_row(store, monkeypatch):
    """Measured bytes-per-row vs a (shrunk) HBM budget turns into a
    planned chunk size: PR-3's reactive OOM-halving becomes a plan. The
    chunk is row-sharded over the mesh, so the budget prices bytes/row ÷
    shard count: 64 rows per DEVICE x the 8-shard test mesh."""
    import keystone_tpu.utils.metrics as metrics_mod

    X, Y = _data(n=512, d=128)
    p = build_reused_subchain(X, Y, LeastSquaresEstimator(lam=1e-3))
    _profiled_fit(p)
    # Estimator input: 512 rows x 256 features f32 = 1024 B/row. An HBM
    # of 256 KiB / CHUNK_BUDGET_FRAC=8 budgets 32768 B -> 32 rows per
    # device -> 256 planned rows across the 8-shard mesh (< the 512
    # measured rows, so the plan actually lands).
    monkeypatch.setattr(metrics_mod, "device_hbm_bytes", lambda: 262144)
    PipelineEnv.reset()
    rules.clear_decisions()
    p2 = build_reused_subchain(X, Y, LeastSquaresEstimator(lam=1e-3))
    PipelineEnv.get().optimizer.execute(p2.graph, [p2.sink])
    plan = PipelineEnv.get().resource_plan
    from keystone_tpu.utils.mesh import num_data_shards

    assert num_data_shards() == 8
    assert plan.get("solve_chunk_rows") == 32 * 8
    planned = [d for d in rules.optimizer_decisions()
               if d.action.startswith("solve_chunk_rows=")]
    assert planned and planned[0].provenance == "measured"
    assert planned[0].cost["bytes_per_row"] == 1024.0


def test_plan_cleared_when_next_pipeline_has_no_profile(store):
    """A plan derived from one profiled pipeline must not leak into an
    unrelated pipeline's solve in the same session: the rule clears its
    keys at every pass entry (a planned chunk split regroups the gram
    accumulation — numerics the other pipeline never opted into)."""
    X, Y = _data()
    plan = PipelineEnv.get().resource_plan
    plan["solve_chunk_rows"] = 99  # stale plan from a profiled pipeline
    plan["exec_workers"] = 7
    p = build_reused_subchain(X, Y)  # no store entry for this one
    PipelineEnv.get().optimizer.execute(p.graph, [p.sink])
    assert "solve_chunk_rows" not in plan
    assert "exec_workers" not in plan
    # Disabling the planner mid-session also retires its last plan: the
    # clear runs BEFORE the enable gate.
    plan["solve_chunk_rows"] = 99
    config.plan_resources = False
    try:
        PipelineEnv.get().optimizer.execute(p.graph, [p.sink])
    finally:
        config.plan_resources = True
    assert "solve_chunk_rows" not in plan


def test_measured_pricing_has_no_consumer_multiplier(store):
    """The executor's structural-hash memo runs a multi-consumer node
    ONCE per walk, so a cache saves one re-execution per later walk —
    pricing must not multiply the saving by consumer count. A node
    measured at 6 ms with a 20 MB output (materialize ~10 ms at the
    assumed 2 GB/s) must be SKIPPED even though 6 ms x 2 consumers
    would beat materialization."""
    from keystone_tpu.workflow.graph import structural_digest

    X, _ = _data()
    g, _src, out = dataset_rooted_reused_graph(X)
    prefix_nid = next(n for n, op in g.operators.items()
                      if "HostWork" in op.label())
    entry = {"label": "HostWork", "calls": 1, "wall_ns": 6_000_000,
             "out_bytes": 20_000_000, "out_rows": 256,
             "queue_wait_ns": 0, "out_shape": [256, 64]}
    ps.save_profile(
        ps.pipeline_profile_digest(g, out),
        {structural_digest(g, prefix_nid): entry}, rows=[],
    )
    PipelineEnv.reset()
    rules.clear_decisions()
    config.auto_cache = True
    try:
        g_on = PipelineEnv.get().optimizer.execute(g, [out])
    finally:
        config.auto_cache = False
    assert not any(isinstance(op, CacheOperator)
                   for op in g_on.operators.values())
    skip = [d for d in rules.optimizer_decisions()
            if d.action == "cache-skip" and d.node == "HostWork"]
    assert skip and "cheaper than materialization" in skip[0].reason


def test_env_pin_beats_session_plan(store, monkeypatch):
    """An explicitly exported 0 pins its setting: the planner never
    overrides an explicit knob, including the 'off' value."""
    from keystone_tpu.linalg.normal_equations import planned_chunk_rows

    PipelineEnv.get().resource_plan["solve_chunk_rows"] = 32
    monkeypatch.setenv("KEYSTONE_SOLVE_CHUNK_ROWS", "0")
    assert planned_chunk_rows() == 0
    # The env is read LIVE (resolved_cache_dir convention): a late
    # export of a nonzero value wins too, not just the 0 pin.
    monkeypatch.setenv("KEYSTONE_SOLVE_CHUNK_ROWS", "4096")
    assert planned_chunk_rows() == 4096
    monkeypatch.delenv("KEYSTONE_SOLVE_CHUNK_ROWS")
    assert planned_chunk_rows() == 32


def test_exec_workers_env_pin_keeps_serial_walk(store, monkeypatch):
    """KEYSTONE_EXEC_WORKERS=0 exported pins the byte-identical legacy
    serial loop even when a session plan exists; with the default
    (unset), the same plan engages the parallel walk. Driven through
    the executor directly — the plan consumer — since an optimizer pass
    would (correctly) clear a plan that has no matching profile."""
    from keystone_tpu.workflow import executor as executor_mod

    def forbidden(*a, **k):
        raise AssertionError("parallel walk constructed under env pin")

    X, _ = _data(n=64, d=16)
    g, _src, out = dataset_rooted_reused_graph(X)
    monkeypatch.setattr(executor_mod, "_ParallelWalk", forbidden)
    PipelineEnv.reset()
    env = PipelineEnv.get()
    env.resource_plan["exec_workers"] = 4
    monkeypatch.setenv("KEYSTONE_EXEC_WORKERS", "0")
    env.executor.execute(g, out)  # serial: forbidden never fires
    monkeypatch.delenv("KEYSTONE_EXEC_WORKERS")
    env.resource_plan["exec_workers"] = 4
    with pytest.raises(AssertionError, match="parallel walk constructed"):
        env.executor.execute(g, out)


def test_planned_chunk_rows_resolution_order(store):
    from keystone_tpu.linalg.normal_equations import planned_chunk_rows

    PipelineEnv.get().resource_plan["solve_chunk_rows"] = 32
    assert planned_chunk_rows() == 32  # session plan when knob unset
    config.solve_chunk_rows = 16
    try:
        assert planned_chunk_rows() == 16  # explicit knob always wins
    finally:
        config.solve_chunk_rows = 0


def test_planned_split_replaces_reactive_halving(store):
    """A chunk over the planned bound splits BEFORE any transfer and the
    split is counted. Splitting regroups the gram accumulation exactly
    like feeding the smaller chunks directly — planned 128-row chunks
    split at 32 are BIT-identical to a native 32-row stream (and agree
    with the unsplit solve to float tolerance, the same contract as the
    reactive OOM halving it replaces)."""
    from keystone_tpu.linalg import solve_least_squares_chunked
    from keystone_tpu.utils.metrics import reliability_counters

    rng = np.random.default_rng(3)
    X = rng.normal(size=(256, 16)).astype(np.float32)
    Y = (X @ rng.normal(size=(16, 2)).astype(np.float32))

    def chunks(rows):
        for s in range(0, 256, rows):
            yield X[s:s + rows], Y[s:s + rows]

    unsplit = np.asarray(solve_least_squares_chunked(
        chunks(128), lam=1e-3, prefetch_depth=0
    ))
    native32 = np.asarray(solve_least_squares_chunked(
        chunks(32), lam=1e-3, prefetch_depth=0
    ))
    before = reliability_counters.get("planned_chunk_splits")
    config.solve_chunk_rows = 32
    try:
        planned = np.asarray(solve_least_squares_chunked(
            chunks(128), lam=1e-3, prefetch_depth=0
        ))
    finally:
        config.solve_chunk_rows = 0
    assert reliability_counters.get("planned_chunk_splits") - before >= 2
    assert np.array_equal(native32, planned)
    assert np.allclose(unsplit, planned, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# Solver dispatch dedup (satellite 1)
# ---------------------------------------------------------------------------


def test_solver_choice_single_constructor_path():
    from keystone_tpu.nodes.learning.block_least_squares import (
        BlockLeastSquaresEstimator,
    )
    from keystone_tpu.nodes.learning.local_least_squares import (
        LocalLeastSquaresEstimator,
    )

    est = LeastSquaresEstimator(lam=1e-3)
    assert isinstance(est._concrete(SolverChoice("local", "")),
                      LocalLeastSquaresEstimator)
    assert isinstance(est._concrete(SolverChoice("normal", "")),
                      LinearMapEstimator)
    assert isinstance(est._concrete(SolverChoice("block", "")),
                      BlockLeastSquaresEstimator)
    with pytest.raises(ValueError, match="unknown solver choice"):
        est._concrete(SolverChoice("bogus", ""))


# ---------------------------------------------------------------------------
# Tools: decision table + bench harness (in-process --quick)
# ---------------------------------------------------------------------------


def _tools(name):
    import importlib
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "tools"))
    try:
        return importlib.import_module(name)
    finally:
        sys.path.pop(0)


def test_decision_table_renders_provenance_and_cost():
    profile_report = _tools("profile_report")
    d = rules.OptimizerDecision(
        rule="AutoCacheRule", node="HostWork", action="cache-insert",
        provenance="measured", reason="why",
        cost={"recompute_s": 0.01, "bytes": 42},
    )
    table = profile_report.render_decision_table([d])
    assert "cache-insert" in table and "measured" in table
    assert "recompute_s=0.01" in table and "bytes=42" in table
    assert profile_report.render_decision_table([]).startswith("(no ")


def test_decision_log_is_bounded():
    rules.clear_decisions()
    for i in range(rules._DECISIONS_CAP + 50):
        rules.record_decision("R", f"n{i}", "a", "model", "r")
    log = rules.optimizer_decisions()
    assert len(log) == rules._DECISIONS_CAP
    assert log[-1].node == f"n{rules._DECISIONS_CAP + 49}"
    rules.clear_decisions()


def test_bench_optimizer_quick_in_process(store):
    """`make bench-opt`'s harness at --quick scale: the row is
    well-formed, bit-identity holds, and the measured store hit ran
    zero sample executions (the speedup gate is timing and belongs to
    the bench, not tier-1)."""
    import argparse

    bench_optimizer = _tools("bench_optimizer")
    args = argparse.Namespace(
        reps=1, applies=1, rows=64, dim=32, classes=4, work_iters=4,
        min_speedup=1.2, quick=True, out=None,
    )
    row = bench_optimizer.run_bench(args)
    row.pop("_decisions")
    det = row["detail"]
    assert row["ok"], row
    assert det["bit_identical"] and det["zero_sample_runs"]
    assert set(det["pipelines"]) == {"reused_subchain", "two_branch"}


def test_profile_report_decisions_demo(store):
    profile_report = _tools("profile_report")
    result = profile_report.run_decisions_demo()
    assert result["ok"], result
    assert result["pass"]["measured_provenance_present"]
    assert "cache-insert" in result["table"]
