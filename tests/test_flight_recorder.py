"""Flight recorder, request-scoped tracing, and the export surface.

What is pinned here, mirroring ISSUE 8's acceptance gates:

1. every accepted request gets a monotonic id and an always-on journey
   record whose phase chain (submitted → flushed → dispatched →
   resolved) survives into ``debug_dump()`` with rows/bucket/replica and
   the final outcome;
2. the forensics round-trip: under an injected ``replica_death`` with 4
   concurrent clients, the AUTO-dumped flight record names every
   re-queued request (requeued phase), and the post-resolution dump
   reconstructs each full journey — re-dispatched requests show BOTH
   replicas;
3. deadline storms and watchdog stalls auto-dump (and the stall bumps
   the ``serve.stalls`` registry counter) instead of failing silently;
4. request-scoped causal tracing: ``serve.queued``/``serve.request``
   spans carry ``req_id``, ``serve.device``/``serve.flush`` carry
   ``req_ids``, and the cross-thread journey reassembles per id; tail
   sampling retains full span trees only for threshold-breaching
   requests;
5. the pull surface: ``MetricsRegistry.prometheus()`` parses under the
   shared validator and agrees with ``snapshot()``; the stdlib metrics
   server serves /metrics + /healthz over a real socket (the
   ``make obs-serve`` smoke, in-process); ``tools/trace_report.py``
   fails loudly on an empty trace and reports a per-request critical
   path.
"""

import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

from keystone_tpu.config import config
from keystone_tpu.utils import reliability
from keystone_tpu.utils.flight_recorder import FlightRecorder, next_request_id
from keystone_tpu.utils.metrics import (
    active_tracer,
    metrics_registry,
    reliability_counters,
    reset_tracer,
)
from keystone_tpu.workflow.pipeline import FusedTransformer
from keystone_tpu.workflow.serving import CompiledPipeline, PipelineService

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture
def faults():
    """Arm a fault plan for the test (test_reliability's idiom)."""
    prior = (config.faults, config.faults_seed)
    reliability_counters.reset()

    def arm(spec: str, seed: int = 0):
        config.faults, config.faults_seed = spec, seed
        reliability.reset_fault_plan()
        return reliability.active_plan()

    arm("")
    yield arm
    config.faults, config.faults_seed = prior
    reliability.reset_fault_plan()
    reliability_counters.reset()


@pytest.fixture
def traced():
    """Arm process-wide tracing for the test (test_observability's
    idiom); also restores the tail-sampling knob."""
    prior = (config.trace, config.trace_tail_ms)

    def arm(on: bool = True, tail_ms: float = 0.0):
        config.trace = on
        config.trace_tail_ms = tail_ms
        reset_tracer()
        return active_tracer()

    try:
        yield arm
    finally:
        config.trace, config.trace_tail_ms = prior
        reset_tracer()


def _head(d=8, D=16, k=3, seed=0):
    from keystone_tpu.nodes.learning.linear_mapper import LinearMapper
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.nodes.stats.random_features import CosineRandomFeatures

    rng = np.random.default_rng(seed)
    return FusedTransformer(
        [
            CosineRandomFeatures.create(d, D, seed=seed),
            L2Normalizer(),
            LinearMapper(rng.normal(size=(D, k)).astype(np.float32)),
        ]
    )


def _phase_names(record):
    return [p["phase"] for p in record["phases"]]


# ---------------------------------------------------------------------------
# FlightRecorder unit behavior
# ---------------------------------------------------------------------------


def test_recorder_ring_bound_and_errors(tmp_path):
    fr = FlightRecorder("t0", capacity=4, directory=str(tmp_path))
    for i in range(10):
        rec = fr.start(i, rows=1)
        rec.finish("ok")
    snap = fr.snapshot()
    assert len(snap["records"]) == 4  # bounded ring, most recent kept
    assert [r["id"] for r in snap["records"]] == [6, 7, 8, 9]
    assert snap["records_started"] == 10
    for i in range(300):
        fr.error("boom", f"event {i}", rid=i)
    snap = fr.snapshot()
    assert len(snap["errors"]) == FlightRecorder.ERROR_CAPACITY
    assert snap["errors"][-1]["message"] == "event 299"
    # 0 = the repo-wide disabled convention: journey ring off, error
    # events and dumps intact; negative is a configuration error.
    off = FlightRecorder("t1", capacity=0, directory=str(tmp_path))
    off.start(1, rows=1).finish("ok")
    off.error("x", "still recorded")
    snap = off.snapshot()
    assert snap["records"] == [] and snap["records_started"] == 1
    assert len(snap["errors"]) == 1
    with pytest.raises(ValueError, match="capacity"):
        FlightRecorder("t1b", capacity=-1)


def test_recorder_dump_rate_limit_and_force(tmp_path):
    fr = FlightRecorder("t2", capacity=8, directory=str(tmp_path))
    rec = fr.start(next_request_id(), rows=3)
    rec.dispatched(1, 8)
    rec.finish("ok")
    p1 = fr.dump("stall")
    assert p1 is not None and os.path.exists(p1)
    assert fr.dump("stall") is None  # rate-limited per reason
    p2 = fr.dump("stall", force=True)
    assert p2 is not None and p2 != p1
    with open(p1) as f:
        doc = json.load(f)
    assert doc["reason"] == "stall"
    assert doc["service"] == "t2"
    assert doc["records"][0]["replicas"] == [1]
    assert doc["records"][0]["bucket"] == 8
    assert doc["records"][0]["outcome"] == "ok"
    assert _phase_names(doc["records"][0]) == [
        "submitted", "dispatched", "resolved",
    ]
    assert fr.dumps == [p1, p2]


def test_failed_dump_write_does_not_consume_rate_limit(tmp_path):
    """A transient write failure must not suppress the retry that would
    have captured the incident: the per-reason slot is stamped only
    after a successful write."""
    fr = FlightRecorder(
        "t4", capacity=4, directory=str(tmp_path / "does" / "not" / "exist")
    )
    assert fr.dump("replica_death") is None  # unwritable: fails, logged
    fr.directory = str(tmp_path)  # "disk back": the retry must land
    p = fr.dump("replica_death")
    assert p is not None and os.path.exists(p)
    assert fr.dumps == [p]
    assert fr.stats()["dumps_total"] == 1


def test_request_report_queue_wait_not_double_counted(tmp_path):
    """Re-dispatched requests record one serve.queued span per flush-group
    pop, all starting at submit: the critical-path view must take the
    longest (true residency), not their overlapping sum."""
    report = _load_tool("trace_report")
    doc = {
        "traceEvents": [
            {"name": "serve.queued", "cat": "serving", "ph": "X",
             "ts": 0.0, "dur": 1000.0, "pid": 1, "tid": 1,
             "args": {"req_id": 5, "rows": 2}},
            {"name": "serve.queued", "cat": "serving", "ph": "X",
             "ts": 0.0, "dur": 3000.0, "pid": 1, "tid": 1,
             "args": {"req_id": 5, "rows": 2}},
            {"name": "serve.device", "cat": "serving", "ph": "X",
             "ts": 3000.0, "dur": 500.0, "pid": 1, "tid": 2,
             "args": {"req_ids": [5]}},
            {"name": "serve.request", "cat": "serving", "ph": "X",
             "ts": 0.0, "dur": 4000.0, "pid": 1, "tid": 1,
             "args": {"req_id": 5, "outcome": "ok"}},
        ]
    }
    rep = report.request_report(doc, 5)
    assert rep["phases"]["queue_wait_ms"] == 3.0  # max, not 4.0 = sum
    assert rep["phases"]["e2e_ms"] == 4.0
    assert rep["phases"]["resolve_tail_ms"] == pytest.approx(0.5)


def test_note_dump_flushes_at_poll_not_inline(tmp_path):
    fr = FlightRecorder("t3", capacity=8, directory=str(tmp_path))
    fr.note_dump("worker_death")
    fr.note_dump("stall")  # first reason wins until flushed
    assert fr.dumps == []
    path = fr.poll()
    assert path is not None and "worker_death" in path
    assert fr.poll() is None  # pending cleared


# ---------------------------------------------------------------------------
# Journey records through the live service
# ---------------------------------------------------------------------------


def test_journey_records_full_phase_chain(rng, tmp_path):
    d = 8
    cp = CompiledPipeline(_head(d=d), max_batch=16, devices=2).warmup((d,))
    svc = PipelineService(
        cp, max_delay_ms=0.5, inflight=2, flight_dir=str(tmp_path)
    )
    try:
        xs = [rng.normal(size=(3, d)).astype(np.float32) for _ in range(12)]
        for x in xs:
            svc.submit(x).result(timeout=30)
        path = svc.debug_dump(str(tmp_path / "journeys.json"))
        with open(path) as f:
            doc = json.load(f)
        assert doc["reason"] == "debug"
        assert doc["stats"]["requests"] == 12  # context = service stats
        records = doc["records"]
        assert len(records) == 12
        ids = [r["id"] for r in records]
        assert ids == sorted(ids) and len(set(ids)) == 12  # monotonic mint
        for r in records:
            assert r["rows"] == 3
            assert r["outcome"] == "ok"
            assert r["bucket"] in cp.ladder
            assert len(r["replicas"]) >= 1
            names = _phase_names(r)
            # The journey in order: queued -> flushed -> dispatched ->
            # resolved, with monotone stamps.
            assert names[0] == "submitted" and names[-1] == "resolved"
            assert "flushed" in names and "dispatched" in names
            stamps = [p["t_ns"] for p in r["phases"]]
            assert stamps == sorted(stamps)
    finally:
        svc.close()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_replica_death_forensics_roundtrip(rng, faults, tmp_path):
    """The acceptance gate: injected replica_death, 4 concurrent
    clients — the auto-dumped flight record names the re-queued
    requests, and the post-resolution dump reconstructs every journey
    (re-dispatched requests show both replicas)."""
    faults("replica_death:1")
    d = 8
    cp = CompiledPipeline(_head(d=d), max_batch=16, devices=4).warmup((d,))
    ref = CompiledPipeline(_head(d=d), max_batch=16, devices=1).warmup((d,))
    trace = [
        rng.normal(size=(3, d)).astype(np.float32) for _ in range(60)
    ]
    errs: list = []
    svc = PipelineService(
        cp, max_delay_ms=0.5, inflight=2, flight_dir=str(tmp_path),
        watchdog_ms=200.0,
    )

    def client(cid):
        try:
            for i in range(cid, len(trace), 4):
                out = svc.submit(trace[i]).result(timeout=60)
                np.testing.assert_allclose(
                    out, ref(trace[i]), rtol=2e-6, atol=2e-6
                )
        except Exception as e:  # pragma: no cover - surfaced below
            errs.append(e)

    try:
        threads = [
            threading.Thread(target=client, args=(k,)) for k in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs[:2]
        assert svc.replica_deaths == 1
        # The AUTO dump fired (poll points / watchdog tick flush it).
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not any(
            "replica_death" in p for p in svc._flight.dumps
        ):
            time.sleep(0.02)
        auto = [p for p in svc._flight.dumps if "replica_death" in p]
        assert auto, "replica death did not auto-dump the flight recorder"
        with open(auto[0]) as f:
            auto_doc = json.load(f)
        assert auto_doc["reason"] == "replica_death"
        assert any(
            e["kind"] == "replica_death" for e in auto_doc["errors"]
        )
        requeued_auto = [
            r for r in auto_doc["records"]
            if "requeued" in _phase_names(r)
        ]
        assert requeued_auto, "auto dump lost the in-flight requests"
        # Post-resolution dump: the full journeys, final outcomes.
        final = svc.debug_dump(str(tmp_path / "final.json"))
        with open(final) as f:
            doc = json.load(f)
        records = {r["id"]: r for r in doc["records"]}
        assert len(records) == 60
        assert all(r["outcome"] == "ok" for r in records.values())
        redispatched = [
            r for r in records.values() if "requeued" in _phase_names(r)
        ]
        assert redispatched
        for r in redispatched:
            # Both replicas on the record: the dead one it was launched
            # on AND the survivor that actually served it.
            assert len(r["replicas"]) >= 2
            assert len(set(r["replicas"])) >= 2
            names = _phase_names(r)
            assert names.index("requeued") < len(names) - 1
            stamps = [p["t_ns"] for p in r["phases"]]
            assert stamps == sorted(stamps)
        # Every in-flight id the auto dump saw is reconstructed fully.
        for r in requeued_auto:
            assert records[r["id"]]["outcome"] == "ok"
    finally:
        svc.close()


def test_deadline_storm_auto_dump(rng, tmp_path):
    """A burst of expiries within one second marks a deadline_storm dump
    that flushes at the next unlocked point."""

    class Slowed:
        def __init__(self, inner, delay):
            self._inner, self._delay = inner, delay

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def __call__(self, X):
            time.sleep(self._delay)
            return self._inner(X)

    d = 8
    cp = CompiledPipeline(_head(d=d), max_batch=16, devices=1).warmup((d,))
    storm_n = config.serve_storm_expired
    svc = PipelineService(
        Slowed(cp, 0.15), max_delay_ms=1.0, inflight=1,
        flight_dir=str(tmp_path), watchdog_ms=200.0, max_pending=64,
    )
    try:
        x = rng.normal(size=(2, d)).astype(np.float32)
        first = svc.submit(x)  # occupies the worker for 150ms
        time.sleep(0.05)  # let the worker pop `first` alone: the doomed
        # requests below must QUEUE behind the slow flush, not coalesce
        # into it, so their 20ms deadlines lapse before the next pop.
        doomed = [
            svc.submit(x, deadline_ms=20.0) for _ in range(storm_n + 2)
        ]
        first.result(timeout=30)
        for f in doomed:
            with pytest.raises(Exception):
                f.result(timeout=30)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not any(
            "deadline_storm" in p for p in svc._flight.dumps
        ):
            time.sleep(0.02)
        storm = [p for p in svc._flight.dumps if "deadline_storm" in p]
        assert storm, "expiry burst did not auto-dump"
        with open(storm[0]) as f:
            doc = json.load(f)
        expired = [
            r for r in doc["records"] if r["outcome"] == "expired"
        ]
        assert len(expired) >= storm_n
    finally:
        svc.close()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_watchdog_detects_stall_and_recovers(rng, faults, tmp_path):
    """A dead dispatcher with queued work = a stall: the watchdog bumps
    serve.stalls, dumps the black box, and the next submit still heals
    the service (detection, not replacement, of the restart path)."""
    from keystone_tpu.workflow.serving import stall_counters

    faults("worker_death:1")
    d = 8
    cp = CompiledPipeline(_head(d=d), max_batch=16, devices=1).warmup((d,))
    svc = PipelineService(
        cp, max_delay_ms=0.5, inflight=1, flight_dir=str(tmp_path),
        watchdog_ms=150.0,
    )
    try:
        before = stall_counters.get(svc.name)
        x = rng.normal(size=(2, d)).astype(np.float32)
        first = svc.submit(x)  # wakes the dispatcher into the death
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and svc.stats()["stalls"] == 0:
            time.sleep(0.02)
        stats = svc.stats()
        assert stats["stalls"] >= 1
        assert stall_counters.get(svc.name) - before >= 1
        assert any("stall" in p for p in svc._flight.dumps)
        with open([p for p in svc._flight.dumps if "stall" in p][0]) as f:
            doc = json.load(f)
        assert any(e["kind"] == "stall" for e in doc["errors"])
        # The stuck request is visible, parked after its submit stamp.
        stuck = [r for r in doc["records"] if r["outcome"] is None]
        assert stuck
        # Recovery: the next submit restarts the worker; both drain.
        second = svc.submit(x)
        np.testing.assert_allclose(
            first.result(timeout=30), cp(x), rtol=2e-6, atol=2e-6
        )
        np.testing.assert_allclose(
            second.result(timeout=30), cp(x), rtol=2e-6, atol=2e-6
        )
        assert svc.worker_restarts == 1
    finally:
        svc.close()


def test_watchdog_quiet_after_idle_period(rng, tmp_path):
    """An idle stretch longer than the watchdog window must NOT read as
    a stall when the next request arrives: submit re-arms the progress
    stamp on the empty->non-empty transition."""
    d = 8
    cp = CompiledPipeline(_head(d=d), max_batch=16, devices=1).warmup((d,))
    svc = PipelineService(
        cp, max_delay_ms=0.5, inflight=1, flight_dir=str(tmp_path),
        watchdog_ms=150.0,
    )
    try:
        time.sleep(0.5)  # idle for > 3 watchdog windows
        x = rng.normal(size=(2, d)).astype(np.float32)
        svc.submit(x).result(timeout=30)
        time.sleep(0.1)  # give a watchdog tick a chance to misfire
        assert svc.stats()["stalls"] == 0
        assert not any("stall" in p for p in svc._flight.dumps)
    finally:
        svc.close()


def test_watchdog_disabled_at_zero(rng, tmp_path):
    d = 8
    cp = CompiledPipeline(_head(d=d), max_batch=16, devices=1).warmup((d,))
    svc = PipelineService(
        cp, max_delay_ms=0.5, inflight=1, flight_dir=str(tmp_path),
        watchdog_ms=0.0,
    )
    try:
        assert svc._watchdog is None
        assert svc.stats()["watchdog_ms"] == 0.0
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# Request-scoped causal tracing + tail sampling
# ---------------------------------------------------------------------------


def test_spans_carry_request_ids_and_reassemble(rng, traced):
    tr = traced(True, tail_ms=-1.0)  # tracing on, tail sampling off
    d = 8
    cp = CompiledPipeline(_head(d=d), max_batch=32, devices=2).warmup((d,))
    errs: list = []

    def client(cid, svc):
        try:
            for _ in range(8):
                x = rng.normal(size=(3, d)).astype(np.float32)
                svc.submit(x).result(timeout=30)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    with PipelineService(cp, max_delay_ms=0.5, inflight=2) as svc:
        threads = [
            threading.Thread(target=client, args=(k, svc)) for k in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not errs
    spans = tr.spans()
    ok = [
        s for s in spans
        if s["name"] == "serve.request" and s["args"].get("outcome") == "ok"
    ]
    assert len(ok) == 32
    rids = {s["args"]["req_id"] for s in ok}
    assert len(rids) == 32  # unique ids, threaded to the resolution span
    queued_ids = {
        s["args"]["req_id"] for s in spans if s["name"] == "serve.queued"
    }
    assert rids <= queued_ids
    device_ids = set()
    for s in spans:
        if s["name"] == "serve.device":
            device_ids.update(s["args"].get("req_ids", ()))
    assert rids <= device_ids  # the cross-thread link is complete
    # Per-request reassembly covers the whole queued→device→resolved
    # journey across >= 2 threads.
    rid = next(iter(rids))
    journey = tr.spans_for_request(rid)
    names = {s["name"] for s in journey}
    assert {"serve.queued", "serve.device", "serve.request"} <= names
    assert len({s["tid"] for s in journey}) >= 2


def test_tail_sampling_retains_only_breaching_requests(rng, traced):
    d = 8
    cp = CompiledPipeline(_head(d=d), max_batch=16, devices=1).warmup((d,))

    def serve(n, svc):
        for _ in range(n):
            svc.submit(
                rng.normal(size=(2, d)).astype(np.float32)
            ).result(timeout=30)

    # Threshold far above any latency: nothing retained.
    tr = traced(True, tail_ms=60_000.0)
    with PipelineService(cp, max_delay_ms=0.5, inflight=1) as svc:
        serve(6, svc)
    assert tr.retained() == {}
    # Threshold below every latency: every request retained, and the
    # export carries the span trees under tailSampled.
    tr = traced(True, tail_ms=1e-6)
    with PipelineService(cp, max_delay_ms=0.5, inflight=1) as svc:
        serve(6, svc)
    kept = tr.retained()
    assert len(kept) == 6
    for rid, spans in kept.items():
        assert any(
            s["name"] == "serve.request" and s["args"]["req_id"] == rid
            for s in spans
        )
    doc = tr.export()
    assert set(doc["tailSampled"]) == {str(rid) for rid in kept}
    # Negative disables retention even for slow requests.
    tr = traced(True, tail_ms=-1.0)
    with PipelineService(cp, max_delay_ms=0.5, inflight=1) as svc:
        serve(3, svc)
    assert tr.retained() == {}


def test_auto_tail_threshold_needs_samples_then_tracks_p99(rng, traced):
    """tail_ms=0 (auto) resolves the threshold from the service's
    always-on e2e histogram: inert below TAIL_MIN_COUNT samples, ~p99
    above it."""
    from keystone_tpu.workflow.serving import TAIL_MIN_COUNT

    d = 8
    cp = CompiledPipeline(_head(d=d), max_batch=16, devices=1).warmup((d,))
    tr = traced(True, tail_ms=0.0)
    with PipelineService(cp, max_delay_ms=0.2, inflight=1) as svc:
        for _ in range(TAIL_MIN_COUNT - 2):
            svc.submit(
                rng.normal(size=(2, d)).astype(np.float32)
            ).result(timeout=30)
        assert tr.retained() == {}  # below the sample floor: inert
        for _ in range(3 * TAIL_MIN_COUNT):
            svc.submit(
                rng.normal(size=(2, d)).astype(np.float32)
            ).result(timeout=30)
        n_ok = svc.stats()["outcomes"]["ok"]
    kept = tr.retained()
    # Running p99: only the tail is retained — never the bulk.
    assert len(kept) < n_ok / 4


# ---------------------------------------------------------------------------
# Export surface: prometheus server + trace_report
# ---------------------------------------------------------------------------


def test_obs_serve_smoke_inprocess():
    """The tier-1 stand-in for `make obs-serve`: live service, real
    socket, validated exposition, scrape-vs-snapshot agreement, healthz
    flip on close."""
    server_mod = _load_tool("metrics_server")
    result = server_mod.run_smoke(port=0, requests=12)
    assert result["pass"]["metrics_200"] is True
    assert result["pass"]["prometheus_valid"] is True
    assert result["pass"]["scrape_agrees_with_snapshot"] is True
    assert result["pass"]["healthz_200_while_open"] is True
    assert result["pass"]["healthz_503_after_close"] is True
    assert result["ok"] is True


def test_metrics_server_unknown_path_404():
    server_mod = _load_tool("metrics_server")
    with server_mod.MetricsServer(port=0) as server:
        status, _ = server_mod._fetch(server.url("/nope"))
        assert status == 404
        status, body = server_mod._fetch(server.url("/healthz"))
        assert status == 200  # no health source = process liveness
        assert json.loads(body)["healthy"] is True


def test_trace_report_rejects_empty_trace(tmp_path, capsys):
    report = _load_tool("trace_report")
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"traceEvents": []}))
    rc = report.main([str(empty)])
    assert rc == 1
    assert "zero spans" in capsys.readouterr().err
    # Metadata-only (no X spans) is just as dead.
    meta_only = tmp_path / "meta.json"
    meta_only.write_text(json.dumps({
        "traceEvents": [
            {"name": "thread_name", "ph": "M", "pid": 1, "tid": 1,
             "args": {"name": "t"}}
        ]
    }))
    assert report.main([str(meta_only), "--validate-only"]) == 1


def test_trace_report_request_critical_path(rng, traced, tmp_path, capsys):
    tr = traced(True, tail_ms=1e-6)  # retain everything: ids survive
    d = 8
    cp = CompiledPipeline(_head(d=d), max_batch=16, devices=2).warmup((d,))
    with PipelineService(cp, max_delay_ms=0.5, inflight=2) as svc:
        for _ in range(6):
            svc.submit(
                rng.normal(size=(2, d)).astype(np.float32)
            ).result(timeout=30)
    path = str(tmp_path / "trace.json")
    tr.export(path)
    ok_ids = sorted(
        s["args"]["req_id"] for s in tr.spans()
        if s["name"] == "serve.request" and s["args"].get("outcome") == "ok"
    )
    report = _load_tool("trace_report")
    rc = report.main([path, "--request", str(ok_ids[0])])
    out = capsys.readouterr()
    assert rc == 0
    rep = json.loads(out.out)
    assert rep["request"] == ok_ids[0]
    assert rep["outcome"] == "ok"
    assert rep["phases"]["e2e_ms"] > 0
    assert rep["phases"]["device_ms"] > 0
    assert rep["phases"]["queue_wait_ms"] >= 0
    names = {s["name"] for s in rep["spans"]}
    assert {"serve.queued", "serve.device", "serve.request"} <= names
    # Unknown id fails loudly.
    rc = report.main([path, "--request", "999999999"])
    assert rc == 1
    assert "NOT FOUND" in capsys.readouterr().err


def test_engine_direct_calls_mint_ids(rng, traced):
    """CompiledPipeline.__call__ (no service) mints a monotonic id per
    batch and tags its serve.device spans with it."""
    tr = traced(True, tail_ms=-1.0)
    d = 8
    cp = CompiledPipeline(_head(d=d), max_batch=16, devices=1).warmup((d,))
    a = next_request_id()
    cp(rng.normal(size=(4, d)).astype(np.float32))
    cp(rng.normal(size=(4, d)).astype(np.float32))
    b = next_request_id()
    assert b >= a + 3  # two engine calls minted ids in between
    device = [s for s in tr.spans() if s["name"] == "serve.device"]
    assert len(device) == 2
    ids = [s["args"]["req_ids"] for s in device]
    assert all(len(i) == 1 for i in ids)
    assert ids[0][0] < ids[1][0]  # monotonic across calls
