"""Loader tests against committed real-format fixtures — no synthetic().

Each test parses the actual on-disk format (CIFAR-10 .bin, MNIST IDX,
per-synset ImageNet .tar + dir, VOC XML+JPEG, 20news dirs, Amazon JSONL,
TIMIT npz) and asserts labels, ordering, and channel layout byte-exactly
(tolerantly for lossy JPEG pixel content). The reference does the same
against src/test/resources fixtures (SURVEY.md §4 [unverified]).
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "fixtures"))
import make_fixtures as fx  # noqa: E402  (shared byte-pattern definitions)

from keystone_tpu.loaders.amazon import AmazonReviewsDataLoader
from keystone_tpu.loaders.cifar import CifarLoader
from keystone_tpu.loaders.imagenet import ImageNetLoader
from keystone_tpu.loaders.mnist import MnistLoader
from keystone_tpu.loaders.newsgroups import NewsgroupsDataLoader
from keystone_tpu.loaders.timit import TimitFeaturesDataLoader
from keystone_tpu.loaders.voc import VOCLoader, VOC_CLASSES

DATA = os.path.join(os.path.dirname(__file__), "fixtures", "data")


def test_cifar_binary_bytes_labels_and_channel_layout():
    d = CifarLoader.load(os.path.join(DATA, "cifar", "data_batch.bin"))
    n = len(fx.CIFAR_LABELS)
    assert d.data.shape == (n, 32, 32, 3)
    np.testing.assert_array_equal(d.labels, np.asarray(fx.CIFAR_LABELS, np.int32))
    # Channel-major planes -> NHWC: plane ch of record i fills X[i,:,:,ch].
    for i in range(n):
        for ch in range(3):
            want = ((i * 40 + 17 * ch) % 256) / 255.0
            np.testing.assert_allclose(
                np.asarray(d.data[i, :, :, ch], np.float64), want, atol=1e-7
            )


def test_cifar_rejects_truncated_file(tmp_path):
    p = tmp_path / "bad.bin"
    p.write_bytes(b"\x00" * 100)
    with pytest.raises(ValueError):
        CifarLoader.load(str(p))


def test_mnist_idx_pair_bytes():
    d = MnistLoader.load(os.path.join(DATA, "mnist", "t10k"))
    n = len(fx.MNIST_LABELS)
    assert d.data.shape == (n, 784)
    np.testing.assert_array_equal(d.labels, np.asarray(fx.MNIST_LABELS, np.int32))
    for i in range(n):
        want = fx.mnist_image_bytes(i).reshape(-1).astype(np.float64) / 255.0
        np.testing.assert_allclose(
            np.asarray(d.data[i], np.float64), want, atol=1e-7
        )


def _mean_color(img):
    return np.asarray(img, np.float64).mean(axis=(0, 1))


def test_imagenet_tar_and_dir_synsets():
    label_map = ImageNetLoader.load_label_map(
        os.path.join(DATA, "imagenet", "labels.txt")
    )
    assert label_map == {s: l for s, (l, _c) in fx.IMAGENET_SYNSETS.items()}
    d = ImageNetLoader.load(
        os.path.join(DATA, "imagenet", "train"), label_map, size=32, workers=2
    )
    # Deterministic walk order: sorted entries (tar synset first), archive
    # order within the tar, sorted filenames within the dir synset.
    want_labels, want_colors = [], []
    for synset, (label, colors) in sorted(fx.IMAGENET_SYNSETS.items()):
        for c in colors:
            want_labels.append(label)
            want_colors.append(np.asarray(c, np.float64) / 255.0)
    assert d.data.shape == (len(want_labels), 32, 32, 3)
    np.testing.assert_array_equal(d.labels, np.asarray(want_labels, np.int32))
    for i, want in enumerate(want_colors):  # JPEG-lossy tolerance
        np.testing.assert_allclose(_mean_color(d.data[i]), want, atol=0.05)


def test_imagenet_stream_matches_bulk_load():
    label_map = ImageNetLoader.load_label_map(
        os.path.join(DATA, "imagenet", "labels.txt")
    )
    root = os.path.join(DATA, "imagenet", "train")
    bulk = ImageNetLoader.load(root, label_map, size=32, workers=2)
    batches = list(
        ImageNetLoader.stream_batches(
            root, label_map, batch_size=3, size=32, workers=2
        )
    )
    X = np.concatenate([b for b, _y in batches])
    y = np.concatenate([y for _b, y in batches])
    np.testing.assert_array_equal(y, np.asarray(bulk.labels))
    np.testing.assert_allclose(
        np.asarray(X, np.float64), np.asarray(bulk.data, np.float64), atol=1e-6
    )


def test_voc_xml_multilabels_and_images():
    d = VOCLoader.load(
        os.path.join(DATA, "voc", "JPEGImages"),
        os.path.join(DATA, "voc", "Annotations"),
        size=32,
        workers=2,
    )
    names = sorted(fx.VOC_FIXTURES)  # loader orders by sorted annotation name
    assert d.data.shape == (len(names), 32, 32, 3)
    for i, name in enumerate(names):
        classes, color = fx.VOC_FIXTURES[name]
        want = np.zeros(len(VOC_CLASSES), np.int32)
        for c in set(classes):  # duplicate <object>s collapse to one bit
            want[VOC_CLASSES.index(c)] = 1
        np.testing.assert_array_equal(np.asarray(d.labels[i]), want)
        np.testing.assert_allclose(
            _mean_color(d.data[i]), np.asarray(color, np.float64) / 255.0, atol=0.05
        )


def test_newsgroups_directory_layout():
    d, classes = NewsgroupsDataLoader.load(
        os.path.join(DATA, "newsgroups", "train")
    )
    groups = sorted(fx.NEWS_DOCS)
    assert classes == groups
    want_texts, want_labels = [], []
    for gi, group in enumerate(groups):
        for doc in sorted(fx.NEWS_DOCS[group]):
            want_texts.append(fx.NEWS_DOCS[group][doc])
            want_labels.append(gi)
    assert list(d.data) == want_texts  # exact bytes, exact order
    np.testing.assert_array_equal(d.labels, np.asarray(want_labels, np.int32))


def test_newsgroups_test_split_label_alignment(tmp_path):
    # A test split missing one class must keep training label indices.
    src = os.path.join(DATA, "newsgroups", "train")
    only = sorted(fx.NEWS_DOCS)[1]
    os.symlink(os.path.join(src, only), tmp_path / only)
    d, classes = NewsgroupsDataLoader.load(
        str(tmp_path), classes=sorted(fx.NEWS_DOCS)
    )
    assert classes == sorted(fx.NEWS_DOCS)
    np.testing.assert_array_equal(
        d.labels, np.full(len(fx.NEWS_DOCS[only]), 1, np.int32)
    )


def test_amazon_jsonl_star_threshold():
    d = AmazonReviewsDataLoader.load(os.path.join(DATA, "amazon", "reviews.jsonl"))
    assert list(d.data) == [t for t, _s in fx.AMAZON_ROWS]
    want = [1 if s > AmazonReviewsDataLoader.THRESHOLD else 0 for _t, s in fx.AMAZON_ROWS]
    np.testing.assert_array_equal(d.labels, np.asarray(want, np.int32))


def test_timit_npz_roundtrip():
    d = TimitFeaturesDataLoader.load(os.path.join(DATA, "timit", "frames.npz"))
    assert d.data.shape == (fx.TIMIT_N, fx.TIMIT_D)
    want = (
        np.arange(fx.TIMIT_N * fx.TIMIT_D, dtype=np.float64).reshape(
            fx.TIMIT_N, fx.TIMIT_D
        )
        / 100.0
    )
    np.testing.assert_allclose(np.asarray(d.data, np.float64), want, atol=1e-6)
    np.testing.assert_array_equal(
        d.labels, (np.arange(fx.TIMIT_N) * 7 % 24).astype(np.int32)
    )
