"""Ring-parallel (d-sharded) BCD vs oracles on the 8-device CPU mesh."""

import numpy as np
import pytest

from keystone_tpu.linalg import block_coordinate_descent_ring


def _ridge_oracle(A, B, lam):
    d = A.shape[1]
    return np.linalg.solve(
        A.astype(np.float64).T @ A.astype(np.float64) + lam * np.eye(d),
        A.astype(np.float64).T @ B.astype(np.float64),
    )


def test_ring_bcd_converges_to_oracle(rng):
    n, d, k = 400, 32, 3  # d_loc = 4 per chip, k pads 3 -> 8 chunks
    A = rng.normal(size=(n, d)).astype(np.float32)
    B = rng.normal(size=(n, k)).astype(np.float32)
    lam = 0.1
    W = np.asarray(block_coordinate_descent_ring(A, B, num_iters=30, lam=lam))
    assert W.shape == (d, k)
    np.testing.assert_allclose(W, _ridge_oracle(A, B, lam), rtol=2e-2, atol=2e-2)


def test_ring_bcd_single_sweep_reduces_residual(rng):
    n, d, k = 320, 64, 8
    A = rng.normal(size=(n, d)).astype(np.float32)
    W_true = rng.normal(size=(d, k)).astype(np.float32)
    B = (A @ W_true).astype(np.float32)
    W1 = np.asarray(block_coordinate_descent_ring(A, B, num_iters=1, lam=1e-3))
    r1 = np.linalg.norm(A @ W1 - B) / np.linalg.norm(B)
    W3 = np.asarray(block_coordinate_descent_ring(A, B, num_iters=3, lam=1e-3))
    r3 = np.linalg.norm(A @ W3 - B) / np.linalg.norm(B)
    assert r1 < 0.5  # one ring sweep already removes most of the signal
    assert r3 < r1  # and more sweeps keep helping


def test_ring_bcd_exact_on_single_device(rng):
    import jax
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("model",))
    n, d, k = 120, 10, 2
    A = rng.normal(size=(n, d)).astype(np.float32)
    B = rng.normal(size=(n, k)).astype(np.float32)
    lam = 0.3
    # One chip = one block = one exact ridge solve per column chunk.
    W = np.asarray(
        block_coordinate_descent_ring(A, B, num_iters=1, lam=lam, mesh=mesh)
    )
    np.testing.assert_allclose(W, _ridge_oracle(A, B, lam), rtol=1e-3, atol=1e-3)


def test_ring_bcd_rejects_padded_d_without_ridge(rng):
    A = rng.normal(size=(64, 30)).astype(np.float32)  # 30 % 8 != 0
    B = rng.normal(size=(64, 2)).astype(np.float32)
    with pytest.raises(ValueError, match="singular"):
        block_coordinate_descent_ring(A, B, num_iters=1, lam=0.0)
    # With ridge, padding is fine and the result is still the oracle size.
    W = np.asarray(block_coordinate_descent_ring(A, B, num_iters=20, lam=0.5))
    assert W.shape == (30, 2)
    np.testing.assert_allclose(
        W, _ridge_oracle(A, B, 0.5), rtol=5e-2, atol=5e-2
    )


def test_ring_bcd_2d_mesh_dp_times_mp(rng):
    """Rows sharded over 'data', columns ringed over 'model' — composed
    parallelism on a 4x2 and a 2x4 mesh must both match the oracle."""
    import jax
    from jax.sharding import Mesh

    n, d, k = 384, 32, 4
    A = rng.normal(size=(n, d)).astype(np.float32)
    B = rng.normal(size=(n, k)).astype(np.float32)
    lam = 0.2
    oracle = _ridge_oracle(A, B, lam)
    devices = np.asarray(jax.devices()[:8])
    for shape in [(4, 2), (2, 4)]:
        mesh = Mesh(devices.reshape(shape), ("data", "model"))
        W = np.asarray(
            block_coordinate_descent_ring(A, B, num_iters=30, lam=lam, mesh=mesh)
        )
        np.testing.assert_allclose(W, oracle, rtol=2e-2, atol=2e-2)


def test_ring_bcd_2d_mesh_row_padding(rng):
    import jax
    from jax.sharding import Mesh

    # n=250 not divisible by 4 data shards: zero row padding must be inert.
    n, d, k = 250, 16, 2
    A = rng.normal(size=(n, d)).astype(np.float32)
    B = rng.normal(size=(n, k)).astype(np.float32)
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(4, 2), ("data", "model"))
    W = np.asarray(
        block_coordinate_descent_ring(A, B, num_iters=25, lam=0.3, mesh=mesh)
    )
    np.testing.assert_allclose(W, _ridge_oracle(A, B, 0.3), rtol=2e-2, atol=2e-2)
