"""VOC and ImageNet pipeline integration tests + LCS/evaluator units."""

import numpy as np
import pytest

from keystone_tpu import native
from keystone_tpu.evaluation.augmented import AugmentedExamplesEvaluator
from keystone_tpu.evaluation.mean_average_precision import (
    MeanAveragePrecisionEvaluator,
)
from keystone_tpu.nodes.images.lcs import LCSExtractor

needs_native = pytest.mark.skipif(
    not native.available(), reason="native lib unavailable"
)


def test_lcs_shapes_and_stats(rng):
    X = rng.uniform(size=(2, 24, 24, 3)).astype(np.float32)
    node = LCSExtractor(step=4, bin_size=4)
    out = np.asarray(node(X))
    assert out.shape == (2, node.num_keypoints(24, 24), 96)
    # First keypoint, first cell stats == direct computation over the cell.
    cell = X[0, :4, :4, :]
    np.testing.assert_allclose(out[0, 0, :3], cell.mean(axis=(0, 1)), atol=1e-5)
    np.testing.assert_allclose(
        out[0, 0, 3:6], cell.std(axis=(0, 1)), atol=1e-3
    )


def test_map_evaluator_perfect_and_random():
    scores = np.array([[0.9, 0.1], [0.8, 0.2], [0.1, 0.9]])
    labels = np.array([[1, 0], [1, 0], [0, 1]])
    ev = MeanAveragePrecisionEvaluator(2)
    out = ev.evaluate(scores, labels)
    assert out["map"] > 0.99
    # Exact-AP variant too.
    assert MeanAveragePrecisionEvaluator(2, eleven_point=False).evaluate(
        scores, labels
    )["map"] == pytest.approx(1.0)


def test_map_evaluator_empty_class_is_nan():
    ev = MeanAveragePrecisionEvaluator(2)
    out = ev.evaluate(np.array([[0.5, 0.5]]), np.array([[1, 0]]))
    assert np.isnan(out["per_class_ap"][1])
    assert out["map"] == pytest.approx(out["per_class_ap"][0])


def test_augmented_evaluator():
    # 2 images x 2 views, 3 classes
    scores = np.array(
        [[1.0, 0, 0], [0.8, 0.2, 0], [0, 0, 1.0], [0, 0.4, 0.6]]
    )
    ev = AugmentedExamplesEvaluator(num_views=2)
    avg = ev.average_scores(scores)
    np.testing.assert_allclose(avg[0], [0.9, 0.1, 0.0])
    assert ev.top_k_error(scores, [0, 2], k=1) == 0.0
    with pytest.raises(ValueError, match="divisible"):
        ev.average_scores(scores[:3])


@needs_native
def test_voc_sift_fisher_end_to_end():
    from keystone_tpu.pipelines.images.voc_sift_fisher import (
        VOCSIFTFisherConfig,
        run,
    )

    out = run(
        VOCSIFTFisherConfig(
            synthetic_n=96,
            synthetic_classes=4,
            pca_dims=24,
            gmm_k=4,
            descriptor_sample=20_000,
            num_iters=1,
        )
    )
    # Multi-label textures are separable; mAP must beat the ~0.4 chance
    # level of this synthetic set decisively.
    assert out["map"] > 0.7, out["summary"]


@needs_native
def test_imagenet_sift_lcs_fv_end_to_end():
    from keystone_tpu.pipelines.images.imagenet_sift_lcs_fv import (
        ImageNetSiftLcsFVConfig,
        run,
    )

    out = run(
        ImageNetSiftLcsFVConfig(
            synthetic_n=256,
            synthetic_classes=8,
            pca_dims=16,
            gmm_k=4,
            descriptor_sample=30_000,
            num_iters=1,
            top_k=5,
        )
    )
    assert out["top_k_error"] < 0.1, out["summary"]
    assert out["top_1_error"] < 0.5, out["summary"]


def test_fisher_branch_fit_served_from_disk(tmp_path, monkeypatch):
    """A second fit of the same FV branch (same images + params) comes from
    the content-addressed store — no SIFT pass, no GMM EM."""
    import numpy as np

    from keystone_tpu.nodes.images import GrayScaler
    from keystone_tpu.nodes.images.external import SIFTExtractor
    from keystone_tpu.nodes.images.external.fisher_vector import (
        GMMFisherVectorEstimator,
        fit_fisher_featurizer,
    )
    from keystone_tpu.workflow import PipelineEnv

    monkeypatch.setenv("KEYSTONE_CACHE_DIR", str(tmp_path))
    calls = {"n": 0}
    orig = GMMFisherVectorEstimator.fit

    def counting_fit(self, *a, **k):
        calls["n"] += 1
        return orig(self, *a, **k)

    monkeypatch.setattr(GMMFisherVectorEstimator, "fit", counting_fit)

    rng = np.random.default_rng(0)
    images = rng.uniform(size=(12, 32, 32, 3)).astype(np.float32)
    front = GrayScaler().and_then(SIFTExtractor(step=8, bin_size=4))

    def build():
        return fit_fisher_featurizer(
            front, images.copy(), pca_dims=8, gmm_k=3, em_iters=3,
            sample_size=2000,
        )

    PipelineEnv.reset()
    b1 = build()
    ref = np.asarray(b1(images[:4]).get())
    assert calls["n"] == 1

    PipelineEnv.reset()  # fresh session state, same disk store
    b2 = build()
    assert calls["n"] == 1  # served from disk: EM never ran again
    np.testing.assert_allclose(np.asarray(b2(images[:4]).get()), ref)


def test_imagenet_streamed_matches_eager():
    """Out-of-core mode: streaming batches through the featurizer and the
    host-streamed solver must reproduce the eager run (same fitting sample,
    same data — only the execution schedule differs)."""
    from keystone_tpu.pipelines.images.imagenet_sift_lcs_fv import (
        ImageNetSiftLcsFVConfig,
        run,
    )

    base = dict(
        synthetic_n=192,
        synthetic_classes=6,
        pca_dims=16,
        gmm_k=4,
        descriptor_sample=20_000,
        num_iters=1,
        top_k=3,
    )
    eager = run(ImageNetSiftLcsFVConfig(**base))
    streamed = run(
        ImageNetSiftLcsFVConfig(
            **base, stream=True, stream_batch=64, fit_sample_images=192
        )
    )
    # Same featurizer (full train as fitting sample), same solve — the
    # schedules agree to solver tolerance.
    assert abs(streamed["top_k_error"] - eager["top_k_error"]) < 0.05
    assert abs(streamed["top_1_error"] - eager["top_1_error"]) < 0.1


@needs_native
def test_fitted_native_pipeline_save_load(tmp_path):
    import numpy as np

    from keystone_tpu.loaders.voc import VOCLoader
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
    from keystone_tpu.pipelines.images.voc_sift_fisher import (
        VOCSIFTFisherConfig,
        build_featurizer,
    )
    from keystone_tpu.workflow import load_pipeline, save_pipeline

    train, test = VOCLoader.synthetic(n=48, num_classes=4)
    conf = VOCSIFTFisherConfig(pca_dims=16, gmm_k=4, descriptor_sample=10000)
    feat = build_featurizer(conf, train.data)
    targets = (2.0 * train.labels - 1.0).astype(np.float32)
    p = feat.and_then(
        BlockLeastSquaresEstimator(block_size=128, num_iters=1, lam=1e-3),
        train.data,
        targets,
    ).fit()
    path = str(tmp_path / "voc.pkl")
    save_pipeline(p, path)
    lp = load_pipeline(path)
    np.testing.assert_array_equal(
        np.asarray(p(test.data).get()), np.asarray(lp(test.data).get())
    )


@needs_native
def test_imagenet_with_test_time_augmentation():
    from keystone_tpu.pipelines.images.imagenet_sift_lcs_fv import (
        ImageNetSiftLcsFVConfig,
        run,
    )

    out = run(
        ImageNetSiftLcsFVConfig(
            synthetic_n=160,
            synthetic_classes=6,
            pca_dims=16,
            gmm_k=4,
            descriptor_sample=20_000,
            num_iters=1,
            augment=True,
        )
    )
    # top-1 carries the signal: 6-class chance is 0.83 top-1 error; the
    # top-5 floor (1/6) is too close to the threshold to be meaningful.
    assert out["top_1_error"] < 0.3, out["summary"]
    assert out["top_k_error"] < 0.1, out["summary"]


def test_imagenet_resolve_scale_defaults():
    """Real data defaults to the reference's 64k-dim headline config
    (gmm_k=256, 3 epochs — BASELINE.json); synthetic stays CI-scale; an
    explicit value always wins (VERDICT r3 missing #4)."""
    from keystone_tpu.pipelines.images.imagenet_sift_lcs_fv import (
        ImageNetSiftLcsFVConfig,
        resolve_scale,
    )

    real = resolve_scale(ImageNetSiftLcsFVConfig(data_path="/d"))
    assert (real.gmm_k, real.num_iters) == (256, 3)
    assert 2 * (2 * real.gmm_k * real.pca_dims) == 65_536
    synth = resolve_scale(ImageNetSiftLcsFVConfig())
    assert (synth.gmm_k, synth.num_iters) == (16, 2)
    explicit = resolve_scale(
        ImageNetSiftLcsFVConfig(data_path="/d", gmm_k=32, num_iters=1)
    )
    assert (explicit.gmm_k, explicit.num_iters) == (32, 1)
