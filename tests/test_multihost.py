"""Multi-host execution: two real processes rendezvous over
``jax.distributed.initialize`` (the DCN control-plane seam,
``utils/distributed.py``) and run one psum'd normal-equations solve across
a mesh spanning both — proving the distributed backend executes, not just
imports. Ref: SURVEY.md §5 distributed-backend row; the reference's
local[n]-vs-cluster equivalence argument [unverified].
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

_WORKER = textwrap.dedent(
    """
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    # The production seam: env knobs -> rendezvous (utils/distributed.py).
    from keystone_tpu.utils.platform import setup_platform
    setup_platform()

    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.local_devices()) == 2
    assert len(jax.devices()) == 4  # the mesh spans both processes

    from keystone_tpu.linalg import RowMatrix, solve_least_squares_normal

    rng = np.random.default_rng(0)  # same bytes on every host
    X = rng.normal(size=(64, 8)).astype(np.float32)
    W_true = rng.normal(size=(8, 3)).astype(np.float32)
    Y = X @ W_true
    A = RowMatrix.from_array(X)
    B = RowMatrix.from_array(Y)
    W = np.asarray(solve_least_squares_normal(A, B, lam=0.0))
    err = np.linalg.norm(W - W_true) / np.linalg.norm(W_true)
    assert err < 1e-4, err
    print(f"MULTIHOST_OK process={jax.process_index()} err={err}", flush=True)
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_psum_solve(tmp_path):
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for pid in (0, 1):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["KEYSTONE_PLATFORM"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env["KEYSTONE_COORDINATOR"] = f"127.0.0.1:{port}"
        env["KEYSTONE_NUM_PROCESSES"] = "2"
        env["KEYSTONE_PROCESS_ID"] = str(pid)
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _WORKER],
                env=env,
                cwd=repo,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"rc={rc}\nstdout:{out[-1000:]}\nstderr:{err[-2000:]}"
        assert "MULTIHOST_OK" in out
