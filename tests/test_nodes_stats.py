"""Stats/util node unit tests vs direct NumPy computation (SURVEY.md §4)."""

import numpy as np

from keystone_tpu.nodes.stats import (
    CosineRandomFeatures,
    LinearRectifier,
    PaddedFFT,
    RandomSignNode,
    SignedHellingerMapper,
    StandardScaler,
    sample_columns,
    sample_rows,
)
from keystone_tpu.nodes.util import (
    Cast,
    ClassLabelIndicators,
    Identity,
    MaxClassifier,
    TopKClassifier,
    VectorCombiner,
    VectorSplitter,
)


def test_random_sign_node(rng):
    node = RandomSignNode.create(dim=16, seed=0)
    signs = np.asarray(node.signs)
    assert set(np.unique(signs)) <= {-1.0, 1.0}
    X = rng.normal(size=(4, 16)).astype(np.float32)
    np.testing.assert_allclose(node(X), X * signs)


def test_padded_fft_matches_numpy(rng):
    X = rng.normal(size=(3, 7)).astype(np.float32)
    out = np.asarray(PaddedFFT()(X))
    ref = np.fft.rfft(np.pad(X, ((0, 0), (0, 1))), axis=-1) / np.sqrt(8)
    np.testing.assert_allclose(out[:, :5], ref.real, atol=1e-5)
    np.testing.assert_allclose(out[:, 5:], ref.imag, atol=1e-5)


def test_linear_rectifier():
    X = np.array([[-1.0, 0.5], [2.0, -3.0]], dtype=np.float32)
    np.testing.assert_allclose(LinearRectifier()(X), np.maximum(X, 0.0))
    np.testing.assert_allclose(
        LinearRectifier(max_val=0.1, alpha=0.5)(X), np.maximum(X - 0.5, 0.1)
    )


def test_standard_scaler(rng):
    X = rng.normal(loc=3.0, scale=2.0, size=(50, 4)).astype(np.float32)
    model = StandardScaler().fit(X)
    out = np.asarray(model(X))
    np.testing.assert_allclose(out.mean(axis=0), 0.0, atol=1e-5)
    np.testing.assert_allclose(out.std(axis=0, ddof=1), 1.0, atol=1e-4)


def test_cosine_random_features_range_and_shape(rng):
    node = CosineRandomFeatures.create(8, 32, gamma=0.5, seed=1)
    X = rng.normal(size=(5, 8)).astype(np.float32)
    out = np.asarray(node(X))
    assert out.shape == (5, 32)
    assert np.all(out >= -1.0) and np.all(out <= 1.0)
    ref = np.cos(X @ np.asarray(node.W) + np.asarray(node.b))
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_cosine_random_features_cauchy(rng):
    node = CosineRandomFeatures.create(4, 16, distribution="cauchy", seed=2)
    assert np.asarray(node.W).shape == (4, 16)


def test_signed_hellinger():
    X = np.array([[4.0, -9.0, 0.0]], dtype=np.float32)
    np.testing.assert_allclose(
        SignedHellingerMapper()(X), [[2.0, -3.0, 0.0]], atol=1e-6
    )


def test_samplers(rng):
    X = rng.normal(size=(20, 10))
    assert sample_rows(X, 5, seed=1).shape == (5, 10)
    assert sample_columns(X, 3, seed=1).shape == (20, 3)
    assert sample_rows(X, 50).shape == (20, 10)


def test_class_label_indicators():
    out = np.asarray(ClassLabelIndicators(4)(np.array([0, 2, 3])))
    expected = -np.ones((3, 4), dtype=np.float32)
    expected[0, 0] = expected[1, 2] = expected[2, 3] = 1.0
    np.testing.assert_allclose(out, expected)


def test_max_and_topk_classifier(rng):
    scores = np.array([[0.1, 0.9, 0.0], [0.5, 0.2, 0.8]], dtype=np.float32)
    np.testing.assert_array_equal(MaxClassifier()(scores), [1, 2])
    topk = np.asarray(TopKClassifier(2)(scores))
    np.testing.assert_array_equal(topk, [[1, 0], [2, 0]])


def test_vector_splitter_combiner(rng):
    X = rng.normal(size=(4, 10)).astype(np.float32)
    blocks = VectorSplitter(4)(X)
    assert [b.shape[-1] for b in blocks] == [4, 4, 2]
    np.testing.assert_allclose(VectorCombiner()(blocks), X, atol=1e-6)


def test_identity_and_cast(rng):
    X = rng.normal(size=(2, 3)).astype(np.float64)
    np.testing.assert_allclose(Identity()(X), X)
    assert np.asarray(Cast("float32")(X)).dtype == np.float32
