"""Streaming-equivalence tests for the prefetching ingestion layer.

The contract the chunked solvers rely on (loaders/stream.py
PrefetchIterator): prefetched iteration yields the producer's batches
bit-identically and in order, producer exceptions surface in the
consumer, ``prefetch_depth=0`` is a true passthrough, and the overlapped
solver paths match their synchronous counterparts exactly.
"""

import threading

import numpy as np
import pytest

from keystone_tpu.config import config


@pytest.fixture
def depth_config():
    """Restore config.prefetch_depth after tests that flip it."""
    prior = config.prefetch_depth
    yield config
    config.prefetch_depth = prior


def test_prefetch_bit_identical_in_order(rng):
    from keystone_tpu.loaders.stream import BatchIterator, PrefetchIterator

    X = rng.normal(size=(1000, 7)).astype(np.float32)
    y = rng.integers(0, 3, 1000).astype(np.int32)
    it = BatchIterator.from_arrays(X, y, batch_rows=128)
    sync = list(it)
    pre = list(PrefetchIterator(iter(it), depth=2))
    assert len(pre) == len(sync)
    for (xs, ys), (xp, yp) in zip(sync, pre):
        np.testing.assert_array_equal(xs, xp)
        np.testing.assert_array_equal(ys, yp)


def test_prefetch_propagates_producer_exception():
    from keystone_tpu.loaders.stream import PrefetchIterator

    def gen():
        yield np.zeros((2, 2)), None
        raise RuntimeError("boom in producer")

    it = PrefetchIterator(gen(), depth=2)
    next(it)
    with pytest.raises(RuntimeError, match="boom in producer"):
        next(it)
    # Exhausted after the error; no hang, no replay.
    with pytest.raises(StopIteration):
        next(it)


def test_prefetch_depth_zero_is_true_passthrough():
    from keystone_tpu.loaders.stream import prefetch_batches

    src = iter([1, 2, 3])
    assert prefetch_batches(src, 0) is src


def test_prefetch_rejects_invalid_depth():
    from keystone_tpu.loaders.stream import PrefetchIterator

    with pytest.raises(ValueError, match="depth"):
        PrefetchIterator(iter([]), depth=0)


def test_prefetch_bounded_queue_and_close_stops_producer():
    from keystone_tpu.loaders.stream import PrefetchIterator

    produced = []

    def gen():
        for i in range(1000):
            produced.append(i)
            yield i

    it = PrefetchIterator(gen(), depth=2)
    assert next(it) == 0
    it.close()
    it._thread.join(timeout=5)
    assert not it._thread.is_alive()
    # Bounded: the producer can never run more than depth ahead of the
    # consumer plus the item in its own hands.
    assert len(produced) <= 1 + 2 + 1
    assert it.max_queued <= 2
    with pytest.raises(StopIteration):
        next(it)


def test_batch_iterator_prefetch_is_reiterable(rng):
    from keystone_tpu.loaders.stream import BatchIterator

    X = rng.normal(size=(64, 3)).astype(np.float32)
    pre = BatchIterator.from_arrays(X, batch_rows=16).prefetch(2)
    first = [x for x, _ in pre]
    second = [x for x, _ in pre]
    assert len(first) == len(second) == 4
    for a, b in zip(first, second):
        np.testing.assert_array_equal(a, b)


def test_map_batches_runs_on_prefetch_thread(rng):
    """The featurization chain (map_batches) executes on the producer
    thread when prefetched — the ingest work leaves the consumer."""
    from keystone_tpu.loaders.stream import BatchIterator

    X = rng.normal(size=(64, 3)).astype(np.float32)
    main_thread = threading.current_thread()
    seen_threads = []

    def feat(batch):
        seen_threads.append(threading.current_thread())
        return batch * 2.0

    it = BatchIterator.from_arrays(X, batch_rows=16).map_batches(feat)
    out = [x for x, _ in it.prefetch(2)]
    assert len(out) == 4
    assert all(t is not main_thread for t in seen_threads)
    np.testing.assert_allclose(np.concatenate(out), X * 2.0, atol=0)


def test_chunked_solve_prefetched_matches_sync(rng):
    from keystone_tpu.linalg import solve_least_squares_chunked
    from keystone_tpu.loaders.stream import BatchIterator

    A = rng.normal(size=(500, 16)).astype(np.float32)
    W0 = rng.normal(size=(16, 3)).astype(np.float32)
    B = A @ W0
    it = lambda: BatchIterator.from_arrays(A, B, batch_rows=128)
    W_sync = np.asarray(
        solve_least_squares_chunked(it(), lam=0.2, prefetch_depth=0)
    )
    W_pre = np.asarray(
        solve_least_squares_chunked(it(), lam=0.2, prefetch_depth=2)
    )
    np.testing.assert_array_equal(W_sync, W_pre)
    # And both still solve the ridge problem.
    reg = A.T @ A + 0.2 * np.eye(16, dtype=np.float32)
    oracle = np.linalg.solve(reg, A.T @ B)
    np.testing.assert_allclose(W_sync, oracle, rtol=1e-3, atol=1e-3)


def test_chunked_solve_prefetched_handles_1d_labels(rng):
    """The CSV label_col shape: labels stream as a 1-D column and AᵀB is a
    vector — the overlapped path must accept it like the sync path does."""
    from keystone_tpu.linalg import solve_least_squares_chunked
    from keystone_tpu.loaders.stream import BatchIterator

    A = rng.normal(size=(300, 12)).astype(np.float32)
    y = (A @ rng.normal(size=(12,)).astype(np.float32)).astype(np.float32)
    it = lambda: BatchIterator.from_arrays(A, y, batch_rows=64)
    w_sync = np.asarray(
        solve_least_squares_chunked(it(), lam=0.1, prefetch_depth=0)
    )
    w_pre = np.asarray(
        solve_least_squares_chunked(it(), lam=0.1, prefetch_depth=2)
    )
    assert w_pre.shape == (12,)
    np.testing.assert_array_equal(w_sync, w_pre)


def test_chunked_solve_error_paths_overlapped(rng):
    from keystone_tpu.linalg import solve_least_squares_chunked

    A = rng.normal(size=(64, 8)).astype(np.float32)
    B = rng.normal(size=(64, 2)).astype(np.float32)

    with pytest.raises(ValueError, match="empty"):
        solve_least_squares_chunked(iter([]), prefetch_depth=2)
    with pytest.raises(ValueError, match="labeled"):
        solve_least_squares_chunked(iter([(A, None)]), prefetch_depth=2)

    def boom():
        yield A, B
        raise RuntimeError("producer exploded")

    with pytest.raises(RuntimeError, match="producer exploded"):
        solve_least_squares_chunked(boom(), prefetch_depth=2)


def test_streamed_bcd_prefetch_matches_sync(rng, depth_config):
    from keystone_tpu.linalg import RowMatrix
    from keystone_tpu.linalg.bcd import (
        assemble_blocks,
        block_coordinate_descent_streamed,
    )

    A = rng.normal(size=(200, 32)).astype(np.float32)
    W0 = rng.normal(size=(32, 4)).astype(np.float32)
    B = A @ W0

    depth_config.prefetch_depth = 2
    W_pre, _ = block_coordinate_descent_streamed(
        A, RowMatrix.from_array(B), 8, 3, lam=0.1
    )
    depth_config.prefetch_depth = 0
    W_sync, _ = block_coordinate_descent_streamed(
        A, RowMatrix.from_array(B), 8, 3, lam=0.1
    )
    np.testing.assert_array_equal(
        np.asarray(assemble_blocks(W_pre)), np.asarray(assemble_blocks(W_sync))
    )


def test_pipeline_apply_batches_matches_eager(rng, depth_config):
    from keystone_tpu.loaders.stream import BatchIterator
    from keystone_tpu.workflow.pipeline import Transformer

    class Times3(Transformer):
        def apply_batch(self, X):
            return X * 3.0

    X = rng.normal(size=(96, 5)).astype(np.float32)
    y = rng.integers(0, 2, 96).astype(np.int32)
    pipe = Times3().to_pipeline()

    batches = BatchIterator.from_arrays(X, y, batch_rows=32)
    eager = [np.asarray(pipe.apply(Xb).get()) for Xb, _ in batches]

    outs, ys = [], []
    for F, yb in pipe.apply_batches(batches, prefetch_depth=2):
        outs.append(np.asarray(F))
        ys.append(np.asarray(yb))
    assert len(outs) == len(eager)
    for a, b in zip(outs, eager):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(np.concatenate(ys), y)

    # depth=0: synchronous passthrough yields the same stream.
    sync = [np.asarray(F) for F, _ in pipe.apply_batches(batches, prefetch_depth=0)]
    for a, b in zip(sync, eager):
        np.testing.assert_array_equal(a, b)


def test_prefetch_abandoned_consumer_stops_thread():
    """A consumer that bails mid-stream (exception/early break) must not
    leave the producer thread parked on the bounded queue."""
    from keystone_tpu.loaders.stream import PrefetchIterator

    stopped = threading.Event()

    def gen():
        try:
            for i in range(10_000):
                yield i
        finally:
            stopped.set()

    it = PrefetchIterator(gen(), depth=1)
    assert next(it) == 0
    thread = it._thread
    it.close()
    thread.join(timeout=5)
    assert not thread.is_alive()
    assert stopped.wait(timeout=1)
