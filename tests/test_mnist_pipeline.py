"""End-to-end MnistRandomFFT integration test (SURVEY.md §4: whole-pipeline
suite on a tiny dataset asserting accuracy above a floor)."""

import numpy as np

from keystone_tpu.loaders import MnistLoader
from keystone_tpu.pipelines.images.mnist_random_fft import (
    MnistRandomFFTConfig,
    build_pipeline,
    run,
)


def test_synthetic_loader_deterministic():
    a, _ = MnistLoader.synthetic(n=64, seed=3)
    b, _ = MnistLoader.synthetic(n=64, seed=3)
    np.testing.assert_array_equal(a.data, b.data)
    np.testing.assert_array_equal(a.labels, b.labels)
    assert a.data.shape == (64, 784)


def test_mnist_random_fft_end_to_end():
    out = run(MnistRandomFFTConfig(num_ffts=2, synthetic_n=1024, seed=0))
    # The acceptance bar from SURVEY.md §7 step 2 (>=96% on MNIST-like data).
    assert out["test_accuracy"] >= 0.96, out["summary"]


def test_fitted_pipeline_reusable():
    conf = MnistRandomFFTConfig(num_ffts=1, synthetic_n=512, seed=1)
    train, test = MnistLoader.synthetic(n=conf.synthetic_n, seed=conf.seed)
    pipe = build_pipeline(conf, train.data, train.labels)
    fitted = pipe.fit()
    p1 = np.asarray(fitted(test.data).get())
    p2 = np.asarray(fitted(test.data).get())
    np.testing.assert_array_equal(p1, p2)
    assert p1.shape == (test.data.shape[0],)
