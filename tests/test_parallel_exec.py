"""Stage-parallel DAG execution (workflow/executor.py _ParallelWalk).

Pins the ISSUE-10 tentpole contract:

1. Bit-identity: every canonical pipeline (MNIST FFT, CIFAR random
   patch, VOC SIFT-fisher, the two-branch ImageNet SIFT|LCS featurizer,
   newsgroups text) produces byte-identical fit/apply outputs under
   ``KEYSTONE_EXEC_WORKERS=4`` vs ``=0`` — the scheduler reorders only
   provably independent nodes.
2. Fault parity: an exception raised on a pool worker surfaces on the
   calling thread (it must not vanish into the pool), and a fit under
   the chaos fault plan stays bit-identical to the fault-free serial
   walk (every injected fault is recovered identically).
3. Scheduler semantics: structural duplicates execute ONCE (the second
   lands as a memo), fit-cache hits stay pruning leaves, independent
   host branches genuinely overlap, and a nested fit re-entering the
   executor from a pool thread takes the serial path (one bounded pool,
   no deadlock).
4. Profiler under concurrency: a 4-worker walk yields exact per-label
   call counts with non-overlapping wall attribution, rows carry the
   worker / queue-wait scheduling attrs, and ``trace_report --fit``
   renders the same table from the spans.
5. ``workers=0`` (the default) never constructs the parallel walk — the
   legacy serial path is byte-identical because it is the same code.
"""

import threading
import time

import numpy as np
import pytest

from keystone_tpu import native
from keystone_tpu.config import config
from keystone_tpu.workflow.executor import PipelineEnv, _ParallelWalk
from keystone_tpu.workflow.pipeline import Pipeline, Transformer

needs_native = pytest.mark.skipif(
    not native.available(), reason="native lib unavailable"
)


@pytest.fixture(autouse=True)
def _serial_default():
    """Every test starts and ends at the workers=0 default."""
    prior = config.exec_workers
    config.exec_workers = 0
    yield
    config.exec_workers = prior


def _fit_apply(pipe, X_test, workers):
    """One cold fit+apply under ``workers`` executor threads."""
    PipelineEnv.reset()
    config.exec_workers = workers
    try:
        out = np.asarray(pipe.fit().apply(X_test).get())
    finally:
        config.exec_workers = 0
        PipelineEnv.reset()
    return out


def _assert_walks_agree(pipe, X_test):
    serial = _fit_apply(pipe, X_test, 0)
    parallel = _fit_apply(pipe, X_test, 4)
    assert serial.dtype == parallel.dtype
    np.testing.assert_array_equal(serial, parallel)
    return serial


# ---------------------------------------------------------------------------
# Canonical-pipeline bit-identity (tiny scales)
# ---------------------------------------------------------------------------


def test_mnist_fft_bit_identical():
    from keystone_tpu.loaders import MnistLoader
    from keystone_tpu.pipelines.images.mnist_random_fft import (
        MnistRandomFFTConfig,
        build_pipeline,
    )

    conf = MnistRandomFFTConfig(num_ffts=1, synthetic_n=256, seed=1)
    train, test = MnistLoader.synthetic(n=conf.synthetic_n, seed=conf.seed)
    pipe = build_pipeline(conf, train.data, train.labels)
    _assert_walks_agree(pipe, test.data[:64])


def test_cifar_random_patch_bit_identical():
    from keystone_tpu.loaders.cifar import CifarLoader
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
    from keystone_tpu.nodes.util import ClassLabelIndicators
    from keystone_tpu.pipelines.images.random_patch_cifar import (
        RandomPatchCifarConfig,
        build_featurizer,
    )

    train, test = CifarLoader.synthetic(n=96, seed=1)
    conf = RandomPatchCifarConfig(
        synthetic_n=96, num_filters=16, patch_sample=500, num_iters=1,
        lam=5.0,
    )
    feat = build_featurizer(conf, train.data)
    targets = ClassLabelIndicators(conf.num_classes)(train.labels)
    pipe = feat.and_then(
        BlockLeastSquaresEstimator(block_size=128, num_iters=1, lam=conf.lam),
        train.data,
        targets,
    )
    _assert_walks_agree(pipe, test.data[:16])


@needs_native
def test_voc_fisher_bit_identical():
    from keystone_tpu.loaders.voc import VOCLoader
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
    from keystone_tpu.pipelines.images.voc_sift_fisher import (
        VOCSIFTFisherConfig,
        build_featurizer,
    )

    train, test = VOCLoader.synthetic(n=32, num_classes=4)
    conf = VOCSIFTFisherConfig(
        pca_dims=8, gmm_k=2, gmm_iters=2, descriptor_sample=5000,
    )
    feat = build_featurizer(conf, train.data)
    targets = (2.0 * train.labels - 1.0).astype(np.float32)
    pipe = feat.and_then(
        BlockLeastSquaresEstimator(block_size=64, num_iters=1, lam=1e-3),
        train.data,
        targets,
    )
    _assert_walks_agree(pipe, test.data[:8])


@needs_native
def test_imagenet_two_branch_featurizer_bit_identical():
    """THE motivating shape: the SIFT|LCS two-branch featurizer, whose
    independent host-bound branches the parallel walk overlaps."""
    from keystone_tpu.loaders.imagenet import ImageNetLoader
    from keystone_tpu.pipelines.images.imagenet_sift_lcs_fv import (
        ImageNetSiftLcsFVConfig,
        build_featurizer,
        resolve_scale,
    )

    train, test = ImageNetLoader.synthetic(n=24, num_classes=4, size=32)
    conf = resolve_scale(ImageNetSiftLcsFVConfig(
        pca_dims=8, gmm_k=2, gmm_iters=2, descriptor_sample=5000,
    ))
    feat = build_featurizer(conf, train.data)
    _assert_walks_agree(feat, test.data[:8])


def test_newsgroups_text_bit_identical():
    from keystone_tpu.loaders.newsgroups import NewsgroupsDataLoader
    from keystone_tpu.nodes.learning import NaiveBayesEstimator
    from keystone_tpu.nodes.nlp import (
        CommonSparseFeatures,
        LowerCase,
        NGramsFeaturizer,
        TermFrequency,
        Tokenizer,
        Trim,
    )
    from keystone_tpu.nodes.util import MaxClassifier

    train, test, classes = NewsgroupsDataLoader.synthetic(
        n=200, num_classes=3
    )
    featurizer = (
        Trim()
        .and_then(LowerCase())
        .and_then(Tokenizer())
        .and_then(NGramsFeaturizer(1, 2))
        .and_then(TermFrequency("log"))
        .and_then(CommonSparseFeatures(200), train.data)
    )
    pipe = featurizer.and_then(
        NaiveBayesEstimator(len(classes)), train.data, train.labels
    ).and_then(MaxClassifier())
    _assert_walks_agree(pipe, test.data[:32])


# ---------------------------------------------------------------------------
# Scheduler semantics
# ---------------------------------------------------------------------------


class HostWork(Transformer):
    """Deterministic non-jittable branch work that releases the GIL
    (numpy elementwise), so branches can genuinely overlap."""

    jittable = False

    def __init__(self, seed: int, iters: int = 40):
        self.seed = seed
        self.iters = iters

    def signature(self):
        return self.stable_signature(self.seed, self.iters)

    def apply_batch(self, X):
        Y = np.asarray(X, dtype=np.float32)
        for _ in range(self.iters):
            Y = np.tanh(Y + float(self.seed) * 1e-3)
        return Y


class Boom(Transformer):
    jittable = False

    def apply_batch(self, X):
        raise RuntimeError("injected worker fault")


def test_worker_fault_surfaces_on_caller(rng):
    """A fault on a pool thread cancels the schedule and re-raises on
    the calling thread — chaos parity with the serial walk."""
    X = rng.normal(size=(16, 8)).astype(np.float32)
    pipe = Pipeline.gather(
        [HostWork(1, iters=2).to_pipeline(), Boom().to_pipeline()]
    )
    config.exec_workers = 4
    with pytest.raises(RuntimeError, match="injected worker fault"):
        pipe.apply(X).get()
    # The session survives: the next walk runs normally.
    ok = Pipeline.gather(
        [HostWork(1, iters=2).to_pipeline(), HostWork(2, iters=2).to_pipeline()]
    )
    out = np.asarray(ok.apply(X).get())
    assert out.shape == (16, 16)


def test_chaos_fit_bit_identical_under_parallel_walk(rng):
    """The standard chaos plan (io:0.05,oom:1) injected while the
    parallel walk drives a fit: every fault recovers invisibly and the
    outputs match the fault-free serial walk bit for bit."""
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.nodes.stats.scalers import StandardScaler

    X = rng.normal(size=(128, 16)).astype(np.float32)
    pipe = StandardScaler().with_data(X).and_then(L2Normalizer())
    baseline = _fit_apply(pipe, X, 0)
    prior = (config.faults, config.faults_seed)
    try:
        config.faults, config.faults_seed = "io:0.05,oom:1", 0
        chaos = _fit_apply(pipe, X, 4)
    finally:
        config.faults, config.faults_seed = prior
    np.testing.assert_array_equal(baseline, chaos)


def test_structural_duplicates_execute_once(rng):
    """Two branches sharing one structural prefix: the parallel walk
    executes the prefix ONCE (hash ownership) and the duplicate lands as
    a memo — same dedup the serial loop's by_hash gives."""
    from keystone_tpu.utils.metrics import profile_scope, resource_profile

    from keystone_tpu.workflow.graph import Graph
    from keystone_tpu.workflow.operators import (
        DatasetOperator,
        TransformerOperator,
    )

    X = rng.normal(size=(32, 8)).astype(np.float32)
    # A raw graph (no optimizer dedup pass) holding two structurally
    # identical HostWork(7) nodes over one dataset — the duplicate shape
    # composition produces and the walk's by_hash memo must collapse.
    g = Graph()
    g, data = g.add(DatasetOperator(X), [])
    g, dup_a = g.add(TransformerOperator(HostWork(7, iters=2)), [data])
    g, dup_b = g.add(TransformerOperator(HostWork(7, iters=2)), [data])
    resource_profile.reset()
    config.exec_workers = 4
    try:
        with profile_scope():
            values = PipelineEnv.get().executor.execute_many(
                g, [dup_a, dup_b]
            )
        np.testing.assert_array_equal(
            np.asarray(values[dup_a]), np.asarray(values[dup_b])
        )
        row = next(
            r for r in resource_profile.rows() if r["node"] == "HostWork"
        )
        # The owner executes once; the duplicate lands as a memo — two
        # duplicates can never compute concurrently.
        assert row["calls"] == 2
        assert row["executed"] == 1
        assert row["cache_hits"] == 1
    finally:
        resource_profile.reset()
        config.exec_workers = 0


def test_fit_cache_hit_stays_a_pruning_leaf(rng):
    """A refit under the parallel walk serves the estimator from the
    session fit cache without re-executing its training subgraph."""
    from keystone_tpu.nodes.stats.scalers import StandardScaler
    from keystone_tpu.utils.metrics import profile_scope, resource_profile

    X = rng.normal(size=(64, 8)).astype(np.float32)
    pipe = StandardScaler().with_data(X)
    PipelineEnv.reset()
    config.exec_workers = 4
    try:
        pipe.fit()
        resource_profile.reset()
        with profile_scope():
            pipe.fit()
        rows = {r["node"]: r for r in resource_profile.rows()}
        fit_row = next(
            r for n, r in rows.items() if n.endswith(".fit")
        )
        assert fit_row["cache_hits"] == 1 and fit_row["executed"] == 0
        # The training Dataset node was pruned by the cache cut.
        assert "Dataset" not in rows
    finally:
        resource_profile.reset()
        config.exec_workers = 0
        PipelineEnv.reset()


def test_independent_host_branches_overlap(rng):
    """Two GIL-releasing host branches under 4 workers: their executor
    spans must overlap in time (the scheduler actually runs them
    concurrently, not merely out of order)."""
    from keystone_tpu.utils.metrics import active_tracer, reset_tracer

    X = rng.normal(size=(64, 512)).astype(np.float32)
    pipe = Pipeline.gather(
        [HostWork(1, iters=400).to_pipeline(),
         HostWork(2, iters=400).to_pipeline()]
    )
    prior_trace = config.trace
    config.trace = True
    reset_tracer()
    config.exec_workers = 4
    try:
        tracer = active_tracer()
        pipe.apply(X).get()
        spans = [
            s for s in tracer.spans()
            if s["name"] == "node:HostWork" and s["args"].get("cache") == "miss"
        ]
        assert len(spans) == 2
        (a, b) = sorted(spans, key=lambda s: s["start_ns"])
        assert b["start_ns"] < a["start_ns"] + a["dur_ns"], (
            "branches ran back to back — no overlap"
        )
        for s in spans:
            assert s["args"].get("worker", "").startswith("keystone-exec")
            assert s["args"].get("queue_wait_ms") is not None
    finally:
        config.trace = prior_trace
        config.exec_workers = 0
        reset_tracer()


def test_nested_fit_on_worker_takes_serial_path(rng):
    """An estimator whose fit() internally applies ANOTHER pipeline
    re-enters the executor from a pool thread: the nested walk must run
    serial (one bounded pool) and still produce the right answer."""
    from keystone_tpu.workflow.pipeline import Estimator

    class InnerApplyEstimator(Estimator):
        def signature(self):
            return ("inner-apply-est",)

        def fit(self, data):
            inner = Pipeline.gather(
                [HostWork(11, iters=2).to_pipeline(),
                 HostWork(12, iters=2).to_pipeline()]
            )
            feats = np.asarray(inner.apply(np.asarray(data)).get())
            mu = feats.mean(axis=0)[: np.asarray(data).shape[1]]

            class Center(Transformer):
                jittable = False

                def __init__(self, mu):
                    self.mu = mu

                def apply_batch(self, X):
                    return np.asarray(X) - self.mu

            return Center(mu)

    X = rng.normal(size=(32, 8)).astype(np.float32)
    pipe = InnerApplyEstimator().with_data(X)
    out_serial = _fit_apply(pipe, X, 0)
    out_parallel = _fit_apply(pipe, X, 4)
    np.testing.assert_array_equal(out_serial, out_parallel)


def test_workers_zero_never_builds_the_parallel_walk(rng, monkeypatch):
    """The default path is the LEGACY serial loop — same code, not a
    1-worker pool: _ParallelWalk must never be constructed."""
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.nodes.stats.scalers import StandardScaler

    def forbid(*a, **kw):
        raise AssertionError("parallel walk engaged at workers=0")

    monkeypatch.setattr(_ParallelWalk, "__init__", forbid)
    X = rng.normal(size=(32, 8)).astype(np.float32)
    assert config.exec_workers == 0
    out = np.asarray(
        StandardScaler().with_data(X).and_then(L2Normalizer())
        .fit().apply(X).get()
    )
    assert out.shape == X.shape


# ---------------------------------------------------------------------------
# Profiler under concurrency + trace_report --fit agreement
# ---------------------------------------------------------------------------


def test_profile_exact_counts_and_trace_report_agreement(rng, tmp_path):
    """A 4-worker profiled+traced walk: exact call counts per label,
    non-overlapping (per-execution) wall attribution, scheduling attrs
    populated — and `trace_report --fit` aggregates the executor spans
    into the SAME table."""
    import importlib
    import os
    import sys

    from keystone_tpu.utils.metrics import (
        active_tracer,
        profile_scope,
        reset_tracer,
        resource_profile,
    )

    X = rng.normal(size=(48, 16)).astype(np.float32)
    pipe = Pipeline.gather(
        [HostWork(1, iters=8).to_pipeline(),
         HostWork(2, iters=8).to_pipeline(),
         HostWork(3, iters=8).to_pipeline()]
    )
    prior_trace = config.trace
    config.trace = True
    reset_tracer()
    resource_profile.reset()
    config.exec_workers = 4
    try:
        tracer = active_tracer()
        with profile_scope():
            pipe.apply(X).get()
        rows = {r["node"]: r for r in resource_profile.rows()}
        # Exact attribution: 3 HostWork executions (one per branch seed —
        # distinct signatures, no dedup), 1 Gather, 1 Dataset.
        assert rows["HostWork"]["calls"] == 3
        assert rows["HostWork"]["executed"] == 3
        assert rows["Gather"]["calls"] == 1
        assert rows["Dataset"]["calls"] == 1
        for r in rows.values():
            if r["executed"]:
                assert r["wall_ms"] > 0
                assert r["queue_wait_ms"] is not None
                assert r["workers"], r
                for w in r["workers"]:
                    assert w.startswith("keystone-exec")
        # Per-label wall equals the sum of that label's span durations
        # (each execution attributed exactly once, no double counting).
        doc = tracer.export(str(tmp_path / "fit_trace.json"))
        sys.path.insert(
            0,
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "tools"),
        )
        try:
            trace_report = importlib.import_module("trace_report")
        finally:
            sys.path.pop(0)
        trows = {r["node"]: r for r in trace_report.fit_rows(doc)}
        assert set(trows) == set(rows)
        for label, tr in trows.items():
            assert tr["calls"] == rows[label]["calls"]
            assert tr["executed"] == rows[label]["executed"]
            assert tr["cache_hits"] == rows[label]["cache_hits"]
            assert tr["wall_ms"] == pytest.approx(
                rows[label]["wall_ms"], rel=0.05, abs=0.05
            )
        # Same renderer, same table shape for both sources.
        from keystone_tpu.utils.metrics import render_attribution_table

        live = render_attribution_table(resource_profile.rows())
        from_trace = render_attribution_table(trace_report.fit_rows(doc))
        assert [ln.split()[0] for ln in live.splitlines()[2:]] == [
            ln.split()[0] for ln in from_trace.splitlines()[2:]
        ]
    finally:
        config.trace = prior_trace
        config.exec_workers = 0
        reset_tracer()
        resource_profile.reset()


def test_bench_fit_harness_in_process():
    """`make bench-fit`'s harness at --quick scale: the row is
    well-formed, fingerprinted, and the bit-identity gate holds (the
    speedup gate is timing and belongs to the bench, not tier-1)."""
    import argparse
    import importlib
    import os
    import sys

    sys.path.insert(
        0,
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools"),
    )
    try:
        bench_fit = importlib.import_module("bench_fit")
    finally:
        sys.path.pop(0)
    args = argparse.Namespace(
        branches=2, workers=2, reps=1, rows=64, dim=32, classes=4,
        work_iters=4, quick=True, out=None,
    )
    row = bench_fit.run_bench(args)
    assert row["metric"] == "fit_parallel_walk"
    assert row["detail"]["bit_identical"] is True
    assert row["ok"] is True
    assert row["env"]["cpu_count"] == row["host_cores"]
    assert row["detail"]["serial_wall_s"] > 0
    assert row["detail"]["parallel_wall_s"] > 0


def test_dead_shared_pool_errors_instead_of_hanging(rng, monkeypatch):
    """A pool whose submit refuses (rebuilt/shut down under an active
    walk) must surface as the walk's error, not wedge run()'s drain wait
    with a phantom in-flight count."""
    from keystone_tpu.workflow import executor as executor_mod

    class DeadPool:
        def submit(self, fn, *a):
            raise RuntimeError("cannot schedule new futures after shutdown")

    monkeypatch.setattr(
        executor_mod, "_exec_pool", lambda workers: DeadPool()
    )
    X = rng.normal(size=(8, 4)).astype(np.float32)
    pipe = HostWork(1, iters=1).to_pipeline()
    config.exec_workers = 4
    with pytest.raises(RuntimeError, match="after shutdown"):
        pipe.apply(X).get()


def test_mark_delta_scopes_workers_to_the_window():
    """rows(since=mark) names only pool threads first seen AFTER the
    mark — pre-mark workers must not bleed into a phase's delta view."""
    from keystone_tpu.utils.metrics import ResourceProfile

    p = ResourceProfile()
    p.record_node("A", wall_ns=1000, worker="w0")
    p.record_node("A", wall_ns=1000, worker="w1")
    mark = p.mark()
    p.record_node("A", wall_ns=1000, worker="w2")
    (delta,) = p.rows(since=mark)
    assert delta["workers"] == ["w2"]
    (cumulative,) = p.rows()
    assert cumulative["workers"] == ["w0", "w1", "w2"]


def test_record_node_is_exact_under_concurrent_writers():
    """The ResourceProfile fold is one atomic read-modify-write: 4
    threads x 500 records keep exact totals."""
    from keystone_tpu.utils.metrics import ResourceProfile

    p = ResourceProfile()

    def pound(worker):
        for _ in range(500):
            p.record_node("N", wall_ns=1000, dispatch_ns=200,
                          queue_wait_ns=10, worker=worker)

    threads = [
        threading.Thread(target=pound, args=(f"w{i}",)) for i in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    (row,) = p.rows()
    assert row["calls"] == 2000
    assert row["wall_ms"] == pytest.approx(2.0)
    assert row["queue_wait_ms"] == pytest.approx(0.02)
    assert row["workers"] == ["w0", "w1", "w2", "w3"]
