"""Deviceless AOT compilation of the device programs for a REAL v5e target.

The chip in this environment dies for whole sessions, which previously left
"first live compile may fail" as an open risk (VERDICT r2 weak #2). JAX's
topology API (`jax.experimental.topologies.get_topology_desc`) builds
compile-only v5e devices from libtpu with zero live hardware, so every hot
program — the BCD updates, the ring step, TSQR, normal-equations reductions,
and the Pallas Fisher-vector kernel (through Mosaic, at the real ImageNet
configuration) — gets XLA:TPU-compiled as a CI property, not a live-window
gamble.

These tests compile only (no execution — there is no device to run on);
numerics are covered by the CPU-mesh tests elsewhere in the suite.
"""

from __future__ import annotations

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "data"


def _v5e_mesh(n: int = 8):
    """An n-device compile-only v5e mesh, or a skip if the installed
    libtpu/PJRT can't build deviceless topologies (the exact failure is the
    skip reason, per the VERDICT's record-the-failure instruction)."""
    import subprocess
    import sys

    from jax.experimental import topologies

    # Probe in a KILLABLE subprocess first: a wedged libtpu (dead chip,
    # stale /tmp/libtpu_lockfile) HANGS topology construction instead of
    # erroring, and an in-process hang would eat the whole suite budget.
    # Only a probe that succeeds promotes to the in-process construction.
    probe_src = (
        "from jax.experimental import topologies;"
        "topologies.get_topology_desc('v5e:2x4', platform='tpu')"
    )
    try:
        probe = subprocess.run(
            [sys.executable, "-c", probe_src],
            capture_output=True, text=True, timeout=60,
        )
    except subprocess.TimeoutExpired:  # pragma: no cover - env-dependent
        pytest.skip("deviceless TPU topology unavailable: libtpu hung (>60s)")
    if probe.returncode != 0:  # pragma: no cover - environment-dependent
        tail = (probe.stderr or probe.stdout or "").strip().splitlines()
        pytest.skip(
            "deviceless TPU topology unavailable: "
            + (tail[-1] if tail else f"probe exit {probe.returncode}")
        )
    try:
        topo = topologies.get_topology_desc("v5e:2x4", platform="tpu")
    except Exception as e:  # pragma: no cover - environment-dependent
        pytest.skip(f"deviceless TPU topology unavailable: {type(e).__name__}: {e}")
    devs = topo.devices
    assert len(devs) >= n
    return Mesh(np.array(devs[:n]), (AXIS,))


@pytest.fixture(scope="module")
def mesh():
    return _v5e_mesh()


def _sds(shape, mesh, spec, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(
        shape, dtype, sharding=NamedSharding(mesh, spec)
    )


def _compiled_ok(compiled) -> bool:
    text = compiled.as_text()
    assert "HloModule" in text
    return True


def test_bcd_block_update_compiles_for_v5e(mesh):
    from keystone_tpu.linalg.bcd import _block_update_fn
    from keystone_tpu.linalg.row_matrix import _precision

    fn = _block_update_fn(mesh, AXIS, _precision(), False)
    n, b, k = 1024, 128, 16
    args = (
        _sds((n, b), mesh, P(AXIS)),  # a_b
        _sds((n, k), mesh, P(AXIS)),  # r
        _sds((b, k), mesh, P()),  # w_b
        _sds((), mesh, P()),  # lam
        _sds((n,), mesh, P(AXIS)),  # w_rows
    )
    compiled = fn.lower(*args).compile()
    assert _compiled_ok(compiled)
    # The gram psum must be present as a TPU collective.
    assert "all-reduce" in compiled.as_text()


@pytest.mark.parametrize(
    "n,b,k,whole_mesh",
    [
        (1024, 128, 16, True),
        # The ImageNet block size on one device — the host-streamed
        # path's production shape (slow: real v5e buffer assignment).
        pytest.param(8192, 8192, 1000, False, marks=pytest.mark.slow),
    ],
)
def test_bcd_streamed_first_and_cached_updates_compile_for_v5e(
    mesh, n, b, k, whole_mesh
):
    from keystone_tpu.linalg.bcd import (
        _cached_block_update_fn,
        _first_epoch_update_fn,
    )
    from keystone_tpu.linalg.row_matrix import _precision

    if not whole_mesh:
        mesh = Mesh(np.array(mesh.devices.flat[:1]), (AXIS,))
    first = _first_epoch_update_fn(mesh, AXIS, _precision(), True)
    c1 = first.lower(
        _sds((n, b), mesh, P(AXIS)),
        _sds((n, k), mesh, P(AXIS)),
        _sds((b, k), mesh, P()),
        _sds((), mesh, P()),
        _sds((n,), mesh, P(AXIS)),
    ).compile()
    assert _compiled_ok(c1)
    cached = _cached_block_update_fn(mesh, AXIS, _precision(), True)
    c2 = cached.lower(
        _sds((n, b), mesh, P(AXIS)),
        _sds((b, b), mesh, P()),  # cached ridge inverse
        _sds((n, k), mesh, P(AXIS)),
        _sds((b, k), mesh, P()),
        _sds((n,), mesh, P(AXIS)),
    ).compile()
    assert _compiled_ok(c2)


def test_batched_factor_phase_compiles_for_v5e(mesh):
    """The batched factor phase (gram-only + batched Cholesky/trsm over a
    leading block axis) must XLA:TPU-compile — it is the accelerator
    default for multi-block cached solves."""
    from keystone_tpu.linalg.bcd import _batched_ridge_inv_fn, _gram_only_fn
    from keystone_tpu.linalg.row_matrix import _precision

    n, b, g = 1024, 128, 16
    gram_only = _gram_only_fn(mesh, AXIS, _precision(), False)
    c1 = gram_only.lower(
        _sds((n, b), mesh, P(AXIS)),
        _sds((), mesh, P()),
        _sds((n,), mesh, P(AXIS)),
    ).compile()
    assert _compiled_ok(c1)
    batched = _batched_ridge_inv_fn(mesh)
    c2 = batched.lower(_sds((g, b, b), mesh, P())).compile()
    assert _compiled_ok(c2)


def test_ring_bcd_step_compiles_for_v5e(mesh):
    """The mp ring: ppermute over the model axis must lower to a TPU
    collective-permute inside a while loop."""
    from keystone_tpu.linalg.ring_bcd import _ring_solve_fn
    from keystone_tpu.linalg.row_matrix import _precision

    fn = _ring_solve_fn(mesh, AXIS, None, _precision())
    n, d, k = 512, 256, 16
    kc = k // 8 if k >= 8 else k
    compiled = fn.lower(
        _sds((n, d), mesh, P(None, AXIS)),
        _sds((n, 8 * kc), mesh, P(None, AXIS)),
        _sds((), mesh, P()),
        _sds((), mesh, P(), dtype=jnp.int32),  # num_steps (dynamic bound)
    ).compile()
    text = compiled.as_text()
    assert "collective-permute" in text
    assert "while" in text


def test_tsqr_compiles_for_v5e(mesh):
    from keystone_tpu.linalg.tsqr import _tsqr_r_fn

    fn = _tsqr_r_fn(mesh, AXIS)
    compiled = fn.lower(_sds((2048, 64), mesh, P(AXIS))).compile()
    text = compiled.as_text()
    assert "all-gather" in text


def test_normal_equations_reductions_compile_for_v5e(mesh):
    from keystone_tpu.linalg.row_matrix import _gram_and_atb_fn, _precision

    fn = _gram_and_atb_fn(mesh, AXIS, _precision())
    compiled = fn.lower(
        _sds((2048, 256), mesh, P(AXIS)), _sds((2048, 16), mesh, P(AXIS))
    ).compile()
    assert "all-reduce" in compiled.as_text()


def test_pallas_fv_mosaic_compiles_for_v5e(mesh):
    """The Pallas kernel through the REAL Mosaic lowering (interpret=False)
    — the exact compile the live-window checkride would otherwise risk."""
    from keystone_tpu.ops.fisher_vector_pallas import fisher_vectors_pallas

    one = Mesh(np.array(mesh.devices.flat[:1]), ("d",))
    repl = NamedSharding(one, P())

    def sds(shape, dtype=jnp.float32):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=repl)

    fv = functools.partial(fisher_vectors_pallas, interpret=False)
    bsz, m, d, k = 2, 256, 64, 16
    compiled = (
        jax.jit(fv)
        .lower(sds((bsz, m, d)), sds((k,)), sds((k, d)), sds((k, d)))
        .compile()
    )
    assert _compiled_ok(compiled)
    assert "custom-call" in compiled.as_text()  # the Mosaic kernel call


@pytest.mark.slow
def test_pallas_fv_mosaic_compiles_at_imagenet_config(mesh):
    """k=256, m≈2000, d=64 — the configuration whose VMEM/tiling limits the
    VERDICT flagged as never exercised. Compiling it for v5e settles that
    without a chip."""
    from keystone_tpu.ops.fisher_vector_pallas import fisher_vectors_pallas

    one = Mesh(np.array(mesh.devices.flat[:1]), ("d",))
    repl = NamedSharding(one, P())

    def sds(shape, dtype=jnp.float32):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=repl)

    fv = functools.partial(fisher_vectors_pallas, interpret=False)
    bsz, m, d, k = 8, 2048, 64, 256
    compiled = (
        jax.jit(fv)
        .lower(sds((bsz, m, d)), sds((k,)), sds((k, d)), sds((k, d)))
        .compile()
    )
    assert _compiled_ok(compiled)


def test_dense_sift_xla_compiles_for_v5e(mesh):
    """The on-chip dense SIFT (grouped 1-D convs) must XLA:TPU-compile —
    it is the --sift-backend xla path that moves the last host-side
    featurization stage onto the chips."""
    import functools

    from keystone_tpu.ops.sift_xla import dense_sift_xla

    fn = functools.partial(dense_sift_xla, step=4, bin_size=4)
    # The batch-sharded input carries the v5e topology — without a
    # sharding the lowering would silently target the default (CPU)
    # backend and prove nothing.
    c = jax.jit(fn).lower(
        _sds((8, 256, 256), mesh, P(AXIS))
    ).compile()
    assert _compiled_ok(c)


def test_convolver_compiles_for_v5e(mesh):
    """The image-pipeline hot op (conv_general_dilated in bf16 compute) on
    the v5e target."""
    from keystone_tpu.nodes.images.convolver import Convolver

    conv = Convolver(np.zeros((64, 6, 6, 3), dtype=np.float32))
    one = Mesh(np.array(mesh.devices.flat[:1]), ("d",))
    x = jax.ShapeDtypeStruct(
        (32, 32, 32, 3), jnp.float32, sharding=NamedSharding(one, P())
    )
    compiled = jax.jit(conv.apply_batch).lower(x).compile()
    assert "convolution" in compiled.as_text()


def test_fused_solver_programs_compile_for_v5e(mesh):
    """The r4 scan-fused solve (stack → batched factor → scanned epochs)
    — the three programs the bench now times — must XLA:TPU-compile."""
    from keystone_tpu.linalg.bcd import (
        _fused_epochs_fn,
        _fused_factor_fn,
        _stack_blocks_fn,
    )
    from keystone_tpu.linalg.row_matrix import _precision

    n, d, b, k, nb = 1024, 512, 128, 16, 4
    stack = _stack_blocks_fn(mesh, AXIS, nb)
    c0 = stack.lower(_sds((n, d), mesh, P(AXIS))).compile()
    assert _compiled_ok(c0)
    factor = _fused_factor_fn(mesh, AXIS, _precision(), False)
    c1 = factor.lower(
        _sds((nb, n, b), mesh, P(None, AXIS)),
        _sds((), mesh, P()),
        _sds((n,), mesh, P(AXIS)),
    ).compile()
    assert "all-reduce" in c1.as_text()
    epochs = _fused_epochs_fn(mesh, AXIS, _precision(), False, 3, True)
    c2 = epochs.lower(
        _sds((nb, n, b), mesh, P(None, AXIS)),
        _sds((nb, b, b), mesh, P()),
        _sds((n, k), mesh, P(AXIS)),
        _sds((nb, b, k), mesh, P()),
        _sds((), mesh, P()),
        _sds((n,), mesh, P(AXIS)),
    ).compile()
    text = c2.as_text()
    assert "while" in text  # the scanned epoch/block loops
    assert "all-reduce" in text


@pytest.mark.slow
def test_two_branch_imagenet_featurizer_compiles_for_v5e(mesh):
    """The FULL gathered featurizer graph at the headline 64k-dim config
    (SIFT-XLA and LCS branches, each PCA→FV(k=256)→signed-sqrt→L2, fused
    and concatenated) XLA:TPU-compiles as ONE program inside a stated
    wall-time budget — SURVEY.md §7 hard part 6 ("two deep branches fused
    without blowing compile time"), previously covered only per-program."""
    import time

    from keystone_tpu.loaders.imagenet import ImageNetLoader
    from keystone_tpu.pipelines.images.imagenet_sift_lcs_fv import (
        ImageNetSiftLcsFVConfig,
        build_featurizer,
    )
    from keystone_tpu.workflow import PipelineEnv, fitted_forward

    conf = ImageNetSiftLcsFVConfig(
        sift_backend="xla",  # the jittable on-chip branch (native = ctypes)
        fv_backend="tpu",
        pca_dims=64,
        gmm_k=256,  # 2·(2·256·64) = 65,536-dim gathered features
        gmm_iters=2,
        descriptor_sample=20_000,
    )
    train, _ = ImageNetLoader.synthetic(n=16, num_classes=4, size=64)
    PipelineEnv.reset()
    try:
        featurizer = build_featurizer(conf, train.data)
        fn = fitted_forward(featurizer, train.data[:2])
        out = jax.eval_shape(
            fn, jax.ShapeDtypeStruct((8, 64, 64, 3), jnp.float32)
        )
        assert out.shape[-1] == 2 * (2 * conf.gmm_k * conf.pca_dims) == 65_536
        t0 = time.time()
        compiled = (
            jax.jit(fn)
            .lower(_sds((8, 64, 64, 3), mesh, P(AXIS)))
            .compile()
        )
        wall = time.time() - t0
    finally:
        PipelineEnv.reset()
    assert _compiled_ok(compiled)
    # Budget: generous for the 1-core host, but low enough that a
    # combinatorial blowup (e.g. per-descriptor unrolling) fails loudly.
    assert wall < 600.0, f"featurizer compile took {wall:.0f}s"


@pytest.mark.slow
@pytest.mark.parametrize(
    "scale_key,expected_chunk",
    [
        ("tpu-imagenet", 2),  # memory cap binds: 128M // 8192² = 2
        ("tpu-xl", 16),  # batch default binds (cap would allow 32)
    ],
)
def test_fused_solver_compiles_at_bench_shapes(mesh, scale_key, expected_chunk):
    """The full-scale bench shapes ('tpu-imagenet' n=8192/d=65536/k=1000/
    b=8192; 'tpu-xl' d=262144, 128 blocks of 2048 — the step that preceded
    two relay deaths) must not hit their first XLA:TPU compile inside a
    live window, and must fit v5e buffer assignment."""
    import bench as bench_mod
    from keystone_tpu.linalg.bcd import (
        _factor_chunk,
        _fused_epochs_fn,
        _fused_factor_fn,
    )
    from keystone_tpu.linalg.row_matrix import _precision

    p = bench_mod.SCALE[scale_key]
    n, d, k, b = p["n"], p["d"], p["k"], p["block"]
    nb = d // b
    one = Mesh(np.array(mesh.devices.flat[:1]), (AXIS,))
    # The production factor phase chunks the stack (_solve_fused): the
    # UNCHUNKED (nb, n, b) factor program at this shape demands ~5 stacked
    # (nb, b, b) temps ≈ 10+ GB of HLO temp and fails v5e buffer
    # assignment — which is exactly why the chunk policy exists. Compile
    # the shape production actually runs.
    from unittest import mock

    with mock.patch("jax.default_backend", return_value="tpu"):
        chunk = _factor_chunk(b)  # the TPU policy, not this CPU host's
    # Pin the policy output per scale so cap rot is detected where the
    # cap binds (imagenet) and batch-default drift where it doesn't (xl).
    assert chunk == expected_chunk and chunk < nb
    factor = _fused_factor_fn(one, AXIS, _precision(), False)
    c1 = factor.lower(
        _sds((chunk, n, b), one, P(None, AXIS)),
        _sds((), one, P()),
        _sds((n,), one, P(AXIS)),
    ).compile()
    assert _compiled_ok(c1)
    epochs = _fused_epochs_fn(one, AXIS, _precision(), False, p["iters"], True)
    c2 = epochs.lower(
        _sds((nb, n, b), one, P(None, AXIS)),
        _sds((nb, b, b), one, P()),
        _sds((n, k), one, P(AXIS)),
        _sds((nb, b, k), one, P()),
        _sds((), one, P()),
        _sds((n,), one, P(AXIS)),
    ).compile()
    assert _compiled_ok(c2)
    if scale_key == "tpu-imagenet":
        # The UNCACHED body (single-epoch solves, cache_grams auto=False
        # at num_iters=1) re-derives each block's inverse INSIDE the scan
        # — the chunked-trsm machinery must fit there too. The dummy invs
        # operand mirrors _solve_fused's (nb, 1, 1) placeholder.
        unc = _fused_epochs_fn(one, AXIS, _precision(), False, 1, False)
        c3 = unc.lower(
            _sds((nb, n, b), one, P(None, AXIS)),
            _sds((nb, 1, 1), one, P()),
            _sds((n, k), one, P(AXIS)),
            _sds((nb, b, k), one, P()),
            _sds((), one, P()),
            _sds((n,), one, P(AXIS)),
        ).compile()
        assert _compiled_ok(c3)
