"""Linear solver estimators vs oracles; evaluation metrics."""

import numpy as np

from keystone_tpu.evaluation import MulticlassClassifierEvaluator
from keystone_tpu.nodes.learning import (
    LinearMapEstimator,
    LocalLeastSquaresEstimator,
)


def _ridge_with_intercept_oracle(X, Y, lam):
    Xc = X - X.mean(axis=0)
    Yc = Y - Y.mean(axis=0)
    d = X.shape[1]
    W = np.linalg.solve(Xc.T @ Xc + lam * np.eye(d), Xc.T @ Yc)
    b = Y.mean(axis=0) - X.mean(axis=0) @ W
    return W, b


def test_linear_map_estimator_matches_oracle(rng):
    X = rng.normal(size=(120, 10)).astype(np.float32)
    Y = rng.normal(size=(120, 3)).astype(np.float32)
    lam = 0.5
    mapper = LinearMapEstimator(lam=lam).fit(X, Y)
    W, b = _ridge_with_intercept_oracle(
        X.astype(np.float64), Y.astype(np.float64), lam
    )
    np.testing.assert_allclose(mapper.W, W, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(mapper.b, b, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(mapper(X), X @ W + b, rtol=1e-3, atol=1e-3)


def test_linear_map_estimator_tsqr_method(rng):
    X = rng.normal(size=(80, 6)).astype(np.float32)
    Y = rng.normal(size=(80, 2)).astype(np.float32)
    m_normal = LinearMapEstimator(lam=0.1).fit(X, Y)
    m_tsqr = LinearMapEstimator(lam=0.1, method="tsqr").fit(X, Y)
    np.testing.assert_allclose(m_normal.W, m_tsqr.W, rtol=1e-3, atol=1e-3)


def test_local_least_squares_matches_distributed(rng):
    X = rng.normal(size=(60, 5)).astype(np.float32)
    Y = rng.normal(size=(60, 2)).astype(np.float32)
    m_local = LocalLeastSquaresEstimator(lam=0.2).fit(X, Y)
    m_dist = LinearMapEstimator(lam=0.2).fit(X, Y)
    np.testing.assert_allclose(m_local.W, m_dist.W, rtol=1e-3, atol=1e-3)


def test_multiclass_evaluator():
    pred = np.array([0, 1, 1, 2, 2, 2])
    act = np.array([0, 1, 2, 2, 2, 0])
    m = MulticlassClassifierEvaluator(3).evaluate(pred, act)
    assert m.confusion.sum() == 6
    assert m.confusion[2, 2] == 2
    np.testing.assert_allclose(m.total_accuracy, 4 / 6)
    np.testing.assert_allclose(m.per_class_accuracy, [0.5, 1.0, 2 / 3])
    assert 0.0 < m.macro_f1 <= 1.0


def test_auto_block_size_resolution_and_fit(rng, monkeypatch):
    """block_size="auto" picks a single exact block for d <= the backend
    cap (matching the fixed-default behavior), shrinks under the HBM
    envelope at huge d, and fits identically to an explicit block size."""
    from keystone_tpu.config import config
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
    from keystone_tpu.nodes.learning.block_least_squares import (
        resolve_block_size,
    )

    import jax

    cap = 4096 if jax.default_backend() == "cpu" else 8192
    assert resolve_block_size(512, 100000) == 512  # explicit wins
    assert resolve_block_size("auto", 24) == 128
    assert resolve_block_size("auto", 3000) == min(4096, cap)  # exact block
    assert resolve_block_size("auto", 10000) == cap
    # HBM envelope: d*b*4 must fit a quarter of the budget.
    monkeypatch.setattr(config, "hbm_budget_bytes", 12 * (1 << 30))
    assert resolve_block_size("auto", 262144) == 2048
    assert resolve_block_size("auto", 524288) == 1024

    X = rng.normal(size=(200, 24)).astype(np.float32)
    Y = rng.normal(size=(200, 3)).astype(np.float32)
    auto = BlockLeastSquaresEstimator(num_iters=2, lam=0.2).fit(X, Y)
    fixed = BlockLeastSquaresEstimator(
        block_size=4096, num_iters=2, lam=0.2
    ).fit(X, Y)
    np.testing.assert_allclose(auto.W, fixed.W, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(auto.b, fixed.b, rtol=1e-5, atol=1e-5)
