"""Versioned model artifacts: the fit→serve handoff contract.

Round-trips for fitted canonical pipelines (MNIST FFT, newsgroups text):
save_artifact → load_artifact → predictions bit-identical. Mismatched
schema versions, tampered payloads, and failed fingerprint pins raise a
typed ArtifactVersionError AT LOAD TIME — never deep inside apply under
traffic.
"""

import json
import os

import numpy as np
import pytest

from keystone_tpu.workflow.serialization import (
    ARTIFACT_SCHEMA_VERSION,
    ArtifactVersionError,
    load_artifact,
    load_pipeline,
    read_artifact_header,
    save_artifact,
    save_pipeline,
)

_MAGIC = b"KEYSTONE_ARTIFACT\n"


def _small_fitted_pipeline(d=6, seed=0):
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.nodes.stats.random_features import CosineRandomFeatures

    return (
        CosineRandomFeatures.create(d, 12, seed=seed)
        .and_then(L2Normalizer())
        .fit()
    )


def _artifact_roundtrip(pipe, sample, tmp_path, tag):
    ref = np.asarray(pipe.apply(sample).get())
    path = str(tmp_path / f"{tag}.kart")
    art = save_artifact(pipe, path)
    assert art.schema_version == ARTIFACT_SCHEMA_VERSION
    assert art.fingerprint
    loaded = load_artifact(path)
    assert loaded.fingerprint == art.fingerprint
    assert loaded.pipeline_digest == art.pipeline_digest
    got = np.asarray(loaded.pipeline.apply(sample).get())
    np.testing.assert_array_equal(got, ref)
    return path, art


def test_mnist_fft_artifact_roundtrip(tmp_path):
    from keystone_tpu.loaders import MnistLoader
    from keystone_tpu.pipelines.images.mnist_random_fft import (
        MnistRandomFFTConfig,
        build_pipeline,
    )

    train, _ = MnistLoader.synthetic(n=256, seed=0)
    conf = MnistRandomFFTConfig(num_ffts=2, synthetic_n=256)
    pipe = build_pipeline(conf, train.data, train.labels).fit()
    _artifact_roundtrip(pipe, train.data[:16], tmp_path, "mnist")


def test_newsgroups_artifact_roundtrip(tmp_path):
    from keystone_tpu.loaders.newsgroups import NewsgroupsDataLoader
    from keystone_tpu.nodes.learning import NaiveBayesEstimator
    from keystone_tpu.nodes.nlp import (
        CommonSparseFeatures,
        LowerCase,
        NGramsFeaturizer,
        TermFrequency,
        Tokenizer,
        Trim,
    )
    from keystone_tpu.nodes.util import MaxClassifier

    train, test, classes = NewsgroupsDataLoader.synthetic(
        n=300, num_classes=4
    )
    pipe = (
        Trim()
        .and_then(LowerCase())
        .and_then(Tokenizer())
        .and_then(NGramsFeaturizer(1, 2))
        .and_then(TermFrequency("log"))
        .and_then(CommonSparseFeatures(300), train.data)
        .and_then(NaiveBayesEstimator(len(classes)), train.data, train.labels)
        .and_then(MaxClassifier())
        .fit()
    )
    ref = np.asarray(pipe.apply(test.data).get())
    path = str(tmp_path / "newsgroups.kart")
    save_artifact(pipe, path)
    got = np.asarray(load_artifact(path).pipeline.apply(test.data).get())
    np.testing.assert_array_equal(got, ref)


def test_artifact_header_readable_without_unpickling(tmp_path):
    pipe = _small_fitted_pipeline()
    path = str(tmp_path / "m.kart")
    art = save_artifact(pipe, path, feature_shape=(6,), dtype="float32",
                        extra={"note": "demo"})
    header = read_artifact_header(path)
    assert header["schema_version"] == ARTIFACT_SCHEMA_VERSION
    assert header["fingerprint"] == art.fingerprint
    assert header["serve"] == {
        "feature_shape": [6], "dtype": "float32", "note": "demo",
    }
    # The environment subset names the backend it was exported under.
    assert "jax" in header["environment"]
    assert "backend" in header["environment"]


def test_mismatched_schema_version_is_typed_error(tmp_path):
    pipe = _small_fitted_pipeline()
    path = str(tmp_path / "m.kart")
    save_artifact(pipe, path)
    with open(path, "rb") as f:
        assert f.read(len(_MAGIC)) == _MAGIC
        header = json.loads(f.readline())
        payload = f.read()
    header["schema_version"] = 99
    with open(path, "wb") as f:
        f.write(_MAGIC)
        f.write(json.dumps(header).encode() + b"\n")
        f.write(payload)
    with pytest.raises(ArtifactVersionError, match="schema version 99"):
        load_artifact(path)


def test_tampered_payload_fails_fingerprint_check(tmp_path):
    pipe = _small_fitted_pipeline()
    path = str(tmp_path / "m.kart")
    save_artifact(pipe, path)
    with open(path, "ab") as f:
        f.write(b"\x00")  # one trailing byte: corruption, not a new model
    with pytest.raises(ArtifactVersionError, match="fingerprint"):
        load_artifact(path)


def test_expect_fingerprint_pin_enforced(tmp_path):
    pipe = _small_fitted_pipeline()
    path = str(tmp_path / "m.kart")
    art = save_artifact(pipe, path)
    # The correct pin loads; a wrong pin is a typed refusal.
    assert load_artifact(
        path, expect_fingerprint=art.fingerprint
    ).fingerprint == art.fingerprint
    with pytest.raises(ArtifactVersionError, match="required"):
        load_artifact(path, expect_fingerprint="deadbeef")


def test_bare_pickle_is_not_an_artifact(tmp_path):
    pipe = _small_fitted_pipeline()
    pkl = str(tmp_path / "bare.pkl")
    save_pipeline(pipe, pkl)
    with pytest.raises(ArtifactVersionError, match="magic"):
        load_artifact(pkl)
    # ...and the bare-pickle path still round-trips unchanged.
    assert load_pipeline(pkl) is not None


def test_unfitted_pipeline_refused(tmp_path):
    from keystone_tpu.loaders.timit import TimitFeaturesDataLoader
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator
    from keystone_tpu.nodes.util import ClassLabelIndicators

    train, _ = TimitFeaturesDataLoader.synthetic(n=64)
    targets = ClassLabelIndicators(int(train.labels.max()) + 1)(train.labels)
    from keystone_tpu.nodes.stats import CosineRandomFeatures

    pipe = CosineRandomFeatures.create(train.data.shape[1], 32, seed=0) \
        .and_then(BlockLeastSquaresEstimator(num_iters=1, lam=1e-2),
                  train.data, targets)
    with pytest.raises(ValueError, match="unfitted"):
        save_artifact(pipe, str(tmp_path / "x.kart"))


def test_digest_stable_across_roundtrip(tmp_path):
    # The content-stable template digest recorded at save time matches a
    # recompute over the LOADED pipeline — the cross-process identity
    # the fit cache relies on survives serialization.
    from keystone_tpu.workflow.serialization import pipeline_digest

    pipe = _small_fitted_pipeline()
    path = str(tmp_path / "m.kart")
    art = save_artifact(pipe, path)
    loaded = load_artifact(path)
    if art.pipeline_digest is not None:
        assert pipeline_digest(loaded.pipeline) == art.pipeline_digest


def test_tampered_header_fails_fingerprint_check(tmp_path):
    """The fingerprint covers the HEADER too: a flipped serve hint
    (feature_shape) must fail the load loudly, not warm a wrong-shaped
    ladder that 400s every request."""
    pipe = _small_fitted_pipeline()
    path = str(tmp_path / "m.kart")
    save_artifact(pipe, path, feature_shape=(6,), dtype="float32")
    with open(path, "rb") as f:
        assert f.read(len(_MAGIC)) == _MAGIC
        header = json.loads(f.readline())
        payload = f.read()
    header["serve"]["feature_shape"] = [60]  # the bit-rot/edit
    with open(path, "wb") as f:
        f.write(_MAGIC)
        f.write(json.dumps(header, sort_keys=True).encode() + b"\n")
        f.write(payload)
    with pytest.raises(ArtifactVersionError, match="fingerprint"):
        load_artifact(path)
