"""Fused & donated fits (the ISSUE-16 tentpole).

Covers the donation contract end to end:

1. **Invisibility** — a donated fused fit is bit-identical to the same
   fit with ``KEYSTONE_DONATE_BUFFERS=0`` (donation changes WHERE the
   output lives, never what it is), including through the Pallas
   Fisher-vector sharded path.
2. **The buffers** — only staging copies ``_sharded_call`` itself
   creates are donated: the staged buffer is provably dead afterwards
   (deleted-buffer error pinned), while caller-owned arrays — host
   batches and mesh-placed ``jax.Array`` inputs — stay readable.
3. **Refusal is counted, never silent** — XLA aliases donated buffers
   to outputs by exact aval, so shrinking/growing chains refuse up
   front and bump ``donation_refused``.
4. **The memory win** — per-device working set (argument + output +
   temp − alias, the PR-8 ``memory_analysis`` attribution) of the
   donated lowering sits strictly below the undonated one. This is the
   CPU-portable form of the peak-HBM gate ``bench_imagenet`` enforces
   on real hardware.
5. **KG106** — a fused sharded fit whose accumulator-carrying chain
   lowers WITHOUT donation (mesh-placed caller-owned input) warns while
   ``config.donate_buffers`` promises one live copy; pinned both ways.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from keystone_tpu.config import Config, config
from keystone_tpu.utils.mesh import SpecLayout, batch_layout
from keystone_tpu.utils.metrics import _memory_analysis, sharding_counters
from keystone_tpu.workflow.executor import PipelineEnv
from keystone_tpu.workflow.pipeline import Transformer


@pytest.fixture(autouse=True)
def _fresh_donation_state():
    """Counters and the shard/donate toggles restored around every test."""
    prior = (config.shard_data_batches, config.donate_buffers)
    sharding_counters.reset()
    PipelineEnv.reset()
    yield
    config.shard_data_batches, config.donate_buffers = prior
    sharding_counters.reset()
    PipelineEnv.reset()


class SquareChain(Transformer):
    """Shape-preserving jittable chain: its output aval matches its
    input aval, so the staged buffer can alias into the output."""

    def __init__(self, seed: int = 0, d: int = 32):
        self.seed, self.d = int(seed), int(d)
        rng = np.random.default_rng(self.seed)
        self._W = jnp.asarray(rng.normal(size=(d, d)).astype(np.float32))

    def signature(self):
        return self.stable_signature(self.seed, self.d)

    def apply_batch(self, X):
        return jnp.tanh(X @ self._W) + 0.25 * X


class ShrinkChain(Transformer):
    """32 → 16 columns: no output aval can alias the donated input."""

    def __init__(self, seed: int = 0, d_in: int = 32, d_out: int = 16):
        self.seed = int(seed)
        rng = np.random.default_rng(self.seed)
        self._W = jnp.asarray(
            rng.normal(size=(d_in, d_out)).astype(np.float32)
        )

    def signature(self):
        return self.stable_signature(self.seed)

    def apply_batch(self, X):
        return jnp.tanh(X @ self._W)


class HostPass(Transformer):
    """Row-preserving host stage: whatever follows it receives a HOST
    batch and must stage (and may donate) its own copy."""

    jittable = False

    def signature(self):
        return self.stable_signature()

    def apply_batch(self, X):
        return np.asarray(X) * 1.0


def _host_staged_fit(donate: bool, rows: int = 128):
    """Fit the host-arrival chain (HostPass → SquareChain → BlockLS):
    the jittable stage's input arrives host-side, so every chain call
    stages its own copy — the flagship ImageNet shape, where SIFT/LCS
    run on the host and the fused jittable tail stages."""
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator

    rng = np.random.default_rng(7)
    X = rng.normal(size=(rows, 32)).astype(np.float32)
    y = rng.normal(size=(rows, 4)).astype(np.float32)
    X_test = rng.normal(size=(rows, 32)).astype(np.float32)
    PipelineEnv.reset()
    config.shard_data_batches = True
    config.donate_buffers = donate
    pipe = HostPass().and_then(SquareChain(3)).and_then(
        BlockLeastSquaresEstimator(block_size=64, num_iters=1, lam=1e-3),
        X, y,
    )
    fitted = pipe.fit()
    preds = np.asarray(fitted.apply(X_test).get())
    return preds, sharding_counters.snapshot()


# ---------------------------------------------------------------------------
# Donation is invisible: bit-identical fits either way
# ---------------------------------------------------------------------------


def test_donated_fit_bit_identical_to_undonated_walk():
    donated, c_on = _host_staged_fit(donate=True)
    sharding_counters.reset()
    undonated, c_off = _host_staged_fit(donate=False)
    assert donated.tobytes() == undonated.tobytes()
    # The donate-on fit actually donated (shape-preserving chain over a
    # staged host arrival), and the knob fully disarms the path.
    assert c_on.get("buffers_donated", 0) > 0
    assert c_off.get("buffers_donated", 0) == 0
    assert c_off.get("donation_refused", 0) == 0


def test_donated_fit_bit_identical_to_single_device_walk():
    """Sharded + donated == the plain unsharded jitted walk, byte for
    byte — donation composes with the PR-13 bit-identity contract."""
    donated, _ = _host_staged_fit(donate=True)
    sharding_counters.reset()
    PipelineEnv.reset()
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator

    rng = np.random.default_rng(7)
    X = rng.normal(size=(128, 32)).astype(np.float32)
    y = rng.normal(size=(128, 4)).astype(np.float32)
    X_test = rng.normal(size=(128, 32)).astype(np.float32)
    config.shard_data_batches = False
    pipe = HostPass().and_then(SquareChain(3)).and_then(
        BlockLeastSquaresEstimator(block_size=64, num_iters=1, lam=1e-3),
        X, y,
    )
    plain = np.asarray(pipe.fit().apply(X_test).get())
    assert donated.tobytes() == plain.tobytes()


# ---------------------------------------------------------------------------
# The donated buffer: staged copies die, caller-owned arrays survive
# ---------------------------------------------------------------------------


def test_donated_staging_buffer_is_deleted_after_call():
    """The staged copy is consumed by the donated lowering — XLA reuses
    its memory for the output, and any later read is the canonical
    deleted-buffer RuntimeError. This pins the failure mode the README
    documents (and proves donation really happened: an undonated call
    leaves the buffer readable)."""
    config.shard_data_batches = True
    config.donate_buffers = True
    chain = SquareChain(1)
    X = np.random.default_rng(0).normal(size=(128, 32)).astype(np.float32)
    layout = batch_layout(X)
    assert layout is not None
    staged = layout.put(X)
    chain._staged_call(staged, layout)
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(staged)
    # Control: with the knob off the same staged call leaves it live.
    config.donate_buffers = False
    chain2 = SquareChain(2)
    staged2 = layout.put(X)
    chain2._staged_call(staged2, layout)
    np.testing.assert_array_equal(np.asarray(staged2), X)


def test_caller_owned_buffers_never_donated():
    """Host batches and mesh-placed jax.Arrays are caller-owned (either
    can be multi-consumer via gather / the by-hash memo): the chain must
    leave both readable after the call."""
    config.shard_data_batches = True
    config.donate_buffers = True
    chain = SquareChain(1)
    X = np.random.default_rng(0).normal(size=(128, 32)).astype(np.float32)
    out_host = np.asarray(chain.batch_call(X))
    np.testing.assert_array_equal(X, X)  # host input untouched
    layout = batch_layout(X)
    placed = layout.put(X)
    before = sharding_counters.snapshot().get("buffers_donated", 0)
    out_dev = np.asarray(chain.batch_call(placed))
    # The placed input went through the caller-owned branch: readable
    # afterwards, and no donation was even decided for it.
    np.testing.assert_array_equal(np.asarray(placed), X)
    after = sharding_counters.snapshot().get("buffers_donated", 0)
    assert after == before
    assert out_host.tobytes() == out_dev.tobytes()


def test_shrinking_chain_refuses_donation_counted():
    """No output aval matches the staged input → donation is refused up
    front (XLA would warn and no-op), counted, and the result is still
    bit-identical to the plain walk."""
    config.shard_data_batches = True
    config.donate_buffers = True
    chain = ShrinkChain(5)
    X = np.random.default_rng(1).normal(size=(128, 32)).astype(np.float32)
    layout = batch_layout(X)
    assert not chain._donation_eligible(layout.put(X), layout)
    out = np.asarray(chain.batch_call(X))
    c = sharding_counters.snapshot()
    assert c.get("donation_refused", 0) >= 1
    assert c.get("buffers_donated", 0) == 0
    ref = np.asarray(jax.jit(chain.apply_batch)(X))
    assert out.tobytes() == ref.tobytes()


# ---------------------------------------------------------------------------
# The memory win: donated working set strictly below undonated
# ---------------------------------------------------------------------------


def test_donated_working_set_strictly_below_undonated():
    """Per-node resource attribution (PR-8 ``memory_analysis``): the
    donated lowering aliases the staged argument into the output, so
    its working set (argument + output + temp − alias) sits strictly
    below the undonated lowering's. The proof chain is elementwise so
    the alias is the whole story on every backend (a matmul chain needs
    the same scratch either way on CPU and the two working sets tie).
    On real hardware `make bench-imagenet` additionally gates live peak
    HBM; this is the backend-portable form of the same evidence."""

    class ElemChain(Transformer):
        def signature(self):
            return self.stable_signature()

        def apply_batch(self, X):
            return jnp.tanh(X) * 2.0 + 0.5

    config.shard_data_batches = True
    config.donate_buffers = True
    chain = ElemChain()
    X = np.random.default_rng(2).normal(size=(128, 32)).astype(np.float32)
    layout = batch_layout(X)
    staged = layout.put(X)

    def working_set(donate: bool) -> float:
        fn = chain._jitted_sharded(layout, donate=donate)
        mem = _memory_analysis(fn.lower(staged).compile())
        alias = mem.get("alias_bytes", 0.0)
        if donate:
            assert alias > 0.0  # the argument really aliases
        else:
            assert alias == 0.0
        return (
            mem.get("argument_bytes", 0.0)
            + mem.get("output_bytes", 0.0)
            + mem.get("temp_bytes", 0.0)
            - alias
        )

    assert working_set(True) < working_set(False)


# ---------------------------------------------------------------------------
# The knob
# ---------------------------------------------------------------------------


def test_donate_buffers_env_knob_resolution(monkeypatch):
    for raw, expect in (
        ("0", False), ("false", False), ("no", False), ("FALSE", False),
        ("", True), ("1", True), ("yes", True), ("on", True),
    ):
        if raw:
            monkeypatch.setenv("KEYSTONE_DONATE_BUFFERS", raw)
        else:
            monkeypatch.delenv("KEYSTONE_DONATE_BUFFERS", raising=False)
        assert Config().donate_buffers is expect, raw


# ---------------------------------------------------------------------------
# Pallas Fisher vectors on the sharded path
# ---------------------------------------------------------------------------


def _gmm(k: int = 4, d: int = 8):
    rng = np.random.default_rng(9)
    w = rng.uniform(0.5, 1.5, size=k).astype(np.float32)
    w /= w.sum()
    mu = rng.normal(size=(k, d)).astype(np.float32)
    var = (0.5 + rng.uniform(size=(k, d))).astype(np.float32)
    return w, mu, var


def test_pallas_fv_sharded_bit_identical_and_counted():
    """The Pallas Fisher-vector chain on the sharded path matches the
    single-device jitted walk byte for byte, and its activity is
    counter-verified (``pallas_sharded_calls``) — the bench's
    zero-silent-fallback evidence at test scale."""
    from keystone_tpu.nodes.images.external.fisher_vector import FisherVector

    config.shard_data_batches = True
    config.donate_buffers = True
    w, mu, var = _gmm()
    fv = FisherVector(w, mu, var, backend="pallas")
    assert fv.uses_pallas
    rng = np.random.default_rng(3)
    X = rng.normal(size=(128, 16, 8)).astype(np.float32)
    sharded = np.asarray(fv.batch_call(X))
    c = sharding_counters.snapshot()
    assert c.get("pallas_sharded_calls", 0) >= 1
    assert c.get("sharded_chain_calls", 0) >= 1
    plain = np.asarray(jax.jit(fv.apply_batch)(X))
    assert sharded.tobytes() == plain.tobytes()
    # FV widens (B, m, d) → (B, 2kd): its donation is refused, counted.
    assert c.get("donation_refused", 0) >= 1


# ---------------------------------------------------------------------------
# KG106: fused sharded fit lowering without donation
# ---------------------------------------------------------------------------


def _placed_fit_pipeline(rows: int = 128):
    """Divisible dataset (the "shard" class: DatasetOperator places it
    onto the mesh) feeding an estimator through a jittable chain — the
    fused fit's input arrives caller-owned, so its lowering cannot
    donate."""
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator

    rng = np.random.default_rng(11)
    X = rng.normal(size=(rows, 32)).astype(np.float32)
    y = rng.normal(size=(rows, 4)).astype(np.float32)
    return SquareChain(6).and_then(
        BlockLeastSquaresEstimator(block_size=64, num_iters=1, lam=1e-3),
        X, y,
    )


def test_kg106_flags_undonated_placed_fit_chain():
    config.shard_data_batches = True
    config.donate_buffers = True
    hits = _placed_fit_pipeline().lint().by_rule("KG106")
    assert hits and all(d.severity == "warning" for d in hits)
    assert "WITHOUT donation" in hits[0].message
    assert "KEYSTONE_DONATE_BUFFERS=0" in hits[0].hint


def test_kg106_silent_when_donation_off_or_chain_not_jittable():
    config.shard_data_batches = True
    config.donate_buffers = False
    assert not _placed_fit_pipeline().lint().by_rule("KG106")

    config.donate_buffers = True
    from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator

    rng = np.random.default_rng(11)
    X = rng.normal(size=(128, 32)).astype(np.float32)
    y = rng.normal(size=(128, 4)).astype(np.float32)
    # No jittable stage between dataset and estimator: RowMatrix stages
    # the solve itself, nothing lowers a fused undonated chain.
    host_only = HostPass().and_then(
        BlockLeastSquaresEstimator(block_size=64, num_iters=1, lam=1e-3),
        X, y,
    )
    assert not host_only.lint().by_rule("KG106")


def test_kg106_silent_on_pad_class_rows():
    """Non-divisible rows are the "pad" class: the chain call stages its
    own mask-padded copy and donates it — KG103's territory, not
    KG106's."""
    config.shard_data_batches = True
    config.donate_buffers = True
    report = _placed_fit_pipeline(rows=130).lint()
    assert not report.by_rule("KG106")
    assert report.by_rule("KG103")  # still flagged, as the pad cliff


def test_kg106_in_catalog():
    from keystone_tpu.workflow.analysis import GRAPH_RULES

    assert "KG106" in GRAPH_RULES
