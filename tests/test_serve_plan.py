"""Memory-bounded serving: HBM-planned bucket ladders + the serving
precision ladder with quality gates (ROADMAP item 4).

Pinned here, both ways each:

1. ``rules.plan_serve_ladder`` — rungs kept smallest-first under the
   budget, trims top-down (the top bucket caps), the smallest rung never
   trims, every trim is a counted ``serve_plan`` registry decision plus
   an optimizer decision-ring entry (never silent);
2. engine warmup planning — an UNPINNED (pow-2 default) ladder auto-sizes
   against the HBM budget at warmup; explicit ``buckets=``, a
   live-exported KEYSTONE_SERVE_BUCKETS, and ``config.plan_resources =
   False`` all pin the ladder untouched; measured-profile provenance
   beats the abstract AOT estimate;
3. the oversize-batch sharding path under a planner-TRIMMED ladder:
   chunks land on the shared rung, outputs BIT-identical to the same
   batch served on the hand-picked ladder, zero silent fallbacks
   (counter-verified: every call on a ladder bucket, zero post-warmup
   compiles);
4. the precision ladder — ``f32`` is the legacy path ITSELF (the serve
   fn is ``apply_batch``, identity-pinned), ``f32h`` is bit-identical on
   CPU, ``bf16`` differs-but-tracks, and the per-pipeline quality gate
   (``qualify``/``check_precision_quality``) passes a trained head and
   REFUSES with a typed error naming the metric and delta;
5. the prefetch-depth satellite — env pin (incl. explicit 0) > session
   plan clamp > config, and ``PlanResourcesRule`` clamps from measured
   per-batch bytes with a logged decision;
6. the plan/precision observability surface — engine + service stats and
   the daemon ``/stats`` endpoint;
7. the ``bench_serve --precision`` harness in-process: every hard gate
   green at a reduced size.
"""

import importlib.util
import os
import sys
import threading

import numpy as np
import pytest

from keystone_tpu.config import config
from keystone_tpu.utils.metrics import (
    metrics_registry,
    serve_plan_counters,
    serving_counters,
)
from keystone_tpu.workflow import rules
from keystone_tpu.workflow.executor import PipelineEnv
from keystone_tpu.workflow.serving import (
    PRECISION_QUALITY_TOLERANCES,
    CompiledPipeline,
    PipelineService,
    PrecisionQualityError,
    check_precision_quality,
    ladder_is_pinned,
    precision_quality_delta,
    resolve_ladder,
)


@pytest.fixture(autouse=True)
def _restore_knobs():
    prior = (
        config.hbm_budget_bytes,
        config.plan_resources,
        config.serve_precision,
        config.serve_buckets,
        config.prefetch_depth,
    )
    yield
    (
        config.hbm_budget_bytes,
        config.plan_resources,
        config.serve_precision,
        config.serve_buckets,
        config.prefetch_depth,
    ) = prior


def _head(d=8, D=16, k=3, seed=0):
    """The canonical fused serving head (test_serving.py shape)."""
    from keystone_tpu.nodes.learning.linear_mapper import LinearMapper
    from keystone_tpu.nodes.stats.hellinger import SignedHellingerMapper
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.nodes.stats.random_features import CosineRandomFeatures
    from keystone_tpu.nodes.stats.scalers import StandardScalerModel
    from keystone_tpu.workflow.pipeline import FusedTransformer

    rng = np.random.default_rng(seed)
    return FusedTransformer([
        StandardScalerModel(
            rng.normal(size=d).astype(np.float32),
            (1.0 + rng.uniform(size=d)).astype(np.float32),
        ),
        CosineRandomFeatures.create(d, D, seed=seed),
        SignedHellingerMapper(),
        L2Normalizer(),
        LinearMapper(rng.normal(size=(D, k)).astype(np.float32)),
    ])


def _counters():
    return dict(serve_plan_counters.snapshot())


def _delta(before, key):
    return _counters().get(key, 0) - before.get(key, 0)


# ---------------------------------------------------------------------------
# plan_serve_ladder: the pure planner
# ---------------------------------------------------------------------------


def test_plan_serve_ladder_trims_top_down_under_budget():
    rules.clear_decisions()
    before = _counters()
    # 100 B/row x 2 replicas: rungs cost 200/400/800/1600/3200; a 1500 B
    # budget keeps (1, 2, 4) = 1400 and trims 8, 16.
    kept, trimmed, info = rules.plan_serve_ladder(
        (1, 2, 4, 8, 16), 100.0, 2, budget_bytes=1500,
        provenance="measured", node="t",
    )
    assert kept == (1, 2, 4)
    assert trimmed == [8, 16]
    assert info["planned_bytes"] == 1400
    assert info["headroom_bytes"] == 100
    assert info["per_bucket_bytes"] == {1: 200, 2: 400, 4: 800}
    assert not info["over_budget"]
    assert _delta(before, "buckets_trimmed") == 2
    assert _delta(before, "top_bucket_capped") == 1
    assert _delta(before, "ladders_planned") == 1
    decisions = [d for d in rules.optimizer_decisions()
                 if d.rule == "PlanServeLadder"]
    trims = [d for d in decisions if d.action.startswith("trim-bucket=")]
    assert {d.action for d in trims} == {"trim-bucket=8", "trim-bucket=16"}
    assert all(d.provenance == "measured" for d in trims)
    (summary,) = [d for d in decisions
                  if d.action == "serve_buckets=1,2,4"]
    assert "2 rung(s) trimmed" in summary.reason


def test_plan_serve_ladder_never_trims_the_last_rung():
    before = _counters()
    kept, trimmed, info = rules.plan_serve_ladder(
        (4, 8), 1000.0, 1, budget_bytes=1,
    )
    assert kept == (4,)  # serving must stay possible
    assert trimmed == [8]
    assert info["over_budget"]
    assert _delta(before, "plans_over_budget") == 1


# ---------------------------------------------------------------------------
# Engine warmup planning: unpinned sized, pinned untouched
# ---------------------------------------------------------------------------


def test_warmup_plans_unpinned_ladder_against_budget():
    before = _counters()
    # The abstract AOT estimate prices this head at a few KB/row; a tiny
    # budget must cap the pow-2 ladder below its top.
    config.hbm_budget_bytes = 4096
    cp = CompiledPipeline(_head(), max_batch=64, devices=1, name="sp-t1")
    assert cp.ladder == (1, 2, 4, 8, 16, 32, 64)  # planned at WARMUP
    cp.warmup((8,))
    plan = cp.stats()["plan"]
    assert plan["enabled"] and plan["provenance"] == "model"
    assert plan["trimmed"], "tiny budget must trim rungs"
    assert cp.ladder[-1] < 64 and cp.max_batch == cp.ladder[-1]
    assert plan["planned_bytes"] <= plan["budget_bytes"]
    assert set(map(int, plan["per_bucket_bytes"])) == set(cp.ladder)
    assert _delta(before, "buckets_trimmed") == len(plan["trimmed"])
    # Serving still works end to end on the trimmed ladder.
    out = cp(np.ones((5, 8), np.float32))
    assert out.shape == (5, 3)


def test_ample_budget_keeps_every_rung():
    cp = CompiledPipeline(_head(), max_batch=16, devices=1, name="sp-t2")
    cp.warmup((8,))
    plan = cp.stats()["plan"]
    assert plan["enabled"] and plan["trimmed"] == []
    assert cp.ladder == (1, 2, 4, 8, 16)


def test_explicit_buckets_pin_the_ladder():
    before = _counters()
    config.hbm_budget_bytes = 1
    cp = CompiledPipeline(
        _head(), buckets=[8, 64], devices=1, name="sp-t3"
    ).warmup((8,))
    assert cp.ladder == (8, 64)  # untouched under an impossible budget
    assert cp.stats()["plan"] == {"enabled": False,
                                  "reason": "ladder pinned"}
    assert _delta(before, "ladders_pinned") == 1
    assert _delta(before, "buckets_trimmed") == 0


def test_env_exported_buckets_pin_the_ladder(monkeypatch):
    monkeypatch.setenv("KEYSTONE_SERVE_BUCKETS", "4,32")
    assert resolve_ladder() == (4, 32)
    assert ladder_is_pinned()
    config.hbm_budget_bytes = 1
    cp = CompiledPipeline(_head(), devices=1, name="sp-t4").warmup((8,))
    assert cp.ladder == (4, 32)
    assert cp.stats()["plan"]["reason"] == "ladder pinned"


def test_plan_resources_off_skips_planning():
    config.plan_resources = False
    config.hbm_budget_bytes = 1
    cp = CompiledPipeline(
        _head(), max_batch=16, devices=1, name="sp-t5"
    ).warmup((8,))
    assert cp.ladder == (1, 2, 4, 8, 16)
    assert cp.stats()["plan"]["reason"] == "config.plan_resources off"


def test_measured_profile_prices_the_plan(monkeypatch):
    """A stored measured profile beats the abstract estimate: the plan's
    provenance is 'measured' and its bytes/row is the profile's summed
    activation bytes per row."""
    from keystone_tpu.workflow import profile_store, serving
    from keystone_tpu.workflow.pipeline import Pipeline

    fake = profile_store.StoredProfile(
        pipeline_digest="d", fingerprint={},
        digests={
            "a": {"out_rows": 10, "out_bytes": 1000},   # 100 B/row
            "b": {"out_rows": 10, "out_bytes": 280},    # 28 B/row
            "c": {"out_rows": 0, "out_bytes": 999},     # unusable: skipped
        },
    )
    monkeypatch.setattr(
        profile_store, "lookup_measured", lambda digest: fake
    )
    monkeypatch.setattr(
        profile_store, "pipeline_profile_digest", lambda g, s: "d"
    )
    pipe = _head().to_pipeline()
    assert isinstance(pipe, Pipeline)
    cp = CompiledPipeline(pipe, max_batch=8, devices=1, name="sp-t6")
    assert cp._measured_bpr == 128.0
    cp.warmup((8,))
    plan = cp.stats()["plan"]
    assert plan["provenance"] == "measured"
    assert plan["bytes_per_row"] == 128.0


def test_replan_on_new_traffic_signature():
    """A re-warm at a new signature re-prices from the ORIGINAL candidate
    rungs (a trimmed ladder must not monotonically shrink across
    signatures)."""
    config.hbm_budget_bytes = 4096
    cp = CompiledPipeline(_head(d=8), max_batch=64, devices=1,
                          name="sp-t7").warmup((8,))
    trimmed_first = list(cp.stats()["plan"]["trimmed"])
    assert trimmed_first
    config.hbm_budget_bytes = 12 * (1 << 30)
    cp.warmup((8,), dtype=np.float16)  # a genuinely new signature
    assert cp.ladder == (1, 2, 4, 8, 16, 32, 64)
    assert cp.stats()["plan"]["trimmed"] == []


# ---------------------------------------------------------------------------
# Oversize sharding under a trimmed ladder: bit-identity, no fallbacks
# ---------------------------------------------------------------------------


def test_oversize_batch_on_trimmed_ladder_bit_identical(rng):
    """The satellite gate: a planner-trimmed ladder serves an oversize
    batch bit-identically to the hand-picked ladder — the chunks land on
    the shared top rung — with zero silent fallbacks (every call a
    ladder bucket, zero post-warmup compiles), including through the
    replica-pool sharding path."""
    d = 8
    # Price so the pow-2-to-64 ladder trims to top out at 8: the head
    # prices ~600 B/row abstractly; rungs 1+2+4+8 cost ~9KB.
    config.hbm_budget_bytes = 2 * 10000
    trimmed = CompiledPipeline(
        _head(d=d), max_batch=64, devices=2, name="sp-o1"
    ).warmup((d,))
    assert trimmed.ladder[-1] == 8, trimmed.stats()["plan"]
    handpicked = CompiledPipeline(
        _head(d=d), buckets=[8], devices=1, name="sp-o2"
    ).warmup((d,))
    compiles_before = (trimmed.compile_count, handpicked.compile_count)
    serving_before = serving_counters.snapshot()
    for n in (3, 8, 16, 48):  # in-ladder and oversize (chunked) batches
        X = rng.normal(size=(n, d)).astype(np.float32)
        a, b = trimmed(X), handpicked(X)
        assert np.array_equal(a, b), n
    # Oversize chunks spread over the pool (the sharding path ran).
    dispatches = trimmed.stats()["replica_dispatches"]
    assert sum(1 for v in dispatches.values() if v > 0) == 2
    # Counter-verified no silent fallback: zero new compiles (nothing
    # served off-ladder or re-traced), and every recorded call landed on
    # a bucket of the trimmed ladder.
    assert (trimmed.compile_count, handpicked.compile_count) \
        == compiles_before
    hits_before = serving_before["bucket_hits"]
    new_hits = {
        b: n - hits_before.get(b, 0)
        for b, n in serving_counters.snapshot()["bucket_hits"].items()
        if n - hits_before.get(b, 0) > 0
    }
    # ...and on nothing outside the two engines' ladders: the oversize
    # chunks all rode the shared top rung (8), the in-ladder batch its
    # own rung — no per-shape escape hatch served anything.
    assert set(new_hits) <= set(trimmed.ladder) | set(handpicked.ladder)
    assert 8 in new_hits


# ---------------------------------------------------------------------------
# Precision ladder
# ---------------------------------------------------------------------------


def test_f32_serve_fn_is_apply_batch_itself():
    """The knob-off contract by construction: at f32 the compiled fn IS
    the transformer's apply_batch — no wrapper, no cast, byte-for-byte
    the pre-precision-ladder path. The default mode is f32."""
    head = _head()
    cp = CompiledPipeline(head, max_batch=8, devices=1, name="sp-p1")
    assert cp.precision == "f32"
    assert cp._serve_fn() == head.apply_batch  # the same bound method
    cp32 = CompiledPipeline(head, max_batch=8, devices=1, name="sp-p2",
                            precision="f32")
    assert cp32._serve_fn() == head.apply_batch


def test_config_knob_selects_engine_default():
    config.serve_precision = "bf16"
    cp = CompiledPipeline(_head(), max_batch=8, devices=1, name="sp-p3")
    assert cp.precision == "bf16"


def test_invalid_precision_refused():
    with pytest.raises(ValueError, match="serve precision"):
        CompiledPipeline(_head(), max_batch=8, devices=1,
                         precision="fp8", name="sp-p4")


def test_bf16_differs_but_tracks_and_stays_f32_out(rng):
    d = 8
    X = rng.normal(size=(5, d)).astype(np.float32)
    f32 = CompiledPipeline(_head(d=d), max_batch=8, devices=1,
                           name="sp-p5").warmup((d,))
    b16 = CompiledPipeline(_head(d=d), max_batch=8, devices=1,
                           precision="bf16", name="sp-p6").warmup((d,))
    of, ob = f32(X), b16(X)
    assert ob.dtype == np.float32  # boundary cast back
    assert not np.array_equal(of, ob)  # the knob really engages
    denom = max(np.abs(of).max(), 1e-6)
    assert np.abs(of - ob).max() / denom < 3e-2  # bf16-rounding scale


def test_f32h_bit_identical_on_cpu(rng):
    """Matmul precision HIGH only changes TPU gemm pass counts; on the
    CPU backend the mode must be a numeric no-op (the bench's
    fingerprint-gated expectation)."""
    import jax

    if jax.default_backend() != "cpu":
        pytest.skip("CPU-only expectation")
    d = 8
    X = rng.normal(size=(5, d)).astype(np.float32)
    f32 = CompiledPipeline(_head(d=d), max_batch=8, devices=1,
                           name="sp-p7").warmup((d,))
    h = CompiledPipeline(_head(d=d), max_batch=8, devices=1,
                         precision="f32h", name="sp-p8").warmup((d,))
    assert np.array_equal(f32(X), h(X))


# ---------------------------------------------------------------------------
# Quality gates
# ---------------------------------------------------------------------------


def _trained_head(d=16, features=64, classes=4, seed=0,
                  n_train=512, n_eval=256):
    """A head whose linear map is least-squares trained on separable
    synthetic classes — argmax margins far above quantization noise, the
    scenario a precision ladder actually serves."""
    from keystone_tpu.nodes.learning.linear_mapper import LinearMapper
    from keystone_tpu.workflow.pipeline import FusedTransformer

    base = _head(d=d, D=features, k=classes, seed=seed)
    prefix = FusedTransformer(base.stages[:-1])
    rng = np.random.default_rng(seed + 1)
    centroids = rng.normal(size=(classes, d)).astype(np.float32) * 2.0
    y = rng.integers(0, classes, n_train)
    X = (centroids[y] + 0.3 * rng.normal(size=(n_train, d))).astype(
        np.float32
    )
    F = np.asarray(prefix.batch_call(X))
    W, *_ = np.linalg.lstsq(
        F, np.eye(classes, dtype=np.float32)[y], rcond=None
    )
    chain = FusedTransformer(
        base.stages[:-1] + [LinearMapper(W.astype(np.float32))]
    )
    ye = rng.integers(0, classes, n_eval)
    Xe = (centroids[ye] + 0.3 * rng.normal(size=(n_eval, d))).astype(
        np.float32
    )
    return chain, Xe, ye


def test_qualify_passes_trained_head_within_declared_tolerance():
    chain, Xe, ye = _trained_head()
    cp = CompiledPipeline(chain, max_batch=256, devices=1,
                          precision="bf16", name="sp-q1")
    report = cp.qualify(Xe, y=ye, metric="multiclass")
    assert report["within_tolerance"]
    assert report["tolerance"] == PRECISION_QUALITY_TOLERANCES["multiclass"]
    assert report["quality_delta"] <= report["tolerance"]
    assert report["metric"] == "multiclass_accuracy"


def test_qualify_refuses_naming_metric_and_delta(rng):
    """The knob must REFUSE, typed, naming the metric and the measured
    delta — a random (margin-free) head at zero tolerance reliably
    breaches."""
    cp = CompiledPipeline(_head(d=16, D=64, k=4), max_batch=64, devices=1,
                          precision="bf16", name="sp-q2")
    X = rng.normal(size=(64, 16)).astype(np.float32)
    with pytest.raises(PrecisionQualityError,
                       match=r"multiclass_accuracy dropped 0\.\d+"):
        cp.qualify(X, tolerance=0.0)
    try:
        cp.qualify(X, tolerance=0.0)
    except PrecisionQualityError as e:
        assert "serve_precision=bf16" in str(e)
        assert "tolerance" in str(e)


def test_qualify_f32_is_the_identity_gate(rng):
    cp = CompiledPipeline(_head(), max_batch=8, devices=1,
                          name="sp-q3").warmup((8,))
    report = cp.qualify(rng.normal(size=(5, 8)).astype(np.float32),
                        tolerance=0.0)
    assert report["quality_delta"] == 0.0 and report["within_tolerance"]


def test_check_precision_quality_binary_and_map(rng):
    scores = rng.normal(size=(200, 4)).astype(np.float32)
    # binary, no labels: oracle's own thresholded predictions are the
    # reference; one flipped sign near zero = a measurable delta.
    degraded = scores.copy()
    flip = np.argsort(np.abs(scores[:, 0]))[:10]
    degraded[flip, 0] = -scores[flip, 0]
    name, delta, ref, got = precision_quality_delta(
        scores, degraded, metric="binary"
    )
    assert name == "binary_accuracy" and ref == 1.0
    assert abs(delta - 10 / 200) < 1e-9
    # map needs multilabel ground truth
    y = (rng.uniform(size=(200, 4)) < 0.3)
    rep = check_precision_quality(
        scores, scores, y=y, metric="map", tolerance=0.0,
        precision="bf16",
    )
    assert rep["metric"] == "map" and rep["quality_delta"] == 0.0
    with pytest.raises(ValueError, match="multilabel"):
        check_precision_quality(scores, scores, metric="map")
    with pytest.raises(ValueError, match="unknown quality metric"):
        check_precision_quality(scores, scores, metric="psnr")


# ---------------------------------------------------------------------------
# Prefetch depth: env pin > session plan clamp > config
# ---------------------------------------------------------------------------


def test_prefetch_depth_resolution_order(monkeypatch):
    from keystone_tpu.loaders.stream import (
        prefetch_batches,
        resolved_prefetch_depth_value,
    )

    config.prefetch_depth = 3
    assert resolved_prefetch_depth_value(None) == 3       # config default
    assert resolved_prefetch_depth_value(7) == 7          # explicit arg
    PipelineEnv.get().resource_plan["prefetch_depth"] = 1
    assert resolved_prefetch_depth_value(None) == 1       # plan clamps
    PipelineEnv.get().resource_plan["prefetch_depth"] = 9
    assert resolved_prefetch_depth_value(None) == 3       # only DOWN
    monkeypatch.setenv("KEYSTONE_PREFETCH_DEPTH", "5")
    assert resolved_prefetch_depth_value(None) == 5       # env beats plan
    monkeypatch.setenv("KEYSTONE_PREFETCH_DEPTH", "0")
    assert resolved_prefetch_depth_value(None) == 0       # explicit 0 pin
    src = [1, 2, 3]
    assert prefetch_batches(src) is src  # 0 = synchronous passthrough


def test_plan_prefetch_depth_clamps_from_measured_bytes(monkeypatch):
    """The rule satellite: measured per-batch bytes vs the budget share
    turns the hand-picked depth into a clamp, decision-logged."""
    import keystone_tpu.utils.metrics as metrics_mod
    from keystone_tpu.nodes.learning import LinearMapEstimator
    from keystone_tpu.workflow.graph import structural_digest
    from keystone_tpu.workflow.profile_store import StoredProfile

    X = np.ones((64, 32), np.float32)
    Y = np.ones((64, 4), np.float32)
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer

    p = L2Normalizer().and_then(LinearMapEstimator(lam=1e-3), X, Y)
    # Feed the rule a measured profile for the estimator's input node:
    # 64 rows x 8192 B = 128 B/row, one call = 8192 B/batch.
    from keystone_tpu.workflow.operators import EstimatorOperator

    est_nid = next(
        nid for nid, op in p.graph.operators.items()
        if isinstance(op, EstimatorOperator)
    )
    dep0 = p.graph.dependencies[est_nid][0]
    digest = structural_digest(p.graph, dep0)
    # out_rows/out_bytes are LAST-WRITE per-call sizes (the store
    # contract) — calls=10 must price identically to calls=1, never
    # divide the per-batch rows by the accumulated call count.
    measured = StoredProfile(
        pipeline_digest="d", fingerprint={},
        digests={digest: {"out_rows": 64, "out_bytes": 8192, "calls": 10}},
    )
    # Budget share 16384 B -> 2 batches fit; hand-picked depth 4 clamps.
    monkeypatch.setattr(metrics_mod, "device_hbm_bytes",
                        lambda: 16384 * rules.PlanResourcesRule
                        .PREFETCH_BUDGET_FRAC)
    config.prefetch_depth = 4
    rules.clear_decisions()
    before = _counters()
    plan: dict = {}
    rules.PlanResourcesRule()._plan_prefetch_depth(
        p.graph, [p.sink], measured, plan
    )
    assert plan["prefetch_depth"] == 2
    assert _delta(before, "prefetch_clamped") == 1
    (d,) = [d for d in rules.optimizer_decisions()
            if d.action == "prefetch_depth=2"]
    assert d.provenance == "measured" and "clamped" in d.reason
    # In-budget: the hand-picked depth stands, decision says so.
    config.prefetch_depth = 2
    plan2: dict = {}
    rules.clear_decisions()
    rules.PlanResourcesRule()._plan_prefetch_depth(
        p.graph, [p.sink], measured, plan2
    )
    assert "prefetch_depth" not in plan2
    (keep,) = [d for d in rules.optimizer_decisions()
               if d.action.startswith("prefetch_depth=")]
    assert "fits" in keep.reason


# ---------------------------------------------------------------------------
# Observability surface
# ---------------------------------------------------------------------------


def test_service_stats_expose_plan_and_precision():
    config.hbm_budget_bytes = 4096
    cp = CompiledPipeline(_head(), max_batch=64, devices=1,
                          precision="bf16", name="sp-s1").warmup((8,))
    with PipelineService(cp, max_delay_ms=0.5, name="sp-s1-svc") as svc:
        stats = svc.stats()["compiled"]
    assert stats["precision"] == "bf16"
    assert stats["plan"]["enabled"] and stats["plan"]["trimmed"]
    assert stats["ladder"] == list(cp.ladder)


def test_daemon_stats_expose_serve_plan(tmp_path):
    """Operators see the planner's choices on the wire: the daemon's
    /stats carries resolved ladder, precision, and the plan dict."""
    import json
    import urllib.request

    from keystone_tpu.workflow.daemon import ServingDaemon
    from keystone_tpu.workflow.serialization import save_artifact

    d = 8
    pipe = _head(d=d).to_pipeline().fit()
    art = os.path.join(tmp_path, "m.kart")
    save_artifact(pipe, art, feature_shape=(d,), dtype="float32")
    with ServingDaemon(artifact=art, devices=1, buckets=(4,),
                       name="sp-daemon") as daemon:
        sp = daemon.stats()["serve_plan"]
        assert sp["ladder"] == [4]
        assert sp["precision"] == "f32"
        assert sp["plan"] == {"enabled": False, "reason": "ladder pinned"}
        with urllib.request.urlopen(
            f"http://127.0.0.1:{daemon.http_port}/stats", timeout=10
        ) as resp:
            doc = json.loads(resp.read())
        assert doc["serve_plan"] == sp


# ---------------------------------------------------------------------------
# The bench harness, in-process
# ---------------------------------------------------------------------------


def _tools(name):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "tools", f"{name}.py")
    sys.path.insert(0, os.path.join(repo, "tools"))
    try:
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    finally:
        sys.path.pop(0)


def test_bench_serve_precision_harness_green():
    """Every hard gate of `make bench-serve-precision` at a reduced size:
    wall AND p99 beat the hand-picked baseline, knob-off bit-identity,
    ladder-change within float noise, quality within tolerance, planner
    ran, zero post-warmup compiles."""
    import argparse

    bench = _tools("bench_serve")
    args = argparse.Namespace(
        requests=24, max_batch=32, d=16, features=128, classes=4, seed=0,
        provisioned_max=256, quality_tolerance=None,
    )
    result = bench.run_precision_bench(args)
    assert result["ok"], result["pass"]
    assert result["handpicked_ladder"] == [256]
    assert result["plan"]["enabled"]
    assert result["quality"]["within_tolerance"]
    assert result["speedup"]["throughput"] >= 1.5
    assert result["speedup"]["p99"] >= 1.5
