"""Observability layer: tracing, histograms, and the unified registry.

What is pinned here:

1. ``LatencyHistogram`` percentiles agree with numpy on known samples
   (log-bucket quantization stays inside the documented ~4.4%/bucket),
   and the histogram survives concurrent recording;
2. ``Tracer`` spans nest (parent attribution + time containment), the
   ring buffer is bounded, and recording is thread-safe under the
   serving micro-batcher's worker + concurrent clients;
3. the disabled tracer is INERT: a traced-path fit with
   ``KEYSTONE_TRACE`` unset is bit-identical to the enabled-tracer run
   (the same enabled-but-silent discipline as test_reliability.py);
4. ``Tracer.export`` emits schema-valid Chrome-trace JSON (the shared
   ``validate_chrome_trace`` oracle also rejects malformed documents);
5. ``MetricsRegistry`` unifies counters/histograms/gauges under one
   snapshot/reset, per-bucket compile counts name which bucket compiled,
   and the registry's serving percentiles agree with an external
   stopwatch over the same requests;
6. the ``make trace-demo`` flow (tools/trace_demo.py) runs fast and
   covers every instrumented surface — the tier-1 stand-in for the
   Makefile target.
"""

import importlib.util
import json
import os
import threading

import numpy as np
import pytest

from keystone_tpu.config import config
from keystone_tpu.utils.metrics import (
    Gauge,
    LatencyHistogram,
    Tracer,
    active_tracer,
    metrics_registry,
    reset_tracer,
    serving_counters,
    validate_chrome_trace,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def traced():
    """Arm process-wide tracing for the test; restores the prior knob and
    drops the cached tracer afterwards (mirror of test_reliability's
    ``faults`` fixture)."""
    prior = config.trace

    def arm(on: bool = True):
        config.trace = on
        reset_tracer()
        return active_tracer()

    try:
        yield arm
    finally:
        config.trace = prior
        reset_tracer()


# ---------------------------------------------------------------------------
# LatencyHistogram
# ---------------------------------------------------------------------------


def _nearest_rank(samples, p):
    s = np.sort(np.asarray(samples))
    return float(s[max(0, int(np.ceil(len(s) * p / 100.0)) - 1)])


@pytest.mark.parametrize("dist", ["lognormal", "uniform", "bimodal"])
def test_histogram_percentiles_match_numpy(dist):
    rng = np.random.default_rng(7)
    if dist == "lognormal":
        vals = rng.lognormal(mean=-5.0, sigma=1.2, size=4000)
    elif dist == "uniform":
        vals = rng.uniform(1e-4, 5e-2, size=4000)
    else:
        vals = np.concatenate(
            [rng.normal(2e-3, 1e-4, 2000), rng.normal(8e-2, 5e-3, 2000)]
        ).clip(min=1e-6)
    h = LatencyHistogram()
    for v in vals:
        h.record(float(v))
    for p in (50, 90, 95, 99):
        est = h.percentile(p)
        ref = _nearest_rank(vals, p)
        # One log bucket is 2**(1/16) ~ 4.4% wide; the representative
        # value sits mid-bucket, so <= ~2.2% + rank discreteness.
        assert abs(est - ref) / ref < 0.05, (p, est, ref)
    snap = h.snapshot()
    assert snap["count"] == 4000
    assert snap["min_ms"] == pytest.approx(float(vals.min()) * 1e3, rel=1e-3)
    assert snap["max_ms"] == pytest.approx(float(vals.max()) * 1e3, rel=1e-3)
    # snapshot rounds to 4 decimals of a millisecond (0.1 µs)
    assert snap["mean_ms"] == pytest.approx(float(vals.mean()) * 1e3, rel=1e-3)


def test_histogram_extremes_clamp_not_crash():
    h = LatencyHistogram()
    h.record(0.0)           # below the first bucket
    h.record(-1.0)          # negative clock skew: clamped to 0
    h.record(1e6)           # beyond the top bucket
    assert h.count == 3
    assert h.percentile(50) is not None
    assert h.snapshot()["max_ms"] == pytest.approx(1e9)


def test_histogram_nonpositive_samples_clamped_and_counted():
    """The satellite guard: non-positive samples never reach the log
    math — they clamp to the minimum bucket and show up as a
    ``dropped_nonpositive`` count in the snapshot, so a clock that
    misbehaves is visible instead of silently skewing the low tail."""
    h = LatencyHistogram()
    h.record(-3.0)
    h.record(0.0)
    h.record(0.01)
    snap = h.snapshot()
    assert snap["count"] == 3
    assert snap["dropped_nonpositive"] == 2
    assert snap["min_ms"] == pytest.approx(1e-3)  # clamped to min bucket
    assert h.percentile(50) is not None
    dist = h.buckets()
    assert dist["dropped_nonpositive"] == 2
    assert dist["buckets"][0][0] == pytest.approx(1e-6)
    assert dist["buckets"][-1][1] == 3  # cumulative reaches the count
    h.reset()
    assert "dropped_nonpositive" not in h.snapshot()  # zero = absent


def test_histogram_empty_and_reset():
    h = LatencyHistogram()
    assert h.percentile(99) is None
    assert h.snapshot() == {"count": 0}
    h.record(0.01)
    assert h.count == 1
    h.reset()
    assert h.snapshot() == {"count": 0}


def test_histogram_concurrent_recording():
    h = LatencyHistogram()
    n_threads, per = 8, 2000

    def work(seed):
        r = np.random.default_rng(seed)
        for v in r.uniform(1e-4, 1e-1, per):
            h.record(float(v))

    threads = [threading.Thread(target=work, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.count == n_threads * per
    assert 1e-4 <= h.percentile(50) <= 1e-1


# ---------------------------------------------------------------------------
# Tracer
# ---------------------------------------------------------------------------


def test_span_nesting_parent_and_containment():
    tr = Tracer(128)
    with tr.span("outer", "t"):
        with tr.span("inner", "t", rows=3):
            pass
    spans = {s["name"]: s for s in tr.spans()}
    inner, outer = spans["inner"], spans["outer"]
    assert inner["args"]["parent"] == "outer"
    assert inner["args"]["rows"] == 3
    assert "parent" not in outer["args"]
    assert inner["tid"] == outer["tid"]
    assert outer["start_ns"] <= inner["start_ns"]
    assert (inner["start_ns"] + inner["dur_ns"]
            <= outer["start_ns"] + outer["dur_ns"])


def test_span_yields_attrs_for_late_annotation():
    tr = Tracer(16)
    with tr.span("node", "t") as attrs:
        attrs["shape"] = [4, 2]
    assert tr.spans()[0]["args"]["shape"] == [4, 2]


def test_ring_buffer_bounded():
    tr = Tracer(32)
    for i in range(100):
        tr.instant(f"e{i}", "t")
    spans = tr.spans()
    assert len(spans) == 32
    assert tr.dropped == 100 - 32
    assert spans[0]["name"] == "e68"  # most recent 32 kept


def test_active_tracer_gate_and_rebuild(traced):
    assert active_tracer() is None  # disabled by default in tests
    tr = traced(True)
    assert tr is not None and active_tracer() is tr  # cached instance
    traced(False)
    assert active_tracer() is None


def test_tracer_thread_safety_under_micro_batcher(traced):
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.workflow.serving import CompiledPipeline, PipelineService

    tr = traced(True)
    cp = CompiledPipeline(L2Normalizer(), max_batch=8)
    cp.warmup((4,))
    n_clients, per = 4, 10
    errs = []

    def client(cid):
        rng = np.random.default_rng(cid)
        try:
            for _ in range(per):
                x = rng.normal(size=(4,)).astype(np.float32)
                with tr.span("client.request", "test", client=cid):
                    svc.submit(x).result(timeout=30)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    with PipelineService(cp, max_delay_ms=1.0) as svc:
        threads = [
            threading.Thread(target=client, args=(c,))
            for c in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not errs
    spans = tr.spans()
    by_name: dict = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    # Every request got a lifecycle span from the worker thread and a
    # client span from its own thread — recorded concurrently.
    ok = [s for s in by_name["serve.request"]
          if s["args"].get("outcome") == "ok"]
    assert len(ok) == n_clients * per
    assert len(by_name["client.request"]) == n_clients * per
    assert len(by_name["serve.queued"]) == n_clients * per
    assert len({s["tid"] for s in spans}) >= n_clients + 1
    # And the whole concurrent recording exports as a valid trace.
    assert validate_chrome_trace(tr.export()) == []


def test_disabled_tracer_fit_bit_identity(traced):
    """Enabled-but-recording vs disabled tracing produce bit-identical
    solver output — spans observe, never perturb (the reliability
    harness's enabled-but-silent discipline)."""
    from keystone_tpu.linalg import solve_least_squares_chunked
    from keystone_tpu.loaders.stream import BatchIterator

    rng = np.random.default_rng(3)
    X = rng.normal(size=(96, 12)).astype(np.float32)
    Y = (X @ rng.normal(size=(12, 4))).astype(np.float32)

    def solve():
        it = BatchIterator.from_arrays(X, Y, batch_rows=16).prefetch(2)
        return np.asarray(solve_least_squares_chunked(it, lam=1e-3))

    traced(False)
    base = solve()
    tr = traced(True)
    armed = solve()
    assert len(tr.spans()) > 0  # it really did trace
    traced(False)
    again = solve()
    np.testing.assert_array_equal(base, armed)
    np.testing.assert_array_equal(base, again)


def test_traced_pipeline_fit_bit_identity(traced):
    from keystone_tpu.nodes.stats.scalers import StandardScaler
    from keystone_tpu.workflow.executor import PipelineEnv

    rng = np.random.default_rng(4)
    X = rng.normal(size=(32, 6)).astype(np.float32)

    def fit_apply():
        PipelineEnv.reset()  # a real refit, not a fit-cache hit
        return np.asarray(
            StandardScaler().with_data(X).fit().apply(X).get()
        )

    traced(False)
    base = fit_apply()
    tr = traced(True)
    armed = fit_apply()
    names = {s["name"] for s in tr.spans()}
    assert "pipeline.fit" in names and "pipeline.apply" in names
    assert any(n.startswith("node:") for n in names)
    np.testing.assert_array_equal(base, armed)


# ---------------------------------------------------------------------------
# Chrome-trace export / schema
# ---------------------------------------------------------------------------


def test_export_schema_valid_and_written(tmp_path):
    tr = Tracer(64)
    with tr.span("a", "cat", rows=5):
        tr.instant("marker", "cat")
    path = str(tmp_path / "trace.json")
    doc = tr.export(path)
    assert validate_chrome_trace(doc) == []
    with open(path) as f:
        reloaded = json.load(f)
    assert validate_chrome_trace(reloaded) == []
    xs = [e for e in reloaded["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"a", "marker"}
    metas = [e for e in reloaded["traceEvents"] if e["ph"] == "M"]
    assert metas and metas[0]["args"]["name"]  # thread_name metadata


def test_validate_rejects_malformed():
    assert validate_chrome_trace([]) != []
    assert validate_chrome_trace({"traceEvents": "nope"}) != []
    bad_phase = {"traceEvents": [{"name": "x", "ph": "Q", "pid": 1}]}
    assert validate_chrome_trace(bad_phase) != []
    neg = {"traceEvents": [
        {"name": "x", "ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": -5}
    ]}
    assert any("negative" in e for e in validate_chrome_trace(neg))
    no_ts = {"traceEvents": [{"name": "x", "ph": "X", "pid": 1}]}
    assert validate_chrome_trace(no_ts) != []


# ---------------------------------------------------------------------------
# MetricsRegistry
# ---------------------------------------------------------------------------


def test_registry_unifies_counters_histograms_gauges():
    snap = metrics_registry.snapshot()
    # The process counter sets live under the one registry...
    assert "serving" in snap and "reliability" in snap
    assert snap["serving"] == serving_counters.snapshot()
    # ...histograms and gauges are get-or-create singletons...
    h = metrics_registry.histogram("test.latency")
    assert metrics_registry.histogram("test.latency") is h
    g = metrics_registry.gauge("test.depth")
    assert metrics_registry.gauge("test.depth") is g
    assert isinstance(g, Gauge)
    # ...with type-confusion refused, not silently served.
    with pytest.raises(TypeError):
        metrics_registry.gauge("test.latency")
    h.record(0.005)
    g.set(3)
    g.set(1)
    snap = metrics_registry.snapshot()
    assert snap["test.latency"]["count"] >= 1
    assert snap["test.depth"] == {"value": 1, "max": 3}
    h.reset()
    g.reset()


def test_registry_reset_resets_every_component():
    h = metrics_registry.histogram("test.reset_probe")
    h.record(0.1)
    serving_counters.record_call(8, 5)
    metrics_registry.reset()
    snap = metrics_registry.snapshot()
    assert snap["test.reset_probe"] == {"count": 0}
    assert snap["serving"]["calls"] == 0


def test_registry_snapshot_under_concurrent_writers():
    """The satellite gate: 4 writer threads hammer counters, histograms,
    and a gauge while a reader snapshots in a loop — no exceptions, no
    torn reads, and every successive counter view is monotone."""
    counters = metrics_registry.counters("test.concurrent_counts")
    hist = metrics_registry.histogram("test.concurrent_lat")
    gauge = metrics_registry.gauge("test.concurrent_depth")
    counters.reset()
    hist.reset()
    gauge.reset()
    n_threads, per = 4, 3000
    stop = threading.Event()
    errs: list = []

    def writer(tid):
        try:
            for i in range(per):
                counters.bump("total")
                counters.bump(f"w{tid}")
                hist.record(1e-4 * (1 + (i % 7)))
                gauge.set(i)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    views: list = []

    def reader():
        try:
            while not stop.is_set():
                snap = metrics_registry.snapshot()
                views.append(snap["test.concurrent_counts"].get("total", 0))
                assert snap["test.concurrent_lat"]["count"] >= 0
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    rt = threading.Thread(target=reader)
    rt.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    rt.join()
    assert not errs, errs[:2]
    # Monotone counter views: no snapshot ever ran backwards.
    assert all(a <= b for a, b in zip(views, views[1:]))
    snap = metrics_registry.snapshot()
    assert snap["test.concurrent_counts"]["total"] == n_threads * per
    assert all(
        snap["test.concurrent_counts"][f"w{t}"] == per
        for t in range(n_threads)
    )
    assert snap["test.concurrent_lat"]["count"] == n_threads * per
    counters.reset()
    hist.reset()
    gauge.reset()


def test_prometheus_exposition_valid_and_agrees_with_snapshot():
    """The export-surface gate, registry-side: ``prometheus()`` parses
    under the shared validator, carries instance labels, and its sample
    values agree with ``snapshot()``."""
    from keystone_tpu.utils.metrics import (
        parse_prometheus_text,
        validate_prometheus_text,
    )

    h = metrics_registry.histogram("test.prom_lat")
    h.reset()
    for v in (0.001, 0.002, 0.004, 0.5):
        h.record(v)
    g = metrics_registry.gauge("test.prom_depth[inst0]")
    g.set(7)
    c = metrics_registry.counters("test.prom_counts[inst0]")
    c.reset()
    c.bump("ok", 3)
    c.bump("error")
    text = metrics_registry.prometheus()
    assert validate_prometheus_text(text) == []
    samples = {
        (s["name"], tuple(sorted(s["labels"].items()))): s["value"]
        for s in parse_prometheus_text(text)
    }
    assert samples[("keystone_test_prom_lat_seconds_count", ())] == 4
    assert samples[
        ("keystone_test_prom_lat_seconds_sum", ())
    ] == pytest.approx(0.507)
    assert samples[
        ("keystone_test_prom_depth", (("instance", "inst0"),))
    ] == 7
    assert samples[
        ("keystone_test_prom_counts_total",
         (("instance", "inst0"), ("key", "ok")))
    ] == 3
    assert samples[
        ("keystone_test_prom_counts_total",
         (("instance", "inst0"), ("key", "error")))
    ] == 1
    # Quantiles ride along as gauges in seconds.
    q99 = samples[
        ("keystone_test_prom_lat_quantile_seconds", (("quantile", "0.99"),))
    ]
    assert q99 == pytest.approx(h.snapshot()["p99_ms"] / 1e3)
    # The serving counter component flattens with its bucket maps.
    serving_counters.record_call(8, 5)
    text = metrics_registry.prometheus()
    assert validate_prometheus_text(text) == []
    bucket_hits = [
        s for s in parse_prometheus_text(text)
        if s["name"] == "keystone_serving_bucket_hits"
    ]
    assert any(
        s["labels"].get("key") == "8" and s["value"] >= 1
        for s in bucket_hits
    )
    serving_counters.reset()
    h.reset()
    g.reset()
    c.reset()


def test_prometheus_label_escaping_round_trips():
    """Escape decoding is single-pass: a label value with a literal
    backslash before an 'n' must round-trip, not decode the tail of the
    escaped backslash as a newline escape."""
    from keystone_tpu.utils.metrics import (
        _prom_labels,
        parse_prometheus_text,
    )

    for value in ("dir\\name", 'quo"te', "line\nbreak", "\\\\n", "plain"):
        line = f"m{_prom_labels({'k': value})} 1\n"
        (sample,) = parse_prometheus_text(line)
        assert sample["labels"]["k"] == value, (value, sample)


def test_retain_request_since_bound_keeps_journey_drops_scan():
    """The bounded tail-sampling scan: spans recorded before the request
    existed are skipped via early exit, spans of its journey are kept."""
    tr = Tracer(256)
    for i in range(50):  # old unrelated traffic, ends well before t_sub
        tr.instant(f"old{i}", "t", req_id=999)
    import time as _t

    _t.sleep(0.02)  # clear the scan slack so the cutoff really binds
    t_sub = Tracer.now()
    tr.record("serve.queued", "serving", t_sub, req_id=7)
    tr.record("serve.device", "serving", t_sub, req_ids=[7, 8])
    tr.record("serve.request", "serving", t_sub, req_id=7, outcome="ok")
    n = tr.retain_request(7, since_ns=t_sub)
    assert n == 3
    kept = tr.retained()[7]
    assert [s["name"] for s in kept] == [
        "serve.queued", "serve.device", "serve.request",
    ]
    # Without since_ns the full ring scan finds the same spans.
    tr2 = Tracer(256)
    tr2.record("serve.request", "serving", Tracer.now(), req_id=3)
    assert tr2.retain_request(3) == 1


def test_validate_prometheus_rejects_malformed():
    from keystone_tpu.utils.metrics import validate_prometheus_text

    assert validate_prometheus_text("not a metric line\n") != []
    assert validate_prometheus_text('x{key=unquoted} 1\n') != []
    assert validate_prometheus_text("x 1e999e9\n") != []
    assert validate_prometheus_text("# TYPE x wrongtype\nx 1\n") != []
    # Histogram discipline: buckets must be cumulative and +Inf-capped.
    bad = (
        "# TYPE h histogram\n"
        'h_bucket{le="0.1"} 5\nh_bucket{le="+Inf"} 3\nh_count 3\n'
    )
    assert any("cumulative" in e for e in validate_prometheus_text(bad))
    no_inf = "# TYPE h histogram\n" 'h_bucket{le="0.1"} 5\n'
    assert any("+Inf" in e for e in validate_prometheus_text(no_inf))
    # A validator reports, never raises — even on a non-numeric le.
    bad_le = "# TYPE h histogram\n" 'h_bucket{le="abc"} 3\n'
    assert any("non-numeric le" in e for e in validate_prometheus_text(bad_le))


def test_record_compile_attributes_bucket():
    """The satellite fix: record_compile(bucket) must no longer drop its
    argument — warmup evidence names which bucket compiled."""
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.workflow.serving import CompiledPipeline

    serving_counters.reset()
    # devices=1 pins the single-replica attribution this test is about;
    # the replica pool multiplies every bucket count by the pool width.
    cp = CompiledPipeline(L2Normalizer(), buckets=(2, 4, 16), devices=1)
    cp.warmup((3,))
    snap = serving_counters.snapshot()
    assert snap["compiles_by_bucket"] == {2: 1, 4: 1, 16: 1}
    assert snap["compiles"] == 3
    assert cp.stats()["compiles_by_bucket"] == {2: 1, 4: 1, 16: 1}
    serving_counters.reset()


def test_registry_latency_agrees_with_external_stopwatch():
    """The acceptance cross-check, in miniature: the registry's serving
    percentiles vs an external timer around the same calls, within 10%."""
    import time

    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.nodes.stats.random_features import CosineRandomFeatures
    from keystone_tpu.workflow.pipeline import FusedTransformer
    from keystone_tpu.workflow.serving import CompiledPipeline

    # A chain heavy enough that per-call latency is well clear of the
    # few-µs Python overhead outside the recorded interval — the regime
    # the 10% contract is about (bench_serve's real serving heads are
    # ms-scale; a bare normalizer at ~50 µs is not).
    chain = FusedTransformer(
        [CosineRandomFeatures.create(32, 512, seed=0), L2Normalizer()]
    )
    cp = CompiledPipeline(chain, max_batch=64)
    cp.warmup((32,))
    hist = metrics_registry.histogram("serve.request_latency")
    hist.reset()
    rng = np.random.default_rng(0)
    lats = []
    for _ in range(80):
        x = rng.normal(size=(int(rng.integers(1, 65)), 32)).astype(np.float32)
        t0 = time.perf_counter()
        cp(x)
        lats.append(time.perf_counter() - t0)
    snap = hist.snapshot()
    assert snap["count"] == 80
    for p in (50, 95, 99):
        ext_ms = _nearest_rank(lats, p) * 1e3
        reg_ms = snap[f"p{p}_ms"]
        assert abs(reg_ms - ext_ms) / ext_ms < 0.10, (p, reg_ms, ext_ms)
    hist.reset()


def test_service_stats_health_surface(traced):
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.workflow.serving import (
        CompiledPipeline,
        PipelineService,
        e2e_latency,
    )

    e2e_latency.reset()
    cp = CompiledPipeline(L2Normalizer(), max_batch=8)
    cp.warmup((4,))
    svc = PipelineService(cp, max_delay_ms=1.0)
    futs = [
        svc.submit(np.ones((4,), dtype=np.float32)) for _ in range(5)
    ]
    for f in futs:
        f.result(timeout=30)
    stats = svc.stats()
    assert stats["requests"] == 5
    assert stats["worker_alive"] and not stats["closed"]
    assert stats["latency"]["count"] == 5
    assert stats["latency"]["p99_ms"] >= stats["latency"]["p50_ms"]
    assert stats["compiled"]["ladder"] == list(cp.ladder)
    svc.close()
    assert svc.stats()["closed"]


# ---------------------------------------------------------------------------
# trace-demo (the `make trace-demo` flow, in-process for tier-1)
# ---------------------------------------------------------------------------


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trace_demo_full_coverage(tmp_path):
    """One small fit+serve under tracing must produce a schema-valid
    export whose spans cover executor nodes, solver chunks, prefetch
    residency, and the serving request lifecycle — the acceptance
    surface, and the in-process stand-in for `make trace-demo`."""
    demo = _load_tool("trace_demo")
    out = str(tmp_path / "demo_trace.json")
    result = demo.run_demo(out)
    assert result["schema_errors"] == []
    assert result["missing_coverage"] == []
    assert result["ok"] is True
    assert result["serving_latency"]["count"] == result["service_requests"]
    # the exported artifact round-trips through the report CLI's summary
    report = _load_tool("trace_report")
    with open(out) as f:
        doc = json.load(f)
    rows = report.summarize(doc)
    assert any(k.startswith("solver/") for k in rows)
    assert any(k.startswith("serving/") for k in rows)
    # and tracing was left OFF for the rest of the suite
    assert active_tracer() is None
