"""NLP node unit tests + text pipeline integration tests."""

import numpy as np
import pytest

from keystone_tpu.evaluation.binary import BinaryClassifierEvaluator
from keystone_tpu.nodes.nlp import (
    CommonSparseFeatures,
    LowerCase,
    NGramsFeaturizer,
    TermFrequency,
    Tokenizer,
    Trim,
    WordFrequencyEncoder,
)
from keystone_tpu.pipelines.text.amazon_reviews import (
    AmazonReviewsConfig,
    run as run_amazon,
)
from keystone_tpu.pipelines.text.newsgroups import (
    NewsgroupsConfig,
    run as run_newsgroups,
)


def test_tokenize_chain():
    p = Trim().and_then(LowerCase()).and_then(Tokenizer())
    out = p(["  Hello, World!  ", "A-B c"]).get()
    assert out == [["hello", "world"], ["a", "b", "c"]]


def test_ngrams():
    node = NGramsFeaturizer(1, 2)
    assert node.apply(["a", "b", "c"]) == ["a", "b", "c", "a b", "b c"]
    with pytest.raises(ValueError):
        NGramsFeaturizer(2, 1)


def test_term_frequency_log():
    node = TermFrequency("log")
    out = node.apply(["x", "x", "y"])
    np.testing.assert_allclose(out["x"], np.log(3.0))
    np.testing.assert_allclose(out["y"], np.log(2.0))


def test_common_sparse_features_keeps_top_terms():
    docs = [{"a": 1.0, "b": 2.0}, {"a": 1.0}, {"a": 3.0, "c": 1.0}]
    enc = CommonSparseFeatures(num_features=2).fit(docs)
    assert set(enc.vocabulary) == {"a", "b"} or set(enc.vocabulary) == {"a", "c"}
    X = enc(docs)
    assert X.shape == (3, 2)
    a_col = enc.index["a"]
    np.testing.assert_allclose(X[:, a_col], [1.0, 1.0, 3.0])


def test_word_frequency_encoder_counts():
    docs = [["a", "b", "a"], ["b"]]
    enc = WordFrequencyEncoder(num_words=2).fit(docs)
    X = enc(docs)
    np.testing.assert_allclose(X[:, enc.index["a"]], [2.0, 0.0])
    np.testing.assert_allclose(X[:, enc.index["b"]], [1.0, 1.0])


def test_binary_evaluator_and_auc():
    pred = np.array([1, 1, 0, 0])
    act = np.array([1, 0, 0, 1])
    m = BinaryClassifierEvaluator.evaluate(pred, act)
    assert (m.tp, m.fp, m.tn, m.fn) == (1, 1, 1, 1)
    assert m.accuracy == 0.5
    # perfect ranking → AUC 1; inverted → 0
    assert BinaryClassifierEvaluator.auc([0.9, 0.8, 0.1], [1, 1, 0]) == 1.0
    assert BinaryClassifierEvaluator.auc([0.1, 0.2, 0.9], [1, 1, 0]) == 0.0
    # ties → 0.5
    assert BinaryClassifierEvaluator.auc([0.5, 0.5], [1, 0]) == 0.5


def test_newsgroups_pipeline_naive_bayes():
    out = run_newsgroups(NewsgroupsConfig(synthetic_n=600, num_features=500))
    assert out["test_accuracy"] > 0.9, out["summary"]


def test_newsgroups_pipeline_logistic():
    out = run_newsgroups(
        NewsgroupsConfig(
            synthetic_n=400, num_features=300, classifier="logistic"
        )
    )
    assert out["test_accuracy"] > 0.9, out["summary"]


def test_amazon_reviews_pipeline():
    out = run_amazon(AmazonReviewsConfig(synthetic_n=600, num_features=500))
    assert out["accuracy"] > 0.9, out["summary"]
    assert out["auc"] > 0.95, out["summary"]


def test_newsgroups_loader_aligns_test_classes(tmp_path):
    from keystone_tpu.loaders.newsgroups import NewsgroupsDataLoader

    for split, groups in [("train", ["alt", "hockey"]), ("test", ["hockey"])]:
        for g in groups:
            d = tmp_path / split / g
            d.mkdir(parents=True)
            (d / "1.txt").write_text(f"{g} words here")
    train, classes = NewsgroupsDataLoader.load(str(tmp_path / "train"))
    test, _ = NewsgroupsDataLoader.load(str(tmp_path / "test"), classes=classes)
    # 'hockey' must keep index 1 even though it's the only test class.
    assert test.labels.tolist() == [classes.index("hockey")]
    # Unknown test class -> clear error, not silent misalignment.
    extra = tmp_path / "test" / "zzz"
    extra.mkdir()
    (extra / "1.txt").write_text("x")
    with pytest.raises(ValueError, match="not present in the training"):
        NewsgroupsDataLoader.load(str(tmp_path / "test"), classes=classes)
