"""Property test: randomly composed pipelines are invariant to every
execution configuration — chain fusion on/off, auto-caching on/off, disk
cache on/off — and structurally identical rebuilds hit the fit cache
instead of refitting.

This is the workflow layer's deepest contract (the reference's optimizer
rules must be semantics-preserving; SURVEY.md §2.1 optimizer rows
[unverified]): whatever DAG the composition algebra produces, optimization
must only change HOW it executes.
"""

import numpy as np
import pytest

from keystone_tpu.config import config
from keystone_tpu.workflow import (
    Estimator,
    PipelineEnv,
    Pipeline,
    Transformer,
)


class Affine(Transformer):
    """Jittable, content-stable: identical params hash alike."""

    def __init__(self, a: float, b: float):
        self.a = float(a)
        self.b = float(b)

    def signature(self):
        return self.stable_signature(self.a, self.b)

    def apply_batch(self, X):
        return X * self.a + self.b


class Clip(Transformer):
    def __init__(self, lo: float):
        self.lo = float(lo)

    def signature(self):
        return self.stable_signature(self.lo)

    def apply_batch(self, X):
        import jax.numpy as jnp

        return jnp.maximum(X, self.lo)


class HostScale(Transformer):
    """Host-side (unjittable) stage — breaks fusion chains."""

    jittable = False

    def __init__(self, c: float):
        self.c = float(c)

    def signature(self):
        return self.stable_signature(self.c)

    def apply_batch(self, X):
        return np.asarray(X) * self.c


class MeanCenter(Estimator):
    """Content-stable estimator whose fits are globally counted."""

    fits = 0

    def __init__(self, tag: int):
        self.tag = tag

    def fit(self, data):
        type(self).fits += 1
        mu = np.asarray(data).mean(axis=0)
        return Affine(1.0, 0.0) if self.tag < 0 else _Shift(-mu)


class _Shift(Transformer):
    def __init__(self, mu):
        self.mu = np.asarray(mu)

    def signature(self):
        return self.stable_signature(self.mu.tobytes(), self.mu.shape)

    def apply_batch(self, X):
        return X + self.mu


def _random_pipeline(rng, data, depth=None):
    """A random composition over the node pool, including estimator splices
    and gathered branches."""
    depth = depth if depth is not None else int(rng.integers(2, 6))
    p = None
    for _ in range(depth):
        roll = rng.uniform()
        if roll < 0.45:
            node = Affine(
                float(rng.uniform(0.5, 1.5)), float(rng.uniform(-0.5, 0.5))
            ).to_pipeline()
        elif roll < 0.6:
            node = Clip(float(rng.uniform(-0.2, 0.2))).to_pipeline()
        elif roll < 0.75:
            node = HostScale(float(rng.uniform(0.9, 1.1))).to_pipeline()
        elif roll < 0.9:
            node = MeanCenter(int(rng.integers(0, 1000))).with_data(
                data.copy()
            )
        else:
            a = Affine(float(rng.uniform(0.5, 1.5)), 0.0)
            b = Clip(0.0)
            node = Pipeline.gather([a.to_pipeline(), b.to_pipeline()])
        p = node if p is None else p.and_then(node)
    return p


def _run(build, X, fuse: bool, auto_cache: bool, cache_dir):
    PipelineEnv.reset()
    old_fuse, old_auto = config.fuse_chains, config.auto_cache
    config.fuse_chains = fuse
    config.auto_cache = auto_cache
    import os

    old_dir = os.environ.get("KEYSTONE_CACHE_DIR")
    if cache_dir is not None:
        os.environ["KEYSTONE_CACHE_DIR"] = str(cache_dir)
    else:
        os.environ.pop("KEYSTONE_CACHE_DIR", None)
    try:
        p = build().fit()
        return np.asarray(p.apply(X).get())
    finally:
        config.fuse_chains = old_fuse
        config.auto_cache = old_auto
        if old_dir is None:
            os.environ.pop("KEYSTONE_CACHE_DIR", None)
        else:
            os.environ["KEYSTONE_CACHE_DIR"] = old_dir
        PipelineEnv.reset()


@pytest.mark.parametrize("seed", range(8))
def test_configs_agree(seed, tmp_path):
    rng = np.random.default_rng(seed)
    data = rng.normal(size=(96, 12)).astype(np.float32)
    X = rng.normal(size=(32, 12)).astype(np.float32)

    def build():
        return _random_pipeline(np.random.default_rng(seed + 1000), data)

    ref = _run(build, X, fuse=True, auto_cache=False, cache_dir=None)
    for fuse, auto_cache, use_disk in [
        (False, False, False),
        (True, True, False),
        (True, False, True),
    ]:
        got = _run(
            build, X, fuse=fuse, auto_cache=auto_cache,
            cache_dir=tmp_path if use_disk else None,
        )
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("seed", range(4))
def test_rebuild_hits_fit_cache(seed):
    """Two structurally identical builds in one session fit each estimator
    once — content-stable prefixes dedup across graph copies."""
    rng = np.random.default_rng(seed)
    data = rng.normal(size=(64, 8)).astype(np.float32)
    X = rng.normal(size=(16, 8)).astype(np.float32)

    def build():
        return _random_pipeline(np.random.default_rng(seed + 2000), data)

    PipelineEnv.reset()
    MeanCenter.fits = 0
    # Keep the first pipeline alive: fit-cache entries are scoped to their
    # estimator's lifetime (dropping every reference frees the pinned
    # training data and evicts — by design; the DISK cache covers rebuilds
    # after that, see test_disk_cache.py).
    p1 = build()
    out1 = np.asarray(p1.fit().apply(X).get())
    fits_first = MeanCenter.fits
    p2 = build()
    out2 = np.asarray(p2.fit().apply(X).get())
    np.testing.assert_allclose(out2, out1, rtol=1e-5)
    assert MeanCenter.fits == fits_first  # zero refits on the rebuild
    PipelineEnv.reset()
