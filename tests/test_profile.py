"""Per-node resource attribution (utils/metrics.ResourceProfile).

Pins the ISSUE-9 training-side profiler contract:

1. A profiled fit+apply gives every executed node an attribution row
   (nonzero wall, dispatch/wait split, cache tallies); cache hits record
   as hit rows with zero cost.
2. Cost-model FLOPs come from the memoized per-(transformer, shape)
   AOT compile — computed once, re-served from the memo, and within 2x
   of the ``achieved_tflops`` oracle for the same computation.
3. KEYSTONE_PROFILE off/on fit+apply outputs are bit-identical (the
   profiler measures, never perturbs) — via the in-process profile-demo,
   which is also the ``make profile-demo`` gate.
4. The registry carries the profile: ``snapshot()["profile"]`` and the
   Prometheus exposition agree per-node (scrape-vs-snapshot), and the
   exposition validates under the shared oracle.
5. The device memory probes are memoized per process: after the first
   call neither ``device_hbm_bytes`` nor ``peak_hbm_bytes`` consults
   ``jax.local_devices`` again, and their CPU return types are pinned
   (int resp. None).
"""

import numpy as np
import pytest

import jax

from keystone_tpu.utils.metrics import (
    ResourceProfile,
    active_profile,
    device_hbm_bytes,
    metrics_registry,
    node_cost_analysis,
    parse_prometheus_text,
    peak_hbm_bytes,
    profile_scope,
    render_attribution_table,
    resource_profile,
    validate_prometheus_text,
)


@pytest.fixture(autouse=True)
def _fresh_profile():
    resource_profile.reset()
    yield
    resource_profile.reset()


def _fit_pipeline(rng, n=96, d=12):
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.nodes.stats.scalers import StandardScaler

    X = rng.normal(size=(n, d)).astype(np.float32)
    return StandardScaler().with_data(X).and_then(L2Normalizer()), X


# ---------------------------------------------------------------------------
# The profile component itself
# ---------------------------------------------------------------------------


def test_record_node_aggregates_and_rows():
    p = ResourceProfile()
    p.record_node("A", wall_ns=2_000_000, dispatch_ns=500_000,
                  flops=100.0, bytes_accessed=400.0, out_nbytes=64)
    p.record_node("A", wall_ns=1_000_000, dispatch_ns=250_000,
                  flops=100.0, bytes_accessed=400.0, out_nbytes=64)
    p.record_node("B", cache="hit")
    rows = p.rows()
    assert [r["node"] for r in rows] == ["A", "B"]
    a, b = rows
    assert a["calls"] == 2 and a["executed"] == 2
    assert a["wall_ms"] == pytest.approx(3.0)
    assert a["device_wait_ms"] == pytest.approx(2.25)
    assert a["flops"] == 200.0 and a["output_bytes"] == 128
    assert a["provenance"] == "cost-model"
    assert b["cache_hits"] == 1 and b["executed"] == 0
    assert b["provenance"] == "measured"
    # The renderer accepts both full and sparse rows (trace_report --fit
    # hands it measured-only rows with None cost columns).
    table = render_attribution_table(rows)
    assert "A" in table and "cost-model" in table and "-" in table


def test_mark_scopes_rows_to_the_delta():
    p = ResourceProfile()
    p.record_node("A", wall_ns=1_000_000, flops=10.0)
    p.record_node("B", wall_ns=1_000_000)
    mark = p.mark()
    p.record_node("A", wall_ns=2_000_000, flops=10.0)
    p.record_node("C", wall_ns=500_000)
    rows = p.rows(since=mark)
    # B was untouched after the mark: dropped; A reports only the delta.
    assert {r["node"] for r in rows} == {"A", "C"}
    a = next(r for r in rows if r["node"] == "A")
    assert a["calls"] == 1 and a["wall_ms"] == pytest.approx(2.0)
    assert a["flops"] == 10.0
    # The cumulative view is unchanged.
    assert {r["node"] for r in p.rows()} == {"A", "B", "C"}
    assert next(r for r in p.rows() if r["node"] == "A")["calls"] == 2


def test_fit_profile_true_logs_per_fit_delta(rng, caplog):
    import logging

    pipe, X = _fit_pipeline(rng)
    with caplog.at_level(logging.INFO, logger="keystone_tpu"):
        pipe.fit(profile=True)
    from keystone_tpu.workflow.executor import PipelineEnv

    PipelineEnv.reset()
    caplog.clear()
    with caplog.at_level(logging.INFO, logger="keystone_tpu"):
        pipe.fit(profile=True)
    # The second fit's logged table reports THIS fit (1 call per node),
    # not the accumulated two-fit totals.
    table = next(r.getMessage() for r in caplog.records
                 if "fit attribution" in r.getMessage())
    row = next(line for line in table.splitlines()
               if line.startswith("StandardScaler.fit"))
    assert row.split()[1] == "1"


def test_active_profile_respects_config_and_scope(monkeypatch):
    from keystone_tpu.config import config

    monkeypatch.setattr(config, "profile", False)
    assert active_profile() is None
    with profile_scope() as p:
        assert active_profile() is p is resource_profile
    assert active_profile() is None
    monkeypatch.setattr(config, "profile", True)
    assert active_profile() is resource_profile


# ---------------------------------------------------------------------------
# Executor integration
# ---------------------------------------------------------------------------


def test_profiled_fit_attributes_every_node(rng):
    pipe, X = _fit_pipeline(rng)
    with profile_scope():
        fitted = pipe.fit()
        fitted.apply(X).get()
    rows = resource_profile.rows()
    by_node = {r["node"]: r for r in rows}
    # The fit: dataset + estimator; the apply: the (fused) transformer
    # chain. Every executed node has nonzero wall.
    assert "Dataset" in by_node
    assert any(n.endswith(".fit") for n in by_node)
    assert any("L2Normalizer" in n for n in by_node)
    for r in rows:
        if r["executed"]:
            assert r["wall_ms"] > 0
    # A refit of the same pipeline is a fit-cache hit: rows record it as
    # a cache hit, not a new execution.
    hits_before = sum(r["cache_hits"] for r in rows)
    with profile_scope():
        pipe.fit()
    hits_after = sum(r["cache_hits"] for r in resource_profile.rows())
    assert hits_after > hits_before


def test_unprofiled_fit_records_nothing(rng):
    pipe, X = _fit_pipeline(rng)
    pipe.fit().apply(X).get()
    assert resource_profile.rows() == []


def test_node_cost_analysis_memoizes_and_matches_oracle(rng):
    from keystone_tpu.nodes.learning.linear_mapper import LinearMapper
    from keystone_tpu.utils.metrics import achieved_tflops

    W = rng.normal(size=(16, 4)).astype(np.float32)
    tr = LinearMapper(W)
    X = rng.normal(size=(32, 16)).astype(np.float32)
    est = node_cost_analysis(tr, X)
    assert est is not None and est["flops"] > 0
    # Memoized: the second call must not lower/compile again.
    compiled = {"n": 0}
    real_jit = jax.jit

    def counting_jit(*a, **kw):
        compiled["n"] += 1
        return real_jit(*a, **kw)

    try:
        jax.jit = counting_jit
        est2 = node_cost_analysis(tr, X)
    finally:
        jax.jit = real_jit
    assert est2 == est
    assert compiled["n"] == 0
    oracle = achieved_tflops(tr.apply_batch, X)
    assert est["flops"] == pytest.approx(oracle["flops"], rel=1.0)


def test_host_transformer_cost_is_none():
    from keystone_tpu.workflow.pipeline import Transformer

    class HostOnly(Transformer):
        jittable = False

        def apply_batch(self, X):
            return X

    assert node_cost_analysis(HostOnly(), np.ones((4, 2), np.float32)) is None


# ---------------------------------------------------------------------------
# Registry / Prometheus exposition
# ---------------------------------------------------------------------------


def test_profile_prometheus_exposition_and_scrape_agreement(rng):
    pipe, X = _fit_pipeline(rng)
    with profile_scope():
        pipe.fit().apply(X).get()
    snap = metrics_registry.snapshot()["profile"]
    assert snap["nodes"] >= 2 and snap["node_calls"]
    assert snap["fingerprint"]["backend"] == "cpu"
    text = metrics_registry.prometheus()
    assert validate_prometheus_text(text) == []
    scraped = {
        s["labels"]["key"]: s["value"]
        for s in parse_prometheus_text(text)
        if s["name"] == "keystone_profile_node_calls"
    }
    assert scraped == {k: float(v) for k, v in snap["node_calls"].items()}
    wall_scraped = {
        s["labels"]["key"]: s["value"]
        for s in parse_prometheus_text(text)
        if s["name"] == "keystone_profile_node_wall_seconds"
    }
    for label, secs in snap["node_wall_seconds"].items():
        assert wall_scraped[label] == pytest.approx(secs)


# ---------------------------------------------------------------------------
# Memoized device memory probes (ISSUE-9 satellite)
# ---------------------------------------------------------------------------


def test_memory_probes_memoize_device_and_pin_types(monkeypatch):
    # Prime the memos (probe allowed here).
    limit = device_hbm_bytes()
    peak = peak_hbm_bytes()
    assert isinstance(limit, int) and limit > 0
    assert peak is None  # CPU reports no peak_bytes_in_use
    # After priming, neither probe may consult jax.local_devices again —
    # that is a host sync and these now sit on the profiled hot path.
    def boom():
        raise AssertionError("device re-probed after memoization")

    monkeypatch.setattr(jax, "local_devices", boom)
    assert device_hbm_bytes() == limit
    assert peak_hbm_bytes() is None
    # Explicit default still honored on backends with no reported limit.
    assert device_hbm_bytes(default=123) in (123, limit)


def test_reset_memory_probe_reprobes():
    from keystone_tpu.utils.metrics import reset_memory_probe

    reset_memory_probe()
    assert isinstance(device_hbm_bytes(), int)
    assert peak_hbm_bytes() is None


# ---------------------------------------------------------------------------
# The full demo (= make profile-demo), in-process
# ---------------------------------------------------------------------------


def test_profile_demo_in_process():
    import importlib
    import os
    import sys

    sys.path.insert(
        0,
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools"),
    )
    try:
        profile_report = importlib.import_module("profile_report")
    finally:
        sys.path.pop(0)
    result = profile_report.run_demo()
    assert result["pass"]["every_executed_node_has_nonzero_wall"], result
    assert result["pass"]["fit_and_apply_nodes_covered"], result
    assert result["pass"]["solve_flops_within_2x_oracle"], result
    assert result["pass"]["profile_off_bit_identical"], result
    assert result["pass"]["chaos_dump_names_last_chunk"], result
    assert result["pass"]["prometheus_valid"], result
    assert result["ok"], result
