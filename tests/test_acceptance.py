"""The acceptance harness itself stays verified (VERDICT r2 #5): synthetic
mode runs real pipelines against the CI floors and returns rc=0; a missing
data root SKIPs rather than failing."""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import acceptance  # noqa: E402


def test_synthetic_subset_passes(capsys):
    rc = acceptance.main(
        ["--synthetic", "--pipelines", "MnistRandomFFT", "NewsgroupsPipeline"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert out.count("PASS") == 2 and "FAIL" not in out


def test_missing_data_skips(tmp_path, capsys):
    rc = acceptance.main(
        [str(tmp_path), "--pipelines", "MnistRandomFFT", "AmazonReviewsPipeline"]
    )
    out = capsys.readouterr().out
    assert rc == 0  # skips are not failures
    assert out.count("SKIP") == 2


def test_real_data_path_runs_from_fixtures(capsys):
    """Point the harness at the committed loader fixtures: tiny but REAL
    newsgroups data exercises the real-data code path end-to-end (train
    and test splits are the same fixture tree — harness plumbing, not a
    quality claim)."""
    import shutil
    import tempfile

    root = tempfile.mkdtemp()
    src = os.path.join(REPO, "tests", "fixtures", "data", "newsgroups", "train")
    os.makedirs(os.path.join(root, "newsgroups"))
    shutil.copytree(src, os.path.join(root, "newsgroups", "train"))
    shutil.copytree(src, os.path.join(root, "newsgroups", "test"))
    rc = acceptance.main([root, "--pipelines", "NewsgroupsPipeline"])
    out = capsys.readouterr().out
    # 4 docs train=test: the pipeline must run end-to-end; the verdict line
    # must carry a real value (tiny data may or may not clear the floor).
    assert "NewsgroupsPipeline" in out and "SKIP" not in out
    assert rc in (0, 1)
