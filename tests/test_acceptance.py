"""The acceptance harness itself stays verified (VERDICT r2 #5): synthetic
mode runs real pipelines against the CI floors and returns rc=0; a missing
data root SKIPs rather than failing."""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import acceptance  # noqa: E402


def test_synthetic_subset_passes(capsys):
    rc = acceptance.main(
        ["--synthetic", "--pipelines", "MnistRandomFFT", "NewsgroupsPipeline"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert out.count("PASS") == 2 and "FAIL" not in out


def test_missing_data_skips(tmp_path, capsys):
    rc = acceptance.main(
        [str(tmp_path), "--pipelines", "MnistRandomFFT", "AmazonReviewsPipeline"]
    )
    out = capsys.readouterr().out
    assert rc == 0  # skips are not failures
    assert out.count("SKIP") == 2


def test_real_data_path_runs_from_fixtures(capsys):
    """Point the harness at the committed loader fixtures: tiny but REAL
    newsgroups data exercises the real-data code path end-to-end (train
    and test splits are the same fixture tree — harness plumbing, not a
    quality claim)."""
    import shutil
    import tempfile

    root = tempfile.mkdtemp()
    src = os.path.join(REPO, "tests", "fixtures", "data", "newsgroups", "train")
    os.makedirs(os.path.join(root, "newsgroups"))
    shutil.copytree(src, os.path.join(root, "newsgroups", "train"))
    shutil.copytree(src, os.path.join(root, "newsgroups", "test"))
    rc = acceptance.main([root, "--pipelines", "NewsgroupsPipeline"])
    out = capsys.readouterr().out
    # 4 docs train=test: the pipeline must run end-to-end; the verdict line
    # must carry a real value (tiny data may or may not clear the floor).
    assert "NewsgroupsPipeline" in out and "SKIP" not in out
    assert rc in (0, 1)


def test_synthetic_band_binds(capsys):
    """With the injected label noise, passing metrics must sit strictly
    inside (floor, ceiling): a 1.0 score would mean the floors are
    decorative again (VERDICT r3 weak #4)."""
    import json

    rc = acceptance.main(
        ["--synthetic", "--json", "--pipelines", "MnistRandomFFT"]
    )
    out = capsys.readouterr().out
    row = next(
        json.loads(line)
        for line in out.splitlines()
        if line.startswith("{") and '"pipeline"' in line
    )
    assert rc == 0 and row["ok"]
    p = acceptance.SYNTH_LABEL_NOISE
    assert row["floor"] <= row["value"] <= 1.0 - p / 2
    # The harness restored the env for in-process callers.
    assert "KEYSTONE_SYNTH_LABEL_NOISE" not in os.environ


def test_noise_band_closed_forms():
    """Spot-check the per-metric reachable bounds (ADVICE r4) against
    their documented closed forms at p=0.1."""
    import pytest

    p = 0.1
    assert acceptance.noise_band("MnistRandomFFT", p) == (None, 0.95)
    lo, hi = acceptance.noise_band("AmazonReviewsPipeline", p)
    assert lo is None and hi == pytest.approx(0.925)  # 1-p+p/4
    lo, hi = acceptance.noise_band("TimitPipeline", p)
    assert lo == pytest.approx(0.05) and hi is None  # p/2
    lo, _ = acceptance.noise_band("ImageNetSiftLcsFV", p)
    assert lo == pytest.approx(p * 3 / 7 / 2)  # p(C-k)/(C-1)/2, C=8 k=5
    _, voc_hi = acceptance.noise_band("VOCSIFTFisher", p)
    assert 0.85 < voc_hi < 0.92  # AP noise model ~0.849 + 0.05 slack
    # More noise must lower the mAP ceiling (sanity on the closed form).
    assert acceptance.noise_band("VOCSIFTFisher", 0.2)[1] < voc_hi


def test_out_of_band_perfect_score_fails(capsys, monkeypatch):
    """A perfect score under injected label noise means the noise never
    reached the metric — the band check must FAIL it, naming the bound."""

    def fake_runner(root):
        return {"test_accuracy": 1.0}

    monkeypatch.setitem(
        acceptance.PIPELINES,
        "MnistRandomFFT",
        (fake_runner, "test_accuracy", 0.96, 0.85, True, "test"),
    )
    rc = acceptance.main(["--synthetic", "--pipelines", "MnistRandomFFT"])
    out = capsys.readouterr().out
    assert rc != 0 and "OUT OF BAND" in out and "ceiling" in out


def test_broken_solver_fails_table(capsys, monkeypatch):
    """A solver regression must FAIL the acceptance table, not pass on
    separable data: zero out the linear solve and assert rc!=0."""
    from keystone_tpu.nodes.learning import linear_mapper as lm
    from keystone_tpu.workflow import PipelineEnv

    PipelineEnv.reset()  # a cached clean fit would mask the breakage
    real_fit = lm.LinearMapEstimator.fit

    def broken_fit(self, data, labels):
        model = real_fit(self, data, labels)
        import jax.numpy as jnp

        model.W = jnp.zeros_like(model.W)
        if model.b is not None:
            model.b = jnp.zeros_like(model.b)
        return model

    monkeypatch.setattr(lm.LinearMapEstimator, "fit", broken_fit)
    rc = acceptance.main(
        ["--synthetic", "--pipelines", "MnistRandomFFT"]
    )
    out = capsys.readouterr().out
    assert rc != 0 and "FAIL" in out
