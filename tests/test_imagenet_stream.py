"""Decode-ahead ImageNet streaming: parity with the eager loader and
composition with the chunked-solver seam (SURVEY.md §7 hard part 4)."""

import os

import numpy as np
import pytest

from keystone_tpu.loaders.imagenet import ImageNetLoader

PIL = pytest.importorskip("PIL")


@pytest.fixture
def jpeg_tree(tmp_path):
    from PIL import Image

    rng = np.random.default_rng(0)
    label_map = {}
    for s in range(2):
        name = f"n{s:08d}"
        label_map[name] = s
        d = tmp_path / name
        d.mkdir()
        for i in range(6):
            arr = (rng.uniform(size=(48, 48, 3)) * 255).astype(np.uint8)
            Image.fromarray(arr).save(d / f"im_{i}.JPEG", quality=92)
    return str(tmp_path), label_map


def test_stream_matches_eager_load(jpeg_tree):
    root, label_map = jpeg_tree
    eager = ImageNetLoader.load(root, label_map, size=32, workers=4)
    Xs, ys = [], []
    for X, y in ImageNetLoader.stream_batches(
        root, label_map, batch_size=5, size=32, workers=4
    ):
        assert X.ndim == 4 and X.shape[1:] == (32, 32, 3)
        Xs.append(X)
        ys.append(y)
    np.testing.assert_allclose(np.concatenate(Xs), eager.data, atol=1e-6)
    np.testing.assert_array_equal(np.concatenate(ys), eager.labels)


def test_stream_respects_limit(jpeg_tree):
    root, label_map = jpeg_tree
    batches = list(
        ImageNetLoader.stream_batches(
            root, label_map, batch_size=4, size=32, workers=2, limit=7
        )
    )
    assert sum(len(x) for x, _ in batches) == 7


def test_stream_feeds_chunked_solver(jpeg_tree):
    """The BatchIterator seam: decode-ahead batches drive the out-of-core
    normal-equations solve directly."""
    from keystone_tpu.linalg import solve_least_squares_chunked

    root, label_map = jpeg_tree
    rng = np.random.default_rng(0)
    # 8 features for 12 rows: keeps the toy normal equations full rank.
    W_true = rng.normal(size=(8, 2)).astype(np.float32)

    def batches():
        for X, _y in ImageNetLoader.stream_batches(
            root, label_map, batch_size=4, size=32, workers=2
        ):
            F = X.reshape(len(X), -1)[:, :8]
            yield F, F @ W_true

    W = np.asarray(solve_least_squares_chunked(batches(), lam=1e-6))
    eager = ImageNetLoader.load(root, label_map, size=32, workers=2)
    F = eager.data.reshape(len(eager.data), -1)[:, :8]
    resid = np.linalg.norm(F @ W - F @ W_true) / np.linalg.norm(F @ W_true)
    assert resid < 1e-2


def test_abandoned_stream_stops_producer(jpeg_tree):
    import threading

    root, label_map = jpeg_tree
    before = threading.active_count()
    gen = ImageNetLoader.stream_batches(
        root, label_map, batch_size=2, size=32, workers=2, prefetch=1
    )
    next(gen)
    gen.close()  # consumer walks away mid-stream
    # The producer must unblock and exit, not strand on the full queue.
    deadline = 50
    while threading.active_count() > before and deadline:
        import time

        time.sleep(0.1)
        deadline -= 1
    assert threading.active_count() <= before


def test_stream_surfaces_decode_errors(tmp_path):
    d = tmp_path / "n00000000"
    d.mkdir()
    (d / "bad.JPEG").write_bytes(b"not a jpeg")
    with pytest.raises(Exception):
        list(
            ImageNetLoader.stream_batches(
                str(tmp_path), {"n00000000": 0}, batch_size=2, size=32
            )
        )
