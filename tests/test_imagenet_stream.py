"""Decode-ahead ImageNet streaming: parity with the eager loader and
composition with the chunked-solver seam (SURVEY.md §7 hard part 4)."""

import os

import numpy as np
import pytest

from keystone_tpu.loaders.imagenet import ImageNetLoader

PIL = pytest.importorskip("PIL")


@pytest.fixture
def jpeg_tree(tmp_path):
    from PIL import Image

    rng = np.random.default_rng(0)
    label_map = {}
    for s in range(2):
        name = f"n{s:08d}"
        label_map[name] = s
        d = tmp_path / name
        d.mkdir()
        for i in range(6):
            arr = (rng.uniform(size=(48, 48, 3)) * 255).astype(np.uint8)
            Image.fromarray(arr).save(d / f"im_{i}.JPEG", quality=92)
    return str(tmp_path), label_map


def test_stream_matches_eager_load(jpeg_tree):
    root, label_map = jpeg_tree
    eager = ImageNetLoader.load(root, label_map, size=32, workers=4)
    Xs, ys = [], []
    for X, y in ImageNetLoader.stream_batches(
        root, label_map, batch_size=5, size=32, workers=4
    ):
        assert X.ndim == 4 and X.shape[1:] == (32, 32, 3)
        Xs.append(X)
        ys.append(y)
    np.testing.assert_allclose(np.concatenate(Xs), eager.data, atol=1e-6)
    np.testing.assert_array_equal(np.concatenate(ys), eager.labels)


def test_stream_respects_limit(jpeg_tree):
    root, label_map = jpeg_tree
    batches = list(
        ImageNetLoader.stream_batches(
            root, label_map, batch_size=4, size=32, workers=2, limit=7
        )
    )
    assert sum(len(x) for x, _ in batches) == 7


def test_stream_feeds_chunked_solver(jpeg_tree):
    """The BatchIterator seam: decode-ahead batches drive the out-of-core
    normal-equations solve directly."""
    from keystone_tpu.linalg import solve_least_squares_chunked

    root, label_map = jpeg_tree
    rng = np.random.default_rng(0)
    # 8 features for 12 rows: keeps the toy normal equations full rank.
    W_true = rng.normal(size=(8, 2)).astype(np.float32)

    def batches():
        for X, _y in ImageNetLoader.stream_batches(
            root, label_map, batch_size=4, size=32, workers=2
        ):
            F = X.reshape(len(X), -1)[:, :8]
            yield F, F @ W_true

    W = np.asarray(solve_least_squares_chunked(batches(), lam=1e-6))
    eager = ImageNetLoader.load(root, label_map, size=32, workers=2)
    F = eager.data.reshape(len(eager.data), -1)[:, :8]
    resid = np.linalg.norm(F @ W - F @ W_true) / np.linalg.norm(F @ W_true)
    assert resid < 1e-2


def test_abandoned_stream_stops_producer(jpeg_tree):
    import threading
    import time

    root, label_map = jpeg_tree
    gen = ImageNetLoader.stream_batches(
        root, label_map, batch_size=2, size=32, workers=2, prefetch=1
    )
    next(gen)
    gen.close()  # consumer walks away mid-stream

    def ours():
        # The producer and its pool carry keystone-specific names, so this
        # can't flake on unrelated threads other tests/jax spin up.
        return [
            t
            for t in threading.enumerate()
            if t.is_alive()
            and ("keystone-ingest" in t.name or "keystone-decode" in t.name)
        ]

    # The producer (and its pool) must unblock and exit, not strand on the
    # full queue.
    for _ in range(100):
        leaked = ours()
        if not leaked:
            break
        time.sleep(0.1)
    assert not leaked, leaked


class TestNativeJpegPool:
    def _native(self):
        from keystone_tpu import native

        if not native.available():
            pytest.skip(f"native lib unavailable: {native.build_error()}")
        return native

    def test_matches_pil_decode(self, jpeg_tree):
        native = self._native()
        root, label_map = jpeg_tree
        import os

        env = os.environ
        old = env.get("KEYSTONE_JPEG_BACKEND")
        try:
            env["KEYSTONE_JPEG_BACKEND"] = "pil"
            pil = ImageNetLoader.load(root, label_map, size=32, workers=2)
            env["KEYSTONE_JPEG_BACKEND"] = "native"
            nat = ImageNetLoader.load(root, label_map, size=32, workers=2)
        finally:
            if old is None:
                env.pop("KEYSTONE_JPEG_BACKEND", None)
            else:
                env["KEYSTONE_JPEG_BACKEND"] = old
        assert nat.data.shape == pil.data.shape
        assert nat.data.min() >= 0.0 and nat.data.max() <= 1.0
        # Different resize filters (PIL vs bilinear+DCT scaling): images
        # agree closely but not bit-exactly.
        assert np.abs(nat.data - pil.data).mean() < 0.05
        np.testing.assert_array_equal(nat.labels, pil.labels)

    def test_corrupt_jpeg_reports_index(self):
        native = self._native()
        from PIL import Image
        import io as _io

        buf = _io.BytesIO()
        Image.fromarray(
            np.zeros((16, 16, 3), dtype=np.uint8)
        ).save(buf, format="JPEG")
        good = buf.getvalue()
        with pytest.raises(ValueError, match="image 1"):
            native.decode_jpeg_batch([good, b"corrupt", good], 16)

    def test_empty_batch(self):
        native = self._native()
        out = native.decode_jpeg_batch([], 16)
        assert out.shape == (0, 16, 16, 3)


def test_balanced_sample_spans_synsets(jpeg_tree):
    root, label_map = jpeg_tree  # 2 synsets x 6 images
    sample = ImageNetLoader.load_balanced_sample(
        root, label_map, total=4, size=32, workers=2
    )
    assert sample.shape == (4, 32, 32, 3)
    # 4 across 2 synsets = 2 per synset: images from BOTH classes, not a
    # prefix of the first (the bug this helper exists to avoid).
    eager = ImageNetLoader.load(root, label_map, size=32, workers=2)
    first = eager.data[:2]  # synset 0's first two
    second = eager.data[6:8]  # synset 1's first two
    np.testing.assert_allclose(sample[:2], first, atol=1e-6)
    np.testing.assert_allclose(sample[2:], second, atol=1e-6)


def test_streamed_tta_matches_eager():
    """Streamed TTA view accounting: the streamed path scores 10 views per
    image in stream_batch-sized slices and averages per image — any
    grouping/order error scrambles the per-image averages, so parity with
    the eager AugmentedExamplesEvaluator path is the accounting check."""
    from keystone_tpu.pipelines.images.imagenet_sift_lcs_fv import (
        ImageNetSiftLcsFVConfig,
        run,
    )
    from keystone_tpu.workflow.executor import PipelineEnv

    base = dict(
        synthetic_n=96, synthetic_classes=4, pca_dims=8, gmm_k=4,
        descriptor_sample=10_000, num_iters=1, top_k=2, augment=True,
    )
    PipelineEnv.reset()
    eager = run(ImageNetSiftLcsFVConfig(**base))
    PipelineEnv.reset()
    # stream_batch=32 < 96·10 views forces multiple featurize slices per
    # test batch; fit_sample_images=96 gives the same PCA/GMM fit set as
    # the eager run.
    streamed = run(ImageNetSiftLcsFVConfig(
        **base, stream=True, stream_batch=32, fit_sample_images=96,
    ))
    assert streamed["num_views"] == 10
    assert abs(streamed["top_k_error"] - eager["top_k_error"]) <= 0.03
    assert abs(streamed["top_1_error"] - eager["top_1_error"]) <= 0.05


def test_stream_surfaces_decode_errors(tmp_path):
    d = tmp_path / "n00000000"
    d.mkdir()
    (d / "bad.JPEG").write_bytes(b"not a jpeg")
    with pytest.raises(Exception):
        list(
            ImageNetLoader.stream_batches(
                str(tmp_path), {"n00000000": 0}, batch_size=2, size=32
            )
        )
