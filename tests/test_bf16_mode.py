"""bf16-storage / f32-accumulate solver mode (config.solver_storage_dtype).

The throughput mode for the MXU: A is stored in bfloat16, every matmul
touching it accumulates in float32, and all solver state (grams, Cholesky
factors, weights, residuals) stays float32. These tests are the accuracy
guard VERDICT.md round-2 item 3 asks for: bf16 solves must track the f32
oracle within bf16-rounding tolerances, and the mode must be off by default.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.config import config
from keystone_tpu.linalg import (
    RowMatrix,
    assemble_blocks,
    block_coordinate_descent,
    block_coordinate_descent_streamed,
    solve_least_squares_normal,
)
from keystone_tpu.nodes.learning import BlockLeastSquaresEstimator


@pytest.fixture
def bf16(monkeypatch):
    monkeypatch.setattr(config, "solver_storage_dtype", "bfloat16")


def _problem(rng, n=512, d=64, k=4):
    X = rng.normal(size=(n, d)).astype(np.float32)
    W = rng.normal(size=(d, k)).astype(np.float32)
    Y = (X @ W).astype(np.float32)
    return X, Y, W


def test_mode_off_by_default(rng):
    X, Y, _ = _problem(rng)
    A = RowMatrix.from_array(X)
    assert A.data.dtype == jnp.float32


def test_storage_and_state_dtypes(bf16, rng):
    from keystone_tpu.linalg.row_matrix import storage_dtype

    X, Y, _ = _problem(rng)
    A = RowMatrix.from_array(X, dtype=storage_dtype())
    B = RowMatrix.from_array(Y)
    assert A.data.dtype == jnp.bfloat16
    # Grams accumulate and land in f32 even with bf16 operands.
    assert A.gram().dtype == jnp.float32
    W_blocks, _ = block_coordinate_descent(
        A, B, block_size=32, num_iters=2, lam=1e-3
    )
    assert all(w.dtype == jnp.float32 for w in W_blocks)


def test_bcd_tracks_f32_oracle(bf16, rng):
    from keystone_tpu.linalg.row_matrix import storage_dtype

    X, Y, W_true = _problem(rng)
    A = RowMatrix.from_array(X, dtype=storage_dtype())
    B = RowMatrix.from_array(Y)
    W_blocks, blocks = block_coordinate_descent(
        A, B, block_size=32, num_iters=3, lam=1e-4
    )
    W = np.asarray(assemble_blocks(W_blocks))
    # bf16 inputs round at ~2^-8 relative; f32 accumulation keeps the solve
    # from drifting beyond that scale.
    resid = np.linalg.norm(X @ W - Y) / np.linalg.norm(Y)
    assert resid < 5e-2
    assert np.linalg.norm(W - W_true) / np.linalg.norm(W_true) < 5e-2


def test_normal_equations_tracks_oracle(bf16, rng):
    from keystone_tpu.linalg.row_matrix import storage_dtype

    X, Y, W_true = _problem(rng)
    A = RowMatrix.from_array(X, dtype=storage_dtype())
    B = RowMatrix.from_array(Y)
    W = np.asarray(solve_least_squares_normal(A, B, lam=1e-4))
    assert np.linalg.norm(W - W_true) / np.linalg.norm(W_true) < 5e-2


def test_streamed_blocks_use_bf16(bf16, rng):
    X, Y, W_true = _problem(rng)
    B = RowMatrix.from_array(Y)
    W_blocks, blocks = block_coordinate_descent_streamed(
        X, B, block_size=32, num_iters=3, lam=1e-4
    )
    assert all(w.dtype == jnp.float32 for w in W_blocks)
    W = np.asarray(assemble_blocks(W_blocks))
    assert np.linalg.norm(W - W_true) / np.linalg.norm(W_true) < 5e-2


def test_ring_bcd_tracks_f32_solve(rng):
    """bf16 storage must track the f32 ring solve at the same iteration
    count (convergence rate is a property of the sweep, not the dtype)."""
    from keystone_tpu.linalg import block_coordinate_descent_ring

    X, Y, W_true = _problem(rng, n=256, d=64, k=4)
    W32 = np.asarray(
        block_coordinate_descent_ring(X, Y, num_iters=6, lam=1e-4)
    )
    config.solver_storage_dtype = "bfloat16"
    try:
        W16 = np.asarray(
            block_coordinate_descent_ring(X, Y, num_iters=6, lam=1e-4)
        )
    finally:
        config.solver_storage_dtype = None
    assert np.linalg.norm(W16 - W32) / np.linalg.norm(W32) < 2e-2
    assert np.linalg.norm(W16 - W_true) / np.linalg.norm(W_true) < 5e-2


def test_bf16_conv_featurization_tracks_f32(rng):
    """The featurization half of the throughput mode: bf16 conv inputs with
    f32 accumulation track the f32 features within bf16 rounding, and the
    outputs stay f32 for the downstream rectify/pool/solve."""
    from keystone_tpu.nodes.images import Convolver

    X = rng.normal(size=(8, 16, 16, 3)).astype(np.float32)
    filters = rng.normal(size=(32, 5, 5, 3)).astype(np.float32) * 0.1
    ref = np.asarray(Convolver(filters).apply_batch(jnp.asarray(X)))
    got = Convolver(filters, compute_dtype="bfloat16").apply_batch(
        jnp.asarray(X)
    )
    assert got.dtype == jnp.float32
    got = np.asarray(got)
    denom = max(np.abs(ref).max(), 1e-6)
    assert np.abs(got - ref).max() / denom < 3e-2


def test_cifar_pipeline_bf16_features():
    """End-to-end: RandomPatchCifar with bf16 featurization keeps quality."""
    from keystone_tpu.pipelines.images.random_patch_cifar import (
        RandomPatchCifarConfig,
        run,
    )

    conf = dict(
        num_filters=32, patch_sample=512, synthetic_n=256, num_iters=2
    )
    f32 = run(RandomPatchCifarConfig(**conf))
    b16 = run(RandomPatchCifarConfig(**conf, feature_dtype="bfloat16"))
    assert b16["test_accuracy"] >= f32["test_accuracy"] - 0.05


def test_estimator_prediction_parity(rng):
    """End-to-end: bf16-mode predictions match the f32 fit within bf16 noise."""
    X, Y, _ = _problem(rng, n=256, d=32, k=3)
    ref = (
        BlockLeastSquaresEstimator(block_size=16, num_iters=2, lam=1e-3)
        .fit(X, Y)
        .apply_batch(X)
    )
    config.solver_storage_dtype = "bfloat16"
    try:
        got = (
            BlockLeastSquaresEstimator(block_size=16, num_iters=2, lam=1e-3)
            .fit(X, Y)
            .apply_batch(X)
        )
    finally:
        config.solver_storage_dtype = None
    ref = np.asarray(ref)
    got = np.asarray(got)
    assert np.linalg.norm(got - ref) / np.linalg.norm(ref) < 5e-2
