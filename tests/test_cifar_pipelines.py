"""CIFAR pipeline integration tests on synthetic data (SURVEY.md §4:
whole-pipeline accuracy floors on tiny datasets)."""

import numpy as np

from keystone_tpu.loaders.cifar import CifarLoader
from keystone_tpu.pipelines.images.linear_pixels import (
    LinearPixelsConfig,
    run as run_linear,
)
from keystone_tpu.pipelines.images.random_patch_cifar import (
    RandomPatchCifarConfig,
    run as run_patch,
)


def test_cifar_synthetic_loader():
    train, test = CifarLoader.synthetic(n=256, seed=1)
    assert train.data.shape == (256, 32, 32, 3)
    assert train.data.min() >= 0.0 and train.data.max() <= 1.0
    assert test.labels.dtype == np.int32


def test_linear_pixels_beats_chance():
    out = run_linear(LinearPixelsConfig(synthetic_n=1024, lam=1.0))
    assert out["test_accuracy"] > 0.5, out["summary"]


def test_random_patch_cifar_end_to_end():
    conf = RandomPatchCifarConfig(
        synthetic_n=768,
        num_filters=64,
        patch_sample=2000,
        num_iters=2,
        lam=5.0,
    )
    out = run_patch(conf)
    # Synthetic classes are color-pattern-separable; the conv featurizer
    # should get well past the linear-pixel floor.
    assert out["test_accuracy"] > 0.8, out["summary"]
