"""Core workflow tests: composition algebra, laziness, fitting, fusion, memo.

Mirrors the reference's workflow suites (PipelineSuite, EstimatorSuite,
TransformerSuite [unverified paths]).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from keystone_tpu.workflow import (
    Estimator,
    LabelEstimator,
    Pipeline,
    PipelineEnv,
    Transformer,
)
from keystone_tpu.workflow.operators import TransformerOperator
from keystone_tpu.workflow.pipeline import FusedTransformer


class Plus(Transformer):
    def __init__(self, c):
        self.c = c

    def apply_batch(self, X):
        return X + self.c


class Times(Transformer):
    def __init__(self, c):
        self.c = c

    def apply_batch(self, X):
        return X * self.c


class MeanShift(Estimator):
    """Fits the mean of the data; transformer subtracts it."""

    def __init__(self):
        self.fit_count = 0

    def fit(self, data):
        self.fit_count += 1
        return Plus(-jnp.mean(jnp.asarray(data), axis=0))


class ScaleToLabels(LabelEstimator):
    def __init__(self):
        self.fit_count = 0

    def fit(self, data, labels):
        self.fit_count += 1
        scale = jnp.mean(jnp.asarray(labels)) / jnp.mean(jnp.asarray(data))
        return Times(scale)


def test_transformer_batch_and_datum():
    t = Plus(2.0)
    X = np.arange(6.0).reshape(3, 2)
    np.testing.assert_allclose(t(X), X + 2.0)
    np.testing.assert_allclose(t.apply(np.ones(2)), np.ones(2) + 2.0)


def test_and_then_composition():
    p = Plus(1.0).and_then(Times(3.0)).and_then(Plus(-2.0))
    X = np.ones((4, 2))
    out = p(X).get()
    np.testing.assert_allclose(out, (1.0 + 1.0) * 3.0 - 2.0)


def test_pipeline_is_lazy():
    calls = []

    class Probe(Transformer):
        jittable = False

        def apply_batch(self, X):
            calls.append(1)
            return X

    p = Probe().to_pipeline()
    ds = p(np.ones((2, 2)))
    assert calls == []
    ds.get()
    assert calls == [1]
    ds.get()  # memoized
    assert calls == [1]


def test_estimator_with_data():
    est = MeanShift()
    X = np.array([[1.0, 2.0], [3.0, 4.0]])
    p = est.with_data(X)
    out = p(X).get()
    np.testing.assert_allclose(out, X - X.mean(axis=0), atol=1e-6)
    assert est.fit_count == 1


def test_to_dot_export():
    est = MeanShift()
    X = np.array([[1.0, 2.0], [3.0, 4.0]])
    p = est.with_data(X)
    dot = p.to_dot()
    assert dot.startswith("digraph pipeline {") and dot.endswith("}")
    assert "MeanShift.fit" in dot and "Delegating" in dot
    assert "input" in dot  # the free source renders as a diamond
    assert "->" in dot


def test_fit_cache_across_applications():
    est = MeanShift()
    X = np.array([[1.0, 2.0], [3.0, 4.0]])
    p = est.with_data(X)
    p(X).get()
    p(X * 2).get()
    assert est.fit_count == 1  # fitted-prefix reuse


def test_label_estimator():
    est = ScaleToLabels()
    X = np.full((4, 1), 2.0)
    y = np.full((4, 1), 6.0)
    p = est.with_data(X, y)
    out = p(np.ones((2, 1))).get()
    np.testing.assert_allclose(out, 3.0 * np.ones((2, 1)), atol=1e-5)


def test_and_then_estimator_fits_on_pipeline_output():
    # pipeline.and_then(est, data): estimator sees pipeline(data)
    est = MeanShift()
    X = np.array([[0.0], [2.0]])  # after Plus(1): mean = 2
    p = Plus(1.0).and_then(est, X)
    out = p(np.array([[5.0]])).get()
    np.testing.assert_allclose(out, np.array([[4.0]]), atol=1e-6)  # 5+1-2


def test_gather_concatenates_branches():
    b1 = Plus(1.0).to_pipeline()
    b2 = Times(2.0).to_pipeline()
    p = Pipeline.gather([b1, b2])
    X = np.ones((3, 2))
    out = p(X).get()
    assert out.shape == (3, 4)
    np.testing.assert_allclose(out[:, :2], 2.0)
    np.testing.assert_allclose(out[:, 2:], 2.0)


def test_gather_shares_common_prefix_computation():
    calls = []

    class Probe(Transformer):
        jittable = False

        def apply_batch(self, X):
            calls.append(1)
            return X

    base = Probe().to_pipeline()
    p = Pipeline.gather([base.and_then(Plus(1.0)), base.and_then(Times(2.0))])
    p(np.ones((2, 2))).get()
    # Structural-hash memo dedups the copied Probe nodes within one execution.
    assert calls == [1]


def test_fit_returns_transformer_only_pipeline():
    est = MeanShift()
    X = np.array([[1.0], [3.0]])
    p = Plus(0.0).and_then(est, X)
    fitted = p.fit()
    ts = fitted.transformers()
    assert all(isinstance(t, Transformer) for t in ts)
    out = fitted(np.array([[2.0]])).get()
    np.testing.assert_allclose(out, np.array([[0.0]]), atol=1e-6)


def test_chain_fusion_rule():
    p = Plus(1.0).and_then(Times(3.0)).and_then(Plus(-2.0))
    env = PipelineEnv.get()
    ds = p(np.ones((2, 2)))
    g = env.optimizer.execute(ds.graph, [ds.sink])
    t_ops = [
        op for op in g.operators.values() if isinstance(op, TransformerOperator)
    ]
    assert len(t_ops) == 1
    assert isinstance(t_ops[0].transformer, FusedTransformer)
    assert len(t_ops[0].transformer.stages) == 3
    np.testing.assert_allclose(ds.get(), 4.0 * np.ones((2, 2)))


def test_fusion_preserves_prefix_hash():
    est = MeanShift()
    X = np.ones((4, 2))
    feats = Plus(1.0).and_then(Times(2.0))
    p = feats.and_then(est, X)
    p(X).get()
    assert est.fit_count == 1
    # Re-applying through a different graph copy must not refit.
    p(X * 3).get()
    assert est.fit_count == 1


def test_apply_datum():
    p = Plus(1.0).and_then(Times(2.0))
    out = p.apply_datum(np.array([1.0, 2.0]))
    np.testing.assert_allclose(out, np.array([4.0, 6.0]))


def test_host_transformer_on_lists():
    class Upper(Transformer):
        jittable = False

        def apply(self, x):
            return x.upper()

    p = Upper().to_pipeline()
    assert p(["ab", "cd"]).get() == ["AB", "CD"]


def test_fusion_is_hash_invariant():
    # The same logical prefix must hash equal whether or not it got fused.
    from keystone_tpu.workflow.graph import structural_hash
    from keystone_tpu.workflow import PipelineEnv

    t1, t2 = Plus(1.0), Times(2.0)
    X = np.ones((2, 2))
    p = t1.and_then(t2)
    ds = p(X)
    env = PipelineEnv.get()
    fused_g = env.optimizer.execute(ds.graph, [ds.sink])

    def no_src(s):
        raise AssertionError

    h_unfused = structural_hash(ds.graph, ds.sink, no_src)
    # sink id survives optimization (merge rule preserves targets)
    h_fused = structural_hash(fused_g, ds.sink, no_src)
    assert h_unfused == h_fused


def test_fitted_pipeline_drops_training_data():
    from keystone_tpu.workflow.operators import DatasetOperator, EstimatorOperator

    est = MeanShift()
    X = np.ones((8, 2))
    p = Plus(0.0).and_then(est, X)
    fitted = p.fit()
    ops = list(fitted.graph.operators.values())
    assert not any(isinstance(o, (DatasetOperator, EstimatorOperator)) for o in ops)


def test_fit_cache_pins_objects_and_evicts_with_estimator():
    import gc

    from keystone_tpu.workflow import PipelineEnv

    est = MeanShift()
    X = np.ones((4, 2))
    est.with_data(X)(X).get()
    env = PipelineEnv.get()
    (entry,) = env.fit_cache.values()
    _fitted, pins, keeper = entry
    # Data is pinned (id-reuse safety); the estimator itself is held weakly.
    assert any(o is X for o in pins)
    assert keeper() is est
    # Dropping the estimator evicts the entry (and frees the pinned data).
    del est, entry, keeper, _fitted
    gc.collect()
    assert env.fit_cache == {}


def test_repeated_apply_reuses_fused_jit():
    p = Plus(1.0).and_then(Times(2.0)).and_then(Plus(0.5))
    X = np.ones((2, 2))
    fused_objs = set()
    from keystone_tpu.workflow import PipelineEnv
    from keystone_tpu.workflow.operators import TransformerOperator

    for _ in range(3):
        ds = p(X)
        g = PipelineEnv.get().optimizer.execute(ds.graph, [ds.sink])
        for op in g.operators.values():
            if isinstance(op, TransformerOperator):
                fused_objs.add(id(op.transformer))
        ds.get()
    # Same FusedTransformer object across graph copies => one jit cache.
    assert len(fused_objs) == 1


def test_apply_datum_respects_batch_contract():
    class RowNormalize(Transformer):
        def apply_batch(self, X):
            return X / X.sum(axis=1, keepdims=True)

    out = RowNormalize().to_pipeline().apply_datum(np.array([1.0, 3.0]))
    np.testing.assert_allclose(out, [0.25, 0.75])


def test_estimator_with_labels_rejected():
    est = MeanShift()
    with pytest.raises(TypeError, match="LabelEstimator"):
        Plus(1.0).and_then(est, np.ones((2, 1)), np.ones((2, 1)))


def test_dataset_sharding_respects_placement_and_dtype():
    import jax
    import jax.numpy as jnp

    from keystone_tpu.utils.mesh import replicated_sharding
    from keystone_tpu.workflow.operators import DatasetOperator

    # Host numpy numeric batch: sharded over the mesh.
    X = np.ones((64, 4), dtype=np.float32)
    out = DatasetOperator(X).execute([])
    assert len(out.sharding.device_set) == len(jax.devices())
    # Explicitly replicated device array: placement preserved.
    rep = jax.device_put(jnp.ones((64, 4)), replicated_sharding())
    out2 = DatasetOperator(rep).execute([])
    assert out2.sharding == rep.sharding
    # String array: untouched (host transformer input).
    s = np.asarray(["a"] * 64)
    assert DatasetOperator(s).execute([]) is s
    # Non-divisible rows: placement DEFERRED to the fused chain's
    # mask-pad path (jax refuses an uneven device_put) — the operator
    # hands the host batch through unchanged and counts the deferral.
    odd = np.ones((65, 4), dtype=np.float32)
    assert DatasetOperator(odd).execute([]) is odd


def test_stable_signatures_dedupe_rebuilt_pipelines():
    from keystone_tpu.nodes.stats import PaddedFFT, RandomSignNode
    from keystone_tpu.nodes.util import Cacher

    calls = []

    class CountingRectifier(Transformer):
        """Stable-signature host stage so recomputation is observable."""

        jittable = False

        def signature(self):
            return self.stable_signature()

        def apply_batch(self, X):
            calls.append(1)
            return np.maximum(np.asarray(X), 0.0)

    X = np.random.default_rng(0).normal(size=(64, 32)).astype(np.float32)

    def build():
        # Two separately-constructed but identical featurizers.
        return (
            RandomSignNode.create(32, seed=5)
            .and_then(PaddedFFT())
            .and_then(CountingRectifier())
            .and_then(Cacher())
        )

    a, b = build(), build()
    out_a = np.asarray(a(X).get())
    out_b = np.asarray(b(X).get())  # session-cache hit via stable signatures
    np.testing.assert_array_equal(out_a, out_b)
    from keystone_tpu.workflow import PipelineEnv

    assert len(PipelineEnv.get().node_cache) == 1  # one shared entry
    # The cache hit must CUT the second execution: upstream never reruns.
    assert calls == [1]


def test_stable_signature_subclass_never_collides():
    from keystone_tpu.nodes.util import Identity

    class Shifted(Identity):
        def apply_batch(self, X):
            return X + 1.0

    assert Identity().signature() != Shifted().signature()


class TestDeepGraphNodeOptimization:
    """NodeOptimizationRule must cost-model-dispatch estimators whose inputs
    are transformer subgraphs, not just directly-attached datasets, by
    running the sampling profiler over the prefix (the reference profiles
    sampled prefixes for stats anywhere in the DAG — SURVEY.md §3.5)."""

    def _deep_pipeline(self, n=131072, d=48, k=8):
        from keystone_tpu.nodes.learning.least_squares import LeastSquaresEstimator

        rng = np.random.default_rng(0)
        X = rng.normal(size=(n, d)).astype(np.float32)
        Y = rng.normal(size=(n, k)).astype(np.float32)
        feats = Plus(1.0).and_then(Times(2.0))
        est = LeastSquaresEstimator(lam=1e-3)
        p = est.with_data(feats(X), Y)
        return p, est

    def test_estimator_behind_featurizer_chain_is_dispatched(self):
        from keystone_tpu.nodes.learning.least_squares import LeastSquaresEstimator
        from keystone_tpu.nodes.learning.linear_mapper import LinearMapEstimator
        from keystone_tpu.workflow.operators import EstimatorOperator
        from keystone_tpu.workflow.rules import NodeOptimizationRule

        p, est = self._deep_pipeline()
        g = NodeOptimizationRule().apply(p.graph, [p.sink])
        concrete = [
            op.estimator
            for op in g.operators.values()
            if isinstance(op, EstimatorOperator)
            and not isinstance(op.estimator, LeastSquaresEstimator)
        ]
        # n=131072 x d=48 exceeds the tiny-problem bar, so the cost model
        # must choose normal equations. Had the rule used the RAW 64-row
        # sample shape instead of the row-scale-corrected one, it would
        # have picked the local solver — this asserts the scaling too.
        assert len(concrete) == 1
        assert isinstance(concrete[0], LinearMapEstimator)
        assert est.last_choice.name == "normal"

    def test_labels_behind_transformer_resolve_k(self):
        from keystone_tpu.nodes.learning.least_squares import LeastSquaresEstimator
        from keystone_tpu.nodes.util.labels import ClassLabelIndicators
        from keystone_tpu.workflow.operators import EstimatorOperator
        from keystone_tpu.workflow.rules import NodeOptimizationRule

        rng = np.random.default_rng(0)
        n, d = 131072, 48
        X = rng.normal(size=(n, d)).astype(np.float32)
        y_int = rng.integers(0, 10, size=n)
        est = LeastSquaresEstimator(lam=1e-3)
        p = est.with_data(X, ClassLabelIndicators(10).to_pipeline()(y_int))
        g = NodeOptimizationRule().apply(p.graph, [p.sink])
        replaced = [
            op.estimator
            for op in g.operators.values()
            if isinstance(op, EstimatorOperator)
            and not isinstance(op.estimator, LeastSquaresEstimator)
        ]
        # Without the sampled prefix the one-hot width k would be unknown
        # (labels_shape=None -> fit-time dispatch, no replacement).
        assert len(replaced) == 1

    def test_deep_graph_replacement_memoized_across_passes(self):
        from keystone_tpu.nodes.learning.least_squares import LeastSquaresEstimator
        from keystone_tpu.workflow.operators import EstimatorOperator
        from keystone_tpu.workflow.rules import NodeOptimizationRule

        p, _est = self._deep_pipeline()
        rule = NodeOptimizationRule()
        g1 = rule.apply(p.graph, [p.sink])
        g2 = rule.apply(p.graph, [p.sink])

        def concrete(g):
            return [
                op.estimator
                for op in g.operators.values()
                if isinstance(op, EstimatorOperator)
                and not isinstance(op.estimator, LeastSquaresEstimator)
            ]

        c1, c2 = concrete(g1), concrete(g2)
        assert c1 and c2 and c1[0] is c2[0]

    def test_sampled_prefix_fit_does_not_mutate_user_estimator(self):
        """The sample run fits a COPY of upstream estimators: a profiling
        probe must not leak fitted state into user-held objects."""
        from keystone_tpu.nodes.learning.least_squares import LeastSquaresEstimator
        from keystone_tpu.workflow.rules import NodeOptimizationRule

        rng = np.random.default_rng(0)
        X = rng.normal(size=(131072, 48)).astype(np.float32)
        Y = rng.normal(size=(131072, 8)).astype(np.float32)
        upstream = MeanShift()
        ls = LeastSquaresEstimator(lam=1e-3)
        p = ls.with_data(upstream.with_data(X)(X), Y)
        NodeOptimizationRule().apply(p.graph, [p.sink])
        assert upstream.fit_count == 0  # probe fit ran on a copy

    def test_shape_memo_skips_resampling_across_passes(self):
        from keystone_tpu.nodes.learning.least_squares import LeastSquaresEstimator
        from keystone_tpu.workflow.rules import NodeOptimizationRule

        calls = []

        class Probe(Transformer):
            def signature(self):
                # Content-stable: the shape memo only serves digestable
                # prefixes (id-based ones are recomputed each pass).
                return self.stable_signature()

            def apply_batch(self, X):
                calls.append(len(X))
                return X

        rng = np.random.default_rng(0)
        X = rng.normal(size=(131072, 48)).astype(np.float32)
        Y = rng.normal(size=(131072, 8)).astype(np.float32)
        est = LeastSquaresEstimator(lam=1e-3)
        p = est.with_data(Probe().to_pipeline()(X), Y)
        rule = NodeOptimizationRule()
        rule.apply(p.graph, [p.sink])
        first = len(calls)
        assert first >= 1
        rule.apply(p.graph, [p.sink])  # memo hit: no re-execution
        assert len(calls) == first

    def test_unbound_source_prefix_skips_sampling(self):
        """An optimizable estimator whose data prefix reaches an unbound
        source can never be sampled or dispatched: the rule must skip it
        without paying a sample run (and without crashing)."""
        from keystone_tpu.nodes.learning.least_squares import LeastSquaresEstimator
        from keystone_tpu.workflow.operators import EstimatorOperator
        from keystone_tpu.workflow.rules import NodeOptimizationRule
        from keystone_tpu.workflow.graph import Graph, fresh_source_id
        from keystone_tpu.workflow.operators import (
            DatasetOperator,
            TransformerOperator,
        )

        rng = np.random.default_rng(0)
        Y = rng.normal(size=(256, 4)).astype(np.float32)
        g = Graph()
        src = fresh_source_id()
        g, t_id = g.add(TransformerOperator(Plus(1.0)), [src])
        g, y_id = g.add(DatasetOperator(Y), [])
        est = LeastSquaresEstimator(lam=1e-3)
        g, e_id = g.add(EstimatorOperator(est), [t_id, y_id])
        out = NodeOptimizationRule().apply(g, [e_id])
        assert isinstance(out.operators[e_id].estimator, LeastSquaresEstimator)

    def test_failing_sample_prefix_falls_back_to_fit_time_dispatch(self):
        """A prefix that can't execute on a 64-row sample must not crash
        optimization — the estimator keeps fit-time dispatch."""
        from keystone_tpu.nodes.learning.least_squares import LeastSquaresEstimator
        from keystone_tpu.workflow.operators import EstimatorOperator
        from keystone_tpu.workflow.rules import NodeOptimizationRule

        class MinBatch(Transformer):
            jittable = False

            def apply_batch(self, X):
                assert len(X) >= 1000, "needs full batch"
                return X

        rng = np.random.default_rng(0)
        X = rng.normal(size=(4096, 8)).astype(np.float32)
        Y = rng.normal(size=(4096, 2)).astype(np.float32)
        est = LeastSquaresEstimator(lam=1e-3)
        p = est.with_data(MinBatch().to_pipeline()(X), Y)
        g = NodeOptimizationRule().apply(p.graph, [p.sink])  # must not raise
        kept = [
            op.estimator
            for op in g.operators.values()
            if isinstance(op, EstimatorOperator)
        ]
        assert any(isinstance(e, LeastSquaresEstimator) for e in kept)

    def test_row_changing_prefix_defers_to_fit_time(self):
        """A row-aggregating prefix makes scaled-n meaningless: the rule
        must NOT dispatch from a fabricated n."""
        from keystone_tpu.nodes.learning.least_squares import LeastSquaresEstimator
        from keystone_tpu.workflow.operators import EstimatorOperator
        from keystone_tpu.workflow.rules import NodeOptimizationRule

        class Head32(Transformer):
            jittable = False

            def signature(self):
                return self.stable_signature()

            def apply_batch(self, X):
                return X[:32]  # row-changing: fixed-size head

        rng = np.random.default_rng(0)
        X = rng.normal(size=(131072, 48)).astype(np.float32)
        Y = rng.normal(size=(131072, 8)).astype(np.float32)
        est = LeastSquaresEstimator(lam=1e-3)
        p = est.with_data(Head32().to_pipeline()(X), Y)
        g = NodeOptimizationRule().apply(p.graph, [p.sink])
        kept = [
            op.estimator
            for op in g.operators.values()
            if isinstance(op, EstimatorOperator)
        ]
        # real fit sees n=32; a scaled n=65536 would have picked "normal".
        assert all(isinstance(e, LeastSquaresEstimator) for e in kept)

    def test_failed_sample_run_is_not_memoized(self):
        """A transient sample failure must not permanently disable
        optimize-time dispatch for that prefix."""
        from keystone_tpu.nodes.learning.least_squares import LeastSquaresEstimator
        from keystone_tpu.nodes.learning.linear_mapper import LinearMapEstimator
        from keystone_tpu.workflow.operators import EstimatorOperator
        from keystone_tpu.workflow.rules import NodeOptimizationRule

        fail = {"on": True}

        class Flaky(Transformer):
            jittable = False

            def signature(self):
                return self.stable_signature()

            def apply_batch(self, X):
                if fail["on"]:
                    raise RuntimeError("transient")
                return X

        rng = np.random.default_rng(0)
        X = rng.normal(size=(131072, 48)).astype(np.float32)
        Y = rng.normal(size=(131072, 8)).astype(np.float32)
        est = LeastSquaresEstimator(lam=1e-3)
        p = est.with_data(Flaky().to_pipeline()(X), Y)
        rule = NodeOptimizationRule()
        g1 = rule.apply(p.graph, [p.sink])  # fails -> fit-time dispatch kept
        assert all(
            isinstance(op.estimator, LeastSquaresEstimator)
            for op in g1.operators.values()
            if isinstance(op, EstimatorOperator)
        )
        fail["on"] = False
        g2 = rule.apply(p.graph, [p.sink])  # retry succeeds -> dispatched
        assert any(
            isinstance(op.estimator, LinearMapEstimator)
            for op in g2.operators.values()
            if isinstance(op, EstimatorOperator)
        )
