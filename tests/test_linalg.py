"""Distributed linalg vs NumPy oracles on the 8-device CPU mesh.

Mirrors the reference's solver test strategy: small fixed-seed systems
checked against direct solves (SURVEY.md §4).
"""

import numpy as np
import pytest

from keystone_tpu.linalg import (
    RowMatrix,
    block_coordinate_descent,
    solve_least_squares_normal,
    solve_least_squares_tsqr,
    tsqr_r,
)
from keystone_tpu.linalg.bcd import assemble_blocks


def _problem(rng, n=200, d=24, k=3):
    A = rng.normal(size=(n, d)).astype(np.float32)
    W_true = rng.normal(size=(d, k)).astype(np.float32)
    B = A @ W_true + 0.01 * rng.normal(size=(n, k)).astype(np.float32)
    return A, B, W_true


def _ridge_oracle(A, B, lam):
    d = A.shape[1]
    return np.linalg.solve(
        A.astype(np.float64).T @ A.astype(np.float64) + lam * np.eye(d),
        A.astype(np.float64).T @ B.astype(np.float64),
    )


def test_from_array_pads_and_collects(rng):
    A = rng.normal(size=(13, 4)).astype(np.float32)
    M = RowMatrix.from_array(A)
    assert M.padded_rows % M.num_shards == 0
    assert M.shape == (13, 4)
    np.testing.assert_allclose(M.collect(), A)


def test_gram_matches_numpy(rng):
    A = rng.normal(size=(100, 8)).astype(np.float32)
    M = RowMatrix.from_array(A)
    np.testing.assert_allclose(M.gram(), A.T @ A, rtol=1e-5, atol=1e-4)


def test_atb_matches_numpy(rng):
    A = rng.normal(size=(57, 6)).astype(np.float32)
    B = rng.normal(size=(57, 3)).astype(np.float32)
    Ma, Mb = RowMatrix.from_array(A), RowMatrix.from_array(B)
    np.testing.assert_allclose(Ma.atb(Mb), A.T @ B, rtol=1e-5, atol=1e-4)


def test_matmul_row_sharded(rng):
    A = rng.normal(size=(30, 5)).astype(np.float32)
    W = rng.normal(size=(5, 2)).astype(np.float32)
    out = RowMatrix.from_array(A).matmul(W)
    np.testing.assert_allclose(out.collect(), A @ W, rtol=1e-5, atol=1e-5)


def test_tsqr_r_reproduces_gram(rng):
    # R is unique up to signs; RᵀR must equal AᵀA.
    A = rng.normal(size=(160, 12)).astype(np.float32)
    R = np.asarray(tsqr_r(RowMatrix.from_array(A)))
    np.testing.assert_allclose(R.T @ R, A.T @ A, rtol=1e-4, atol=1e-3)


def test_tsqr_r_short_shards(rng):
    # Local shard rows (24/8 = 3) < d = 5 exercises the R padding path.
    A = rng.normal(size=(24, 5)).astype(np.float32)
    R = np.asarray(tsqr_r(RowMatrix.from_array(A)))
    np.testing.assert_allclose(R.T @ R, A.T @ A, rtol=1e-4, atol=1e-3)


def test_normal_equations_solve(rng):
    A, B, _ = _problem(rng)
    lam = 0.1
    W = solve_least_squares_normal(
        RowMatrix.from_array(A), RowMatrix.from_array(B), lam
    )
    np.testing.assert_allclose(W, _ridge_oracle(A, B, lam), rtol=1e-3, atol=1e-3)


def test_tsqr_solve_matches_lstsq(rng):
    A, B, _ = _problem(rng)
    W = solve_least_squares_tsqr(RowMatrix.from_array(A), RowMatrix.from_array(B))
    oracle = np.linalg.lstsq(A.astype(np.float64), B.astype(np.float64), rcond=None)[0]
    np.testing.assert_allclose(W, oracle, rtol=1e-3, atol=1e-3)


def test_tsqr_solve_with_ridge(rng):
    A, B, _ = _problem(rng)
    lam = 0.5
    W = solve_least_squares_tsqr(
        RowMatrix.from_array(A), RowMatrix.from_array(B), lam
    )
    np.testing.assert_allclose(W, _ridge_oracle(A, B, lam), rtol=1e-3, atol=1e-3)


def test_bcd_single_block_equals_normal_equations(rng):
    A, B, _ = _problem(rng)
    lam = 0.2
    Ma, Mb = RowMatrix.from_array(A), RowMatrix.from_array(B)
    W_blocks, blocks = block_coordinate_descent(
        Ma, Mb, block_size=A.shape[1], num_iters=1, lam=lam
    )
    assert blocks == [(0, A.shape[1])]
    np.testing.assert_allclose(
        assemble_blocks(W_blocks),
        _ridge_oracle(A, B, lam),
        rtol=1e-3,
        atol=1e-3,
    )


def test_bcd_converges_to_direct_solution(rng):
    A, B, _ = _problem(rng, n=400, d=32)
    lam = 0.1
    W_blocks, blocks = block_coordinate_descent(
        RowMatrix.from_array(A),
        RowMatrix.from_array(B),
        block_size=8,
        num_iters=30,
        lam=lam,
    )
    W = np.asarray(assemble_blocks(W_blocks))
    oracle = _ridge_oracle(A, B, lam)
    np.testing.assert_allclose(W, oracle, rtol=2e-2, atol=2e-2)


def test_bcd_weighted_matches_weighted_oracle(rng):
    A, B, _ = _problem(rng)
    lam = 0.3
    w = rng.uniform(0.5, 2.0, size=A.shape[0]).astype(np.float32)
    W_blocks, blocks = block_coordinate_descent(
        RowMatrix.from_array(A),
        RowMatrix.from_array(B),
        block_size=A.shape[1],
        num_iters=1,
        lam=lam,
        row_weights=w,
    )
    Aw = A * w[:, None]
    d = A.shape[1]
    oracle = np.linalg.solve(
        Aw.astype(np.float64).T @ A.astype(np.float64) + lam * np.eye(d),
        Aw.astype(np.float64).T @ B.astype(np.float64),
    )
    np.testing.assert_allclose(
        assemble_blocks(W_blocks), oracle, rtol=1e-3, atol=1e-3
    )


def test_alignment_errors(rng):
    Ma = RowMatrix.from_array(rng.normal(size=(16, 3)))
    Mb = RowMatrix.from_array(rng.normal(size=(24, 3)))
    with pytest.raises(ValueError, match="share n"):
        Ma.atb(Mb)


def test_bcd_cached_grams_matches_uncached(rng):
    A, B, _ = _problem(rng, n=240, d=24)
    Ma, Mb = RowMatrix.from_array(A), RowMatrix.from_array(B)
    W_cached, blocks = block_coordinate_descent(
        Ma, Mb, block_size=8, num_iters=5, lam=0.2, cache_grams=True
    )
    W_plain, _ = block_coordinate_descent(
        Ma, Mb, block_size=8, num_iters=5, lam=0.2, cache_grams=False
    )
    from keystone_tpu.linalg.bcd import assemble_blocks

    np.testing.assert_allclose(
        assemble_blocks(W_cached),
        assemble_blocks(W_plain),
        rtol=1e-4,
        atol=1e-4,
    )


def test_bcd_batched_factor_ragged_and_chunked(rng):
    """Batched factor phase: ragged tail block + factor_batch smaller than
    the block count must still match the uncached solve digit-for-digit."""
    from keystone_tpu.config import config

    A, B, _ = _problem(rng, d=26)  # blocks of 8 -> 3 equal + ragged 2-wide
    Ma, Mb = RowMatrix.from_array(A), RowMatrix.from_array(B)
    old = config.factor_batch
    config.factor_batch = 2  # forces two batched chunks + tail path
    try:
        W_c, _ = block_coordinate_descent(
            Ma, Mb, block_size=8, num_iters=4, lam=0.2, cache_grams=True
        )
    finally:
        config.factor_batch = old
    W_p, _ = block_coordinate_descent(
        Ma, Mb, block_size=8, num_iters=4, lam=0.2, cache_grams=False
    )
    np.testing.assert_allclose(
        assemble_blocks(W_c), assemble_blocks(W_p), rtol=1e-4, atol=1e-4
    )


def test_spd_inv_rhs_chunked_matches_full(rng):
    """The column-chunked identity-RHS inverse (the v5e HBM fix for the
    unrolled trsm expansion) must equal the one-shot inverse — including a
    ragged final chunk and the batched leading axis."""
    import jax.numpy as jnp

    from keystone_tpu.linalg.bcd import _batched_spd_inv

    b = 13
    X = rng.normal(size=(3, b, b)).astype(np.float32)
    grams = X @ np.swapaxes(X, 1, 2) / b + 2.0 * np.eye(b, dtype=np.float32)
    full = np.asarray(_batched_spd_inv(jnp.asarray(grams)))
    chunked = np.asarray(_batched_spd_inv(jnp.asarray(grams), rhs_chunk=5))
    np.testing.assert_allclose(chunked, full, rtol=1e-5, atol=1e-5)
    oracle = np.linalg.inv(grams.astype(np.float64))
    np.testing.assert_allclose(chunked, oracle, rtol=1e-3, atol=1e-3)
    # Unbatched path with an exact-multiple chunk.
    one = np.asarray(_batched_spd_inv(jnp.asarray(grams[0]), rhs_chunk=13))
    np.testing.assert_allclose(one, oracle[0], rtol=1e-3, atol=1e-3)
    two = np.asarray(_batched_spd_inv(jnp.asarray(grams[0]), rhs_chunk=4))
    np.testing.assert_allclose(two, oracle[0], rtol=1e-3, atol=1e-3)


def test_bcd_cached_grams_weighted(rng):
    A, B, _ = _problem(rng)
    w = rng.uniform(0.5, 2.0, size=A.shape[0]).astype(np.float32)
    Ma, Mb = RowMatrix.from_array(A), RowMatrix.from_array(B)
    kwargs = dict(block_size=8, num_iters=3, lam=0.2, row_weights=w)
    W_c, blocks = block_coordinate_descent(Ma, Mb, cache_grams=True, **kwargs)
    W_p, _ = block_coordinate_descent(Ma, Mb, cache_grams=False, **kwargs)
    from keystone_tpu.linalg.bcd import assemble_blocks

    np.testing.assert_allclose(
        assemble_blocks(W_c), assemble_blocks(W_p),
        rtol=1e-4, atol=1e-4,
    )


def test_streamed_bcd_matches_device_resident(rng):
    from keystone_tpu.linalg import block_coordinate_descent_streamed

    A, B, _ = _problem(rng, n=240, d=32)
    Mb = RowMatrix.from_array(B)
    W_s, blocks = block_coordinate_descent_streamed(
        A, Mb, block_size=8, num_iters=4, lam=0.2
    )
    Ma = RowMatrix.from_array(A)
    W_d, _ = block_coordinate_descent(
        Ma, RowMatrix.from_array(B), block_size=8, num_iters=4, lam=0.2
    )
    np.testing.assert_allclose(
        assemble_blocks(W_s), assemble_blocks(W_d),
        rtol=1e-4, atol=1e-4,
    )


def test_streamed_bcd_weighted_and_row_mismatch(rng):
    from keystone_tpu.linalg import block_coordinate_descent_streamed

    A, B, _ = _problem(rng)
    w = rng.uniform(0.5, 2.0, size=A.shape[0]).astype(np.float32)
    Mb = RowMatrix.from_array(B)
    W_s, blocks = block_coordinate_descent_streamed(
        A, Mb, block_size=8, num_iters=2, lam=0.1, row_weights=w
    )
    Ma = RowMatrix.from_array(A)
    W_d, _ = block_coordinate_descent(
        Ma, RowMatrix.from_array(B), block_size=8, num_iters=2, lam=0.1,
        row_weights=w,
    )
    np.testing.assert_allclose(
        assemble_blocks(W_s), assemble_blocks(W_d),
        rtol=1e-4, atol=1e-4,
    )
    with pytest.raises(ValueError, match="must match B rows"):
        block_coordinate_descent_streamed(A[:10], Mb, 8, 1)


def test_normal_equations_refinement_reduces_system_residual(rng):
    # Refinement corrects the factorization/solve error of the f32 Cholesky
    # (it cannot fix f32 gram *formation* error, the other error source):
    # the residual of the regularized normal-equation system must not grow
    # and the solution must stay at the oracle within f32 tolerances.
    n, d = 400, 24
    U = rng.normal(size=(n, d)).astype(np.float32)
    scales = np.logspace(0, -3.5, d).astype(np.float32)
    A = U * scales
    B = rng.normal(size=(n, 2)).astype(np.float32)
    lam = 1e-6
    Ma, Mb = RowMatrix.from_array(A), RowMatrix.from_array(B)
    reg = (
        np.asarray(Ma.gram(), dtype=np.float64) + lam * np.eye(d)
    )
    atb = np.asarray(Ma.atb(Mb), dtype=np.float64)

    def sys_resid(w):
        return np.linalg.norm(reg @ w.astype(np.float64) - atb)

    w0 = np.asarray(solve_least_squares_normal(Ma, Mb, lam, refine_steps=0))
    w2 = np.asarray(solve_least_squares_normal(Ma, Mb, lam, refine_steps=2))
    assert sys_resid(w2) <= sys_resid(w0) * 1.5
    oracle = _ridge_oracle(A, B, lam)
    np.testing.assert_allclose(
        w2, oracle, rtol=1e-3, atol=1e-3
    )


def test_streamed_bcd_checkpoint_resume(rng, tmp_path):
    from keystone_tpu.linalg import block_coordinate_descent_streamed

    A, B, _ = _problem(rng, n=160, d=16)
    Mb = RowMatrix.from_array(B)
    ck = str(tmp_path / "sbcd")
    W_ref, blocks = block_coordinate_descent_streamed(A, Mb, 8, 4, lam=0.1)
    block_coordinate_descent_streamed(A, Mb, 8, 2, lam=0.1, checkpoint_dir=ck)
    W_res, _ = block_coordinate_descent_streamed(
        A, Mb, 8, 4, lam=0.1, checkpoint_dir=ck
    )
    np.testing.assert_allclose(
        assemble_blocks(W_res), assemble_blocks(W_ref),
        rtol=1e-4, atol=1e-4,
    )


def test_chunked_normal_equations_matches_full_solve(rng):
    from keystone_tpu.linalg import solve_least_squares_chunked
    from keystone_tpu.loaders.stream import BatchIterator

    A, B, _ = _problem(rng, n=500, d=16)
    lam = 0.2
    batches = BatchIterator.from_arrays(A, B, batch_rows=128)
    W = np.asarray(solve_least_squares_chunked(batches, lam=lam))
    np.testing.assert_allclose(W, _ridge_oracle(A, B, lam), rtol=1e-3, atol=1e-3)
    with pytest.raises(ValueError, match="empty"):
        solve_least_squares_chunked(iter([]), lam=lam)


def test_batch_iterator_csv_and_map(rng, tmp_path):
    from keystone_tpu.loaders.stream import BatchIterator

    X = rng.normal(size=(10, 3)).astype(np.float32)
    y = rng.integers(0, 2, 10)
    path = tmp_path / "d.csv"
    with open(path, "w") as f:
        for i in range(10):
            f.write(",".join([str(y[i])] + [f"{v:.6f}" for v in X[i]]) + "\n")
    it = BatchIterator.from_csv(str(path), label_col=0, batch_rows=4)
    chunks = list(it)
    assert [c[0].shape[0] for c in chunks] == [4, 4, 2]
    np.testing.assert_allclose(np.concatenate([c[0] for c in chunks]), X, atol=1e-5)
    np.testing.assert_array_equal(np.concatenate([c[1] for c in chunks]), y)
    doubled = list(it.map_batches(lambda b: b * 2))
    np.testing.assert_allclose(doubled[0][0], chunks[0][0] * 2, atol=1e-6)
    # Re-iterable (a second pass yields the same data).
    assert len(list(it)) == 3


def test_checkpoint_resumes_across_device_and_streamed_paths(rng, tmp_path):
    # Fingerprints must agree between the two paths so a solve checkpointed
    # on one can resume on the other (n chosen NOT divisible by 8 shards so
    # the padded last row differs from the logical last row).
    from keystone_tpu.linalg import block_coordinate_descent_streamed

    A, B, _ = _problem(rng, n=150, d=16)
    ck = str(tmp_path / "xpath")
    Ma, Mb = RowMatrix.from_array(A), RowMatrix.from_array(B)
    W_ref, blocks = block_coordinate_descent(Ma, Mb, 8, 4, lam=0.1)
    block_coordinate_descent(Ma, Mb, 8, 2, lam=0.1, checkpoint_dir=ck)
    W_res, _ = block_coordinate_descent_streamed(
        A, RowMatrix.from_array(B), 8, 4, lam=0.1, checkpoint_dir=ck
    )
    np.testing.assert_allclose(
        assemble_blocks(W_res), assemble_blocks(W_ref),
        rtol=1e-4, atol=1e-4,
    )


def test_gram_and_atb_fused(rng):
    A = rng.normal(size=(90, 7)).astype(np.float32)
    B = rng.normal(size=(90, 2)).astype(np.float32)
    Ma, Mb = RowMatrix.from_array(A), RowMatrix.from_array(B)
    g, ab = Ma.gram_and_atb(Mb)
    np.testing.assert_allclose(g, A.T @ A, rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(ab, A.T @ B, rtol=1e-5, atol=1e-4)


# -- fused scan path vs legacy per-block loop --------------------------------


def _both_paths(rng, **kwargs):
    from keystone_tpu.config import config

    A, B, _ = _problem(rng, n=240, d=32)
    Ma, Mb = RowMatrix.from_array(A), RowMatrix.from_array(B)
    prior = config.fused_epochs  # restore whatever the caller had set
    try:
        config.fused_epochs = None  # auto: fused (blocks tile d)
        W_f, blocks = block_coordinate_descent(Ma, Mb, **kwargs)
        config.fused_epochs = False
        W_l, _ = block_coordinate_descent(Ma, Mb, **kwargs)
    finally:
        config.fused_epochs = prior
    return A, B, W_f, W_l, blocks


def test_fused_matches_legacy_cached(rng):
    A, B, W_f, W_l, blocks = _both_paths(
        rng, block_size=8, num_iters=4, lam=0.15, cache_grams=True
    )
    assert len(blocks) == 4
    np.testing.assert_allclose(
        assemble_blocks(W_f), assemble_blocks(W_l), rtol=1e-4, atol=1e-4
    )
    # And both agree with the direct ridge oracle after enough epochs.
    W_oracle = _ridge_oracle(A, B, 0.15)
    np.testing.assert_allclose(
        assemble_blocks(W_f), W_oracle, rtol=5e-2, atol=5e-2
    )


def test_fused_matches_legacy_uncached(rng):
    _, _, W_f, W_l, _ = _both_paths(
        rng, block_size=16, num_iters=2, lam=0.3, cache_grams=False
    )
    np.testing.assert_allclose(
        assemble_blocks(W_f), assemble_blocks(W_l), rtol=1e-4, atol=1e-4
    )


def test_fused_matches_legacy_weighted(rng):
    from keystone_tpu.config import config

    A, B, _ = _problem(rng, n=160, d=16)
    w = (1.0 + rng.uniform(size=(160,))).astype(np.float32)
    Ma, Mb = RowMatrix.from_array(A), RowMatrix.from_array(B)
    kwargs = dict(block_size=8, num_iters=3, lam=0.2, row_weights=w)
    W_f, _ = block_coordinate_descent(Ma, Mb, **kwargs)
    config.fused_epochs = False
    try:
        W_l, _ = block_coordinate_descent(Ma, Mb, **kwargs)
    finally:
        config.fused_epochs = None
    np.testing.assert_allclose(
        assemble_blocks(W_f), assemble_blocks(W_l), rtol=1e-4, atol=1e-4
    )


def test_fused_single_block_and_ragged_fallback(rng):
    # nb=1 exercises the scan's degenerate length; ragged d falls back to
    # the legacy loop (same answer either way).
    A, B, _ = _problem(rng, n=120, d=20)
    Ma, Mb = RowMatrix.from_array(A), RowMatrix.from_array(B)
    W1, blocks1 = block_coordinate_descent(
        Ma, Mb, block_size=20, num_iters=2, lam=0.1
    )
    assert len(blocks1) == 1
    W2, blocks2 = block_coordinate_descent(
        Ma, Mb, block_size=12, num_iters=6, lam=0.1  # ragged: 12 + 8
    )
    assert [e - s for s, e in blocks2] == [12, 8]
    W_oracle = _ridge_oracle(A, B, 0.1)
    np.testing.assert_allclose(
        assemble_blocks(W2), W_oracle, rtol=5e-2, atol=5e-2
    )


def test_fused_checkpoint_resume_across_paths(rng, tmp_path):
    """A fused solve checkpoints per epoch with the same fingerprint as the
    legacy loop: 2 epochs fused + resume to 4 == 4 epochs straight (legacy),
    in either direction."""
    from keystone_tpu.config import config

    A, B, _ = _problem(rng, n=120, d=16)
    Ma, Mb = RowMatrix.from_array(A), RowMatrix.from_array(B)
    kwargs = dict(block_size=8, lam=0.1)
    W_ref, _ = block_coordinate_descent(Ma, Mb, num_iters=4, **kwargs)

    ck = str(tmp_path / "ck")
    block_coordinate_descent(
        Ma, Mb, num_iters=2, checkpoint_dir=ck, **kwargs
    )
    config.fused_epochs = False  # resume the fused checkpoint on the legacy path
    try:
        W_res, _ = block_coordinate_descent(
            Ma, Mb, num_iters=4, checkpoint_dir=ck, **kwargs
        )
    finally:
        config.fused_epochs = None
    np.testing.assert_allclose(
        assemble_blocks(W_res), assemble_blocks(W_ref), rtol=1e-4, atol=1e-4
    )


def test_fused_factor_chunking_matches_whole_batch(rng):
    """config.factor_batch bounds the fused factor phase's transient (and
    forces per-block factorization on request) without changing results."""
    from keystone_tpu.config import config

    A, B, _ = _problem(rng, n=200, d=32)
    Ma, Mb = RowMatrix.from_array(A), RowMatrix.from_array(B)
    kwargs = dict(block_size=8, num_iters=3, lam=0.2, cache_grams=True)
    W_whole, _ = block_coordinate_descent(Ma, Mb, **kwargs)  # auto chunk
    config.factor_batch = 2  # 4 blocks → two chunked factor programs
    try:
        W_chunk, _ = block_coordinate_descent(Ma, Mb, **kwargs)
    finally:
        config.factor_batch = None
    np.testing.assert_allclose(
        assemble_blocks(W_whole), assemble_blocks(W_chunk), rtol=1e-5, atol=1e-5
    )
