"""Solver progress telemetry (utils/flight_recorder.ProgressReporter).

Pins the ISSUE-9 streaming-solve observability contract:

1. Every streaming solve gets an always-on journey record in the solver
   flight recorder: units/rows done, rates, ETA (when the total is
   known), checkpoint age, structured progress events, and the
   environment fingerprint (so bench_watch can refuse cross-backend
   comparisons).
2. A solve that dies mid-fit force-dumps the solver recorder and the
   journey names the last completed unit — for both chunked-LSQ paths
   and the streamed BCD.
3. The per-solve watchdog turns a stalled solve into a counter bump plus
   an auto-dump, then keeps quiet once progress resumes or the solve
   finishes.
4. ``solver_stats()`` is the live health surface and is served at the
   metrics server's ``/solves`` endpoint.
5. Progress reporting never perturbs solve RESULTS (bit-identity with a
   plain solve is covered by the solver equivalence suites, which now
   run over the instrumented paths).
"""

import glob
import json
import os
import time

import numpy as np
import pytest

from keystone_tpu.config import config
from keystone_tpu.utils import flight_recorder
from keystone_tpu.utils.flight_recorder import (
    FlightRecorder,
    ProgressReporter,
    SolveRecord,
    solver_stats,
)
from keystone_tpu.utils.metrics import metrics_registry, reliability_counters


@pytest.fixture
def solver_dir(tmp_path, monkeypatch):
    """Route the process solver recorder's dumps at a tmpdir."""
    monkeypatch.setattr(config, "flight_dir", str(tmp_path))
    flight_recorder.reset_solver_recorder()
    yield str(tmp_path)
    flight_recorder.reset_solver_recorder()


def _solver_dumps(d):
    return sorted(glob.glob(os.path.join(d, "keystone_flight_solver_*")))


def _journeys(dump_path, kind):
    doc = json.load(open(dump_path))
    return [r for r in doc["records"] if r.get("kind") == kind]


# ---------------------------------------------------------------------------
# ProgressReporter unit behavior
# ---------------------------------------------------------------------------


def test_reporter_tracks_progress_and_events(tmp_path):
    rec = FlightRecorder("solver-test", capacity=16, directory=str(tmp_path))
    rep = ProgressReporter("unit_test", total_units=10, recorder=rec,
                           watchdog_ms=0, progress_every=2)
    with rep:
        for i in range(6):
            rep.unit_done(rows=100, block=i)
        rep.checkpoint()
    s = rep.stats()
    assert s["units_done"] == 6 and s["rows_done"] == 600
    assert s["outcome"] == "ok"
    assert s["eta_s"] is not None and s["eta_s"] >= 0
    assert s["checkpoint_unit"] == 6 and s["checkpoint_age_s"] >= 0
    snap = rec.snapshot()
    (journey,) = snap["records"]
    # progress_every=2 thins the event ring: units 2, 4, 6.
    assert [e["unit"] for e in journey["events"]] == [2, 4, 6]
    assert journey["events"][-1]["block"] == 5
    assert journey["fingerprint"]["backend"] == "cpu"
    assert journey["outcome"] == "ok"


def test_reporter_failure_dumps_naming_last_unit(tmp_path):
    rec = FlightRecorder("solver-test", capacity=16, directory=str(tmp_path))
    with pytest.raises(RuntimeError):
        with ProgressReporter("unit_test", recorder=rec, watchdog_ms=0) as rep:
            rep.unit_done(rows=10)
            rep.unit_done(rows=10)
            raise RuntimeError("boom")
    dumps = sorted(glob.glob(os.path.join(str(tmp_path), "*solver-test*")))
    assert dumps, "failure must force-dump the recorder"
    (journey,) = _journeys(dumps[-1], "unit_test")
    assert journey["units_done"] == 2
    assert journey["outcome"] == "error:RuntimeError"
    errors = json.load(open(dumps[-1]))["errors"]
    assert any(e["kind"] == "solve_death" for e in errors)


def test_reporter_finish_is_idempotent_and_unregisters(tmp_path):
    rec = FlightRecorder("solver-test", capacity=16, directory=str(tmp_path))
    rep = ProgressReporter("unit_test", recorder=rec, watchdog_ms=0)
    assert any(s["id"] == rep.rid for s in solver_stats()["solves"])
    rep.finish()
    rep.finish()
    rep.fail(RuntimeError("late"))  # after finish: no-op, no dump
    assert not any(s["id"] == rep.rid for s in solver_stats()["solves"])
    assert rep.stats()["outcome"] == "ok"
    assert not glob.glob(os.path.join(str(tmp_path), "*solver-test*"))


def test_watchdog_stall_dumps_then_heals(tmp_path):
    rec = FlightRecorder("solver-test", capacity=16, directory=str(tmp_path))
    before = reliability_counters.get("solve_stalls")
    rep = ProgressReporter("stall_test", recorder=rec, watchdog_ms=150)
    try:
        rep.unit_done(rows=1)
        deadline = time.monotonic() + 5
        while rec.stats()["dumps_total"] == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert rec.stats()["dumps_total"] >= 1, "stall must auto-dump"
        assert reliability_counters.get("solve_stalls") > before
        assert metrics_registry.counters("solver.events").get(
            "stall_test_stalls"
        ) >= 1
        assert rep.stats()["stalls"] >= 1
        # The stall re-arm must NOT falsify the health surface: the
        # journey still reports the true time since real progress
        # (>= the watchdog window), not the watchdog's fire time.
        assert rep.stats()["last_progress_age_s"] >= 0.15
    finally:
        rep.finish()
    # The watchdog thread exits promptly once finished.
    rep._watchdog.join(timeout=2)
    assert not rep._watchdog.is_alive()


# ---------------------------------------------------------------------------
# Solver integration
# ---------------------------------------------------------------------------


def _xy(rng, n=64, d=8, k=3):
    X = rng.normal(size=(n, d)).astype(np.float32)
    Y = (X @ rng.normal(size=(d, k))).astype(np.float32)
    return X, Y


@pytest.mark.parametrize("depth", [0, 2])
def test_chunked_solve_records_journey(rng, solver_dir, depth):
    from keystone_tpu.linalg import solve_least_squares_chunked
    from keystone_tpu.loaders.stream import BatchIterator

    X, Y = _xy(rng)
    solve_least_squares_chunked(
        BatchIterator.from_arrays(X, Y, batch_rows=16), lam=1e-3,
        prefetch_depth=depth,
    )
    snap = flight_recorder.solver_recorder().snapshot()
    journeys = [r for r in snap["records"] if r["kind"] == "lsq_chunked"]
    assert journeys and journeys[-1]["outcome"] == "ok"
    assert journeys[-1]["units_done"] == 4
    assert journeys[-1]["rows_done"] == 64
    assert journeys[-1]["fingerprint"]["device_count"] == 8


@pytest.mark.parametrize("depth", [0, 2])
def test_chunked_solve_death_dumps_last_chunk(rng, solver_dir, depth):
    from keystone_tpu.linalg import solve_least_squares_chunked

    X, Y = _xy(rng)

    def dying():
        for i in range(4):
            if i == 2:
                raise RuntimeError("injected death")
            yield (X[i * 16:(i + 1) * 16], Y[i * 16:(i + 1) * 16])

    with pytest.raises(RuntimeError):
        solve_least_squares_chunked(dying(), lam=1e-3, prefetch_depth=depth)
    dumps = _solver_dumps(solver_dir)
    assert dumps, "mid-solve death must dump the solver recorder"
    journeys = _journeys(dumps[-1], "lsq_chunked")
    assert journeys[-1]["units_done"] == 2
    assert journeys[-1]["outcome"].startswith("error:")


def test_streamed_bcd_journey_has_total_and_checkpoints(
    rng, solver_dir, tmp_path
):
    from keystone_tpu.linalg import block_coordinate_descent_streamed
    from keystone_tpu.linalg.row_matrix import RowMatrix

    n, d, k = 64, 32, 3
    A = rng.normal(size=(n, d)).astype(np.float32)
    B = rng.normal(size=(n, k)).astype(np.float32)
    ckpt = tmp_path / "bcd_ckpt"
    block_coordinate_descent_streamed(
        A, RowMatrix.from_array(B), block_size=8, num_iters=2, lam=0.1,
        checkpoint_dir=str(ckpt), checkpoint_every=3,
    )
    snap = flight_recorder.solver_recorder().snapshot()
    journeys = [r for r in snap["records"] if r["kind"] == "bcd_streamed"]
    assert journeys and journeys[-1]["outcome"] == "ok"
    # 2 epochs x 4 blocks, total known up front -> ETA was available.
    assert journeys[-1]["units_done"] == 8
    assert journeys[-1]["total_units"] == 8
    assert journeys[-1]["checkpoint_unit"] is not None
    assert journeys[-1]["events"]


# ---------------------------------------------------------------------------
# Health surface / metrics server
# ---------------------------------------------------------------------------


def test_solver_stats_shape(tmp_path):
    rec = FlightRecorder("solver-test", capacity=8, directory=str(tmp_path))
    rep = ProgressReporter("surface_test", total_units=4, recorder=rec,
                           watchdog_ms=0)
    try:
        rep.unit_done(rows=5)
        stats = solver_stats()
        assert stats["active_solves"] >= 1
        mine = [s for s in stats["solves"] if s["id"] == rep.rid]
        assert mine and mine[0]["units_done"] == 1
        assert mine[0]["kind"] == "surface_test"
    finally:
        rep.finish()


def test_metrics_server_serves_solves_endpoint(tmp_path):
    import importlib
    import sys
    import urllib.request

    sys.path.insert(
        0,
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools"),
    )
    try:
        metrics_server = importlib.import_module("metrics_server")
    finally:
        sys.path.pop(0)
    rec = FlightRecorder("solver-test", capacity=8, directory=str(tmp_path))
    rep = ProgressReporter("endpoint_test", recorder=rec, watchdog_ms=0)
    server = metrics_server.MetricsServer(port=0).start()
    try:
        rep.unit_done(rows=7)
        with urllib.request.urlopen(server.url("/solves"), timeout=10) as r:
            assert r.status == 200
            doc = json.loads(r.read().decode())
        assert doc["active_solves"] >= 1
        assert any(s["kind"] == "endpoint_test" for s in doc["solves"])
    finally:
        server.stop()
        rep.finish()


def test_solve_record_serializes_whole(tmp_path):
    rec = SolveRecord(7, "shape_test", total_units=3,
                      fingerprint={"backend": "cpu"})
    d = rec.as_dict()
    assert d["id"] == 7 and d["kind"] == "shape_test"
    assert d["total_units"] == 3 and d["units_done"] == 0
    assert d["outcome"] is None and d["events"] == []
    json.dumps(d)  # must be JSON-serializable for the dump path
