"""Bench regression sentinel (tools/bench_watch.py = make bench-watch).

Pins the ISSUE-9 gate contract:

1. The checked-in bench history passes (exit 0) — the sentinel must
   gate the trajectory as committed, or it could never run in CI.
2. A synthetic 2x p99 regression row appended with a COMPATIBLE
   fingerprint exits nonzero and names the metric.
3. The same row under a different backend/device-count fingerprint is
   refused for comparison (skipped), NOT flagged — the
   environment_fingerprint provenance satellite.
4. Boolean gate flags flipping true -> false regress; throughput-like
   leaves regress downward; unknown leaves are never gated.
5. ``--bless`` records an intentional change and waives exactly that
   series while its value holds.
"""

import json
import os
import shutil
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
try:
    import bench_watch
finally:
    sys.path.pop(0)


@pytest.fixture
def history(tmp_path):
    """A private copy of the repo's checked-in bench history."""
    for name in os.listdir(REPO_ROOT):
        if name.startswith(("BENCH_", "MULTICHIP_")) and name.endswith(
            ".json"
        ):
            shutil.copy(os.path.join(REPO_ROOT, name), tmp_path / name)
    return tmp_path


def _append_serve_row(root, mutate, metric="serve_bucketed_vs_pershape"):
    path = os.path.join(root, "BENCH_serve.json")
    rows = [json.loads(line) for line in open(path)]
    # Latest row of the named family — the file interleaves families
    # (main anchor, overload, replicas, daemon), one latest row each.
    latest = [r for r in rows if r.get("metric") == metric][-1]
    row = json.loads(json.dumps(latest))  # deep copy
    mutate(row)
    with open(path, "a") as f:
        f.write(json.dumps(row) + "\n")
    return row


def test_checked_in_history_passes():
    result = bench_watch.run(REPO_ROOT)
    assert result["ok"], result["regressions"]
    assert result["series"] > 50
    # The fingerprint refusal is live on the real history: the TPU round
    # (BENCH_r03) must be excluded from the CPU rounds' bands.
    skipped = [v for v in result["verdicts"]
               if v.get("skipped_incompatible")]
    assert skipped, "expected the TPU history row to be refused"


def test_make_bench_watch_cli_green():
    assert bench_watch.main(["--root", REPO_ROOT]) == 0


def test_synthetic_p99_regression_fails(history):
    def mutate(row):
        row["bucketed"]["p99_ms"] *= 2.0

    _append_serve_row(history, mutate)
    result = bench_watch.run(str(history))
    assert not result["ok"]
    names = [v["series"] for v in result["regressions"]]
    assert "serve:serve_bucketed_vs_pershape:bucketed.p99_ms" in names
    (reg,) = [v for v in result["regressions"]
              if v["series"].endswith("bucketed.p99_ms")]
    assert "above noise band" in reg["reason"]
    assert bench_watch.main(["--root", str(history)]) == 1


def test_fingerprint_change_is_refused_not_flagged(history):
    def mutate(row):
        row["bucketed"]["p99_ms"] *= 2.0
        row["env"]["backend"] = "tpu"
        row["env"]["device_count"] = 4
        row["backend"] = "tpu"

    _append_serve_row(history, mutate)
    result = bench_watch.run(str(history))
    assert result["ok"], result["regressions"]
    v = next(v for v in result["verdicts"]
             if v["series"] == "serve:serve_bucketed_vs_pershape:"
                               "bucketed.p99_ms")
    assert v["status"] == "no_history"
    assert v["skipped_incompatible"] >= 1


def test_throughput_drop_and_bool_flip_regress(history):
    def mutate(row):
        row["bucketed"]["rows_per_s"] /= 3.0
        row["pass"]["zero_post_warmup_compiles"] = False

    _append_serve_row(history, mutate)
    result = bench_watch.run(str(history))
    names = {v["series"] for v in result["regressions"]}
    assert "serve:serve_bucketed_vs_pershape:bucketed.rows_per_s" in names
    assert any(s.endswith("pass.zero_post_warmup_compiles") for s in names)


def test_fit_family_loaded_and_regression_flagged(history):
    """ISSUE-10: the BENCH_fit.json JSONL history is a gated family —
    wall-like leaves regress upward, the speedup value downward, and the
    bit-identity flag flipping false regresses by definition."""
    path = os.path.join(str(history), "BENCH_fit.json")
    rows = [json.loads(line) for line in open(path)]
    # Anchor by metric: the file interleaves fit families (parallel
    # walk, optimizer A/B), one JSONL row per run of each.
    latest = [r for r in rows if r.get("metric") == "fit_parallel_walk"][-1]
    row = json.loads(json.dumps(latest))
    row["value"] *= 0.3  # speedup collapses
    row["detail"]["parallel_wall_s"] *= 4.0  # wall-like, up = regress
    row["detail"]["bit_identical"] = False
    with open(path, "a") as f:
        f.write(json.dumps(row) + "\n")
    result = bench_watch.run(str(history))
    assert not result["ok"]
    names = {v["series"] for v in result["regressions"]}
    assert "fit:fit_parallel_walk:value" in names
    assert "fit:fit_parallel_walk:detail.parallel_wall_s" in names
    assert "fit:fit_parallel_walk:detail.bit_identical" in names


def test_optimizer_family_loaded_and_regression_flagged(history):
    """ISSUE-12: the `make bench-opt` row gates under the same generic
    loader — the optimizer speedup regressing down, a per-pipeline
    speedup collapsing, or the bit-identity / zero-sample-run flags
    flipping false all fail the watch."""
    path = os.path.join(str(history), "BENCH_fit.json")
    rows = [json.loads(line) for line in open(path)]
    latest = [r for r in rows if r.get("metric") == "fit_optimizer"][-1]
    row = json.loads(json.dumps(latest))
    row["value"] *= 0.3
    row["detail"]["pipelines"]["reused_subchain"]["speedup"] *= 0.3
    row["detail"]["bit_identical"] = False
    row["detail"]["zero_sample_runs"] = False
    with open(path, "a") as f:
        f.write(json.dumps(row) + "\n")
    result = bench_watch.run(str(history))
    assert not result["ok"]
    names = {v["series"] for v in result["regressions"]}
    assert "fit:fit_optimizer:value" in names
    assert ("fit:fit_optimizer:detail.pipelines.reused_subchain.speedup"
            in names)
    assert "fit:fit_optimizer:detail.bit_identical" in names
    assert "fit:fit_optimizer:detail.zero_sample_runs" in names


def test_unjudged_leaves_never_gate(history):
    def mutate(row):
        row["features"] = row.get("features", 512) * 100  # config, not perf

    _append_serve_row(history, mutate)
    result = bench_watch.run(str(history))
    assert result["ok"], result["regressions"]


def test_bless_waives_exactly_that_series(history):
    def mutate(row):
        row["bucketed"]["p99_ms"] *= 2.0

    _append_serve_row(history, mutate)
    assert bench_watch.main(["--root", str(history)]) == 1
    series = "serve:serve_bucketed_vs_pershape:bucketed.p99_ms"
    assert bench_watch.main([
        "--root", str(history), "--bless", series,
        "--why", "intentional trade for test",
    ]) == 0
    result = bench_watch.run(str(history))
    assert result["ok"]
    v = next(x for x in result["verdicts"] if x["series"] == series)
    assert v["status"] == "blessed"
    # A FURTHER regression past the blessed value re-fires the gate.
    _append_serve_row(history, mutate)
    result = bench_watch.run(str(history))
    assert not result["ok"]


def test_bless_waives_boolean_series_too(history):
    def mutate(row):
        row["pass"]["zero_post_warmup_compiles"] = False

    _append_serve_row(history, mutate)
    result = bench_watch.run(str(history))
    (reg,) = [v for v in result["regressions"]
              if v["series"].endswith("pass.zero_post_warmup_compiles")]
    assert "true -> false" in reg["reason"]
    assert bench_watch.main([
        "--root", str(history), "--bless", reg["series"],
        "--why", "known infra outage",
    ]) == 0
    result = bench_watch.run(str(history))
    v = next(x for x in result["verdicts"] if x["series"] == reg["series"])
    assert v["status"] == "blessed"
    assert result["ok"]


def test_residual_leaf_is_gated(history):
    # A numeric-quality regression (relative_residual blowing up) must
    # gate, not ride through as unjudged.
    path = os.path.join(str(history), "BENCH_r06.json")
    doc = json.load(open(os.path.join(str(history), "BENCH_r05.json")))
    doc["n"] = 6
    doc["parsed"]["detail"]["relative_residual"] = 0.9
    json.dump(doc, open(path, "w"))
    result = bench_watch.run(str(history))
    assert any(
        v["series"].endswith("detail.relative_residual")
        for v in result["regressions"]
    ), result["by_status"]


def test_bless_requires_known_series_and_why(history):
    assert bench_watch.main([
        "--root", str(history), "--bless", "no:such:series", "--why", "x",
    ]) == 2
    assert bench_watch.main([
        "--root", str(history), "--bless", "a:b:c",
    ]) == 2


def test_unreadable_history_fails_loudly(history):
    (history / "BENCH_r09.json").write_text("{not json")
    with pytest.raises(RuntimeError, match="unreadable history row"):
        bench_watch.run(str(history))
    assert bench_watch.main(["--root", str(history)]) == 2


def test_serve_precision_family_judged(history):
    """The serve_precision family's three regression axes: speedup down,
    quality_delta up (the LOWER_BETTER fragment), and the knob-off
    bit-identity flag flipping true -> false."""
    def mutate(row):
        row["speedup"]["throughput"] /= 3.0
        row["quality"]["quality_delta"] += 0.5
        row["bit_identical_f32"] = False

    _append_serve_row(history, mutate, metric="serve_precision")
    result = bench_watch.run(str(history))
    assert not result["ok"]
    names = {v["series"] for v in result["regressions"]}
    assert "serve:serve_precision:speedup.throughput" in names
    assert "serve:serve_precision:quality.quality_delta" in names
    assert "serve:serve_precision:bit_identical_f32" in names


def test_serve_precision_healthy_rerun_passes(history):
    """A same-fingerprint re-run inside the noise band gates green."""
    def mutate(row):
        row["speedup"]["throughput"] *= 1.05
        row["planned_bf16"]["p99_ms"] *= 1.1

    _append_serve_row(history, mutate, metric="serve_precision")
    result = bench_watch.run(str(history))
    assert result["ok"], result["regressions"]


def test_serve_telemetry_family_judged(history):
    """The serve_telemetry family's regression axes: the telemetry-on
    overhead blowing past its band, export drops appearing (the
    LOWER_BETTER ``dropped`` fragment), and the never-blocks
    accounting gate flipping true -> false."""
    def mutate(row):
        row["overhead_frac"] *= 4.0
        row["records_dropped"] += 50
        row["pass"]["nonblocking_accounted"] = False

    _append_serve_row(history, mutate, metric="serve_telemetry")
    result = bench_watch.run(str(history))
    assert not result["ok"]
    names = {v["series"] for v in result["regressions"]}
    assert "serve:serve_telemetry:overhead_frac" in names
    assert "serve:serve_telemetry:records_dropped" in names
    assert "serve:serve_telemetry:pass.nonblocking_accounted" in names


def test_serve_telemetry_healthy_rerun_passes(history):
    """A same-fingerprint re-run inside the noise band gates green."""
    def mutate(row):
        row["overhead_frac"] *= 1.05
        row["on"]["req_per_s"] *= 1.02
        row["on"]["lat"]["p99_ms"] *= 1.04

    _append_serve_row(history, mutate, metric="serve_telemetry")
    result = bench_watch.run(str(history))
    assert result["ok"], result["regressions"]


def test_serve_capacity_family_judged(history):
    """ISSUE-20: the `make bench-capacity` serve_capacity row gates
    under the same generic loader — the model-on goodput regressing
    down, the model-on gold p99 blowing past its band, or the
    goodput-improved A/B gate flipping true -> false all fail the
    watch."""
    def mutate(row):
        row["goodput_on_per_s"] /= 3.0
        row["on"]["gold"]["p99_ms"] *= 3.0
        row["pass"]["goodput_improved"] = False

    _append_serve_row(history, mutate, metric="serve_capacity")
    result = bench_watch.run(str(history))
    assert not result["ok"]
    names = {v["series"] for v in result["regressions"]}
    assert "serve:serve_capacity:goodput_on_per_s" in names
    assert "serve:serve_capacity:on.gold.p99_ms" in names
    assert "serve:serve_capacity:pass.goodput_improved" in names


def test_serve_capacity_healthy_rerun_passes(history):
    """A same-fingerprint re-run inside the noise band gates green."""
    def mutate(row):
        row["goodput_on_per_s"] *= 1.03
        row["goodput_off_per_s"] *= 0.98
        row["on"]["gold"]["p99_ms"] *= 1.05

    _append_serve_row(history, mutate, metric="serve_capacity")
    result = bench_watch.run(str(history))
    assert result["ok"], result["regressions"]


def test_online_family_loaded_and_regression_flagged(history):
    """ISSUE-15: the `make bench-online` fit_online row gates under the
    same generic loader — the re-solve speedup regressing down, the
    post-refresh accuracy / recovery sliding down, the re-solve wall
    creeping up, dropped requests appearing, or the swap gate flipping
    false all fail the watch."""
    path = os.path.join(str(history), "BENCH_fit.json")
    rows = [json.loads(line) for line in open(path)]
    latest = [r for r in rows if r.get("metric") == "fit_online"][-1]
    row = json.loads(json.dumps(latest))
    row["value"] *= 0.2  # re-solve speedup collapses
    row["detail"]["post_refresh_accuracy"] *= 0.3
    row["detail"]["accuracy_recovery"] *= 0.3
    row["detail"]["resolve_wall_s"] *= 4.0
    row["detail"]["swap_gate"] = False
    with open(path, "a") as f:
        f.write(json.dumps(row) + "\n")
    result = bench_watch.run(str(history))
    assert not result["ok"]
    names = {v["series"] for v in result["regressions"]}
    assert "fit:fit_online:value" in names
    assert "fit:fit_online:detail.post_refresh_accuracy" in names
    assert "fit:fit_online:detail.accuracy_recovery" in names
    assert "fit:fit_online:detail.resolve_wall_s" in names
    assert "fit:fit_online:detail.swap_gate" in names


def test_online_family_healthy_rerun_passes(history):
    """A same-fingerprint re-run inside the noise band must stay green
    (the band gates the trajectory, not determinism)."""
    path = os.path.join(str(history), "BENCH_fit.json")
    rows = [json.loads(line) for line in open(path)]
    latest = [r for r in rows if r.get("metric") == "fit_online"][-1]
    row = json.loads(json.dumps(latest))
    row["value"] *= 1.1
    row["detail"]["resolve_wall_s"] *= 0.95
    with open(path, "a") as f:
        f.write(json.dumps(row) + "\n")
    result = bench_watch.run(str(history))
    bad = [v for v in result["regressions"]
           if v["series"].startswith("fit:fit_online:")]
    assert not bad, bad
