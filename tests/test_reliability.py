"""Fault-injection harness + recovery-path tests.

Three layers of evidence, mirroring ISSUE 3's acceptance gates:

1. the harness itself is deterministic (a seed reproduces the fault
   sequence) and inert when enabled-but-silent (a zero-probability plan
   yields bit-identical results to a disabled one);
2. chaos equivalence — with ``io``/``oom``/``producer_death`` faults
   armed, the chunked solve completes and matches the fault-free run
   bit-for-bit, and a killed fit resumes from its checkpoint recomputing
   at most K chunks, also bit-identically;
3. serving degrades, never cliffs: overload fast-fails with
   ``QueueFullError``/``DeadlineExceeded``, worker death restarts, and
   no future is ever stranded — including across ``close()``.
"""

import threading
import time

import numpy as np
import pytest

from keystone_tpu.config import config
from keystone_tpu.utils import reliability
from keystone_tpu.utils.metrics import reliability_counters
from keystone_tpu.utils.reliability import (
    DeadlineExceeded,
    FaultPlan,
    InjectedIOError,
    InjectedOOM,
    QueueFullError,
    RecordCorruptError,
    RetryPolicy,
    ServiceClosed,
    is_oom,
    is_transient,
)


@pytest.fixture
def faults():
    """Arm a fault plan for the test; starts DISARMED (even under ``make
    chaos``'s process-wide plan, so counter assertions stay exact) and
    restores the prior plan + counters after."""
    prior = (config.faults, config.faults_seed)
    reliability_counters.reset()

    def arm(spec: str, seed: int = 0):
        config.faults, config.faults_seed = spec, seed
        reliability.reset_fault_plan()
        return reliability.active_plan()

    arm("")
    yield arm
    config.faults, config.faults_seed = prior
    reliability.reset_fault_plan()
    reliability_counters.reset()


def _stream(rng_seed=0, n=512, d=16, k=3, batch_rows=64):
    from keystone_tpu.loaders.stream import BatchIterator

    rng = np.random.default_rng(rng_seed)
    A = rng.normal(size=(n, d)).astype(np.float32)
    B = (A @ rng.normal(size=(d, k)).astype(np.float32))
    return A, B, (lambda: BatchIterator.from_arrays(A, B, batch_rows=batch_rows))


# ---------------------------------------------------------------------------
# The harness itself
# ---------------------------------------------------------------------------


class TestFaultPlan:
    def test_disabled_by_default(self, faults):
        assert faults("") is None

    def test_parse_counts_and_probabilities(self, faults):
        plan = faults("io:0.25,oom:2,producer_death:1")
        assert plan.sites == ("io", "oom", "producer_death")
        # Counts fire on the first N checks, then never again.
        assert plan.check("oom") and plan.check("oom")
        assert not any(plan.check("oom") for _ in range(20))
        assert plan.check("producer_death")
        assert not plan.check("producer_death")

    def test_unlisted_site_never_fires(self, faults):
        plan = faults("io:1")
        assert not plan.check("oom")
        assert plan.checked["oom"] == 1  # observed, just not armed

    def test_probability_sequence_is_seed_deterministic(self):
        def seq(seed):
            plan = FaultPlan("io:0.3", seed=seed)
            return [plan.check("io") for _ in range(100)]

        a, b, c = seq(7), seq(7), seq(8)
        assert a == b
        assert a != c
        assert any(a) and not all(a)

    def test_sites_draw_independent_streams(self):
        # The io pattern must not shift when another site is added.
        solo = FaultPlan("io:0.3", seed=3)
        lone = [solo.check("io") for _ in range(50)]
        plan = FaultPlan("io:0.3,corrupt:0.5", seed=3)
        paired = []
        for _ in range(50):
            paired.append(plan.check("io"))
            plan.check("corrupt")
        assert lone == paired

    def test_maybe_raise_types(self, faults):
        plan = faults("io:1,oom:1,corrupt:1")
        with pytest.raises(InjectedIOError):
            plan.maybe_raise("io")
        with pytest.raises(InjectedOOM, match="RESOURCE_EXHAUSTED"):
            plan.maybe_raise("oom")
        with pytest.raises(RecordCorruptError):
            plan.maybe_raise("corrupt")

    def test_malformed_spec_rejected(self):
        for bad in ("io", "io:", ":1", "io:-1", "io:1.5", "io:x"):
            with pytest.raises(ValueError, match="KEYSTONE_FAULTS"):
                FaultPlan(bad)

    def test_plan_rebuilds_when_config_changes(self, faults):
        assert faults("io:1") is not None
        assert reliability.active_plan() is reliability.active_plan()
        assert faults("") is None


class TestClassifierAndRetry:
    def test_taxonomy(self):
        assert is_transient(ConnectionResetError())
        assert is_transient(TimeoutError())
        assert is_transient(InjectedIOError("x"))
        assert is_transient(InjectedOOM("RESOURCE_EXHAUSTED: x"))
        assert not is_transient(FileNotFoundError("gone"))
        assert not is_transient(RecordCorruptError("bad bytes"))
        assert not is_transient(ValueError("logic bug"))
        assert is_oom(RuntimeError("RESOURCE_EXHAUSTED: out of memory"))
        assert not is_oom(RuntimeError("INVALID_ARGUMENT"))

    def test_retry_recovers_and_counts(self):
        reliability_counters.reset()
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise ConnectionResetError("blip")
            return "ok"

        policy = RetryPolicy(max_attempts=4, base_delay=0.0, seed=0)
        assert policy.call(flaky, site="t", counter="io_retries") == "ok"
        assert calls["n"] == 3
        assert reliability_counters.get("io_retries") == 2

    def test_retry_gives_up_after_cap_and_skips_nontransient(self):
        policy = RetryPolicy(max_attempts=3, base_delay=0.0, seed=0)
        calls = {"n": 0}

        def always():
            calls["n"] += 1
            raise TimeoutError("never heals")

        with pytest.raises(TimeoutError):
            policy.call(always, site="t")
        assert calls["n"] == 3
        calls["n"] = 0

        def broken():
            calls["n"] += 1
            raise ValueError("not transient")

        with pytest.raises(ValueError):
            policy.call(broken, site="t")
        assert calls["n"] == 1

    def test_backoff_jittered_capped_and_seeded(self):
        p1 = RetryPolicy(max_attempts=8, base_delay=0.01, max_delay=0.04, seed=5)
        p2 = RetryPolicy(max_attempts=8, base_delay=0.01, max_delay=0.04, seed=5)
        d1 = [p1.delay(i) for i in range(8)]
        assert d1 == [p2.delay(i) for i in range(8)]
        for i, d in enumerate(d1):
            assert 0.0 <= d <= min(0.04, 0.01 * 2**i)


# ---------------------------------------------------------------------------
# Chaos equivalence on the streaming solve
# ---------------------------------------------------------------------------


class TestChaosEquivalence:
    def test_enabled_but_silent_is_bit_identical(self, faults):
        from keystone_tpu.linalg import solve_least_squares_chunked

        _, _, it = _stream()
        faults("")
        ref = np.asarray(solve_least_squares_chunked(it(), lam=0.1))
        plan = faults("io:0.0,oom:0")
        assert plan is not None  # armed...
        out = np.asarray(solve_least_squares_chunked(it(), lam=0.1))
        assert plan.fired == {}  # ...but silent
        np.testing.assert_array_equal(ref, out)

    def test_injected_faults_recover_bit_identically(self, faults):
        """The acceptance gate: io+oom+producer_death armed, fixed seed —
        the solve completes, recoveries fire, and the solution matches the
        fault-free run bit-for-bit."""
        from keystone_tpu.linalg import solve_least_squares_chunked

        _, _, it = _stream()
        faults("")
        ref = np.asarray(solve_least_squares_chunked(it(), lam=0.1))
        faults("io:0.2,oom:1,producer_death:1", seed=0)
        out = np.asarray(solve_least_squares_chunked(it(), lam=0.1))
        np.testing.assert_array_equal(ref, out)
        snap = reliability_counters.snapshot()
        assert snap.get("faults_injected_oom") == 1
        assert snap.get("faults_injected_producer_death") == 1
        assert snap.get("h2d_retries", 0) >= 1
        assert snap.get("producer_restarts") == 1
        # io:0.2 over ~8 record boundaries fires with seed 0; every fire
        # was retried invisibly.
        if snap.get("faults_injected_io", 0):
            assert snap.get("io_retries", 0) >= snap["faults_injected_io"]

    def test_sync_path_oom_downshift_still_solves(self, faults):
        """OOM that survives the whole retry budget halves the chunk: not
        bit-identical (different flop grouping) but the same least-squares
        problem — and the downshift is recorded."""
        from keystone_tpu.linalg import solve_least_squares_chunked

        A, B, it = _stream(n=256, batch_rows=128)
        faults("")
        ref = np.asarray(solve_least_squares_chunked(it(), lam=0.1))
        # More oom firings than retry attempts: the first chunk's retries
        # all fail, forcing a split (then its halves succeed).
        faults(f"oom:{config.retry_attempts}")
        out = np.asarray(
            solve_least_squares_chunked(it(), lam=0.1, prefetch_depth=0)
        )
        assert reliability_counters.get("oom_downshifts") >= 1
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


class TestCheckpointResume:
    def test_kill_and_resume_bit_identical(self, faults, tmp_path):
        from keystone_tpu.linalg import solve_least_squares_chunked

        _, _, it = _stream()  # 8 chunks of 64 rows
        ref = np.asarray(solve_least_squares_chunked(it(), lam=0.1))

        class Kill(Exception):
            pass

        def killed_stream(at):
            for i, batch in enumerate(it()):
                if i == at:
                    raise Kill()
                yield batch

        ckpt = str(tmp_path / "ckpt")
        with pytest.raises(Kill):
            solve_least_squares_chunked(
                killed_stream(6), lam=0.1,
                checkpoint_dir=ckpt, checkpoint_every=2,
            )
        assert reliability_counters.get("checkpoints_written") == 3  # 2,4,6
        out = np.asarray(
            solve_least_squares_chunked(
                it(), lam=0.1, checkpoint_dir=ckpt, checkpoint_every=2
            )
        )
        np.testing.assert_array_equal(ref, out)
        # Resumed at the chunk-6 snapshot: recomputed 8-6=2 <= K chunks.
        assert reliability_counters.get("checkpoints_resumed") == 1
        assert reliability_counters.get("chunks_skipped_on_resume") == 6

    def test_resume_under_chaos_matches_clean_run(self, faults, tmp_path):
        from keystone_tpu.linalg import solve_least_squares_chunked

        _, _, it = _stream()
        ref = np.asarray(solve_least_squares_chunked(it(), lam=0.1))
        ckpt = str(tmp_path / "ckpt")
        # Seed a mid-stream checkpoint, then resume WITH faults armed.
        class Kill(Exception):
            pass

        def killed_stream():
            for i, batch in enumerate(it()):
                if i == 5:
                    raise Kill()
                yield batch

        with pytest.raises(Kill):
            solve_least_squares_chunked(
                killed_stream(), lam=0.1,
                checkpoint_dir=ckpt, checkpoint_every=4,
            )
        faults("io:0.2,oom:1", seed=1)
        out = np.asarray(
            solve_least_squares_chunked(
                it(), lam=0.1, checkpoint_dir=ckpt, checkpoint_every=4
            )
        )
        np.testing.assert_array_equal(ref, out)

    def test_completed_solve_consumes_its_checkpoint(self, faults, tmp_path):
        """A snapshot is mid-flight state: the successful solve deletes it,
        so a later solve over CHANGED data whose first-chunk probe happens
        to match can never silently resume stale accumulators."""
        from keystone_tpu.linalg import solve_least_squares_chunked
        from keystone_tpu.linalg.normal_equations import (
            _STREAM_CKPT_KEY,
            _stream_ckpt_store,
        )

        ckpt = str(tmp_path / "ckpt")
        _, _, it = _stream()
        solve_least_squares_chunked(
            it(), lam=0.1, checkpoint_dir=ckpt, checkpoint_every=2
        )
        assert _stream_ckpt_store(ckpt).get(_STREAM_CKPT_KEY) is None

    def test_mismatched_fingerprint_starts_fresh(self, faults, tmp_path):
        from keystone_tpu.linalg import solve_least_squares_chunked

        ckpt = str(tmp_path / "ckpt")
        _, _, it = _stream(rng_seed=0)

        class Kill(Exception):
            pass

        def killed():
            for i, batch in enumerate(it()):
                if i == 6:
                    raise Kill()
                yield batch

        # A mid-flight snapshot from one problem...
        with pytest.raises(Kill):
            solve_least_squares_chunked(
                killed(), lam=0.1, checkpoint_dir=ckpt, checkpoint_every=2
            )
        # ...must not be resumed by a DIFFERENT problem in the same dir.
        _, _, other = _stream(rng_seed=9)
        out = np.asarray(
            solve_least_squares_chunked(
                other(), lam=0.1, checkpoint_dir=ckpt, checkpoint_every=2
            )
        )
        clean = np.asarray(solve_least_squares_chunked(other(), lam=0.1))
        np.testing.assert_array_equal(out, clean)
        assert reliability_counters.get("checkpoints_resumed") == 0

    def test_streamed_bcd_block_checkpoint_resumes_mid_epoch(
        self, faults, tmp_path
    ):
        from keystone_tpu.linalg import RowMatrix
        from keystone_tpu.linalg.bcd import (
            _BCD_CKPT_KEY,
            _bcd_ckpt_store,
            assemble_blocks,
            block_coordinate_descent_streamed,
        )

        rng = np.random.default_rng(0)
        A = rng.normal(size=(200, 32)).astype(np.float32)
        B = (A @ rng.normal(size=(32, 4)).astype(np.float32))
        ref, _ = block_coordinate_descent_streamed(
            A, RowMatrix.from_array(B), 8, 2, lam=0.1
        )
        ref = np.asarray(assemble_blocks(ref))

        class Kill(Exception):
            pass

        class KillingMatrix(np.ndarray):
            """A_host whose block slicing dies on the LAST block of epoch
            0 — the mid-fit kill, upstream of the device. Killing before
            the epoch completes keeps the test deterministic: epoch 0
            never finishes, so no async orbax epoch save races the block
            snapshot for resume precedence (a later kill point made the
            outcome depend on whether that save committed before the
            resume run checked)."""

            reads = 0

            def __getitem__(self, idx):
                if (
                    isinstance(idx, tuple)
                    and len(idx) == 2
                    and isinstance(idx[1], slice)
                ):
                    type(self).reads += 1
                    if type(self).reads > 4:  # nb=4: dies at epoch 0 block 3
                        raise Kill()
                return super().__getitem__(idx)

        ckpt = str(tmp_path / "bcd")
        A_killing = A.view(KillingMatrix)
        with pytest.raises(Kill):
            block_coordinate_descent_streamed(
                A_killing, RowMatrix.from_array(B), 8, 2, lam=0.1,
                checkpoint_dir=ckpt, checkpoint_every=3,
            )
        # The mid-epoch block snapshot (blocks_done 3 = epoch 0, block 3)
        # outlived the kill; resume restores W/R/invs there and recomputes
        # only the remaining block updates, bit-identically.
        reliability_counters.reset()
        resumed, _ = block_coordinate_descent_streamed(
            A, RowMatrix.from_array(B), 8, 2, lam=0.1,
            checkpoint_dir=ckpt, checkpoint_every=3,
        )
        np.testing.assert_array_equal(
            np.asarray(assemble_blocks(resumed)), ref
        )
        assert reliability_counters.get("checkpoints_resumed") == 1
        # ...and the successful solve consumed its block snapshot.
        assert _bcd_ckpt_store(ckpt).get(_BCD_CKPT_KEY) is None


# ---------------------------------------------------------------------------
# Prefetch producer recovery
# ---------------------------------------------------------------------------


class TestPrefetchRecovery:
    def test_quarantine_skips_corrupt_records(self, faults):
        from keystone_tpu.loaders.stream import PrefetchIterator

        faults("corrupt:2")
        out = list(PrefetchIterator(iter(range(10)), depth=2))
        # Two records quarantined deterministically from the stream head.
        assert out == list(range(2, 10))
        assert reliability_counters.get("records_quarantined") == 2

    def test_corrupt_from_durable_source_is_quarantined(self, faults):
        from keystone_tpu.loaders.stream import PrefetchIterator

        class Flaky:
            """Iterator (not a generator) that survives its own raises."""

            def __init__(self):
                self.i = 0

            def __iter__(self):
                return self

            def __next__(self):
                self.i += 1
                if self.i == 3:
                    raise RecordCorruptError("bad bytes at record 3")
                if self.i > 6:
                    raise StopIteration
                return self.i

        out = list(PrefetchIterator(Flaky(), depth=2))
        assert out == [1, 2, 4, 5, 6]
        assert reliability_counters.get("records_quarantined") == 1

    def test_transient_read_errors_retried_from_durable_source(self, faults):
        from keystone_tpu.loaders.stream import PrefetchIterator

        class Blippy:
            def __init__(self):
                self.i = 0
                self.blipped = False

            def __iter__(self):
                return self

            def __next__(self):
                if self.i == 2 and not self.blipped:
                    self.blipped = True
                    raise ConnectionResetError("nfs blip")
                self.i += 1
                if self.i > 5:
                    raise StopIteration
                return self.i

        out = list(PrefetchIterator(Blippy(), depth=2))
        assert out == [1, 2, 3, 4, 5]
        assert reliability_counters.get("io_retries") == 1

    def test_producer_death_detected_and_restarted(self, faults):
        from keystone_tpu.loaders.stream import PrefetchIterator

        faults("producer_death:2")
        out = list(PrefetchIterator(iter(range(12)), depth=2))
        assert out == list(range(12))  # nothing lost, order kept
        assert reliability_counters.get("producer_restarts") == 2

    def test_restart_cap_gives_up(self, faults, monkeypatch):
        from keystone_tpu.loaders.stream import PrefetchIterator

        monkeypatch.setattr(PrefetchIterator, "_MAX_RESTARTS", 2)
        faults("producer_death:50")
        it = PrefetchIterator(iter(range(100)), depth=2)
        with pytest.raises(RuntimeError, match="died"):
            list(it)
        it.close()

    def test_close_while_blocked_on_full_queue(self):
        """Regression (ISSUE 3 satellite): a producer parked on a FULL
        queue must join promptly at close — and no leak warning fires."""
        from keystone_tpu.loaders.stream import PrefetchIterator

        reliability_counters.reset()
        it = PrefetchIterator(iter(range(10_000)), depth=1)
        deadline = time.monotonic() + 5
        while it._queue.qsize() < 1 and time.monotonic() < deadline:
            time.sleep(0.005)  # producer now blocked on the full queue
        t0 = time.monotonic()
        it.close()
        assert time.monotonic() - t0 < 2.0
        assert not it._thread.is_alive()
        assert reliability_counters.get("producer_leaks") == 0

    def test_leaked_producer_warns_once_with_thread_name(
        self, monkeypatch, caplog
    ):
        from keystone_tpu.loaders.stream import PrefetchIterator

        reliability_counters.reset()
        release = threading.Event()

        class Stuck:
            def __iter__(self):
                return self

            def __next__(self):
                release.wait()  # upstream I/O that honors no deadline
                raise StopIteration

        monkeypatch.setattr(PrefetchIterator, "_JOIN_TIMEOUT_S", 0.05)
        it = PrefetchIterator(Stuck(), depth=1)
        with caplog.at_level("WARNING", logger="keystone_tpu"):
            it.close()
            it.close()  # idempotent: still exactly one warning
        warnings = [
            r for r in caplog.records if "still alive" in r.getMessage()
        ]
        assert len(warnings) == 1
        assert "keystone-prefetch" in warnings[0].getMessage()
        assert reliability_counters.get("producer_leaks") == 1
        release.set()


# ---------------------------------------------------------------------------
# Serving under overload and failure
# ---------------------------------------------------------------------------


def _service(delay_s: float = 0.0, **kwargs):
    """A warmed single-op service whose device call can be slowed to pin
    the worker, exposing queue/deadline behavior deterministically.

    Pinned to devices=1 / inflight=1 — the serial flush path, where the
    slowed ``__call__`` really does occupy the one worker (the pipelined
    dispatcher launches through ``call_async`` and would bypass the
    wrapper's delay). Multi-replica behavior has its own tests below."""
    from keystone_tpu.workflow.pipeline import Transformer
    from keystone_tpu.workflow.serving import CompiledPipeline, PipelineService

    class Double(Transformer):
        def apply_batch(self, X):
            return X * 2.0

    cp = CompiledPipeline(Double(), buckets=(8, 32), devices=1).warmup((4,))

    class Slowed:
        def __init__(self, inner, delay):
            self._inner, self._delay = inner, delay

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def __call__(self, X):
            if self._delay:
                time.sleep(self._delay)
            return self._inner(X)

    return PipelineService(
        Slowed(cp, delay_s), max_delay_ms=1.0, inflight=1, **kwargs
    )


class TestServingHardening:
    def test_queue_full_fast_fails(self, faults):
        svc = _service(delay_s=0.15, max_pending=2)
        try:
            x = np.ones(4, dtype=np.float32)
            first = svc.submit(x)  # worker picks this up and sleeps
            time.sleep(0.05)
            held = [svc.submit(x) for _ in range(2)]  # fills the queue
            with pytest.raises(QueueFullError):
                svc.submit(x)
            assert svc.rejected == 1
            assert reliability_counters.get("requests_rejected") == 1
            np.testing.assert_array_equal(first.result(timeout=5), x * 2.0)
            for f in held:
                f.result(timeout=5)  # accepted work still completes
        finally:
            svc.close()

    def test_deadline_expires_before_device_call(self, faults):
        svc = _service(delay_s=0.2, max_pending=16)
        try:
            x = np.ones(4, dtype=np.float32)
            first = svc.submit(x)  # occupies the worker for 200ms
            time.sleep(0.02)
            doomed = svc.submit(x, deadline_ms=30.0)
            ok = svc.submit(x)  # no deadline: waits its turn
            with pytest.raises(DeadlineExceeded):
                doomed.result(timeout=5)
            np.testing.assert_array_equal(first.result(timeout=5), x * 2.0)
            np.testing.assert_array_equal(ok.result(timeout=5), x * 2.0)
            assert svc.expired == 1
            assert reliability_counters.get("deadline_expired") == 1
        finally:
            svc.close()

    def test_close_rejects_pending_instead_of_hanging(self, faults):
        svc = _service(delay_s=0.15, max_pending=16)
        x = np.ones(4, dtype=np.float32)
        first = svc.submit(x)
        time.sleep(0.05)
        queued = [svc.submit(x) for _ in range(4)]
        svc.close(drain=False)
        for f in queued:
            with pytest.raises(ServiceClosed):
                f.result(timeout=5)
        assert first.done()  # in-flight: served or failed, never stranded
        assert reliability_counters.get("futures_failed_on_close") == 4
        with pytest.raises(ServiceClosed):
            svc.submit(x)

    def test_draining_close_serves_everything(self, faults):
        svc = _service(delay_s=0.02, max_pending=64)
        x = np.ones(4, dtype=np.float32)
        futs = [svc.submit(x) for _ in range(8)]
        svc.close()  # default drain=True
        for f in futs:
            np.testing.assert_array_equal(f.result(timeout=5), x * 2.0)

    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning"
    )
    def test_worker_death_detected_and_restarted(self, faults):
        faults("worker_death:1")
        svc = _service(max_pending=16)
        try:
            x = np.ones(4, dtype=np.float32)
            first = svc.submit(x)  # wakes the worker into the injected death
            deadline = time.monotonic() + 5
            while svc._worker.is_alive() and time.monotonic() < deadline:
                time.sleep(0.01)
            assert not svc._worker.is_alive()
            second = svc.submit(x)  # detects the corpse, restarts
            assert svc.worker_restarts == 1
            assert reliability_counters.get("worker_restarts") == 1
            # Both requests still complete: pending survived the death.
            np.testing.assert_array_equal(first.result(timeout=5), x * 2.0)
            np.testing.assert_array_equal(second.result(timeout=5), x * 2.0)
        finally:
            svc.close()

    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning"
    )
    def test_close_after_worker_death_strands_nothing(self, faults):
        faults("worker_death:1")
        svc = _service(max_pending=16)
        x = np.ones(4, dtype=np.float32)
        fut = svc.submit(x)
        deadline = time.monotonic() + 5
        while svc._worker.is_alive() and time.monotonic() < deadline:
            time.sleep(0.01)
        svc.close()  # worker already dead: close must fail the future
        assert fut.done()
        with pytest.raises(ServiceClosed):
            fut.result(timeout=1)

    def test_sustained_overload_bounded_not_cliff(self, faults):
        """2x-capacity style hammering: excess fast-fails, accepted work
        completes, and EVERY future resolves one way or the other."""
        svc = _service(delay_s=0.01, max_pending=4)
        x = np.ones(4, dtype=np.float32)
        outcomes = {"ok": 0, "rejected": 0, "expired": 0}
        futs = []
        lock = threading.Lock()

        def client(n):
            for _ in range(n):
                try:
                    f = svc.submit(x, deadline_ms=250.0)
                    with lock:
                        futs.append(f)
                except QueueFullError:
                    with lock:
                        outcomes["rejected"] += 1
        threads = [
            threading.Thread(target=client, args=(25,)) for _ in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        svc.close()
        for f in futs:
            assert f.done()  # the no-stranded-future invariant
            try:
                f.result(timeout=0)
                outcomes["ok"] += 1
            except DeadlineExceeded:
                outcomes["expired"] += 1
            except ServiceClosed:
                pass
        assert outcomes["ok"] >= 1
        assert outcomes["rejected"] >= 1  # backpressure actually engaged
        assert outcomes["ok"] + outcomes["expired"] + outcomes[
            "rejected"
        ] <= 100
