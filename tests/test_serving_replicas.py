"""Multi-device serving: replica pool, pipelined dispatch, re-dispatch.

What is pinned here, mirroring ISSUE 6's acceptance gates:

1. replica-pool outputs are BIT-identical to the single-device engine on
   canonical fused chains — padding and replica choice must not change a
   single ulp (the same XLA program runs on same-kind devices);
2. with ``devices=1`` and in-flight window 1 the service takes the exact
   pre-pipelining serial flush path (the enabled-but-silent gate);
3. every replica serves traffic under a uniform trace — dispatch-balance
   counters within 3x — and a traced run shows temporally OVERLAPPING
   ``serve.device`` spans on distinct devices (the pipelining evidence);
4. chaos: a dead replica's in-flight groups re-dispatch to survivors
   with zero stranded futures; a fully dead pool revives; the pipelined
   dispatcher survives ``worker_death`` like the serial one;
5. the offline data-parallel path (``CompiledPipeline.apply_batches`` /
   ``Pipeline.apply_batches(engine=)``) preserves source order and
   matches per-batch serving;
6. per-instance metric namespacing: two services never share a
   queue-depth/in-flight gauge, and failed/expired/rejected requests
   land in an outcome-tagged registry counter;
7. the ``make bench-serve-replicas`` flow runs in-process (fast variant)
   with its bit-identity and balance gates green.
"""

import importlib.util
import os
import threading

import numpy as np
import pytest

from keystone_tpu.config import config
from keystone_tpu.utils import reliability
from keystone_tpu.utils.metrics import (
    active_tracer,
    metrics_registry,
    reliability_counters,
    reset_tracer,
)
from keystone_tpu.workflow.pipeline import FusedTransformer, Transformer
from keystone_tpu.workflow.serving import (
    CompiledPipeline,
    PipelineService,
    resolve_serve_devices,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def faults():
    """Arm a fault plan for the test (test_reliability's idiom)."""
    prior = (config.faults, config.faults_seed)
    reliability_counters.reset()

    def arm(spec: str, seed: int = 0):
        config.faults, config.faults_seed = spec, seed
        reliability.reset_fault_plan()
        return reliability.active_plan()

    arm("")
    yield arm
    config.faults, config.faults_seed = prior
    reliability.reset_fault_plan()
    reliability_counters.reset()


@pytest.fixture
def traced():
    """Arm process-wide tracing for the test (test_observability's
    idiom)."""
    prior = config.trace

    def arm(on: bool = True):
        config.trace = on
        reset_tracer()
        return active_tracer()

    try:
        yield arm
    finally:
        config.trace = prior
        reset_tracer()


def _head(d=8, D=16, k=3, seed=0):
    from keystone_tpu.nodes.learning.linear_mapper import LinearMapper
    from keystone_tpu.nodes.stats.hellinger import SignedHellingerMapper
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.nodes.stats.random_features import CosineRandomFeatures
    from keystone_tpu.nodes.stats.scalers import StandardScalerModel

    rng = np.random.default_rng(seed)
    return FusedTransformer(
        [
            StandardScalerModel(
                rng.normal(size=d).astype(np.float32),
                (1.0 + rng.uniform(size=d)).astype(np.float32),
            ),
            CosineRandomFeatures.create(d, D, seed=seed),
            SignedHellingerMapper(),
            L2Normalizer(),
            LinearMapper(rng.normal(size=(D, k)).astype(np.float32)),
        ]
    )


# ---------------------------------------------------------------------------
# Pool resolution + bit-identity
# ---------------------------------------------------------------------------


def test_resolve_serve_devices_validation():
    import jax

    local = jax.local_devices()
    assert resolve_serve_devices(0) == tuple(local)
    assert resolve_serve_devices(2) == tuple(local[:2])
    assert resolve_serve_devices([local[3], local[5]]) == (
        local[3], local[5],
    )
    with pytest.raises(ValueError, match="devices"):
        resolve_serve_devices(-1)
    with pytest.raises(ValueError, match="exceeds"):
        resolve_serve_devices(len(local) + 1)
    with pytest.raises(ValueError, match="empty"):
        resolve_serve_devices([])
    # An explicit inflight=0 must error, not silently take the default.
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer

    with pytest.raises(ValueError, match="inflight"):
        CompiledPipeline(L2Normalizer(), max_batch=8, inflight=0)


def test_replica_outputs_bit_identical_to_single_device(rng):
    """The acceptance gate: on canonical fused chains, every request's
    output from the pool equals the single-device engine's bit for bit —
    including oversize batches that shard across replicas."""
    from keystone_tpu.nodes.stats.hellinger import SignedHellingerMapper
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer

    d = 8
    chains = [
        _head(d=d),
        FusedTransformer([SignedHellingerMapper(), L2Normalizer()]),
    ]
    for chain in chains:
        cp1 = CompiledPipeline(chain, max_batch=16, devices=1).warmup((d,))
        cp4 = CompiledPipeline(chain, max_batch=16, devices=4).warmup((d,))
        # 1..16 exercise every bucket; 37/64 shard across the pool.
        for n in (1, 3, 9, 16, 37, 64):
            X = rng.normal(size=(n, d)).astype(np.float32)
            a, b = cp1(X), cp4(X)
            assert a.dtype == b.dtype and a.shape == b.shape
            assert np.array_equal(a, b), (type(chain).__name__, n)


def test_oversize_batches_shard_across_replicas(rng):
    """A batch beyond the top bucket spreads its chunks over the pool
    instead of chunking serially through one device."""
    d = 4
    cp = CompiledPipeline(_head(d=d), max_batch=8, devices=4).warmup((d,))
    X = rng.normal(size=(8 * 6, d)).astype(np.float32)
    out = cp(X)
    assert out.shape == (48, 3)
    dispatches = cp.stats()["replica_dispatches"]
    assert sum(dispatches.values()) == 6
    assert sum(1 for v in dispatches.values() if v > 0) >= 2


# ---------------------------------------------------------------------------
# Dispatch: serial gate, balance, span overlap
# ---------------------------------------------------------------------------


def test_single_replica_window1_takes_serial_path(rng):
    """devices=1 + inflight=1 = the pre-pipelining serial flush loop (the
    enabled-but-silent discipline): no completion threads, same results."""
    d = 4
    cp = CompiledPipeline(_head(d=d), max_batch=8, devices=1).warmup((d,))
    with PipelineService(cp, max_delay_ms=1.0, inflight=1) as svc:
        assert svc._pipelined is False
        assert svc._completers == []
        x = rng.normal(size=(d,)).astype(np.float32)
        out = svc.submit(x).result(timeout=30)
        np.testing.assert_allclose(out, cp(x[None])[0], rtol=2e-6, atol=2e-6)
        assert svc.stats()["replicas"]["count"] == 1
    # Default devices (the whole local mesh) + default window pipelines.
    cp_all = CompiledPipeline(_head(d=d), max_batch=8).warmup((d,))
    with PipelineService(cp_all, max_delay_ms=1.0) as svc:
        assert svc._pipelined is True
        assert len(svc._completers) == len(cp_all.replicas)


def test_service_dispatch_balance_uniform_trace(rng):
    """The 160-request acceptance trace: every replica serves traffic and
    the dispatch-balance counters stay within 3x, while every output
    matches a single-device reference."""
    d = 8
    cp1 = CompiledPipeline(_head(d=d), max_batch=64, devices=1).warmup((d,))
    cp = CompiledPipeline(_head(d=d), max_batch=64, devices=4).warmup((d,))
    trace = [
        rng.normal(size=(int(rng.integers(1, 65)), d)).astype(np.float32)
        for _ in range(160)
    ]
    errs: list = []

    def client(cid, svc):
        try:
            for i in range(cid, len(trace), 4):
                out = svc.submit(trace[i]).result(timeout=60)
                # Coalescing can serve the request inside a different
                # bucket than a solo call — equal to gemm-shape (last
                # ulp) tolerance, as for the single-device service.
                np.testing.assert_allclose(
                    out, cp1(trace[i]), rtol=2e-6, atol=2e-6
                )
        except Exception as e:  # pragma: no cover - surfaced below
            errs.append(e)

    with PipelineService(cp, max_delay_ms=0.5, inflight=2) as svc:
        threads = [
            threading.Thread(target=client, args=(k, svc)) for k in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats = svc.stats()
    assert not errs, errs[:2]
    dispatches = stats["compiled"]["replica_dispatches"]
    assert len(dispatches) == 4
    assert min(dispatches.values()) > 0  # every replica served traffic
    assert max(dispatches.values()) <= 3 * min(dispatches.values())
    # The registry mirror carries the same balance, per-instance.
    reg = metrics_registry.counters(
        f"serve.dispatch[{cp.name}]"
    ).snapshot()
    assert reg == dispatches
    assert stats["outcomes"]["ok"] == 160


def test_overlapping_serve_device_spans_on_distinct_devices(rng, traced):
    """The pipelining evidence: a traced multi-replica run must contain
    >=2 serve.device spans on DISTINCT devices whose [start, end]
    intervals overlap — replica B computing while replica A's results
    materialize."""
    tr = traced(True)
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.nodes.stats.random_features import CosineRandomFeatures

    chain = FusedTransformer(
        [CosineRandomFeatures.create(32, 512, seed=0), L2Normalizer()]
    )
    cp = CompiledPipeline(chain, max_batch=64, devices=4).warmup((32,))
    trace = [
        rng.normal(size=(int(rng.integers(16, 65)), 32)).astype(np.float32)
        for _ in range(48)
    ]
    errs: list = []

    def client(cid, svc):
        try:
            for i in range(cid, len(trace), 4):
                svc.submit(trace[i]).result(timeout=60)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    with PipelineService(cp, max_delay_ms=0.5, inflight=2) as svc:
        threads = [
            threading.Thread(target=client, args=(k, svc)) for k in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not errs
    spans = [s for s in tr.spans() if s["name"] == "serve.device"]
    assert {s["args"]["device"] for s in spans} >= {0, 1}
    ivals = [
        (s["start_ns"], s["start_ns"] + s["dur_ns"], s["args"]["device"])
        for s in spans
    ]
    overlapping = any(
        a[2] != b[2] and a[0] < b[1] and b[0] < a[1]
        for i, a in enumerate(ivals)
        for b in ivals[i + 1:]
    )
    assert overlapping, "no temporally overlapping spans across devices"


# ---------------------------------------------------------------------------
# Chaos: replica death, pool revival, dispatcher death
# ---------------------------------------------------------------------------


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_replica_death_redispatches_zero_stranded(rng, faults):
    """KEYSTONE_FAULTS replica_death with >=2 replicas: the dead
    replica's in-flight groups re-queue and the survivors serve them —
    every future resolves with the right value."""
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer

    faults("replica_death:1")
    cp = CompiledPipeline(
        L2Normalizer(), max_batch=16, devices=4
    ).warmup((8,))
    ref = CompiledPipeline(
        L2Normalizer(), max_batch=16, devices=1
    ).warmup((8,))
    xs = [rng.normal(size=(3, 8)).astype(np.float32) for _ in range(60)]
    svc = PipelineService(cp, max_delay_ms=0.5, inflight=2)
    try:
        futs = [svc.submit(x) for x in xs]
        for x, f in zip(xs, futs):
            np.testing.assert_allclose(
                f.result(timeout=30), ref(x), rtol=2e-6, atol=2e-6
            )
        stats = svc.stats()
        assert stats["replicas"]["deaths"] == 1
        # The dead replica either still shows dead (death after the last
        # submit) or has already been revived by a later submit — both
        # are healthy; what may NOT happen is a stranded future.
        assert (
            sum(stats["replicas"]["dead"]) == 1
            or stats["replicas"]["revivals"] >= 1
        )
        assert reliability_counters.get("replica_deaths") == 1
        assert all(f.done() for f in futs)  # zero stranded
        # ...and zero leaked slots: the dead replica's abandoned launches
        # released their engine-level outstanding counts, so direct-call
        # least-outstanding dispatch isn't biased away from it forever.
        assert all(
            v == 0 for v in cp.stats()["replica_outstanding"].values()
        )
    finally:
        svc.close()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_whole_pool_death_revives(rng, faults):
    """A single-replica pipelined pool whose one replica dies revives
    itself: service keeps serving, nothing stranded."""
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer

    faults("replica_death:1")
    cp = CompiledPipeline(
        L2Normalizer(), max_batch=16, devices=1
    ).warmup((8,))
    xs = [rng.normal(size=(2, 8)).astype(np.float32) for _ in range(20)]
    svc = PipelineService(cp, max_delay_ms=0.5, inflight=2)
    try:
        futs = [svc.submit(x) for x in xs]
        outs = [f.result(timeout=30) for f in futs]
        assert len(outs) == 20
        stats = svc.stats()
        assert stats["replicas"]["deaths"] == 1
        assert stats["replicas"]["revivals"] == 1
        assert reliability_counters.get("replica_revivals") == 1
    finally:
        svc.close()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_dead_replica_heals_on_next_submit(rng, faults):
    """A partially dead pool must not serve at reduced capacity forever:
    the next submit revives dead replicas (the worker-death detection
    point), restoring full width."""
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer

    faults("replica_death:1")
    cp = CompiledPipeline(
        L2Normalizer(), max_batch=16, devices=2
    ).warmup((8,))
    svc = PipelineService(cp, max_delay_ms=0.5, inflight=2)
    try:
        x = rng.normal(size=(2, 8)).astype(np.float32)
        futs = [svc.submit(x) for _ in range(30)]
        for f in futs:
            f.result(timeout=30)
        assert svc.replica_deaths == 1
        # Post-drain submit: detects and revives whatever is still dead.
        svc.submit(x).result(timeout=30)
        stats = svc.stats()
        assert sum(stats["replicas"]["dead"]) == 0
        assert stats["replicas"]["revivals"] >= 1
        assert reliability_counters.get("replica_revivals") >= 1
    finally:
        svc.close()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_worker_death_pipelined_restart(rng, faults):
    """The worker_death site under the PIPELINED dispatcher: submit
    detects the corpse, restarts it, queued work drains, launched groups
    (owned by the completion threads) are unaffected."""
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer

    faults("worker_death:1")
    cp = CompiledPipeline(
        L2Normalizer(), max_batch=16, devices=2
    ).warmup((8,))
    svc = PipelineService(cp, max_delay_ms=0.5, inflight=2)
    try:
        x = rng.normal(size=(2, 8)).astype(np.float32)
        first = svc.submit(x)  # wakes the dispatcher into the death
        import time

        deadline = time.monotonic() + 5
        while svc._worker.is_alive() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not svc._worker.is_alive()
        second = svc.submit(x)  # detects the corpse, restarts
        assert svc.worker_restarts == 1
        np.testing.assert_allclose(
            first.result(timeout=30), cp(x), rtol=2e-6, atol=2e-6
        )
        np.testing.assert_allclose(
            second.result(timeout=30), cp(x), rtol=2e-6, atol=2e-6
        )
    finally:
        svc.close()


def test_deadline_expires_during_slot_wait(rng):
    """A request whose deadline lapses while the dispatcher waits for an
    in-flight slot must fail with DeadlineExceeded BEFORE the device call
    (the PR-3 contract), not get served late."""
    import time

    from keystone_tpu.utils.reliability import DeadlineExceeded

    d = 4
    cp = CompiledPipeline(_head(d=d), max_batch=8, devices=1).warmup((d,))

    class SlowAsyncEngine:
        """Delays result materialization so the one replica's in-flight
        window stays full long enough for a queued group to expire."""

        def __init__(self, inner, delay):
            self._inner, self._delay = inner, delay

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def call_async(self, X, **kw):
            handle = self._inner.call_async(X, **kw)
            delay = self._delay

            class _H:
                def wait(self):
                    time.sleep(delay)
                    return handle.wait()

                def abandon(self):
                    handle.abandon()

            return _H()

    svc = PipelineService(
        SlowAsyncEngine(cp, 0.25), max_delay_ms=0.5, max_rows=2,
        inflight=2,
    )
    try:
        assert svc._pipelined
        x = np.ones((2, d), np.float32)
        a = svc.submit(x)  # fills slot 1
        b = svc.submit(x)  # fills slot 2: window full for ~0.25s
        time.sleep(0.02)
        doomed = svc.submit(x, deadline_ms=50.0)
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=10)
        np.testing.assert_allclose(
            a.result(timeout=10), cp(x), rtol=2e-6, atol=2e-6
        )
        np.testing.assert_allclose(
            b.result(timeout=10), cp(x), rtol=2e-6, atol=2e-6
        )
        assert svc.expired == 1
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# Offline data parallelism
# ---------------------------------------------------------------------------


def test_engine_apply_batches_order_and_equivalence(rng):
    """The data-parallel offline apply: batches round-robin over the pool
    with a bounded async window, results come back in source order and
    bit-equal to per-batch serving; labels pass through."""
    d = 8
    cp = CompiledPipeline(_head(d=d), max_batch=32, devices=4).warmup((d,))
    batches = [
        (
            rng.normal(size=(int(rng.integers(1, 33)), d)).astype(np.float32),
            np.full(1, i),
        )
        for i in range(17)
    ]
    got = list(cp.apply_batches(iter(batches), prefetch_depth=2))
    assert len(got) == 17
    for i, ((X, y), (out, y_out)) in enumerate(zip(batches, got)):
        assert y_out is y  # label passthrough, source order
        assert np.array_equal(out, cp(X)), i
    # Bare batches (no labels) work too.
    bare = list(cp.apply_batches([b[0] for b in batches[:3]]))
    assert all(y is None for _, y in bare)
    dispatches = cp.stats()["replica_dispatches"]
    assert sum(1 for v in dispatches.values() if v > 0) >= 2


def test_pipeline_apply_batches_engine_path(rng):
    """Pipeline.apply_batches(engine=...) routes the stream through the
    replica pool; outputs match graph execution to float tolerance (the
    padded-bucket executables can differ in the last ulp)."""
    from keystone_tpu.nodes.stats.normalizer import L2Normalizer
    from keystone_tpu.nodes.stats.scalers import StandardScaler

    d = 6
    Xtrain = rng.normal(size=(32, d)).astype(np.float32)
    pipe = StandardScaler().with_data(Xtrain).and_then(L2Normalizer())
    fitted = pipe.fit()
    engine = fitted.compiled(max_batch=16, devices=2).warmup((d,))
    batches = [
        (rng.normal(size=(5, d)).astype(np.float32), None) for _ in range(4)
    ]
    via_engine = list(fitted.apply_batches(iter(batches), engine=engine))
    via_graph = list(fitted.apply_batches(iter(batches)))
    assert len(via_engine) == len(via_graph) == 4
    for (a, _), (b, _) in zip(via_engine, via_graph):
        np.testing.assert_allclose(
            a, np.asarray(b), rtol=2e-6, atol=2e-6
        )


# ---------------------------------------------------------------------------
# Per-instance metrics + outcome counters (satellites)
# ---------------------------------------------------------------------------


def test_per_service_metric_namespacing(rng):
    """Two services in one process own DISTINCT registry gauges — no more
    get-or-create collisions overwriting each other's queue depth."""
    d = 4
    cp = CompiledPipeline(_head(d=d), max_batch=8, devices=1).warmup((d,))
    svc_a = PipelineService(cp, max_delay_ms=1.0, inflight=1)
    svc_b = PipelineService(cp, max_delay_ms=1.0, inflight=1)
    try:
        assert svc_a.name != svc_b.name
        names = metrics_registry.names()
        for svc in (svc_a, svc_b):
            assert f"serve.queue_depth[{svc.name}]" in names
            assert f"serve.inflight[{svc.name}]" in names
            assert f"serve.requests[{svc.name}]" in names
        ga = metrics_registry.gauge(f"serve.queue_depth[{svc_a.name}]")
        gb = metrics_registry.gauge(f"serve.queue_depth[{svc_b.name}]")
        assert ga is not gb
        # Engine-level per-replica metrics are namespaced too.
        dev0 = cp.devices[0]
        assert f"serve.outstanding[{cp.name}:d{dev0.id}]" in names
        assert f"serve.dispatch[{cp.name}]" in names
    finally:
        svc_a.close()
        svc_b.close()


def test_outcome_counters_count_rejected_and_expired(rng):
    """The satellite fix: rejected/expired requests land in the
    outcome-tagged registry counter, so overload analyses see failed
    work, not just the successes."""
    import time

    d = 4
    cp = CompiledPipeline(_head(d=d), max_batch=8, devices=1).warmup((d,))

    class Slowed:
        def __init__(self, inner, delay):
            self._inner, self._delay = inner, delay

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def __call__(self, X):
            time.sleep(self._delay)
            return self._inner(X)

    from keystone_tpu.utils.reliability import QueueFullError

    svc = PipelineService(
        Slowed(cp, 0.15), max_delay_ms=1.0, inflight=1, max_pending=2
    )
    try:
        x = np.ones(d, np.float32)
        first = svc.submit(x)  # occupies the worker
        time.sleep(0.05)
        doomed = svc.submit(x, deadline_ms=20.0)  # expires in queue
        held = svc.submit(x)
        with pytest.raises(QueueFullError):
            svc.submit(x)  # queue full: rejected
        first.result(timeout=5)
        held.result(timeout=5)
        with pytest.raises(Exception):
            doomed.result(timeout=5)
        outcomes = metrics_registry.counters(
            f"serve.requests[{svc.name}]"
        ).snapshot()
        assert outcomes["rejected"] == 1
        assert outcomes["expired"] == 1
        assert outcomes["ok"] == 2
    finally:
        svc.close()


def test_error_path_span_carries_rows(rng, traced):
    """The satellite fix: serve.request error spans carry the same `rows`
    attr the ok spans do."""
    tr = traced(True)
    d = 4
    cp = CompiledPipeline(_head(d=d), max_batch=8, devices=1).warmup((d,))

    class Exploding:
        def __init__(self, inner):
            self._inner = inner

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def __call__(self, X):
            raise RuntimeError("injected flush failure")

    svc = PipelineService(Exploding(cp), max_delay_ms=1.0, inflight=1)
    try:
        fut = svc.submit(np.ones((3, d), np.float32))
        with pytest.raises(RuntimeError, match="injected"):
            fut.result(timeout=10)
    finally:
        svc.close()
    spans = [
        s for s in tr.spans()
        if s["name"] == "serve.request"
        and s["args"].get("outcome") == "RuntimeError"
    ]
    assert spans and all(s["args"]["rows"] == 3 for s in spans)
    outcomes = metrics_registry.counters(
        f"serve.requests[{svc.name}]"
    ).snapshot()
    assert outcomes["error"] == 1


# ---------------------------------------------------------------------------
# bench-serve-replicas (the `make` flow, in-process fast variant)
# ---------------------------------------------------------------------------


def test_replica_bench_inprocess():
    """The tier-1 stand-in for `make bench-serve-replicas`: small trace,
    full gate surface. Timing-dependent throughput is recorded but only
    the structural gates (bit-identity, balance, coverage) are asserted —
    the >=1.3x scaling gate binds on >=2-core hosts per the fingerprint."""
    import argparse

    spec = importlib.util.spec_from_file_location(
        "bench_serve", os.path.join(REPO, "tools", "bench_serve.py")
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    args = argparse.Namespace(
        devices=4, requests=24, max_batch=16, d=8, features=64, classes=4,
        seed=0, service_clients=4, inflight=2,
    )
    row = bench.run_replica_bench(args)
    assert row["metric"] == "serve_replica_scaling"
    assert row["devices_swept"] == [1, 4]
    assert row["pass"]["outputs_bit_identical"] is True
    assert row["pass"]["every_replica_served"] is True
    assert row["pass"]["balance_max_min_le_3x"] is True
    assert isinstance(row["speedup_vs_single"], float)
    assert row["env"]["cpu_count"] == os.cpu_count()
    assert row["pass"]["throughput_gate_is_hard"] == (os.cpu_count() >= 2)
