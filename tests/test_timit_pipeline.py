"""TIMIT pipeline integration test."""

from keystone_tpu.loaders.timit import TimitFeaturesDataLoader
from keystone_tpu.pipelines.speech.timit import TimitConfig, run


def test_timit_synthetic_loader_shapes():
    train, test = TimitFeaturesDataLoader.synthetic(
        n=256, num_phones=8, frame_dim=10, context=2
    )
    assert train.data.shape == (256, 50)
    assert int(train.labels.max()) < 8


def test_timit_pipeline_end_to_end():
    out = run(
        TimitConfig(
            synthetic_n=2048,
            num_features=1024,
            num_phones=12,
            num_iters=2,
            gamma=0.1,
        )
    )
    # Synthetic phone clusters are separable; random-feature + block LS
    # should land well above the 1/12 chance floor.
    assert out["test_accuracy"] > 0.85, out["summary"]
