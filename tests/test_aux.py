"""Auxiliary subsystems: profiler, auto-cache, node-level optimization,
serialization, checkpoint/resume, metrics."""

import numpy as np
import pytest

import jax.numpy as jnp

from keystone_tpu.workflow import Estimator, Pipeline, PipelineEnv, Transformer


class Plus(Transformer):
    def __init__(self, c):
        self.c = c
        self.calls = 0

    def apply_batch(self, X):
        self.calls += 1
        return X + self.c


class CountingHost(Transformer):
    jittable = False

    def __init__(self):
        self.calls = 0

    def apply_batch(self, X):
        self.calls += 1
        return np.asarray(X) * 2.0


def test_profiler_measures_nodes(rng):
    from keystone_tpu.workflow.cache import Profiler

    X = rng.normal(size=(256, 4)).astype(np.float32)
    ds = Plus(1.0).and_then(Plus(2.0))(X)
    profiles = Profiler(sample_rows=32).profile(ds.graph, [ds.sink])
    assert len(profiles) == 3  # dataset + 2 transformers
    for p in profiles.values():
        assert p.bytes > 0 and p.seconds >= 0
    # Scale estimate: 256 rows / 32 sampled.
    assert any(abs(p.scale - 8.0) < 1e-6 for p in profiles.values())


def test_explicit_cache_persists_across_executions(rng):
    host = CountingHost()
    X = rng.normal(size=(8, 3)).astype(np.float32)
    p = host.to_pipeline().cache()
    out1 = np.asarray(p(X).get())
    assert host.calls == 1
    # New application => new graph copy; the session cache must hit.
    out2 = np.asarray(p(X).get())
    assert host.calls == 1
    np.testing.assert_array_equal(out1, out2)


def test_auto_cache_rule_inserts_cache_nodes(rng):
    from keystone_tpu.workflow.cache import CacheOperator
    from keystone_tpu.workflow.rules import AutoCacheRule

    X = rng.normal(size=(128, 4)).astype(np.float32)
    base = CountingHost().to_pipeline()
    p = Pipeline.gather([base.and_then(Plus(1.0)), base.and_then(Plus(2.0))])
    ds = p(X)
    g = AutoCacheRule(budget_bytes=1 << 30, sample_rows=16).apply(
        ds.graph, [ds.sink]
    )
    cache_nodes = [
        op for op in g.operators.values() if isinstance(op, CacheOperator)
    ]
    assert cache_nodes  # profitable shared nodes got cached
    # Graph still executes correctly with caches inserted.
    out = PipelineEnv.get().executor.execute(g, ds.sink)
    assert np.asarray(out).shape == (128, 8)


def test_node_optimization_rule_swaps_estimator(rng):
    from keystone_tpu.nodes.learning import (
        LeastSquaresEstimator,
        LocalLeastSquaresEstimator,
    )
    from keystone_tpu.workflow.operators import EstimatorOperator

    X = rng.normal(size=(40, 5)).astype(np.float32)
    Y = rng.normal(size=(40, 2)).astype(np.float32)
    est = LeastSquaresEstimator(lam=0.1)
    p = est.with_data(X, Y)
    ds = p(X)
    g = PipelineEnv.get().optimizer.execute(ds.graph, [ds.sink])
    est_ops = [
        op for op in g.operators.values() if isinstance(op, EstimatorOperator)
    ]
    assert len(est_ops) == 1
    assert isinstance(est_ops[0].estimator, LocalLeastSquaresEstimator)
    assert est.last_choice.name == "local"


def test_save_load_fitted_pipeline(rng, tmp_path):
    from keystone_tpu.workflow.serialization import load_pipeline, save_pipeline

    class MeanShift(Estimator):
        def fit(self, data):
            return Plus(-jnp.mean(jnp.asarray(data), axis=0))

    X = rng.normal(size=(32, 4)).astype(np.float32)
    p = Plus(1.0).and_then(MeanShift(), X).fit()
    path = str(tmp_path / "model.pkl")
    save_pipeline(p, path)
    loaded = load_pipeline(path)
    np.testing.assert_allclose(
        np.asarray(loaded(X[:4]).get()),
        np.asarray(p(X[:4]).get()),
        atol=1e-6,
    )


def test_save_rejects_unfitted_pipeline(rng, tmp_path):
    from keystone_tpu.workflow.serialization import save_pipeline

    class E(Estimator):
        def fit(self, data):
            return Plus(0.0)

    p = E().with_data(np.ones((4, 2), dtype=np.float32))
    with pytest.raises(ValueError, match="unfitted"):
        save_pipeline(p, str(tmp_path / "x.pkl"))


def test_bcd_checkpoint_resume(rng, tmp_path):
    from keystone_tpu.linalg import RowMatrix, block_coordinate_descent
    from keystone_tpu.linalg.bcd import assemble_blocks

    X = rng.normal(size=(160, 16)).astype(np.float32)
    Y = rng.normal(size=(160, 2)).astype(np.float32)
    A, B = RowMatrix.from_array(X), RowMatrix.from_array(Y)
    ck = str(tmp_path / "bcd")
    # Full 4-epoch run without checkpointing = reference result.
    W_ref, blocks = block_coordinate_descent(A, B, 8, 4, lam=0.1)
    # Run 2 epochs with checkpointing, then "crash" and resume to 4.
    block_coordinate_descent(A, B, 8, 2, lam=0.1, checkpoint_dir=ck)
    W_resumed, _ = block_coordinate_descent(
        A, B, 8, 4, lam=0.1, checkpoint_dir=ck
    )
    np.testing.assert_allclose(
        assemble_blocks(W_resumed),
        assemble_blocks(W_ref),
        rtol=1e-4,
        atol=1e-4,
    )


def test_stage_timer_and_cost_analysis(rng):
    from keystone_tpu.utils.metrics import achieved_tflops, cost_analysis, stage_timer

    sink = {}
    with stage_timer("featurize", sink):
        pass
    assert "featurize" in sink

    X = jnp.asarray(rng.normal(size=(64, 64)).astype(np.float32))
    cost = cost_analysis(lambda a: a @ a, X)
    # 2 n^3 FLOPs for a square matmul.
    assert cost["flops"] == pytest.approx(2 * 64**3, rel=0.1)
    perf = achieved_tflops(lambda a: a @ a, X, repeats=2)
    assert perf["tflops"] > 0


def test_fit_and_save_with_auto_cache_enabled(rng, tmp_path):
    from keystone_tpu.config import config
    from keystone_tpu.nodes.learning import LinearMapEstimator
    from keystone_tpu.nodes.util import ClassLabelIndicators, MaxClassifier
    from keystone_tpu.workflow import PipelineEnv
    from keystone_tpu.workflow.serialization import load_pipeline, save_pipeline

    config.auto_cache = True
    PipelineEnv.reset()  # rebuild the optimizer with the auto-cache batch
    try:
        X = rng.normal(size=(60, 8)).astype(np.float32)
        y = rng.integers(0, 3, 60).astype(np.int32)
        p = (
            LinearMapEstimator(0.1)
            .with_data(X, ClassLabelIndicators(3)(y))
            .and_then(MaxClassifier())
            .fit()
        )
        path = str(tmp_path / "m.pkl")
        save_pipeline(p, path)  # must not see any estimator nodes
        loaded = load_pipeline(path)
        np.testing.assert_array_equal(
            np.asarray(loaded(X[:5]).get()), np.asarray(p(X[:5]).get())
        )
    finally:
        config.auto_cache = False
        PipelineEnv.reset()


def test_bcd_checkpoint_rejects_different_problem(rng, tmp_path):
    from keystone_tpu.linalg import RowMatrix, block_coordinate_descent
    from keystone_tpu.linalg.bcd import assemble_blocks

    ck = str(tmp_path / "bcd")
    X1 = rng.normal(size=(80, 8)).astype(np.float32)
    Y1 = rng.normal(size=(80, 2)).astype(np.float32)
    block_coordinate_descent(
        RowMatrix.from_array(X1), RowMatrix.from_array(Y1), 8, 2,
        lam=0.1, checkpoint_dir=ck,
    )
    # Same shapes, different data: stale checkpoint must NOT be restored.
    X2 = rng.normal(size=(80, 8)).astype(np.float32)
    Y2 = rng.normal(size=(80, 2)).astype(np.float32)
    W2, blocks = block_coordinate_descent(
        RowMatrix.from_array(X2), RowMatrix.from_array(Y2), 8, 2,
        lam=0.1, checkpoint_dir=ck,
    )
    W_fresh, _ = block_coordinate_descent(
        RowMatrix.from_array(X2), RowMatrix.from_array(Y2), 8, 2, lam=0.1
    )
    np.testing.assert_allclose(
        assemble_blocks(W2), assemble_blocks(W_fresh),
        rtol=1e-5, atol=1e-5,
    )


def test_gmm_fisher_estimator_tpu_backend_without_native(rng):
    from keystone_tpu.nodes.images.external import GMMFisherVectorEstimator

    X = np.concatenate(
        [rng.normal(-2, 0.5, (300, 4)), rng.normal(2, 0.8, (300, 4))]
    ).astype(np.float32)
    fv = GMMFisherVectorEstimator(k=2, em_iters=30, gmm_backend="tpu").fit(X)
    means = np.sort(np.asarray(fv.means)[:, 0])
    np.testing.assert_allclose(means, [-2, 2], atol=0.3)
    out = np.asarray(fv(rng.normal(size=(3, 20, 4)).astype(np.float32)))
    assert out.shape == (3, 2 * 2 * 4)


def test_cacher_node_parity(rng):
    from keystone_tpu.nodes.util import Cacher

    host = CountingHost()
    X = rng.normal(size=(4, 2)).astype(np.float32)
    p = host.to_pipeline().and_then(Cacher())
    p(X).get()
    p(X).get()
    assert host.calls == 1  # identical to pipeline.cache()


def test_pil_conversions(rng):
    from keystone_tpu.utils.image import from_pil, to_pil

    arr = rng.uniform(size=(10, 12, 3)).astype(np.float32)
    back = from_pil(to_pil(arr))
    assert back.shape == (10, 12, 3)
    np.testing.assert_allclose(back, arr, atol=0.5 / 255 + 1e-6)
    resized = from_pil(to_pil(arr), size=6)
    assert resized.shape == (6, 6, 3)
