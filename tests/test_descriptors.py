"""HOG / DAISY / Cropper / Densify-Sparsify unit tests."""

import numpy as np
import pytest

from keystone_tpu.nodes.images import Cropper, DaisyExtractor, HogExtractor
from keystone_tpu.nodes.util import Densify, Sparsify


def test_hog_shapes_and_orientation(rng):
    X = rng.uniform(size=(2, 32, 32, 1)).astype(np.float32)
    out = np.asarray(HogExtractor(cell_size=8, num_bins=9)(X))
    # 4x4 cells -> 3x3 blocks of 4*9 values.
    assert out.shape == (2, 3 * 3 * 36)
    # L2-hys: nonnegative, renormalized after the 0.2 clip (so entries can
    # exceed 0.2 but each block stays unit-or-less norm).
    assert np.all(out >= 0) and np.all(out <= 1.0 + 1e-5)
    # A pure vertical ramp (gradient along y) must put its energy in the
    # bin containing theta = pi/2.
    ramp = np.tile(
        (np.arange(32, dtype=np.float32) / 31.0)[:, None], (1, 32)
    )[None, ..., None]
    desc = np.asarray(HogExtractor(cell_size=8, num_bins=9)(ramp))
    per_bin = desc.reshape(-1, 9).sum(axis=0)
    assert np.argmax(per_bin) == 4  # bin 4 of 9 covers [4pi/9, 5pi/9) ∋ pi/2


def test_hog_handles_rgb(rng):
    X = rng.uniform(size=(1, 16, 16, 3)).astype(np.float32)
    out = np.asarray(HogExtractor(cell_size=8)(X))
    assert out.shape[0] == 1 and np.isfinite(out).all()


def test_daisy_shapes_and_normalization(rng):
    X = rng.uniform(size=(2, 48, 48, 1)).astype(np.float32)
    node = DaisyExtractor(step=16, radius=8, rings=2, ring_points=4)
    out = np.asarray(node(X))
    assert out.shape[0] == 2 and out.shape[2] == node.descriptor_dim
    # Each histogram sample is L2-normalized (or zero).
    hist = out.reshape(2, out.shape[1], -1, node.num_bins)
    norms = np.linalg.norm(hist, axis=-1)
    assert np.all((np.abs(norms - 1.0) < 1e-3) | (norms < 1e-6))


def test_daisy_rejects_tiny_images(rng):
    X = rng.uniform(size=(1, 10, 10, 1)).astype(np.float32)
    with pytest.raises(ValueError, match="smaller than the DAISY radius"):
        DaisyExtractor(radius=12)(X)


def test_cropper(rng):
    X = rng.uniform(size=(2, 8, 8, 3)).astype(np.float32)
    out = np.asarray(Cropper(2, 3, 4, 5)(X))
    np.testing.assert_allclose(out, X[:, 2:6, 3:8, :])


def test_densify_sparsify_roundtrip(rng):
    X = (rng.uniform(size=(3, 6)) > 0.5).astype(np.float32) * rng.uniform(
        size=(3, 6)
    ).astype(np.float32)
    docs = Sparsify()(X)
    back = Densify(6)(docs)
    np.testing.assert_allclose(back, X, atol=1e-6)


def test_gradients_edge_clamped():
    # A bright right edge must not leak into left-border gradients.
    import jax.numpy as jnp

    from keystone_tpu.utils.image import clamped_gradients

    g = np.zeros((1, 8, 8), dtype=np.float32)
    g[0, :, -1] = 10.0
    gx, _ = clamped_gradients(jnp.asarray(g))
    np.testing.assert_allclose(np.asarray(gx)[0, :, 0], 0.0)
    assert np.all(np.asarray(gx)[0, :, -2] > 0)


def test_cropper_rejects_out_of_bounds(rng):
    X = rng.uniform(size=(1, 8, 8, 1)).astype(np.float32)
    with pytest.raises(ValueError, match="exceeds image"):
        Cropper(0, 0, 16, 16)(X)
    with pytest.raises(ValueError, match="invalid crop"):
        Cropper(-1, 0, 4, 4)


def test_densify_rejects_bad_index():
    with pytest.raises(ValueError, match="out of range"):
        Densify(4)([{-1: 3.0}])


def test_hog_rejects_tiny_images(rng):
    X = rng.uniform(size=(1, 12, 12, 1)).astype(np.float32)
    with pytest.raises(ValueError, match="too small for HOG"):
        HogExtractor(cell_size=8)(X)


class TestXlaSift:
    """The on-chip dense SIFT must match the clean-room native kernel —
    same grid, same soft binning, same normalization."""

    def _native_or_skip(self):
        from keystone_tpu import native

        if not native.available():
            pytest.skip(f"native lib unavailable: {native.build_error()}")
        return native

    def test_parity_with_native_kernel(self, rng):
        native = self._native_or_skip()
        from keystone_tpu.ops.sift_xla import dense_sift_xla

        imgs = rng.uniform(size=(3, 48, 40)).astype(np.float32)
        ref = native.dense_sift(imgs, step=4, bin_size=4)
        got = np.asarray(dense_sift_xla(imgs, step=4, bin_size=4))
        assert got.shape == ref.shape  # same dense grid
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)

    def test_parity_nondefault_geometry(self, rng):
        native = self._native_or_skip()
        from keystone_tpu.ops.sift_xla import dense_sift_xla

        imgs = rng.uniform(size=(2, 37, 53)).astype(np.float32)
        ref = native.dense_sift(imgs, step=5, bin_size=3)
        got = np.asarray(dense_sift_xla(imgs, step=5, bin_size=3))
        assert got.shape == ref.shape
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)

    def test_flat_image_zero_descriptors(self):
        from keystone_tpu.ops.sift_xla import dense_sift_xla

        flat = np.full((1, 32, 32), 0.5, dtype=np.float32)
        d = np.asarray(dense_sift_xla(flat, step=4, bin_size=4))
        np.testing.assert_allclose(d, 0.0, atol=1e-7)

    def test_extractor_backend_xla_matches_native(self, rng):
        self._native_or_skip()
        from keystone_tpu.nodes.images.external import SIFTExtractor

        imgs = rng.uniform(size=(2, 40, 40, 1)).astype(np.float32)
        a = SIFTExtractor(step=4, backend="native")(imgs)
        b = SIFTExtractor(step=4, backend="xla")(imgs)
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(a), rtol=2e-4, atol=2e-4
        )
        assert SIFTExtractor(step=4, backend="xla").jittable
        assert not SIFTExtractor(step=4, backend="native").jittable
        with pytest.raises(ValueError):
            SIFTExtractor(backend="cuda")


@pytest.mark.slow
def test_xla_sift_parity_at_reference_geometry(rng):
    """256px / step 4 / bin 4 — the EXACT geometry the host-elimination
    claim rides on (tools/northstar.py): parity with the native kernel AND
    descriptor-count equality with HOSTBENCH's 3,721/img grid
    (VERDICT r3 weak #7 / next #7)."""
    from keystone_tpu import native

    if not native.available():
        pytest.skip(f"native lib unavailable: {native.build_error()}")
    from keystone_tpu.ops.sift_xla import dense_sift_xla

    imgs = rng.uniform(size=(4, 256, 256)).astype(np.float32)
    ref = native.dense_sift(imgs, step=4, bin_size=4)
    got = np.asarray(dense_sift_xla(imgs, step=4, bin_size=4))
    assert got.shape == ref.shape
    # (256 - 16)/4 + 1 = 61 keypoints per axis -> 3721/img (HOSTBENCH.json).
    assert got.shape[1] == 61 * 61 == 3721
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)
