"""Least squares via normal equations.

Ref: ml-matrix `NormalEquations.solveLeastSquares` — AᵀA and AᵀB accumulated
with `treeAggregate`, Cholesky solve on the driver (SURVEY.md §2.2, §3.2)
[unverified]. Here: per-shard grams + `psum` over ICI, replicated on-device
Cholesky (every chip solves the small (d, d) system redundantly — cheaper
than shipping it anywhere).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax import lax

from keystone_tpu.utils.compat import shard_map
from jax.scipy.linalg import cho_factor, cho_solve
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from keystone_tpu.config import config
from keystone_tpu.utils.mesh import register_reshard_adapter
from keystone_tpu.linalg.row_matrix import (
    RowMatrix,
    _precision,
    donate_argnums,
    sharded_rowsum,
    solver_matmul,
    storage_dtype,
)


@partial(jax.jit, static_argnames=("refine_steps",))
def _chol_solve(gram, atb, lam, refine_steps: int = 1):
    d = gram.shape[0]
    reg = gram + lam * jnp.eye(d, dtype=gram.dtype)
    c, low = cho_factor(reg)
    W = cho_solve((c, low), atb)
    # Iterative refinement: each step removes most of the factorization
    # rounding error, pushing the f32 solve toward the f64 oracle the
    # reference's Breeze/LAPACK path produces (SURVEY.md §7 hard part 2).
    for _ in range(refine_steps):
        resid = atb - jnp.matmul(reg, W, precision=lax.Precision.HIGHEST)
        W = W + cho_solve((c, low), resid)
    return W


def solve_least_squares_normal(
    A: RowMatrix, B: RowMatrix, lam: float = 0.0, refine_steps: int = 1
) -> jax.Array:
    """argmin_W ||A W - B||² + lam ||W||²  →  (d, k) replicated array."""
    gram = A.gram()
    atb = A.atb(B)
    return _chol_solve(
        gram, atb, jnp.asarray(lam, dtype=gram.dtype), refine_steps
    )


@lru_cache(maxsize=None)
def _accum_gram_atb_fn(mesh: Mesh, axis: str, precision):
    """One fused program per chunk: (AᵀA, AᵀB) — reduced over rows in the
    canonical width-independent fold (``sharded_rowsum``, so a stream
    checkpointed on one mesh width resumes on another bit-identically) —
    added into the running accumulators. Everything is donated — the
    accumulators because the previous values are dead once the sums
    exist, and the CHUNK buffers because the overlapped loop never
    touches a chunk after its accumulation step, so XLA recycles their
    HBM for the next transfer and device residency stays at two in-flight
    chunk buffers regardless of stream length."""
    width = mesh.shape[axis]

    def local(gram, atb, a, b):
        g, t = sharded_rowsum(
            lambda ab, bb: (
                solver_matmul(ab.T, ab, precision),
                solver_matmul(ab.T, bb, precision),
            ),
            axis, width, (a, b),
        )
        return gram + g, atb + t

    sm = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(), P(axis), P(axis)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(sm, donate_argnums=donate_argnums(mesh, 0, 1, 2, 3))


def _put_labeled_chunk(chunk):
    X_chunk, Y_chunk = chunk
    if Y_chunk is None:
        raise ValueError("chunked solve needs labeled batches")
    A = RowMatrix.from_array(X_chunk, dtype=storage_dtype())
    B = RowMatrix.from_array(Y_chunk)
    return A, B


def planned_chunk_rows() -> int:
    """The PLANNED per-transfer row bound: ``config.solve_chunk_rows``
    (env KEYSTONE_SOLVE_CHUNK_ROWS) when set, else the session plan the
    profile-guided ``PlanResourcesRule`` wrote from measured
    bytes-per-row vs the HBM budget (``PipelineEnv.resource_plan``).
    An explicitly exported KEYSTONE_SOLVE_CHUNK_ROWS wins outright —
    including an explicit 0, which pins reactive-halving-only (the
    planner never overrides an explicit setting; the env is read live,
    not the config-instantiation snapshot). The unset default 0 falls
    through to the plan."""
    from keystone_tpu.config import resolved_solve_chunk_rows

    env_rows = resolved_solve_chunk_rows()
    if env_rows is not None:
        return env_rows
    rows = int(config.solve_chunk_rows or 0)
    if rows > 0:
        return rows
    from keystone_tpu.workflow.executor import PipelineEnv

    env = PipelineEnv._instance  # never CREATE an env from a solver
    if env is not None:
        return int(env.resource_plan.get("solve_chunk_rows", 0) or 0)
    return 0


def _put_chunks_resilient(chunk, plan, retry):
    """H2D one labeled chunk with OOM recovery; returns the (A, B) pairs
    to accumulate, in row order.

    A chunk larger than the PLANNED row bound (``planned_chunk_rows``:
    the profile-guided HBM-budget plan, or the explicit knob) is split to
    plan size BEFORE any transfer is attempted — the memory-safe-by-
    construction path (arXiv:2206.14148) that makes the reactive halving
    below a fallback instead of the mechanism.

    RESOURCE_EXHAUSTED at the transfer (real, or the harness's ``oom``
    site) is retried with backoff — transient allocation pressure clears,
    and a successful retry transfers the SAME host bytes, so the solve
    stays bit-identical. OOM that survives the whole retry budget is
    structural (the chunk itself doesn't fit): halve its rows and recurse,
    recording the downshift in ``reliability_counters``. Sub-chunks
    accumulate in row order, so the split solve is the same least-squares
    sum at a different flop grouping — numerically equivalent, though not
    bit-identical to the unsplit run.
    """
    import numpy as np

    X_chunk, Y_chunk = chunk
    if Y_chunk is None:
        raise ValueError("chunked solve needs labeled batches")

    planned = planned_chunk_rows()
    if planned > 0:
        n_rows = int(np.asarray(X_chunk).shape[0])
        if n_rows > planned:
            from keystone_tpu.utils.metrics import reliability_counters

            reliability_counters.bump("planned_chunk_splits")
            out = []
            for s in range(0, n_rows, planned):
                out.extend(_put_chunks_resilient(
                    (X_chunk[s:s + planned], Y_chunk[s:s + planned]),
                    plan, retry,
                ))
            return out

    def attempt():
        if plan is not None:
            plan.maybe_raise("oom")
        return _put_labeled_chunk(chunk)

    from keystone_tpu.utils.reliability import is_oom

    try:
        if retry is None:
            return [attempt()]
        return [retry.call(attempt, site="h2d", counter="h2d_retries")]
    except Exception as exc:
        if not is_oom(exc):
            raise
        n = int(np.asarray(X_chunk).shape[0])
        if n <= 1:
            raise  # can't split a single row: genuinely out of memory
        import logging

        from keystone_tpu.utils.metrics import reliability_counters

        reliability_counters.bump("oom_downshifts")
        logging.getLogger("keystone_tpu").warning(
            "chunked solve: device OOM persisted across retries on a "
            "%d-row chunk; halving and re-transferring", n,
        )
        mid = n // 2
        lo = (X_chunk[:mid], Y_chunk[:mid])
        hi = (X_chunk[mid:], Y_chunk[mid:])
        return _put_chunks_resilient(lo, plan, retry) + _put_chunks_resilient(
            hi, plan, retry
        )


def _chol_solve_maybe_traced(tracer, gram, atb, lam, refine_steps):
    """The final replicated Cholesky, spanned when tracing is live."""
    lam_arr = jnp.asarray(lam, dtype=gram.dtype)
    if tracer is None:
        return _chol_solve(gram, atb, lam_arr, refine_steps)
    t0 = tracer.now()
    out = _chol_solve(gram, atb, lam_arr, refine_steps)
    tracer.record("solve.cholesky", "solver", t0, d=int(gram.shape[0]))
    return out


def _put_chunks_traced(chunk, plan, retry, tracer, idx: int):
    """``_put_chunks_resilient`` wrapped in a per-chunk H2D span (chunk
    index, rows, and how many OOM-downshift splits it took). The untraced
    path calls ``_put_chunks_resilient`` directly — zero added work."""
    import numpy as np

    t0 = tracer.now()
    out = _put_chunks_resilient(chunk, plan, retry)
    tracer.record(
        "solve.h2d", "solver", t0, chunk=idx,
        rows=int(np.asarray(chunk[0]).shape[0]), splits=len(out),
    )
    return out


_STREAM_CKPT_KEY = "stream_solve"


def _stream_ckpt_store(checkpoint_dir: str):
    from keystone_tpu.workflow.disk_cache import DiskCache

    return DiskCache(checkpoint_dir, suffix=".ckpt.pkl")


def _stream_fingerprint(first_chunk) -> dict:
    """Solve identity for checkpoint binding: shapes, dtypes, a probe of
    the stream's first record — enough to refuse resuming a different
    problem into these accumulators — plus the per-shard manifest (mesh
    width and data axis), so a snapshot folded under one mesh can never
    SILENTLY continue under another: a width change either migrates the
    snapshot through ``utils.mesh.reshard_state`` (elastic mesh, default
    on, counted) or refuses typed."""
    import numpy as np

    from keystone_tpu.utils.mesh import num_data_shards

    X, Y = first_chunk
    X = np.asarray(X)
    return {
        "d": int(X.shape[1]),
        "b_tail": tuple(int(t) for t in np.asarray(Y).shape[1:]),
        "accum_dtype": str(config.accum_dtype),
        "storage_dtype": str(jnp.dtype(storage_dtype())),
        "chunk_rows": int(X.shape[0]),
        "x0_probe": float(np.asarray(X[0], dtype=np.float64).sum()),
        "device_count": int(num_data_shards()),
        "data_axis": str(config.data_axis),
    }


def _reshard_stream_state(state, layout):
    """Elastic-mesh adapter for chunked-solve snapshots: the retained
    gram/AᵀB are full (d, d)/(d, b) f64 sums — placement-free, nothing
    per-shard to re-fold — so migration rewrites the fingerprint's mesh
    manifest onto ``layout`` and passes every accumulator byte through
    untouched. Torn payloads (accumulator shapes contradicting the
    fingerprint) refuse typed instead."""
    import numpy as np

    from keystone_tpu.utils.mesh import reshard_refused

    fp = dict(state.get("fingerprint") or {})
    gram, atb = state.get("gram"), state.get("atb")
    d = int(fp.get("d", -1))
    gram = np.asarray(gram) if gram is not None else None
    atb = np.asarray(atb) if atb is not None else None
    if (
        gram is None
        or atb is None
        or gram.shape != (d, d)
        or atb.shape[:1] != (d,)
        or int(state.get("chunks_done", -1)) < 0
    ):
        raise reshard_refused(
            "stream solve",
            "snapshot accumulators do not match their fingerprint "
            "(torn or partially written checkpoint)",
        )
    fp["device_count"] = int(layout.num_shards)
    fp["data_axis"] = str(layout.axis)
    return dict(state, fingerprint=fp)


register_reshard_adapter("stream_solve", _reshard_stream_state)


class _StreamCheckpointer:
    """THE checkpoint/resume protocol of the chunked solve — one
    implementation driven by both the overlapped and sync paths, so the
    fingerprint binding, skip accounting, every-K save cadence, and
    consume-on-success can never drift between them. Inert (every call a
    no-op) when constructed without a ``checkpoint_dir``."""

    def __init__(self, checkpoint_dir: str | None, checkpoint_every: int | None):
        self.store = (
            _stream_ckpt_store(checkpoint_dir)
            if checkpoint_dir is not None
            else None
        )
        every = (
            config.checkpoint_every
            if checkpoint_every is None
            else int(checkpoint_every)
        )
        #: Snapshot cadence K; 0 = resume-only (no mid-stream saves).
        self.every = max(0, every)
        self.fingerprint = None
        self.done = 0
        self.skip = 0
        self.gram_np = None
        self.atb_np = None

    def resume(self, first_chunk) -> None:
        """Bind to the stream's identity (call once, with the first host
        chunk) and load a matching snapshot if one exists."""
        if self.store is None:
            return
        import logging

        from keystone_tpu.utils.metrics import reliability_counters

        from keystone_tpu.utils.mesh import (
            mesh_resume_decision,
            reshard_state,
        )

        self.fingerprint = _stream_fingerprint(first_chunk)
        state = self.store.get(_STREAM_CKPT_KEY)
        if state is None:
            return
        # Pre-manifest snapshots (no device_count/data_axis keys) compare
        # with the absent keys backfilled as wildcards (the shared
        # mesh_resume_decision triage), so a legacy checkpoint of the
        # SAME problem still resumes after the manifest upgrade instead
        # of silently recomputing hours of accumulation. The same problem
        # on a different mesh width MIGRATES (elastic mesh, counted) or
        # refuses typed — never a wrong-answer resume, never a silent
        # restart.
        decision, saved_fp = mesh_resume_decision(
            state.get("fingerprint"), self.fingerprint, "stream solve"
        )
        if decision == "fresh":
            logging.getLogger("keystone_tpu").warning(
                "stream-solve checkpoint holds a different solve "
                "(fingerprint mismatch); starting fresh"
            )
            return
        if decision == "migrate":
            state = reshard_state(
                dict(state, fingerprint=saved_fp), family="stream_solve"
            )
        reliability_counters.bump("checkpoints_resumed")
        self.skip = int(state["chunks_done"])
        self.gram_np, self.atb_np = state["gram"], state["atb"]

    def skipping(self) -> bool:
        """True while fast-forwarding past already-accumulated chunks —
        the caller drops the chunk unread (no transfer, no gram)."""
        if self.done < self.skip:
            self.done += 1
            from keystone_tpu.utils.metrics import reliability_counters

            reliability_counters.bump("chunks_skipped_on_resume")
            return True
        return False

    def restored(self, cdtype):
        """(gram, atb) from the snapshot in the accumulation dtype, or
        (None, None) on a fresh start. The numpy round-trip is bit-exact,
        which is what makes resumed solves bit-identical."""
        if self.gram_np is None:
            return None, None
        return (
            jnp.asarray(self.gram_np, dtype=cdtype),
            jnp.asarray(self.atb_np, dtype=cdtype),
        )

    def chunk_done(self, gram, atb) -> bool:
        """Count one accumulated chunk; snapshot at the cadence. The D2H
        fetch is the only sync this adds, once per K chunks; the atomic
        DiskCache rewrite means a kill mid-save leaves the previous
        complete snapshot. Returns True when a snapshot was written (the
        progress journey stamps its checkpoint age from this)."""
        self.done += 1
        if (
            self.store is None
            or self.every <= 0
            or self.done % self.every != 0
        ):
            return False
        import numpy as np

        from keystone_tpu.utils.metrics import reliability_counters

        self.store.put(
            _STREAM_CKPT_KEY,
            {
                "fingerprint": dict(self.fingerprint),
                "chunks_done": int(self.done),
                "gram": np.asarray(gram),
                "atb": np.asarray(atb),
            },
            overwrite=True,
        )
        from keystone_tpu.utils.mesh import write_mesh_manifest

        write_mesh_manifest(self.store.root, self.fingerprint)
        reliability_counters.bump("checkpoints_written")
        return True

    def consume(self) -> None:
        """Delete the snapshot: it belongs to the solve that just
        completed over it, and a later solve over changed data must never
        silently resume stale accumulators."""
        if self.store is not None:
            self.store.delete(_STREAM_CKPT_KEY)


def solve_least_squares_chunked(
    batches, lam: float = 0.0, refine_steps: int = 1,
    prefetch_depth: int | None = None,
    checkpoint_dir: str | None = None,
    checkpoint_every: int | None = None,
) -> jax.Array:
    """Normal-equation solve over an out-of-core row stream.

    ``batches`` yields (X_chunk, Y_chunk) row batches (see
    loaders.stream.BatchIterator); AᵀA and AᵀB accumulate chunk by chunk —
    the same additive decomposition the reference exploits with
    ``treeAggregate`` over RDD partitions, so n is bounded only by the
    source, not by host or device memory. Each chunk's gram rides the
    mesh's psum; the accumulator stays replicated on-device.

    ``prefetch_depth`` (default ``config.prefetch_depth``) > 0 takes the
    overlapped path: the producer runs ``depth`` batches ahead on a
    background thread (unless ``batches`` is already a PrefetchIterator),
    the next chunk's host→device transfer is issued while the current
    chunk's accumulation is in flight, and the accumulation step donates
    both accumulators and the consumed chunk buffers. 0 restores the
    fully synchronous loop.

    Reliability: the H2D step retries transient RESOURCE_EXHAUSTED with
    backoff and halves chunks that structurally don't fit (see
    ``_put_chunks_resilient``). With ``checkpoint_dir``, the AᵀA/AᵀB
    accumulators plus the stream cursor snapshot every
    ``checkpoint_every`` chunks (default ``config.checkpoint_every``,
    env ``KEYSTONE_CHECKPOINT_EVERY``; 0 = resume-only) through the atomic
    ``DiskCache``: a killed fit re-run with the same stream resumes at
    the last snapshot, recomputes at most K chunks, and — because the
    restored accumulators round-trip bit-exactly and the remaining
    chunks accumulate through the same program in the same order —
    yields a bit-identical solution. A snapshot is CONSUMED by the
    successful solve that completes over it (deleted on return), so a
    later solve over changed data can never silently resume stale
    accumulators.
    """
    depth = config.prefetch_depth if prefetch_depth is None else int(prefetch_depth)
    from contextlib import nullcontext

    from keystone_tpu.config import env_flag
    from keystone_tpu.loaders.stream import PrefetchIterator, prefetched
    from keystone_tpu.utils.reliability import RetryPolicy, active_plan

    # The measurement knob wins over any depth (matching the streamed BCD
    # path): serialized means serialized, even at the default prefetch
    # depth or for a caller-built PrefetchIterator.
    if env_flag("KEYSTONE_STREAM_NO_OVERLAP") or (
        depth <= 0 and not isinstance(batches, PrefetchIterator)
    ):
        return _solve_chunked_sync(
            batches, lam, refine_steps, checkpoint_dir, checkpoint_every
        )

    from keystone_tpu.utils.flight_recorder import ProgressReporter
    from keystone_tpu.utils.metrics import active_tracer

    plan = active_plan()
    retry = RetryPolicy()
    tracer = active_tracer()  # resolved once per solve, like the plan
    ckpt = _StreamCheckpointer(checkpoint_dir, checkpoint_every)

    # Respect an upstream-constructed prefetcher (the bench hands one in to
    # read its queue high-water afterwards) instead of double-wrapping —
    # and leave closing it to its owner.
    own = not isinstance(batches, PrefetchIterator)
    ctx = prefetched(iter(batches), depth) if own else nullcontext(batches)
    # Always-on solve journey (utils/flight_recorder.ProgressReporter):
    # chunk progress, rows/s, checkpoint age, stall watchdog; an
    # exception anywhere in the solve force-dumps the solver recorder
    # naming the last completed chunk.
    progress = ProgressReporter("lsq_chunked")
    with progress, ctx as src:
        it = iter(src)
        first = next(it, None)
        if first is None:
            raise ValueError("empty batch stream")
        if first[1] is None:
            raise ValueError("chunked solve needs labeled batches")
        ckpt.resume(first)
        # Fast-forward past checkpointed chunks: the producer re-reads
        # them (row streams don't seek) but no transfer or gram runs.
        cur_host = first
        while cur_host is not None and ckpt.skipping():
            cur_host = next(it, None)
        cdtype = jnp.dtype(config.accum_dtype)
        if cur_host is None:
            # The whole stream was already accumulated before the kill:
            # nothing left to recompute, solve straight off the snapshot.
            gram, atb = ckpt.restored(cdtype)
            if gram is None:
                raise ValueError("empty batch stream")
            ckpt.consume()
            return _chol_solve_maybe_traced(
                tracer, gram, atb, lam, refine_steps
            )
        if tracer is None:
            cur = _put_chunks_resilient(cur_host, plan, retry)
        else:
            cur = _put_chunks_traced(cur_host, plan, retry, tracer, ckpt.done)
        mesh = cur[0][0].mesh
        accum = _accum_gram_atb_fn(mesh, config.data_axis, _precision())
        d = cur[0][0].data.shape[1]
        # Labels may be 1-D (a single regression/class column — the CSV
        # label_col shape); AᵀB is then (d,) and the Cholesky solve
        # accepts the vector rhs directly, same as the sync path.
        b_tail = cur[0][1].data.shape[1:]
        replicated = NamedSharding(mesh, P())
        gram, atb = ckpt.restored(cdtype)
        if gram is not None:
            gram = jax.device_put(gram, replicated)
            atb = jax.device_put(atb, replicated)
        else:
            gram = jax.device_put(jnp.zeros((d, d), dtype=cdtype), replicated)
            atb = jax.device_put(
                jnp.zeros((d,) + b_tail, dtype=cdtype), replicated
            )
        while cur is not None:
            # Dispatch is async: the gemms run while the host fetches (the
            # producer thread parses/featurizes ahead) and stages the next
            # chunk's transfer. An OOM-downshifted chunk accumulates its
            # halves in row order.
            rows = sum(int(A.data.shape[0]) for A, _B in cur)
            if tracer is None:
                for A, B in cur:
                    gram, atb = accum(gram, atb, A.data, B.data)
            else:
                t0 = tracer.now()
                for A, B in cur:
                    gram, atb = accum(gram, atb, A.data, B.data)
                # The span measures DISPATCH, not device completion — the
                # gemms drain asynchronously (flagged so the trace reads
                # honestly next to the blocking H2D spans).
                tracer.record(
                    "solve.accum", "solver", t0,
                    chunk=ckpt.done, async_dispatch=True,
                )
            wrote = ckpt.chunk_done(gram, atb)
            progress.unit_done(rows=rows, chunk=ckpt.done)
            if wrote:
                progress.checkpoint(ckpt.done)
            nxt = next(it, None)
            if nxt is None:
                cur = None
            elif tracer is None:
                cur = _put_chunks_resilient(nxt, plan, retry)
            else:
                cur = _put_chunks_traced(nxt, plan, retry, tracer, ckpt.done)
        ckpt.consume()
        return _chol_solve_maybe_traced(tracer, gram, atb, lam, refine_steps)


def _solve_chunked_sync(
    batches, lam: float, refine_steps: int,
    checkpoint_dir: str | None = None,
    checkpoint_every: int | None = None,
) -> jax.Array:
    """The prefetch_depth=0 path: one thread, one chunk in flight — the
    pre-overlap behavior, preserved exactly for A/B measurement and as the
    fallback where background threads are unwelcome. Shares the overlapped
    path's OOM recovery and checkpoint/resume.

    KEYSTONE_STREAM_NO_OVERLAP=1 additionally blocks on each chunk's
    reduction, serializing ingest and compute outright — the same
    measurement knob the streamed BCD path honors, so benches can price
    what overlap (including plain async dispatch) buys. Never the right
    setting for real runs."""
    from keystone_tpu.config import env_flag
    from keystone_tpu.utils.flight_recorder import ProgressReporter
    from keystone_tpu.utils.metrics import active_tracer
    from keystone_tpu.utils.reliability import RetryPolicy, active_plan

    serialize = env_flag("KEYSTONE_STREAM_NO_OVERLAP")
    plan = active_plan()
    retry = RetryPolicy()
    tracer = active_tracer()
    ckpt = _StreamCheckpointer(checkpoint_dir, checkpoint_every)
    bound = False
    gram = None
    atb = None
    # Same always-on journey as the overlapped path: a death mid-stream
    # dumps the solver recorder naming the last completed chunk.
    progress = ProgressReporter("lsq_chunked")
    with progress:
        for chunk in batches:
            if not bound:
                bound = True
                if ckpt.store is not None:
                    if chunk[1] is None:
                        raise ValueError(
                            "chunked solve needs labeled batches"
                        )
                    ckpt.resume(chunk)
                    gram, atb = ckpt.restored(jnp.dtype(config.accum_dtype))
            if ckpt.skipping():
                continue
            if tracer is None:
                pairs = _put_chunks_resilient(chunk, plan, retry)
            else:
                pairs = _put_chunks_traced(
                    chunk, plan, retry, tracer, ckpt.done
                )
                t0 = tracer.now()
            rows = 0
            for A, B in pairs:
                rows += int(A.data.shape[0])
                g, ab = A.gram_and_atb(B)  # fused: one read of the chunk
                if serialize:
                    jax.block_until_ready((g, ab))
                gram = g if gram is None else gram + g
                atb = ab if atb is None else atb + ab
            if tracer is not None:
                tracer.record(
                    "solve.accum", "solver", t0,
                    chunk=ckpt.done, async_dispatch=not serialize,
                )
            wrote = ckpt.chunk_done(gram, atb)
            progress.unit_done(rows=rows, chunk=ckpt.done)
            if wrote:
                progress.checkpoint(ckpt.done)
        if gram is None:
            raise ValueError("empty batch stream")
        ckpt.consume()
        return _chol_solve_maybe_traced(tracer, gram, atb, lam, refine_steps)
