"""Least squares via normal equations.

Ref: ml-matrix `NormalEquations.solveLeastSquares` — AᵀA and AᵀB accumulated
with `treeAggregate`, Cholesky solve on the driver (SURVEY.md §2.2, §3.2)
[unverified]. Here: per-shard grams + `psum` over ICI, replicated on-device
Cholesky (every chip solves the small (d, d) system redundantly — cheaper
than shipping it anywhere).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.scipy.linalg import cho_factor, cho_solve

from keystone_tpu.linalg.row_matrix import RowMatrix


@partial(jax.jit, static_argnames=("refine_steps",))
def _chol_solve(gram, atb, lam, refine_steps: int = 1):
    d = gram.shape[0]
    reg = gram + lam * jnp.eye(d, dtype=gram.dtype)
    c, low = cho_factor(reg)
    W = cho_solve((c, low), atb)
    # Iterative refinement: each step removes most of the factorization
    # rounding error, pushing the f32 solve toward the f64 oracle the
    # reference's Breeze/LAPACK path produces (SURVEY.md §7 hard part 2).
    for _ in range(refine_steps):
        resid = atb - jnp.matmul(reg, W, precision=lax.Precision.HIGHEST)
        W = W + cho_solve((c, low), resid)
    return W


def solve_least_squares_normal(
    A: RowMatrix, B: RowMatrix, lam: float = 0.0, refine_steps: int = 1
) -> jax.Array:
    """argmin_W ||A W - B||² + lam ||W||²  →  (d, k) replicated array."""
    gram = A.gram()
    atb = A.atb(B)
    return _chol_solve(
        gram, atb, jnp.asarray(lam, dtype=gram.dtype), refine_steps
    )


def solve_least_squares_chunked(
    batches, lam: float = 0.0, refine_steps: int = 1
) -> jax.Array:
    """Normal-equation solve over an out-of-core row stream.

    ``batches`` yields (X_chunk, Y_chunk) row batches (see
    loaders.stream.BatchIterator); AᵀA and AᵀB accumulate chunk by chunk —
    the same additive decomposition the reference exploits with
    ``treeAggregate`` over RDD partitions, so n is bounded only by the
    source, not by host or device memory. Each chunk's gram rides the
    mesh's psum; the accumulator stays replicated on-device.
    """
    gram = None
    atb = None
    from keystone_tpu.linalg.row_matrix import storage_dtype

    for X_chunk, Y_chunk in batches:
        if Y_chunk is None:
            raise ValueError("chunked solve needs labeled batches")
        A = RowMatrix.from_array(X_chunk, dtype=storage_dtype())
        B = RowMatrix.from_array(Y_chunk)
        g, ab = A.gram_and_atb(B)  # fused: one read of the chunk
        gram = g if gram is None else gram + g
        atb = ab if atb is None else atb + ab
    if gram is None:
        raise ValueError("empty batch stream")
    return _chol_solve(
        gram, atb, jnp.asarray(lam, dtype=gram.dtype), refine_steps
    )
