"""Least squares via normal equations.

Ref: ml-matrix `NormalEquations.solveLeastSquares` — AᵀA and AᵀB accumulated
with `treeAggregate`, Cholesky solve on the driver (SURVEY.md §2.2, §3.2)
[unverified]. Here: per-shard grams + `psum` over ICI, replicated on-device
Cholesky (every chip solves the small (d, d) system redundantly — cheaper
than shipping it anywhere).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax import lax

from keystone_tpu.utils.compat import shard_map
from jax.scipy.linalg import cho_factor, cho_solve
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from keystone_tpu.config import config
from keystone_tpu.linalg.row_matrix import (
    RowMatrix,
    _precision,
    donate_argnums,
    solver_matmul,
    storage_dtype,
)


@partial(jax.jit, static_argnames=("refine_steps",))
def _chol_solve(gram, atb, lam, refine_steps: int = 1):
    d = gram.shape[0]
    reg = gram + lam * jnp.eye(d, dtype=gram.dtype)
    c, low = cho_factor(reg)
    W = cho_solve((c, low), atb)
    # Iterative refinement: each step removes most of the factorization
    # rounding error, pushing the f32 solve toward the f64 oracle the
    # reference's Breeze/LAPACK path produces (SURVEY.md §7 hard part 2).
    for _ in range(refine_steps):
        resid = atb - jnp.matmul(reg, W, precision=lax.Precision.HIGHEST)
        W = W + cho_solve((c, low), resid)
    return W


def solve_least_squares_normal(
    A: RowMatrix, B: RowMatrix, lam: float = 0.0, refine_steps: int = 1
) -> jax.Array:
    """argmin_W ||A W - B||² + lam ||W||²  →  (d, k) replicated array."""
    gram = A.gram()
    atb = A.atb(B)
    return _chol_solve(
        gram, atb, jnp.asarray(lam, dtype=gram.dtype), refine_steps
    )


@lru_cache(maxsize=None)
def _accum_gram_atb_fn(mesh: Mesh, axis: str, precision):
    """One fused program per chunk: psum'd (AᵀA, AᵀB) added into the
    running accumulators. Everything is donated — the accumulators because
    the previous values are dead once the sums exist, and the CHUNK buffers
    because the overlapped loop never touches a chunk after its
    accumulation step, so XLA recycles their HBM for the next transfer and
    device residency stays at two in-flight chunk buffers regardless of
    stream length."""

    def local(gram, atb, a, b):
        return (
            gram + lax.psum(solver_matmul(a.T, a, precision), axis),
            atb + lax.psum(solver_matmul(a.T, b, precision), axis),
        )

    sm = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(), P(axis), P(axis)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(sm, donate_argnums=donate_argnums(mesh, 0, 1, 2, 3))


def _put_labeled_chunk(chunk):
    X_chunk, Y_chunk = chunk
    if Y_chunk is None:
        raise ValueError("chunked solve needs labeled batches")
    A = RowMatrix.from_array(X_chunk, dtype=storage_dtype())
    B = RowMatrix.from_array(Y_chunk)
    return A, B


def solve_least_squares_chunked(
    batches, lam: float = 0.0, refine_steps: int = 1,
    prefetch_depth: int | None = None,
) -> jax.Array:
    """Normal-equation solve over an out-of-core row stream.

    ``batches`` yields (X_chunk, Y_chunk) row batches (see
    loaders.stream.BatchIterator); AᵀA and AᵀB accumulate chunk by chunk —
    the same additive decomposition the reference exploits with
    ``treeAggregate`` over RDD partitions, so n is bounded only by the
    source, not by host or device memory. Each chunk's gram rides the
    mesh's psum; the accumulator stays replicated on-device.

    ``prefetch_depth`` (default ``config.prefetch_depth``) > 0 takes the
    overlapped path: the producer runs ``depth`` batches ahead on a
    background thread (unless ``batches`` is already a PrefetchIterator),
    the next chunk's host→device transfer is issued while the current
    chunk's accumulation is in flight, and the accumulation step donates
    both accumulators and the consumed chunk buffers. 0 restores the
    fully synchronous loop.
    """
    depth = config.prefetch_depth if prefetch_depth is None else int(prefetch_depth)
    from contextlib import nullcontext

    from keystone_tpu.config import env_flag
    from keystone_tpu.loaders.stream import PrefetchIterator, prefetched

    # The measurement knob wins over any depth (matching the streamed BCD
    # path): serialized means serialized, even at the default prefetch
    # depth or for a caller-built PrefetchIterator.
    if env_flag("KEYSTONE_STREAM_NO_OVERLAP"):
        return _solve_chunked_sync(batches, lam, refine_steps)
    if depth <= 0 and not isinstance(batches, PrefetchIterator):
        return _solve_chunked_sync(batches, lam, refine_steps)

    # Respect an upstream-constructed prefetcher (the bench hands one in to
    # read its queue high-water afterwards) instead of double-wrapping —
    # and leave closing it to its owner.
    own = not isinstance(batches, PrefetchIterator)
    ctx = prefetched(iter(batches), depth) if own else nullcontext(batches)
    with ctx as src:
        it = iter(src)
        first = next(it, None)
        if first is None:
            raise ValueError("empty batch stream")
        cur = _put_labeled_chunk(first)
        mesh = cur[0].mesh
        accum = _accum_gram_atb_fn(mesh, config.data_axis, _precision())
        cdtype = jnp.dtype(config.accum_dtype)
        d = cur[0].data.shape[1]
        # Labels may be 1-D (a single regression/class column — the CSV
        # label_col shape); AᵀB is then (d,) and the Cholesky solve
        # accepts the vector rhs directly, same as the sync path.
        b_tail = cur[1].data.shape[1:]
        replicated = NamedSharding(mesh, P())
        gram = jax.device_put(jnp.zeros((d, d), dtype=cdtype), replicated)
        atb = jax.device_put(jnp.zeros((d,) + b_tail, dtype=cdtype), replicated)
        while cur is not None:
            A, B = cur
            # Dispatch is async: the gemms run while the host fetches (the
            # producer thread parses/featurizes ahead) and stages the next
            # chunk's transfer.
            gram, atb = accum(gram, atb, A.data, B.data)
            nxt = next(it, None)
            cur = None if nxt is None else _put_labeled_chunk(nxt)
    return _chol_solve(
        gram, atb, jnp.asarray(lam, dtype=gram.dtype), refine_steps
    )


def _solve_chunked_sync(batches, lam: float, refine_steps: int) -> jax.Array:
    """The prefetch_depth=0 path: one thread, one chunk in flight — the
    pre-overlap behavior, preserved exactly for A/B measurement and as the
    fallback where background threads are unwelcome.

    KEYSTONE_STREAM_NO_OVERLAP=1 additionally blocks on each chunk's
    reduction, serializing ingest and compute outright — the same
    measurement knob the streamed BCD path honors, so benches can price
    what overlap (including plain async dispatch) buys. Never the right
    setting for real runs."""
    from keystone_tpu.config import env_flag

    serialize = env_flag("KEYSTONE_STREAM_NO_OVERLAP")
    gram = None
    atb = None
    for chunk in batches:
        A, B = _put_labeled_chunk(chunk)
        g, ab = A.gram_and_atb(B)  # fused: one read of the chunk
        if serialize:
            jax.block_until_ready((g, ab))
        gram = g if gram is None else gram + g
        atb = ab if atb is None else atb + ab
    if gram is None:
        raise ValueError("empty batch stream")
    return _chol_solve(
        gram, atb, jnp.asarray(lam, dtype=gram.dtype), refine_steps
    )
