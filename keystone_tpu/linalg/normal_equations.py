"""Least squares via normal equations.

Ref: ml-matrix `NormalEquations.solveLeastSquares` — AᵀA and AᵀB accumulated
with `treeAggregate`, Cholesky solve on the driver (SURVEY.md §2.2, §3.2)
[unverified]. Here: per-shard grams + `psum` over ICI, replicated on-device
Cholesky (every chip solves the small (d, d) system redundantly — cheaper
than shipping it anywhere).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.linalg import cho_factor, cho_solve

from keystone_tpu.linalg.row_matrix import RowMatrix


@jax.jit
def _chol_solve(gram, atb, lam):
    d = gram.shape[0]
    reg = gram + lam * jnp.eye(d, dtype=gram.dtype)
    c, low = cho_factor(reg)
    return cho_solve((c, low), atb)


def solve_least_squares_normal(
    A: RowMatrix, B: RowMatrix, lam: float = 0.0
) -> jax.Array:
    """argmin_W ||A W - B||² + lam ||W||²  →  (d, k) replicated array."""
    gram = A.gram()
    atb = A.atb(B)
    return _chol_solve(gram, atb, jnp.asarray(lam, dtype=gram.dtype))
