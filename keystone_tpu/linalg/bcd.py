"""Block coordinate descent over feature blocks — the reference's workhorse
solver for 64k–256k-dim featurized problems.

Ref: ml-matrix `BlockCoordinateDescent` driving
`BlockLeastSquaresEstimator.fit` (SURVEY.md §3.2) [unverified]:

    for epoch; for block b:
        residual update: R ← R + A_b W_b       [per-partition gemm]
        gram/gradient via treeAggregate        [the comm bottleneck]
        driver Cholesky solve → broadcast W_b

TPU lowering (the SURVEY's north-star stack): the per-partition gemms are
per-chip MXU matmuls on the row-sharded A_b and residual; `treeAggregate`
becomes `psum` over ICI; the (b, b) Cholesky solve runs replicated on every
chip (no driver hop, no broadcast — the result is already everywhere).

Supports per-row weights for the class-balanced ImageNet variant
(Ref: BlockWeightedLeastSquaresEstimator [unverified]).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from keystone_tpu.utils.compat import shard_map
from jax.scipy.linalg import cho_factor, cho_solve, solve_triangular
from jax.sharding import Mesh, PartitionSpec as P

from keystone_tpu.config import config
from keystone_tpu.utils.mesh import fold_blocks, register_reshard_adapter
from keystone_tpu.linalg.row_matrix import (
    RowMatrix,
    _precision,
    donate_argnums as _donate,
    sharded_rowsum,
    solver_matmul,
    storage_dtype,
)


# -- shared per-shard solver math (single source for every shard_map body) --


def _local_weighted(a_b, w_rows, weighted: bool):
    return a_b * w_rows[:, None] if weighted else a_b


def _local_ridge_gram(a_b, aw, lam, precision, axis, width):
    """Ridge gram AᵀA + λI for one block, reduced over the sharded rows in
    the canonical width-independent fold (``sharded_rowsum`` — the
    elastic-mesh bit-identity contract) — THE single source for the gram
    expression across every shard_map body (fused, batched, uncached)."""
    gram = sharded_rowsum(
        lambda awb, ab: solver_matmul(awb.T, ab, precision),
        axis, width, (aw, a_b),
    )
    b = a_b.shape[1]
    return gram + lam * jnp.eye(b, dtype=gram.dtype)


def _local_gram_inv(a_b, aw, lam, precision, axis, width):
    """Explicit ridge resolvent (AᵀA + λI)⁻¹ for the block.

    The inverse — not the Cholesky factor — is the cached quantity: XLA
    lowers triangular solves to a sequential substitution that dominates
    BCD wall-clock on TPU, while multiplying by a precomputed inverse is
    one MXU gemm. Forming the inverse costs a one-time pair of triangular
    solves per block; the λ-regularized SPD gram keeps it well-conditioned,
    and later epochs re-solve against the residual, so per-epoch solve
    error self-corrects instead of accumulating."""
    return _batched_spd_inv(
        _local_ridge_gram(a_b, aw, lam, precision, axis, width)
    )


def _local_solve_update(a_b, aw, inv, r, w_b, precision, axis, width):
    r_plus = r + solver_matmul(a_b, w_b, precision)
    rhs = sharded_rowsum(
        lambda awb, rb: solver_matmul(awb.T, rb, precision),
        axis, width, (aw, r_plus),
    )
    w_b_new = solver_matmul(inv, rhs, precision)
    r_new = r_plus - solver_matmul(a_b, w_b_new, precision)
    return r_new, w_b_new


@lru_cache(maxsize=None)
def _gram_inv_fn(mesh: Mesh, axis: str, precision, weighted: bool,
                 fold: int):
    """Per-block gram + ridge inverse, computed once per block
    (epoch-invariant)."""
    width = mesh.shape[axis]

    def local(a_b, lam, w_rows):
        aw = _local_weighted(a_b, w_rows, weighted)
        return _local_gram_inv(a_b, aw, lam, precision, axis, width)

    sm = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(), P(axis)),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sm)


@lru_cache(maxsize=None)
def _gram_only_fn(mesh: Mesh, axis: str, precision, weighted: bool,
                  fold: int):
    """Per-block ridge gram (no factorization) — the gemm half of
    the factor phase. Kept per-block: block grams are already large MXU
    gemms; it is only the FACTORIZATION that wants batching."""
    width = mesh.shape[axis]

    def local(a_b, lam, w_rows):
        aw = _local_weighted(a_b, w_rows, weighted)
        return _local_ridge_gram(a_b, aw, lam, precision, axis, width)

    sm = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(), P(axis)),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sm)


def _trsm_rhs_chunk(b: int, batch: int, itemsize: int) -> int:
    """Column-chunk width for the identity-RHS triangular solves below.

    XLA:TPU expands TriangularSolve into an UNROLLED 128-row panel chain
    that materializes one (batch, rows_left, rhs_w) HLO temp per panel —
    about batch·b²·w·itemsize/128 bytes across the chain. Against the
    full b-wide identity at (batch=2, b=8192) that is ~17 GB and fails
    v5e buffer assignment outright (measured via the deviceless AOT
    compile: "Used 16.23G of 15.75G hbm"). Chunking the RHS columns and
    scanning the chunks (scan = real while loop, temps REUSED per
    iteration) caps the chain at ~2 GB while each panel step stays at
    least one full 128-lane MXU tile wide. The floor is the 128 lane
    width, NOT larger: a bigger floor would silently override the budget
    right where it matters most (ring-path d_loc ≥ 16k). At the floor the
    chain still grows as batch·b²·itemsize — but there the b×b operands
    themselves approach HBM capacity and the caller must shard d
    further."""
    budget = 2 << 30
    w = budget * 128 // max(1, batch * b * b * itemsize)
    if w >= b:
        return b
    return max(128, 1 << int(np.floor(np.log2(max(w, 1)))))


def _batched_spd_inv(grams, rhs_chunk: Optional[int] = None):
    """(Batched) SPD inverse — THE single source for the factor-phase
    inverse, batched (leading block axis) or not.

    Two TPU-shaped choices:
    - ONE triangular solve, not two. A⁻¹ = (L⁻¹)ᵀ(L⁻¹), so only
      Y = L⁻¹ is computed by substitution; the second "solve" is an MXU
      gemm (YᵀY, HIGHEST precision). XLA lowers trsm as a sequential
      panel loop — halving the trsm count halves the sequential tail of
      every factor phase, and the batch dimension amortizes what's left.
    - The identity RHS is column-chunked per ``_trsm_rhs_chunk``
      (``rhs_chunk`` overrides, for tests) so the unrolled trsm expansion
      can't blow the HBM temp budget at large b."""
    chol = jnp.linalg.cholesky(grams)
    b = grams.shape[-1]
    batch = int(np.prod(grams.shape[:-2])) if grams.ndim > 2 else 1
    # `is None`, not truthiness: an explicit rhs_chunk=0 must error, not
    # silently fall back to the policy (ADVICE r5).
    if rhs_chunk is None:
        w = _trsm_rhs_chunk(b, batch, jnp.dtype(grams.dtype).itemsize)
    else:
        assert rhs_chunk >= 1, f"rhs_chunk must be >= 1, got {rhs_chunk}"
        w = rhs_chunk
    eye = jnp.eye(b, dtype=grams.dtype)
    if w >= b:
        eyeb = jnp.broadcast_to(eye, grams.shape)
        y = solve_triangular(chol, eyeb, lower=True)
    else:
        nc = -(-b // w)
        eye_pad = jnp.pad(eye, ((0, 0), (0, nc * w - b)))

        def chunk_cols(_, c0):
            rhs = jnp.broadcast_to(
                lax.dynamic_slice(eye_pad, (0, c0), (b, w)),
                grams.shape[:-2] + (b, w),
            )
            return None, solve_triangular(chol, rhs, lower=True)

        _, cols = lax.scan(
            chunk_cols, None, jnp.arange(0, nc * w, w, dtype=jnp.int32)
        )
        # cols: (nc, *batch, b, w) → (*batch, b, nc·w), drop padding.
        cols = jnp.moveaxis(cols, 0, -2)
        y = cols.reshape(grams.shape[:-1] + (nc * w,))[..., :b]
    return jnp.matmul(
        jnp.swapaxes(y, -1, -2), y, precision=lax.Precision.HIGHEST
    )


@lru_cache(maxsize=None)
def _batched_ridge_inv_fn(mesh: Mesh):
    """One XLA program factorizing `factor_batch` stacked grams at once."""
    # Donate the gram stack — dead once the inverses exist; caps the factor
    # phase's transient at one stack instead of two.
    return jax.jit(_batched_spd_inv, donate_argnums=_donate(mesh, 0))


@lru_cache(maxsize=None)
def _stack_blocks_fn(mesh: Mesh, axis: str, nb: int):
    """(rows, d) → (nb, rows, d/nb) stacked equal-size column blocks, in one
    program. This is the fused path's analog of the a_blocks partition (same
    one-extra-copy-of-A aggregate cost), laid out so a `lax.scan` can carry
    the epoch loop over the leading block axis."""

    def local(a):
        r, d = a.shape
        return jnp.moveaxis(a.reshape(r, nb, d // nb), 1, 0)

    sm = shard_map(
        local,
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(None, axis),
        check_vma=False,
    )
    return jax.jit(sm)


@lru_cache(maxsize=None)
def _fused_factor_fn(mesh: Mesh, axis: str, precision, weighted: bool,
                     fold: int):
    """All blocks' ridge inverses in ONE program: batched canonical-fold
    grams (one big MXU batch-gemm per row block) into batched Cholesky +
    triangular solves. The single dispatch matters as much as the
    batching — through the relay transport, per-program launch latency
    between many small factor programs was a real slice of solver
    wall-clock."""
    width = mesh.shape[axis]

    def local(a3, lam, w_rows):  # a3: (chunk, rows_shard, b)
        aw = a3 * w_rows[None, :, None] if weighted else a3
        gram = sharded_rowsum(
            lambda awb, ab: solver_matmul(
                jnp.swapaxes(awb, 1, 2), ab, precision
            ),
            axis, width, (aw, a3), row_axes=(1, 1),
        )
        b = a3.shape[2]
        return _batched_spd_inv(gram + lam * jnp.eye(b, dtype=gram.dtype))

    sm = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(None, axis), P(), P(axis)),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sm)


@lru_cache(maxsize=None)
def _fused_epochs_fn(
    mesh: Mesh, axis: str, precision, weighted: bool, num_epochs: int,
    cached: bool, fold: int,
):
    """The whole multi-epoch BCD sweep as ONE XLA program: scan over blocks
    inside scan over epochs, per-shard under shard_map.

    This is the TPU-shaped fix for the dispatch-bound solver: the legacy
    loop launches one program per (block, epoch) — each launch a host→relay
    round trip whose latency rivals the skinny per-epoch gemms it wraps.
    Fused, the solve is a single launch regardless of nb·epochs, XLA
    pipelines the scan body's gemms back-to-back on the MXU, and the psum
    schedule is fixed at compile time (also immune to the CPU in-process
    rendezvous deadlock that forces the legacy loop to throttle).

    ``cached=True`` consumes precomputed ridge inverses (xs carries them);
    ``cached=False`` re-derives gram+Cholesky per block visit — the
    single-epoch / factor-cache-disabled mode."""
    width = mesh.shape[axis]

    def local(a3, invs, r, w3, lam, w_rows):
        def block_step(rc, xs):
            a_b, inv, w_b = xs
            aw = _local_weighted(a_b, w_rows, weighted)
            if not cached:
                inv = _local_gram_inv(
                    a_b, aw, lam, precision, axis, width
                )
            r_new, w_new = _local_solve_update(
                a_b, aw, inv, rc, w_b, precision, axis, width
            )
            return r_new, w_new

        def epoch_step(carry, _):
            rc, w3c = carry
            rc, w3c = lax.scan(block_step, rc, (a3, invs, w3c))
            return (rc, w3c), None

        (r, w3), _ = lax.scan(epoch_step, (r, w3), None, length=num_epochs)
        return r, w3

    sm = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(None, axis), P(), P(axis), P(), P(), P(axis)),
        out_specs=(P(axis), P()),
        check_vma=False,
    )
    return jax.jit(sm, donate_argnums=_donate(mesh, 2, 3))


@lru_cache(maxsize=None)
def _cached_block_update_fn(mesh: Mesh, axis: str, precision,
                            weighted: bool, fold: int):
    """BCD block update reusing the precomputed ridge inverse: only MXU
    gemms remain in the epoch loop — the dominant 2·n·b² gram FLOPs drop
    out after the first epoch, and no triangular solve ever runs in it."""
    width = mesh.shape[axis]

    def local(a_b, inv, r, w_b, w_rows):
        aw = _local_weighted(a_b, w_rows, weighted)
        return _local_solve_update(
            a_b, aw, inv, r, w_b, precision, axis, width
        )

    sm = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(), P(axis), P(), P(axis)),
        out_specs=(P(axis), P()),
        check_vma=False,
    )
    return jax.jit(sm, donate_argnums=_donate(mesh, 2, 3))


@lru_cache(maxsize=None)
def _first_epoch_update_fn(mesh: Mesh, axis: str, precision,
                           weighted: bool, fold: int):
    """Fused block update that also emits the gram's ridge inverse — the
    streamed path's first epoch. Fusion keeps a_b in one XLA program so the
    block is read from HBM once for gram + update instead of twice."""
    width = mesh.shape[axis]

    def local(a_b, r, w_b, lam, w_rows):
        aw = _local_weighted(a_b, w_rows, weighted)
        inv = _local_gram_inv(a_b, aw, lam, precision, axis, width)
        r_new, w_b_new = _local_solve_update(
            a_b, aw, inv, r, w_b, precision, axis, width
        )
        return r_new, w_b_new, inv

    sm = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(), P(), P(axis)),
        out_specs=(P(axis), P(), P()),
        check_vma=False,
    )
    return jax.jit(sm, donate_argnums=_donate(mesh, 1, 2))


@lru_cache(maxsize=None)
def _block_update_fn(mesh: Mesh, axis: str, precision, weighted: bool,
                     fold: int):
    """One BCD block update, jitted once per (mesh, shapes) and reused for
    every block and epoch — the hot loop of the whole framework."""
    width = mesh.shape[axis]

    def local(a_b, r, w_b, lam, w_rows):
        # r is the current residual B - A W (row-sharded).
        r_plus = r + solver_matmul(a_b, w_b, precision)
        if weighted:
            aw = a_b * w_rows[:, None]
        else:
            aw = a_b
        gram, rhs = sharded_rowsum(
            lambda awb, ab, rb: (
                solver_matmul(awb.T, ab, precision),
                solver_matmul(awb.T, rb, precision),
            ),
            axis, width, (aw, a_b, r_plus),
        )
        b = a_b.shape[1]
        c, low = cho_factor(gram + lam * jnp.eye(b, dtype=gram.dtype))
        w_b_new = cho_solve((c, low), rhs)
        r_new = r_plus - solver_matmul(a_b, w_b_new, precision)
        return r_new, w_b_new

    sm = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(), P(), P(axis)),
        out_specs=(P(axis), P()),
        check_vma=False,
    )
    return jax.jit(sm, donate_argnums=_donate(mesh, 1, 2))


def _factor_chunk(block_size: Optional[int] = None) -> int:
    """Blocks factorized per batched XLA program — THE single chunk policy
    for both the legacy and fused factor phases. Auto: batching amortizes
    TPU's sequential factorization lowering, but measured 2.3× slower than
    independent per-block programs on the CPU backend — there, per-block.
    An explicit config.factor_batch forces that chunk on any backend.

    The auto chunk is additionally MEMORY-capped: XLA's batched
    triangular-solve lowering holds a handful of (chunk, b, b) HLO temps,
    so an uncapped chunk·b² OOMs HBM at large blocks — the deviceless v5e
    AOT compile of the ImageNet bench shape (chunk 8 · b 8192) demanded
    >16 GiB of temps. Capping chunk·b² at 128M f32 elements (512 MB per
    temp) keeps the factor transient ~1-2 GiB: b=8192 gets chunk 2
    (128M // 8192² = 2), b≤2896 keeps the full batch of 16."""
    if config.factor_batch is not None:
        return max(1, int(config.factor_batch))
    if jax.default_backend() == "cpu":
        return 1
    chunk = 16
    if block_size:
        chunk = min(chunk, max(1, (128 << 20) // (block_size * block_size)))
    return chunk


def _factor_blocks(
    a_blocks, blocks, lam_arr, w_rows, mesh, axis, weighted, throttle
) -> list:
    """Gram ridge inverses for every block, factorized in batched chunks.

    Grams stay per-block (each is one large psum'd MXU gemm); the
    Cholesky + triangular solves — TPU's sequentially-lowered tail — run
    batched over up to ``config.factor_batch`` equal-size blocks per XLA
    program. A ragged final block (d % block_size != 0) keeps the fused
    per-block path. Transient memory per chunk: chunk · b² in accum dtype,
    donated into the inverse stack."""
    precision = _precision()
    n_eq = len(blocks)
    if n_eq > 1 and blocks[-1][1] - blocks[-1][0] != blocks[0][1] - blocks[0][0]:
        n_eq -= 1  # ragged tail handled per-block below
    chunk = _factor_chunk(blocks[0][1] - blocks[0][0])
    invs: list = []
    # A singleton final chunk would pay a fresh (1,b,b) batched compile and
    # lose gram/factor fusion; leave it to the fused per-block path below.
    if n_eq % chunk == 1:
        n_eq -= 1
    if n_eq > 1 and chunk > 1:
        gram_only = _gram_only_fn(
            mesh, axis, precision, weighted, fold_blocks(mesh.shape[axis])
        )
        batched_inv = _batched_ridge_inv_fn(mesh)
        for c0 in range(0, n_eq, chunk):
            part = a_blocks[c0 : min(c0 + chunk, n_eq)]
            grams = []
            for a_b in part:
                g = gram_only(a_b, lam_arr, w_rows)
                if throttle:
                    # Independent collective programs in an un-serialized
                    # burst deadlock the CPU in-process rendezvous.
                    g.block_until_ready()
                grams.append(g)
            stacked = batched_inv(jnp.stack(grams, axis=0))
            if throttle:
                stacked.block_until_ready()
            # Unstacked views keep the epoch-loop interface unchanged.
            invs.extend(stacked[i] for i in range(stacked.shape[0]))
    gram_inv = _gram_inv_fn(
        mesh, axis, precision, weighted, fold_blocks(mesh.shape[axis])
    )
    for a_b in a_blocks[len(invs) :]:
        c = gram_inv(a_b, lam_arr, w_rows)
        if throttle:
            c.block_until_ready()
        invs.append(c)
    return invs


def block_coordinate_descent(
    A: RowMatrix,
    B: RowMatrix,
    block_size: int,
    num_iters: int,
    lam: float = 0.0,
    row_weights: Optional[jax.Array] = None,
    checkpoint_dir: Optional[str] = None,
    cache_grams: Optional[bool] = None,
) -> Tuple[List[jax.Array], List[Tuple[int, int]]]:
    """Solve min_W ||A W - B||² + lam ||W||² block-by-block.

    Returns (per-block weight matrices, block column ranges). The caller
    (BlockLinearMapper) keeps the blocks — applying block-by-block is how
    the reference streams 256k-dim models through memory.

    With ``checkpoint_dir``, solver state (W blocks + residual) is written
    after every epoch via orbax and the solve resumes from the latest epoch
    on restart — the fault-recovery analog of Spark's lineage recompute
    (SURVEY.md §5 failure-detection row): deterministic re-execution from
    the last epoch boundary instead of RDD lineage.

    ``cache_grams`` (default: auto) precomputes each block's gram ridge
    INVERSE once — grams are epoch-invariant, so multi-epoch solves drop
    the dominant 2·n·b² FLOPs from every epoch after the first, and the
    per-epoch solve is a pure MXU gemm (TPU triangular solves are
    sequential and would dominate otherwise). Auto enables it when
    num_iters > 1 and the (num_blocks · b²) factors fit a quarter of the
    HBM budget.
    """
    A._check_aligned(B)
    mesh, axis = A.mesh, config.data_axis
    d = A.data.shape[1]
    k = B.data.shape[1]
    # A may be stored bf16 (throughput mode); solver state — weights,
    # residual, lam, grams — always lives in the accumulation dtype.
    dtype = A.data.dtype
    cdtype = jnp.dtype(config.accum_dtype)
    blocks = [(s, min(s + block_size, d)) for s in range(0, d, block_size)]

    weighted = row_weights is not None
    if weighted:
        w_rows = jnp.asarray(row_weights, dtype=dtype)
        if w_rows.shape[0] != A.padded_rows:
            w_rows = jnp.pad(w_rows, (0, A.padded_rows - w_rows.shape[0]))
        w_rows = jax.device_put(
            w_rows, jax.sharding.NamedSharding(mesh, P(axis))
        )
    else:
        w_rows = jnp.zeros((A.padded_rows,), dtype=dtype)
        w_rows = jax.device_put(
            w_rows, jax.sharding.NamedSharding(mesh, P(axis))
        )

    if cache_grams is None:
        itemsize = jnp.dtype(cdtype).itemsize
        factor_bytes = sum((e - s) ** 2 for s, e in blocks) * itemsize
        cache_grams = num_iters > 1 and factor_bytes < config.hbm_budget_bytes // 4
    update = _block_update_fn(
        mesh, axis, _precision(), weighted, fold_blocks(mesh.shape[axis])
    )
    lam_arr = jnp.asarray(lam, dtype=cdtype)

    W = [jnp.zeros((e - s, k), dtype=cdtype) for s, e in blocks]
    # jnp.array COPIES: astype is a no-op alias when dtypes already
    # match, and the first update DONATES R — donating an alias of the
    # caller's B.data would delete their labels out from under them.
    R = jnp.array(B.data, dtype=cdtype)
    sharding = jax.sharding.NamedSharding(mesh, P(axis))
    fingerprint = None
    if checkpoint_dir is not None:
        fingerprint = _make_fingerprint(
            B, d, block_size, lam, weighted,
            a_probe=float(jnp.sum(A.data[0]) + jnp.sum(A.data[A.n - 1])),
            a_dtype=dtype,
        )
    start_epoch, W, R = _resume_or_default(
        checkpoint_dir, fingerprint, W, R, sharding
    )
    # Slice each column block once, not once per epoch: the blocks partition
    # A (one extra A-sized copy in aggregate) and every epoch then reads them
    # without re-materializing slices in the hot loop. When feature blocks
    # stop fitting in HBM the estimator layer streams them from host instead.
    # The CPU-emulated mesh's in-process all-reduce rendezvous can deadlock
    # when many small collective programs are in flight concurrently (7/8
    # threads arrive -> 40s timeout -> abort). Throttle dispatch per epoch
    # on CPU only; TPU keeps full async pipelining.
    throttle = jax.default_backend() == "cpu"

    # Fused scan path: when the blocks tile d exactly, the entire solve —
    # factor phase and every (block, epoch) update — runs in three XLA
    # programs instead of one program per block visit. See _fused_epochs_fn
    # for why dispatch count is a first-order solver cost on this hardware.
    # A ragged tail block (d % block_size != 0) keeps the legacy loop.
    if (
        config.fused_epochs is not False
        and d % block_size == 0
        and start_epoch < num_iters
    ):
        return _solve_fused(
            A, blocks, lam_arr, w_rows, W, R, num_iters, start_epoch,
            cache_grams, weighted, checkpoint_dir, fingerprint, mesh, axis,
            throttle,
        )

    a_blocks = [lax.slice_in_dim(A.data, s, e, axis=1) for s, e in blocks]
    if cache_grams and start_epoch < num_iters:
        cached_update = _cached_block_update_fn(
            mesh, axis, _precision(), weighted,
            fold_blocks(mesh.shape[axis]),
        )
        invs = _factor_blocks(
            a_blocks, blocks, lam_arr, w_rows, mesh, axis, weighted, throttle
        )
        for epoch in range(start_epoch, num_iters):
            for i in range(len(blocks)):
                R, W[i] = cached_update(
                    a_blocks[i], invs[i], R, W[i], w_rows
                )
            if throttle:
                R.block_until_ready()
            if checkpoint_dir is not None:
                _save_epoch(checkpoint_dir, epoch + 1, W, R, fingerprint)
        if checkpoint_dir is not None:
            wait_for_checkpoints(checkpoint_dir)
        return W, blocks
    for epoch in range(start_epoch, num_iters):
        for i in range(len(blocks)):
            R, W[i] = update(a_blocks[i], R, W[i], lam_arr, w_rows)
        if throttle:
            R.block_until_ready()
        if checkpoint_dir is not None:
            _save_epoch(checkpoint_dir, epoch + 1, W, R, fingerprint)
    if checkpoint_dir is not None:
        wait_for_checkpoints(checkpoint_dir)
    return W, blocks


def _solve_fused(
    A, blocks, lam_arr, w_rows, W, R, num_iters, start_epoch, cache_grams,
    weighted, checkpoint_dir, fingerprint, mesh, axis, throttle,
):
    """The scan-fused solve body: stacked blocks → (optional) one batched
    factor program → one epochs program (or one per epoch when
    checkpointing). Returns the same (W blocks, ranges) as the legacy loop."""
    precision = _precision()
    nb = len(blocks)
    a3 = _stack_blocks_fn(mesh, axis, nb)(A.data)
    if cache_grams:
        # Chunked like _factor_blocks (shared _factor_chunk policy): bounds
        # the factor transient to chunk·b² buffers instead of nb·b².
        chunk = _factor_chunk(blocks[0][1] - blocks[0][0])
        factor = _fused_factor_fn(
            mesh, axis, precision, weighted, fold_blocks(mesh.shape[axis])
        )
        if chunk >= nb:
            invs = factor(a3, lam_arr, w_rows)
        else:
            parts = []
            for c0 in range(0, nb, chunk):
                part = factor(a3[c0 : c0 + chunk], lam_arr, w_rows)
                if throttle:
                    # An unserialized burst of independent collective
                    # programs deadlocks the CPU in-process rendezvous
                    # (same guard as _factor_blocks).
                    part.block_until_ready()
                parts.append(part)
            invs = jnp.concatenate(parts, axis=0)
    else:
        # Dummy scan operand: the uncached body re-derives each block's
        # inverse in-place; scan only needs a leading-nb structure to carry.
        invs = jnp.zeros((nb, 1, 1), dtype=R.dtype)
    W3 = jnp.stack(W)
    if checkpoint_dir is None:
        step = _fused_epochs_fn(
            mesh, axis, precision, weighted, num_iters - start_epoch,
            cache_grams, fold_blocks(mesh.shape[axis]),
        )
        R, W3 = step(a3, invs, R, W3, lam_arr, w_rows)
    else:
        step = _fused_epochs_fn(
            mesh, axis, precision, weighted, 1, cache_grams,
            fold_blocks(mesh.shape[axis]),
        )
        for epoch in range(start_epoch, num_iters):
            R, W3 = step(a3, invs, R, W3, lam_arr, w_rows)
            _save_epoch(
                checkpoint_dir, epoch + 1,
                [W3[i] for i in range(nb)], R, fingerprint,
            )
        wait_for_checkpoints(checkpoint_dir)
    return [W3[i] for i in range(nb)], blocks


def _make_fingerprint(
    B: RowMatrix,
    d: int,
    block_size: int,
    lam,
    weighted: bool,
    a_probe: float,
    a_dtype,
) -> dict:
    """Problem identity for checkpoint binding. Probes use LOGICAL rows
    (first and last real row), so the device-resident and host-streamed
    paths produce identical fingerprints and can resume each other. The
    storage dtype is part of the identity — an f32 solve must not resume a
    bf16 one (mixed-precision epochs with no warning). ``device_count`` /
    ``data_axis`` are the per-shard manifest: same problem on a different
    mesh width either MIGRATES at restore (``utils.mesh.reshard_state``
    trims and re-pads the residual onto the new shard multiple — elastic
    mesh, default on, counted) or refuses typed (``MeshMismatchError``)
    with ``KEYSTONE_ELASTIC_MESH=0`` — never resumed into
    differently-folded accumulators, never silently discarded."""
    from keystone_tpu.utils.mesh import num_data_shards

    return {
        "rows": B.padded_rows,
        "n": B.n,
        "d": d,
        "k": B.data.shape[1],
        "block_size": block_size,
        "lam": float(lam),
        "weighted": weighted,
        "a_dtype": str(jnp.dtype(a_dtype)),
        "a_probe": a_probe,
        "b_probe": float(jnp.sum(B.data[0]) + jnp.sum(B.data[B.n - 1])),
        "device_count": int(num_data_shards(B.mesh)),
        "data_axis": str(config.data_axis),
    }


def _resume_or_default(checkpoint_dir, fingerprint, W, R, sharding):
    """Restore (epoch, W, R) from a matching checkpoint, else the defaults."""
    if checkpoint_dir is None:
        return 0, W, R
    restored = _restore_latest(checkpoint_dir, fingerprint)
    if restored is None:
        return 0, W, R
    epoch, W_np, R_np = restored
    W = [jnp.asarray(w) for w in W_np]
    R = jax.device_put(jnp.asarray(R_np), sharding)
    return epoch, W, R


# One async checkpointer per checkpoint directory (keyed by abspath):
# writes overlap the next epoch's device work; orbax's save() itself blocks
# on any previous in-flight save, so at most one write per directory is ever
# outstanding. Per-directory scoping confines a failed background write to
# the solve that issued it, and wait_for_checkpoints closes + drops the
# entry at every solver return so instances don't accumulate
# (SURVEY.md §5 failure-recovery row).
_ASYNC_CKPT: dict = {}


def _async_checkpointer(ckpt_dir: str):
    import os

    import orbax.checkpoint as ocp

    key = os.path.abspath(ckpt_dir)
    cp = _ASYNC_CKPT.get(key)
    if cp is None:
        cp = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
        _ASYNC_CKPT[key] = cp
    return cp


def _save_epoch(ckpt_dir: str, epoch: int, W, R, fingerprint) -> None:
    import os

    path = os.path.join(os.path.abspath(ckpt_dir), f"epoch_{epoch}")
    # Host-resident pytree: checkpoints cross process/mesh boundaries, so
    # shardings are re-applied on restore rather than persisted. The D2H
    # fetch is synchronous; serialization + write run in the background
    # (save blocks internally on the previous in-flight save).
    tree = {
        "epoch": epoch,
        "W": [np.asarray(w) for w in W],
        "R": np.asarray(R),
        "fingerprint": dict(fingerprint),
    }
    _async_checkpointer(ckpt_dir).save(path, tree, force=True)
    # JSON mesh sidecar: the static lint's (KG107) no-execution window
    # into what mesh this directory's epochs were folded under.
    from keystone_tpu.utils.mesh import write_mesh_manifest

    write_mesh_manifest(ckpt_dir, fingerprint)


def wait_for_checkpoints(ckpt_dir: str) -> None:
    """Block until ``ckpt_dir``'s in-flight epoch save is durable, then
    release its checkpointer. The solvers call this before returning;
    callers only need it for mid-solve probes."""
    import os

    cp = _ASYNC_CKPT.pop(os.path.abspath(ckpt_dir), None)
    if cp is not None:
        try:
            cp.wait_until_finished()
        finally:
            cp.close()


def _fingerprint_matches(saved, expected) -> bool:
    if set(saved) != set(expected):
        return False
    for key, val in expected.items():
        sval = saved[key]
        if isinstance(val, float):
            if abs(float(sval) - val) > 1e-3 * max(1.0, abs(val)):
                return False
        elif sval != val:
            return False
    return True


def _restore_latest(ckpt_dir: str, fingerprint):
    import logging
    import os
    import re

    import orbax.checkpoint as ocp

    ckpt_dir = os.path.abspath(ckpt_dir)
    if not os.path.isdir(ckpt_dir):
        return None
    epochs = []
    for name in os.listdir(ckpt_dir):
        m = re.fullmatch(r"epoch_(\d+)", name)
        if m:
            epochs.append(int(m.group(1)))
    if not epochs:
        return None
    latest = max(epochs)
    tree = ocp.PyTreeCheckpointer().restore(
        os.path.join(ckpt_dir, f"epoch_{latest}")
    )
    from keystone_tpu.utils.mesh import mesh_resume_decision, reshard_state

    # Pre-manifest snapshots (no device_count/data_axis keys) compare
    # with the absent keys backfilled as wildcards (the shared
    # mesh_resume_decision triage), so a legacy epoch checkpoint of the
    # SAME problem still resumes after the manifest upgrade instead of
    # silently restarting at epoch 0. Same problem on a different mesh
    # width migrates (elastic, counted) or refuses typed.
    decision, saved_fp = mesh_resume_decision(
        tree.get("fingerprint"), fingerprint,
        f"BCD checkpoint {ckpt_dir}",
        extra_mesh_keys=("rows",), same_problem=_fingerprint_matches,
    )
    if decision == "fresh":
        logging.getLogger("keystone_tpu").warning(
            "checkpoint dir %s holds a different solve (fingerprint "
            "mismatch); starting fresh",
            ckpt_dir,
        )
        return None
    if decision == "migrate":
        tree = reshard_state(
            dict(tree, fingerprint=saved_fp), family="bcd_epoch"
        )
    return int(tree["epoch"]), tree["W"], tree["R"]


def _refuse_bcd_mesh_mismatch(saved_fp, expected_fp, ckpt_dir) -> bool:
    """The shared mesh-width rule (``utils.mesh.refuse_mesh_mismatch``)
    with the BCD-specific exclusions: padded ``rows`` follow the mesh (the
    shard multiple changes them for the same logical solve), and problem
    identity uses the solver's tolerant float matching. Returns True when
    the elastic path should migrate the checkpoint via ``reshard_state``;
    raises the typed ``MeshMismatchError`` when elastic migration is
    pinned off (resuming W/R folded under one shard layout into another
    unmigrated would be a wrong-answer resume); other mismatches stay on
    the warn-and-start-fresh path."""
    from keystone_tpu.utils.mesh import refuse_mesh_mismatch

    return refuse_mesh_mismatch(
        saved_fp, expected_fp, f"BCD checkpoint {ckpt_dir}",
        extra_mesh_keys=("rows",), same_problem=_fingerprint_matches,
    )


def _reshard_bcd_R(state, layout, where):
    """Shared residual migration for both BCD checkpoint families: trim
    the zero pad rows folded under the OLD shard multiple off ``R``,
    re-pad to the NEW multiple, and rewrite the fingerprint's ``rows`` +
    mesh keys. Pad rows are zero by construction (A and B are zero-padded,
    so every epoch's residual update leaves them zero) — a nonzero pad
    region can only mean a torn per-shard payload, which refuses typed."""
    from keystone_tpu.utils.mesh import (
        pad_multiple,
        pad_rows,
        reshard_refused,
    )

    fp = dict(state.get("fingerprint") or {})
    R = state.get("R")
    n, rows = int(fp.get("n", -1)), int(fp.get("rows", -1))
    R = np.asarray(R) if R is not None else None
    if R is None or n < 0 or R.shape[0] != rows or n > rows:
        raise reshard_refused(
            where,
            "residual shape does not match its fingerprint "
            "(torn or partially written checkpoint)",
        )
    if R[n:].any():
        raise reshard_refused(
            where,
            "nonzero rows in the residual's pad region — a partial "
            "per-shard write, not a clean epoch snapshot",
        )
    R_new, _ = pad_rows(R[:n], pad_multiple(layout.num_shards))
    fp["rows"] = int(R_new.shape[0])
    fp["device_count"] = int(layout.num_shards)
    fp["data_axis"] = str(layout.axis)
    return dict(state, R=R_new, fingerprint=fp)


def _reshard_bcd_epoch(state, layout):
    """Elastic-mesh adapter for epoch checkpoints (orbax ``epoch_N``
    trees): W blocks are replicated (placement-free) and pass through
    byte-identical; only the residual's row padding follows the mesh."""
    return _reshard_bcd_R(state, layout, "BCD epoch checkpoint")


def _reshard_bcd_stream(state, layout):
    """Elastic-mesh adapter for mid-epoch block snapshots: W blocks and
    the cached ridge inverses are replicated (placement-free); the
    residual re-pads exactly as the epoch family does."""
    state = _reshard_bcd_R(state, layout, "BCD block checkpoint")
    if int(state.get("block", -1)) < 0 or int(state.get("epoch", -1)) < 0:
        from keystone_tpu.utils.mesh import reshard_refused

        raise reshard_refused(
            "BCD block checkpoint",
            "snapshot is missing its block cursor",
        )
    return state


register_reshard_adapter("bcd_epoch", _reshard_bcd_epoch)
register_reshard_adapter("bcd_stream", _reshard_bcd_stream)


def assemble_blocks(W: List[jax.Array]) -> jax.Array:
    """Concatenate per-block solutions into the full (d, k) matrix (blocks
    are contiguous ascending column ranges by construction)."""
    return jnp.concatenate(W, axis=0)


_BCD_CKPT_KEY = "bcd_stream"


def _bcd_ckpt_store(checkpoint_dir: str):
    from keystone_tpu.workflow.disk_cache import DiskCache

    return DiskCache(checkpoint_dir, suffix=".ckpt.pkl")


def _bcd_ckpt_save(store, fingerprint, epoch, block, W, R, invs) -> None:
    """Mid-epoch snapshot: solver state (W blocks + residual + the ridge
    inverses computed so far) and the block cursor — ``block`` blocks of
    ``epoch`` are complete. The atomic DiskCache rewrite means a kill
    mid-save leaves the previous complete snapshot. D2H of R is the sync
    this costs, once per K blocks."""
    from keystone_tpu.utils.metrics import reliability_counters

    store.put(
        _BCD_CKPT_KEY,
        {
            "fingerprint": dict(fingerprint),
            "epoch": int(epoch),
            "block": int(block),
            "W": [np.asarray(w) for w in W],
            "R": np.asarray(R),
            "invs": {
                i: np.asarray(v) for i, v in enumerate(invs) if v is not None
            },
        },
        overwrite=True,
    )
    from keystone_tpu.utils.mesh import write_mesh_manifest

    write_mesh_manifest(store.root, fingerprint)
    reliability_counters.bump("checkpoints_written")


def _bcd_ckpt_resume(store, fingerprint):
    """The block snapshot, or None when absent / bound to another solve.
    Same mesh triage as the epoch family: a snapshot of THIS solve under
    a different mesh width migrates (elastic, counted) or refuses typed —
    it is never silently discarded as if it were another problem."""
    import logging

    from keystone_tpu.utils.mesh import mesh_resume_decision, reshard_state

    state = store.get(_BCD_CKPT_KEY)
    if state is None:
        return None
    decision, saved_fp = mesh_resume_decision(
        state.get("fingerprint"), fingerprint,
        f"BCD block checkpoint {store.root}",
        extra_mesh_keys=("rows",), same_problem=_fingerprint_matches,
    )
    if decision == "fresh":
        logging.getLogger("keystone_tpu").warning(
            "block checkpoint in %s holds a different solve (fingerprint "
            "mismatch); ignoring it", store.root,
        )
        return None
    if decision == "migrate":
        state = reshard_state(
            dict(state, fingerprint=saved_fp), family="bcd_stream"
        )
    return state


def block_coordinate_descent_streamed(
    A_host,
    B: RowMatrix,
    block_size: int,
    num_iters: int,
    lam: float = 0.0,
    row_weights: Optional[jax.Array] = None,
    checkpoint_dir: Optional[str] = None,
    col_center: Optional[np.ndarray] = None,
    checkpoint_every: Optional[int] = None,
) -> Tuple[List[jax.Array], List[Tuple[int, int]]]:
    """BCD for feature matrices that exceed HBM: A stays in host RAM and
    column blocks stream to the device double-buffered — the transfer of
    block b+1 overlaps the MXU work on block b (SURVEY.md §7 hard part 1:
    the replacement for Spark's cached-RDD block access).

    ``A_host`` is a dense ndarray or a CSR ``SparseBatch`` (the large-vocab
    text path): sparse blocks densify per column block right here, so an
    (n, vocab) dense matrix never exists anywhere.

    ``col_center`` (dense only): per-column means subtracted from each
    block AS it streams — the intercept-centering of the estimator layer
    without a second full-size host copy of A (each block is a fresh copy
    on its way to the device anyway).

    The first epoch fuses gram+Cholesky into each block update and keeps
    the small (b, b) factors resident, so later epochs run the cheap
    cached update while still streaming only one block of A at a time.

    Reliability: each block's H2D retries transient RESOURCE_EXHAUSTED
    with backoff (a column block can't be split without changing the
    solve — persistent OOM propagates with the advice to shrink
    ``block_size``). With ``checkpoint_dir``, epoch snapshots (orbax, as
    before) are supplemented by mid-epoch block snapshots every
    ``checkpoint_every`` blocks (default ``config.checkpoint_every``,
    env ``KEYSTONE_CHECKPOINT_EVERY``; 0 = epoch-only) holding W, R, the
    ridge inverses computed so far, and the block cursor — a killed fit
    resumes recomputing at most K block updates.
    """
    from keystone_tpu.utils.metrics import active_tracer
    from keystone_tpu.utils.reliability import RetryPolicy, active_plan
    from keystone_tpu.utils.sparse import SparseBatch

    tracer = active_tracer()  # resolved once per solve, like the plan
    sparse = isinstance(A_host, SparseBatch)
    if sparse and col_center is not None:
        raise ValueError(
            "col_center is a dense-path feature (sparse fits learn the "
            "intercept via an appended ones column)"
        )
    mesh, axis = B.mesh, config.data_axis
    if A_host.shape[0] != B.n:
        raise ValueError(
            f"A rows ({A_host.shape[0]}) must match B rows ({B.n})"
        )
    d = A_host.shape[1]
    k = B.data.shape[1]
    # Streamed blocks take the storage dtype (bf16 halves H2D traffic in
    # throughput mode); solver state stays in the accumulation dtype.
    dtype = storage_dtype()
    cdtype = jnp.dtype(config.accum_dtype)
    blocks = [(s, min(s + block_size, d)) for s in range(0, d, block_size)]
    nb = len(blocks)
    pad = B.padded_rows - A_host.shape[0]
    sharding = jax.sharding.NamedSharding(mesh, P(axis))

    # Center in A's own (full-width) dtype BEFORE any storage-dtype cast:
    # subtracting a large mean after bf16 quantization would leave the
    # centered values carrying the uncentered magnitude's rounding error
    # (catastrophic cancellation) — the device path centers in f32 too.
    center = (
        None if col_center is None else np.asarray(col_center, dtype=A_host.dtype)
    )

    def host_block(i: int) -> np.ndarray:
        """Host-side block prep — slice/densify, center, cast, pad. Pure
        numpy on read-only A_host, so the prefetch thread runs it safely."""
        s, e = blocks[i]
        if sparse:
            block = A_host.densify(s, e, dtype=dtype)
        elif center is not None:
            block = np.asarray(A_host[:, s:e] - center[s:e], dtype=dtype)
        else:
            block = np.ascontiguousarray(A_host[:, s:e], dtype=dtype)
        if pad:
            block = np.pad(block, ((0, pad), (0, 0)))
        return block

    plan = active_plan()
    retry = RetryPolicy()

    def _transfer(block: np.ndarray) -> jax.Array:
        def attempt():
            if plan is not None:
                plan.maybe_raise("oom")
            return jax.device_put(block, sharding)

        try:
            return retry.call(attempt, site="h2d", counter="h2d_retries")
        except Exception as exc:
            from keystone_tpu.utils.reliability import is_oom

            if is_oom(exc):
                raise type(exc)(
                    f"{exc} [streamed BCD: a ({block.shape[0]}, "
                    f"{block.shape[1]}) block does not fit on device even "
                    "after retries; reduce block_size]"
                ) from exc
            raise

    def put_host(block: np.ndarray) -> jax.Array:
        """H2D one prepared block, retrying transient RESOURCE_EXHAUSTED
        (real or the harness's ``oom`` site). Unlike the row-chunked
        solver there is no downshift — halving a column block would
        change the solve — so a persistent OOM propagates, annotated.
        Spanned per block when tracing is live."""
        if tracer is None:
            return _transfer(block)
        t0 = tracer.now()
        out = _transfer(block)
        tracer.record(
            "bcd.h2d", "solver", t0,
            shape=[int(block.shape[0]), int(block.shape[1])],
        )
        return out

    def put(i: int) -> jax.Array:
        return put_host(host_block(i))

    weighted = row_weights is not None
    if weighted:
        w_rows = jnp.asarray(row_weights, dtype=dtype)
        if w_rows.shape[0] != B.padded_rows:
            w_rows = jnp.pad(w_rows, (0, B.padded_rows - w_rows.shape[0]))
    else:
        w_rows = jnp.zeros((B.padded_rows,), dtype=dtype)
    w_rows = jax.device_put(w_rows, sharding)

    first = _first_epoch_update_fn(
        mesh, axis, _precision(), weighted, fold_blocks(mesh.shape[axis])
    )
    cached = _cached_block_update_fn(
        mesh, axis, _precision(), weighted, fold_blocks(mesh.shape[axis])
    )
    lam_arr = jnp.asarray(lam, dtype=cdtype)
    throttle = jax.default_backend() == "cpu"

    W = [jnp.zeros((e - s, k), dtype=cdtype) for s, e in blocks]
    invs: List[Optional[jax.Array]] = [None] * nb
    # jnp.array COPIES: astype is a no-op alias when dtypes already
    # match, and the first update DONATES R — donating an alias of the
    # caller's B.data would delete their labels out from under them.
    R = jnp.array(B.data, dtype=cdtype)
    fingerprint = None
    if checkpoint_dir is not None:
        if sparse:
            a_probe = A_host.row_sum(0) + A_host.row_sum(len(A_host) - 1)
        else:
            # Probe the EFFECTIVE (centered) matrix so device-path and
            # streamed-path checkpoints stay mutually resumable.
            shift = 2.0 * float(center.sum()) if center is not None else 0.0
            a_probe = float(A_host[0].sum() + A_host[-1].sum()) - shift
        fingerprint = _make_fingerprint(
            B, d, block_size, lam, weighted, a_probe=a_probe, a_dtype=dtype
        )
    # On resume, ridge inverses rebuild lazily: the `first` update at the
    # resumed epoch recomputes them as part of a normal update.
    start_epoch, W, R = _resume_or_default(
        checkpoint_dir, fingerprint, W, R, sharding
    )
    # Mid-epoch block snapshots (atomic DiskCache) can be FURTHER along
    # than the last orbax epoch save; prefer whichever resumes later.
    start_block = 0
    every = (
        config.checkpoint_every if checkpoint_every is None
        else int(checkpoint_every)
    )
    ckpt_store = None
    if checkpoint_dir is not None and every > 0:
        from keystone_tpu.utils.metrics import reliability_counters

        ckpt_store = _bcd_ckpt_store(checkpoint_dir)
        state = _bcd_ckpt_resume(ckpt_store, fingerprint)
        if state is not None and (state["epoch"], state["block"]) > (
            start_epoch, 0,
        ):
            start_epoch, start_block = state["epoch"], state["block"]
            W = [jnp.asarray(w) for w in state["W"]]
            R = jax.device_put(jnp.asarray(state["R"]), sharding)
            for i, v in state["invs"].items():
                invs[int(i)] = jnp.asarray(v)
            reliability_counters.bump("checkpoints_resumed")
            if start_block >= nb:  # snapshot landed on an epoch boundary
                start_epoch, start_block = start_epoch + 1, 0
    if start_epoch >= num_iters:
        if ckpt_store is not None:
            ckpt_store.delete(_BCD_CKPT_KEY)  # consumed by this solve
        return W, blocks
    # KEYSTONE_STREAM_NO_OVERLAP=1 serializes transfer and compute — it
    # exists so the checkride can MEASURE what double-buffering buys; it is
    # never the right setting for real runs.
    from keystone_tpu.config import env_flag
    from keystone_tpu.loaders.stream import PrefetchIterator

    no_overlap = env_flag("KEYSTONE_STREAM_NO_OVERLAP")
    # Host-side block prep (densify/center/cast/pad) runs on a background
    # prefetch thread, config.prefetch_depth blocks ahead, on top of the
    # existing H2D double buffer: the device then never waits on the numpy
    # prep either. depth=0 keeps the prep inline on the consumer thread.
    depth = 0 if no_overlap else max(0, int(config.prefetch_depth))
    total = (num_iters - start_epoch) * nb - start_block
    src = None
    if depth > 0:

        def host_blocks():
            for e in range(start_epoch, num_iters):
                for i in range(start_block if e == start_epoch else 0, nb):
                    yield host_block(i)

        src = PrefetchIterator(host_blocks(), depth)

    def put_ahead(i_next: int) -> jax.Array:
        if src is not None:
            return put_host(next(src))
        return put(i_next)

    from keystone_tpu.utils.flight_recorder import ProgressReporter

    # Always-on solve journey: block/epoch progress with a known total
    # (so ETA is live), checkpoint age, stall watchdog; a death mid-epoch
    # force-dumps the solver recorder naming the last completed block.
    progress = ProgressReporter("bcd_streamed", total_units=total)
    n_rows = int(A_host.shape[0])
    try:
        with progress:
            next_buf = None if no_overlap else put_ahead(start_block)
            consumed = 0
            blocks_done = 0
            for epoch in range(start_epoch, num_iters):
                first_block = start_block if epoch == start_epoch else 0
                for i in range(first_block, nb):
                    if no_overlap:
                        cur = put(i)
                        cur.block_until_ready()
                    else:
                        cur = next_buf
                        consumed += 1
                        # Prefetch the next block while this one computes
                        # (double buffering): H2D DMA overlaps the MXU
                        # work.
                        if consumed < total:
                            next_buf = put_ahead((i + 1) % nb)
                    was_cached = invs[i] is not None
                    t0 = tracer.now() if tracer is not None else 0
                    if invs[i] is None:
                        R, W[i], invs[i] = first(
                            cur, R, W[i], lam_arr, w_rows
                        )
                    else:
                        R, W[i] = cached(cur, invs[i], R, W[i], w_rows)
                    if throttle:
                        R.block_until_ready()
                    if tracer is not None:
                        # Dispatch time unless throttled (the block above
                        # makes the CPU path synchronous anyway).
                        tracer.record(
                            "bcd.block_update", "solver", t0, epoch=epoch,
                            block=i, cached_inverse=was_cached,
                            async_dispatch=not throttle,
                        )
                    blocks_done += 1
                    progress.unit_done(rows=n_rows, epoch=epoch, block=i)
                    if ckpt_store is not None and blocks_done % every == 0:
                        _bcd_ckpt_save(
                            ckpt_store, fingerprint, epoch, i + 1, W, R,
                            invs,
                        )
                        progress.checkpoint()
                if checkpoint_dir is not None:
                    _save_epoch(checkpoint_dir, epoch + 1, W, R, fingerprint)
    finally:
        if src is not None:
            src.close()
    if checkpoint_dir is not None:
        wait_for_checkpoints(checkpoint_dir)
    if ckpt_store is not None:
        # Block snapshots are mid-flight state, consumed by the solve that
        # completes over them; the epoch-boundary orbax saves remain the
        # durable cross-run artifact (pre-existing semantics).
        ckpt_store.delete(_BCD_CKPT_KEY)
    return W, blocks
