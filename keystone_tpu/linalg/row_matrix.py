"""Row-sharded tall-skinny distributed matrix.

Ref: ml-matrix `RowPartitionedMatrix` / `DistributedMatrix` (SURVEY.md §2.2)
[unverified]. An ``RDD[RowPartition(DenseMatrix)]`` becomes a single device
array sharded on its leading axis over the mesh's ``data`` axis; rows are
zero-padded to a multiple of the shard count (zero rows are invisible to the
gram/normal-equation reductions, and `collect` strips them).
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from keystone_tpu.utils.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from keystone_tpu.config import config
from keystone_tpu.utils.mesh import (
    default_mesh,
    fold_blocks,
    pad_multiple,
    pad_rows,
)


def _precision():
    return {
        "highest": lax.Precision.HIGHEST,
        "high": lax.Precision.HIGH,
        "default": lax.Precision.DEFAULT,
    }[config.solver_precision]


def storage_dtype():
    """Dtype for the solver's big operands (config.solver_storage_dtype)."""
    return jnp.dtype(config.solver_storage_dtype or config.default_dtype)


def donate_argnums(mesh: Mesh, *argnums: int):
    """donate_argnums for the solver hot loops on real hardware: the old
    residual/weight/accumulator buffers are dead the moment the update
    returns, and donating them caps the solver's HBM high-water at one live
    copy (SURVEY.md §5 sanitizer row's donation/aliasing prescription).
    Gated on ``config.donate_buffers`` (KEYSTONE_DONATE_BUFFERS=0 pins the
    non-donated baseline for A/B benches and deleted-buffer debugging).
    CPU meshes keep the legacy refusal: these loops predate runtimes that
    honor host donation, and their CPU test surface pins the undonated
    lowering — the workflow layer's staged-chain donation
    (``SpecLayout.jit``) is the path that donates on every backend."""
    if not config.donate_buffers:
        return ()
    if mesh.devices.flat[0].platform == "cpu":
        return ()
    return argnums


def solver_matmul(x, y, precision):
    """Matmul on the solver path, dtype-aware.

    When either operand is stored in bfloat16 (the throughput mode), both
    are fed to the MXU as bf16 with f32 accumulation — its native fast path
    (one pass, full accumulator width). Full-width operands keep the
    configured solver precision (HIGHEST = 6-pass bf16 emulation of f32).
    """
    if x.dtype == jnp.bfloat16 or y.dtype == jnp.bfloat16:
        return jnp.matmul(
            x.astype(jnp.bfloat16),
            y.astype(jnp.bfloat16),
            preferred_element_type=jnp.dtype(config.accum_dtype),
        )
    return jnp.matmul(x, y, precision=precision)


def sharded_rowsum(block_fn, axis: str, width: int, operands, row_axes=None):
    """THE reduction over the sharded row axis for every solver
    accumulator (grams, AᵀB, column sums) — call inside a shard_map body.

    ``block_fn(*row_slices)`` maps row slices of ``operands`` to a pytree
    of partial sums. With the canonical fold active
    (``utils.mesh.fold_blocks``), the logical rows are cut into a FIXED
    number of blocks — the same blocks on every mesh width, because rows
    pad to a multiple of the block count (``pad_multiple``) — and the
    per-block partials combine in a balanced binary tree: local subtrees
    per shard, then a butterfly (log₂ width ppermute rounds) across them.
    Every width that divides the block count therefore sums in the SAME
    order and produces the SAME bits — the invariance the elastic mesh
    resume gate (reshard then continue, bit-identical to a fresh fit at
    the new width) stands on. Widths outside the fold's reach keep the
    legacy whole-shard ``psum`` (order differs per width, sums still
    exact). ``row_axes`` names the row axis per operand (default 0 — the
    batched-gram callers reduce over axis 1 of a stacked operand)."""
    if row_axes is None:
        row_axes = (0,) * len(operands)
    C = fold_blocks(width)
    if not C:
        return jax.tree_util.tree_map(
            lambda v: lax.psum(v, axis), block_fn(*operands)
        )
    blocks_per_shard = C // width
    parts = []
    for i in range(blocks_per_shard):
        slices = [
            lax.slice_in_dim(
                op,
                i * (op.shape[ra] // blocks_per_shard),
                (i + 1) * (op.shape[ra] // blocks_per_shard),
                axis=ra,
            )
            for op, ra in zip(operands, row_axes)
        ]
        parts.append(block_fn(*slices))
    while len(parts) > 1:
        parts = [
            jax.tree_util.tree_map(jnp.add, parts[i], parts[i + 1])
            for i in range(0, len(parts), 2)
        ]
    acc = parts[0]
    step = 1
    while step < width:
        perm = [(i, i ^ step) for i in range(width)]
        acc = jax.tree_util.tree_map(
            lambda v, p=perm: v + lax.ppermute(v, axis, p), acc
        )
        step *= 2
    return acc


@lru_cache(maxsize=None)
def _gram_fn(mesh: Mesh, axis: str, precision, fold: int):
    width = mesh.shape[axis]

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(), check_vma=False)
    def gram(a):
        return sharded_rowsum(
            lambda ab: solver_matmul(ab.T, ab, precision), axis, width, (a,)
        )

    return gram


@lru_cache(maxsize=None)
def _atb_fn(mesh: Mesh, axis: str, precision, fold: int):
    width = mesh.shape[axis]

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=(P(axis), P(axis)), out_specs=P(), check_vma=False)
    def atb(a, b):
        return sharded_rowsum(
            lambda ab, bb: solver_matmul(ab.T, bb, precision),
            axis, width, (a, b),
        )

    return atb


@lru_cache(maxsize=None)
def _gram_and_atb_fn(mesh: Mesh, axis: str, precision, fold: int):
    width = mesh.shape[axis]

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=(P(axis), P(axis)), out_specs=(P(), P()), check_vma=False)
    def gram_and_atb(a, b):
        # One program: a is read from HBM once for both reductions.
        return sharded_rowsum(
            lambda ab, bb: (
                solver_matmul(ab.T, ab, precision),
                solver_matmul(ab.T, bb, precision),
            ),
            axis, width, (a, b),
        )

    return gram_and_atb


@lru_cache(maxsize=None)
def _col_sum_fn(mesh: Mesh, axis: str, fold: int):
    width = mesh.shape[axis]

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(), check_vma=False)
    def col_sum(a):
        return sharded_rowsum(
            lambda ab: jnp.sum(ab, axis=0), axis, width, (a,)
        )

    return col_sum


@lru_cache(maxsize=None)
def _weighted_col_sum_fn(mesh: Mesh, axis: str, fold: int):
    width = mesh.shape[axis]

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=(P(axis), P(axis)), out_specs=P(), check_vma=False)
    def weighted_col_sum(w, a):
        return sharded_rowsum(
            lambda wb, ab: jnp.sum(wb * ab, axis=0), axis, width, (w, a)
        )

    return weighted_col_sum


@lru_cache(maxsize=None)
def _matmul_fn(mesh: Mesh, axis: str, precision):
    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=(P(axis), P()), out_specs=P(axis))
    def mm(a, w):
        return solver_matmul(a, w, precision)

    return mm


class RowMatrix:
    """An (n, d) matrix stored row-sharded over the mesh ``data`` axis.

    ``data`` has shape (n_padded, d) with ``n_padded % num_shards == 0``;
    ``n`` is the logical row count.
    """

    def __init__(self, data: jax.Array, n: int, mesh: Mesh):
        self.data = data
        self.n = int(n)
        self.mesh = mesh

    # -- construction ------------------------------------------------------

    @classmethod
    def from_array(
        cls,
        x,
        mesh: Optional[Mesh] = None,
        dtype=None,
    ) -> "RowMatrix":
        mesh = mesh or default_mesh()
        axis = config.data_axis
        k = mesh.shape[axis]
        dtype = dtype or config.default_dtype
        x = np.asarray(x, dtype=dtype) if isinstance(x, np.ndarray) else jnp.asarray(x, dtype=dtype)
        # pad_multiple, not the raw width: with the canonical fold active
        # every mesh width pads (and blocks) rows identically, which is
        # what makes the gram fold — and thus whole solves —
        # bit-identical across widths (the elastic-mesh resume gate).
        padded, n = pad_rows(x, pad_multiple(k))
        sharding = NamedSharding(mesh, P(axis))
        data = jax.device_put(padded, sharding)
        return cls(data, n, mesh)

    # -- properties --------------------------------------------------------

    @property
    def shape(self):
        return (self.n, self.data.shape[1])

    @property
    def padded_rows(self) -> int:
        return self.data.shape[0]

    @property
    def num_shards(self) -> int:
        return self.mesh.shape[config.data_axis]

    # -- ops ---------------------------------------------------------------

    def collect(self) -> np.ndarray:
        """Gather to host, stripping padding (the RDD ``collect`` analog)."""
        return np.asarray(self.data)[: self.n]

    def gram(self) -> jax.Array:
        """AᵀA, replicated: per-shard MXU gemm + psum over ICI
        (the ``treeAggregate`` of local grams in NormalEquations)."""
        return _gram_fn(
            self.mesh, config.data_axis, _precision(),
            fold_blocks(self.num_shards),
        )(self.data)

    def atb(self, other: "RowMatrix") -> jax.Array:
        """AᵀB for a row-aligned B."""
        self._check_aligned(other)
        return _atb_fn(
            self.mesh, config.data_axis, _precision(),
            fold_blocks(self.num_shards),
        )(self.data, other.data)

    def gram_and_atb(self, other: "RowMatrix"):
        """(AᵀA, AᵀB) in one fused program — A is read once."""
        self._check_aligned(other)
        return _gram_and_atb_fn(
            self.mesh, config.data_axis, _precision(),
            fold_blocks(self.num_shards),
        )(self.data, other.data)

    def col_sums(self) -> jax.Array:
        """Column sums over the LOGICAL rows, replicated: per-shard sum +
        psum over ICI. Zero pad rows are inert, so this equals the
        unpadded sum — and because every construction path re-shards onto
        the same mesh, the result is bit-identical no matter what
        placement the source array arrived with (the property that keeps
        intercept means — and thus whole fits — placement-invariant)."""
        return _col_sum_fn(
            self.mesh, config.data_axis, fold_blocks(self.num_shards)
        )(self.data)

    def weighted_col_sums(self, weights: "RowMatrix") -> jax.Array:
        """Σ_i w_i · row_i for a row-aligned (n, 1) weight column — the
        weighted-centering reduction, psum'd like ``col_sums``."""
        self._check_aligned(weights)
        return _weighted_col_sum_fn(
            self.mesh, config.data_axis, fold_blocks(self.num_shards)
        )(weights.data, self.data)

    def centered(self, means: jax.Array, dtype=None) -> "RowMatrix":
        """``self - means`` over the LOGICAL rows, pad rows kept ZERO (a
        plain subtraction would turn them into ``-means`` and poison the
        gram-inertness contract), optionally cast to the solver storage
        dtype. Derived on-device from the already-sharded data, so
        intercept centering costs ZERO additional host-to-device
        transfers of the big operand — the subtraction/mask/cast are
        elementwise and placement-inert, keeping centered fits
        bit-identical across arrival placements."""
        mask = (jnp.arange(self.padded_rows) < self.n)[:, None]
        data = jnp.where(mask, self.data - means, 0)
        if dtype is not None:
            data = data.astype(dtype)
        return RowMatrix(data, self.n, self.mesh)

    def matmul(self, w: jax.Array) -> "RowMatrix":
        """A @ W for replicated W; result stays row-sharded."""
        out = _matmul_fn(self.mesh, config.data_axis, _precision())(
            self.data, jnp.asarray(w, dtype=self.data.dtype)
        )
        return RowMatrix(out, self.n, self.mesh)

    def cols(self, start: int, stop: int) -> "RowMatrix":
        """Column block view (feature-block parallelism's unit of work)."""
        return RowMatrix(self.data[:, start:stop], self.n, self.mesh)

    def _check_aligned(self, other: "RowMatrix") -> None:
        if (
            other.padded_rows != self.padded_rows
            or other.n != self.n
            or other.mesh is not self.mesh
        ):
            raise ValueError(
                "row-matrices must share n, padding, and mesh "
                f"(got {self.shape}/{self.padded_rows} vs {other.shape}/{other.padded_rows})"
            )
