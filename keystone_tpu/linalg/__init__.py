"""Distributed linear algebra over a TPU device mesh.

The TPU-native rebuild of the reference's `amplab/ml-matrix` dependency
(RowPartitionedMatrix, TSQR, NormalEquations, BlockCoordinateDescent —
Ref: edu.berkeley.cs.amplab:mlmatrix, see SURVEY.md §2.2 [unverified]).

Spark `treeAggregate` tree-reductions become XLA `psum`/`all_gather`
collectives over the ICI mesh (emitted inside `shard_map` regions); the
per-partition Breeze gemms become per-chip MXU matmuls; the driver-side
Cholesky/QR solves become replicated on-device solves.
"""

from keystone_tpu.linalg.row_matrix import RowMatrix
from keystone_tpu.linalg.normal_equations import (
    solve_least_squares_chunked,
    solve_least_squares_normal,
)
from keystone_tpu.linalg.tsqr import tsqr_r, solve_least_squares_tsqr
from keystone_tpu.linalg.bcd import (
    assemble_blocks,
    block_coordinate_descent,
    block_coordinate_descent_streamed,
)
from keystone_tpu.linalg.ring_bcd import block_coordinate_descent_ring

__all__ = [
    "RowMatrix",
    "assemble_blocks",
    "solve_least_squares_normal",
    "solve_least_squares_chunked",
    "tsqr_r",
    "solve_least_squares_tsqr",
    "block_coordinate_descent",
    "block_coordinate_descent_streamed",
    "block_coordinate_descent_ring",
]
