"""Ring-parallel block coordinate descent — d-axis model parallelism.

The reference has no sequence/attention machinery; SURVEY.md §5 identifies
the feature dimension (64k–256k, ≫ single-node memory) as this workload's
"long axis" and prescribes exactly this design: shard the d-axis across
the ICI mesh into per-chip feature blocks and pass residuals around a ring
— the collective-matmul / ring-attention scheduling idea applied to
blocked least squares (PAPERS.md arXiv:2112.09017 family).

Layout and schedule:

- chip c owns feature block A_c (n × d/P columns, rows replicated) and its
  weights W_c — the model axis is sharded, nothing is all-gathered;
- B's columns split into P chunks; chunk c starts on chip c as its
  residual R_c (different B columns are independent least-squares
  problems sharing A);
- each step, every chip runs one BCD block update of ITS block against the
  residual chunk it currently holds, then `ppermute`s the chunk to the
  next chip. After P steps each chunk has visited every block once (one
  full Gauss-Seidel sweep, block order rotated per chunk — an equally
  valid sweep order), and all P chips were busy every step.

Per-chip per-epoch communication is exactly n·k/P · P = n·k values over
ICI neighbor links — no psum trees, no gathers; per-chip grams are local
(columns live on one chip) and their Cholesky factors are computed once.
Compare the data-parallel path (bcd.py): that shards n and psums b×b
grams; this shards d and rings n×k/P residuals — the right trade when d
dwarfs n·k, i.e. the reference's high-dimensional featurized regime.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax, shard_map
from jax.scipy.linalg import cho_solve
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from keystone_tpu.config import config
from keystone_tpu.linalg.row_matrix import _precision


@lru_cache(maxsize=None)
def _ring_solve_fn(mesh: Mesh, axis: str, precision):
    nshards = mesh.shape[axis]

    # num_steps is a dynamic operand (fori_loop takes traced bounds, lowering
    # to while_loop), so different iteration counts share one compilation.
    def local(a_loc, b_chunk, lam, num_steps):
        # a_loc: (n, d_loc) — this chip's feature block (rows replicated)
        # b_chunk: (n, kc) — the residual chunk starting on this chip
        d_loc = a_loc.shape[1]
        kc = b_chunk.shape[1]
        gram = jnp.matmul(a_loc.T, a_loc, precision=precision)
        chol = jnp.linalg.cholesky(
            gram + lam * jnp.eye(d_loc, dtype=gram.dtype)
        )
        idx = lax.axis_index(axis)
        w0 = jnp.zeros((d_loc, nshards * kc), dtype=a_loc.dtype)

        def step(s, carry):
            r, w = carry
            # Which chunk this chip holds at step s (chunks move +1/step).
            j = jnp.mod(idx - s, nshards)
            w_old = lax.dynamic_slice(w, (0, j * kc), (d_loc, kc))
            r_plus = r + jnp.matmul(a_loc, w_old, precision=precision)
            rhs = jnp.matmul(a_loc.T, r_plus, precision=precision)
            w_new = cho_solve((chol, True), rhs)
            r_new = r_plus - jnp.matmul(a_loc, w_new, precision=precision)
            w = lax.dynamic_update_slice(w, w_new, (0, j * kc))
            r_next = lax.ppermute(
                r_new, axis, [(p, (p + 1) % nshards) for p in range(nshards)]
            )
            return r_next, w

        _r, w = lax.fori_loop(0, num_steps, step, (b_chunk, w0))
        return w  # (d_loc, k) — concatenates to the full W over the axis

    sm = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(None, axis), P(None, axis), P(), P()),
        out_specs=P(axis, None),
        check_vma=False,
    )
    return jax.jit(sm)


def block_coordinate_descent_ring(
    A,
    B,
    num_iters: int,
    lam: float = 0.0,
    mesh: Mesh | None = None,
) -> jax.Array:
    """Solve min_W ||A W − B||² + lam ||W||² with d-sharded ring BCD.

    A: (n, d), B: (n, k) — host or device arrays; columns of A and B are
    padded to multiples of the mesh size and sharded across it. Returns the
    full (d, k) solution (model-sharded on device; slice is unpadded).
    """
    from keystone_tpu.utils.mesh import default_mesh

    mesh = mesh or default_mesh()
    axis = mesh.axis_names[0]
    nshards = mesh.shape[axis]
    dtype = jnp.dtype(config.default_dtype)
    A = np.asarray(A, dtype=dtype)
    B = np.asarray(B, dtype=dtype)
    n, d = A.shape
    k = B.shape[1]
    pad_d = (-d) % nshards
    pad_k = (-k) % nshards
    if pad_d and lam <= 0.0:
        raise ValueError(
            f"d={d} is not a multiple of the {nshards}-chip mesh; the "
            "zero-padded feature columns make the per-chip gram singular — "
            "pass lam > 0 or pad the features yourself"
        )
    if pad_d:
        A = np.pad(A, ((0, 0), (0, pad_d)))
    if pad_k:
        B = np.pad(B, ((0, 0), (0, pad_k)))
    A_dev = jax.device_put(A, NamedSharding(mesh, P(None, axis)))
    B_dev = jax.device_put(B, NamedSharding(mesh, P(None, axis)))
    solve = _ring_solve_fn(mesh, axis, _precision())
    W = solve(
        A_dev,
        B_dev,
        jnp.asarray(lam, dtype=dtype),
        jnp.int32(num_iters * nshards),
    )
    return W[:d, :k]
