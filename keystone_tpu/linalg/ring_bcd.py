"""Ring-parallel block coordinate descent — d-axis model parallelism.

The reference has no sequence/attention machinery; SURVEY.md §5 identifies
the feature dimension (64k–256k, ≫ single-node memory) as this workload's
"long axis" and prescribes exactly this design: shard the d-axis across
the ICI mesh into per-chip feature blocks and pass residuals around a ring
— the collective-matmul / ring-attention scheduling idea applied to
blocked least squares (PAPERS.md arXiv:2112.09017 family).

Layout and schedule:

- chip c owns feature block A_c (n × d/P columns, rows replicated) and its
  weights W_c — the model axis is sharded, nothing is all-gathered;
- B's columns split into P chunks; chunk c starts on chip c as its
  residual R_c (different B columns are independent least-squares
  problems sharing A);
- each step, every chip runs one BCD block update of ITS block against the
  residual chunk it currently holds, then `ppermute`s the chunk to the
  next chip. After P steps each chunk has visited every block once (one
  full Gauss-Seidel sweep, block order rotated per chunk — an equally
  valid sweep order), and all P chips were busy every step.

Per-chip per-epoch communication is exactly n·k/P · P = n·k values over
ICI neighbor links — no psum trees, no gathers; per-chip grams are local
(columns live on one chip) and their Cholesky factors are computed once.
Compare the data-parallel path (bcd.py): that shards n and psums b×b
grams; this shards d and rings n×k/P residuals — the right trade when d
dwarfs n·k, i.e. the reference's high-dimensional featurized regime.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from keystone_tpu.utils.compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from keystone_tpu.config import config
from keystone_tpu.linalg.bcd import _batched_spd_inv
from keystone_tpu.linalg.row_matrix import _precision, solver_matmul, storage_dtype


@lru_cache(maxsize=None)
def _ring_solve_fn(mesh: Mesh, model_axis: str, data_axis, precision):
    """2-D-capable ring solver: columns sharded over ``model_axis`` (the
    ring); rows optionally sharded over ``data_axis`` (grams/gradients then
    psum across it — the dp×mp composition)."""
    nshards = mesh.shape[model_axis]

    def maybe_psum(x):
        return lax.psum(x, data_axis) if data_axis is not None else x

    # num_steps is a dynamic operand (fori_loop takes traced bounds, lowering
    # to while_loop), so different iteration counts share one compilation.
    def local(a_loc, b_chunk, lam, num_steps):
        # a_loc: (n_loc, d_loc) — this chip's (row shard ×) feature block
        # b_chunk: (n_loc, kc) — its shard of the chunk starting on this ring slot
        d_loc = a_loc.shape[1]
        kc = b_chunk.shape[1]
        gram = maybe_psum(solver_matmul(a_loc.T, a_loc, precision))
        # Explicit ridge inverse ONCE per chip, outside the ring loop: the
        # per-step solve becomes one MXU gemm instead of a sequential
        # triangular solve (same rework + self-correction argument as
        # bcd._local_gram_inv). The trace-scaled jitter floors cond even
        # at the lam=0.0 default — an explicit f32 inverse of a singular
        # gram would otherwise poison every ring step (the kernel_ridge
        # NOTE's divergence mode); the shift it introduces is ~1e-6
        # relative, inside solver tolerance.
        eye = jnp.eye(d_loc, dtype=gram.dtype)
        jitter = 1e-6 * (jnp.trace(gram) / d_loc)
        # Shared chunked-RHS inverse (bcd._batched_spd_inv): the naive
        # full-identity trsm pair blows XLA:TPU's unrolled-panel temp
        # budget at large d_loc.
        inv = _batched_spd_inv(gram + (lam + jitter) * eye)
        idx = lax.axis_index(model_axis)
        # Solver state in the accumulation dtype even when A stores bf16.
        w0 = jnp.zeros((d_loc, nshards * kc), dtype=b_chunk.dtype)

        def step(s, carry):
            r, w = carry
            # Which chunk this ring slot holds at step s (chunks move +1/step).
            j = jnp.mod(idx - s, nshards)
            w_old = lax.dynamic_slice(w, (0, j * kc), (d_loc, kc))
            r_plus = r + solver_matmul(a_loc, w_old, precision)
            rhs = maybe_psum(solver_matmul(a_loc.T, r_plus, precision))
            w_new = solver_matmul(inv, rhs, precision)
            r_new = r_plus - solver_matmul(a_loc, w_new, precision)
            w = lax.dynamic_update_slice(w, w_new, (0, j * kc))
            r_next = lax.ppermute(
                r_new,
                model_axis,
                [(p, (p + 1) % nshards) for p in range(nshards)],
            )
            return r_next, w

        _r, w = lax.fori_loop(0, num_steps, step, (b_chunk, w0))
        return w  # (d_loc, k) — concatenates to the full W over model axis

    sm = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(data_axis, model_axis), P(data_axis, model_axis), P(), P()),
        out_specs=P(model_axis, None),
        check_vma=False,
    )
    return jax.jit(sm)


def block_coordinate_descent_ring(
    A,
    B,
    num_iters: int,
    lam: float = 0.0,
    mesh: Mesh | None = None,
) -> jax.Array:
    """Solve min_W ||A W − B||² + lam ||W||² with d-sharded ring BCD.

    A: (n, d), B: (n, k) — host or device arrays; columns of A and B are
    padded to multiples of the ring size and sharded across it. Returns
    the full (d, k) solution (model-sharded on device; slice is unpadded).

    Mesh shapes: a 1-D mesh rings over its only axis with rows replicated;
    a 2-D mesh named (data_axis, model_axis) additionally shards rows over
    the data axis and psums grams/gradients across it — data and model
    parallelism composed, the full pod-slice layout.
    """
    from keystone_tpu.utils.mesh import default_mesh

    mesh = mesh or default_mesh()
    if len(mesh.axis_names) == 1:
        axis = mesh.axis_names[0]
        data_axis = None
        row_shards = 1
    else:
        data_axis, axis = mesh.axis_names[:2]
        row_shards = mesh.shape[data_axis]
    nshards = mesh.shape[axis]
    dtype = jnp.dtype(config.default_dtype)
    A = np.asarray(A, dtype=storage_dtype())  # bf16 in throughput mode
    B = np.asarray(B, dtype=dtype)
    n, d = A.shape
    k = B.shape[1]
    pad_d = (-d) % nshards
    pad_k = (-k) % nshards
    pad_n = (-n) % row_shards
    if pad_d and lam <= 0.0:
        raise ValueError(
            f"d={d} is not a multiple of the {nshards}-chip ring; the "
            "zero-padded feature columns make the per-chip gram singular — "
            "pass lam > 0 or pad the features yourself"
        )
    if pad_d:
        A = np.pad(A, ((0, 0), (0, pad_d)))
    if pad_k:
        B = np.pad(B, ((0, 0), (0, pad_k)))
    if pad_n:
        A = np.pad(A, ((0, pad_n), (0, 0)))
        B = np.pad(B, ((0, pad_n), (0, 0)))
    A_dev = jax.device_put(A, NamedSharding(mesh, P(data_axis, axis)))
    B_dev = jax.device_put(B, NamedSharding(mesh, P(data_axis, axis)))
    solve = _ring_solve_fn(mesh, axis, data_axis, _precision())
    W = solve(
        A_dev,
        B_dev,
        jnp.asarray(lam, dtype=dtype),
        jnp.int32(num_iters * nshards),
    )
    return W[:d, :k]
