"""Communication-avoiding tall-skinny QR (TSQR) on the device mesh.

Ref: ml-matrix `TSQR.qrR` / `TSQR.solveLeastSquares` — local QR per
partition, tree-reduce of R factors via `treeAggregate` (SURVEY.md §2.2,
§3.2) [unverified]. TPU lowering (PAPERS.md arXiv:2112.09017): each shard
QRs its local block, `all_gather`s the small R factors over ICI, and every
chip reduces the stacked Rs with one more QR — replicated, so no driver hop.

The torus all-gather is the compiler-scheduled analog of the reference's
log-depth aggregation tree.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax import lax

from keystone_tpu.utils.compat import shard_map
from jax.scipy.linalg import solve_triangular
from jax.sharding import Mesh, PartitionSpec as P

from keystone_tpu.config import config
from keystone_tpu.linalg.row_matrix import RowMatrix


@lru_cache(maxsize=None)
def _tsqr_r_fn(mesh: Mesh, axis: str):
    # check_vma=False: the all_gather makes the value replicated, but the
    # static replication checker can't see through the second QR.
    @jax.jit
    @partial(
        shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(), check_vma=False
    )
    def tsqr_r(a):  # a: (m_local, d)
        d = a.shape[1]
        r = jnp.linalg.qr(a, mode="r")  # (min(m_local, d), d)
        if r.shape[0] < d:  # static shapes: pad so all_gather stacks cleanly
            r = jnp.pad(r, ((0, d - r.shape[0]), (0, 0)))
        rs = lax.all_gather(r, axis)  # (shards, d, d)
        return jnp.linalg.qr(rs.reshape(-1, d), mode="r")  # (d, d)

    return tsqr_r


def tsqr_r(A: RowMatrix) -> jax.Array:
    """The R factor of A's QR decomposition, replicated. R is unique up to
    row signs; RᵀR == AᵀA regardless."""
    return _tsqr_r_fn(A.mesh, config.data_axis)(A.data)


@partial(jax.jit, static_argnames=("d",))
def _solve_from_augmented_r(r_aug, d: int, lam):
    """Given R of [A | B] and ridge lam, solve min ||AW-B||² + lam||W||².

    R11 = R[:d, :d], R12 = R[:d, d:]. Ridge: stack sqrt(lam)·I under R11
    (equivalent to appending those rows to A) and re-QR the small system.
    """
    k = r_aug.shape[1] - d
    dtype = r_aug.dtype
    sq = jnp.sqrt(lam)
    top = r_aug[:d]  # [R11 | R12]
    bot = jnp.concatenate(
        [sq * jnp.eye(d, dtype=dtype), jnp.zeros((d, k), dtype=dtype)], axis=1
    )
    rr = jnp.linalg.qr(jnp.concatenate([top, bot], axis=0), mode="r")
    return solve_triangular(rr[:d, :d], rr[:d, d : d + k])


def solve_least_squares_tsqr(
    A: RowMatrix, B: RowMatrix, lam: float = 0.0
) -> jax.Array:
    """Least squares through TSQR of the augmented [A | B] — numerically
    stabler than normal equations (condition κ instead of κ²), the same
    reason the reference offers TSQR next to NormalEquations."""
    A._check_aligned(B)
    d = A.data.shape[1]
    aug = RowMatrix(
        jnp.concatenate([A.data, B.data.astype(A.data.dtype)], axis=1),
        A.n,
        A.mesh,
    )
    r_aug = tsqr_r(aug)
    return _solve_from_augmented_r(
        r_aug, d, jnp.asarray(lam, dtype=r_aug.dtype)
    )
