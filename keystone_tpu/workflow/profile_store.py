"""Measured-profile store — the persistence layer that closes the
cost-model loop.

``Pipeline.fit(profile=True)`` measures what every node of a pipeline
actually cost (utils/metrics.py ResourceProfile: wall, output nbytes,
HBM delta, per prefix digest); this module persists those rows to a
versioned JSON artifact keyed by the pipeline's content-stable
structural digest, and the optimizer rules (workflow/rules.py) load them
back on the NEXT optimization of the same pipeline — measured costs
replacing sample-run extrapolation, the profile-once-optimize-forever
workflow ("A Learned Performance Model for TPUs", arXiv:2008.01040,
re-grounded in measurements instead of a learned surrogate).

Store contract (the bench_watch band rule, applied at load):

- an entry records the ``runtime_fingerprint()`` backend subset
  (backend / device kind / device count); loading under an incompatible
  runtime raises the typed ``ProfileFingerprintError`` — a CPU profile
  must never size a TPU plan;
- the payload carries a blake2b content digest; a corrupt or tampered
  entry is SKIPPED with a warning (``load_profile`` returns None), never
  crashes an optimizer pass;
- unknown schema versions are skipped the same way (forward compat).

Layout: one ``<pipeline_digest[:40]>.json`` per pipeline under the
directory named by ``KEYSTONE_PROFILE_STORE`` / ``config.profile_store``
(``config.resolved_profile_store``), written atomically.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import tempfile
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

logger = logging.getLogger("keystone_tpu")

#: Store schema version; bump on any incompatible shape change.
STORE_VERSION = 1

#: Fingerprint keys that must agree between the recording and consuming
#: runtimes (None on either side is a wildcard — the bench_watch rule).
_FINGERPRINT_KEYS = ("backend", "device_kind", "device_count")

#: Parsed-entry memo keyed by (path, mtime_ns, size): optimizer batches
#: re-apply rules to fixed point, and each apply must not re-read and
#: re-parse the same JSON. Bounded FIFO (dict keeps insertion order);
#: lock-guarded — parallel-walk estimator sub-fits re-enter the
#: optimizer from pool threads, and an unguarded evict can double-pop.
_LOAD_MEMO_CAP = 64
_load_memo: Dict[tuple, "StoredProfile"] = {}
_load_memo_lock = threading.Lock()


class ProfileStoreError(RuntimeError):
    """Base class for profile-store failures."""


class ProfileFingerprintError(ProfileStoreError):
    """A stored profile was recorded under an incompatible runtime
    (different backend / device kind / device count) — refused at load,
    the bench_watch fingerprint-band rule."""


@dataclass
class StoredProfile:
    """One loaded store entry: per-node measured aggregates keyed by the
    node's content-stable prefix digest, plus provenance."""

    pipeline_digest: str
    fingerprint: Dict[str, Any]
    #: digest -> {label, calls, wall_ns, out_bytes, out_rows, queue_wait_ns}
    digests: Dict[str, Dict[str, Any]]
    #: label-keyed attribution rows (ResourceProfile.rows shape) — the
    #: human/explainability side; rules consume ``digests``.
    rows: List[Dict[str, Any]] = field(default_factory=list)
    path: Optional[str] = None

    def node(self, digest: Optional[str]) -> Optional[Dict[str, Any]]:
        if digest is None:
            return None
        return self.digests.get(digest)


def pipeline_profile_digest(graph, sink) -> Optional[str]:
    """THE store key for a pipeline: content-stable structural digest of
    its sink with the free input tokenized (a profile describes the
    pipeline TEMPLATE plus its bound training data, not one serve
    request). One definition shared by the save side (Pipeline.fit), the
    consume side (the optimizer rules), and the lint side (KG203), so
    the key can never drift between them. None when any operator in the
    prefix lacks content identity — such pipelines cannot be stored."""
    from keystone_tpu.workflow.graph import structural_digest

    return structural_digest(graph, sink, source_token="profile-input")


def _payload_digest(digests: Dict[str, Any], rows: List[dict]) -> str:
    blob = json.dumps([digests, rows], sort_keys=True).encode()
    return hashlib.blake2b(blob, digest_size=16).hexdigest()


def _entry_path(store_dir: str, pipeline_digest: str) -> str:
    return os.path.join(store_dir, pipeline_digest[:40] + ".json")


def _fingerprint_compatible(a: Dict[str, Any], b: Dict[str, Any]) -> bool:
    for k in _FINGERPRINT_KEYS:
        if a.get(k) is not None and b.get(k) is not None and a[k] != b[k]:
            return False
    return True


def store_dir_or_none(store_dir: Optional[str] = None) -> Optional[str]:
    """The effective store directory (explicit arg > env > config)."""
    if store_dir is not None:
        return store_dir or None
    from keystone_tpu.config import resolved_profile_store

    return resolved_profile_store()


def save_profile(
    pipeline_digest: str,
    digests: Dict[str, Dict[str, Any]],
    rows: List[Dict[str, Any]],
    store_dir: Optional[str] = None,
    fingerprint: Optional[Dict[str, Any]] = None,
) -> str:
    """Persist one pipeline's measured profile (atomic write). Returns
    the entry path. Raises ``ProfileStoreError`` when no store directory
    is configured or the directory cannot be created."""
    root = store_dir_or_none(store_dir)
    if not root:
        raise ProfileStoreError(
            "no profile store configured (set KEYSTONE_PROFILE_STORE or "
            "config.profile_store)"
        )
    if fingerprint is None:
        from keystone_tpu.utils.metrics import runtime_fingerprint

        fingerprint = runtime_fingerprint()
    try:
        os.makedirs(root, exist_ok=True)
    except OSError as e:
        raise ProfileStoreError(f"cannot create profile store {root}: {e}")
    doc = {
        "version": STORE_VERSION,
        "pipeline_digest": pipeline_digest,
        "fingerprint": {k: fingerprint.get(k) for k in _FINGERPRINT_KEYS},
        "digests": digests,
        "rows": rows,
        "payload_digest": _payload_digest(digests, rows),
    }
    path = _entry_path(root, pipeline_digest)
    # Unique tmp name (not a fixed path+".tmp"): a fit(profile=True)
    # auto-save racing a forced-profile apply save of the same pipeline
    # must not interleave bytes into one tmp file, and a failed write
    # must not litter a stale tmp (the serialization.py save_artifact
    # rule).
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".", suffix=".tmp", dir=root
    )
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def has_profile(
    pipeline_digest: Optional[str], store_dir: Optional[str] = None
) -> bool:
    """Cheap existence probe (no parse, no fingerprint check) — the lint
    layer's KG203 question: 'does a stored profile exist at all?'."""
    root = store_dir_or_none(store_dir)
    if not root or not pipeline_digest:
        return False
    return os.path.exists(_entry_path(root, pipeline_digest))


def load_profile(
    pipeline_digest: Optional[str],
    store_dir: Optional[str] = None,
    fingerprint: Optional[Dict[str, Any]] = None,
) -> Optional[StoredProfile]:
    """Load the store entry for ``pipeline_digest``.

    Returns None when the store is unconfigured, the entry is absent, or
    the entry is corrupt/tampered/unknown-version (warned, skipped — an
    optimizer pass must degrade to model-only, not crash). An entry from
    the same backend/device kind whose only disagreement is the mesh
    width MIGRATES onto the live width (elastic mesh, default on: the
    per-shard plan rows are re-scaled through ``utils.mesh.reshard_state``
    and the migrated entry is persisted back, counted — never silent).
    Raises ``ProfileFingerprintError`` when the entry exists and parses
    but was recorded under an incompatible runtime (or elastic migration
    is pinned off): that is a refusal the caller must hear about, not
    silently equivalent to 'no profile'.
    """
    root = store_dir_or_none(store_dir)
    if not root or not pipeline_digest:
        return None
    path = _entry_path(root, pipeline_digest)
    try:
        st = os.stat(path)
    except OSError:
        return None
    memo_key = (path, st.st_mtime_ns, st.st_size)
    with _load_memo_lock:
        entry = _load_memo.get(memo_key)
    if entry is None:
        entry = _parse_entry(path, pipeline_digest)
        if entry is None:
            return None
        with _load_memo_lock:
            while len(_load_memo) >= _LOAD_MEMO_CAP:
                _load_memo.pop(next(iter(_load_memo)))
            _load_memo[memo_key] = entry
    if fingerprint is None:
        from keystone_tpu.utils.metrics import runtime_fingerprint

        fingerprint = runtime_fingerprint()
    if not _fingerprint_compatible(entry.fingerprint, fingerprint):
        migrated = _elastic_profile_migration(entry, fingerprint, root)
        if migrated is not None:
            return migrated
        raise ProfileFingerprintError(
            f"stored profile {path} was recorded under "
            f"{entry.fingerprint}, incompatible with this runtime "
            f"{ {k: fingerprint.get(k) for k in _FINGERPRINT_KEYS} }; "
            "re-profile with Pipeline.fit(profile=True) on this backend "
            "(a mesh-width-only mismatch migrates automatically via "
            "utils.mesh.reshard_state unless KEYSTONE_ELASTIC_MESH=0)"
        )
    return entry


def _reshard_profile_doc(doc: Dict[str, Any], layout) -> Dict[str, Any]:
    """Elastic-mesh adapter for store entries: the measured wall/bytes
    aggregates describe the pipeline, not the mesh — only the per-shard
    plan provenance (``data_shards`` on digest aggregates and attribution
    rows) and the fingerprint's ``device_count`` follow the width. Rows
    recorded at the OLD width re-scale onto ``layout``; the payload
    digest is recomputed so the migrated entry passes the integrity
    check. Entries with no recorded width refuse typed."""
    from keystone_tpu.utils.mesh import reshard_refused

    fp = dict(doc.get("fingerprint") or {})
    old = fp.get("device_count")
    new = int(layout.num_shards)
    if not isinstance(old, int) or old <= 0:
        raise reshard_refused(
            "profile store",
            "entry has no recorded mesh width to migrate from",
        )
    digests = {k: dict(v) for k, v in (doc.get("digests") or {}).items()}
    rows = [dict(r) for r in (doc.get("rows") or [])]
    for agg in digests.values():
        if agg.get("data_shards") == old:
            agg["data_shards"] = new
    for row in rows:
        if row.get("data_shards") == old:
            row["data_shards"] = new
    fp["device_count"] = new
    out = dict(doc, fingerprint=fp, digests=digests, rows=rows)
    out["payload_digest"] = _payload_digest(digests, rows)
    return out


def _register_profile_adapter() -> None:
    from keystone_tpu.utils.mesh import register_reshard_adapter

    register_reshard_adapter("profile", _reshard_profile_doc)


_register_profile_adapter()


def _elastic_profile_migration(
    entry: StoredProfile, fingerprint: Dict[str, Any], root: str
) -> Optional[StoredProfile]:
    """Migrate ``entry`` onto the live mesh width when that is its ONLY
    incompatibility, elastic mesh is on, and the lookup fingerprint IS
    the live runtime (a synthetic fingerprint is a question about another
    machine, not a resume — it keeps the typed refusal). Persists the
    migrated entry back to the store (best-effort: a read-only store
    still serves the migrated copy this load). Returns None when the
    mismatch is not elastically recoverable."""
    from keystone_tpu.config import config

    if not config.elastic_mesh:
        return None
    saved_dc = entry.fingerprint.get("device_count")
    want_dc = fingerprint.get("device_count")
    if saved_dc is None or want_dc is None or saved_dc == want_dc:
        return None
    others_saved = {
        k: entry.fingerprint.get(k)
        for k in _FINGERPRINT_KEYS if k != "device_count"
    }
    others_want = {
        k: fingerprint.get(k)
        for k in _FINGERPRINT_KEYS if k != "device_count"
    }
    if not _fingerprint_compatible(others_saved, others_want):
        return None
    from keystone_tpu.utils.mesh import SpecLayout

    try:
        layout = SpecLayout.for_mesh()
    except Exception:  # lint: broad-ok deviceless backend: no live mesh to migrate onto
        return None
    if int(want_dc) != int(layout.num_shards):
        return None
    from keystone_tpu.utils.mesh import reshard_state

    doc = {
        "version": STORE_VERSION,
        "pipeline_digest": entry.pipeline_digest,
        "fingerprint": dict(entry.fingerprint),
        "digests": entry.digests,
        "rows": entry.rows,
    }
    migrated = reshard_state(doc, new_layout=layout, family="profile")
    try:
        save_profile(
            entry.pipeline_digest, migrated["digests"], migrated["rows"],
            store_dir=root, fingerprint=migrated["fingerprint"],
        )
    except (ProfileStoreError, OSError) as e:
        logger.warning(
            "profile store: migrated entry for %s could not be persisted "
            "(%s); serving the in-memory migration", entry.pipeline_digest, e,
        )
    return StoredProfile(
        pipeline_digest=entry.pipeline_digest,
        fingerprint=migrated["fingerprint"],
        digests=migrated["digests"],
        rows=migrated["rows"],
        path=entry.path,
    )


def _parse_entry(path: str, pipeline_digest: str) -> Optional[StoredProfile]:
    """Parse + integrity-check one entry file; None (with a warning) on
    any corruption — the skip-don't-crash half of the store contract."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
        logger.warning(
            "profile store: skipping unreadable entry %s (%s)", path, e
        )
        return None
    if not isinstance(doc, dict) or doc.get("version") != STORE_VERSION:
        logger.warning(
            "profile store: skipping %s (unknown schema version %r)",
            path, doc.get("version") if isinstance(doc, dict) else None,
        )
        return None
    digests = doc.get("digests")
    rows = doc.get("rows")
    if not isinstance(digests, dict) or not isinstance(rows, list):
        logger.warning(
            "profile store: skipping malformed entry %s", path
        )
        return None
    if doc.get("payload_digest") != _payload_digest(digests, rows):
        logger.warning(
            "profile store: skipping %s — payload digest mismatch "
            "(tampered or truncated entry)", path,
        )
        return None
    if doc.get("pipeline_digest") != pipeline_digest:
        logger.warning(
            "profile store: skipping %s — entry names pipeline %r, "
            "looked up %r", path, doc.get("pipeline_digest"),
            pipeline_digest,
        )
        return None
    return StoredProfile(
        pipeline_digest=pipeline_digest,
        fingerprint=doc.get("fingerprint") or {},
        digests=digests,
        rows=rows,
        path=path,
    )


def lookup_measured(
    pipeline_digest: Optional[str], store_dir: Optional[str] = None
) -> Optional[StoredProfile]:
    """The optimizer rules' entry point: the stored profile for a
    pipeline digest, or None when nothing usable is stored. A fingerprint
    refusal is logged and treated as no-profile here — the rules fall
    back to model/sample costing; callers who must surface the refusal
    (tests, tools) use ``load_profile`` directly. An entry with ZERO
    digest rows is likewise no-profile: it carries no per-node
    information, and letting it shadow the sampled path would turn
    auto-cache into a silent no-op for that pipeline."""
    if pipeline_digest is None:
        return None
    try:
        entry = load_profile(pipeline_digest, store_dir=store_dir)
    except ProfileFingerprintError as e:
        logger.warning("profile store: %s", e)
        return None
    if entry is not None and not entry.digests:
        logger.warning(
            "profile store: entry %s has no per-node rows; falling back "
            "to sampled costing", entry.path,
        )
        return None
    return entry


@dataclass
class FitProfile:
    """The handle ``Pipeline.fit(profile=True)`` attaches to the fitted
    pipeline: this fit's own attribution delta (not the process-wide
    registry accumulation), ready to inspect or persist."""

    pipeline_digest: Optional[str]
    fingerprint: Dict[str, Any]
    rows: List[Dict[str, Any]]
    digests: Dict[str, Dict[str, Any]]
    #: Store path when the fit auto-saved (store configured), else None.
    saved_to: Optional[str] = None

    def table(self) -> str:
        from keystone_tpu.utils.metrics import render_attribution_table

        return render_attribution_table(self.rows)

    def save(self, store_dir: Optional[str] = None) -> str:
        """Persist this fit's measurements (see ``save_profile``).
        Raises ``ProfileStoreError`` when the pipeline has no content
        identity, no store is configured, or this fit recorded no
        executions (a warm-session delta must not clobber a good entry
        with zero rows)."""
        if self.pipeline_digest is None:
            raise ProfileStoreError(
                "pipeline has no content-stable digest; its profile "
                "cannot be stored (an id-keyed operator is in the graph)"
            )
        if not self.digests:
            raise ProfileStoreError(
                "this fit recorded no executions (every node came from "
                "the session cache); nothing to store — an empty entry "
                "would clobber the measurements a cold profiled fit saved"
            )
        self.saved_to = save_profile(
            self.pipeline_digest, self.digests, self.rows,
            store_dir=store_dir, fingerprint=self.fingerprint,
        )
        return self.saved_to
