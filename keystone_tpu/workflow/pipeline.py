"""Pipeline API: Transformer / Estimator / LabelEstimator / Pipeline.

Ref: src/main/scala/workflow/{Pipeline,Transformer,Estimator,LabelEstimator,
PipelineDataset}.scala [unverified]. The algebra is preserved:

- ``Transformer`` — a pure per-datum (liftable to per-batch) function; itself
  composable like a one-node pipeline.
- ``Estimator.fit(data) -> Transformer``; ``with_data`` splices a lazy fit
  into a graph.
- ``pipeline.and_then(...)`` composes; ``Pipeline.gather([...])`` merges
  branches by feature concatenation.
- Applying a pipeline is lazy: you get a ``PipelineDataset`` handle; ``get()``
  optimizes the graph and executes it.

The execution difference from the reference: instead of staging RDD
transformations, contiguous jittable transformer chains are fused by the
optimizer into single XLA computations (see workflow/optimizer.py), and batch
values are (possibly sharded) device arrays.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from keystone_tpu.workflow.graph import (
    Graph,
    GraphId,
    NodeId,
    SourceId,
    fresh_source_id,
)
from keystone_tpu.workflow.operators import (
    DatasetOperator,
    DatumOperator,
    DelegatingOperator,
    EstimatorOperator,
    GatherOperator,
    Operator,
    TransformerOperator,
)


def _is_array(x: Any) -> bool:
    return isinstance(x, (jax.Array, np.ndarray))


# ---------------------------------------------------------------------------
# Transformer
# ---------------------------------------------------------------------------


class Transformer:
    """A pure function applied per-datum, lifted to batches.

    Subclasses override ``apply_batch`` (device code operating on a batch with
    a leading example axis — the common case, jitted and fused by the
    executor) or ``apply`` (per-datum host code; set ``jittable = False``).

    Ref: workflow/Transformer.scala — per-datum ``apply`` lifted to RDDs via
    mapPartitions [unverified]. Here the lift is vectorization: the batch IS
    the unit of execution, which is what the MXU wants.
    """

    jittable: bool = True

    # Output row i depends on input row i alone AND output rows == input
    # rows — the contract that makes bucket-padding sound (pad rows cannot
    # perturb real outputs, and slicing [:n] recovers exactly them). True
    # for the per-datum-lifted common case; transformers that couple rows
    # (batch statistics at apply time) or fan rows out (Windower,
    # CenterCornerPatcher) set False, and the bucketed serving path refuses
    # them with serving.RowDependenceError.
    row_independent: bool = True

    def apply(self, x: Any) -> Any:
        if _is_array(x) or jnp.isscalar(x):
            return self.batch_call(jnp.asarray(x)[None, ...])[0]
        raise NotImplementedError(
            f"{type(self).__name__} must override apply() for non-array data"
        )

    def apply_batch(self, X: Any) -> Any:
        # Host-side default: per-datum loop. Device transformers override.
        if type(self).apply is Transformer.apply:
            # Neither method overridden — fail clearly instead of letting the
            # two defaults recurse into each other.
            raise NotImplementedError(
                f"{type(self).__name__} must override apply_batch() or apply()"
            )
        return [self.apply(x) for x in X]

    # -- execution ---------------------------------------------------------

    def batch_call(self, X: Any) -> Any:
        """Apply to a batch, via the cached jitted function when possible.

        With ``config.serve_buckets`` set (env KEYSTONE_SERVE_BUCKETS),
        array batches are rounded up the bucket ladder, padded, run at the
        bucket shape, and sliced — the jit cache then only ever sees ladder
        shapes, so variable-size traffic stops recompiling once the ladder
        is warm. Empty ladder = per-shape jit, exactly as before.

        Under ``config.shard_data_batches``, a batch carrying (or owed)
        the mesh's data-parallel layout lowers the WHOLE chain once with
        explicit ``in_shardings``/``out_shardings`` (``mesh.SpecLayout``)
        instead of inheriting whatever placement the input happened to
        carry — and a non-divisible host batch is mask-padded onto the
        mesh and trimmed, never silently run single-device.
        """
        if self.jittable and _is_array(X):
            from keystone_tpu.config import config

            if config.serve_buckets:
                from keystone_tpu.workflow.serving import bucketed_call

                return bucketed_call(self, X)
            if config.shard_data_batches:
                from keystone_tpu.utils.mesh import batch_layout

                layout = batch_layout(X)
                if layout is not None:
                    return self._sharded_call(X, layout)
            return self._jitted()(X)
        return self.apply_batch(X)

    def _jitted(self) -> Callable:
        fn = getattr(self, "_jit_cache", None)
        if fn is None:
            fn = jax.jit(self.apply_batch)
            object.__setattr__(self, "_jit_cache", fn)
        return fn

    def apply_sharded(self, X, layout):
        """The chain body the sharded lowering traces — ``apply_batch``
        unless a transformer needs the mesh layout to pick a sharded
        kernel strategy (``FisherVector``'s Pallas backend wraps its
        kernel in ``shard_map`` on real TPU meshes; everywhere else the
        plain body partitions under GSPMD bit-identically)."""
        return self.apply_batch(X)

    #: Does this chain run a Pallas kernel? Drives the
    #: ``pallas_sharded_calls`` evidence counter on the sharded path.
    uses_pallas: bool = False

    def _jitted_sharded(self, layout, donate: bool = False) -> Callable:
        """The chain lowered ONCE per mesh layout with the SpecLayout
        convention's explicit shardings (rows sharded in, rows sharded
        out) — memoized per (transformer, layout, donate) like
        ``_jitted``. The donated variant aliases the staged input buffer
        into the chain's output (``SpecLayout.jit`` donation)."""
        cache = getattr(self, "_shard_jit_cache", None)
        if cache is None:
            cache = {}
            object.__setattr__(self, "_shard_jit_cache", cache)
        key = (layout, donate)
        fn = cache.get(key)
        if fn is None:
            body = lambda X: self.apply_sharded(X, layout)  # noqa: E731
            fn = cache[key] = layout.jit(
                body, donate_argnums=(0,) if donate else ()
            )
        return fn

    def _donation_eligible(self, X, layout) -> bool:
        """Can the staged input buffer alias into this chain's output?
        XLA matches donated buffers to outputs by aval (shape + dtype);
        a shrinking/growing chain has no match, so donating there would
        be a per-compile warning and a no-op — refused up front (and
        counted by the caller). Shape-only: one ``eval_shape`` per
        (shape, dtype, layout), memoized beside the jit cache."""
        from keystone_tpu.config import config

        if not config.donate_buffers:
            return False
        cache = getattr(self, "_donate_ok_cache", None)
        if cache is None:
            cache = {}
            object.__setattr__(self, "_donate_ok_cache", cache)
        key = (tuple(X.shape), str(X.dtype), layout)
        ok = cache.get(key)
        if ok is None:
            try:
                spec = jax.ShapeDtypeStruct(X.shape, X.dtype)
                out = jax.eval_shape(
                    lambda a: self.apply_sharded(a, layout), spec
                )
                leaves = jax.tree_util.tree_leaves(out)
                ok = any(
                    getattr(leaf, "shape", None) == spec.shape
                    and getattr(leaf, "dtype", None) == spec.dtype
                    for leaf in leaves
                )
            except Exception:  # lint: broad-ok abstract eval is best-effort; anything it can't trace just keeps the undonated lowering
                ok = False
            cache[key] = ok
        return ok

    def _staged_call(self, staged, layout):
        """Run the lowered chain on a staging buffer ``_sharded_call``
        itself created (``put``/``pad_put``) — the ONLY buffers the chain
        ever donates: they are provably dead here, unlike caller-owned
        arrays (anything placed upstream can be multi-consumer via
        gather/by-hash memo). Donation is refused — counted, never
        silent — when no output aval can alias the buffer."""
        from keystone_tpu.utils.metrics import sharding_counters

        donate = self._donation_eligible(staged, layout)
        if donate:
            sharding_counters.bump("buffers_donated")
        else:
            from keystone_tpu.config import config

            if config.donate_buffers:
                sharding_counters.bump("donation_refused")
        if self.uses_pallas:
            sharding_counters.bump("pallas_sharded_calls")
        return self._jitted_sharded(layout, donate=donate)(staged)

    def _sharded_call(self, X, layout):
        """Run the chain data-parallel under ``layout``: host batches are
        staged onto the mesh by this call (``put`` when divisible,
        mask-pad + trim otherwise) and the staging copy is donated into
        the lowered chain where an output can alias it; already-sharded
        device batches go straight through the explicitly-specced jit,
        never donated (the caller owns them). Row-independence makes pad
        rows inert, so outputs are bit-identical to the unsharded walk
        while the compute spans every shard. Row-coupled host chains
        (padding unsound, rows non-divisible) keep the propagation path,
        counted so the narrow run is visible."""
        from keystone_tpu.utils.metrics import sharding_counters

        n = int(X.shape[0])
        if isinstance(X, jax.Array):
            # Caller-owned placement (DatasetOperator / upstream chain):
            # only divisible row counts carry a layout here.
            sharding_counters.bump("sharded_chain_calls")
            if self.uses_pallas:
                sharding_counters.bump("pallas_sharded_calls")
            return self._jitted_sharded(layout)(X)
        if n % layout.num_shards == 0:
            sharding_counters.bump("sharded_chain_calls")
            return self._staged_call(layout.put(X), layout)
        if not self.row_independent:
            sharding_counters.bump("fallback_row_coupled")
            return self._jitted()(X)
        padded, n = layout.pad_put(X)
        sharding_counters.bump("sharded_chain_calls")
        sharding_counters.bump("batches_padded")
        sharding_counters.bump("pad_rows_added", padded.shape[0] - n)
        out = self._staged_call(padded, layout)
        return out[:n]

    def __getstate__(self):
        """Pickle without the per-instance jit caches (jitted callables are
        unpicklable; they rebuild lazily after load). Non-mutating, so
        persisting a live fitted transformer keeps its warm compilation."""
        state = dict(self.__dict__)
        state.pop("_jit_cache", None)
        state.pop("_shard_jit_cache", None)
        state.pop("_donate_ok_cache", None)  # keys carry the (live) mesh
        return state

    def signature(self) -> Any:
        """Key for structural prefix hashing; object identity by default.

        Deterministic nodes either override this to build a
        ``stable_signature`` from their current parameters, or (factory-
        created nodes) install one on ``self._sig`` — then two separately-
        constructed-but-identical nodes hash (and cache) alike, including
        across pipeline rebuilds in one session. The id fallback carries the
        UNSTABLE poison so it can never masquerade as persistable content.
        """
        sig = getattr(self, "_sig", None)
        if sig is not None:
            return sig
        from keystone_tpu.workflow.fingerprint import UNSTABLE

        return ("t-id", id(self), UNSTABLE)

    def stable_signature(self, *params) -> tuple:
        """Content-based signature: concrete class + constructor params.
        The class OBJECT is part of the key (not its name), so two distinct
        classes — even same-named locals — can never collide."""
        return (type(self),) + params

    def chain_hash(self, h_in: int) -> int:
        """Prefix hash of applying this transformer to an input with hash
        ``h_in``. FusedTransformer folds so fusion never changes hashes."""
        return hash((("transformer", self.signature()), (h_in,)))

    def chain_digest(self, d_in):
        """Content-stable fold mirroring ``chain_hash`` (None = unstable)."""
        if d_in is None:
            return None
        from keystone_tpu.workflow.fingerprint import digest_tree

        return digest_tree((("transformer", self.signature()), (d_in,)))

    # -- composition sugar -------------------------------------------------

    def to_pipeline(self) -> "Pipeline":
        source = fresh_source_id()
        graph, nid = Graph().add(TransformerOperator(self), [source])
        return Pipeline(graph, source, nid)

    def and_then(self, nxt, *fit_args) -> "Pipeline":
        return self.to_pipeline().and_then(nxt, *fit_args)

    def apply_pipeline(self, data) -> "PipelineDataset":
        return self.to_pipeline().apply(data)

    def __call__(self, data):
        """Eager convenience: transform a batch (or datum) directly."""
        if isinstance(data, PipelineDataset):
            return self.to_pipeline().apply(data)
        if _is_array(data):
            return self.batch_call(data)
        return self.apply_batch(data)


class FusedTransformer(Transformer):
    """A chain of jittable transformers compiled as one XLA computation.

    Produced by the optimizer's chain-fusion rule — the analog of the
    reference's lowering of a whole RDD stage, except the "stage" here is a
    single jitted program XLA can fuse end-to-end.
    """

    def __init__(self, stages: Sequence[Transformer]):
        flat: List[Transformer] = []
        for s in stages:
            if isinstance(s, FusedTransformer):
                flat.extend(s.stages)
            else:
                flat.append(s)
        self.stages = flat
        self.jittable = all(s.jittable for s in flat)
        self.row_independent = all(
            getattr(s, "row_independent", True) for s in flat
        )
        self.uses_pallas = any(
            getattr(s, "uses_pallas", False) for s in flat
        )

    def apply_batch(self, X):
        for s in self.stages:
            X = s.apply_batch(X)
        return X

    def apply_sharded(self, X, layout):
        # Thread the layout so stages with a sharded kernel strategy
        # (Pallas shard_map on TPU) see it inside the ONE fused lowering.
        for s in self.stages:
            X = s.apply_sharded(X, layout)
        return X

    def signature(self):
        return ("fused",) + tuple(s.signature() for s in self.stages)

    def chain_hash(self, h_in: int) -> int:
        # Fold stage-by-stage so the fused node's prefix hash equals the
        # unfused chain's — fusion is hash-invariant (fit_cache keeps hitting
        # whether or not a prefix got fused in a particular graph copy).
        for s in self.stages:
            h_in = s.chain_hash(h_in)
        return h_in

    def chain_digest(self, d_in):
        for s in self.stages:
            d_in = s.chain_digest(d_in)
        return d_in

    def __repr__(self):
        return "Fused(" + " | ".join(type(s).__name__ for s in self.stages) + ")"


# ---------------------------------------------------------------------------
# Estimators
# ---------------------------------------------------------------------------


def _splice_data(graph: Graph, data: Any):
    """Splice data (raw batch or lazy PipelineDataset) into ``graph``.

    Returns (graph, graph_id_producing_the_data).
    """
    if isinstance(data, PipelineDataset):
        return graph.union(data.graph), data.sink
    g, nid = graph.add(DatasetOperator(data), [])
    return g, nid


def _estimator_signature(est) -> tuple:
    """Content signature: class + public hyperparameter fields.

    Fields starting with ``_`` and names in ``_signature_exclude`` (mutable
    outputs like diagnostics set at fit time) are skipped. Values without a
    content identity poison the tree — the in-process cache still works via
    their ids, but nothing gets persisted under an unstable key.
    """
    from keystone_tpu.workflow.fingerprint import stable_value

    exclude = set(getattr(est, "_signature_exclude", ()))
    fields = {
        k: v
        for k, v in est.__dict__.items()
        if not k.startswith("_") and k not in exclude
    }
    return ("est", stable_value(type(est)), stable_value(fields))


class Estimator:
    """``fit(data) -> Transformer``. Ref: workflow/Estimator.scala [unverified]."""

    def signature(self) -> tuple:
        return _estimator_signature(self)

    def fit(self, data) -> Transformer:
        raise NotImplementedError

    def with_data(self, data) -> "Pipeline":
        """A pipeline that lazily fits this estimator on ``data`` and applies
        the fitted transformer to the pipeline input (Estimator.withData)."""
        graph = Graph()
        graph, data_id = _splice_data(graph, data)
        graph, est_id = graph.add(EstimatorOperator(self), [data_id])
        source = fresh_source_id()
        graph, out_id = graph.add(DelegatingOperator(), [est_id, source])
        return Pipeline(graph, source, out_id)

    def fit_pipeline(self, data) -> "Pipeline":
        """Eagerly fit and return the fitted transformer as a pipeline."""
        return self.fit(_force(data)).to_pipeline()


class LabelEstimator:
    """``fit(data, labels) -> Transformer``.

    Ref: workflow/LabelEstimator.scala [unverified].
    """

    def signature(self) -> tuple:
        return _estimator_signature(self)

    def fit(self, data, labels) -> Transformer:
        raise NotImplementedError

    def with_data(self, data, labels) -> "Pipeline":
        graph = Graph()
        graph, data_id = _splice_data(graph, data)
        graph, labels_id = _splice_data(graph, labels)
        graph, est_id = graph.add(EstimatorOperator(self), [data_id, labels_id])
        source = fresh_source_id()
        graph, out_id = graph.add(DelegatingOperator(), [est_id, source])
        return Pipeline(graph, source, out_id)


# ---------------------------------------------------------------------------
# Pipeline
# ---------------------------------------------------------------------------


class Pipeline:
    """A lazily-constructed dataflow from one source to one sink.

    Ref: workflow/Pipeline.scala [unverified].
    """

    def __init__(self, graph: Graph, source: SourceId, sink: GraphId):
        self.graph = graph
        self.source = source
        self.sink = sink

    # -- composition -------------------------------------------------------

    @staticmethod
    def _coerce(obj) -> "Pipeline":
        if isinstance(obj, Pipeline):
            return obj
        if isinstance(obj, Transformer):
            return obj.to_pipeline()
        raise TypeError(f"cannot compose with {type(obj).__name__}")

    def and_then(self, nxt, *fit_args) -> "Pipeline":
        """``pipeline.and_then(transformer_or_pipeline)``, or
        ``pipeline.and_then(estimator, data[, labels])`` which fits the
        estimator on this pipeline applied to ``data``."""
        if isinstance(nxt, (Estimator, LabelEstimator)):
            return self._and_then_fit(nxt, *fit_args)
        if fit_args:
            raise TypeError("fit data only valid when composing an estimator")
        nxt = Pipeline._coerce(nxt)
        merged = self.graph.union(nxt.graph)
        merged, (new_sink,) = merged.instantiate([nxt.sink], {nxt.source: self.sink})
        return Pipeline(merged.pruned([new_sink]), self.source, new_sink)

    def _and_then_fit(self, est, data, labels=None) -> "Pipeline":
        if labels is None and not isinstance(est, Estimator):
            raise TypeError("LabelEstimator requires labels")
        if labels is not None and not isinstance(est, LabelEstimator):
            raise TypeError("labels are only valid for a LabelEstimator")
        features = self.apply(data)
        if labels is None:
            tail = est.with_data(features)
        else:
            tail = est.with_data(features, labels)
        return self.and_then(tail)

    @staticmethod
    def gather(branches: Sequence[Union["Pipeline", Transformer]]) -> "Pipeline":
        """Merge parallel branches over the same input by concatenating their
        outputs on the feature axis (Pipeline.gather)."""
        branches = [Pipeline._coerce(b) for b in branches]
        source = fresh_source_id()
        merged = Graph()
        sinks: List[GraphId] = []
        for b in branches:
            merged = merged.union(b.graph)
            merged, (s,) = merged.instantiate([b.sink], {b.source: source})
            sinks.append(s)
        merged, out = merged.add(GatherOperator(), sinks)
        return Pipeline(merged.pruned([out]), source, out)

    def cache(self) -> "Pipeline":
        """Mark this pipeline's output for session-cache persistence (the
        explicit Cacher; the auto-cache rule inserts these automatically)."""
        from keystone_tpu.workflow.cache import CacheOperator

        graph, nid = self.graph.add(CacheOperator(), [self.sink])
        return Pipeline(graph, self.source, nid)

    # -- application -------------------------------------------------------

    def apply(self, data) -> "PipelineDataset":
        """Lazily apply to a batch (array / host sequence / PipelineDataset)."""
        if isinstance(data, PipelineDataset):
            merged = self.graph.union(data.graph)
            merged, (sink,) = merged.instantiate([self.sink], {self.source: data.sink})
            return PipelineDataset(merged.pruned([sink]), sink)
        graph, data_id = self.graph.add(DatasetOperator(data), [])
        graph, (sink,) = graph.instantiate([self.sink], {self.source: data_id})
        return PipelineDataset(graph.pruned([sink]), sink)

    def __call__(self, data) -> "PipelineDataset":
        return self.apply(data)

    def apply_batches(
        self, batches, prefetch_depth: Optional[int] = None, engine=None
    ):
        """Stream row batches through the pipeline with ingest overlap.

        ``batches`` is any iterable of ``(features, labels-or-None)`` pairs
        or bare feature batches (``loaders.stream.BatchIterator`` included).
        The upstream producer — CSV parse, JPEG decode, ``map_batches``
        featurization — runs on a background prefetch thread
        (``prefetch_depth`` deep, default ``config.prefetch_depth``; 0 =
        synchronous passthrough) while the fused transformer chain computes
        on the current batch, so host ingest leaves the device's critical
        path. Yields ``(transformed_batch, labels)`` in source order —
        the out-of-core scoring/featurization loop of the streamed
        pipelines.

        ``engine`` takes a ``workflow.serving.CompiledPipeline`` (e.g.
        ``self.compiled()``) and round-robins batches over its device
        replica pool instead of executing the graph per batch: up to
        in-flight × replicas device calls overlap with the prefetcher —
        the data-parallel offline apply. Requires the serve chain to be
        linear, jittable, and row-independent (``compiled()`` enforces
        this); outputs are the padded-bucket executables' and so can
        differ from graph execution in the last ulp across gemm shapes.
        """
        from contextlib import nullcontext

        from keystone_tpu.loaders.stream import prefetched
        from keystone_tpu.utils.metrics import active_tracer

        if engine is not None:
            yield from engine.apply_batches(batches, prefetch_depth)
            return

        tracer = active_tracer()  # once per stream, like the fault plan
        with prefetched(iter(batches), prefetch_depth) as src:
            for i, item in enumerate(src):
                if isinstance(item, tuple) and len(item) == 2:
                    X, y = item
                else:
                    X, y = item, None
                ctx = (
                    tracer.span(
                        "pipeline.apply_batch", "pipeline", batch=i,
                        rows=int(getattr(X, "shape", (len(X),))[0]),
                    )
                    if tracer is not None else nullcontext()
                )
                with ctx:
                    out = self.apply(X).get()
                yield out, y

    def apply_datum(self, datum) -> Any:
        """Apply to a single datum, eagerly (driver-local in the reference).

        Lifts the datum to a one-element batch so every transformer sees the
        leading example axis its ``apply_batch`` contract promises, then
        unwraps the result.
        """
        if _is_array(datum) or jnp.isscalar(datum):
            batch: Any = jnp.asarray(datum)[None, ...]
        else:
            batch = [datum]
        from keystone_tpu.workflow.executor import PipelineEnv

        ds = self.apply(batch)
        fitted_graph = PipelineEnv.get().executor.fit_estimators(ds.graph, ds.sink)
        out = PipelineEnv.get().execute(fitted_graph, ds.sink)
        return out[0]

    # -- fitting -----------------------------------------------------------

    def fit(self, profile: Optional[bool] = None) -> "Pipeline":
        """Force every estimator in the graph and return a transformer-only
        pipeline (the reference's fitted pipeline).

        ``profile=True`` forces per-node resource attribution for this
        fit (``utils.metrics.profile_scope``) regardless of
        KEYSTONE_PROFILE, and logs the attribution table — wall/device
        time, cost-model FLOPs/bytes, output nbytes, HBM delta per node
        — when the fit completes; the rows stay readable afterwards via
        ``utils.metrics.resource_profile`` and the registry/Prometheus
        surface, AND this fit's own delta is attached to the returned
        pipeline as ``fit_profile`` (a ``profile_store.FitProfile``) so
        callers can inspect or persist it without re-reading the
        process-wide registry. When a profile store is configured
        (``KEYSTONE_PROFILE_STORE`` / ``config.profile_store``) the
        measurements are saved there automatically, keyed by the
        pipeline's content digest — the profile-once half of the
        profile-guided optimizer loop. ``None`` (default) follows
        ``config.profile``. Profiling never changes fit OUTPUTS
        (bit-identical either way); it only measures.

        Ref: Pipeline.fit returning FittedPipeline [unverified].
        """
        from contextlib import nullcontext

        from keystone_tpu.utils.metrics import (
            active_tracer,
            profile_scope,
            resource_profile,
        )
        from keystone_tpu.workflow.analysis import enforce_lint
        from keystone_tpu.workflow.executor import PipelineEnv

        # Opt-in static gate (KEYSTONE_LINT=warn|error, default off):
        # graph hazards surface before any estimator runs.
        enforce_lint(self, "fit")
        # Cold path (once per fit): nullcontext keeps one call body; the
        # hot loops (solvers, prefetch, serving) branch explicitly instead.
        tracer = active_tracer()
        # mark() scopes the logged table to THIS fit's delta — the
        # process-wide profile keeps accumulating for registry readers.
        mark = resource_profile.mark() if profile else None
        dmark = resource_profile.mark_digests() if profile else None
        with (profile_scope() if profile else nullcontext()):
            with (tracer.span("pipeline.fit", "pipeline")
                  if tracer is not None else nullcontext()):
                graph = PipelineEnv.get().executor.fit_estimators(
                    self.graph, self.sink
                )
        fitted = Pipeline(graph, self.source, self.sink)
        if profile:
            import logging

            logging.getLogger("keystone_tpu").info(
                "fit attribution:\n%s", resource_profile.table(since=mark)
            )
            fitted.fit_profile = self._build_fit_profile(mark, dmark)
        # Prune to the subgraph feeding our sink.
        return fitted

    def _build_fit_profile(self, mark, dmark):
        """This fit's measurement handle (+ auto-save when a store is
        configured and the pipeline has content identity)."""
        from keystone_tpu.config import resolved_profile_store
        from keystone_tpu.utils.metrics import (
            resource_profile,
            runtime_fingerprint,
        )
        from keystone_tpu.workflow.profile_store import (
            FitProfile,
            ProfileStoreError,
            pipeline_profile_digest,
        )

        fp = FitProfile(
            pipeline_digest=pipeline_profile_digest(self.graph, self.sink),
            fingerprint=runtime_fingerprint(),
            rows=resource_profile.rows(since=mark),
            digests=resource_profile.digest_rows(since=dmark),
        )
        if (
            resolved_profile_store()
            and fp.pipeline_digest is not None
            and fp.digests
            # An empty delta (warm session: every node served from the
            # fit cache) must KEEP the existing store entry, not clobber
            # a good one with zero rows — the _profile_save_ctx rule.
        ):
            import logging

            try:
                fp.save()
                logging.getLogger("keystone_tpu").info(
                    "measured profile saved: %s", fp.saved_to
                )
            except ProfileStoreError as e:
                logging.getLogger("keystone_tpu").warning(
                    "measured profile not saved: %s", e
                )
        return fp

    def refit_stream(self, batches, every: int = 1, *, decay=None,
                     window=None, state=None, seed_state: bool = True):
        """Incrementally refit the HEAD of this pipeline on a labeled
        stream, freezing the fitted featurize stages.

        ``self`` must be the ``featurize.and_then(head_est, X0, y0)``
        shape (sink = a lazily-fit estimator application). The pipeline
        is fitted once up front — every featurize stage (including
        estimator-fitted ones like feature selectors) is FROZEN from
        then on. Each ``(X, y)`` batch from ``batches`` is featurized
        through the frozen prefix and folded into the head's retained
        accumulators (``head_est.partial_fit``); every ``every`` batches
        the head is re-solved cheaply and a refreshed fitted pipeline is
        yielded (prefix reused by reference — zero featurize refit cost).
        A final refresh is yielded for any tail batches.

        ``seed_state=True`` (default) folds the INITIAL training problem
        into a fresh state first, so the first tick re-solves
        initial ∪ streamed rather than the first batches alone; pass a
        ``state`` (or ``seed_state=False``) to refit on the stream only.

        A head WITHOUT ``partial_fit`` still works but silently costs a
        FULL head refit per cadence tick (all streamed features are
        buffered): the fallback is logged once and counted
        (``online.full_refits``), and the static linter flags the shape
        up front (KG105 via ``Pipeline.lint(refit=True)``). The
        ``decay``/``window`` forgetting modes need the online path and
        are REFUSED (not silently dropped) on the fallback.

        ``decay``/``window`` select the forgetting mode (see
        ``workflow/online.py``); ``state`` lets a caller hand in (and
        keep observing) the retained ``OnlineState``.

        Validation, the lint gate, the initial fit, and the seed all run
        EAGERLY (this returns an inner generator): a misconfiguration
        refuses HERE, not at whatever distant point first iterates.
        """
        import numpy as np

        from keystone_tpu.utils.metrics import online_counters
        from keystone_tpu.workflow.analysis import enforce_lint
        from keystone_tpu.workflow.online import (
            refit_head_estimator,
            split_fitted_head,
            supports_partial_fit,
        )

        # Opt-in static gate (KEYSTONE_LINT): KG105 names the
        # full-refit-per-tick hazard before any batch streams.
        enforce_lint(self, "refit_stream", refit=True)
        head_est = refit_head_estimator(self.graph, self.sink)
        if head_est is None:
            raise ValueError(
                "refit_stream needs a pipeline whose sink is a lazily-fit "
                "estimator head (featurize.and_then(est, data, labels))"
            )
        fitted = self.fit()
        prefix, _ = split_fitted_head(fitted)  # ticks rebuild the head
        online = supports_partial_fit(head_est)
        if not online:
            import logging

            if decay is not None or window is not None:
                # Refuse, never silently drop: the fallback's unweighted
                # full refit is NOT the forgetting semantics asked for.
                raise ValueError(
                    f"decay/window need a partial_fit head; "
                    f"{type(head_est).__name__} would full-refit with "
                    "every batch weighted equally"
                )
            if state is not None:
                # Same rule for a caller-supplied state: the fallback
                # never reads it, and silently excluding its retained
                # history from every tick is a wrong model, not a mode.
                raise ValueError(
                    f"a caller-supplied OnlineState needs a partial_fit "
                    f"head; {type(head_est).__name__}'s full-refit "
                    "fallback would never fold it"
                )
            logging.getLogger("keystone_tpu").warning(
                "refit_stream: %s lacks partial_fit — every cadence tick "
                "is a FULL head refit over the buffered stream (KG105)",
                type(head_est).__name__,
            )
        feats_all: List[Any] = []
        ys_all: List[Any] = []
        if state is None and seed_state:
            from keystone_tpu.workflow.online import head_fit_values

            feats0, labels0 = head_fit_values(self.graph, self.sink)
            if online:
                state = head_est.partial_fit(feats0, labels0,
                                             window=window)
            else:
                # The fallback honors the seed too: its full refits run
                # over initial ∪ streamed, same as the online path.
                feats_all.append(feats0)
                ys_all.append(labels0)

        def tick():
            from keystone_tpu.workflow.online import combine_head

            if online:
                new_head = head_est.solve_online(state)
            else:
                online_counters.bump("full_refits")
                new_head = head_est.fit(
                    np.concatenate([np.asarray(f) for f in feats_all]),
                    np.concatenate([np.asarray(y) for y in ys_all]),
                )
            return combine_head(prefix, new_head)

        def run():
            nonlocal state
            since = 0
            for item in batches:
                if not (isinstance(item, tuple) and len(item) == 2):
                    raise ValueError(
                        "refit_stream needs (features, labels) batches"
                    )
                X, y = item
                feats = prefix.apply(X).get() if prefix is not None else X
                if online:
                    state = head_est.partial_fit(
                        feats, y, state=state, decay=decay, window=window
                    )
                else:
                    # NOT batches_folded: nothing reached retained
                    # accumulators on this path — the buffer feeds the
                    # counted full refit. Copies, same as
                    # OnlineState.fold: a caller reusing one
                    # preallocated batch buffer must not overwrite what
                    # a later tick will refit on.
                    feats_all.append(np.array(feats, copy=True))
                    ys_all.append(np.array(y, copy=True))
                    online_counters.bump("batches_buffered")
                since += 1
                if since >= int(every):
                    since = 0
                    yield tick()
            if since > 0:
                yield tick()

        return run()

    def compiled(
        self, buckets=None, max_batch=None, donate=None, devices=None,
        inflight=None,
    ):
        """Fit (if needed) and lower to a shape-stable serving engine.

        Returns a ``workflow.serving.CompiledPipeline``: call ``warmup()``
        with the traffic's feature shape to AOT-compile the whole bucket
        ladder — on every device of the replica pool (``devices=``, env
        ``KEYSTONE_SERVE_DEVICES``, default all local) — before first
        traffic, then serve mixed-size batches with zero steady-state
        recompiles. Requires the serve path to be a linear chain of
        jittable, row-independent transformers.
        """
        from keystone_tpu.workflow.analysis import enforce_lint
        from keystone_tpu.workflow.serving import CompiledPipeline

        # Opt-in static gate: with KEYSTONE_LINT=error a chain the engine
        # would refuse (host/row-coupled/gather nodes) fails HERE, with a
        # rule id and fix hint, before fit or warmup spend any compute.
        # The engine always has a bucket ladder, so KG101 is moot.
        enforce_lint(self, "compiled", serve=True, have_ladder=True)
        return CompiledPipeline(
            self, buckets=buckets, max_batch=max_batch, donate=donate,
            devices=devices, inflight=inflight,
        )

    # -- introspection -----------------------------------------------------

    def lint(self, example=None, serve: bool = False,
             have_ladder=None, refit: bool = False) -> "LintReport":
        """Statically lint the pipeline DAG (workflow/analysis.py): the
        abstract shape/dtype pass plus the KG rule catalog. ``example``
        (sample batch, ShapeDtypeStruct, or per-row feature-shape tuple)
        feeds shape propagation; ``serve=True`` escalates serveability
        findings to errors — the would-be ``compiled()`` contract;
        ``refit=True`` checks the ``refit_stream`` contract (KG105).
        Returns a ``LintReport``; never executes the graph."""
        from keystone_tpu.workflow.analysis import lint_graph

        return lint_graph(
            self.graph, self.source, self.sink,
            example=example, serve=serve, have_ladder=have_ladder,
            refit=refit,
        )

    def transformers(self) -> List[Transformer]:
        """Transformer chain in topological order (fitted pipelines only)."""
        out = []
        for nid in self.graph.reachable([self.sink]):
            op = self.graph.operators[nid]
            if isinstance(op, TransformerOperator):
                out.append(op.transformer)
        return out

    def describe(self) -> str:
        lines = []
        for nid in self.graph.reachable([self.sink]):
            op = self.graph.operators[nid]
            deps = ", ".join(map(repr, self.graph.dependencies[nid]))
            lines.append(f"{nid!r}: {op.label()} <- [{deps}]")
        return "\n".join(lines)

    def to_dot(self) -> str:
        """Graphviz DOT source of the DAG — the pipeline-debugging export
        (Ref: workflow Pipeline DOT export [unverified, low confidence]).
        Render with ``dot -Tpng``; sources are diamonds, the sink is bold.
        """
        lines = ["digraph pipeline {", "  rankdir=LR;"]
        seen_srcs = set()
        for nid in self.graph.reachable([self.sink]):
            op = self.graph.operators[nid]
            style = ' style=bold' if nid == self.sink else ""
            lines.append(f'  "{nid!r}" [label="{op.label()}"{style}];')
            for dep in self.graph.dependencies[nid]:
                if isinstance(dep, SourceId) and dep not in seen_srcs:
                    seen_srcs.add(dep)
                    lines.append(f'  "{dep!r}" [label="input" shape=diamond];')
                lines.append(f'  "{dep!r}" -> "{nid!r}";')
        lines.append("}")
        return "\n".join(lines)


class PipelineDataset:
    """Lazy handle to the result of applying a pipeline to a batch.

    Ref: workflow/PipelineDataset.scala [unverified]. ``get()`` triggers
    optimization + execution (memoized).
    """

    def __init__(self, graph: Graph, sink: GraphId):
        self.graph = graph
        self.sink = sink
        self._value: Any = None
        self._computed = False

    def get(self) -> Any:
        if not self._computed:
            from contextlib import nullcontext

            from keystone_tpu.utils.metrics import active_tracer
            from keystone_tpu.workflow.executor import PipelineEnv

            tracer = active_tracer()
            ctx = (
                tracer.span("pipeline.apply", "pipeline")
                if tracer is not None else nullcontext({})
            )
            with ctx as attrs:
                self._value = PipelineEnv.get().optimize_and_execute(
                    self.graph, self.sink
                )
                shape = getattr(self._value, "shape", None)
                if shape is not None:
                    attrs["shape"] = [int(s) for s in shape]
            self._computed = True
        return self._value


def _force(data):
    return data.get() if isinstance(data, PipelineDataset) else data
