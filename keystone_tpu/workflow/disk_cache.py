"""On-disk fitted-prefix store — prefix-state reuse across processes.

The reference's fitted pipelines persist their prefix state so a rerun skips
refits (SURVEY.md §2.1 auto-caching + §5 checkpoint/resume rows [unverified]).
Here the store is content-addressed: the key is the structural digest of the
estimator node's prefix (class + hyperparams + data fingerprints, see
workflow/fingerprint.py), so a hit is byte-level evidence the same fit would
recompute the same transformer — no invalidation logic needed, stale entries
are simply never addressed.

Enabled by ``KEYSTONE_CACHE_DIR`` (or ``config.cache_dir``); corrupt or
unreadable entries degrade to cache misses, never errors.
"""

from __future__ import annotations

import logging
import os
import pickle
import tempfile
from typing import Any, Optional

logger = logging.getLogger("keystone_tpu")


class DiskFitCache:
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, f"{key}.fit.pkl")

    def get(self, key: str) -> Optional[Any]:
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                fitted = pickle.load(f)
        except FileNotFoundError:
            return None
        except Exception as e:  # corrupt/unpicklable entry: miss, don't die
            logger.warning("disk fit cache: dropping unreadable %s (%s)", path, e)
            try:
                os.remove(path)
            except OSError:
                pass
            return None
        logger.info("disk fit cache: hit %s", key)
        return fitted

    def put(self, key: str, fitted: Any) -> None:
        # Transformer.__getstate__ drops jit caches during pickling, so the
        # live object (still in the session cache / user's hands) keeps its
        # warm compilation.
        path = self._path(key)
        if os.path.exists(path):
            return
        try:
            fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    pickle.dump(fitted, f)
                os.replace(tmp, path)  # atomic: concurrent writers race safely
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
        except Exception as e:  # persistence is best-effort
            logger.warning("disk fit cache: could not persist %s (%s)", key, e)
