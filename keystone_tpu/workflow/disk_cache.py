"""On-disk fitted-prefix store — prefix-state reuse across processes.

The reference's fitted pipelines persist their prefix state so a rerun skips
refits (SURVEY.md §2.1 auto-caching + §5 checkpoint/resume rows [unverified]).
Here the store is content-addressed: the key is the structural digest of the
estimator node's prefix (class + hyperparams + data fingerprints, see
workflow/fingerprint.py), so a hit is byte-level evidence the same fit would
recompute the same transformer — no invalidation logic needed, stale entries
are simply never addressed.

Enabled by ``KEYSTONE_CACHE_DIR`` (or ``config.cache_dir``); corrupt or
unreadable entries degrade to cache misses, never errors.
"""

from __future__ import annotations

import logging
import os
import pickle
import tempfile
from typing import Any, Optional

logger = logging.getLogger("keystone_tpu")


class DiskFitCache:
    def __init__(self, root: str, max_bytes: Optional[int] = None):
        self.root = root
        if max_bytes is None:
            raw = os.environ.get("KEYSTONE_CACHE_MAX_BYTES", "")
            try:
                max_bytes = int(raw) if raw else 10 << 30
            except ValueError:  # malformed knob: default, don't abort runs
                logger.warning(
                    "ignoring malformed KEYSTONE_CACHE_MAX_BYTES=%r", raw
                )
                max_bytes = 10 << 30
        self.max_bytes = max_bytes
        # Approximate directory size, refreshed by each sweep: puts only pay
        # the full listdir+stat sweep when the estimate crosses the budget —
        # but at most _SWEEP_EVERY puts go by between real sweeps, because
        # the estimate only counts THIS process's writes and a shared
        # directory grows under everyone's.
        self._approx_total: Optional[int] = None
        self._puts_since_sweep = 0
        os.makedirs(root, exist_ok=True)

    _SWEEP_EVERY = 32

    def _path(self, key: str) -> str:
        return os.path.join(self.root, f"{key}.fit.pkl")

    def _trim(self) -> None:
        """Evict least-recently-USED entries (get() refreshes mtime) until
        under the size budget — content-addressed entries are always safe to
        drop (pure misses). Per-file errors skip and continue: a concurrent
        trimmer racing us must not abort the whole sweep."""
        if (
            self._approx_total is not None
            and self._approx_total <= self.max_bytes
            and self._puts_since_sweep < self._SWEEP_EVERY
        ):
            return
        self._puts_since_sweep = 0
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        entries = []
        total = 0
        for name in names:
            if not name.endswith(".fit.pkl"):
                continue
            path = os.path.join(self.root, name)
            try:
                st = os.stat(path)
            except OSError:
                continue  # removed by a concurrent trimmer
            entries.append((st.st_mtime, st.st_size, path))
            total += st.st_size
        if total <= self.max_bytes:
            self._approx_total = total
            return
        entries.sort()
        for _mtime, size, path in entries:
            try:
                os.remove(path)
            except OSError:
                continue
            total -= size
            if total <= self.max_bytes:
                break
        self._approx_total = total

    def get(self, key: str) -> Optional[Any]:
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                fitted = pickle.load(f)
        except FileNotFoundError:
            return None
        except Exception as e:  # corrupt/unpicklable entry: miss, don't die
            logger.warning("disk fit cache: dropping unreadable %s (%s)", path, e)
            try:
                os.remove(path)
            except OSError:
                pass
            return None
        try:
            os.utime(path)  # refresh recency: eviction is LRU, not FIFO
        except OSError:
            pass
        logger.info("disk fit cache: hit %s", key)
        return fitted

    def put(self, key: str, fitted: Any) -> None:
        # Transformer.__getstate__ drops jit caches during pickling, so the
        # live object (still in the session cache / user's hands) keeps its
        # warm compilation.
        path = self._path(key)
        if os.path.exists(path):
            return
        try:
            fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    pickle.dump(fitted, f)
                os.replace(tmp, path)  # atomic: concurrent writers race safely
                self._puts_since_sweep += 1
                if self._approx_total is not None:
                    try:
                        self._approx_total += os.path.getsize(path)
                    except OSError:
                        self._approx_total = None  # force a real sweep
                self._trim()
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
        except Exception as e:  # persistence is best-effort
            logger.warning("disk fit cache: could not persist %s (%s)", key, e)
