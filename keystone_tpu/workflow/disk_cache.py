"""On-disk fitted-prefix store — prefix-state reuse across processes.

The reference's fitted pipelines persist their prefix state so a rerun skips
refits (SURVEY.md §2.1 auto-caching + §5 checkpoint/resume rows [unverified]).
Here the store is content-addressed: the key is the structural digest of the
estimator node's prefix (class + hyperparams + data fingerprints, see
workflow/fingerprint.py), so a hit is byte-level evidence the same fit would
recompute the same transformer — no invalidation logic needed, stale entries
are simply never addressed.

Enabled by ``KEYSTONE_CACHE_DIR`` (or ``config.cache_dir``); corrupt or
unreadable entries degrade to cache misses, never errors.

Trust boundary: entries are pickles, and unpickling runs code. The cache
directory MUST be private to the user — it is created mode 0o700, and loads
go through a restricted unpickler that only resolves classes from an
allowlist of module prefixes (keystone_tpu / numpy / jax / stdlib containers),
so a planted entry cannot smuggle in ``os.system``-style callables. Entries
that reference anything else degrade to misses. Set
``KEYSTONE_CACHE_TRUST_ALL=1`` to disable the allowlist for caches holding
user-defined transformer classes outside these prefixes.
"""

from __future__ import annotations

import io
import logging
import os
import pickle
import tempfile
import time
from typing import Any, Optional

logger = logging.getLogger("keystone_tpu")

#: Exact non-class reconstruction callables array pickles need (measured by
#: recording find_class over real fitted-transformer pickles). Everything
#: else callable is denied — broad module prefixes would leave gadget chains
#: (e.g. ``functools.partial(numpy.load, allow_pickle=True)`` re-enters
#: unrestricted pickle), so functions are enumerated, never pattern-matched.
_SAFE_CALLABLES = frozenset(
    {
        ("numpy._core.multiarray", "_reconstruct"),
        ("numpy.core.multiarray", "_reconstruct"),
        ("numpy._core.multiarray", "scalar"),
        ("numpy.core.multiarray", "scalar"),
        ("jax._src.array", "_reconstruct_array"),
    }
)

#: Module roots whose *classes* (types only — never functions) may appear in
#: an entry: array/dtype containers and stdlib collections. A type's
#: constructor runs on unpickle, but these are data containers, not
#: exec/eval/system-shaped.
_SAFE_CLASS_ROOTS = ("keystone_tpu", "numpy", "jax", "jaxlib", "ml_dtypes", "collections")

#: The handful of builtins pickles legitimately need for container types.
_SAFE_BUILTINS = frozenset(
    {
        "complex", "frozenset", "set", "slice", "range", "bytearray",
        "list", "dict", "tuple", "int", "float", "bool", "str", "bytes",
        "object",
    }
)


def _class_root_ok(root: str, obj: type) -> bool:
    """Per-root class rules. numpy is the sharp edge: ndarray SUBCLASSES
    include ``numpy.memmap``, whose constructor creates/truncates arbitrary
    files during REDUCE — so exactly ``ndarray`` itself plus the dtype and
    scalar hierarchies are admitted, nothing derived."""
    import numpy as _np

    if root == "numpy":
        return obj is _np.ndarray or issubclass(obj, (_np.dtype, _np.generic))
    return True


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module: str, name: str):
        if module == "builtins":
            if name in _SAFE_BUILTINS:
                return super().find_class(module, name)
            raise pickle.UnpicklingError(
                f"disk fit cache: builtins.{name} not allowlisted"
            )
        if (module, name) in _SAFE_CALLABLES:
            return super().find_class(module, name)
        root = module.split(".", 1)[0]
        # Resolution itself imports the module and runs its top-level code,
        # so outside the known roots the module must ALREADY be imported —
        # an attacker-named module (including a planted .py on sys.path)
        # never gets imported by a cache read.
        import sys as _sys

        if root not in _SAFE_CLASS_ROOTS and module not in _sys.modules:
            raise pickle.UnpicklingError(
                f"disk fit cache: module {module!r} not imported; refusing "
                "to import it on behalf of a cache entry"
            )
        obj = super().find_class(module, name)
        if root in _SAFE_CLASS_ROOTS:
            if isinstance(obj, type) and _class_root_ok(root, obj):
                return obj
            raise pickle.UnpicklingError(
                f"disk fit cache: {module}.{name} is not an allowlisted "
                "class or reconstructor"
            )
        # User-defined transformers live outside the roots but are the
        # store's whole purpose: require an actual subclass of the framework
        # bases — ``os.system`` (not a class) and ``subprocess.Popen`` (a
        # class, but not a Transformer) both fail.
        from keystone_tpu.workflow.pipeline import Estimator, LabelEstimator, Transformer

        if isinstance(obj, type) and issubclass(
            obj, (Transformer, Estimator, LabelEstimator)
        ):
            return obj
        raise pickle.UnpicklingError(
            f"disk fit cache: {module}.{name} not allowlisted "
            "(set KEYSTONE_CACHE_TRUST_ALL=1 for caches holding arbitrary "
            "user-defined state)"
        )


def _load_entry(f) -> Any:
    # Strict "=1" on purpose (NOT env_flag): this knob disables the
    # restricted unpickler entirely, so a mistyped spelling ("off",
    # "disabled", ...) must fail closed (keep the allowlist), not open.
    if os.environ.get("KEYSTONE_CACHE_TRUST_ALL") == "1":
        return pickle.load(f)
    return _RestrictedUnpickler(f).load()


class DiskCache:
    """Key-addressed atomic pickle store — the durability substrate shared
    by the fitted-prefix cache (``DiskFitCache``) and the streaming
    solvers' checkpoint/resume state (linalg/normal_equations.py,
    linalg/bcd.py).

    Crash-safety contract: ``put`` writes to a temp file in the cache root
    and ``os.replace``s it into place, so a process killed mid-write can
    never leave a truncated entry that poisons later ``get``s — the reader
    sees either the old entry or the new one, both complete. Temp files
    orphaned by a mid-write kill are swept (age-gated, so a concurrent
    writer's in-flight temp survives) on the next construction. Reads go
    through the restricted unpickler above; corrupt or unreadable entries
    degrade to misses, never errors.
    """

    #: Entry filename suffix — namespaces co-resident stores (trim and the
    #: stale-temp sweep only ever touch their own suffix).
    SUFFIX = ".pkl"

    #: Orphaned temp files older than this are removed at construction; the
    #: age gate keeps a live concurrent writer's temp out of the sweep.
    _TMP_MAX_AGE_S = 3600.0

    def __init__(
        self,
        root: str,
        max_bytes: Optional[int] = None,
        suffix: Optional[str] = None,
    ):
        self.root = root
        self.suffix = suffix if suffix is not None else self.SUFFIX
        if max_bytes is None:
            raw = os.environ.get("KEYSTONE_CACHE_MAX_BYTES", "")
            try:
                max_bytes = int(raw) if raw else 10 << 30
            except ValueError:  # malformed knob: default, don't abort runs
                logger.warning(
                    "ignoring malformed KEYSTONE_CACHE_MAX_BYTES=%r", raw
                )
                max_bytes = 10 << 30
        self.max_bytes = max_bytes
        # Approximate directory size, refreshed by each sweep: puts only pay
        # the full listdir+stat sweep when the estimate crosses the budget —
        # but at most _SWEEP_EVERY puts go by between real sweeps, because
        # the estimate only counts THIS process's writes and a shared
        # directory grows under everyone's.
        self._approx_total: Optional[int] = None
        self._puts_since_sweep = 0
        # 0o700 on creation: pickled entries execute on load, so the dir
        # must not be writable (or readable) by other users. Pre-existing
        # dirs keep their mode — tightening a deliberately shared cache
        # behind the owner's back would break it silently.
        os.makedirs(root, mode=0o700, exist_ok=True)
        self._sweep_stale_tmps()

    _SWEEP_EVERY = 32

    def _owns(self, name: str, extra: str = "") -> bool:
        """Suffix scoping for directory sweeps. ``endswith`` alone is
        hierarchical ('.fit.pkl' ends with '.pkl'), so additionally the
        part before the suffix must be dot-free — true of every key this
        layer writes (digests, snapshot names, mkstemp stems), false for
        a longer co-resident suffix's files."""
        tail = f"{self.suffix}{extra}"
        return name.endswith(tail) and "." not in name[: -len(tail)]

    def _sweep_stale_tmps(self) -> None:
        """Remove temp files orphaned by a writer killed between mkstemp
        and os.replace — they hold partial pickles nothing will ever
        complete. Age-gated so an in-flight concurrent write survives,
        and suffix-scoped (temps are named <suffix>.tmp) so this store
        never touches a co-resident store's in-flight writes."""
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        now = time.time()  # lint: ok(KL005) compared against st_mtime, which is wall-clock
        for name in names:
            if not self._owns(name, extra=".tmp"):
                continue
            path = os.path.join(self.root, name)
            try:
                if now - os.stat(path).st_mtime > self._TMP_MAX_AGE_S:
                    os.remove(path)
            except OSError:
                continue  # racing sweeper/writer: theirs to handle

    def _path(self, key: str) -> str:
        return os.path.join(self.root, f"{key}{self.suffix}")

    def _trim(self) -> None:
        """Evict least-recently-USED entries (get() refreshes mtime) until
        under the size budget — content-addressed entries are always safe to
        drop (pure misses). Per-file errors skip and continue: a concurrent
        trimmer racing us must not abort the whole sweep."""
        if (
            self._approx_total is not None
            and self._approx_total <= self.max_bytes
            and self._puts_since_sweep < self._SWEEP_EVERY
        ):
            return
        self._puts_since_sweep = 0
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        entries = []
        total = 0
        for name in names:
            if not self._owns(name):
                continue
            path = os.path.join(self.root, name)
            try:
                st = os.stat(path)
            except OSError:
                continue  # removed by a concurrent trimmer
            entries.append((st.st_mtime, st.st_size, path))
            total += st.st_size
        if total <= self.max_bytes:
            self._approx_total = total
            return
        entries.sort()
        for _mtime, size, path in entries:
            try:
                os.remove(path)
            except OSError:
                continue
            total -= size
            if total <= self.max_bytes:
                break
        self._approx_total = total

    def delete(self, key: str) -> None:
        """Remove one entry; missing is fine. The checkpoint stores call
        this on successful solve completion — a consumed snapshot left
        behind could silently resume a LATER solve over changed data whose
        fingerprint probe happens to match."""
        try:
            os.remove(self._path(key))
        except OSError:
            pass

    def get(self, key: str) -> Optional[Any]:
        path = self._path(key)
        try:
            with open(path, "rb") as f:
                fitted = _load_entry(f)
        except FileNotFoundError:
            return None
        except Exception as e:  # lint: broad-ok corrupt/unpicklable entry (any unpickling error): miss, don't die
            logger.warning("disk fit cache: dropping unreadable %s (%s)", path, e)
            try:
                os.remove(path)
            except OSError:
                pass
            return None
        try:
            os.utime(path)  # refresh recency: eviction is LRU, not FIFO
        except OSError:
            pass
        logger.info("disk fit cache: hit %s", key)
        return fitted

    def put(self, key: str, fitted: Any, overwrite: bool = False) -> None:
        """Persist one entry atomically (temp file + ``os.replace``).

        ``overwrite=False`` (content-addressed use: the bytes behind a key
        never change) skips keys that already exist; ``overwrite=True``
        (checkpoint use: the same key is rewritten every K chunks)
        replaces the entry — still atomically, so a kill mid-rewrite
        leaves the PREVIOUS complete checkpoint, never a truncated one.
        """
        path = self._path(key)
        if not overwrite and os.path.exists(path):
            return
        try:
            fd, tmp = tempfile.mkstemp(
                dir=self.root, suffix=f"{self.suffix}.tmp"
            )
            try:
                with os.fdopen(fd, "wb") as f:
                    pickle.dump(fitted, f)
                os.replace(tmp, path)  # atomic: concurrent writers race safely
                self._puts_since_sweep += 1
                if self._approx_total is not None:
                    try:
                        self._approx_total += os.path.getsize(path)
                    except OSError:
                        self._approx_total = None  # force a real sweep
                self._trim()
            except BaseException:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                raise
        except Exception as e:  # lint: broad-ok persistence is best-effort; a failed put must never fail the fit
            logger.warning("disk fit cache: could not persist %s (%s)", key, e)


class DiskFitCache(DiskCache):
    """The cross-process fitted-prefix store (module docstring above): a
    ``DiskCache`` whose keys are structural digests of estimator prefixes,
    so entries are content-addressed and never overwritten."""

    SUFFIX = ".fit.pkl"
